"""Rail pipeliner: phase-interleave the ICI and DCN rails across
buckets and workloads.

Horovod's core speedup is pipelining — the background loop keeps the
wire busy while compute proceeds (arXiv:1802.05799 §4), and the RS+AG
decomposition exists precisely so phases can be scheduled
independently (arXiv:2004.13336).  PRs 5–10 made each *op* optimal but
left the two networks idle in alternation: a ``hier`` bucket's three
phases (ICI reduce-scatter → DCN hop → ICI all-gather) serialize, and
the per-bucket ``lax.optimization_barrier`` chain in
``sched/execute.py`` forces bucket *i*'s ICI all-gather before bucket
*i+1*'s ICI reduce-scatter even though the DCN hop between them uses a
different network entirely.

This pass re-expresses the ordering **per rail instead of per
bucket**: two independent ``optimization_barrier`` token chains — one
for the ICI rail, one for the DCN rail — so bucket *i*'s cross-slice
DCN hop runs concurrently with bucket *i+1*'s intra-slice ICI
reduce-scatter (and bucket *i−1*'s ICI all-gather).  The barriers are
identity on values and summation grouping within a bucket never
changes, so f32 dense losses are **bitwise identical** to the
serialized emission in every mode (the knob is a scheduling lever,
never a numerics one).

Three jobs live here:

* **Engagement** (:func:`engaged`): ``HVD_TPU_XIR_PIPELINE`` =
  ``off`` (per-bucket chains, the PR 10 emission exactly) | ``auto``
  (default: engage the rail chains when the cost model prices the
  pipelined order cheaper — reorder-only, the bucket plan is
  untouched) | ``on`` (rail chains AND bucket split points from the
  fitted per-rail bandwidths, :func:`plan_bucket_bytes`).
* **Pricing** (:func:`estimate_schedule_cost`): the serialized
  schedule costs the sum of every phase; the pipelined schedule costs
  the **max of the two rail sums** plus one bucket's worth of
  fill/drain — so pipelined ≤ serialized and ≥ either rail alone, by
  construction (``Topology.rail_times`` supplies the per-bucket
  split, fitted parameters included).
* **Cross-workload merge** (:func:`merge` / :func:`merge_order`): two
  lowered programs whose traffic lives on *disjoint rails* (a
  slice-local MoE all_to_all or Ulysses flip is ICI-only; flat dense
  buckets over a multi-slice axis are DCN-only in the model) can ride
  one emission, interleaved so each program fills the other's idle
  rail windows — ``xir.interp.execute_merged`` drives it.

The :class:`RailChain` helper owns the two token chains; both
``sched/execute.py`` (dense buckets) and ``xir/interp.py`` (merged
programs) emit through it.  ``ScheduleTuner(explore_pipeline=True)``
window-scores the knob and persists the winner in the tune DB
(``meta.pipeline``), and ``tools/topo_bench.py --pipeline`` measures
the pipelined-vs-serialized wall time on the simulated 2×4 mesh.
See docs/exchange_ir.md ("Program scheduling").
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..exceptions import HorovodTpuError
from ..utils import env

MODES = ("off", "on", "auto")

# Buckets/ops the pipeliner can decompose into rail phases: the "hier"
# lowering only.  hier_adasum's DCN leg is ceil(log2 s) dot-product
# *rounds* interleaved with local combines — a cross-rail dependency
# chain per bucket, not one hop — so hier_adasum buckets pin the
# serialized path in v1 (docs/adasum.md).  Flat buckets occupy one rail
# end to end and need no decomposition (they serialize against both
# rails so their summation order never reorders across a wire change).
DECOMPOSABLE_LOWERINGS = ("hier",)

_mode_override: Optional[str] = None


def set_mode_override(mode: Optional[str]) -> None:
    """Trace-time knob override (the sched config-override pattern):
    tests and bench variants pin the pipeliner without touching the
    environment."""
    global _mode_override
    if mode is not None and mode not in MODES:
        raise HorovodTpuError(
            f"pipeline mode override must be one of {MODES}, got {mode!r}"
        )
    _mode_override = mode


def mode() -> str:
    """``HVD_TPU_XIR_PIPELINE`` policy: ``off`` | ``on`` | ``auto``
    (default).  See the module docstring for what each engages."""
    if _mode_override is not None:
        return _mode_override
    raw = (env.get_env(env.XIR_PIPELINE, "auto") or "auto").strip().lower()
    if raw in ("0", "false", "no", "none", ""):
        raw = "off"
    if raw in ("1", "true", "yes"):
        raw = "on"
    if raw not in MODES:
        raise HorovodTpuError(
            f"HVD_TPU_XIR_PIPELINE must be off|on|auto, got {raw!r}"
        )
    return raw


# ------------------------------------------------------------ pricing

def rail_times(
    collective: str,
    nbytes: int,
    lowering: str,
    axis_size: Optional[int] = None,
    topo=None,
) -> Tuple[float, float]:
    """Per-rail ``(ici_s, dcn_s)`` of one exchange under the current
    (fitted) cost parameters — ``Topology.rail_times`` against the
    process-wide topology by default."""
    from ..topo import model as topo_model

    topo = topo if topo is not None else topo_model.current()
    return topo.rail_times(collective, nbytes, lowering, axis_size)


def estimate_schedule_cost(
    items: Sequence[Tuple[str, int, str]],
    axis_size: Optional[int] = None,
    *,
    pipelined: bool = False,
    topo=None,
) -> float:
    """Price a multi-bucket exchange: ``items`` is a sequence of
    ``(collective, nbytes, lowering)`` stages in schedule order.

    Serialized: the sum of every stage's two rail times (phases run
    back to back).  Pipelined: ``max(Σici, Σdcn)`` — the busy rail is
    the wall clock — plus one stage's worth of the other rail as
    fill/drain (the pipeline must start and finish somewhere).  The
    construction guarantees the property the tests pin::

        max(Σici, Σdcn)  ≤  pipelined  ≤  serialized
    """
    if not items:
        return 0.0
    splits = [
        rail_times(c, b, lo, axis_size, topo) for c, b, lo in items
    ]
    sum_ici = sum(s[0] for s in splits)
    sum_dcn = sum(s[1] for s in splits)
    if not pipelined:
        return sum_ici + sum_dcn
    return max(sum_ici, sum_dcn) + min(sum_ici, sum_dcn) / len(items)


def plan_bucket_bytes(
    total_nbytes: int,
    axis_size: Optional[int] = None,
    topo=None,
) -> Optional[int]:
    """Bucket split point for a pipelined schedule, from the fitted
    per-rail bandwidths: the bucket size whose equal-split schedule
    the max-of-rails model prices cheapest.

    Small buckets amortize fill/drain but pay a phase-overhead tax per
    bucket; large buckets do the opposite.  The search walks
    power-of-two sizes between 64 KiB and ``total/2`` (a pipeline
    needs ≥ 2 stages) and returns the argmin — ``None`` when the
    topology is single-slice, the payload too small to split, or the
    mode is not ``on`` (under ``auto`` the pass is reorder-only: the
    bucket plan must stay identical to the serialized one)."""
    from ..topo import model as topo_model

    if mode() != "on":
        return None
    topo = topo if topo is not None else topo_model.current()
    n = topo.world if axis_size is None else axis_size
    s, _ = topo.factor_axis(n)
    if s == 1 or total_nbytes < 2 * 65536:
        return None
    best_b, best_cost = None, None
    b = 65536
    while b <= max(total_nbytes // 2, 65536):
        count = -(-total_nbytes // b)
        items = [("all_reduce", min(b, total_nbytes), "hier")] * count
        cost = estimate_schedule_cost(
            items, n, pipelined=True, topo=topo
        )
        if best_cost is None or cost < best_cost:
            best_b, best_cost = b, cost
        b *= 2
    return best_b


# --------------------------------------------------------- engagement

def _nbytes_of(bucket_or_op) -> int:
    nb = getattr(bucket_or_op, "nbytes", None)
    if nb is None and hasattr(bucket_or_op, "attr"):
        nb = bucket_or_op.attr("nbytes")
    return int(nb or 0)


def decomposable(bucket_or_op) -> bool:
    """Whether one bucket/op can split into rail phases: the ``hier``
    lowering, a single wire dtype (one flat buffer), and no explicit
    replica subgroups (the hierarchy factors the whole axis)."""
    lowering = getattr(bucket_or_op, "lowering", "flat")
    if lowering not in DECOMPOSABLE_LOWERINGS:
        return False
    dtypes = getattr(bucket_or_op, "wire_dtypes", None)
    if dtypes is not None and len(set(dtypes)) != 1:
        return False
    if getattr(bucket_or_op, "groups", None) is not None:
        return False
    return True


def engaged(schedule, axis_size: Optional[int] = None) -> bool:
    """Whether the rail-chained emission runs for ``schedule`` (a
    ``BucketSchedule`` or anything with ``.buckets``): off-mode never;
    otherwise at least two decomposable buckets must exist (a single
    stage has nothing to overlap) — and under ``auto`` the cost model
    must price the pipelined order cheaper than the serialized one."""
    m = mode()
    if m == "off":
        return False
    buckets = list(getattr(schedule, "buckets", schedule))
    n_decomp = sum(1 for b in buckets if decomposable(b))
    if n_decomp < 2:
        return False
    if m == "on":
        return True
    items = [
        ("all_reduce", _nbytes_of(b), b.lowering) for b in buckets
    ]
    pipe = estimate_schedule_cost(items, axis_size, pipelined=True)
    serial = estimate_schedule_cost(items, axis_size, pipelined=False)
    return pipe < serial


# ------------------------------------------------------ rail chaining

class RailChain:
    """Two independent ``lax.optimization_barrier`` token chains — one
    per rail.  ``tie`` makes tensors wait for the named rails' previous
    occupants; ``bump`` installs a scalar carried out of an op as the
    rails' new token.  Identity on values: the chains only add ordering
    edges, which is the whole trick."""

    RAILS = ("ici", "dcn")

    def __init__(self):
        self._tok: Dict[str, Any] = {r: None for r in self.RAILS}
        self.overlap_windows = 0

    def tie(self, tensors: List[Any], rails: Sequence[str]) -> List[Any]:
        from jax import lax

        toks = tuple(
            self._tok[r] for r in rails if self._tok[r] is not None
        )
        if not toks or not tensors:
            return list(tensors)
        out = lax.optimization_barrier(tuple(tensors) + toks)
        return list(out[: len(tensors)])

    def bump(self, tensor: Any, rails: Sequence[str]) -> None:
        tok = tensor.reshape(-1)[0]
        for r in rails:
            self._tok[r] = tok


def measured_rail_busy() -> Dict[str, Optional[float]]:
    """The measured per-rail utilization this process last published:
    ``{"ici": frac, "dcn": frac}`` from the ``topo.rail_busy_frac``
    gauges the tracer derives out of the rail-phase spans emitted at
    the RailChain boundaries (``trace/tracer.py``).  ``None`` per rail
    until a traced step with hier buckets has run — this is the
    *measured* counterpart to :func:`estimate_schedule_cost`'s modeled
    overlap, the gauge the pipeliner's speedup claims are checked
    against (docs/tracing.md)."""
    from .. import metrics

    return {
        r: metrics.get_gauge("topo.rail_busy_frac", {"rail": r})
        for r in RailChain.RAILS
    }


# --------------------------------------------------- workload merging

def _op_rail_split(op, axis_size: Optional[int]) -> Tuple[float, float]:
    """One lowered op's ``(ici, dcn)`` occupancy.  Ungrouped
    reduce-shaped ops use the cost model's rail split (flat over a
    multi-slice axis rides the DCN bottleneck end to end — its ring
    *time* is DCN-gated even where individual hops stay on ICI; hier
    occupies both rails); shuffle-shaped and subgroup ops — which the
    ring cost model has no row for — split by modeled bytes (a
    slice-local all_to_all is ICI-only)."""
    from . import ir, lower as lower_mod

    if op.op in ir.REDUCE_OPS and op.groups is None:
        lowering = op.lowering if op.lowering in (
            "flat", "hier", "hier_adasum") else "flat"
        return rail_times(
            op.op, int(op.attr("nbytes") or 0), lowering, axis_size
        )
    by = lower_mod.op_network_bytes(op, axis_size)
    return float(by["ici"]), float(by["dcn"])


def op_rail(op, axis_size: Optional[int] = None) -> str:
    """Dominant rail of one lowered op: ``"dcn"`` when its cross-slice
    occupancy exceeds its intra-slice one (flat dense buckets over a
    multi-slice axis), ``"ici"`` otherwise (slice-local subgroups,
    single-slice worlds, and the ICI-heavy hier phases)."""
    ici, dcn = _op_rail_split(op, axis_size)
    return "dcn" if dcn > ici else "ici"


def program_rails(program, axis_size: Optional[int] = None) -> frozenset:
    """The set of rails a lowered program occupies: ``hier`` ops both;
    a slice-local shuffle only ``{"ici"}``; flat dense buckets over a
    multi-slice axis only ``{"dcn"}`` (the cost-model view — their
    wall-clock is DCN-gated, leaving the ICI rail's windows free for a
    merged rider)."""
    rails = set()
    for op in program.ops:
        ici, dcn = _op_rail_split(op, axis_size)
        if ici > 0:
            rails.add("ici")
        if dcn > 0:
            rails.add("dcn")
        if op.lowering in ("hier", "hier_adasum"):
            rails.update(("ici", "dcn"))
    return frozenset(rails)


def rails_disjoint(a, b, axis_size: Optional[int] = None) -> bool:
    """Merge eligibility: two programs may co-schedule when their rail
    sets do not overlap — each one's traffic fills windows the other
    leaves idle, so interleaving can only hide time, never contend."""
    return not (program_rails(a, axis_size) & program_rails(b, axis_size))


def merge_order(
    programs: Sequence,
    axis_size: Optional[int] = None,
) -> List[Tuple[int, int]]:
    """Interleaved emission order of several co-scheduled programs:
    ``[(program_idx, op_idx), ...]``.  Round-robin over the programs,
    preferring at each step a program whose next op sits on a
    different rail than the op just emitted — the DCN-heavy loop and
    the ICI-only rider alternate, each landing in the other's idle
    window.  Deterministic (pure function of the lowered programs), so
    every SPMD rank emits the identical merged order."""
    queues = [list(range(len(p.ops))) for p in programs]
    order: List[Tuple[int, int]] = []
    last_rail: Optional[str] = None
    while any(queues):
        pick = None
        for pi, q in enumerate(queues):
            if not q:
                continue
            r = op_rail(programs[pi].ops[q[0]], axis_size)
            if last_rail is None or r != last_rail:
                pick = pi
                break
        if pick is None:
            pick = next(pi for pi, q in enumerate(queues) if q)
        oi = queues[pick].pop(0)
        last_rail = op_rail(programs[pick].ops[oi], axis_size)
        order.append((pick, oi))
    return order


def merge_concat(
    programs: Sequence,
    axis_size: Optional[int] = None,
    threshold: Optional[int] = None,
) -> Optional[List[Tuple[str, List[Tuple[int, int]]]]]:
    """Same-rail concatenation plan for co-scheduled programs whose
    rails OVERLAP (the case :func:`merge` declines): ops in the same
    fusion class (``svc/fuse.class_key`` — same kind/axis/wire/
    lowering/reduce/dtype) coalesce into ONE padded buffer and
    dispatch as one collective, bounded by the service fusion
    threshold; everything else emits solo.  Returns emission units
    ``[("fused", [(pi, oi), ...]) | ("solo", [(pi, oi)]), ...]`` in
    deterministic first-appearance order, or ``None`` when no class
    has two members (nothing to concatenate — callers fall back to
    sequential execution).  ``xir.interp.execute_merged`` gives the
    plan meaning through one :class:`RailChain` emission, so the fused
    buffers still interleave with solo ops across rails."""
    from ..svc import fuse

    threshold = fuse.fusion_threshold() if threshold is None else threshold
    if threshold <= 0 or len(programs) < 1:
        return None
    units: List[Tuple[str, List[Tuple[int, int]]]] = []
    open_classes: dict = {}
    open_bytes: dict = {}
    for pi, p in enumerate(programs):
        for oi, op in enumerate(p.ops):
            key = fuse.class_key(op, axis_size)
            nbytes = int(op.attr("nbytes") or 0)
            if key is None or nbytes > threshold:
                units.append(("solo", [(pi, oi)]))
                continue
            members = open_classes.get(key)
            if members is not None and \
                    open_bytes[key] + nbytes > threshold:
                members = None  # class buffer full: open a new unit
            if members is None:
                members = []
                unit = ("fused", members)
                units.append(unit)
                open_classes[key] = members
                open_bytes[key] = 0
            members.append((pi, oi))
            open_bytes[key] += nbytes
    if not any(kind == "fused" and len(m) > 1 for kind, m in units):
        return None
    # Singleton "fused" units emit solo — no packing for one member.
    return [
        ("solo", m) if kind == "fused" and len(m) == 1 else (kind, m)
        for kind, m in units
    ]


def merge(programs: Sequence, axis_size: Optional[int] = None):
    """Merge several lowered programs into one co-scheduled
    :class:`~horovod_tpu.xir.ir.ExchangeProgram` (kind =
    ``"kind_a+kind_b"``, ops renumbered in the interleaved order), or
    ``None`` when merging is ineligible: pipelining off, fewer than
    two programs, or any pair sharing a rail.  The merged program is
    pure metadata — ``xir.interp.execute_merged`` gives it meaning
    with one :class:`RailChain` emission."""
    from . import ir

    if mode() == "off" or len(programs) < 2:
        return None
    for i in range(len(programs)):
        for j in range(i + 1, len(programs)):
            if not rails_disjoint(programs[i], programs[j], axis_size):
                return None
    order = merge_order(programs, axis_size)
    ops = [
        programs[pi].ops[oi].replace(bucket=pos)
        for pos, (pi, oi) in enumerate(order)
    ]
    return ir.program("+".join(p.kind for p in programs), ops)
