"""Control-flow exceptions for elastic training.

TPU-native re-design of the reference's ``horovod/common/exceptions.py``:
the same two exceptions drive the elastic retry loop (reference
``horovod/common/elastic.py:151``), plus a NotInitialized error for API
misuse.
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class NotInitializedError(HorovodTpuError):
    """Raised when the API is used before ``init()`` was called."""

    def __init__(self, name: str = "horovod_tpu"):
        super().__init__(
            f"{name} has not been initialized; call horovod_tpu.init() first."
        )


class HorovodInternalError(HorovodTpuError):
    """Internal error raised when a collective fails at runtime.

    In elastic mode this unwinds to the ``elastic.run`` retry loop which
    restores committed state and re-initializes the mesh (reference
    ``horovod/common/exceptions.py`` + ``elastic.py:151``).
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """Raised when cluster membership changed but no worker failed.

    The elastic retry loop re-initializes without restoring state
    (reference ``horovod/common/elastic.py:73-96``).
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


class RemeshInterrupt(HostsUpdatedInterrupt):
    """Membership changed AND the driver authorized an in-process
    remesh (``elastic/remesh.py``): instead of exiting for a respawn
    round, the worker pauses at this step boundary, reshards live
    state to the new world, and continues.  Subclasses
    :class:`HostsUpdatedInterrupt` so a handler unaware of remesh
    degrades to the plain restart path.  ``request`` carries the
    driver's :class:`~horovod_tpu.elastic.remesh.RemeshRequest`."""

    def __init__(self, request=None):
        super().__init__()
        self.request = request


class RemeshError(HorovodTpuError):
    """The in-process remesh cannot proceed (incompatible plans, a
    source shard missing, a peer died mid-exchange, reinit failure).
    The elastic loop catches this and falls back to the
    checkpoint-restore restart path — a failed remesh degrades, it
    never wedges (``docs/fault_tolerance.md``)."""


class ShardChecksumError(RemeshError):
    """A moved shard failed its sha256 integrity check during the
    remesh state exchange (torn KV write, corrupted transport).  Like
    every :class:`RemeshError`, falls back to checkpoint restore."""


class FaultInjected(HorovodTpuError):
    """Raised by ``horovod_tpu.faults.inject`` when an ``error``/``flake``
    fault fires at a call site — the scripted stand-in for a transient
    infrastructure failure (discovery flake, spawn hiccup, KV blip)."""

    def __init__(self, site: str, message: str = ""):
        super().__init__(message or f"injected fault at {site!r}")
        self.site = site


class RetryTimeoutError(HorovodTpuError):
    """A single attempt under ``utils.retry.RetryPolicy`` exceeded its
    per-attempt timeout (the attempt may still be running in its worker
    thread; the policy moves on and retries)."""


class CheckpointCorruptionError(HorovodTpuError):
    """A checkpoint failed integrity verification (checksum mismatch,
    truncated file, or undecodable payload).  ``restore_or_init`` catches
    this and falls back to the previous good step."""


class CheckpointMissingKeysError(HorovodTpuError):
    """A params-only restore (``checkpoint.load_params``) asked for
    state keys the checkpoint does not hold.  Carries the structured
    identity of the failure instead of a raw ``KeyError``: ``missing``
    names every absent key and ``available`` what the checkpoint
    actually stores, so a serving replica pointed at the wrong
    checkpoint says *which* keys are wrong, on every rank."""

    def __init__(self, missing, available, path: str = ""):
        self.missing = tuple(sorted(missing))
        self.available = tuple(sorted(available))
        self.path = path
        super().__init__(
            f"checkpoint{f' at {path}' if path else ''} is missing "
            f"key(s) {list(self.missing)}; it holds "
            f"{list(self.available)}"
        )


class QuantizedWireError(HorovodTpuError, ValueError):
    """The int8 quantized-wire path cannot serve this reduction
    (unsupported op, non-global process set, or IndexedSlices
    gradients).  Subclasses ``ValueError`` for backward compatibility;
    the autotune quantized-probe retry catches exactly this type so an
    unrelated user ``ValueError`` never silently rejects the knob."""


class ProcessSetTilingError(QuantizedWireError):
    """A rank subset cannot tile the axis into equal-size XLA replica
    groups — the one structured error shared by everything that lowers
    to ``replica_groups``: process-set partitioning
    (``process_sets.tiling_groups``), the quantized wire's phase
    collectives (``ops/quantized.py``), and hierarchical ICI/DCN group
    construction (``topo/``).  Subclasses :class:`QuantizedWireError`
    so callers that historically caught the quantized type keep
    working.  Structured fields: ``ranks`` (the offending subset),
    ``world_size`` (the axis extent), ``context`` (which machinery
    needed the tiling)."""

    def __init__(self, ranks, world_size: int, context: str = ""):
        self.ranks = tuple(int(r) for r in ranks)
        self.world_size = int(world_size)
        self.context = context
        where = f" ({context})" if context else ""
        super().__init__(
            f"ranks {list(self.ranks)} do not tile the axis of size "
            f"{self.world_size} into equal replica groups{where}; XLA "
            "replica_groups require an equal-size partition — use the "
            "dense/masked path for arbitrary subsets"
        )
