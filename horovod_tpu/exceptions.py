"""Control-flow exceptions for elastic training.

TPU-native re-design of the reference's ``horovod/common/exceptions.py``:
the same two exceptions drive the elastic retry loop (reference
``horovod/common/elastic.py:151``), plus a NotInitialized error for API
misuse.
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class NotInitializedError(HorovodTpuError):
    """Raised when the API is used before ``init()`` was called."""

    def __init__(self, name: str = "horovod_tpu"):
        super().__init__(
            f"{name} has not been initialized; call horovod_tpu.init() first."
        )


class HorovodInternalError(HorovodTpuError):
    """Internal error raised when a collective fails at runtime.

    In elastic mode this unwinds to the ``elastic.run`` retry loop which
    restores committed state and re-initializes the mesh (reference
    ``horovod/common/exceptions.py`` + ``elastic.py:151``).
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """Raised when cluster membership changed but no worker failed.

    The elastic retry loop re-initializes without restoring state
    (reference ``horovod/common/elastic.py:73-96``).
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync
