"""Elastic training on Ray.

Reference: ``horovod/ray/elastic.py`` — ``ElasticRayExecutor`` drives the
elastic driver with a Ray-native ``RayHostDiscovery`` (queries the Ray
GCS for alive nodes instead of running a user discovery script).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..elastic.discovery import HostDiscovery
from ..utils.logging import get_logger

log = get_logger()


class RayHostDiscovery(HostDiscovery):
    """Discover available hosts/slots from Ray's cluster state.

    Reference: ``ray/elastic.py:34-76``.  ``use_gpu``/``cpus_per_slot``
    translate node resources into slot counts.
    """

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        import ray

        out: Dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("Alive", False):
                continue
            resources = node.get("Resources", {})
            hostname = node.get("NodeManagerHostname")
            if self.use_gpu:
                slots = int(resources.get("GPU", 0) // self.gpus_per_slot)
            else:
                slots = int(resources.get("CPU", 0) // self.cpus_per_slot)
            if hostname and slots > 0:
                out[hostname] = slots
        return out


class ElasticRayExecutor:
    """Elastic executor: Ray actors join/leave as nodes come and go.

    Reference: ``ray/elastic.py:120-465``.  Wraps our elastic driver
    (``horovod_tpu/runner/elastic_driver.py``) with RayHostDiscovery and
    runs ``fn`` under the elastic retry loop on each worker.
    """

    def __init__(
        self,
        settings: Optional[Dict[str, Any]] = None,
        min_workers: int = 1,
        max_workers: Optional[int] = None,
        use_gpu: bool = False,
        cpus_per_slot: int = 1,
        override_discovery: Optional[HostDiscovery] = None,
    ):
        self.settings = settings or {}
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.discovery = override_discovery or RayHostDiscovery(
            use_gpu=use_gpu, cpus_per_slot=cpus_per_slot
        )
        self.driver = None

    def start(self) -> None:
        from ..elastic.discovery import HostManager
        from ..runner.elastic_driver import ElasticDriver

        self.driver = ElasticDriver(
            host_manager=HostManager(self.discovery),
            min_np=self.min_workers,
            max_np=self.max_workers,
        )
        self.driver.start_discovery()

    def run(self, fn_or_command, args: Optional[list] = None,
            kwargs: Optional[dict] = None) -> int:
        """Run an elastic job; returns the job exit code.

        Accepts either a worker command (``List[str]``, executed as-is on
        each slot like ``run_rounds``) or a callable, which is shipped to
        workers via cloudpickle the way ``horovod_tpu.runner.run`` ships
        functions.
        """
        import ray  # noqa: F401 — fail fast if Ray is unavailable

        if self.driver is None:
            self.start()
        publish = None
        if callable(fn_or_command):
            # Ship the payload through the rendezvous KV store (the
            # ``horovod.run`` func-delivery path): works for remote ssh
            # workers (no driver-local temp file) and has no argv size
            # cap (cloudpickled closures can be arbitrarily large).
            import sys

            import cloudpickle

            publish = {
                ("__run__", "func"): cloudpickle.dumps(
                    (fn_or_command, args or [], kwargs or {})
                ),
            }
            command = [
                sys.executable, "-m", "horovod_tpu.runner.task_runner",
            ]
        else:
            command = list(fn_or_command)
        return self.driver.run_rounds(command, publish=publish)
