"""Placement-group strategies.

Reference: ``horovod/ray/strategy.py`` — ``PGStrategy`` variants decide
how worker slots map onto Ray placement-group bundles: *pack* fills
whole hosts first (fewest hosts, best for ICI locality on TPU pods);
*spread* one slot per host (most hosts, best host-memory headroom);
*colocated* pins a fixed per-host slot count.
"""

from __future__ import annotations

from typing import Dict, List


class PlacementStrategy:
    def __init__(self, num_workers: int, num_workers_per_host: int = 1,
                 cpus_per_worker: int = 1, gpus_per_worker: int = 0):
        self.num_workers = num_workers
        self.num_workers_per_host = num_workers_per_host
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker

    def _bundle(self, slots: int) -> Dict[str, int]:
        b = {"CPU": self.cpus_per_worker * slots}
        if self.gpus_per_worker:
            b["GPU"] = self.gpus_per_worker * slots
        return b

    def bundles(self) -> List[Dict[str, int]]:
        raise NotImplementedError()


class PackStrategy(PlacementStrategy):
    """Fill hosts to ``num_workers_per_host`` before opening new ones."""

    def bundles(self) -> List[Dict[str, int]]:
        out = []
        remaining = self.num_workers
        while remaining > 0:
            slots = min(remaining, self.num_workers_per_host)
            out.append(self._bundle(slots))
            remaining -= slots
        return out


class SpreadStrategy(PlacementStrategy):
    """One slot per bundle — maximally distributed."""

    def bundles(self) -> List[Dict[str, int]]:
        return [self._bundle(1) for _ in range(self.num_workers)]


class ColocatedStrategy(PlacementStrategy):
    """Exactly ``num_workers_per_host`` slots on each of N hosts; requires
    the worker count to divide evenly (reference colocated strategy)."""

    def bundles(self) -> List[Dict[str, int]]:
        if self.num_workers % self.num_workers_per_host != 0:
            raise ValueError(
                f"num_workers={self.num_workers} not divisible by "
                f"num_workers_per_host={self.num_workers_per_host}"
            )
        hosts = self.num_workers // self.num_workers_per_host
        return [self._bundle(self.num_workers_per_host) for _ in range(hosts)]
