"""Ray cluster integration.

Reference: ``horovod/ray/`` — ``RayExecutor`` (``ray/runner.py:128``)
spawns one Ray actor per slot, a ``Coordinator`` (``ray/runner.py:41``)
collects hostnames, assigns ranks and builds the rendezvous env, and
placement-group strategies (``ray/strategy.py``) pack or spread slots
over nodes.  ``ElasticRayExecutor`` (``ray/elastic.py``) adds Ray-based
host discovery.

The rank-assignment / env-construction / placement logic here is pure
Python (unit-testable without a Ray cluster); only
:class:`RayExecutor`'s ``start``/``run`` require ``ray`` to be
importable.
"""

from .runner import Coordinator, RayExecutor  # noqa: F401
from .strategy import ColocatedStrategy, PackStrategy, SpreadStrategy  # noqa: F401
from .elastic import ElasticRayExecutor, RayHostDiscovery  # noqa: F401
