"""Ray executor + coordinator.

Reference: ``horovod/ray/runner.py`` — the ``Coordinator``
(``runner.py:41-126``) maps registered (hostname, world_rank) pairs to
Horovod's rank/local_rank/cross_rank layout and emits the worker env;
``RayExecutor`` (``runner.py:128``) creates the actors and runs user
functions on them.  Here workers are TPU-host processes that
``jax.distributed.initialize`` against the coordinator address the env
describes.
"""

from __future__ import annotations

from collections import OrderedDict, defaultdict
from typing import Any, Callable, Dict, List, Optional

from ..runner.hosts import SlotInfo
from ..utils.logging import get_logger

log = get_logger()


def _ray():
    try:
        import ray  # noqa: F811

        return ray
    except ImportError as e:
        raise ImportError(
            "RayExecutor requires the `ray` package, which is not "
            "installed in this environment."
        ) from e


class Coordinator:
    """Collect registered workers and compute the cluster layout.

    Reference: ``ray/runner.py:41-126``.  Ranks are assigned host-major
    in registration order of hosts (stable node_id ordering), matching
    the reference's ``rank_assignment`` semantics.
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self.settings = settings or {}
        # hostname -> list of world-rank placeholders in registration order
        self.hostnames_by_rank: "OrderedDict[str, List[int]]" = OrderedDict()
        self.world_size = 0

    @property
    def node_id_by_rank(self) -> Dict[int, str]:
        out = {}
        for hostname, ranks in self.hostnames_by_rank.items():
            for r in ranks:
                out[r] = hostname
        return out

    def register(self, hostname: str, world_rank: int) -> None:
        self.hostnames_by_rank.setdefault(hostname, []).append(world_rank)
        self.world_size += 1

    def finalize_registration(self) -> Dict[int, Dict[str, str]]:
        """Return per-worker env maps (reference ``runner.py:84-126``).

        Follows the launcher env contract (``runner/launch.py``
        ``make_worker_env``): ``HVD_TPU_CROSS_RANK``/``CROSS_SIZE`` are
        the *process id / process count* consumed by
        ``runtime._init_distributed`` as ``jax.distributed`` identity —
        NOT the reference's host-index semantics, which live in
        ``HVD_TPU_HOST_RANK``/``HOST_SIZE`` here.
        """
        rank_to_info: Dict[int, Dict[str, Any]] = {}
        host_size = len(self.hostnames_by_rank)
        for host_rank, (hostname, ranks) in enumerate(
            self.hostnames_by_rank.items()
        ):
            local_size = len(ranks)
            for local_rank, world_rank in enumerate(sorted(ranks)):
                rank_to_info[world_rank] = dict(
                    hostname=hostname,
                    rank=world_rank,
                    local_rank=local_rank,
                    local_size=local_size,
                    host_rank=host_rank,
                    host_size=host_size,
                )
        size = self.world_size
        envs: Dict[int, Dict[str, str]] = {}
        for world_rank, info in rank_to_info.items():
            envs[world_rank] = {
                "HVD_TPU_HOSTNAME": info["hostname"],
                "HVD_TPU_CROSS_RANK": str(info["rank"]),
                "HVD_TPU_CROSS_SIZE": str(size),
                "HVD_TPU_LOCAL_RANK": str(info["local_rank"]),
                "HVD_TPU_LOCAL_SIZE": str(info["local_size"]),
                "HVD_TPU_HOST_RANK": str(info["host_rank"]),
                "HVD_TPU_HOST_SIZE": str(info["host_size"]),
            }
        return envs

    def slot_infos(self) -> List[SlotInfo]:
        envs = self.finalize_registration()
        return [
            SlotInfo(
                hostname=e["HVD_TPU_HOSTNAME"],
                rank=int(e["HVD_TPU_CROSS_RANK"]),
                local_rank=int(e["HVD_TPU_LOCAL_RANK"]),
                cross_rank=int(e["HVD_TPU_HOST_RANK"]),
                size=int(e["HVD_TPU_CROSS_SIZE"]),
                local_size=int(e["HVD_TPU_LOCAL_SIZE"]),
                cross_size=int(e["HVD_TPU_HOST_SIZE"]),
            )
            for _, e in sorted(envs.items())
        ]


class RayExecutor:
    """Run a function on a fleet of Ray actors, one per slot.

    Reference: ``ray/runner.py:128-396``.  ``num_workers`` slots are
    placed by ``strategy`` ('pack' minimizes node count, 'spread'
    maximizes it), each actor receives the Coordinator-derived env plus
    the JAX distributed-coordinator address, then runs ``fn``.
    """

    def __init__(
        self,
        settings: Optional[Dict[str, Any]] = None,
        num_workers: Optional[int] = None,
        num_hosts: Optional[int] = None,
        num_workers_per_host: int = 1,
        cpus_per_worker: int = 1,
        use_current_placement_group: bool = True,
        strategy: str = "pack",
    ):
        if num_workers is None and num_hosts is None:
            raise ValueError("specify num_workers or num_hosts")
        self.settings = settings or {}
        self.num_workers = num_workers or (num_hosts * num_workers_per_host)
        self.num_workers_per_host = num_workers_per_host
        self.cpus_per_worker = cpus_per_worker
        self.strategy_name = strategy
        self.use_current_placement_group = use_current_placement_group
        self.workers: List[Any] = []
        self.coordinator = Coordinator(self.settings)
        self._pg = None

    def placement_bundles(self) -> List[Dict[str, int]]:
        from .strategy import PackStrategy, SpreadStrategy

        cls = PackStrategy if self.strategy_name == "pack" else SpreadStrategy
        return cls(
            num_workers=self.num_workers,
            num_workers_per_host=self.num_workers_per_host,
            cpus_per_worker=self.cpus_per_worker,
        ).bundles()

    def start(self, executable_cls: Optional[type] = None,
              executable_args: Optional[list] = None) -> None:
        ray = _ray()
        from ray.util.placement_group import placement_group

        bundles = self.placement_bundles()
        self._pg = placement_group(
            bundles, strategy="PACK" if self.strategy_name == "pack" else "SPREAD"
        )
        ray.get(self._pg.ready())

        @ray.remote(num_cpus=self.cpus_per_worker)
        class Worker:
            def __init__(self):
                import socket

                self.hostname = socket.gethostname()

            def info(self):
                import ray.util

                return self.hostname, ray.util.get_node_ip_address()

            def free_port(self):
                from horovod_tpu.runner.launch import free_port

                return free_port()

            def set_env(self, env):
                import os

                os.environ.update(env)

            def execute(self, fn, *a, **kw):
                return fn(*a, **kw)

        self.workers = [
            Worker.options(placement_group=self._pg).remote()
            for _ in range(self.num_workers)
        ]
        infos = ray.get([w.info.remote() for w in self.workers])
        for world_rank, (hostname, _ip) in enumerate(infos):
            self.coordinator.register(hostname, world_rank)
        envs = self.coordinator.finalize_registration()
        # Worker 0 hosts the jax.distributed coordination service; every
        # actor gets its address (runtime._init_distributed contract).
        coord_ip = infos[0][1]
        coord_port = ray.get(self.workers[0].free_port.remote())
        for e in envs.values():
            e["HVD_TPU_COORDINATOR_ADDR"] = f"{coord_ip}:{coord_port}"
        ray.get([
            w.set_env.remote(envs[i]) for i, w in enumerate(self.workers)
        ])

    def run(self, fn: Callable, args: Optional[list] = None,
            kwargs: Optional[dict] = None) -> List[Any]:
        ray = _ray()
        args, kwargs = args or [], kwargs or {}
        return ray.get([
            w.execute.remote(fn, *args, **kwargs) for w in self.workers
        ])

    def execute(self, fn: Callable) -> List[Any]:
        """Apply ``fn(worker)`` on each actor (reference ``execute``)."""
        ray = _ray()
        return ray.get([w.execute.remote(fn) for w in self.workers])

    def shutdown(self) -> None:
        ray = _ray()
        for w in self.workers:
            ray.kill(w)
        self.workers = []
        if self._pg is not None:
            from ray.util.placement_group import remove_placement_group

            remove_placement_group(self._pg)
            self._pg = None
