"""Benchmark entry point (run by the driver on real TPU hardware).

Measures ResNet-50 synthetic-data training throughput per chip — the
TPU equivalent of the reference's
``examples/pytorch/pytorch_synthetic_benchmark.py`` / the
``docs/benchmarks.rst`` tf_cnn_benchmarks methodology (batch 64,
synthetic ImageNet, fwd+bwd+allreduce+update).

Prints one JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline: the reference publishes 1656.82 images/sec for ResNet-101 on
16 P100s (``docs/benchmarks.rst:32-43``) = 103.55 images/sec/GPU; no
per-GPU ResNet-50 number exists in-tree, so vs_baseline compares our
ResNet-50/chip against that 103.55 img/s/P100 figure (the closest
published per-accelerator number).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import ResNet50

BASELINE_IMG_PER_SEC_PER_ACCEL = 1656.82 / 16  # docs/benchmarks.rst:32-43


def main():
    hvd.init()
    batch_per_chip = 64
    image_size = 224
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, image_size, image_size, 3)),
        train=True,
    )
    params, batch_stats = variables["params"], variables["batch_stats"]
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=hvd.Compression.bf16
    )

    def loss_fn(p, stats, batch):
        x, y = batch
        logits, updated = model.apply(
            {"params": p, "batch_stats": stats}, x, train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        return loss, updated["batch_stats"]

    step = hvd.distributed_train_step(loss_fn, tx, stateful=True)
    opt_state = step.init(params)

    global_batch = batch_per_chip * hvd.size()
    rng = np.random.RandomState(0)
    data = jnp.asarray(
        rng.rand(global_batch, image_size, image_size, 3), jnp.float32
    )
    target = jnp.asarray(rng.randint(0, 1000, global_batch), jnp.int32)

    for _ in range(5):  # warmup + compile
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, (data, target)
        )
    # Force real completion with a scalar host transfer:
    # block_until_ready is not a reliable fence on every PJRT transport
    # (observed on the axon relay), but a device->host read is.
    float(loss)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, (data, target)
        )
    float(loss)  # final loss depends on the whole step chain
    dt = time.perf_counter() - t0

    ips_per_chip = global_batch * iters / dt / hvd.size()
    print(
        json.dumps(
            {
                "metric": "resnet50_synthetic_train_throughput",
                "value": round(ips_per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(ips_per_chip / BASELINE_IMG_PER_SEC_PER_ACCEL, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
