"""Benchmark entry point (run by the driver on real TPU hardware).

Measures two flagship workloads and reports MFU against the detected
chip's peak, per the tf_cnn_benchmarks methodology the reference
publishes (``docs/benchmarks.rst:67-80``: synthetic data, warmup then
timed iterations, fwd+bwd+allreduce+update):

  * ResNet-50 synthetic ImageNet training (images/sec/chip) — the
    reference's headline CNN benchmark
    (``examples/pytorch/pytorch_synthetic_benchmark.py``).
  * GPT-2-small (124M) LM training (tokens/sec/chip) — the scaling
    workload; MFU via the 6ND + attention FLOPs estimate.

Prints ONE JSON line.  The primary metric stays the ResNet-50
images/sec/chip (comparable across rounds); step time, MFU, and the GPT
numbers ride along as extra fields.  On any failure a JSON line with an
``"error"`` field is still emitted (degraded-run hardening).

Baseline: the reference publishes 1656.82 images/sec for ResNet-101 on
16 P100s (``docs/benchmarks.rst:32-43``) = 103.55 images/sec/GPU; no
per-GPU ResNet-50 number exists in-tree, so vs_baseline compares our
ResNet-50/chip against that 103.55 img/s/P100 figure (the closest
published per-accelerator number).
"""

import json
import os
import subprocess
import sys
import time
from typing import Optional

BASELINE_IMG_PER_SEC_PER_ACCEL = 1656.82 / 16  # docs/benchmarks.rst:32-43

# Best completed sweep result so far: emitted instead of a bare error
# when a later config (or the GPT workload) hangs past the deadline.
_PARTIAL = None

# When the SIGALRM was armed (__main__): the sweep's remaining-budget
# guards must measure against the real deadline, not main()'s start —
# the device probe + init can eat minutes before main() runs.
_ALARM_ARMED_AT = None

# Device peak model: shared with the online MFU gauge and the ResNet
# sweep (one table, added-to once) — see horovod_tpu/prof/peak.py.
from horovod_tpu.prof.peak import (  # noqa: E402
    PEAK_BF16_TFLOPS as _PEAK_BF16_TFLOPS,
    RESNET50_TRAIN_GFLOPS_PER_IMAGE,
    chip_peak_tflops as _chip_peak_tflops,
    measured_peak_tflops as _measured_peak_tflops,
    peak_tflops as _peak_tflops,
)


def _phase_profile(hvd, jnp, model, params, batch_stats, data, target,
                   step_ms: float, iters: int = 3) -> dict:
    """Per-step phase split: time a forward-only and a forward+backward
    (local-grad, no exchange) program and difference them against the
    full step — where the milliseconds go (compute vs gradient exchange
    + update) without a device profiler trace."""
    import jax
    import optax

    def fwd(p, stats, x, y):
        logits, _ = model.apply(
            {"params": p, "batch_stats": stats}, x, train=True,
            mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    f_fwd = jax.jit(fwd)
    f_grad = jax.jit(jax.grad(fwd))

    def timed(f, reduce_out):
        out = f(params, batch_stats, data, target)
        float(reduce_out(out))  # compile fence
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(params, batch_stats, data, target)
        float(reduce_out(out))
        return (time.perf_counter() - t0) / iters * 1000.0

    fwd_ms = timed(f_fwd, lambda o: o)
    fwdbwd_ms = timed(
        f_grad, lambda g: jax.tree.leaves(g)[0].reshape(-1)[0]
    )
    return {
        "forward_ms": round(fwd_ms, 2),
        "backward_ms": round(max(fwdbwd_ms - fwd_ms, 0.0), 2),
        "exchange_update_ms": round(max(step_ms - fwdbwd_ms, 0.0), 2),
    }


def bench_resnet(hvd, jnp, batch_per_chip: int, iters: int = 20,
                 stem: str = "conv7", profile: bool = False) -> dict:
    import jax

    from horovod_tpu.models import ResNet50
    from horovod_tpu.utils.benchmarks import build_dp_step, timed_throughput

    image_size = 224
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16, stem=stem)
    step, params, batch_stats, opt_state = build_dp_step(
        hvd, model, image_size, compression=hvd.Compression.bf16,
    )

    global_batch = batch_per_chip * hvd.size()
    key = jax.random.PRNGKey(1)
    data = jax.random.uniform(
        key, (global_batch, image_size, image_size, 3), jnp.float32
    )
    target = jax.random.randint(key, (global_batch,), 0, 1000, jnp.int32)

    dt, (params, batch_stats, opt_state) = timed_throughput(
        step, params, batch_stats, opt_state, (data, target), iters,
        warmup=5,
    )

    ips_per_chip = global_batch * iters / dt / hvd.size()
    step_ms = dt / iters * 1000.0
    peak, peak_source = _peak_tflops(jax.devices()[0])
    achieved_tflops = ips_per_chip * RESNET50_TRAIN_GFLOPS_PER_IMAGE / 1000.0
    out = {
        "images_per_sec_per_chip": round(ips_per_chip, 2),
        "step_time_ms": round(step_ms, 2),
        "batch_per_chip": batch_per_chip,
        "achieved_tflops": round(achieved_tflops, 1),
        "mfu": round(achieved_tflops / peak, 4),
        "peak_source": peak_source,
    }
    if profile:
        try:
            # the step donates its inputs, so the profile must use the
            # FINAL state timed_throughput handed back, never the
            # originals (donated buffers are deleted)
            out["phase_profile"] = _phase_profile(
                hvd, jnp, model, params, batch_stats, data, target,
                step_ms,
            )
        except Exception as e:  # profiling is advisory, never fatal
            out["phase_profile"] = {
                "error": f"{type(e).__name__}: {e}"
            }
    return out


def bench_gpt(hvd, jnp, batch_per_chip: int = 16, seq_len: int = 1024,
              iters: int = 10, packed: bool = False) -> dict:
    import jax
    import numpy as np
    import optax

    from horovod_tpu.models.transformer import (
        gpt_small,
        packed_token_cross_entropy,
        token_cross_entropy,
    )

    model = gpt_small(max_len=seq_len)
    cfg = model.cfg
    b_global = batch_per_chip * hvd.size()
    pack_stats = {}
    if packed:
        # Realistic document-length mix (lognormal, mean ~420 tokens):
        # unpacked each doc would waste (seq_len - len) pad positions;
        # packing recovers that as useful compute.
        from horovod_tpu.data.packing import (
            pack_documents,
            packing_efficiency,
        )

        rng = np.random.RandomState(3)
        docs, rows = [], 0
        while rows < b_global + 2:
            ln = int(np.clip(rng.lognormal(5.8, 0.7), 32, seq_len))
            docs.append(rng.randint(0, cfg.vocab_size, ln).astype(np.int32))
            rows = sum(len(d) for d in docs) // seq_len
        tok_np, seg_np = pack_documents(docs, seq_len)
        tok_np, seg_np = tok_np[:b_global], seg_np[:b_global]
        toks = jnp.asarray(tok_np)
        segs = jnp.asarray(seg_np)
        eff_packed = packing_efficiency(seg_np)
        eff_padded = float(np.mean([len(d) for d in docs]) / seq_len)
        pack_stats = {
            "packing_efficiency": round(eff_packed, 4),
            "padded_row_efficiency": round(eff_padded, 4),
            "speedup_vs_padded_rows": round(eff_packed / eff_padded, 2),
        }
        batch = (toks, segs)
    else:
        toks = jax.random.randint(
            jax.random.PRNGKey(2),
            (b_global, seq_len), 0, cfg.vocab_size, jnp.int32,
        )
        batch = toks
    params = model.init(jax.random.PRNGKey(0), toks[:1])
    params = hvd.broadcast_parameters(params, root_rank=0)
    n_params = sum(x.size for x in jax.tree.leaves(params))

    tx = hvd.DistributedOptimizer(
        optax.adamw(3e-4), compression=hvd.Compression.bf16
    )

    if packed:
        def loss_fn(p, batch):
            t, s = batch
            logits, aux = model.apply(p, t, s)
            return packed_token_cross_entropy(logits, t, s) + 0.01 * aux
    else:
        def loss_fn(p, batch):
            logits, aux = model.apply(p, batch)
            tgt = jnp.roll(batch, -1, axis=-1)
            # gather-form CE: no (B, T, vocab) one-hot temporary (~3 GB
            # at this config) on the hot path
            return token_cross_entropy(logits, tgt) + 0.01 * aux

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)

    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, batch)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, batch)
    float(loss)
    dt = time.perf_counter() - t0

    tokens = batch_per_chip * seq_len * iters
    tps_per_chip = tokens / dt
    # Train FLOPs/token: 6*N (fwd 2N + bwd 4N) plus attention
    # 12 * L * T * d_model (QK^T and AV, fwd+bwd).
    flops_per_token = (
        6.0 * n_params
        + 12.0 * cfg.num_layers * seq_len * cfg.num_heads * cfg.head_dim
    )
    achieved_tflops = tps_per_chip * flops_per_token / 1e12
    peak, peak_source = _peak_tflops(jax.devices()[0])
    out = {
        "tokens_per_sec_per_chip": round(tps_per_chip, 1),
        "step_time_ms": round(dt / iters * 1000.0, 2),
        "batch_per_chip": batch_per_chip,
        "seq_len": seq_len,
        "params_millions": round(n_params / 1e6, 1),
        "achieved_tflops": round(achieved_tflops, 1),
        "mfu": round(achieved_tflops / peak, 4),
        "peak_source": peak_source,
    }
    if packed:
        out.update(pack_stats)
        out["useful_tokens_per_sec_per_chip"] = round(
            tps_per_chip * pack_stats["packing_efficiency"], 1
        )
    return out


def main():
    global _PARTIAL

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    device = jax.devices()[0]
    result = {
        "metric": "resnet50_synthetic_train_throughput",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "device_kind": device.device_kind,
        "peak_bf16_tflops": _chip_peak_tflops(device),
    }
    if device.platform == "cpu":
        # A CPU-only backend cannot finish the 224px ResNet-50 sweep
        # inside the deadline (the alarm would fire mid-compile and the
        # round would record a raw error blob).  Measure the CPU-sim
        # resnet config instead — a real, non-null images/sec + MFU
        # with peak_source provenance, flagged scale=cpu_sim — plus
        # every device-free record.
        result["reason"] = (
            "cpu-only backend: resnet50@224 cannot finish inside the "
            "deadline; measured the cpu_sim config instead"
        )
        deadline_s = int(os.environ.get("HVD_BENCH_DEADLINE_S", "480"))
        t_start = _ALARM_ARMED_AT if _ALARM_ARMED_AT is not None else (
            time.monotonic()
        )
        _device_free_records(result, deadline_s, t_start)
        print(json.dumps(result))
        return
    # Config sweep (HVD_BENCH_SWEEP=0 pins the single explicit config):
    # space-to-depth leads (the known MFU winner for the 7x7/2 stem on
    # MXU hardware — the SNIPPETS.md MFU>=0.30 target's first lever),
    # with the conv7 baseline and larger batches swept after.  Each
    # config is guarded, earlier results survive a late failure, and
    # the primary metric is the best completed config.
    stem = os.environ.get("HVD_BENCH_STEM", "space_to_depth")
    if stem not in ("conv7", "space_to_depth"):
        # fail before paying any compile: the __main__ wrapper turns
        # this into the error-JSON line the driver records
        raise ValueError(
            f"HVD_BENCH_STEM must be 'conv7' or 'space_to_depth', "
            f"got {stem!r}"
        )
    sweep = os.environ.get("HVD_BENCH_SWEEP", "1") != "0"
    deadline_s = int(os.environ.get("HVD_BENCH_DEADLINE_S", "480"))
    t_start = _ALARM_ARMED_AT if _ALARM_ARMED_AT is not None else (
        time.monotonic()
    )
    configs = [(stem, 256)]
    if sweep:
        for cfg in (("space_to_depth", 256), ("space_to_depth", 512),
                    ("conv7", 256), ("conv7", 512)):
            if cfg not in configs:
                configs.append(cfg)
    runs = []
    hit_deadline = False
    for i, (s, b) in enumerate(configs):
        # budget check: a config costs ~60s (compile+timed run); always
        # run the first, keep ~120s for the GPT workload afterwards
        remaining = deadline_s - (time.monotonic() - t_start)
        if i > 0 and remaining < 180:
            break
        try:
            # phase-profile the primary config only (two extra compiles)
            r = bench_resnet(hvd, jnp, batch_per_chip=b, stem=s,
                             profile=(i == 0))
            r["stem"] = s
            runs.append(r)
        except TimeoutError as e:
            # The one-shot SIGALRM fired: the device is wedged and the
            # alarm is disarmed — no further device calls, ever.
            runs.append({"stem": s, "batch_per_chip": b,
                         "error": f"TimeoutError: {e}"})
            hit_deadline = True
        except Exception as e:  # OOM at 512 etc: keep earlier results
            runs.append({"stem": s, "batch_per_chip": b,
                         "error": f"{type(e).__name__}: {e}"})
        ok = [r for r in runs if "error" not in r]
        if ok:
            best = max(ok, key=lambda r: r["images_per_sec_per_chip"])
            result.update(
                value=best["images_per_sec_per_chip"],
                vs_baseline=round(
                    best["images_per_sec_per_chip"]
                    / BASELINE_IMG_PER_SEC_PER_ACCEL, 3
                ),
                step_time_ms=best["step_time_ms"],
                batch_per_chip=best["batch_per_chip"],
                mfu=best["mfu"],
                peak_source=best.get("peak_source"),
                achieved_tflops=best["achieved_tflops"],
                stem=best["stem"],
                sweep=runs if sweep else None,
            )
            if "phase_profile" in runs[0]:
                result["phase_profile"] = runs[0]["phase_profile"]
            # a mid-sweep device hang must not discard finished configs
            _PARTIAL = dict(result)
        if hit_deadline:
            break
    if not any("error" not in r for r in runs):
        raise RuntimeError(f"all resnet configs failed: {runs}")
    if hit_deadline:
        # alarm already fired (and is one-shot): emit what we have
        # rather than touching the wedged device again
        result["sweep_note"] = "deadline hit during sweep; gpt skipped"
        print(json.dumps(result))
        return
    try:
        gpt = bench_gpt(hvd, jnp)
        result["gpt2_small"] = gpt
        _PARTIAL = dict(result)
        # batch 32 halves the per-token overhead if it fits — measure
        # it when budget remains, keep whichever clocks faster
        if sweep and deadline_s - (time.monotonic() - t_start) > 120:
            try:
                gpt32 = bench_gpt(hvd, jnp, batch_per_chip=32)
                if (gpt32["tokens_per_sec_per_chip"]
                        > gpt["tokens_per_sec_per_chip"]):
                    result["gpt2_small"] = gpt32
                result["gpt2_small"]["sweep"] = [
                    {k: r[k] for k in
                     ("batch_per_chip", "tokens_per_sec_per_chip", "mfu")}
                    for r in (gpt, gpt32)
                ]
            except TimeoutError as e:
                result["gpt2_small"]["sweep_note"] = (
                    f"batch-32 probe aborted: {e}"
                )
            except Exception as e:  # OOM at 32: batch-16 result stands
                result["gpt2_small"]["sweep_note"] = (
                    f"batch-32 probe failed: {type(e).__name__}: {e}"
                )
        # Packed-sequence config: the LM-throughput lever on real
        # (variable-length) documents — reported separately with its
        # packing-efficiency provenance, not competing in the dense
        # sweep max.
        if sweep and deadline_s - (time.monotonic() - t_start) > 120:
            try:
                result["gpt2_small_packed"] = bench_gpt(
                    hvd, jnp, packed=True
                )
                _PARTIAL = dict(result)
            except TimeoutError as e:
                result["gpt2_small_packed"] = {
                    "error": f"TimeoutError: {e}"
                }
            except Exception as e:
                result["gpt2_small_packed"] = {
                    "error": f"{type(e).__name__}: {e}"
                }
    except TimeoutError as e:
        # no retry on a disarmed alarm: the device is gone
        result["gpt2_small"] = {"error": f"TimeoutError: {e}"}
    except Exception:  # e.g. OOM at batch 16: retry the known-good size
        try:
            result["gpt2_small"] = bench_gpt(hvd, jnp, batch_per_chip=8)
        except Exception as e:  # secondary workload must not sink primary
            result["gpt2_small"] = {"error": f"{type(e).__name__}: {e}"}
    _device_free_records(result, deadline_s, t_start)
    print(json.dumps(result))


def _device_free_records(result: dict, deadline_s: float,
                         t_start: float) -> None:
    """Every record that needs no device tunnel, in budget order: the
    CPU-sim resnet fallback (only when the primary metric is missing)
    plus the scaling/topo/quant/adasum/railpipe subprocess records.
    One function serves the cpu-only path, the probe-skip path, and
    the regression test that pins "a hung probe still yields real sim
    records" — the skip path can no longer drift away from the record
    list."""
    if result.get("value", 0.0) == 0.0:
        _cpu_resnet_fallback(result, deadline_s, t_start)
    _maybe_scaling(result, deadline_s, t_start)
    _maybe_topo(result, deadline_s, t_start)
    _maybe_quant_backend(result, deadline_s, t_start)
    _maybe_adasum(result, deadline_s, t_start)
    _maybe_railpipe(result, deadline_s, t_start)
    _maybe_onestep(result, deadline_s, t_start)
    _maybe_svc_fusion(result, deadline_s, t_start)
    _maybe_tenant(result, deadline_s, t_start)
    _maybe_serve(result, deadline_s, t_start)


def _maybe_svc_fusion(result: dict, deadline_s: float,
                      t_start: float) -> None:
    """Append the ``svc_fusion_amortization`` record
    (HVD_BENCH_FUSION=0 skips): the service-side fusion buffer's
    step-time speedup on the N=32 small-program workload, fused vs
    serial dispatch, via ``tools/topo_bench.py --fusion`` in a
    scrubbed 8-device CPU subprocess (docs/exchange_service.md
    "Fusion buffers").  Structured-skip on deadline pressure like the
    other device-free records."""
    if os.environ.get("HVD_BENCH_FUSION", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["svc_fusion_amortization"] = {
            "error": "skipped: deadline too close"
        }
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = _scrubbed_cpu_env()
        env.setdefault("HVD_TPU_TOPO", "2x4")
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py"),
             "--fusion"],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["svc_fusion_amortization"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["svc_fusion_amortization"] = {
            "error": f"{type(e).__name__}: {e}"
        }


def _maybe_tenant(result: dict, deadline_s: float,
                  t_start: float) -> None:
    """Append the ``svc_tenant_interference`` record
    (HVD_BENCH_TENANT=0 skips): two tenants sharing one service — A's
    small ICI-local exchanges vs B's DCN-heavy buckets — measured
    three ways (B off / FIFO / arbiter) via ``tools/topo_bench.py
    --tenant`` in a scrubbed 8-device CPU subprocess
    (docs/multitenant.md).  The headline is tenant A's step-time p99
    shift when B turns on: the arbiter must hold it under the 10%
    bound the FIFO baseline measurably breaks."""
    if os.environ.get("HVD_BENCH_TENANT", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["svc_tenant_interference"] = {
            "error": "skipped: deadline too close"
        }
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = _scrubbed_cpu_env()
        env.setdefault("HVD_TPU_TOPO", "2x4")
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py"),
             "--tenant"],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["svc_tenant_interference"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["svc_tenant_interference"] = {
            "error": f"{type(e).__name__}: {e}"
        }


def _maybe_serve(result: dict, deadline_s: float,
                 t_start: float) -> None:
    """Append the ``serve_plane`` record (HVD_BENCH_SERVE=0 skips):
    the inference serving plane's two measured claims via
    ``tools/topo_bench.py --serve`` in a scrubbed 8-device CPU
    subprocess (docs/serving.md).  (A) continuous batching vs
    sequential serving of the same 16-request synthetic trace —
    bitwise-identical tokens, continuous tokens/sec must win; (B)
    decode-tenant exchange p99 under prefill-tenant DCN bulk, FIFO vs
    arbiter — arbiter p99 must hold at or under 0.6x FIFO."""
    if os.environ.get("HVD_BENCH_SERVE", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["serve_plane"] = {
            "error": "skipped: deadline too close"
        }
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = _scrubbed_cpu_env()
        env.setdefault("HVD_TPU_TOPO", "2x4")
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py"),
             "--serve"],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["serve_plane"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["serve_plane"] = {
            "error": f"{type(e).__name__}: {e}"
        }


def _maybe_railpipe(result: dict, deadline_s: float,
                    t_start: float) -> None:
    """Append the ``railpipe_overlap`` record (HVD_BENCH_RAILPIPE=0
    skips): pipelined vs serialized hier multi-bucket exchange wall
    time on the simulated 2-slice mesh via ``tools/topo_bench.py
    --pipeline`` in a scrubbed 8-device CPU subprocess
    (docs/exchange_ir.md "Program scheduling").  Structured-skip on
    deadline pressure like the other device-free records."""
    if os.environ.get("HVD_BENCH_RAILPIPE", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["railpipe_overlap"] = {
            "error": "skipped: deadline too close"
        }
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = _scrubbed_cpu_env()
        env.setdefault("HVD_TPU_TOPO", "2x4")
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py"),
             "--pipeline"],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["railpipe_overlap"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["railpipe_overlap"] = {"error": f"{type(e).__name__}: {e}"}


def _maybe_onestep(result: dict, deadline_s: float,
                   t_start: float) -> None:
    """Append the ``onestep_hostgap`` record (HVD_BENCH_ONESTEP=0
    skips): the whole-step single-dispatch fold off vs on on the
    N-small-buckets service burst via ``tools/topo_bench.py
    --onestep`` in a scrubbed 8-device CPU subprocess
    (docs/exchange_ir.md "Whole-step emission").  Structured-skip on
    deadline pressure like the other device-free records."""
    if os.environ.get("HVD_BENCH_ONESTEP", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["onestep_hostgap"] = {
            "error": "skipped: deadline too close"
        }
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = _scrubbed_cpu_env()
        env.setdefault("HVD_TPU_TOPO", "2x4")
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py"),
             "--onestep"],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["onestep_hostgap"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["onestep_hostgap"] = {"error": f"{type(e).__name__}: {e}"}


def _scrubbed_cpu_env() -> dict:
    """Environment for the device-free CPU-subprocess records: repo on
    the path, 8 virtual CPU devices, every device-tunnel variable
    scrubbed (prepend/append, never clobber — the driver may rely on
    its own PYTHONPATH entries or XLA flags)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=8 "
        + env.get("XLA_FLAGS", "")
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    for key in ("JAX_PLATFORM_NAME", "PJRT_DEVICE",
                "TPU_LIBRARY_PATH", "PALLAS_AXON_POOL_IPS"):
        env.pop(key, None)
    return env


def _maybe_adasum(result: dict, deadline_s: float,
                  t_start: float) -> None:
    """Append the ``adasum_vs_sum`` record (HVD_BENCH_ADASUM=0 skips):
    steps-to-loss-target at 4x batch without LR retuning, flat summed
    gradients vs the ``hier_adasum`` lowering, on the simulated 2-slice
    mesh via ``tools/topo_bench.py --adasum`` in a scrubbed 8-device
    CPU subprocess (docs/adasum.md).  Structured-skip on deadline
    pressure like the other device-free records."""
    if os.environ.get("HVD_BENCH_ADASUM", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["adasum_vs_sum"] = {"error": "skipped: deadline too close"}
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = _scrubbed_cpu_env()
        env.setdefault("HVD_TPU_TOPO", "2x4")
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py"),
             "--adasum"],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["adasum_vs_sum"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["adasum_vs_sum"] = {"error": f"{type(e).__name__}: {e}"}


def _cpu_resnet_fallback(result: dict, deadline_s: float,
                         t_start: float) -> None:
    """Fill the primary resnet record from the CPU-sim measurement when
    the device probe is dead (``tools/resnet_cpu_bench.py``): the
    record then carries a *measured* non-null images/sec + MFU with
    ``peak_source`` provenance — flagged ``scale: cpu_sim`` so rounds
    on real chips never cross-compare with it — instead of the bare
    ``value 0.0`` skip blob BENCH_r05 recorded."""
    if deadline_s - (time.monotonic() - t_start) < 90:
        result["cpu_fallback"] = {"error": "skipped: deadline too close"}
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        out = sp.run(
            [sys.executable,
             os.path.join(repo, "tools", "resnet_cpu_bench.py")],
            capture_output=True, text=True, timeout=540,
            env=_scrubbed_cpu_env(), cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        rec = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        rec = {"error": f"{type(e).__name__}: {e}"}
    result["cpu_fallback"] = rec
    if "error" not in rec:
        result.update(
            value=rec["images_per_sec_per_chip"],
            vs_baseline=round(
                rec["images_per_sec_per_chip"]
                / BASELINE_IMG_PER_SEC_PER_ACCEL, 3
            ),
            step_time_ms=rec["step_time_ms"],
            batch_per_chip=rec["batch_per_chip"],
            mfu=rec["mfu"],
            peak_source=rec["peak_source"],
            achieved_tflops=rec["achieved_tflops"],
            scale="cpu_sim",
            status="cpu_fallback",
        )


def _maybe_scaling(result: dict, deadline_s: float,
                   t_start: float) -> None:
    """--scaling / HVD_BENCH_SCALING=1: append the weak-scaling
    efficiency record (the reference's headline metric,
    docs/benchmarks.rst:13-14) by running tools/scaling_bench.py on a
    scrubbed 8-device CPU backend in a subprocess — the structural
    collective-overhead ratio, produced unattended regardless of how
    many real chips this process owns (the parent already holds the
    accelerator, so a child could not re-open it; the true multi-chip
    figure comes from running tools/scaling_bench.py standalone on the
    slice)."""
    import sys

    if ("--scaling" not in sys.argv
            and os.environ.get("HVD_BENCH_SCALING", "0") != "1"):
        return
    if deadline_s - (time.monotonic() - t_start) < 90:
        result["scaling"] = {"error": "skipped: deadline too close"}
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        # prepend/append, never clobber: the driver may rely on its own
        # PYTHONPATH entries or XLA flags
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8 "
            + env.get("XLA_FLAGS", "")
        ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        for key in ("JAX_PLATFORM_NAME", "PJRT_DEVICE",
                    "TPU_LIBRARY_PATH"):
            env.pop(key, None)
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "scaling_bench.py"),
             "--batch-per-chip", "4", "--image-size", "32", "--iters", "5"],
            capture_output=True, text=True, timeout=600, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["scaling"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["scaling"] = {"error": f"{type(e).__name__}: {e}"}


def _maybe_topo(result: dict, deadline_s: float, t_start: float) -> None:
    """Append the ``topo_hier_vs_flat`` record (HVD_BENCH_TOPO=0 skips):
    flat-vs-hierarchical gradient exchange on a simulated 2-slice mesh,
    run by tools/topo_bench.py on a scrubbed 8-device CPU backend in a
    subprocess — the structural bytes-over-DCN ratio plus step times,
    produced unattended regardless of the real chip count (same
    rationale as the scaling record above)."""
    import sys

    if os.environ.get("HVD_BENCH_TOPO", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["topo_hier_vs_flat"] = {"error": "skipped: deadline too close"}
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8 "
            + env.get("XLA_FLAGS", "")
        ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("HVD_TPU_TOPO", "2x4")
        for key in ("JAX_PLATFORM_NAME", "PJRT_DEVICE",
                    "TPU_LIBRARY_PATH", "PALLAS_AXON_POOL_IPS"):
            env.pop(key, None)
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py")],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["topo_hier_vs_flat"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["topo_hier_vs_flat"] = {"error": f"{type(e).__name__}: {e}"}


def _maybe_quant_backend(result: dict, deadline_s: float,
                         t_start: float) -> None:
    """Append the ``quant_fused_vs_phase`` record (HVD_BENCH_QUANT=0
    skips): the int8 wire under the phase vs fused
    (``HVD_TPU_QUANT_BACKEND``) backends on the simulated 2-slice
    mesh, run by ``tools/topo_bench.py --quant`` in a scrubbed
    8-device CPU subprocess — per-bucket exchange wall time, wire
    bytes, fused-path counters, and the phase/fused loss delta.
    Structured-skip on probe/deadline failure like the topo record."""
    import sys

    if os.environ.get("HVD_BENCH_QUANT", "1") == "0":
        return
    if deadline_s - (time.monotonic() - t_start) < 75:
        result["quant_fused_vs_phase"] = {
            "error": "skipped: deadline too close"
        }
        return
    try:
        import subprocess as sp

        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8 "
            + env.get("XLA_FLAGS", "")
        ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("HVD_TPU_TOPO", "2x4")
        for key in ("JAX_PLATFORM_NAME", "PJRT_DEVICE",
                    "TPU_LIBRARY_PATH", "PALLAS_AXON_POOL_IPS"):
            env.pop(key, None)
        out = sp.run(
            [sys.executable, os.path.join(repo, "tools", "topo_bench.py"),
             "--quant"],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        line = (out.stdout or "").strip().splitlines()
        result["quant_fused_vs_phase"] = (
            json.loads(line[-1]) if out.returncode == 0 and line
            else {"error": f"rc={out.returncode}: {(out.stderr or '')[-300:]}"}
        )
    except Exception as e:
        result["quant_fused_vs_phase"] = {
            "error": f"{type(e).__name__}: {e}"
        }


# --- device-probe result cache (module level: tested directly) -------
#
# A successful probe is cached to a sidecar file so within 24 h the
# budget goes to the actual measurement instead of re-proving the same
# runtime boots.  The key must cover everything that changes what a
# probe proves: interpreter + jax version (the runtime), AND the
# HVD_TPU_SCHED*/WIRE*/TOPO*/QUANT* knob fingerprint — a knob change
# recompiles different programs, so a stale probe result must not be
# reused across it.  Kept dependency-free: importing horovod_tpu (and
# with it jax) before the probe would defeat the probe's purpose.

def _probe_cache_path() -> str:
    return os.environ.get(
        "HVD_BENCH_PROBE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_probe_cache.json"),
    )


def _knob_fingerprint() -> str:
    import hashlib

    prefixes = ("SCHED", "WIRE", "TOPO", "QUANT")
    items = []
    for k in sorted(os.environ):
        for head in ("HVD_TPU_", "HOROVOD_"):
            if k.startswith(head) and k[len(head):].startswith(prefixes):
                items.append((k, os.environ[k]))
                break
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def _probe_cache_key() -> str:
    try:
        from importlib.metadata import version

        jax_version = version("jax")
    except Exception:
        jax_version = "unknown"
    return f"{sys.executable}:{jax_version}:{_knob_fingerprint()}"


def _resolved_backend_record() -> dict:
    """The requested/platform/family triple every structured skip
    carries, so a reader can tell "no TPU on this host" from "GPU host
    routed through the gpu backend family" without rerunning anything.
    Hang-safe by construction: consults jax only when a backend is
    ALREADY initialized in this process (a wedged device tunnel hangs
    the first backend init forever — the exact failure these records
    describe); otherwise the platform field reports the JAX_PLATFORMS
    request."""
    requested = (os.environ.get("HVD_TPU_BACKEND")
                 or os.environ.get("HOROVOD_BACKEND") or "auto")
    platform = None
    try:
        jax_mod = sys.modules.get("jax")
        if jax_mod is not None:
            bridge = getattr(getattr(jax_mod, "_src", None),
                             "xla_bridge", None)
            if bridge is not None and getattr(bridge, "_backends", None):
                platform = str(jax_mod.default_backend())
    except Exception:
        platform = None
    if platform is None:
        platform = os.environ.get("JAX_PLATFORMS") or "uninitialized"
    fam = requested.strip().lower()
    fam = {"axon": "tpu", "cuda": "gpu", "rocm": "gpu",
           "nvidia": "gpu"}.get(fam, fam)
    if fam not in ("tpu", "gpu"):
        head = platform.split(",")[0].strip().lower()
        fam = "gpu" if head in ("gpu", "cuda", "rocm") else "tpu"
    return {"requested": requested, "platform": platform, "family": fam}


def emit_structured_abort(e: BaseException,
                          grace_s: Optional[int] = None) -> dict:
    """Last-resort primary record: structured skip, never a raw error
    blob (the BENCH_r05 failure mode — an escape that reached the
    outer handler printed ``{"error": "TimeoutExpired: ..."}`` with
    value 0.0 and no sim records).  Builds the same structured-skip
    shape the probe path emits, re-arms a bounded grace alarm, and
    still runs every device-free record — the CPU-sim resnet fallback
    fills the primary metric with a real measured number whenever the
    subprocess path survives.  Prints the JSON line and returns it."""
    import signal

    result = {
        "metric": "resnet50_synthetic_train_throughput",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "status": "skipped",
        "reason": (
            f"bench aborted before a primary measurement: "
            f"{type(e).__name__}: {e}".strip()
        ),
        "backend": _resolved_backend_record(),
    }
    if grace_s is None:
        grace_s = int(os.environ.get("HVD_BENCH_GRACE_S", "240"))
    try:
        # The one-shot deadline alarm may already have fired; the
        # device-free records run in their own subprocesses, so a fresh
        # bounded alarm keeps THIS pass from hanging without touching
        # the wedged device.
        if hasattr(signal, "alarm"):
            signal.alarm(0)
            signal.alarm(max(1, int(grace_s)))
        _device_free_records(result, grace_s, time.monotonic())
    except BaseException as e2:  # records are best-effort here
        if isinstance(e2, (KeyboardInterrupt, SystemExit)):
            raise
        result["records_error"] = f"{type(e2).__name__}: {e2}"
    finally:
        if hasattr(signal, "alarm"):
            signal.alarm(0)
    print(json.dumps(result))
    return result


def run_device_probe(deadline_s: float, armed_at: float,
                     retry=None):
    """Prove the device runtime boots before paying compiles in-process
    (the BENCH_r03..r05 failure mode: a wedged TPU tunnel hangs the
    first jax call forever).  Returns ``None`` when the device is live
    (or a fresh cache entry says so); on exhaustion returns the
    structured skip fields — a non-empty ``reason`` plus the probe
    subprocess's captured ``probe_stderr`` tail, so the round records
    *why* the tunnel died instead of a bare TimeoutExpired repr.

    Every attempt runs with its own bounded deadline **inside** the
    alarm window: the per-attempt subprocess timeout is recomputed
    from the remaining alarm budget (never more than half of it, and
    always leaving ≥ 90 s for the device-free records), so two
    attempts can never race the SIGALRM into the outer raw-error path.
    ``retry`` injects a prebuilt RetryPolicy (tests); the default is 2
    attempts with a 5 s backoff."""
    if _probe_cached_ok():
        return None

    stderr_tail = {"text": ""}

    def _tail(err) -> str:
        if err is None:
            return ""
        if isinstance(err, bytes):
            err = err.decode("utf-8", "replace")
        return str(err)[-400:]

    def _attempt():
        remaining = deadline_s - (time.monotonic() - armed_at)
        budget = max(20, int(min(
            float(os.environ.get("HVD_BENCH_PROBE_TIMEOUT_S", "150")),
            remaining / 2 - 45,
        )))
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp; "
                 "print(float(jnp.ones(8).sum()))"],
                capture_output=True, text=True,
                timeout=budget,
                env=dict(os.environ),
            )
        except subprocess.TimeoutExpired as e:
            # A hung probe still surfaces whatever the runtime said
            # before it stalled (partial stderr rides the exception).
            stderr_tail["text"] = _tail(getattr(e, "stderr", None))
            raise
        if probe.returncode != 0:
            stderr_tail["text"] = _tail(probe.stderr)
            raise RuntimeError(
                f"device probe failed (rc={probe.returncode})"
            )

    if retry is None:
        from horovod_tpu.utils.retry import RetryPolicy

        retry = RetryPolicy(
            max_attempts=2, base_delay_s=5.0, jitter=0.0,
            name="bench.probe",
            retry_on=(RuntimeError, subprocess.TimeoutExpired),
        )
    try:
        retry.call(_attempt)
    except BaseException as e:  # alarm TimeoutError included: probe
        # exhaustion must ALWAYS yield the structured skip record,
        # never the outer raw-error blob
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        skip = {
            "status": "skipped",
            "reason": (
                f"device probe exhausted retries: "
                f"{type(e).__name__}: {e}".strip()
                or "device probe exhausted retries"
            ),
            "probe_stderr": stderr_tail["text"],
            "backend": _resolved_backend_record(),
        }
        diagnosis = _probe_diagnosis(deadline_s, armed_at)
        if diagnosis is not None:
            skip["probe_diagnosis"] = diagnosis
        return skip
    _probe_cache_store()
    return None


def _probe_diagnosis(deadline_s: float, armed_at: float):
    """Best-effort root-cause pass over a dead probe: run the staged
    doctor (``tools/probe_doctor.py`` — import vs backend-init vs
    compute, each its own bounded subprocess) so the skip record names
    the sick layer instead of just "exhausted retries".  Bounded to
    the remaining alarm budget minus the device-free-records reserve;
    any failure (or no budget) returns None — the doctor must never
    sink the bench."""
    try:
        remaining = deadline_s - (time.monotonic() - armed_at)
        budget = min(
            float(os.environ.get("HVD_BENCH_DOCTOR_TIMEOUT_S", "30")),
            (remaining - 120) / len_doctor_stages(),
        )
        if budget < 5:
            return None
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "probe_doctor.py")
        spec = importlib.util.spec_from_file_location(
            "hvd_tpu_probe_doctor", path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.diagnose(timeout_s=budget)
    except Exception:
        return None


def len_doctor_stages() -> int:
    # the doctor's three stages (import / backend_init / compute); kept
    # as a function so the budget math above reads as intent
    return 3


def _probe_cached_ok() -> bool:
    try:
        with open(_probe_cache_path()) as f:
            rec = json.load(f)
        return (
            rec.get("key") == _probe_cache_key()
            and rec.get("ok") is True
            and 0 <= time.time() - rec.get("ts", 0) < 24 * 3600
        )
    except Exception:
        return False


def _probe_cache_store() -> None:
    try:
        path = _probe_cache_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"key": _probe_cache_key(), "ok": True,
                       "ts": time.time()}, f)
        os.replace(tmp, path)
    except Exception:
        pass  # cache is best-effort; never sink the bench


if __name__ == "__main__":
    # Hard deadline: a wedged device tunnel would otherwise hang forever
    # and the driver would record nothing — emit an error JSON instead.
    import signal

    def _deadline(signum, frame):
        raise TimeoutError(
            "bench deadline exceeded (device hang or tunnel stall)"
        )

    signal.signal(signal.SIGALRM, _deadline)
    _ALARM_ARMED_AT = time.monotonic()
    signal.alarm(int(os.environ.get("HVD_BENCH_DEADLINE_S", "480")))
    try:
        # Fail fast on a wedged device tunnel: probe device liveness in
        # a short-lived subprocess before paying compiles in-process
        # (run_device_probe above — a RetryPolicy around per-attempt
        # timeouts bounded inside the alarm window, with the probe's
        # stderr captured into the skip record; a successful probe is
        # cached to the sidecar so within 24 h the budget goes to the
        # actual measurement instead of re-proving the runtime boots).
        deadline_s = int(os.environ.get("HVD_BENCH_DEADLINE_S", "480"))
        probe_skip = run_device_probe(deadline_s, _ALARM_ARMED_AT)
        if probe_skip is not None:
            # Structured skip for the device-bound primary metric — but
            # the CPU-subprocess records need no device tunnel: the
            # resnet record itself falls back to a measured CPU-sim
            # number (non-null MFU with peak_source provenance), and
            # the scaling/topo/quant/adasum/railpipe records run as
            # usual, so a bench round with a wedged device still
            # produces real numbers instead of nothing.
            result = {
                "metric": "resnet50_synthetic_train_throughput",
                "value": 0.0,
                "unit": "images/sec/chip",
                "vs_baseline": 0.0,
            }
            result.update(probe_skip)
            _device_free_records(result, deadline_s, _ALARM_ARMED_AT)
            print(json.dumps(result))
            sys.exit(0)
        main()
    except Exception as e:  # TimeoutError from the alarm lands here too
        if _PARTIAL is not None:
            # A later sweep config or the GPT workload died, but a full
            # primary measurement finished: report it (with a note, not
            # an "error" field — the number is real).
            _PARTIAL["sweep_note"] = (
                f"later config aborted: {type(e).__name__}: {e}"
            )
            print(json.dumps(_PARTIAL))
        else:
            # No primary measurement at all: the structured-skip path
            # (status/reason + CPU-sim fallback + the device-free
            # records), never a raw {"error": ...} value-0.0 blob.
            emit_structured_abort(e)
        sys.exit(0)
