"""Elastic training on Spark — the reference's ``run_elastic`` story.

Reference: ``horovod/spark/runner.py:29`` (``run_elastic``) and the
elastic Spark integration tests (``elastic_spark_common.py``): Spark
tasks host the workers, a lost executor blacklists its host, the job
continues on the survivors, and Spark task retries re-register fresh
hosts.

Run on a real cluster (pyspark installed, SparkSession active)::

    python examples/spark_elastic.py --num-proc 4 --min-np 2

Smoke-run anywhere (no pyspark: subprocess agents + respawn watchdog
stand in for Spark tasks, with a simulated executor loss)::

    python examples/spark_elastic.py --local --simulate-loss
"""

import argparse
import os
import sys


def train(epochs: int, crash_round_rank=None):
    """Per-worker training fn: tiny DP regression with real collectives.
    ``crash_round_rank`` hard-kills one rank in round 1 (an executor
    loss mid-epoch) to demonstrate the recovery path."""
    import numpy as np

    import horovod_tpu as hvd

    rnd = int(os.environ.get("HVD_TPU_ELASTIC_ROUND", "0"))
    rank = int(os.environ["HVD_TPU_CROSS_RANK"])
    if crash_round_rank is not None and rnd == 1 and rank == crash_round_rank:
        os._exit(17)

    hvd.init()
    import jax.numpy as jnp
    import optax

    rng = np.random.RandomState(rank)
    X = rng.randn(32, 8).astype(np.float32)
    y = X @ np.arange(8.0, dtype=np.float32)
    params = {"w": jnp.zeros(8)}
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    losses = []
    for _ in range(epochs):
        params, opt_state, loss = step(
            params, opt_state, (jnp.asarray(X), jnp.asarray(y))
        )
        losses.append(float(loss))
    hvd.shutdown()
    return {
        "rank": rank,
        "round": rnd,
        "world": int(os.environ["HVD_TPU_CROSS_SIZE"]),
        "first_loss": losses[0],
        "last_loss": losses[-1],
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-proc", type=int, default=3)
    parser.add_argument("--min-np", type=int, default=2)
    parser.add_argument("--epochs", type=int, default=10)
    parser.add_argument("--local", action="store_true",
                        help="local agent backend (no pyspark needed)")
    parser.add_argument("--simulate-loss", action="store_true",
                        help="hard-kill rank 1 in round 1 to demo recovery")
    args = parser.parse_args()

    import cloudpickle

    from horovod_tpu.spark import run_elastic

    # workers import this module by path, not from site-packages
    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = run_elastic(
        train,
        kwargs={
            "epochs": args.epochs,
            "crash_round_rank": 1 if args.simulate_loss else None,
        },
        num_proc=args.num_proc,
        min_np=args.min_np,
        max_np=args.num_proc,
        extra_env={
            "HVD_TPU_FORCE_CPU": "1",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        } if args.local else None,
        _backend="local" if args.local else None,
    )
    print(f"job finished on round {results[0]['round']} with "
          f"{results[0]['world']} worker(s):")
    for r in results:
        print(f"  rank {r['rank']}: loss {r['first_loss']:.3f} -> "
              f"{r['last_loss']:.4f}")


if __name__ == "__main__":
    main()
