"""GPT pre-training with hybrid parallelism — the long-context flagship.

Capability add beyond the reference (SURVEY.md §5: Horovod has no
TP/SP/CP; its building blocks are alltoall + process sets): a GPT
language model trained over a ``dp × sp × tp`` mesh with

  - ring attention (``attn_impl="ring"``) streaming KV blocks around the
    ``sp`` axis via ``ppermute`` — sequence length scales with chips;
  - Megatron-style column/row tensor parallelism over ``tp``;
  - per-parameter mixed gradient sync (pmean over dp, psum for
    TP-sharded params) via ``sync_gradients``;
  - flash attention Pallas kernel inside each shard
    (``attn_impl="flash"``) when sequence fits on-chip.

Run (8-way virtual CPU mesh for a smoke test)::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/gpt_pretrain.py --dp 2 --sp 2 --tp 2 --steps 20
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd  # installs the jax<0.5 compat shims

shard_map = jax.shard_map
from horovod_tpu.models import gpt_small, gpt_tiny
from horovod_tpu.models.transformer import (
    packed_token_cross_entropy,
    param_shard_axes,
    token_cross_entropy,
)
from horovod_tpu.parallel import make_mesh, sync_gradients


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch-per-dp", type=int, default=2)
    parser.add_argument("--seq-per-sp", type=int, default=128)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--small", action="store_true",
                        help="124M GPT-2-small config instead of tiny")
    parser.add_argument("--attn", default="ring",
                        choices=["ring", "ulysses", "flash", "full"])
    parser.add_argument("--remat", action="store_true",
                        help="jax.checkpoint each block (long-context "
                        "activation memory)")
    parser.add_argument("--packed", action="store_true",
                        help="sequence packing: variable-length documents "
                        "share fixed rows under segment-id attention "
                        "masking (requires --attn flash/full, --sp 1)")
    args = parser.parse_args()
    if args.packed and (args.sp > 1 or args.attn not in ("flash", "full")):
        raise SystemExit(
            "--packed requires --sp 1 and --attn flash|full (packed rows "
            "are whole by construction; see docs/parallelism.md)"
        )

    hvd.init()
    mesh = make_mesh(dp=args.dp, sp=args.sp, tp=args.tp)
    build = gpt_small if args.small else gpt_tiny
    model = build(attn_impl=args.attn, max_len=args.seq_per_sp * args.sp,
                  remat=args.remat)
    cfg = model.cfg

    b = args.batch_per_dp * args.dp
    t = args.seq_per_sp * args.sp
    rng = np.random.RandomState(0)
    # Synthetic corpus: next-token prediction on structured random data.
    data = rng.randint(0, cfg.vocab_size, (64, t + 1)).astype(np.int32)
    if args.packed:
        # Variable-length "documents" packed into fixed rows: every
        # position does useful work instead of padding.
        from horovod_tpu.data.packing import (
            pack_documents,
            packing_efficiency,
        )

        docs = [
            rng.randint(
                0, cfg.vocab_size,
                int(np.clip(rng.lognormal(np.log(t / 3.0), 0.7), 8, t)),
            ).astype(np.int32)
            for _ in range(256)
        ]
        ptoks, psegs = pack_documents(docs, t)
        if hvd.rank() == 0:
            print(f"packed {len(docs)} docs into {len(ptoks)} rows, "
                  f"efficiency {packing_efficiency(psegs):.2f}")

    tx = optax.adamw(args.lr, b1=0.9, b2=0.95, weight_decay=0.1)
    shard_axes = None  # filled after init

    tok_spec = P("dp" if args.dp > 1 else None,
                 "sp" if args.sp > 1 else None)

    def init_step(toks):
        return model.init(jax.random.PRNGKey(0), toks)

    init_f = jax.jit(shard_map(
        init_step, mesh=mesh, in_specs=(tok_spec,),
        out_specs=P(),  # replicated container; TP params device-vary
        check_vma=False,
    ))
    toks0 = jnp.asarray(data[:b, :t])
    params = init_f(toks0)
    shard_axes = {"params": param_shard_axes(params["params"], cfg)}
    opt_state = jax.jit(shard_map(
        tx.init, mesh=mesh, in_specs=(P(),), out_specs=P(),
        check_vma=False,
    ))(params)

    def train_step(params, opt_state, toks, aux_in):
        """One SPMD step; ``aux_in`` is the shifted targets (dense mode)
        or the segment ids (--packed)."""
        def loss_fn(p):
            if args.packed:
                logits, aux = model.apply(p, toks, aux_in)
                ce = packed_token_cross_entropy(logits, toks, aux_in)
            else:
                logits, aux = model.apply(p, toks)
                # gather-form CE: no vocab-sized one-hot temporary
                ce = token_cross_entropy(logits, aux_in)
            return ce + 0.01 * aux  # aux = MoE load-balance (0 w/o MoE)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = sync_gradients(grads, shard_axes)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        axes = [a for a in ("dp", "sp", "tp") if a in mesh.axis_names]
        return params, opt_state, jax.lax.pmean(loss, tuple(axes))

    step_f = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), tok_spec, tok_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ), donate_argnums=(0, 1))

    losses = []
    t0 = time.time()
    for i in range(args.steps):
        if args.packed:
            rows = rng.randint(0, len(ptoks), b)
            toks = jnp.asarray(ptoks[rows])
            targets = jnp.asarray(psegs[rows])  # segment ids
        else:
            rows = rng.randint(0, len(data), b)
            toks = jnp.asarray(data[rows, :t])
            targets = jnp.asarray(data[rows, 1:t + 1])
        params, opt_state, loss = step_f(params, opt_state, toks, targets)
        losses.append(float(loss))
    jax.block_until_ready(loss)
    dt = time.time() - t0
    if hvd.rank() == 0:
        tok_s = args.steps * b * t / dt
        print(f"attn={args.attn} mesh dp{args.dp}/sp{args.sp}/tp{args.tp}: "
              f"loss {losses[0]:.3f} -> {losses[-1]:.3f}, "
              f"{tok_s:,.0f} tok/s")


if __name__ == "__main__":
    main()
