"""Synthetic CNN throughput benchmark (TPU-native equivalent of
reference ``examples/pytorch/pytorch_synthetic_benchmark.py`` and the
tf_cnn_benchmarks methodology cited by ``docs/benchmarks.rst``).

Measures images/sec for forward+backward+allreduce+update on synthetic
ImageNet-shaped data across the reference's headline models
(``--model resnet50|resnet101|vgg16|inception3``).
Run: ``python examples/synthetic_benchmark.py [--model resnet50]``.
"""

import argparse
import time

import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd
from horovod_tpu.models import InceptionV3, ResNet50, ResNet101, VGG16

MODELS = {
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "vgg16": VGG16,
    "inception3": InceptionV3,
}


def build_benchmark(args):
    from horovod_tpu.utils.benchmarks import build_dp_step

    kwargs = {}
    if args.model.startswith("resnet") and args.stem != "conv7":
        kwargs["stem"] = args.stem
    model = MODELS[args.model](num_classes=1000, dtype=jnp.bfloat16,
                               **kwargs)
    step, params, batch_stats, opt_state = build_dp_step(
        hvd, model, args.image_size,
        compression=hvd.Compression.fp16 if args.fp16_allreduce
        else hvd.Compression.none,
    )
    return model, params, batch_stats, step, opt_state


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50",
                        choices=sorted(MODELS))
    parser.add_argument("--batch-size", type=int, default=32,
                        help="per-chip batch (reference default 32)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--fp16-allreduce", action="store_true")
    parser.add_argument("--stem", default="conv7",
                        choices=["conv7", "space_to_depth"],
                        help="ResNet stem: space_to_depth folds the "
                        "7x7/3ch conv for MXU utilization")
    args = parser.parse_args()
    if args.stem != "conv7" and not args.model.startswith("resnet"):
        parser.error(f"--stem {args.stem} only applies to resnet models")

    hvd.init()
    model, params, batch_stats, step, opt_state = build_benchmark(args)

    global_batch = args.batch_size * hvd.size()
    rng = np.random.RandomState(0)
    data = jnp.asarray(
        rng.rand(global_batch, args.image_size, args.image_size, 3), jnp.float32
    )
    target = jnp.asarray(rng.randint(0, 1000, global_batch), jnp.int32)

    def run_one():
        nonlocal params, batch_stats, opt_state
        if batch_stats is not None:
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, (data, target)
            )
        else:
            params, opt_state, loss = step(params, opt_state, (data, target))
        return loss

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/chip x {hvd.size()} chips")
    loss = None
    for _ in range(args.num_warmup_batches):
        loss = run_one()
    if loss is not None:
        float(loss)  # scalar host read: a real completion fence on every transport

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            loss = run_one()
        float(loss)
        dt = time.perf_counter() - t0
        ips = global_batch * args.num_batches_per_iter / dt
        img_secs.append(ips)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {ips:.1f} img/sec total")
    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per chip: {mean / hvd.size():.1f} +- {conf / hvd.size():.1f}")
        print(f"Total img/sec on {hvd.size()} chip(s): {mean:.1f} +- {conf:.1f}")


if __name__ == "__main__":
    main()
