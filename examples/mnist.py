"""MNIST-style data-parallel training (the TPU-native equivalent of
reference ``examples/pytorch/pytorch_mnist.py``).

Run: ``python examples/mnist.py [--epochs N]``.  Uses a synthetic
MNIST-shaped dataset when the real one is unavailable (this image has no
network egress); the training mechanics — broadcast of initial params,
DistributedOptimizer allreduce each step, metric averaging — mirror the
reference script step for step.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MnistCNN


def synthetic_mnist(n=8192, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    # deterministic labels derived from the image so the task is learnable
    y = (x.mean(axis=(1, 2, 3)) * 1000).astype(np.int32) % 10
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-chip batch size (reference default 64)")
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--momentum", type=float, default=0.5)
    parser.add_argument("--use-adasum", action="store_true",
                        help="use Adasum gradient combining")
    parser.add_argument("--num-samples", type=int, default=8192,
                        help="synthetic dataset size (shrink for smoke tests)")
    args = parser.parse_args()

    hvd.init()  # reference: hvd.init()
    global_batch = args.batch_size * hvd.size()

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    # reference: hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    params = hvd.broadcast_parameters(params, root_rank=0)

    # reference: optimizer scaled by hvd.size(); Adasum uses local_size
    lr_scale = hvd.local_size() if args.use_adasum else hvd.size()
    tx = hvd.DistributedOptimizer(
        optax.sgd(args.lr * lr_scale, momentum=args.momentum),
        op=hvd.Adasum if args.use_adasum else hvd.Average,
    )

    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)

    X, Y = synthetic_mnist(n=args.num_samples)
    steps_per_epoch = len(X) // global_batch
    if steps_per_epoch < 1:
        raise SystemExit(
            f"--num-samples {args.num_samples} < global batch "
            f"{global_batch}; nothing to train"
        )
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(X))
        for i in range(steps_per_epoch):
            idx = perm[i * global_batch : (i + 1) * global_batch]
            params, opt_state, loss = step(
                params, opt_state, (jnp.asarray(X[idx]), jnp.asarray(Y[idx]))
            )
            if i % 10 == 0 and hvd.rank() == 0:
                print(f"epoch {epoch} step {i}/{steps_per_epoch} "
                      f"loss {float(loss):.4f}")
    if hvd.rank() == 0:
        print(f"final loss: {float(loss):.4f}")


if __name__ == "__main__":
    main()
