"""Process sets: concurrent collectives on disjoint rank subsets.

The reference fork's headline feature (CHANGELOG "Added process sets",
``common/process_set.{h,cc}``, ``test/parallel/test_process_sets_*``):
different subsets of ranks run *different* collectives at the same
time — e.g. two models trained side by side, or an encoder team and a
critic team syncing independently.

Here each process set lowers to XLA replica groups, so the two halves'
allreduces ride disjoint ICI links concurrently.  Run::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        HVD_TPU_DYNAMIC_PROCESS_SETS=1 python examples/process_sets.py
"""

import os

os.environ.setdefault("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MnistMLP


def main():
    hvd.init()
    n = hvd.size()
    if n < 2 or n % 2:
        raise SystemExit("need an even world size >= 2")

    # Two disjoint halves (reference: hvd.add_process_set([...]))
    even = hvd.add_process_set(list(range(0, n, 2)))
    odd = hvd.add_process_set(list(range(1, n, 2)))

    # --- eager: independent metric averages per team --------------------
    metrics = jnp.arange(n, dtype=jnp.float32).reshape(n, 1)
    even_avg = hvd.allreduce(metrics, op=hvd.Average, process_set=even)
    odd_avg = hvd.allreduce(metrics, op=hvd.Average, process_set=odd)
    # members of each set see their own team's average; non-members
    # pass through unchanged
    print("even-team avg:", float(even_avg[0, 0]),
          "| odd-team avg:", float(odd_avg[1, 0]))

    # --- two models trained concurrently, one per team ------------------
    # Both teams' allreduces appear in the same compiled step; XLA
    # schedules them on disjoint replica groups.
    model = MnistMLP()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 28, 28, 1).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 1000).astype(np.int32) % 10

    def make_team(ps, seed, lr):
        params = model.init(jax.random.PRNGKey(seed),
                            jnp.zeros((1, 28, 28, 1)))
        tx = hvd.DistributedOptimizer(optax.sgd(lr), process_set=ps)
        step = hvd.distributed_train_step(
            lambda p, b: optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, b[0]), b[1]).mean(),
            tx,
        )
        return params, step, step.init(params)

    pe, step_e, se = make_team(even, seed=0, lr=0.1)
    po, step_o, so = make_team(odd, seed=1, lr=0.05)
    batch = (jnp.asarray(x), jnp.asarray(y))
    for i in range(5):
        pe, se, loss_e = step_e(pe, se, batch)
        po, so, loss_o = step_o(po, so, batch)
    print(f"team even loss {float(loss_e):.4f} | "
          f"team odd loss {float(loss_o):.4f}")

    hvd.remove_process_set(even)
    hvd.remove_process_set(odd)


if __name__ == "__main__":
    main()
