"""Elastic MNIST training — survive worker joins/leaves mid-run.

TPU-native equivalent of reference
``examples/elastic/pytorch/pytorch_mnist_elastic.py``: wrap training in
``@hvd.elastic.run`` with an ``ArrayState``; on membership change the
state re-syncs from rank 0 and training continues from the last commit;
the ``ElasticSampler`` reshards remaining work over the new world.

Launch elastically::

    python -m horovod_tpu.runner --min-np 1 --max-np 4 \
        --host-discovery-script ./discover_hosts.sh \
        python examples/elastic_mnist.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ElasticSampler
from horovod_tpu.elastic import ArrayState
from horovod_tpu.models import MnistCNN


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 1000).astype(np.int32) % 10
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--batches-per-commit", type=int, default=10)
    args = parser.parse_args()

    hvd.init()
    x, y = synthetic_mnist()

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = hvd.DistributedOptimizer(
        optax.sgd(args.lr * hvd.size(), momentum=0.5)
    )

    def loss_fn(p, batch):
        xb, yb = batch
        logits = model.apply(p, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)

    sampler = ElasticSampler(dataset_size=len(x), seed=7)
    state = ArrayState(
        params=params, opt_state=opt_state, epoch=0, batch_idx=0,
        sampler_state=sampler.state_dict(),
    )

    @hvd.elastic.run
    def train(state):
        sampler.load_state_dict(state.sampler_state)
        sampler.reset()  # pick up the (possibly new) world size
        while state.epoch < args.epochs:
            indices = list(sampler)
            nb = len(indices) // args.batch_size
            for b in range(state.batch_idx, nb):
                idx = indices[b * args.batch_size:(b + 1) * args.batch_size]
                state.params, state.opt_state, loss = step(
                    state.params, state.opt_state,
                    (jnp.asarray(x[idx]), jnp.asarray(y[idx])),
                )
                sampler.record_batch(b, args.batch_size)
                if (b + 1) % args.batches_per_commit == 0:
                    state.batch_idx = b + 1
                    state.sampler_state = sampler.state_dict()
                    state.commit()  # checkpoint + host-update check
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss {float(loss):.4f} "
                      f"(world size {hvd.size()})")
            state.epoch += 1
            state.batch_idx = 0
            sampler.set_epoch(state.epoch)
            state.sampler_state = sampler.state_dict()
            state.commit()

    train(state)


if __name__ == "__main__":
    main()
