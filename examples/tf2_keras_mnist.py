"""TF2/Keras MNIST through the interop bridge (the tracked
``tf2_keras_mnist`` config — reference
``examples/tensorflow2/tensorflow2_keras_mnist.py`` mechanics:
``broadcast_variables`` after the first step, gradients averaged through
``DistributedGradientTape``, lr scaled by world size).

The keras model runs in TF on host CPU; gradient averaging rides the
runtime's XLA eager collectives.

Run: ``python examples/tf2_keras_mnist.py [--epochs N]``.
"""

import argparse

import numpy as np

import horovod_tpu as hvd
import horovod_tpu.interop.tf as hvd_tf


def synthetic_mnist(n=8192, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 1000).astype(np.int64) % 10
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.001)
    parser.add_argument("--num-samples", type=int, default=8192)
    parser.add_argument(
        "--use-fit", action="store_true",
        help="train via model.fit with DistributedOptimizer + "
             "BroadcastGlobalVariablesCallback (the reference's keras "
             "callback recipe) instead of the custom tape loop",
    )
    parser.add_argument(
        "--backward-passes-per-step", type=int, default=1,
        help="local gradient aggregation factor (reference keras knob)",
    )
    args = parser.parse_args()

    import tensorflow as tf

    hvd.init()  # reference: hvd.init()
    tf.random.set_seed(42)

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(32, 3, activation="relu",
                               input_shape=(28, 28, 1)),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True
    )
    # reference: lr scaled by the data-parallel worker count
    opt = tf.keras.optimizers.SGD(args.lr * hvd.process_count())

    x, y = synthetic_mnist(args.num_samples)
    # the torch/TF bridges reduce gradients at the PROCESS level
    # (one framework model per host process), so data sharding and
    # LR scaling follow process topology, not chip topology
    x = x[hvd.process_rank()::hvd.process_count()]
    y = y[hvd.process_rank()::hvd.process_count()]

    if args.use_fit:
        # reference recipe: wrap the optimizer, compile, and let the
        # callback broadcast model+optimizer state after the first
        # batch (slot variables are created lazily).
        opt = hvd_tf.DistributedOptimizer(
            opt, backward_passes_per_step=args.backward_passes_per_step,
            average_aggregated_gradients=args.backward_passes_per_step > 1,
        )
        model.compile(optimizer=opt, loss=loss_obj, metrics=["accuracy"])
        # every rank must run the SAME number of optimizer steps (each
        # one is a collective): derive steps from the MINIMUM shard
        # length (global // count — strided shards differ by up to one
        # sample) and drop the partial batch, the reference example's
        # steps_per_epoch trick.
        steps = (args.num_samples // hvd.process_count()) // args.batch_size
        x, y = x[: steps * args.batch_size], y[: steps * args.batch_size]
        hist = model.fit(
            x, y, batch_size=args.batch_size, epochs=args.epochs,
            steps_per_epoch=steps,
            verbose=1 if hvd.process_rank() == 0 else 0,
            callbacks=[hvd_tf.BroadcastGlobalVariablesCallback(0)],
        )
        if hvd.process_rank() == 0:
            print(f"final loss {hist.history['loss'][-1]:.4f}")
        return

    first_batch = True
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(x))
        losses = []
        for i in range(0, len(x) - args.batch_size + 1, args.batch_size):
            idx = perm[i:i + args.batch_size]
            data = tf.constant(x[idx])
            target = tf.constant(y[idx])
            with tf.GradientTape() as tape:
                logits = model(data, training=True)
                loss = loss_obj(target, logits)
            # reference: hvd.DistributedGradientTape wraps the tape
            tape = hvd_tf.DistributedGradientTape(tape)
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            if first_batch:
                # reference: broadcast AFTER the first step so optimizer
                # slot variables exist (tensorflow2_keras_mnist.py
                # BroadcastGlobalVariablesCallback comment)
                hvd_tf.broadcast_variables(model.variables, root_rank=0)
                hvd_tf.broadcast_variables(opt.variables, root_rank=0)
                first_batch = False
            losses.append(float(loss))
        avg = float(hvd.metric_average(float(np.mean(losses))))
        if hvd.process_rank() == 0:
            print(f"epoch {epoch}: loss {avg:.4f}")


if __name__ == "__main__":
    main()
