"""Embedding training with sparse (IndexedSlices) gradients.

The TPU-native equivalent of training an embedding-heavy model under
the reference's sparse gradient path (``tensorflow/__init__.py:95-162``
allgathers the touched slices instead of allreducing the dense table;
``torch/optimizer.py`` exposes ``sparse_as_dense`` to opt out).

Run: ``python examples/embedding_sparse.py [--sparse-as-dense]``.

A skip-gram-style task on synthetic token co-occurrences: only the
batch's touched embedding rows cross the wire each step —
``dense_grad_to_indexed_slices`` recovers the sparsity from JAX's dense
gradient, and ``DistributedOptimizer`` reduces those rows as an
allgather-of-slices.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd  # installs the jax<0.5 compat shims

shard_map = jax.shard_map

VOCAB, DIM = 2048, 64


def synthetic_pairs(n, seed=0):
    """(center, context) pairs with simple structure: context tends to
    be center+1 mod VOCAB, so the embedding geometry is learnable."""
    rng = np.random.RandomState(seed)
    center = rng.randint(0, VOCAB, n).astype(np.int32)
    context = (center + rng.choice([1, 2], n)) % VOCAB
    return center, context.astype(np.int32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-chip batch size")
    parser.add_argument("--lr", type=float, default=0.5)
    parser.add_argument("--sparse-as-dense", action="store_true",
                        help="densify before reduction (reference "
                        "torch sparse_as_dense knob)")
    parser.add_argument("--num-samples", type=int, default=65536)
    args = parser.parse_args()

    hvd.init()
    n = hvd.size()
    global_batch = args.batch_size * n

    params = {
        "emb": jax.random.normal(jax.random.PRNGKey(0), (VOCAB, DIM)) * 0.1,
        "out": jax.random.normal(jax.random.PRNGKey(1), (DIM, VOCAB)) * 0.1,
    }
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = hvd.DistributedOptimizer(
        optax.sgd(args.lr), sparse_as_dense=args.sparse_as_dense
    )

    nnz = args.batch_size  # capacity: per-chip batch touches <= B rows

    def loss_fn(p, batch):
        center, context = batch
        h = p["emb"][center]                      # [B, D]
        logits = h @ p["out"]                     # [B, V]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, context
        ).mean()

    def step_body(p, opt_state, center, context):
        loss, grads = jax.value_and_grad(loss_fn)(p, (center, context))
        # Recover the embedding grad's sparsity: only `center`'s rows
        # are non-zero in the dense gradient.
        grads = dict(grads)
        grads["emb"] = hvd.dense_grad_to_indexed_slices(
            grads["emb"], center, nnz=nnz
        )
        updates, opt_state = tx.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
        return p, opt_state, jax.lax.pmean(loss, hvd.WORLD_AXIS)

    mesh = hvd.mesh()

    def make_step():
        return jax.jit(shard_map(
            step_body, mesh=mesh,
            in_specs=(P(), P(), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))

    opt_state = tx.init(params)
    center, context = synthetic_pairs(args.num_samples)
    steps = min(args.steps, args.num_samples // global_batch)

    # The sparse exchange runs through the exchange IR by default
    # (HVD_TPU_XIR=on routes the allgather-of-slices as a
    # gather_dense_from_sparse program — docs/exchange_ir.md).  Prove
    # the parity contract in-script before training: two steps from
    # identical state, IR on vs off, must produce bitwise-equal losses.
    check = []
    for flag in (True, False):
        hvd.xir.set_enabled_override(flag)
        try:
            p, st = params, tx.init(params)
            s = make_step()
            ls = []
            for i in range(2):
                c = jnp.asarray(center[i * global_batch:(i + 1) * global_batch])
                t = jnp.asarray(context[i * global_batch:(i + 1) * global_batch])
                p, st, loss = s(p, st, c, t)
                ls.append(float(loss))
            check.append(ls)
        finally:
            hvd.xir.set_enabled_override(None)
    assert check[0] == check[1], \
        f"exchange-IR parity violated: {check[0]} vs {check[1]}"
    a2a = hvd.metrics.get_counter("xir.programs.sparse_embed")
    if hvd.rank() == 0:
        print(f"exchange-IR parity OK (IR on == off bitwise over "
              f"{len(check[0])} steps; {a2a} sparse programs)")

    step = make_step()
    for i in range(steps):
        lo = i * global_batch
        c = jnp.asarray(center[lo : lo + global_batch])
        t = jnp.asarray(context[lo : lo + global_batch])
        params, opt_state, loss = step(params, opt_state, c, t)
        if hvd.rank() == 0 and (i % 50 == 0 or i == steps - 1):
            mode = "dense" if args.sparse_as_dense else "sparse"
            print(f"step {i:4d}  loss {float(loss):.4f}  ({mode} reduction)")

    hvd.shutdown()


if __name__ == "__main__":
    main()
