"""Estimator-API MNIST (the reference ``examples/spark/keras`` +
``examples/spark/pytorch`` family).

Shows all three estimator flavors against the same array-backed Store
— without a Spark cluster (``fit_on_arrays``; with pyspark installed,
``fit(df)`` distributes through barrier-mode ``spark.run``):

  * ``KerasEstimator``  — flax model + optax optimizer + metrics,
  * ``TorchEstimator``  — torch module + loss + optimizer factory,
  * checkpoint resume   — a second ``fit`` continues from the store.

Run: ``python examples/estimator_mnist.py [--epochs N]``.
"""

import argparse

import numpy as np

from horovod_tpu.spark import KerasEstimator, LocalStore, TorchEstimator


def synthetic_mnist(n=2048, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28 * 28).astype(np.float32)
    y = ((x.mean(axis=1) * 1000) % 10).astype(np.int64)
    return x, y


def _flax_mlp():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(10)(x)

    return MLP()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--store", default="/tmp/hvd_estimator_store")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    import torch

    x, y = synthetic_mnist()

    def ce(pred, label):
        logp = jax.nn.log_softmax(pred)
        onehot = jax.nn.one_hot(label.astype(jnp.int32), 10)
        return -jnp.mean(jnp.sum(logp * onehot, axis=-1))

    keras_est = KerasEstimator(
        model=_flax_mlp(), optimizer=optax.adam(1e-3), loss=ce,
        validation=0.2, batch_size=args.batch_size, epochs=args.epochs,
        store=LocalStore(args.store + "/keras"), run_id="keras_mnist",
    )
    km = keras_est.fit_on_arrays(features=x, label=y)
    print("keras-style history:",
          {k: round(v[-1], 4) for k, v in km.history.items()})

    torch_est = TorchEstimator(
        model=torch.nn.Sequential(
            torch.nn.Linear(28 * 28, 64), torch.nn.ReLU(),
            torch.nn.Linear(64, 10),
        ),
        optimizer=lambda params: torch.optim.Adam(params, lr=1e-3),
        loss=lambda pred, t: torch.nn.functional.cross_entropy(
            pred, t.long()
        ),
        batch_size=args.batch_size, epochs=args.epochs,
        store=LocalStore(args.store + "/torch"), run_id="torch_mnist",
    )
    tm = torch_est.fit_on_arrays(features=x, label=y)
    preds = tm.predict(x[:256])
    acc = float((preds.argmax(-1) == y[:256]).mean())
    print(f"torch-style train accuracy (256 rows): {acc:.3f}")

    # resume: a fresh estimator with more epochs continues from the
    # store checkpoint (reference _has_checkpoint semantics)
    keras_more = KerasEstimator(
        model=_flax_mlp(), optimizer=optax.adam(1e-3), loss=ce,
        validation=0.2, batch_size=args.batch_size,
        epochs=args.epochs + 1,
        store=LocalStore(args.store + "/keras"), run_id="keras_mnist",
    )
    km2 = keras_more.fit_on_arrays(features=x, label=y)
    print(f"resumed for {len(km2.history['loss'])} new epoch(s)")


if __name__ == "__main__":
    main()
