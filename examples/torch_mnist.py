"""PyTorch MNIST through the interop bridge (the tracked
``pytorch_mnist`` config — reference
``examples/pytorch/pytorch_mnist.py`` step for step: broadcast of
initial parameters and optimizer state, ``DistributedOptimizer``
allreduce each step, metric averaging at epoch end).

The torch model runs on host CPU (torch has no TPU backend here);
gradient averaging rides the runtime's XLA eager collectives, so
multi-process runs synchronize exactly like the reference's
hooks-and-allreduce loop.

Run: ``python examples/torch_mnist.py [--epochs N]``.
"""

import argparse

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu as hvd
import horovod_tpu.interop.torch as hvd_torch


class Net(torch.nn.Module):
    """The reference script's small conv net (pytorch_mnist.py Net)."""

    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = torch.nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def synthetic_mnist(n=8192, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 28, 28).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 1000).astype(np.int64) % 10
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--momentum", type=float, default=0.5)
    parser.add_argument("--num-samples", type=int, default=8192)
    args = parser.parse_args()

    hvd.init()  # reference: hvd.init()
    torch.manual_seed(42)  # reference seeds before model construction

    model = Net()
    # reference: hvd.broadcast_parameters / broadcast_optimizer_state
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    optimizer = torch.optim.SGD(
        model.parameters(), lr=args.lr * hvd.process_count(),
        momentum=args.momentum,
    )
    hvd_torch.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd_torch.DistributedOptimizer(optimizer)

    x, y = synthetic_mnist(args.num_samples)
    # shard rows like the reference DistributedSampler, by PROCESS:
    # the torch/TF bridges reduce gradients at the process level
    # (one framework model per host process), so data sharding and
    # LR scaling follow process topology, not chip topology
    x = x[hvd.process_rank()::hvd.process_count()]
    y = y[hvd.process_rank()::hvd.process_count()]

    model.train()
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(x))
        losses = []
        for i in range(0, len(x) - args.batch_size + 1, args.batch_size):
            idx = perm[i:i + args.batch_size]
            data = torch.from_numpy(x[idx])
            target = torch.from_numpy(y[idx])
            optimizer.zero_grad()
            loss = F.nll_loss(model(data), target)
            loss.backward()
            optimizer.step()
            losses.append(float(loss.detach()))
        # reference: metric averaging across ranks at epoch end
        avg = float(hvd.metric_average(float(np.mean(losses))))
        if hvd.process_rank() == 0:
            print(f"epoch {epoch}: loss {avg:.4f}")


if __name__ == "__main__":
    main()
