"""Fully sharded (ZeRO-3/FSDP) GPT pretraining.

Capability add beyond the reference (which replicates optimizer state
and parameters on every rank): ``hvd.fsdp_train_step`` keeps params AND
optimizer state as 1/N flat shards between steps — per-chip persistent
memory is ``(1 + adam moments)/N`` of the model.

Run: ``python examples/fsdp_gpt.py [--steps N] [--small]``.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import gpt_small, gpt_tiny
from horovod_tpu.models.transformer import token_cross_entropy


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--batch-per-chip", type=int, default=2)
    parser.add_argument("--seq", type=int, default=64)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--small", action="store_true",
                        help="124M GPT-2-small instead of tiny")
    args = parser.parse_args()

    hvd.init()
    n = hvd.size()
    build = gpt_small if args.small else gpt_tiny
    model = build(attn_impl="full", max_len=args.seq)
    cfg = model.cfg

    b = args.batch_per_chip * n
    rng = np.random.RandomState(0)
    data = rng.randint(0, cfg.vocab_size, (64, args.seq + 1)).astype(np.int32)

    def loss_fn(params, batch):
        toks, tgt = batch[:, :-1], batch[:, 1:]
        logits, aux = model.apply(params, toks)
        # gather-form CE: no vocab-sized one-hot temporary
        return token_cross_entropy(logits, tgt) + 0.01 * aux

    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, args.seq), jnp.int32)
    )
    n_params = sum(x.size for x in jax.tree.leaves(params))

    # FSDP's per-step all_gather + reduce_scatter run through the
    # exchange IR by default (HVD_TPU_XIR=on — docs/exchange_ir.md).
    # Prove the parity contract in-script before training: one step
    # from identical shards, IR on vs off, must match bitwise.
    check = []
    for flag in (True, False):
        hvd.xir.set_enabled_override(flag)
        try:
            s = hvd.fsdp_train_step(loss_fn, optax.adamw(args.lr))
            ps, st = s.init(params)
            ps, st, loss = s(ps, st, jnp.asarray(data[:b]))
            check.append(float(loss))
        finally:
            hvd.xir.set_enabled_override(None)
    assert check[0] == check[1], \
        f"exchange-IR parity violated: {check[0]} vs {check[1]}"
    if hvd.rank() == 0:
        print(f"exchange-IR parity OK (fsdp step IR on == off bitwise, "
              f"loss {check[0]:.4f})")

    step = hvd.fsdp_train_step(loss_fn, optax.adamw(args.lr))
    pshards, opt_state = step.init(params)
    del params  # full copy no longer needed: it lives sharded now

    shard_elems = pshards.size // n
    if hvd.rank() == 0:
        print(f"params {n_params/1e6:.1f}M; per-chip shard "
              f"{shard_elems/1e6:.2f}M elems "
              f"(x3 with adam moments) vs {n_params/1e6:.1f}M replicated")

    for i in range(args.steps):
        lo = (i * b) % (len(data) - b + 1)
        batch = jnp.asarray(data[lo : lo + b])
        pshards, opt_state, loss = step(pshards, opt_state, batch)
        if hvd.rank() == 0 and (i % 10 == 0 or i == args.steps - 1):
            print(f"step {i:3d}  loss {float(loss):.4f}")

    # eval path: re-materialize full params once
    full = step.gather(pshards)
    logits, _ = model.apply(full, jnp.asarray(data[:1, : args.seq]))
    if hvd.rank() == 0:
        print("gathered eval logits:", tuple(logits.shape))
    hvd.shutdown()


if __name__ == "__main__":
    main()
