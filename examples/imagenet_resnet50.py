"""ImageNet ResNet-50 training — the north-star benchmark config.

TPU-native equivalent of reference
``examples/pytorch/pytorch_imagenet_resnet50.py``: ResNet-50, SGD with
the linear-scaling rule + 5-epoch gradual warmup
(``LearningRateWarmupCallback``), bf16 compute with fp32 master params,
fused-allreduce DistributedOptimizer, sharded async data loading, and
cross-rank metric averaging.

Run: ``python examples/imagenet_resnet50.py [--epochs N] [--synthetic]``.
No network egress in this image, so ``--synthetic`` (default) generates
ImageNet-shaped data; point ``--data-dir`` at real npz shards otherwise.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.callbacks import (
    BroadcastGlobalVariablesCallback,
    CallbackList,
    MetricAverageCallback,
    TrainingLoop,
    warmup_schedule,
)
from horovod_tpu.data import AsyncArrayDataLoader
from horovod_tpu.models import ResNet50


def synthetic_imagenet(n=2048, image_size=176, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, image_size, image_size, 3).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 10_000).astype(np.int32) % 1000
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=32,
                        help="per-chip batch size")
    parser.add_argument("--base-lr", type=float, default=0.0125,
                        help="per-worker LR (reference default), scaled "
                        "by world size via the linear-scaling rule")
    parser.add_argument("--warmup-epochs", type=int, default=5)
    parser.add_argument("--wd", type=float, default=5e-5)
    parser.add_argument("--sync-bn", action="store_true",
                        help="synchronized BatchNorm: moments allreduced "
                        "across chips (hvd.SyncBatchNorm)")
    parser.add_argument("--image-size", type=int, default=176)
    parser.add_argument("--num-samples", type=int, default=2048,
                        help="synthetic dataset size (shrink for smoke tests)")
    parser.add_argument("--synthetic", action="store_true", default=True)
    parser.add_argument("--data-dir", default=None)
    args = parser.parse_args()

    hvd.init()

    if args.data_dir:
        blob = np.load(args.data_dir)
        x, y = blob["images"], blob["labels"]
    else:
        x, y = synthetic_imagenet(
            n=args.num_samples, image_size=args.image_size
        )

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16,
                     sync_bn=args.sync_bn)
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, args.image_size, args.image_size, 3)), train=True,
    )
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Global batch = per-chip * size; loader yields the process-local
    # slice of it.
    global_batch = args.batch_size * hvd.size()
    per_process = global_batch // hvd.process_count()
    loader = AsyncArrayDataLoader([x, y], batch_size=per_process, seed=42)
    steps_per_epoch = max(len(loader), 1)

    # Fully-traced warmup: base_lr -> base_lr*size over warmup_epochs.
    sched = warmup_schedule(
        args.base_lr, args.warmup_epochs, steps_per_epoch
    )
    tx = hvd.DistributedOptimizer(
        optax.chain(
            optax.add_decayed_weights(args.wd),
            optax.sgd(sched, momentum=0.9, nesterov=False),
        ),
        compression=hvd.Compression.bf16,
    )

    def loss_fn(p, stats, batch):
        xb, yb = batch
        logits, updated = model.apply(
            {"params": p, "batch_stats": stats}, xb, train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, yb
        ).mean()
        return loss, updated["batch_stats"]

    step = hvd.distributed_train_step(loss_fn, tx, stateful=True)
    opt_state = step.init(params)

    loop = TrainingLoop(params=params)
    cbs = CallbackList([
        BroadcastGlobalVariablesCallback(0), MetricAverageCallback(),
    ])
    cbs.on_train_begin(loop)
    params = loop.params

    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        loop.epoch = epoch
        cbs.on_epoch_begin(loop)
        t0, seen, last_loss = time.time(), 0, float("nan")
        for xb, yb in loader:
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state,
                (jnp.asarray(xb, jnp.bfloat16), jnp.asarray(yb)),
            )
            seen += global_batch
            last_loss = loss
        jax.block_until_ready(last_loss)
        dt = time.time() - t0
        loop.logs = {"loss": float(last_loss),
                     "images_per_sec": seen / dt}
        cbs.on_epoch_end(loop)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {loop.logs['loss']:.4f}  "
                  f"{loop.logs['images_per_sec']:.1f} img/s "
                  f"({loop.logs['images_per_sec'] / hvd.size():.1f}/chip)")
    loader.close_async_loader()


if __name__ == "__main__":
    main()
