"""Lightning-protocol MNIST (the reference
``examples/pytorch/pytorch_lightning_mnist.py`` family).

The module implements the lightning protocol (``training_step`` /
``validation_step`` / ``configure_optimizers``) as a plain
``torch.nn.Module`` — no pytorch_lightning dependency — and trains
through :class:`horovod_tpu.spark.LightningEstimator`, which wires the
interop DistributedOptimizer, per-epoch checkpoints, and a
keras-shaped history.

Run: ``python examples/lightning_mnist.py [--epochs N]``.
"""

import argparse

import numpy as np
import torch
import torch.nn.functional as F

from horovod_tpu.spark import LightningEstimator, LocalStore


class LitMnist(torch.nn.Module):
    """The reference lightning example's net, protocol-only."""

    def __init__(self, lr=0.01):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
        self.fc = torch.nn.Linear(10 * 12 * 12, 10)
        self.lr = lr

    def forward(self, x):
        x = x.reshape(-1, 1, 28, 28)
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        return F.log_softmax(self.fc(x.flatten(1)), dim=1)

    def training_step(self, batch, batch_idx):
        x, y = batch
        return F.nll_loss(self(x), y.long())

    def validation_step(self, batch, batch_idx):
        x, y = batch
        logits = self(x)
        return {"val_loss": F.nll_loss(logits, y.long()),
                "val_acc": (logits.argmax(-1) == y).float().mean()}

    def configure_optimizers(self):
        return torch.optim.Adam(self.parameters(), lr=self.lr)


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28 * 28).astype(np.float32)
    y = (x.mean(axis=1) * 1000).astype(np.int64) % 10
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--num-samples", type=int, default=4096)
    parser.add_argument("--store", default="/tmp/hvd_lightning_store")
    args = parser.parse_args()

    x, y = synthetic_mnist(args.num_samples)
    est = LightningEstimator(
        model=LitMnist(),
        batch_size=args.batch_size,
        epochs=args.epochs,
        validation=0.2,
        store=LocalStore(args.store),
        run_id="lightning_mnist",
    )
    model = est.fit_on_arrays(features=x, label=y)
    for k, series in model.history.items():
        print(f"{k}: " + " ".join(f"{v:.4f}" for v in series))
    preds = model.predict(x[:256])
    acc = float((preds.argmax(-1) == y[:256]).mean())
    print(f"train-set accuracy (256 rows): {acc:.3f}")


if __name__ == "__main__":
    main()
