"""Async exchange service (svc/): queue, negotiation, cache, faults,
bounded staleness.

Contracts under test:

* **Submission** — TensorQueue ordering/depth accounting; coordinator-
  bitvector negotiation gates multi-participant programs and releases
  deterministically.
* **ResponseCache** — repeated program signatures hit the cache with
  ZERO re-lowering, results bitwise-equal to the cold path; the key
  folds in the topo-fit epoch so a cost-model refit invalidates it.
* **Producers** — N concurrent threads submitting interleaved dense-
  grad + a2a programs drain deterministically; the traced producers
  (sched/execute.py, xir/interp.py) make HVD_TPU_SVC on/off bitwise
  identical at staleness 0.
* **Faults** — svc.submit / svc.drain / svc.loop fault sites kill the
  service mid-flight and every submission degrades to synchronous
  inline dispatch (svc.fallback_sync), never a wedged step.
* **Staleness** — the delayed-DCN-sync pipeline converges on the
  quadratic bowl with k=1 while overlapping hops into later steps
  (svc.overlap_steps).
* **Satellites** — the xir/lower.py store-sync memo invalidates on a
  topo-fit refit; service accounting renders on the /metrics surface.
"""

import threading

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults, metrics, sched, svc, topo, xir
from horovod_tpu.exceptions import HorovodTpuError
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.svc.cache import CachedResponse, ResponseCache
from horovod_tpu.svc.negotiate import Negotiator
from horovod_tpu.svc.queue import Submission, SvcFuture, TensorQueue
from horovod_tpu.topo import model as topo_model

pytestmark = pytest.mark.svc

N = 8
T24 = topo_model.Topology(num_slices=2, slice_size=4)


@pytest.fixture(autouse=True)
def _svc_isolation():
    metrics.reset_counters("svc.")
    yield
    svc.set_enabled_override(None)
    svc.set_staleness_override(None)
    svc.reset_service()
    sched.set_config_override(None)
    topo.set_topology_override(None)
    faults.set_plan(None)
    xir.lower.reset()


def _sub(program, args=(), producer="p", participants=(), seq=None,
         queue=None):
    return Submission(
        seq=seq if seq is not None else (queue or TensorQueue()).next_seq(),
        producer=producer, program=program, args=list(args),
        future=SvcFuture(), participants=tuple(participants),
    )


def _ar_program(kind="test", nbytes=32, bucket=0, reduce="mean"):
    return xir.program(kind, [
        xir.all_reduce(WORLD_AXIS, reduce=reduce, bucket=bucket,
                       nbytes=nbytes, dtype="float32"),
    ])


class TestTensorQueue:
    def test_fifo_order_and_depth_gauges(self):
        q = TensorQueue()
        p = _ar_program()
        for producer in ("a", "b", "a"):
            q.put(_sub(p, producer=producer, seq=q.next_seq()))
        assert q.depth() == 3
        assert q.depth("a") == 2 and q.depth("b") == 1
        assert metrics.get_gauge("svc.queue_depth") == 3
        assert metrics.get_gauge(
            "svc.queue_depth", {"producer": "a"}) == 2
        batch = q.pop_batch(timeout=0)
        assert [s.producer for s in batch] == ["a", "b", "a"]
        assert [s.seq for s in batch] == sorted(s.seq for s in batch)
        # drained producers read 0, not a stale last value
        assert metrics.get_gauge(
            "svc.queue_depth", {"producer": "a"}) == 0

    def test_close_rejects_puts_and_returns_leftovers(self):
        q = TensorQueue()
        q.put(_sub(_ar_program(), seq=q.next_seq()))
        left = q.close()
        assert len(left) == 1
        with pytest.raises(HorovodTpuError, match="closed"):
            q.put(_sub(_ar_program(), seq=q.next_seq()))

    def test_capacity_bound(self):
        q = TensorQueue(capacity=2)
        q.put(_sub(_ar_program(), seq=q.next_seq()))
        q.put(_sub(_ar_program(), seq=q.next_seq()))
        with pytest.raises(HorovodTpuError, match="capacity"):
            q.put(_sub(_ar_program(), seq=q.next_seq()))


class TestNegotiator:
    def test_single_producer_bypasses_negotiation(self):
        neg = Negotiator()
        s = _sub(_ar_program(), producer="solo")
        assert neg.post(s) == [s]
        assert neg.pending_count() == 0

    def test_bitvector_gates_until_every_participant_posts(self):
        neg = Negotiator()
        p = _ar_program()
        a = _sub(p, producer="a", participants=("a", "b"), seq=1)
        assert neg.post(a) == []
        assert neg.pending_count() == 1
        b = _sub(p, producer="b", participants=("a", "b"), seq=2)
        ready = neg.post(b)
        # deterministic release order: participant-sorted
        assert [s.producer for s in ready] == ["a", "b"]
        assert neg.pending_count() == 0
        assert metrics.get_counter("svc.negotiations") == 1
        hist = metrics.get_histogram("svc.negotiation_seconds")
        assert hist is not None and hist["count"] == 1

    def test_different_signatures_do_not_cross_release(self):
        neg = Negotiator()
        a = _sub(_ar_program(nbytes=32), producer="a",
                 participants=("a", "b"), seq=1)
        b = _sub(_ar_program(nbytes=64), producer="b",
                 participants=("a", "b"), seq=2)
        assert neg.post(a) == [] and neg.post(b) == []
        assert neg.pending_count() == 2

    def test_abandon_counts_and_returns_orphans(self):
        neg = Negotiator()
        s = _sub(_ar_program(), producer="a", participants=("a", "b"),
                 seq=1)
        neg.post(s)
        orphans = neg.abandon()
        assert orphans == [s]
        assert metrics.get_counter("svc.negotiations_abandoned") == 1

    def test_release_order_invariant_under_post_permutations(self):
        """Cross-producer property (the fusion-layout contract): a
        released class — which the FusionPacker will pack into ONE
        buffer — must come out in deterministic global order no matter
        which order the producers posted in.  Release is participant-
        sorted (never arrival-sorted), and the packer's (producer, seq)
        member order is invariant under arrival permutations, so every
        process computes the identical fused layout."""
        import itertools

        from horovod_tpu.svc import fuse

        producers = ("a", "b", "c")
        releases, layouts = [], []
        for perm in itertools.permutations(producers):
            neg = Negotiator()
            prog = xir.program("test", [
                xir.all_reduce(WORLD_AXIS, reduce="mean",
                               lowering="flat", nbytes=64,
                               dtype="float32"),
            ])
            ready = []
            for seq, producer in enumerate(perm, start=1):
                sub = _sub(prog, args=[jnp.zeros((N, 16), jnp.float32)],
                           producer=producer, participants=producers,
                           seq=seq)
                ready = neg.post(sub)
            assert [s.producer for s in ready] == list(producers)
            releases.append([s.producer for s in ready])
            buffers, passthrough = fuse.plan_cycle(
                [(s, s.program) for s in ready], threshold=1 << 20
            )
            assert passthrough == [] and len(buffers) == 1
            layouts.append(
                [m.sub.producer for m in buffers[0].members]
            )
        assert all(r == releases[0] for r in releases), releases
        assert all(lo == layouts[0] for lo in layouts), layouts


class TestResponseCache:
    def test_miss_insert_hit_counters(self):
        cache = ResponseCache(cap=8)
        key = ResponseCache.key(_ar_program(), None)
        assert cache.lookup(key) is None
        cache.insert(key, CachedResponse(program=_ar_program()))
        assert cache.lookup(key) is not None
        assert metrics.get_counter("svc.cache_miss") == 1
        assert metrics.get_counter("svc.cache_hit") == 1

    def test_lru_eviction(self):
        cache = ResponseCache(cap=2)
        keys = [ResponseCache.key(_ar_program(nbytes=32 * (i + 1)), None)
                for i in range(3)]
        for k in keys:
            cache.insert(k, CachedResponse(program=_ar_program()))
        assert len(cache) == 2
        assert metrics.get_counter("svc.cache_evict") == 1
        assert cache.lookup(keys[0]) is None  # the oldest went

    def test_zero_capacity_disables(self):
        cache = ResponseCache(cap=0)
        key = ResponseCache.key(_ar_program(), None)
        cache.insert(key, CachedResponse(program=_ar_program()))
        assert cache.lookup(key) is None

    def test_key_folds_in_fit_epoch(self):
        from horovod_tpu.topo import fit

        p = _ar_program()
        k1 = ResponseCache.key(p, None)
        assert k1 == ResponseCache.key(p, None)
        _force_fit_epoch_bump()
        assert ResponseCache.key(p, None) != k1
        fit.reset()


def _force_fit_epoch_bump():
    """Drive a real measured fit so the epoch advances the way it does
    in production (never by poking the counter)."""
    from horovod_tpu.topo import fit
    from horovod_tpu.topo.model import cost_coefficients

    topo.set_topology_override(T24)
    before = fit.fit_epoch()
    for lo in ("flat", "hier"):
        for nb in (1 << 16, 1 << 18, 1 << 20, 1 << 22):
            c = cost_coefficients("all_reduce", nb, lo, N, T24)
            base = (
                c[0] * T24.phase_overhead_s
                + c[1] * T24.ici_latency_s + c[2] * T24.dcn_latency_s
                + c[3] / (T24.ici_gbps * 1e9)
                + c[4] / (T24.dcn_gbps * 1e9)
            )
            for _ in range(5):
                fit.record_observation("all_reduce", lo, nb, N, base)
    fp = fit.refresh(force=True)
    assert fp is not None, "synthetic observations did not fit"
    assert fit.fit_epoch() == before + 1
    return fp


@pytest.mark.usefixtures("hvd_module")
class TestServiceHostPath:
    def test_all_reduce_matches_numpy_and_cache_hits_bitwise(self):
        s = svc.get_service()
        x = jnp.asarray(
            np.random.RandomState(0).randn(N, 16).astype(np.float32)
        )
        prog = _ar_program(nbytes=64)
        cold = s.submit(prog, [x], producer="t").result(timeout=60)[0]
        np.testing.assert_allclose(
            np.asarray(cold),
            np.broadcast_to(np.asarray(x).mean(0), (N, 16)),
            rtol=1e-6,
        )
        lowerings = metrics.get_counter("svc.lowerings")
        warm = s.submit(prog, [x], producer="t").result(timeout=60)[0]
        # zero re-lowering on the repeat, bitwise-equal payloads
        assert metrics.get_counter("svc.lowerings") == lowerings
        assert metrics.get_counter("svc.cache_hit") >= 1
        assert (np.asarray(warm) == np.asarray(cold)).all()

    def test_all_to_all_program(self):
        s = svc.get_service()
        x = jnp.arange(N * N, dtype=jnp.float32).reshape(N, N)
        prog = xir.program("moe", [
            xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=0,
                           nbytes=int(x.nbytes), dtype="float32"),
        ])
        out = s.submit(prog, [x], producer="moe").result(timeout=60)[0]
        # one row per rank in == transposed block layout out
        np.testing.assert_array_equal(
            np.asarray(out).reshape(N, N), np.asarray(x).reshape(N, N).T
        )

    def test_negotiated_multi_producer_submission(self):
        s = svc.get_service()
        x = jnp.ones((N, 4), jnp.float32)
        prog = _ar_program(nbytes=16, reduce="sum")
        fa = s.submit(prog, [x], producer="a", participants=("a", "b"))
        assert not fa.done()  # gated on b's bit
        fb = s.submit(prog, [x * 2], producer="b",
                      participants=("a", "b"))
        ra = fa.result(timeout=60)[0]
        rb = fb.result(timeout=60)[0]
        np.testing.assert_allclose(np.asarray(ra), N * 1.0)
        np.testing.assert_allclose(np.asarray(rb), N * 2.0)
        assert metrics.get_counter("svc.negotiations") == 1

    def test_concurrent_producers_drain_deterministically(self):
        """Satellite: N threads submitting interleaved dense-grad +
        a2a programs drain deterministically, with response-cache hits
        bitwise-equal to cold-path results."""
        rng = np.random.RandomState(3)
        grads = [
            jnp.asarray(rng.randn(N, 8).astype(np.float32))
            for _ in range(4)
        ]
        shuf = jnp.asarray(rng.randn(N, N, 2).astype(np.float32))

        def run_once():
            s = svc.get_service()
            results = {}

            def dense_producer(tid):
                prog = _ar_program("dense_grad", nbytes=32, bucket=tid)
                futs = [
                    s.submit(prog, [g], producer=f"dense{tid}")
                    for g in grads
                ]
                results[f"dense{tid}"] = [
                    np.asarray(f.result(timeout=60)[0]) for f in futs
                ]

            def a2a_producer(tid):
                prog = xir.program("moe", [
                    xir.all_to_all(WORLD_AXIS, split_axis=0,
                                   concat_axis=0,
                                   nbytes=int(shuf.nbytes),
                                   dtype="float32"),
                ])
                futs = [
                    s.submit(prog, [shuf], producer=f"moe{tid}")
                    for _ in range(3)
                ]
                results[f"moe{tid}"] = [
                    np.asarray(f.result(timeout=60)[0]) for f in futs
                ]

            threads = [
                threading.Thread(target=dense_producer, args=(i,))
                for i in range(2)
            ] + [
                threading.Thread(target=a2a_producer, args=(i,))
                for i in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert s.drain(timeout_s=30)
            return results

        first = run_once()
        hits_after_first = metrics.get_counter("svc.cache_hit")
        assert hits_after_first > 0  # repeat submissions hit in-run
        svc.reset_service()
        second = run_once()
        assert sorted(first) == sorted(second)
        for key in first:
            for a, b in zip(first[key], second[key]):
                assert (a == b).all(), f"nondeterministic drain: {key}"


@pytest.mark.faults
@pytest.mark.usefixtures("hvd_module")
class TestServiceFaults:
    def test_submit_fault_kills_service_and_falls_back_inline(self):
        faults.set_plan("svc.submit:error:nth=1")
        s = svc.get_service()
        x = jnp.ones((N, 4), jnp.float32)
        out = s.submit(_ar_program(nbytes=16), [x],
                       producer="t").result(timeout=60)[0]
        np.testing.assert_allclose(np.asarray(out), 1.0)
        assert s.dead
        assert metrics.get_counter("svc.fallback_sync") >= 1
        assert metrics.get_counter("svc.deaths") == 1
        # the dead service keeps serving, synchronously
        out2 = s.submit(_ar_program(nbytes=16), [x * 3],
                        producer="t").result(timeout=60)[0]
        np.testing.assert_allclose(np.asarray(out2), 3.0)

    def test_loop_fault_mid_flight_resolves_queued_futures(self):
        faults.set_plan("svc.loop:error:nth=1")
        s = svc.get_service()
        x = jnp.ones((N, 4), jnp.float32)
        futs = [
            s.submit(_ar_program(nbytes=16, bucket=i), [x * (i + 1)],
                     producer="t")
            for i in range(3)
        ]
        for i, f in enumerate(futs):
            np.testing.assert_allclose(
                np.asarray(f.result(timeout=60)[0]), float(i + 1)
            )
        assert s.dead
        assert metrics.get_counter(
            "faults.injected.svc.loop.error") == 1

    def test_drain_fault_degrades_clean(self):
        faults.set_plan("svc.drain:error:nth=1")
        s = svc.get_service()
        assert s.drain(timeout_s=5) is False
        assert s.dead
        # a post-death submit still resolves inline
        x = jnp.ones((N, 2), jnp.float32)
        out = s.submit(_ar_program(nbytes=8), [x],
                       producer="t").result(timeout=60)[0]
        np.testing.assert_allclose(np.asarray(out), 1.0)

    def test_traced_producer_fault_falls_back_to_local_lowering(self):
        faults.set_plan("svc.submit:error:nth=1")
        svc.set_enabled_override(True)
        prog = _ar_program(nbytes=1 << 20)
        lowered = svc.get_service().submit_traced(prog, producer="x")
        assert lowered.lowered
        assert metrics.get_counter("svc.fallback_sync") >= 1

    def _assert_depth_gauges_zero(self, producers):
        # The PR 13 satellite contract: after ANY fault-injection path,
        # every queue-depth gauge — global and per-producer — decays to
        # 0: a submission that degraded to inline dispatch after a
        # service death must not leave the gauge it incremented.
        assert metrics.get_gauge("svc.queue_depth") in (0, 0.0), \
            "global svc.queue_depth did not decay to 0"
        for prod in producers:
            g = metrics.get_gauge("svc.queue_depth", {"producer": prod})
            assert g in (None, 0, 0.0), \
                f"svc.queue_depth{{producer={prod}}} leaked at {g}"

    def test_queue_depth_decays_to_zero_after_loop_fault(self):
        faults.set_plan("svc.loop:error:nth=1")
        s = svc.get_service()
        x = jnp.ones((N, 4), jnp.float32)
        futs = [
            s.submit(_ar_program(nbytes=16, bucket=i), [x],
                     producer=f"p{i % 2}")
            for i in range(4)
        ]
        for f in futs:
            f.result(timeout=60)
        assert s.dead
        self._assert_depth_gauges_zero(["p0", "p1"])
        # submissions AFTER the death take the closed-queue fallback
        # and must not resurrect any depth series
        out = s.submit(_ar_program(nbytes=8), [x],
                       producer="late").result(timeout=60)
        assert out is not None
        self._assert_depth_gauges_zero(["p0", "p1", "late"])

    def test_queue_depth_decays_to_zero_after_submit_and_drain_faults(self):
        for site in ("svc.submit", "svc.drain"):
            svc.reset_service()
            metrics.reset_counters("svc.")
            faults.set_plan(f"{site}:error:nth=1")
            s = svc.get_service()
            x = jnp.ones((N, 2), jnp.float32)
            if site == "svc.drain":
                s.submit(_ar_program(nbytes=8), [x], producer="a")
                s.drain(timeout_s=5)
            else:
                s.submit(_ar_program(nbytes=8), [x],
                         producer="a").result(timeout=60)
            assert s.dead
            self._assert_depth_gauges_zero(["a"])
            faults.set_plan(None)

    def test_dead_service_loop_thread_terminates(self):
        # The loop must EXIT after a kill, not spin hot on the closed
        # queue (the pre-PR-13 behavior burned a core per dead service).
        faults.set_plan("svc.loop:error:nth=1")
        s = svc.get_service()
        x = jnp.ones((N, 2), jnp.float32)
        s.submit(_ar_program(nbytes=8), [x], producer="t").result(
            timeout=60)
        assert s.dead
        t = s._thread
        if t is not None:
            t.join(timeout=10)
            assert not t.is_alive(), "dead service loop still running"


class TestNegotiationStallInspector:
    def test_stall_names_missing_participants(self, caplog):
        neg = Negotiator()
        prog = _ar_program(kind="stallk")
        sub = _sub(prog, producer="a", participants=("a", "b", "ghost"))
        assert neg.post(sub) == []
        # nothing stalls before the timeout
        assert neg.check_stalls(timeout_s=60.0) == []
        reports = neg.check_stalls(timeout_s=0.0)
        assert len(reports) == 1
        assert reports[0]["missing"] == ["b", "ghost"]
        assert reports[0]["posted"] == ["a"]
        assert sorted(reports[0]["expected"]) == ["a", "b", "ghost"]
        assert metrics.get_counter("svc.stall") == 1
        assert metrics.get_gauge("svc.stalled_negotiations") == 1
        # warn-once: a second sweep reports but does not re-count
        neg.check_stalls(timeout_s=0.0)
        assert metrics.get_counter("svc.stall") == 1
        # completion clears the stall bookkeeping
        for prod in ("b", "ghost"):
            neg.post(_sub(prog, producer=prod,
                          participants=("a", "b", "ghost")))
        assert neg.check_stalls(timeout_s=0.0) == []
        assert metrics.get_gauge("svc.stalled_negotiations") == 0

    def test_service_loop_runs_stall_check(self):
        import time as _time

        from horovod_tpu.utils import env as hvd_env

        # A 2-participant program with one producer missing: the live
        # service loop itself must emit the svc.stall warning once the
        # (tiny) timeout passes — no drain needed to see it.
        hvd_env.set_env(hvd_env.STALL_TIMEOUT, "0.2")
        try:
            s = svc.get_service()
            x = jnp.ones((N, 2), jnp.float32)
            s.submit(_ar_program(nbytes=8), [x], producer="a",
                     participants=("a", "never"))
            deadline = _time.monotonic() + 15
            while metrics.get_counter("svc.stall") == 0 \
                    and _time.monotonic() < deadline:
                _time.sleep(0.05)
            assert metrics.get_counter("svc.stall") >= 1, \
                "service loop never flagged the stalled negotiation"
        finally:
            import os

            os.environ.pop("HVD_TPU_STALL_TIMEOUT", None)
            svc.reset_service()


def _train(svc_on, iters=6, lr=0.05):
    svc.set_enabled_override(svc_on)
    sched.set_config_override(
        sched.SchedConfig(enabled=True, bucket_bytes=2048)
    )
    try:
        rng = np.random.RandomState(0)
        X = rng.randn(16, 32).astype(np.float32)
        Y = (X @ rng.randn(32, 4).astype(np.float32)).astype(np.float32)

        def lf(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

        p = {
            "w": jnp.asarray(rng.randn(32, 4).astype(np.float32) * 0.1),
            "b": jnp.zeros((4,), jnp.float32),
        }
        tx = hvd.DistributedOptimizer(optax.sgd(lr))
        step = hvd.distributed_train_step(lf, tx)
        st = step.init(p)
        batch = (jnp.asarray(X), jnp.asarray(Y))
        losses = []
        for _ in range(iters):
            p, st, loss = step(p, st, batch)
            losses.append(float(loss))
        return losses
    finally:
        sched.set_config_override(None)
        svc.set_enabled_override(None)


@pytest.mark.usefixtures("hvd_module")
class TestTracedProducers:
    def test_svc_on_off_bitwise_identical_at_staleness_zero(self):
        off = _train(False)
        on = _train(True)
        assert off == on, f"svc on diverged from off: {on} vs {off}"
        assert metrics.get_counter("svc.submits") > 0

    def test_xir_execute_routes_lowering_through_cache(self):
        svc.set_enabled_override(True)
        # lowering="auto": the program arrives unlowered, so execute()
        # must resolve it — through the service's ResponseCache.
        prog = xir.program("fsdp", [
            xir.all_reduce(WORLD_AXIS, lowering="auto",
                           nbytes=1024, dtype="float32"),
        ])
        x = jnp.arange(N * N, dtype=jnp.float32).reshape(N, N)

        def body(v):
            return xir.execute(prog, [v], store=False)[0]

        from tests.test_xir import _shard_run

        lowerings0 = metrics.get_counter("svc.lowerings")
        out1 = _shard_run(body, x)
        hits0 = metrics.get_counter("svc.cache_hit")
        out2 = _shard_run(lambda v: body(v) * 1.0, x)  # fresh trace
        assert metrics.get_counter("svc.cache_hit") > hits0
        assert metrics.get_counter("svc.lowerings") == lowerings0 + 1
        np.testing.assert_array_equal(np.asarray(out1),
                                      np.asarray(out2))


@pytest.mark.usefixtures("hvd_module")
class TestBoundedStaleness:
    def test_single_slice_is_ineligible(self):
        topo.set_topology_override(
            topo_model.Topology(num_slices=1, slice_size=8)
        )
        assert svc.stale.eligible() is not None

    def test_staleness_zero_returns_synchronous_step(self):
        svc.set_enabled_override(True)
        svc.set_staleness_override(0)
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        step = hvd.distributed_train_step(lambda p, b: jnp.sum(p), tx)
        from horovod_tpu.optim.distributed_optimizer import TrainStep

        assert isinstance(step, TrainStep)

    def test_quadratic_bowl_converges_with_overlap(self):
        topo.set_topology_override(T24)
        svc.set_enabled_override(True)
        svc.set_staleness_override(1)

        def lf(p, b):
            return jnp.sum((p["w"] - 3.0) ** 2) + 0.0 * jnp.sum(b)

        tx = hvd.DistributedOptimizer(optax.sgd(0.2))
        step = hvd.distributed_train_step(lf, tx)
        assert isinstance(step, svc.StaleTrainStep)
        sp, st = step.init({"w": jnp.zeros((4,), jnp.float32)})
        batch = jnp.zeros((N, 1), jnp.float32)
        loss = None
        for _ in range(40):
            sp, st, loss = step(sp, st, batch)
        assert float(loss) < 1e-6, float(loss)
        final = step.consolidate(sp)
        np.testing.assert_allclose(np.asarray(final["w"]), 3.0,
                                   atol=1e-3)
        assert metrics.get_counter("svc.overlap_steps") > 0
        assert metrics.get_gauge("svc.staleness") == 1
        step.drain()

    def test_ineligible_optimizer_stays_synchronous(self):
        topo.set_topology_override(T24)
        svc.set_enabled_override(True)
        svc.set_staleness_override(1)
        # Sum (not Average) reduction is ineligible for the delayed
        # correction: the pipeline falls back to the sync step.
        from horovod_tpu.ops.traced import Sum

        tx = hvd.DistributedOptimizer(optax.sgd(0.1), op=Sum)
        step = hvd.distributed_train_step(lambda p, b: jnp.sum(p), tx)
        from horovod_tpu.optim.distributed_optimizer import TrainStep

        assert isinstance(step, TrainStep)


@pytest.mark.tune
class TestFitEpochMemoInvalidation:
    def test_store_sync_memo_revalidates_after_refit(self, tmp_path,
                                                     monkeypatch):
        """Satellite regression: xir/lower.py's per-process store-sync
        memo must re-consult the tune DB after topo/fit.py refits the
        cost model — before the fix it served the pre-fit entry
        forever."""
        from horovod_tpu.sched.store import ScheduleStore
        from horovod_tpu.topo import fit
        from horovod_tpu.xir import lower as lower_mod

        db = tmp_path / "tune.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        topo.set_topology_override(T24)
        lower_mod.reset()
        fit.reset()
        metrics.reset_counters("xir.db")

        prog = _ar_program("dense_grad", nbytes=1 << 20)
        first = lower_mod.lower(prog)
        assert metrics.get_counter("xir.db_seeded") == 1
        # a better-scored winner lands in the DB (a fleet peer tuned it)
        store = ScheduleStore.from_env()
        key = lower_mod.tuner_key(first)
        store.record(key, bucket_bytes=1 << 20, wire="bf16",
                     lowering=first.ops[0].lowering, score=99.0)
        # same epoch: the memo serves the stale adoption (by design —
        # one store read per process per program)
        again = lower_mod.lower(prog)
        assert again.ops[0].wire == first.ops[0].wire
        # refit: epoch bumps, the memo key changes, the store is
        # re-consulted and the new winner adopted
        _force_fit_epoch_bump()
        refreshed = lower_mod.lower(prog)
        assert metrics.get_counter("xir.db_hit") >= 1
        assert refreshed.ops[0].wire == "bf16"
        fit.reset()


@pytest.mark.usefixtures("hvd_module")
class TestMetricsSurface:
    def test_service_accounting_renders_on_metrics_endpoint(self):
        """Satellite: per-producer queue depth, negotiation quantiles,
        and cache hit/miss counters all reach the Prometheus surface
        the elastic driver scrapes."""
        s = svc.get_service()
        x = jnp.ones((N, 4), jnp.float32)
        prog = _ar_program(nbytes=16)
        fa = s.submit(prog, [x], producer="tenant_a",
                      participants=("tenant_a", "tenant_b"))
        fb = s.submit(prog, [x], producer="tenant_b",
                      participants=("tenant_a", "tenant_b"))
        fa.result(timeout=60), fb.result(timeout=60)
        s.submit(prog, [x], producer="tenant_a").result(timeout=60)

        from horovod_tpu.runner.telemetry_http import TelemetryServer

        server = TelemetryServer(port=0, bind_host="127.0.0.1")
        try:
            import urllib.request

            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=10
            ).read().decode()
        finally:
            server.stop()
        assert 'hvd_tpu_svc_queue_depth{producer="tenant_a"}' in body
        assert "hvd_tpu_svc_negotiation_seconds" in body
        assert 'quantile="0.99"' in body
        assert "hvd_tpu_svc_cache_hit_total" in body
        assert "hvd_tpu_svc_cache_miss_total" in body
        assert "hvd_tpu_svc_dispatches_total" in body
