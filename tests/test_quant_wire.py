"""Quantized wire v2 through the scheduler: per-bucket wire choice,
error-feedback residual state (DistributedOptimizer / ZeRO-1), the
reduce_scatter-mode routing, wire observability gauges, the tuner's
wire exploration, and the 2×2 dp×tp acceptance run."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import metrics, sched
from horovod_tpu.exceptions import QuantizedWireError
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.sched import SchedConfig, build_schedule, hooks

pytestmark = [pytest.mark.quant, pytest.mark.sched]

F32 = 4


@pytest.fixture(autouse=True)
def _clean_sched_state():
    hooks.reset()
    sched.set_config_override(None)
    yield
    hooks.reset()
    sched.set_config_override(None)


def fresh(tree):
    return jax.tree.map(lambda a: jnp.array(a), tree)


# ------------------------------------------------------------- plan

def test_config_wire_from_env(monkeypatch):
    monkeypatch.setenv("HVD_TPU_SCHED_WIRE", "int8")
    monkeypatch.setenv("HVD_TPU_SCHED_WIRE_EF", "0")
    cfg = SchedConfig.from_env()
    assert cfg.wire == "int8"
    assert not cfg.wire_ef
    monkeypatch.setenv("HVD_TPU_SCHED_WIRE", "e4m3")
    assert SchedConfig.from_env().wire == "fp8"
    monkeypatch.setenv("HVD_TPU_SCHED_WIRE", "none")
    assert SchedConfig.from_env().wire == "off"
    monkeypatch.setenv("HVD_TPU_SCHED_WIRE", "int4")
    with pytest.raises(ValueError, match="HVD_TPU_SCHED_WIRE"):
        SchedConfig.from_env()


def test_default_wire_is_off():
    assert SchedConfig().wire == "off"
    s = build_schedule([100, 100], ["float32"] * 2, SchedConfig())
    assert all(b.wire == "off" for b in s.buckets)


def test_bucket_wire_eligibility():
    cfg = SchedConfig(bucket_bytes=400, wire="int8")
    s = build_schedule(
        [100, 100, 100], ["float32", "float32", "int32"], cfg,
    )
    by_dtype = {b.wire_dtypes: b.wire for b in s.buckets}
    assert by_dtype[("float32",)] == "int8"
    assert by_dtype[("int32",)] == "off"  # non-float: never quantized
    # pinned mixed-dtype buckets downgrade too
    s2 = build_schedule(
        [100, 100], ["float32", "bfloat16"], cfg, pinned=[[0, 1]],
    )
    assert s2.buckets[0].wire == "off"
    # bf16 wire allows any floating bucket
    s3 = build_schedule(
        [100, 100], ["float32", "bfloat16"],
        SchedConfig(bucket_bytes=400, wire="bf16"), pinned=[[0, 1]],
    )
    assert s3.buckets[0].wire == "bf16"


def test_wire_bytes_ratio():
    from horovod_tpu.sched.plan import wire_bytes

    cfg = SchedConfig(bucket_bytes=1 << 20, wire="int8")
    s = build_schedule([4096 * F32], ["float32"], cfg)
    dense = build_schedule([4096 * F32], ["float32"],
                           SchedConfig(bucket_bytes=1 << 20))
    ratio = wire_bytes(dense.buckets[0]) / wire_bytes(s.buckets[0])
    assert ratio >= 3.0  # 4 bytes -> 1 byte + scale sidecar


def test_signature_includes_wire():
    a = build_schedule([100], ["float32"], SchedConfig(wire="int8"))
    b = build_schedule([100], ["float32"], SchedConfig())
    assert a.signature() != b.signature()


# ------------------------------------------- DistributedOptimizer + EF

def _problem(out_dim=2):
    X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    Y = (X @ np.full((4, out_dim), 0.7)).astype(np.float32)

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

    params = {
        "w1": jnp.full((4, 4), 0.2),
        "w2": jnp.full((4, out_dim), 0.5),
        "b": jnp.zeros((out_dim,)),
    }
    return params, (jnp.asarray(X), jnp.asarray(Y)), loss_fn


def _run_steps(loss_fn, params, batch, cfg, n=5, **opt_kwargs):
    sched.set_config_override(cfg)
    try:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), **opt_kwargs)
        step = hvd.distributed_train_step(loss_fn, tx)
        st = step.init(params)
        p = fresh(params)
        losses = []
        for _ in range(n):
            p, st, loss = step(p, st, batch)
            losses.append(float(loss))
        return p, losses, st
    finally:
        sched.set_config_override(None)


def test_wire_off_bitwise_identical_to_dense(hvd_module):
    """Acceptance: HVD_TPU_SCHED_WIRE=off (the default) keeps losses
    f32-bitwise-identical to the PR 3 scheduler behavior."""
    params, batch, loss_fn = _problem()
    _, dense, _ = _run_steps(loss_fn, params, batch,
                             SchedConfig(bucket_bytes=64))
    _, off, st = _run_steps(loss_fn, params, batch,
                            SchedConfig(bucket_bytes=64, wire="off"))
    assert dense == off
    assert st.residual is None  # no EF state allocated


@pytest.mark.parametrize("wire", ["int8", "fp8"])
def test_ef_wire_trains_close_to_dense(hvd_module, wire):
    params, batch, loss_fn = _problem()
    _, dense, _ = _run_steps(loss_fn, params, batch,
                             SchedConfig(bucket_bytes=64), n=30)
    _, quant, st = _run_steps(
        loss_fn, params, batch,
        SchedConfig(bucket_bytes=64, wire=wire), n=30,
    )
    assert st.residual is not None
    assert quant[-1] == pytest.approx(dense[-1], abs=1e-3)


def test_ef_residual_state_is_nonzero_after_steps(hvd_module):
    params, batch, loss_fn = _problem()
    _, _, st = _run_steps(
        loss_fn, params, batch, SchedConfig(bucket_bytes=64, wire="int8"),
    )
    total = sum(
        float(jnp.abs(r).sum()) for r in jax.tree.leaves(st.residual)
    )
    assert total > 0.0  # the wire is lossy; EF captured the error


def test_wire_ef_off_allocates_no_residual(hvd_module):
    params, batch, loss_fn = _problem()
    _, _, st = _run_steps(
        loss_fn, params, batch,
        SchedConfig(bucket_bytes=64, wire="int8", wire_ef=False),
    )
    assert st.residual is None


def test_bf16_wire_rides_per_bucket(hvd_module):
    params, batch, loss_fn = _problem()
    _, dense, _ = _run_steps(loss_fn, params, batch,
                             SchedConfig(bucket_bytes=64))
    _, b16, _ = _run_steps(loss_fn, params, batch,
                           SchedConfig(bucket_bytes=64, wire="bf16"))
    np.testing.assert_allclose(b16, dense, rtol=5e-2)


def test_wire_bytes_gauges_and_ratio(hvd_module):
    """Acceptance: sched.wire_bytes{wire=int8} shows >= 3x reduction vs
    the fp32 wire on the same schedule."""
    params, batch, loss_fn = _problem()
    metrics.reset_counters("sched.")
    _run_steps(loss_fn, params, batch, SchedConfig(bucket_bytes=64))
    dense_bytes = metrics.get_gauge("sched.wire_bytes",
                                    {"wire": "off"})
    assert dense_bytes and dense_bytes > 0
    metrics.reset_counters("sched.")
    _run_steps(loss_fn, params, batch,
               SchedConfig(bucket_bytes=64, wire="int8"))
    int8_bytes = metrics.get_gauge("sched.wire_bytes", {"wire": "int8"})
    assert int8_bytes and int8_bytes > 0
    assert dense_bytes / int8_bytes >= 3.0
    assert metrics.get_gauge("sched.compression_ratio") >= 3.0
    assert metrics.get_counter("sched.wire_bytes.int8") > 0


def test_gradient_accumulation_threads_residual(hvd_module):
    params, batch, loss_fn = _problem()
    X, Y = batch
    cfg = SchedConfig(bucket_bytes=64, wire="int8")
    sched.set_config_override(cfg)
    try:
        tx = hvd.DistributedOptimizer(
            optax.sgd(0.1), backward_passes_per_step=2)
        step = hvd.distributed_train_step(loss_fn, tx)
        p = fresh(params)
        st = step.init(p)
        for _ in range(2):
            p, st, _ = step(p, st, (X[:8], Y[:8]))
            p, st, _ = step(p, st, (X[8:], Y[8:]))
        assert st.residual is not None
        total = sum(
            float(jnp.abs(r).sum()) for r in jax.tree.leaves(st.residual)
        )
        assert total > 0.0
    finally:
        sched.set_config_override(None)


# ------------------------------------------ reduce_scatter mode routing

def test_int8_compression_routes_quantized_in_rs_mode(hvd_module):
    """Satellite: Compression.int8 + HVD_TPU_SCHED_MODE=reduce_scatter
    must run the quantized RS/AG primitives, not silently degrade to the
    dense path — the wire gauges prove which wire carried the bytes."""
    params, batch, loss_fn = _problem()
    metrics.reset_counters("sched.")
    _, losses, st = _run_steps(
        loss_fn, params, batch,
        SchedConfig(bucket_bytes=64, mode="reduce_scatter"),
        n=30, compression=hvd.Compression.int8,
    )
    assert st.residual is not None  # EF rides the explicit int8 wire
    int8_bytes = metrics.get_gauge("sched.wire_bytes", {"wire": "int8"})
    assert int8_bytes and int8_bytes > 0
    assert metrics.get_gauge("sched.wire_bytes", {"wire": "off"}) is None
    # and it still trains to the dense answer
    _, dense, _ = _run_steps(
        loss_fn, params, batch,
        SchedConfig(bucket_bytes=64, mode="reduce_scatter"), n=30,
    )
    assert losses[-1] == pytest.approx(dense[-1], abs=1e-3)


def test_rs_mode_wire_env_matches_allreduce_mode(hvd_module):
    params, batch, loss_fn = _problem()
    _, ar, _ = _run_steps(
        loss_fn, params, batch,
        SchedConfig(bucket_bytes=64, wire="int8"), n=10,
    )
    _, rs, _ = _run_steps(
        loss_fn, params, batch,
        SchedConfig(bucket_bytes=64, wire="int8", mode="reduce_scatter"),
        n=10,
    )
    # for a quantized bucket the RS+AG decomposition IS the allreduce
    assert ar == rs


def test_quantized_wire_raises_for_adasum(hvd_module):
    """Satellite: unsupported combinations raise QuantizedWireError
    instead of silently degrading."""
    from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
    from horovod_tpu.ops.traced import Adasum

    sched.set_config_override(
        SchedConfig(bucket_bytes=64, wire="int8"))
    try:
        with pytest.raises(QuantizedWireError, match="Average"):
            jax.jit(shard_map(
                lambda g: _reduce_gradients(
                    [g[0]], axis=WORLD_AXIS, op=Adasum,
                    compression=hvd.Compression.none,
                    prescale_factor=1.0, postscale_factor=1.0,
                    process_set=None, fusion_threshold_bytes=None,
                )[0][None],
                mesh=hvd.mesh(), in_specs=(P(WORLD_AXIS),),
                out_specs=P(WORLD_AXIS), check_vma=False,
            ))(jnp.ones((8, 16)))
    finally:
        sched.set_config_override(None)


# --------------------------------------------------- bucketed ZeRO-1

def test_bucketed_zero_int8_ef_matches_dense(hvd_module):
    """Acceptance: bucketed_zero_step composes with the quantized wire
    — int8+EF reaches the dense final loss within 1e-3, optimizer
    update fed in fp32, state carries per-bucket residuals."""
    params, batch, loss_fn = _problem()

    def run(cfg):
        step = sched.bucketed_zero_step(loss_fn, optax.adam(1e-2), cfg=cfg)
        st = step.init(params)
        p = fresh(params)
        loss = None
        for _ in range(30):
            p, st, loss = step(p, st, batch)
        return float(loss), st

    dense_loss, dense_st = run(SchedConfig(bucket_bytes=32))
    q_loss, q_st = run(SchedConfig(bucket_bytes=32, wire="int8"))
    assert q_loss == pytest.approx(dense_loss, abs=1e-3)
    # dense state structure unchanged; quantized buckets carry {"tx","ef"}
    assert not any(isinstance(s, dict) for s in dense_st)
    assert all(isinstance(s, dict) and "ef" in s for s in q_st)


def test_bucketed_zero_int8_state_still_sharded(hvd_module):
    params, batch, loss_fn = _problem()
    world = hvd.size()
    step = sched.bucketed_zero_step(
        loss_fn, optax.adam(1e-2),
        cfg=SchedConfig(bucket_bytes=32, wire="int8"),
    )
    st = step.init(params)
    for s in st:
        mu = s["tx"][0].mu
        assert len(mu.sharding.device_set) == world


def test_zero_train_step_int8_wire(hvd_module):
    from horovod_tpu.optim.zero import zero_train_step

    params, batch, loss_fn = _problem()

    def run(wire):
        step = zero_train_step(loss_fn, optax.sgd(0.05), wire=wire)
        st = step.init(params)
        p = fresh(params)
        loss = None
        for _ in range(30):
            p, st, loss = step(p, st, batch)
        return float(loss)

    assert run("int8") == pytest.approx(run("off"), abs=1e-3)


# ---------------------------------------------------- 2x2 dp x tp mesh

def test_2x2_dp_tp_int8_ef_matches_dense(hvd_module):
    """Acceptance: a 2×2 dp×tp CPU-mesh train loop with int8 wire + EF
    (residuals threaded through sync_gradients_bucketed) matches the
    dense path's final loss within 1e-3, with >= 3x wire reduction."""
    from horovod_tpu.parallel import make_mesh

    d, n_tp, n_dp = 8, 2, 2
    rng = np.random.RandomState(9)
    x = rng.randn(8, d).astype(np.float32)
    tgt = rng.randn(8, d).astype(np.float32)
    w_rep0 = (rng.randn(d, d) * 0.3).astype(np.float32)
    wo0 = (rng.randn(n_tp, d, d) * 0.1).astype(np.float32)  # tp-sharded
    mesh = make_mesh(dp=n_dp, tp=n_tp, devices=jax.devices()[:4])
    shard_axes = {"w_rep": "", "wo": "tp"}
    specs = {"w_rep": P(), "wo": P("tp")}
    lr = 0.05

    def make_step(cfg, ef):
        def body(p, res, x, tgt):
            def loss_fn(p):
                y = jnp.tanh(x @ p["w_rep"]) @ p["wo"][0]
                y = jax.lax.psum(y, "tp")
                return jnp.mean((y - tgt) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(p)
            if res is not None:
                g, res = sched.sync_gradients_bucketed(
                    g, shard_axes, axes=("dp", "tp"), cfg=cfg,
                    residuals=res,
                )
            else:
                g = sched.sync_gradients_bucketed(
                    g, shard_axes, axes=("dp", "tp"), cfg=cfg,
                )
            p = jax.tree.map(lambda w, gw: w - lr * gw, p, g)
            return (p, res, loss) if res is not None else (p, loss)

        if ef:
            return body
        return lambda p, x, tgt: body(p, None, x, tgt)

    def run(cfg, ef):
        p = {"w_rep": jnp.asarray(w_rep0), "wo": jnp.asarray(wo0)}
        res = (
            jax.tree.map(lambda a: jnp.zeros_like(a), p) if ef else None
        )
        in_specs = (specs,) + ((specs,) if ef else ()) + (P("dp"), P("dp"))
        out_specs = (specs,) + ((specs,) if ef else ()) + (P(),)
        f = jax.jit(shard_map(
            make_step(cfg, ef), mesh=mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=False,
        ))
        loss = None
        for _ in range(30):
            if ef:
                p, res, loss = f(p, res, jnp.asarray(x), jnp.asarray(tgt))
            else:
                p, loss = f(p, jnp.asarray(x), jnp.asarray(tgt))
        return float(loss)

    metrics.reset_counters("sched.")
    dense = run(SchedConfig(bucket_bytes=64), ef=False)
    dense_bytes = metrics.get_gauge("sched.wire_bytes", {"wire": "off"})
    metrics.reset_counters("sched.")
    quant = run(SchedConfig(bucket_bytes=64, wire="int8"), ef=True)
    int8_bytes = metrics.get_gauge("sched.wire_bytes", {"wire": "int8"})
    assert quant == pytest.approx(dense, abs=1e-3), (dense, quant)
    assert int8_bytes and dense_bytes
    assert dense_bytes / int8_bytes >= 3.0


# -------------------------------------------------------------- tuner

def test_tuner_explores_and_freezes_wire():
    metrics.reset_counters("train.")
    metrics.reset_counters("sched.")
    tuner = sched.ScheduleTuner(explore_wire=True, warmup_windows=2)
    seen = []
    # off/bf16/int8/fp8 each get one scored window; int8 made fastest
    rates = {"off": 5, "bf16": 8, "int8": 20, "fp8": 10}
    for _ in range(4):
        w = tuner.wire()
        seen.append(w)
        tuner.begin_window()
        metrics.inc_counter("train.steps", rates[w])
        metrics.observe("train.step_seconds", 1.0)
        metrics.set_gauge("sched.bytes_per_step", 1000.0)
        assert tuner.end_window() > 0
    assert seen == ["off", "bf16", "int8", "fp8"]
    assert tuner.wire() == "int8"  # frozen winner
    assert metrics.get_gauge(
        "sched.tune_wire_score", {"wire": "int8"}) is not None
    # bucket-size tuning proceeds under the frozen wire
    assert not tuner.converged
    for _ in range(2):
        tuner.begin_window()
        metrics.inc_counter("train.steps", 10)
        metrics.observe("train.step_seconds", 1.0)
        tuner.end_window()
    assert tuner.converged


def test_tuner_apply_keeps_small_buckets_dense():
    tuner = sched.ScheduleTuner(explore_wire=False,
                                wire_min_bucket_bytes=1024)
    tuner._wire_frozen = "int8"
    s = build_schedule(
        [2048, 100], ["float32", "float32"],
        SchedConfig(bucket_bytes=2048),
    )
    applied = tuner.apply(s)
    wires = {b.nbytes: b.wire for b in applied.buckets}
    assert wires[2048] == "int8"
    assert wires[100] == "off"


# ------------------------------------------- checkpoint / elastic flow

def test_ef_residual_survives_checkpoint_roundtrip(hvd_module, tmp_path):
    """The EF residual is ordinary optimizer-state pytree: it rides
    save_checkpoint/load_checkpoint (and therefore elastic
    restore) without special handling, and training resumes from the
    restored residual exactly."""
    params, batch, loss_fn = _problem()
    cfg = SchedConfig(bucket_bytes=64, wire="int8")
    sched.set_config_override(cfg)
    try:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        step = hvd.distributed_train_step(loss_fn, tx)
        p = fresh(params)
        st = step.init(p)
        for _ in range(3):
            p, st, _ = step(p, st, batch)

        path = str(tmp_path / "ckpt")
        hvd.save_checkpoint(path, {"params": p, "opt_state": st}, step=3)
        loaded = hvd.load_checkpoint(path, step=3)
        restored = jax.tree.unflatten(
            jax.tree.structure(st), jax.tree.leaves(loaded["opt_state"])
        )
        for a, b in zip(
            jax.tree.leaves(st.residual),
            jax.tree.leaves(restored.residual),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # resuming from the restored state tracks the uninterrupted run
        p1, st1, l1 = step(p, st, batch)
        p2, st2, l2 = step(
            jax.tree.unflatten(
                jax.tree.structure(p), jax.tree.leaves(loaded["params"])
            ),
            restored, batch,
        )
        assert float(l1) == float(l2)
    finally:
        sched.set_config_override(None)
