"""DistributedOptimizer semantics tests (reference analog:
``test/parallel/test_torch.py`` optimizer cases +
``test_tensorflow2_keras.py`` gradient aggregation tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd


def fresh(tree):
    """Deep-copy arrays: train steps donate their inputs."""
    return jax.tree.map(lambda a: jnp.array(a), tree)


def _quadratic_setup():
    X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    Y = (X @ np.full((4, 1), 0.7)).astype(np.float32)

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w"] - y) ** 2)

    params = {"w": jnp.full((4, 1), 0.3)}
    return X, Y, loss_fn, params


def test_train_step_matches_single_device_sgd(hvd_module):
    """Data-parallel step on 8 chips == single big-batch SGD step."""
    X, Y, loss_fn, params = _quadratic_setup()
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    p1, _, loss = step(fresh(params), st, (jnp.asarray(X), jnp.asarray(Y)))

    # plain JAX single-device reference
    ref_p = {"w": jnp.full((4, 1), 0.3)}
    g = jax.grad(loss_fn)(ref_p, (jnp.asarray(X), jnp.asarray(Y)))
    ref_w = ref_p["w"] - 0.1 * g["w"]
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(ref_w), rtol=1e-5)


def test_backward_passes_per_step_equivalence(hvd_module):
    """k micro-steps with accumulation == one step on the union batch."""
    X, Y, loss_fn, params = _quadratic_setup()
    tx2 = hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=2)
    s2 = hvd.distributed_train_step(loss_fn, tx2)
    st2 = s2.init(params)
    p2 = {"w": jnp.full((4, 1), 0.3)}
    p2, st2, _ = s2(p2, st2, (jnp.asarray(X[:8]), jnp.asarray(Y[:8])))
    p2, st2, _ = s2(p2, st2, (jnp.asarray(X[8:]), jnp.asarray(Y[8:])))

    tx1 = hvd.DistributedOptimizer(optax.sgd(0.1))
    s1 = hvd.distributed_train_step(loss_fn, tx1)
    p1 = {"w": jnp.full((4, 1), 0.3)}
    st1 = s1.init(p1)
    p1, st1, _ = s1(p1, st1, (jnp.asarray(X), jnp.asarray(Y)))
    np.testing.assert_allclose(
        np.asarray(p2["w"]), np.asarray(p1["w"]), rtol=1e-5
    )


def test_no_update_on_non_boundary_step(hvd_module):
    X, Y, loss_fn, params = _quadratic_setup()
    w0 = np.asarray(params["w"]).copy()
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=3)
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    p = {"w": jnp.asarray(w0)}
    p, st, _ = step(p, st, (jnp.asarray(X[:8]), jnp.asarray(Y[:8])))
    np.testing.assert_array_equal(np.asarray(p["w"]), w0)
    p, st, _ = step(p, st, (jnp.asarray(X[8:]), jnp.asarray(Y[8:])))
    np.testing.assert_array_equal(np.asarray(p["w"]), w0)
    p, st, _ = step(p, st, (jnp.asarray(X[:8]), jnp.asarray(Y[:8])))
    assert not np.allclose(np.asarray(p["w"]), w0)


def test_gradient_predivide_factor(hvd_module):
    """predivide split must equal plain averaging numerically
    (reference optimizer.py:194-205)."""
    X, Y, loss_fn, params = _quadratic_setup()
    batch = (jnp.asarray(X), jnp.asarray(Y))
    txa = hvd.DistributedOptimizer(optax.sgd(0.1))
    txb = hvd.DistributedOptimizer(optax.sgd(0.1), gradient_predivide_factor=4.0)
    sa = hvd.distributed_train_step(loss_fn, txa)
    sb = hvd.distributed_train_step(loss_fn, txb)
    pa, _, _ = sa(fresh(params), sa.init(params), batch)
    pb, _, _ = sb(fresh(params), sb.init(params), batch)
    np.testing.assert_allclose(
        np.asarray(pa["w"]), np.asarray(pb["w"]), rtol=1e-5
    )


def test_compression_bf16_close_to_fp32(hvd_module):
    X, Y, loss_fn, params = _quadratic_setup()
    batch = (jnp.asarray(X), jnp.asarray(Y))
    txa = hvd.DistributedOptimizer(optax.sgd(0.1))
    txb = hvd.DistributedOptimizer(optax.sgd(0.1), compression=hvd.Compression.bf16)
    sa = hvd.distributed_train_step(loss_fn, txa)
    sb = hvd.distributed_train_step(loss_fn, txb)
    pa, _, _ = sa(fresh(params), sa.init(params), batch)
    pb, _, _ = sb(fresh(params), sb.init(params), batch)
    np.testing.assert_allclose(
        np.asarray(pa["w"]), np.asarray(pb["w"]), rtol=2e-2, atol=2e-2
    )


def test_explicit_groups(hvd_module):
    """Explicit fusion groups (reference optimizer.py:128-162) keep
    numerics identical."""
    X, Y, _, _ = _quadratic_setup()

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w1"] @ p["w2"] - y) ** 2)

    params = {"w1": jnp.ones((4, 4)) * 0.2, "w2": jnp.ones((4, 1)) * 0.5}
    batch = (jnp.asarray(X), jnp.asarray(Y))
    txa = hvd.DistributedOptimizer(optax.sgd(0.05))
    txb = hvd.DistributedOptimizer(optax.sgd(0.05), groups=[[0, 1]])
    sa = hvd.distributed_train_step(loss_fn, txa)
    sb = hvd.distributed_train_step(loss_fn, txb)
    pa, _, _ = sa(fresh(params), sa.init(params), batch)
    pb, _, _ = sb(fresh(params), sb.init(params), batch)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(pa[k]), np.asarray(pb[k]), rtol=1e-5
        )


def test_adasum_op_in_optimizer(hvd_module):
    """Adasum training step runs and produces finite updates."""
    X, Y, loss_fn, params = _quadratic_setup()
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd.Adasum)
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    p, _, loss = step(fresh(params), st, (jnp.asarray(X), jnp.asarray(Y)))
    assert np.isfinite(np.asarray(p["w"])).all()
    assert float(loss) > 0


def test_stateful_train_step_syncbn(hvd_module):
    """Stateful step: model state is cross-replica averaged (SyncBN)."""

    def loss_fn(p, stats, b):
        x, y = b
        pred = x @ p["w"]
        # running mean of the local batch shard: differs per rank before
        # sync; the step must return the cross-replica average
        new_stats = {"mean": jnp.mean(x)}
        return jnp.mean((pred - y) ** 2), new_stats

    X, Y, _, params = _quadratic_setup()
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    step = hvd.distributed_train_step(loss_fn, tx, stateful=True)
    st = step.init(params)
    stats = {"mean": jnp.zeros(())}
    p, stats, st, loss = step(fresh(params), stats, st, (jnp.asarray(X), jnp.asarray(Y)))
    np.testing.assert_allclose(float(stats["mean"]), X.mean(), rtol=1e-5)
