"""Durable checkpoint save/restore (the reference's Keras
``load_model``-with-hvd-optimizer analog plus the imagenet example's
resume_from_epoch pattern)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd


def _state():
    return {
        "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros(3)},
        "epoch": 4,
    }


def test_save_load_roundtrip(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    hvd.save_checkpoint(path, _state())
    got = hvd.load_checkpoint(path)
    np.testing.assert_allclose(np.asarray(got["params"]["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert got["epoch"] == 4


def test_load_missing_returns_none(hvd_module, tmp_path):
    assert hvd.load_checkpoint(str(tmp_path / "nope")) is None


def test_stepped_checkpoints_and_latest(hvd_module, tmp_path):
    from horovod_tpu.checkpoint import latest_step

    path = str(tmp_path / "ckpt")
    for s in (1, 5, 3):
        hvd.save_checkpoint(path, {"step": s}, step=s)
    assert latest_step(path) == 5
    assert hvd.load_checkpoint(path, step=5)["step"] == 5


def test_restore_or_init_fresh_and_resume(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    init = {"w": jnp.ones((2, 2))}
    state, step = hvd.restore_or_init(path, init)
    assert step == 0
    np.testing.assert_allclose(np.asarray(state["w"]), 1.0)

    hvd.save_checkpoint(path, {"w": jnp.full((2, 2), 7.0)}, step=3)
    state, step = hvd.restore_or_init(path, init)
    assert step == 3
    np.testing.assert_allclose(np.asarray(state["w"]), 7.0)


def test_full_training_state_roundtrip(hvd_module, tmp_path):
    """params + optax opt_state survive the disk round-trip and training
    continues bit-identically (the reference's broadcast_optimizer_state
    + checkpoint resume guarantee)."""
    path = str(tmp_path / "ckpt")

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    rng = np.random.RandomState(0)
    w0 = jnp.asarray(rng.randn(3, 2), jnp.float32)
    batch = (jnp.asarray(rng.randn(8, 3), jnp.float32),
             jnp.asarray(rng.randn(8, 2), jnp.float32))

    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init({"w": w0})
    p, st, _ = step({"w": jnp.array(w0)}, st, batch)
    hvd.save_checkpoint(path, {"params": p, "opt_state": st})

    loaded = hvd.load_checkpoint(path)
    p2 = jax.tree.map(jnp.asarray, loaded["params"])
    st2 = jax.tree.unflatten(
        jax.tree.structure(st),
        [jnp.asarray(l) for l in jax.tree.leaves(loaded["opt_state"])],
    )
    # continue training from both copies: identical trajectories
    pa, _, la = step(jax.tree.map(jnp.array, p), st, batch)
    pb, _, lb = step(p2, st2, batch)
    np.testing.assert_allclose(np.asarray(pa["w"]), np.asarray(pb["w"]),
                               rtol=1e-6, atol=1e-6)
    assert float(la) == pytest.approx(float(lb))
