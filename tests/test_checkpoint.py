"""Durable checkpoint save/restore (the reference's Keras
``load_model``-with-hvd-optimizer analog plus the imagenet example's
resume_from_epoch pattern), plus the integrity guarantees: atomic
writes, content checksums, and corruption fallback."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults, metrics
from horovod_tpu.checkpoint import _META_FILE
from horovod_tpu.exceptions import CheckpointCorruptionError


def _state():
    return {
        "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros(3)},
        "epoch": 4,
    }


def test_save_load_roundtrip(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    hvd.save_checkpoint(path, _state())
    got = hvd.load_checkpoint(path)
    np.testing.assert_allclose(np.asarray(got["params"]["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert got["epoch"] == 4


def test_load_missing_returns_none(hvd_module, tmp_path):
    assert hvd.load_checkpoint(str(tmp_path / "nope")) is None


def test_stepped_checkpoints_and_latest(hvd_module, tmp_path):
    from horovod_tpu.checkpoint import latest_step

    path = str(tmp_path / "ckpt")
    for s in (1, 5, 3):
        hvd.save_checkpoint(path, {"step": s}, step=s)
    assert latest_step(path) == 5
    assert hvd.load_checkpoint(path, step=5)["step"] == 5


def test_restore_or_init_fresh_and_resume(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    init = {"w": jnp.ones((2, 2))}
    state, step = hvd.restore_or_init(path, init)
    assert step == 0
    np.testing.assert_allclose(np.asarray(state["w"]), 1.0)

    hvd.save_checkpoint(path, {"w": jnp.full((2, 2), 7.0)}, step=3)
    state, step = hvd.restore_or_init(path, init)
    assert step == 3
    np.testing.assert_allclose(np.asarray(state["w"]), 7.0)


def test_full_training_state_roundtrip(hvd_module, tmp_path):
    """params + optax opt_state survive the disk round-trip and training
    continues bit-identically (the reference's broadcast_optimizer_state
    + checkpoint resume guarantee)."""
    path = str(tmp_path / "ckpt")

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    rng = np.random.RandomState(0)
    w0 = jnp.asarray(rng.randn(3, 2), jnp.float32)
    batch = (jnp.asarray(rng.randn(8, 3), jnp.float32),
             jnp.asarray(rng.randn(8, 2), jnp.float32))

    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init({"w": w0})
    p, st, _ = step({"w": jnp.array(w0)}, st, batch)
    hvd.save_checkpoint(path, {"params": p, "opt_state": st})

    loaded = hvd.load_checkpoint(path)
    p2 = jax.tree.map(jnp.asarray, loaded["params"])
    st2 = jax.tree.unflatten(
        jax.tree.structure(st),
        [jnp.asarray(l) for l in jax.tree.leaves(loaded["opt_state"])],
    )
    # continue training from both copies: identical trajectories
    pa, _, la = step(jax.tree.map(jnp.array, p), st, batch)
    pb, _, lb = step(p2, st2, batch)
    np.testing.assert_allclose(np.asarray(pa["w"]), np.asarray(pb["w"]),
                               rtol=1e-6, atol=1e-6)
    assert float(la) == pytest.approx(float(lb))


# ---- integrity: atomic write, checksums, corruption fallback ----------


@pytest.fixture(autouse=True)
def _no_fault_plan():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


@pytest.mark.faults
def test_atomic_write_leaves_no_temp_files(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    target = hvd.save_checkpoint(path, _state(), use_orbax=False)
    names = sorted(os.listdir(target))
    assert names == ["checkpoint.meta.json", "checkpoint.pkl"]
    meta = json.loads((tmp_path / "ckpt" / _META_FILE).read_text())
    payload = (tmp_path / "ckpt" / "checkpoint.pkl").read_bytes()
    import hashlib

    assert meta["sha256"] == hashlib.sha256(payload).hexdigest()
    assert meta["size"] == len(payload)
    assert hvd.verify_checkpoint(target)


@pytest.mark.faults
def test_checksum_mismatch_raises_corruption_error(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    target = hvd.save_checkpoint(path, _state(), use_orbax=False)
    pkl = os.path.join(target, "checkpoint.pkl")
    data = bytearray(open(pkl, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(pkl, "wb").write(bytes(data))
    assert not hvd.verify_checkpoint(target)
    with pytest.raises(CheckpointCorruptionError):
        hvd.load_checkpoint(path)


@pytest.mark.faults
def test_truncated_payload_detected(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    target = hvd.save_checkpoint(path, _state(), use_orbax=False)
    pkl = os.path.join(target, "checkpoint.pkl")
    open(pkl, "r+b").truncate(os.path.getsize(pkl) // 2)
    assert not hvd.verify_checkpoint(target)
    with pytest.raises(CheckpointCorruptionError):
        hvd.load_checkpoint(path)


@pytest.mark.faults
def test_legacy_checkpoint_without_sidecar_still_loads(
        hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    target = hvd.save_checkpoint(path, _state(), use_orbax=False)
    os.remove(os.path.join(target, _META_FILE))
    assert hvd.verify_checkpoint(target)  # nothing to check against
    assert hvd.load_checkpoint(path)["epoch"] == 4


@pytest.mark.faults
def test_restore_falls_back_to_last_good_step(hvd_module, tmp_path):
    """The acceptance-criteria scenario: the newest checkpoint is
    corrupted (via the seeded fault plan, not by hand) and resume lands
    on the previous good step with counters to show for it."""
    metrics.reset_counters("checkpoint.")
    path = str(tmp_path / "ckpt")
    for s in (1, 2):
        hvd.save_checkpoint(path, {"epoch": s}, step=s, use_orbax=False)
    faults.set_plan("checkpoint.write:corrupt:nth=1")
    hvd.save_checkpoint(path, {"epoch": 3}, step=3, use_orbax=False)
    faults.set_plan(None)

    from horovod_tpu.checkpoint import latest_step

    assert latest_step(path) == 3
    assert hvd.latest_good_step(path) == 2
    state, step = hvd.restore_or_init(path, {"epoch": 0})
    assert (state["epoch"], step) == (2, 2)
    got = metrics.get_counters("checkpoint.")
    assert got["checkpoint.corrupt_detected"] >= 1
    assert got["checkpoint.fallback"] >= 1
    assert got["checkpoint.saved"] == 3


@pytest.mark.faults
def test_restore_falls_back_with_orbax_format(hvd_module, tmp_path):
    pytest.importorskip("orbax.checkpoint")
    metrics.reset_counters("checkpoint.")
    path = str(tmp_path / "ckpt")
    hvd.save_checkpoint(path, {"w": jnp.ones((2,))}, step=1)
    faults.set_plan("checkpoint.write:corrupt:nth=1")
    hvd.save_checkpoint(path, {"w": jnp.full((2,), 9.0)}, step=2)
    faults.set_plan(None)
    assert hvd.latest_good_step(path) == 1
    state, step = hvd.restore_or_init(path, {"w": jnp.zeros((2,))})
    assert step == 1
    np.testing.assert_allclose(np.asarray(state["w"]), 1.0)


@pytest.mark.faults
def test_all_steps_corrupt_falls_back_to_init(hvd_module, tmp_path):
    path = str(tmp_path / "ckpt")
    faults.set_plan("checkpoint.write:corrupt:times=0")
    hvd.save_checkpoint(path, {"epoch": 1}, step=1, use_orbax=False)
    faults.set_plan(None)
    assert hvd.latest_good_step(path) is None
    state, step = hvd.restore_or_init(path, {"epoch": 0})
    assert (state["epoch"], step) == (0, 0)
