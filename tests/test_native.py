"""Native core tests (the analog of the reference's C++-logic unit tier:
controller/fusion/cache logic driven in-process, SURVEY.md §4)."""

import json
import os
import secrets as pysecrets
import threading
import time

import numpy as np
import pytest

from horovod_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core not built"
)


def test_version():
    lib = native.load()
    assert lib.hvd_version().decode() == "0.1.0"


def test_fusion_plan_basic():
    sizes = [10, 20, 30, 1000, 5]
    dtypes = [0, 0, 0, 0, 0]
    buckets = native.fusion_plan(sizes, dtypes, 100)
    # 10+20+30 fits; 1000 overflows into its own; 5 joins the open 1000?
    # no: 1000+5 > 100 -> 5 opens a new bucket
    assert buckets == [[0, 1, 2], [3], [4]]


def test_fusion_plan_mixed_dtype_lookahead():
    sizes = [10, 10, 10, 10]
    dtypes = [0, 1, 0, 1]
    buckets = native.fusion_plan(sizes, dtypes, 100)
    # interleaved dtypes fuse per-dtype with look-ahead
    assert buckets == [[0, 2], [1, 3]]


def test_fusion_plan_matches_python():
    from horovod_tpu.ops import fusion

    rng = np.random.RandomState(0)
    sizes = [int(s) for s in rng.randint(1, 10_000, 200)]
    dtypes = [str(d) for d in rng.randint(0, 3, 200)]
    ids = {d: i for i, d in enumerate(dict.fromkeys(dtypes))}
    nat = native.fusion_plan(sizes, [ids[d] for d in dtypes], 16_384)
    # python reference implementation (the fallback path)
    open_b = {}
    py = []
    for i, (sz, dt) in enumerate(zip(sizes, dtypes)):
        cur = open_b.get(dt)
        if cur is not None and cur[1] + sz <= 16_384:
            cur[0].append(i)
            open_b[dt] = (cur[0], cur[1] + sz)
        else:
            b = [i]
            py.append(b)
            open_b[dt] = (b, sz)
    assert nat == py


def test_response_cache_lru():
    cache = native.ResponseCache(capacity=2)
    assert not cache.lookup("a", 1)   # miss, insert
    assert cache.lookup("a", 1)       # hit
    assert not cache.lookup("a", 2)   # signature change -> miss
    assert cache.lookup("a", 2)
    cache.lookup("b", 1)
    cache.lookup("c", 1)              # evicts LRU ("a")
    assert len(cache) == 2
    assert not cache.lookup("a", 2)   # was evicted
    cache.close()


def test_native_timeline_valid_json(tmp_path):
    path = str(tmp_path / "tl.json")
    tl = native.NativeTimeline(path)
    for i in range(100):
        tl.record_op(f"tensor_{i}", "ALLREDUCE", 1024 * i)
    tl.begin("neg", "NEGOTIATE_ALLREDUCE")
    tl.end("neg", "NEGOTIATE_ALLREDUCE")
    tl.mark_cycle()
    assert tl.dropped() == 0
    tl.close()
    events = json.load(open(path))
    assert len(events) == 103
    assert events[0]["name"] == "tensor_0"
    assert events[0]["args"]["bytes"] == 0
    assert events[-1]["ph"] == "i"


def test_stall_inspector():
    si = native.StallInspector(warn_seconds=0.05, shutdown_seconds=0.0)
    si.begin("grad_1")
    si.begin("grad_2")
    si.end("grad_2")
    names, shutdown = si.report()
    assert names == []  # not yet stalled
    time.sleep(0.1)
    names, shutdown = si.report()
    assert names == ["grad_1"]
    assert not shutdown
    si.end("grad_1")
    names, _ = si.report()
    assert names == []
    si.close()


def test_wire_roundtrip():
    buf = native.encode_request(
        rank=3, rtype=native.REQUEST_ALLREDUCE, dtype=7, root=-1,
        dims=[64, 128, 3], name="layer1/conv/kernel",
    )
    msg = native.decode_request(buf)
    assert msg["rank"] == 3
    assert msg["type"] == native.REQUEST_ALLREDUCE
    assert msg["dtype"] == 7
    assert msg["dims"] == [64, 128, 3]
    assert msg["name"] == "layer1/conv/kernel"
    assert msg["consumed"] == len(buf)


def test_controller_kv_and_barrier():
    secret = pysecrets.token_hex(16)
    srv = native.ControllerServer(secret=secret, world=4)
    try:
        port = srv.port
        assert port > 0
        clients = [
            native.ControllerClient("127.0.0.1", port, secret, rank=r)
            for r in range(4)
        ]
        clients[0].put("scope", "hello", b"world")
        assert clients[1].get("scope", "hello", timeout_ms=1000) == b"world"
        # blocking get: value published later by another client
        def publisher():
            time.sleep(0.1)
            clients[2].put("scope", "late", b"\x00\x01binary\xff")

        t = threading.Thread(target=publisher)
        t.start()
        assert clients[3].get("scope", "late", timeout_ms=5000) == b"\x00\x01binary\xff"
        t.join()
        # get timeout on missing key
        assert clients[0].get("scope", "missing", timeout_ms=100) is None
        # barrier across 4 participants
        results = [None] * 4

        def do_barrier(r):
            results[r] = clients[r].barrier("round0", 4, timeout_ms=5000)

        threads = [threading.Thread(target=do_barrier, args=(r,)) for r in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(results)
        # scope cleanup
        clients[0].delete_scope("scope")
        assert clients[1].get("scope", "hello", timeout_ms=50) is None
        for c in clients:
            c.close()
    finally:
        srv.stop()


def test_controller_rejects_bad_secret():
    secret = pysecrets.token_hex(16)
    srv = native.ControllerServer(secret=secret, world=1)
    try:
        evil = native.ControllerClient("127.0.0.1", srv.port, "wrong", rank=0)
        with pytest.raises(OSError):
            evil.put("s", "k", b"v")
        evil.close()
    finally:
        srv.stop()


def test_autotune_finds_peak():
    at = native.Autotune(low_log2_bytes=16, high_log2_bytes=28)

    def objective(x):
        return -((x - 23.0) ** 2) + 100.0  # peak at 2^23 bytes

    for _ in range(12):
        x = at.suggest()
        at.observe(x, objective(x))
    best_x, best_y = at.best()
    assert abs(best_x - 23.0) < 1.5, f"best {best_x} too far from 23"
    at.close()


class TestWireResponse:
    """Response codec (reference Response record, message.h)."""

    def test_ok_roundtrip(self):
        from horovod_tpu import native

        if not native.available():
            pytest.skip("native core unavailable")
        blob = native.encode_response(
            native.REQUEST_ALLGATHER, ["t1", "t2"], "", [5, 9, 13]
        )
        d = native.decode_response(blob)
        assert d["type"] == native.REQUEST_ALLGATHER
        assert d["names"] == ["t1", "t2"]
        assert d["sizes"] == [5, 9, 13]
        assert d["error"] == ""
        assert d["consumed"] == len(blob)

    def test_error_roundtrip(self):
        from horovod_tpu import native

        if not native.available():
            pytest.skip("native core unavailable")
        blob = native.encode_response(
            native.RESPONSE_ERROR, [], "rank 2 sent float16, rank 0 float32"
        )
        d = native.decode_response(blob)
        assert d["type"] == native.RESPONSE_ERROR
        assert d["names"] == []
        assert "float16" in d["error"]

    def test_truncated_rejected(self):
        from horovod_tpu import native

        if not native.available():
            pytest.skip("native core unavailable")
        blob = native.encode_response(0, ["x"], "", [1])
        with pytest.raises(ValueError):
            native.decode_response(blob[:4])

    def test_unicode_and_many_sizes(self):
        from horovod_tpu import native

        if not native.available():
            pytest.skip("native core unavailable")
        # multibyte names/error (byte-length cap) + >64 sizes (no clamp)
        blob = native.encode_response(
            2, ["テンソル" * 20], "ошибка: несоответствие " * 5,
            list(range(100)),
        )
        d = native.decode_response(blob)
        assert d["names"] == ["テンソル" * 20]
        assert "несоответствие" in d["error"]
        assert d["sizes"] == list(range(100))
