"""Inference serving plane (horovod_tpu/serve/): checkpoint-to-replica
pipeline, TP-sharded forward through the exchange service, continuous
batching over the arbiter, KV pool, HTTP surfaces.

Contracts under test:

* **Params-only restore** — ``checkpoint.load_params`` returns only
  the requested keys (optimizer state never materializes past the
  reader), resolves the newest good step of a run directory, and names
  missing keys in a structured ``CheckpointMissingKeysError`` instead
  of a raw ``KeyError``.
* **Parity** — a replica restored from a checkpoint produces logits
  bitwise identical (f32, wire off) to a replica built from the
  trained params directly, through the full TP-sharded service path;
  the same holds when serving rides a process-set subgroup; and
  continuous batching yields bitwise the tokens sequential serving
  does (decode math is batch-size invariant).
* **Tenancy** — every serve exchange carries the
  ``serve:<replica>:<phase>`` tenant; request admission is arbiter
  backpressure on the ``serve:<replica>:request`` lane
  (``HVD_TPU_SERVE_INFLIGHT``), blocking not dropping.
* **KV pool** — all-or-nothing extend, LRU eviction of *finished*
  sequences only, backpressure on exhaustion, and svc/fuse
  pack/unpack round-trips (one packer, train and serve).
* **Warm start** — replica N pins replica 1's tune-DB (cycle,
  threshold) entry, keyed by model signature.
* **Surfaces** — ``GET /serve`` payload aggregation (sum counters,
  worst-rank p99), the bench-record pass-through, the standalone
  frontend's ``POST /generate``, and the ``_maybe_serve`` bench
  record's structured-skip contract.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import metrics, svc
from horovod_tpu.exceptions import HorovodTpuError
from horovod_tpu.serve import frontend as frontend_mod
from horovod_tpu.serve import loadgen
from horovod_tpu.serve.batcher import ContinuousBatcher, serve_sequential
from horovod_tpu.serve.frontend import ServeFrontend, serve_payload
from horovod_tpu.serve.kvcache import KVCachePool
from horovod_tpu.serve.replica import Replica, toy_lm_params
from horovod_tpu.svc import arbiter

pytestmark = pytest.mark.serve

TP24 = ((0, 1, 2, 3), (4, 5, 6, 7))


@pytest.fixture(autouse=True)
def _serve_isolation(monkeypatch):
    metrics.reset_counters("serve.")
    metrics.reset_counters("checkpoint.")
    metrics.reset_counters("svc.")
    for knob in ("HVD_TPU_SERVE_WIRE", "HVD_TPU_SERVE_BATCH",
                 "HVD_TPU_SERVE_INFLIGHT", "HVD_TPU_SERVE_KV_TOKENS",
                 "HVD_TPU_TUNE_DB", "HVD_TPU_SVC_CYCLE_TIME",
                 "HVD_TPU_SVC_FUSION_THRESHOLD"):
        monkeypatch.delenv(knob, raising=False)
    frontend_mod._last_bench = None
    yield
    arbiter.set_enabled_override(None)
    svc.set_threshold_override(None)
    svc.reset_service()
    # warm start pins knobs into the process env on purpose; tests
    # must not leak them forward
    import os

    os.environ.pop("HVD_TPU_SVC_CYCLE_TIME", None)
    os.environ.pop("HVD_TPU_SVC_FUSION_THRESHOLD", None)


# ---------------------------------------------------------------------
# satellite 1: params-only restore


@pytest.mark.usefixtures("hvd_module")
class TestParamsOnlyRestore:
    def test_restore_drops_optimizer_state(self, tmp_path):
        params = toy_lm_params()
        state = {"params": params,
                 "opt_state": {"m": np.ones((512,), np.float32)},
                 "step": 7}
        hvd.save_checkpoint(str(tmp_path), state, step=7)
        out = hvd.load_params(str(tmp_path), step=7)
        assert set(out) == {"params"}, "optimizer state leaked through"
        for k in params:
            assert np.array_equal(np.asarray(out["params"][k]),
                                  params[k])
        assert metrics.get_counter("checkpoint.params_only_restore") >= 1

    def test_missing_key_is_structured(self, tmp_path):
        hvd.save_checkpoint(str(tmp_path),
                            {"weights": np.ones((2,), np.float32)},
                            step=1)
        with pytest.raises(hvd.CheckpointMissingKeysError) as ei:
            hvd.load_params(str(tmp_path), step=1)
        err = ei.value
        assert not isinstance(err, KeyError)
        assert "params" in tuple(err.missing)
        assert "weights" in tuple(err.available)
        assert "params" in str(err) and "weights" in str(err)

    def test_run_dir_resolves_latest_step(self, tmp_path):
        for step, seed in ((1, 1), (3, 3)):
            hvd.save_checkpoint(
                str(tmp_path), {"params": toy_lm_params(seed=seed)},
                step=step,
            )
        out = hvd.load_params(str(tmp_path))
        want = toy_lm_params(seed=3)
        assert np.array_equal(np.asarray(out["params"]["emb"]),
                              want["emb"])


# ---------------------------------------------------------------------
# replica: TP-sharded forward, checkpoint parity, process sets


@pytest.mark.usefixtures("hvd_module")
class TestReplicaParity:
    def test_checkpoint_to_serve_bitwise(self, tmp_path):
        """train -> checkpoint -> serve: the restored TP-sharded
        replica's logits are bitwise the direct replica's (f32, wire
        off), through the real exchange service."""
        svc.reset_service()
        params = toy_lm_params(seed=5)
        hvd.save_checkpoint(
            str(tmp_path),
            {"params": params,
             "opt_state": {"v": np.zeros((64,), np.float32)}},
            step=2,
        )
        direct = Replica(params, name="rA", tp_groups=TP24,
                         warm_start=False)
        restored = Replica.from_checkpoint(
            str(tmp_path), name="rB", tp_groups=TP24, warm_start=False,
        )
        toks = [3, 1, 4, 1, 5]
        a = direct.forward(toks)
        b = restored.forward(toks)
        assert a.dtype == np.float32
        assert np.array_equal(a, b), "restored replica diverged"
        # determinism of the service path itself
        assert np.array_equal(a, direct.forward(toks))
        assert metrics.get_counter("serve.replicas_started") == 1
        assert metrics.get_counter("serve.exchanges.decode") >= 3

    def test_process_set_subgroup_bitwise(self, monkeypatch):
        """Serving on a rank subgroup (the "serve on half the pod"
        arrangement) matches the grouped direct path bitwise."""
        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        svc.reset_service()
        params = toy_lm_params(seed=9)
        ps = hvd.add_process_set([0, 1, 2, 3])
        toks = [7, 2, 9]
        sub = Replica(params, name="sub", process_set=ps,
                      warm_start=False)
        # the full-cover grouped replica's first group reduces the same
        # four rows in the same order -> its read row must match bitwise
        grouped = Replica(params, name="grp", tp_groups=TP24,
                          warm_start=False)
        assert np.array_equal(sub.forward(toks), grouped.forward(toks))

    def test_rejects_incomplete_params(self):
        with pytest.raises(HorovodTpuError):
            Replica({"emb": np.zeros((4, 4), np.float32)},
                    warm_start=False)

    def test_serve_tenant_stamping(self):
        assert arbiter.serve_tenant("r0", "decode") == "serve:r0:decode"
        assert arbiter.parse_serve_tenant("serve:r0:decode") == \
            ("r0", "decode")
        assert arbiter.parse_serve_tenant("trainer") is None
        prog = Replica(toy_lm_params(), tp_groups=TP24,
                       warm_start=False).decode_program(2)
        assert prog.kind == "serve_decode"
        assert prog.ops[0].groups == TP24


class TestWarmStart:
    def test_replica_n_warm_starts_from_replica_1(self, tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("HVD_TPU_TUNE_DB",
                           str(tmp_path / "tune.json"))
        params = toy_lm_params()
        r1 = Replica(params, name="r1")
        assert metrics.get_counter("serve.tune.db_miss") == 1
        monkeypatch.setenv("HVD_TPU_SVC_FUSION_THRESHOLD", "12345")
        r1.record_tuned(score=2.0)
        r2 = Replica(params, name="r2")
        assert metrics.get_counter("serve.tune.db_hit") == 1
        assert r2.store_key() == r1.store_key()
        import os

        assert os.environ["HVD_TPU_SVC_FUSION_THRESHOLD"] == "12345"
        assert metrics.get_gauge("serve.tune.warm_start",
                                 {"replica": "r2"}) == 1.0

    def test_signature_separates_models(self):
        a = Replica(toy_lm_params(), warm_start=False)
        b = Replica(toy_lm_params(vocab=16), warm_start=False)
        c = Replica(toy_lm_params(), wire="int8", warm_start=False)
        assert a.signature() != b.signature()
        assert a.signature() != c.signature()
        assert a.signature() == \
            Replica(toy_lm_params(), warm_start=False).signature()

    def test_wire_knob(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SERVE_WIRE", "int8")
        assert Replica(toy_lm_params(), warm_start=False).wire == "int8"


# ---------------------------------------------------------------------
# KV pool


class TestKVCachePool:
    def test_extend_context_roundtrip(self):
        kv = KVCachePool(4, capacity=8)
        rows = np.arange(12, dtype=np.float32).reshape(3, 4)
        assert kv.extend(1, rows)
        assert np.array_equal(kv.tokens(1), rows)
        assert np.array_equal(kv.context(1),
                              rows.mean(axis=0, dtype=np.float32))
        assert kv.append(1, np.full((4,), 9.0, np.float32))
        assert kv.length(1) == 4
        assert kv.used() == 4
        kv.free(1)
        assert kv.used() == 0

    def test_backpressure_and_lru_eviction(self):
        kv = KVCachePool(2, capacity=4)
        assert kv.extend(1, np.ones((3, 2), np.float32))
        kv.mark_finished(1)
        # evicting the finished seq makes room for the next one
        assert kv.extend(2, np.ones((3, 2), np.float32))
        assert metrics.get_counter("serve.kv.evictions") == 1
        assert kv.length(1) == 0
        # seq 2 is active: a pool-filling extend must fail all-or-
        # nothing, leaving both the new seq and the free list untouched
        used_before = kv.used()
        assert not kv.extend(3, np.ones((2, 2), np.float32))
        assert metrics.get_counter("serve.kv.rejects") == 1
        assert kv.length(3) == 0 and kv.used() == used_before

    def test_fused_payload_write_back(self):
        kv = KVCachePool(4, capacity=16)
        r1 = np.arange(8, dtype=np.float32).reshape(2, 4)
        r2 = np.arange(8, 20, dtype=np.float32).reshape(3, 4)
        kv.extend(1, r1)
        kv.extend(2, r2)
        buf, layout = kv.fused_payload([1, 2])
        assert buf.ndim == 1 and buf.size % kv.align == 0
        kv.write_back([1, 2], buf * 2.0, layout)
        assert np.array_equal(kv.tokens(1), r1 * 2.0)
        assert np.array_equal(kv.tokens(2), r2 * 2.0)


# ---------------------------------------------------------------------
# continuous batching


@pytest.mark.usefixtures("hvd_module")
class TestContinuousBatching:
    def test_continuous_equals_sequential_bitwise(self):
        """The headline parity: a request decoded in a shifting batch
        yields bitwise the tokens it gets served alone."""
        svc.reset_service()
        params = toy_lm_params(seed=2)
        prompts = loadgen.synthetic_prompts(6, seed=11)
        seq_out = serve_sequential(
            Replica(params, name="s", tp_groups=TP24, warm_start=False),
            prompts, max_new_tokens=4,
        )
        bat = ContinuousBatcher(
            Replica(params, name="c", tp_groups=TP24, warm_start=False),
            batch=4,
        )
        try:
            reqs = [bat.submit(p, max_new_tokens=4) for p in prompts]
            cont_out = [r.result(timeout=120) for r in reqs]
        finally:
            bat.stop()
        assert cont_out == seq_out
        assert loadgen.output_digest(cont_out) == \
            loadgen.output_digest(seq_out)
        assert metrics.get_counter("serve.requests_completed") >= 6
        assert metrics.get_counter("serve.tokens_generated") >= 24

    def test_request_lifecycle_timestamps(self):
        svc.reset_service()
        bat = ContinuousBatcher(
            Replica(toy_lm_params(), name="t", tp_groups=TP24,
                    warm_start=False),
            batch=2,
        )
        try:
            req = bat.submit([1, 2], max_new_tokens=3)
            out = req.result(timeout=120)
        finally:
            bat.stop()
        assert len(out) == 3
        assert req.arrival <= req.prefilled_at <= req.first_token_at \
            <= req.finished_at
        assert req.tenant == "serve:t:request"
        assert req.lane_released, "retire must release the lane slot"


class TestAdmissionControl:
    def test_inflight_cap_blocks_then_admits(self):
        """HVD_TPU_SERVE_INFLIGHT backpressure: the lane at cap blocks
        submit; an expired wait admits anyway (never a drop)."""
        bat = ContinuousBatcher(
            Replica(toy_lm_params(), name="adm", warm_start=False),
            inflight=1, start=False,
        )
        bat.submit([1], max_new_tokens=1)
        t0 = time.monotonic()
        req2 = bat.submit([2], max_new_tokens=1, admit_timeout_s=0.2)
        waited = time.monotonic() - t0
        assert waited >= 0.15, "second submit did not block at the cap"
        assert req2.admitted
        assert metrics.get_counter("svc.tenant.admission_timeouts") >= 1

    def test_result_timeout_raises(self):
        bat = ContinuousBatcher(
            Replica(toy_lm_params(), name="to", warm_start=False),
            start=False,
        )
        req = bat.submit([1], max_new_tokens=1)
        with pytest.raises(HorovodTpuError, match="timed out"):
            req.result(timeout=0.05)


# ---------------------------------------------------------------------
# surfaces: /serve payload, frontend HTTP, loadgen, bench record


@pytest.mark.usefixtures("hvd_module")
class TestServeSurfaces:
    def test_serve_payload_local(self):
        svc.reset_service()
        bat = ContinuousBatcher(
            Replica(toy_lm_params(), name="pay", tp_groups=TP24,
                    warm_start=False),
            batch=2,
        )
        try:
            reqs = [bat.submit([i, i + 1], max_new_tokens=2)
                    for i in range(3)]
            for r in reqs:
                r.result(timeout=120)
        finally:
            bat.stop()
        payload = serve_payload()
        assert payload["counters"]["serve.requests_completed"] >= 3
        assert "pay" in payload["replicas"]
        assert payload["latency"]["request"]["count"] >= 3
        assert payload["latency"]["decode"]["p99_s"] is not None
        assert payload["kv"].get("capacity", 0) > 0

    def test_serve_payload_aggregates_ranks(self):
        """Driver-side view: counters sum across ranks, latency takes
        the worst rank's p99."""
        def snap(completed, bound):
            return {
                "counters": {"serve.requests_completed": completed},
                "gauges": [
                    {"name": "serve.tokens_per_s",
                     "labels": {"replica": "r"}, "value": 10.0},
                ],
                "histograms": {"serve.decode_seconds": {
                    "count": 4, "sum": 4 * bound,
                    "buckets": [bound], "counts": [4, 0],
                }},
            }

        slow = snap(3, 0.050)
        payload = serve_payload({0: snap(2, 0.010), 1: slow})
        assert payload["counters"]["serve.requests_completed"] == 5
        assert payload["replicas"]["r"]["tokens_per_s"] == 20.0
        assert payload["latency"]["decode"]["p99_s"] == \
            metrics.hist_quantile(
                slow["histograms"]["serve.decode_seconds"], 0.99)
        assert set(payload["ranks"]) == {"0", "1"}

    def test_bench_record_rides_serve_payload(self):
        frontend_mod.note_bench({"metric": "serve_plane", "value": 2.0})
        assert serve_payload()["bench"]["metric"] == "serve_plane"
        assert frontend_mod.last_bench()["value"] == 2.0

    def test_frontend_http_generate_and_stats(self):
        svc.reset_service()
        params = toy_lm_params(seed=4)
        bat = ContinuousBatcher(
            Replica(params, name="web", tp_groups=TP24,
                    warm_start=False),
            batch=2,
        )
        fe = ServeFrontend(bat, port=0)
        try:
            body = json.dumps(
                {"prompt": [1, 2, 3], "max_new_tokens": 3}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{fe.port}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
            assert len(out["tokens"]) == 3
            # the HTTP path serves bitwise what the oracle generates
            want = serve_sequential(
                Replica(params, name="web2", tp_groups=TP24,
                        warm_start=False),
                [[1, 2, 3]], max_new_tokens=3,
            )[0]
            assert out["tokens"] == want
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{fe.port}/serve",
                    timeout=10) as resp:
                stats = json.loads(resp.read())
            assert stats["counters"]["serve.requests_completed"] >= 1
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{fe.port}/health",
                    timeout=10) as resp:
                health = json.loads(resp.read())
            assert health["status"] == "ok"
            assert health["replica"] == "web"
        finally:
            fe.stop()
            bat.stop()

    def test_telemetry_server_serves_serve_route(self):
        from horovod_tpu.runner.telemetry_http import TelemetryServer

        frontend_mod.note_bench({"metric": "serve_plane", "value": 3.0})
        ts = TelemetryServer(port=0, bind_host="127.0.0.1")
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ts.port}/serve",
                    timeout=10) as resp:
                payload = json.loads(resp.read())
            assert payload["bench"]["metric"] == "serve_plane"
        finally:
            ts.stop()

    def test_loadgen_deterministic_summary(self):
        assert loadgen.synthetic_prompts(5, seed=3) == \
            loadgen.synthetic_prompts(5, seed=3)
        assert loadgen.output_digest([[1, 2], [3]]) != \
            loadgen.output_digest([[3], [1, 2]])
        svc.reset_service()
        bat = ContinuousBatcher(
            Replica(toy_lm_params(), name="lg", tp_groups=TP24,
                    warm_start=False),
            batch=4,
        )
        try:
            gen = loadgen.LoadGenerator(bat, rate_rps=200, count=5,
                                        max_new_tokens=2)
            summary = gen.run(timeout_s=120)
        finally:
            bat.stop()
        assert summary["requests"] == 5
        assert summary["tokens"] == 10
        assert summary["digest"] == \
            loadgen.output_digest(summary["outputs"])
        assert summary["achieved_rps"] > 0
        assert "p99_ms" in summary["ttft"]


# ---------------------------------------------------------------------
# bench record plumbing (the _maybe_tenant contract, serve edition)


class TestMaybeServe:
    def _bench(self):
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
        spec = importlib.util.spec_from_file_location("bench_mod", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_env_skip(self, monkeypatch):
        bench = self._bench()
        monkeypatch.setenv("HVD_BENCH_SERVE", "0")
        result = {}
        bench._maybe_serve(result, 480, time.monotonic())
        assert "serve_plane" not in result

    def test_deadline_structured_skip(self, monkeypatch):
        bench = self._bench()
        monkeypatch.delenv("HVD_BENCH_SERVE", raising=False)
        result = {}
        bench._maybe_serve(result, 10, time.monotonic())
        assert result["serve_plane"]["error"] == \
            "skipped: deadline too close"
