"""SLO self-healing control plane (``runner/slo.py`` +
``elastic/remediate.py``).

Contracts under test:

* **Specs** — ``HVD_TPU_SLO_SPEC`` parsing: per-tenant step/p99
  targets, malformed entries skipped (never a dead driver).
* **Watchdog** — breach detection folds the tenant phase histograms,
  the ``/tenants`` wait aggregation, and the straggler verdicts;
  N-consecutive-window hysteresis gates confirmation, recovery re-arms.
* **Ladder** — a confirmed breach escalates preempt -> degrade ->
  handoff one rung per cooldown; every rung runs under its
  RetryPolicy; the handoff moves REAL shard buffers through
  :func:`~horovod_tpu.elastic.remesh.reshard_shards` bitwise.
* **Abort contract** — a fault at ``remediate.plan`` aborts before
  anything changed; at ``remediate.handoff`` the placement rolls back
  to the pre-handoff state and the shards continue bitwise; at
  ``remediate.rollback`` the abort record says ``stable=False``.
* **Surfaces** — ``GET /slo`` serves specs + status + remediation
  history; the negotiator's stall escalation abandons a dead
  producer's negotiation after ``HVD_TPU_STALL_ABANDON`` stalled
  checks and the service resolves its futures inline; the arbiter's
  admission-timeout and preemption-expiry paths land in the event log.

``tools/tier1_slo_smoke.sh`` drives the same marker end-to-end across
4 worker processes.
"""

import json
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from horovod_tpu import events, faults, metrics
from horovod_tpu.elastic import remediate, remesh
from horovod_tpu.elastic.remediate import (
    RemediationError,
    Remediator,
    pick_donor,
    plan_handoff,
)
from horovod_tpu.runner import slo
from horovod_tpu.runner.telemetry_http import TelemetryServer

pytestmark = pytest.mark.slo


@pytest.fixture(autouse=True)
def _slo_isolation():
    metrics.reset_counters("slo.")
    metrics.reset_counters("trace.")
    metrics.reset_counters("svc.")
    metrics.reset_counters("faults.")
    metrics.reset_counters("retry.")
    yield
    faults.set_plan(None)
    events.set_event_log(None)
    metrics.reset_counters("slo.")
    metrics.reset_counters("trace.")


@pytest.fixture()
def event_log(tmp_path):
    path = str(tmp_path / "events.jsonl")
    events.set_event_log(events.EventLog(path))
    yield path
    events.set_event_log(None)


def _named(path, name):
    return [e for e in events.read_events(path) if e["event"] == name]


# --------------------------------------------------------- snapshots

def rank_snapshot(tenant_ms=None, wait_ms=None, phase_ms=None, n=8):
    """One synthetic worker metrics snapshot: per-tenant phase
    histograms (``trace.tenant_seconds.<t>.dcn``), optional arbiter
    wait histograms, optional untagged phase histograms — built
    through the real registry so the bucket shapes are authentic."""
    metrics.reset_counters("trace.")
    metrics.reset_counters("svc.tenant.wait_seconds")
    for _ in range(n):
        for t, ms in (tenant_ms or {}).items():
            metrics.observe(f"trace.tenant_seconds.{t}.dcn", ms / 1e3)
        for t, ms in (wait_ms or {}).items():
            metrics.observe(f"svc.tenant.wait_seconds.{t}", ms / 1e3)
        if phase_ms is not None:
            metrics.observe("trace.phase_seconds.dcn", phase_ms / 1e3)
    snap = metrics.snapshot()
    metrics.reset_counters("trace.")
    metrics.reset_counters("svc.tenant.wait_seconds")
    return snap


# ------------------------------------------------------------- specs

class TestSpecParsing:
    def test_full_syntax(self):
        specs = slo.parse_slo_spec(
            "jobA:step=0.5,p99=0.05;jobB:p99=0.1"
        )
        assert specs["jobA"].step_s == 0.5
        assert specs["jobA"].p99_s == 0.05
        assert specs["jobB"].step_s is None
        assert specs["jobB"].p99_s == 0.1
        assert specs["jobA"].targets() == [("step", 0.5),
                                           ("p99", 0.05)]

    @pytest.mark.parametrize("raw", [
        "", ";;", "noseparator", "t:", "t:step", "t:step=abc",
        "t:step=-1", "t:latency=0.5",
    ])
    def test_malformed_entries_skipped(self, raw):
        assert slo.parse_slo_spec(raw) == {}

    def test_bad_entry_does_not_kill_good_ones(self):
        specs = slo.parse_slo_spec("bad:wat=1;good:step=0.2")
        assert list(specs) == ["good"]

    def test_specs_from_env(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SLO_SPEC", "j:step=0.25")
        assert slo.specs_from_env()["j"].step_s == 0.25
        monkeypatch.delenv("HVD_TPU_SLO_SPEC")
        assert slo.specs_from_env() == {}


# ---------------------------------------------------------- observed

class TestObserveTenants:
    def test_step_is_worst_rank_phase_p50_sum(self):
        fast = rank_snapshot(tenant_ms={"a": 1.0})
        slow = rank_snapshot(tenant_ms={"a": 50.0})
        obs = slo.observe_tenants({0: fast, 1: slow})
        assert obs["a"]["step_s"] == pytest.approx(0.05, rel=0.5)
        assert obs["a"]["step_s"] > 0.02  # the slow rank, not the fast

    def test_p99_prefers_arbiter_wait_histogram(self):
        snap = rank_snapshot(tenant_ms={"a": 1.0},
                             wait_ms={"a": 200.0})
        obs = slo.observe_tenants({0: snap})
        assert obs["a"]["p99_s"] > 0.05  # the wait hist, not the 1ms phase

    def test_p99_falls_back_to_phase_p99(self):
        snap = rank_snapshot(tenant_ms={"a": 30.0})
        obs = slo.observe_tenants({0: snap})
        assert obs["a"]["p99_s"] is not None
        assert obs["a"]["p99_s"] > 0.01

    def test_straggler_verdicts_attach_to_tenant(self):
        fast = rank_snapshot(tenant_ms={"a": 1.0}, phase_ms=1.0)
        slow = rank_snapshot(tenant_ms={"a": 40.0}, phase_ms=40.0)
        obs = slo.observe_tenants({0: fast, 1: slow})
        assert any(s["rank"] == 1 for s in obs["a"]["stragglers"])


# ---------------------------------------------------------- watchdog

class TestWatchdogHysteresis:
    def _breaching(self):
        return {0: rank_snapshot(tenant_ms={"jobA": 50.0})}

    def _green(self):
        return {0: rank_snapshot(tenant_ms={"jobA": 0.5})}

    def test_confirm_only_after_n_consecutive_windows(self, event_log):
        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=3)
        assert wd.evaluate(self._breaching())["breaches"] == []
        assert wd.evaluate(self._breaching())["breaches"] == []
        status = wd.evaluate(self._breaching())
        assert [b["tenant"] for b in status["breaches"]] == ["jobA"]
        assert status["breaches"][0]["kind"] == "step"
        assert status["breaches"][0]["windows"] == 3
        assert metrics.get_counter("slo.breaches") == 1
        assert metrics.get_counter("slo.breaches.jobA.step") == 1
        assert len(_named(event_log, events.SLO_BREACH)) == 1
        assert metrics.get_gauge(
            "slo.breached", {"tenant": "jobA", "kind": "step"}) == 1.0

    def test_green_window_resets_the_streak(self):
        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=3)
        wd.evaluate(self._breaching())
        wd.evaluate(self._breaching())
        wd.evaluate(self._green())  # streak broken at 2
        wd.evaluate(self._breaching())
        wd.evaluate(self._breaching())
        assert wd.evaluate(self._breaching())["breaches"], \
            "streak should re-confirm after 3 fresh windows"
        assert metrics.get_counter("slo.breaches") == 1

    def test_recovery_emits_event_and_counter(self, event_log):
        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=1)
        assert wd.evaluate(self._breaching())["breaches"]
        assert wd.evaluate(self._green())["breaches"] == []
        assert metrics.get_counter("slo.recoveries") == 1
        assert len(_named(event_log, events.SLO_RECOVERED)) == 1
        assert metrics.get_gauge(
            "slo.breached", {"tenant": "jobA", "kind": "step"}) == 0.0

    def test_unobserved_tenant_never_breaches(self):
        wd = slo.SLOWatchdog(slo.parse_slo_spec("ghost:step=0.01"),
                             windows=1)
        assert wd.evaluate(self._breaching())["breaches"] == []

    def test_no_data_is_not_recovery(self, event_log):
        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=1)
        assert wd.evaluate(self._breaching())["breaches"]
        # ranks stop reporting (workers died, histograms gone): the
        # window that cannot see the tenant must hold the breach, not
        # declare it recovered with observed=None.
        status = wd.evaluate({})
        assert [b["tenant"] for b in status["breaches"]] == ["jobA"]
        assert status["breaches"][0]["observed"] is None
        assert status["breaches"][0]["no_data"] is True
        assert status["recovered"] == []
        assert status["tenants"]["jobA"]["no_data"] == ["step"]
        assert metrics.get_counter("slo.recoveries") in (None, 0)
        assert _named(event_log, events.SLO_RECOVERED) == []
        assert metrics.get_gauge(
            "slo.no_data", {"tenant": "jobA", "kind": "step"}) == 1.0
        # data returns green: a genuine recovery, this time with a value
        status = wd.evaluate(self._green())
        assert status["breaches"] == []
        assert [r["tenant"] for r in status["recovered"]] == ["jobA"]
        recs = _named(event_log, events.SLO_RECOVERED)
        assert recs and recs[0]["observed"] is not None
        assert metrics.get_gauge(
            "slo.no_data", {"tenant": "jobA", "kind": "step"}) == 0.0

    def test_no_data_holds_streak_without_advancing(self):
        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=2)
        assert wd.evaluate(self._breaching())["breaches"] == []
        # a blind window neither breaks the streak nor advances it
        assert wd.evaluate({})["breaches"] == []
        assert wd.evaluate({})["breaches"] == []
        status = wd.evaluate(self._breaching())
        assert [b["tenant"] for b in status["breaches"]] == ["jobA"]
        assert status["breaches"][0]["windows"] == 2


# ------------------------------------------------------------ ladder

def _breach(tenant="jobA", kind="step"):
    return {"tenant": tenant, "kind": kind, "observed": 0.9,
            "target": 0.1}


class TestEscalationLadder:
    def test_rungs_escalate_and_cap_at_handoff(self):
        calls = []
        r = Remediator(
            placement={"jobA": 1, "jobB": 3},
            actuators={
                "preempt": lambda t, b: calls.append("preempt"),
                "degrade": lambda t, b: calls.append("degrade") or {},
                "handoff": lambda o, n, b: calls.append("handoff"),
            },
            cooldown_s_=0.0, retry_attempts=1, retry_timeout_s=5.0,
            sleep=lambda s: None,
        )
        for _ in range(4):
            r.consider(_breach())
        assert calls == ["preempt", "degrade", "handoff", "handoff"]
        assert r.placement() == {"jobA": 3, "jobB": 1}
        assert metrics.get_counter("slo.remediations.preempt") == 1
        assert metrics.get_counter("slo.remediations.handoff") == 2

    def test_cooldown_gates_reactions(self):
        clock = {"t": 100.0}
        calls = []
        r = Remediator(
            placement={"jobA": 1, "jobB": 2},
            actuators={"preempt": lambda t, b: calls.append("p"),
                       "degrade": lambda t, b: {}},
            cooldown_s_=30.0, retry_attempts=1,
            clock=lambda: clock["t"], sleep=lambda s: None,
        )
        assert r.consider(_breach()) is not None
        assert r.consider(_breach()) is None  # inside cooldown
        clock["t"] += 31.0
        assert r.consider(_breach()) is not None  # escalated rung
        assert calls == ["p"]

    def test_reset_rearms_from_cheapest_rung(self):
        calls = []
        r = Remediator(
            actuators={"preempt": lambda t, b: calls.append("p"),
                       "degrade": lambda t, b: {}},
            cooldown_s_=0.0, retry_attempts=1, sleep=lambda s: None,
        )
        r.consider(_breach())
        r.reset("jobA")
        r.consider(_breach())
        assert calls == ["p", "p"]

    def test_rung_retries_then_aborts(self, event_log):
        attempts = []

        def flaky(t, b):
            attempts.append(1)
            raise RuntimeError("actuator down")

        r = Remediator(actuators={"preempt": flaky},
                       cooldown_s_=0.0, retry_attempts=3,
                       retry_timeout_s=5.0, sleep=lambda s: None)
        rec = r.remediate(_breach(), "preempt")
        assert rec["outcome"] == "abort"
        assert rec["stable"] is True  # nothing moved
        assert len(attempts) == 3
        aborts = _named(event_log, events.REMEDIATE_ABORT)
        assert aborts and aborts[0]["stable"] is True
        assert metrics.get_counter("slo.remediation_abort") == 1

    def test_degrade_records_knob_changes(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SVC_STALENESS", "1")
        monkeypatch.setenv("HVD_TPU_TOPO_LOWER", "hier")
        r = Remediator(cooldown_s_=0.0, retry_attempts=1,
                       sleep=lambda s: None)
        rec = r.remediate(_breach(), "degrade")
        assert rec["outcome"] == "ok"
        assert rec["changes"]["HVD_TPU_SVC_STALENESS"] == "2"
        assert rec["changes"]["HVD_TPU_TOPO_LOWER"] == "flat"
        import os

        assert os.environ["HVD_TPU_SVC_STALENESS"] == "2"
        assert os.environ["HVD_TPU_TOPO_LOWER"] == "flat"

    def test_reset_reverts_degrade_knobs(self, monkeypatch, event_log):
        import os

        monkeypatch.setenv("HVD_TPU_SVC_STALENESS", "1")
        monkeypatch.delenv("HVD_TPU_TOPO_LOWER", raising=False)
        published = []
        r = Remediator(
            actuators={"undegrade":
                       lambda t, restored: published.append((t, restored))},
            cooldown_s_=0.0, retry_attempts=1, sleep=lambda s: None)
        r.remediate(_breach(), "degrade")
        r.remediate(_breach(), "degrade")  # second bump: 1 -> 2 -> 3
        assert os.environ["HVD_TPU_SVC_STALENESS"] == "3"
        assert os.environ["HVD_TPU_TOPO_LOWER"] == "flat"
        r.reset("jobA")
        # a breach/recover cycle is a round trip, not a ratchet: the
        # ORIGINAL values return, not the first bump's.
        assert os.environ["HVD_TPU_SVC_STALENESS"] == "1"
        assert "HVD_TPU_TOPO_LOWER" not in os.environ
        assert published == [("jobA", {"HVD_TPU_SVC_STALENESS": "1",
                                       "HVD_TPU_TOPO_LOWER": None})]
        assert metrics.get_counter("slo.degrade_reverts") == 1
        reverts = _named(event_log, events.REMEDIATE_REVERT)
        assert reverts and reverts[0]["tenant"] == "jobA"
        # re-arming twice is idempotent: nothing left to revert
        r.reset("jobA")
        assert metrics.get_counter("slo.degrade_reverts") == 1

    def test_plan_handoff_validates_before_mutation(self):
        with pytest.raises(RemediationError):
            plan_handoff({"a": 1, "b": 1}, "a", "b")  # starves donor
        with pytest.raises(RemediationError):
            plan_handoff({"a": 2}, "a", "a")
        assert plan_handoff({"a": 3, "b": 1}, "a", "b", slices=2) == \
            {"a": 1, "b": 3}

    def test_pick_donor_most_slices_ties_by_name(self):
        assert pick_donor({"a": 2, "b": 3, "c": 3}, "a") == "b"
        assert pick_donor({"a": 1, "b": 1}, "a") is None


# ----------------------------------------------- handoff via remesh

def _split(buf, layout):
    padded = np.zeros(layout.shards * layout.shard_len, buf.dtype)
    padded[:buf.size] = buf
    return [
        padded[r * layout.shard_len:(r + 1) * layout.shard_len].copy()
        for r in range(layout.shards)
    ]


class TestHandoffMovesRealState:
    """The in-process slice handoff: donor shrink + recipient grow are
    reshard_shards calls, so the exchanged state is a permutation —
    training continues bitwise after both the handoff and a rollback.
    """

    def _actuators(self, store):
        # store: tenant -> {"buf": flat valid array, "layout": ShardLayout,
        # "shards": list}; the handoff re-lays each tenant's shards out
        # over its NEW slice count.
        def relayout(tenant, new_slices):
            st = store[tenant]
            old = st["layout"]
            new = remesh.ShardLayout(
                old.n, new_slices,
                -(-old.n // new_slices),  # ceil
            )
            st["shards"] = remesh.reshard_shards(st["shards"], old, new)
            st["layout"] = new

        def handoff(old_p, new_p, breach):
            for tenant in sorted(set(old_p) | set(new_p)):
                if old_p.get(tenant) != new_p.get(tenant):
                    relayout(tenant, new_p[tenant])

        def rollback(old_p, new_p, breach):
            for tenant in sorted(set(old_p) | set(new_p)):
                if store[tenant]["layout"].shards != old_p[tenant]:
                    relayout(tenant, old_p[tenant])

        return {"handoff": handoff, "rollback": rollback,
                "preempt": lambda t, b: None,
                "degrade": lambda t, b: {}}

    def _store(self):
        store = {}
        rng = np.random.RandomState(0)
        for tenant, slices in (("jobA", 1), ("jobB", 3)):
            buf = rng.rand(23).astype(np.float32)
            layout = remesh.ShardLayout(23, slices, -(-23 // slices))
            store[tenant] = {"buf": buf, "layout": layout,
                             "shards": _split(buf, layout)}
        return store

    def _valid(self, st):
        flat = np.concatenate([np.asarray(s).reshape(-1)
                               for s in st["shards"]])
        return flat[:st["layout"].n]

    def test_handoff_is_bitwise_and_measured(self, event_log):
        store = self._store()
        before = {t: self._valid(st).copy() for t, st in store.items()}
        r = Remediator(placement={"jobA": 1, "jobB": 3},
                       actuators=self._actuators(store),
                       cooldown_s_=0.0, retry_attempts=1,
                       sleep=lambda s: None)
        rec = r.remediate(_breach("jobA"), "handoff")
        assert rec["outcome"] == "ok"
        assert rec["donor"] == "jobB"
        assert r.placement() == {"jobA": 2, "jobB": 2}
        assert store["jobA"]["layout"].shards == 2
        assert store["jobB"]["layout"].shards == 2
        for tenant in store:
            np.testing.assert_array_equal(
                self._valid(store[tenant]), before[tenant]
            ), f"handoff permuted {tenant} state"
        # measured: per-phase wall clocks in the record + histogram
        assert [p["phase"] for p in rec["phases"]] == \
            ["plan", "handoff"]
        assert all(p["seconds"] >= 0 for p in rec["phases"])
        assert metrics.get_counter("slo.handoffs") == 1
        oks = _named(event_log, events.REMEDIATE_OK)
        assert oks and oks[0]["rung"] == "handoff"

    def test_handoff_fault_rolls_back_bitwise(self, event_log):
        store = self._store()
        before = {t: self._valid(st).copy() for t, st in store.items()}
        faults.set_plan("remediate.handoff:error:times=0")
        r = Remediator(placement={"jobA": 1, "jobB": 3},
                       actuators=self._actuators(store),
                       cooldown_s_=0.0, retry_attempts=2,
                       retry_timeout_s=5.0, sleep=lambda s: None)
        rec = r.remediate(_breach("jobA"), "handoff")
        assert rec["outcome"] == "abort"
        assert rec["stable"] is True
        # placement restored, state untouched bitwise
        assert r.placement() == {"jobA": 1, "jobB": 3}
        for tenant in store:
            assert store[tenant]["layout"].shards == \
                {"jobA": 1, "jobB": 3}[tenant]
            np.testing.assert_array_equal(
                self._valid(store[tenant]), before[tenant]
            )
        assert metrics.get_counter("slo.rollbacks") == 1
        assert metrics.get_counter(
            "faults.injected.remediate.handoff.error") == 2
        aborts = _named(event_log, events.REMEDIATE_ABORT)
        assert aborts and aborts[0]["stable"] is True

    def test_plan_fault_aborts_before_any_mutation(self):
        store = self._store()
        faults.set_plan("remediate.plan:error:nth=1")
        r = Remediator(placement={"jobA": 1, "jobB": 3},
                       actuators=self._actuators(store),
                       cooldown_s_=0.0, retry_attempts=1,
                       sleep=lambda s: None)
        rec = r.remediate(_breach("jobA"), "handoff")
        assert rec["outcome"] == "abort"
        assert rec["stable"] is True
        assert r.placement() == {"jobA": 1, "jobB": 3}
        assert store["jobB"]["layout"].shards == 3  # nothing moved
        assert metrics.get_counter("slo.rollbacks") == 0  # no rollback needed

    def test_rollback_fault_marks_unstable(self, event_log):
        store = self._store()
        faults.set_plan(
            "remediate.handoff:error:times=0;"
            "remediate.rollback:error:times=0"
        )
        r = Remediator(placement={"jobA": 1, "jobB": 3},
                       actuators=self._actuators(store),
                       cooldown_s_=0.0, retry_attempts=1,
                       retry_timeout_s=5.0, sleep=lambda s: None)
        rec = r.remediate(_breach("jobA"), "handoff")
        assert rec["outcome"] == "abort"
        assert rec["stable"] is False
        assert rec["rollback_error"]
        aborts = _named(event_log, events.REMEDIATE_ABORT)
        assert aborts and aborts[0]["stable"] is False
        assert metrics.get_counter("slo.remediation_unstable") == 1


# -------------------------------------------------- controller + /slo

class TestController:
    def test_from_env_none_without_spec(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_SLO_SPEC", raising=False)
        assert slo.SLOController.from_env() is None

    def test_tick_rate_limit_and_remediation(self, monkeypatch):
        acted = []

        class FakeRemediator:
            def consider(self, breach):
                acted.append(breach["tenant"])

            def history(self):
                return []

            def placement(self):
                return {}

        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=1)
        c = slo.SLOController(wd, remediator=FakeRemediator(),
                              check_interval_s_=10.0)
        snaps = {0: rank_snapshot(tenant_ms={"jobA": 50.0})}
        assert c.maybe_tick(lambda: snaps, now=100.0) is not None
        assert c.maybe_tick(lambda: snaps, now=105.0) is None
        assert c.maybe_tick(lambda: snaps, now=111.0) is not None
        assert acted == ["jobA", "jobA"]
        assert metrics.get_counter("slo.windows") == 2

    def test_recovery_rearms_the_ladder(self):
        resets = []

        class FakeRemediator:
            def consider(self, breach):
                pass

            def reset(self, tenant):
                resets.append(tenant)

            def history(self):
                return []

            def placement(self):
                return {}

        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=1)
        c = slo.SLOController(wd, remediator=FakeRemediator(),
                              check_interval_s_=0.0)
        breaching = {0: rank_snapshot(tenant_ms={"jobA": 50.0})}
        green = {0: rank_snapshot(tenant_ms={"jobA": 0.5})}
        c.maybe_tick(lambda: breaching, now=0.0)
        assert resets == []  # still breached: the rung sticks
        c.maybe_tick(lambda: {}, now=1.0)
        assert resets == []  # blind window: no phantom recovery
        c.maybe_tick(lambda: green, now=2.0)
        assert resets == ["jobA"]  # real green data re-arms

    def test_tick_never_raises(self):
        wd = slo.SLOWatchdog(slo.parse_slo_spec("j:step=0.1"))
        c = slo.SLOController(wd, check_interval_s_=0.0)

        def explode():
            raise RuntimeError("kv down")

        assert c.maybe_tick(explode) is None

    def test_slo_endpoint_serves_status_and_history(self):
        r = Remediator(placement={"jobA": 1, "jobB": 3},
                       actuators={"preempt": lambda t, b: None},
                       cooldown_s_=0.0, retry_attempts=1,
                       sleep=lambda s: None)
        r.remediate(_breach("jobA"), "preempt")
        wd = slo.SLOWatchdog(slo.parse_slo_spec("jobA:step=0.01"),
                             windows=1)
        c = slo.SLOController(wd, remediator=r,
                              check_interval_s_=0.0)
        c.maybe_tick(lambda: {0: rank_snapshot(
            tenant_ms={"jobA": 50.0})})
        server = TelemetryServer(port=0, bind_host="127.0.0.1",
                                 slo_fn=c.payload)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/slo", timeout=10
            ).read())
            assert body["specs"]["jobA"]["step_s"] == 0.01
            assert body["tenants"]["jobA"]["windows"]["step"] == 1
            assert [b["tenant"] for b in body["breaches"]] == ["jobA"]
            assert body["placement"] == {"jobA": 1, "jobB": 3}
            # one direct remediate() + one the tick's breach triggered
            assert [h["rung"] for h in body["remediations"]] == \
                ["preempt", "preempt"]
        finally:
            server.stop()

    def test_slo_endpoint_404_without_watchdog(self):
        server = TelemetryServer(port=0, bind_host="127.0.0.1")
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/slo", timeout=10
                )
            assert e.value.code == 404
        finally:
            server.stop()


# ------------------------------------------- worker-side enactment

class FakeKV:
    """Dict-backed stand-in for the rendezvous KV client (the scope/key
    get-put surface the consumer and the driver's actuators share)."""

    def __init__(self):
        self.data = {}

    def put(self, scope, key, blob):
        self.data[(scope, key)] = blob

    def get(self, scope, key, timeout_ms=0):
        return self.data.get((scope, key))


class TestWorkerSLOConsumer:
    def _put(self, kv, action, payload):
        kv.put("__slo__", action, json.dumps(payload).encode())

    def test_degrade_and_placement_enacted_once_and_acked(self):
        import os

        from horovod_tpu.runner import slo_consumer

        kv = FakeKV()
        placements = []
        consumer = slo_consumer.SLOActionConsumer(
            rank_fn=lambda: 2, on_placement=placements.append)
        saved = {k: os.environ.get(k) for k in
                 ("HVD_TPU_SVC_STALENESS", "HVD_TPU_SVC_TENANT_WEIGHTS")}
        try:
            os.environ.pop("HVD_TPU_SVC_STALENESS", None)
            self._put(kv, "degrade", {
                "seq": 1, "tenant": "jobA",
                "changes": {"HVD_TPU_SVC_STALENESS": "2"}})
            self._put(kv, "placement", {
                "seq": 2, "tenant": "jobA",
                "placement": {"jobA": 2, "jobB": 2}})
            assert consumer.poll(kv) == 2
            assert os.environ["HVD_TPU_SVC_STALENESS"] == "2"
            # slice counts became live DRR weights for the arbiter
            assert os.environ["HVD_TPU_SVC_TENANT_WEIGHTS"] == \
                "jobA:2,jobB:2"
            assert placements == [{"jobA": 2, "jobB": 2}]
            assert kv.get("__slo__", "ack_degrade_1_rank_2") == b"1"
            assert kv.get("__slo__", "ack_placement_2_rank_2") == b"1"
            assert metrics.get_counter("slo.worker.degrade") == 1
            # a heartbeat re-reading the same publication is a no-op
            assert consumer.poll(kv) == 0
            # the revert rides the same channel: null unsets the knob
            self._put(kv, "degrade", {
                "seq": 3, "tenant": "jobA", "revert": True,
                "changes": {"HVD_TPU_SVC_STALENESS": None}})
            assert consumer.poll(kv) == 1
            assert "HVD_TPU_SVC_STALENESS" not in os.environ
            assert kv.get("__slo__", "ack_degrade_3_rank_2") == b"1"
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def test_preempt_reaches_inprocess_arbiter(self, monkeypatch):
        from horovod_tpu.runner import slo_consumer
        from horovod_tpu.svc import service as service_mod

        preempted = []
        stub = SimpleNamespace(arbiter=SimpleNamespace(
            request_preempt=preempted.append))
        monkeypatch.setattr(service_mod, "get_service_or_none",
                            lambda: stub)
        kv = FakeKV()
        consumer = slo_consumer.SLOActionConsumer(rank_fn=lambda: 0)
        self._put(kv, "preempt", {"seq": 5, "tenant": "jobA"})
        assert consumer.poll(kv) == 1
        assert preempted == ["jobA"]
        assert kv.get("__slo__", "ack_preempt_5_rank_0") == b"1"

    def test_malformed_and_failing_actions_never_loop(self, monkeypatch):
        from horovod_tpu.runner import slo_consumer

        kv = FakeKV()
        consumer = slo_consumer.SLOActionConsumer(rank_fn=lambda: 0)
        kv.put("__slo__", "degrade", b"not json")
        assert consumer.poll(kv) == 0
        assert consumer.poll(kv) == 0  # malformed: consumed, not retried
        # an action that fails to apply is consumed but never acked
        monkeypatch.setattr(
            consumer, "_apply",
            lambda action, payload: (_ for _ in ()).throw(
                RuntimeError("boom")))
        self._put(kv, "placement", {"seq": 7, "placement": {"a": 1}})
        assert consumer.poll(kv) == 0
        assert kv.get("__slo__", "ack_placement_7_rank_0") is None
        assert consumer.poll(kv) == 0  # consumed despite the failure

    def test_weights_spec_drops_nonpositive(self):
        from horovod_tpu.runner import slo_consumer

        assert slo_consumer.weights_spec(
            {"b": 1, "a": 2, "gone": 0}) == "a:2,b:1"


# ------------------------------------------- two-tenant acceptance

class TestTwoTenantSelfHealing:
    """The PR's acceptance scenario, in process: two tenants under a
    fault plan; a load spike on jobA confirms a breach, the ladder
    walks to a measured slice handoff, both tenants' SLOs go green
    after, and zero worker processes were restarted (everything moved
    through reshard_shards in this very process)."""

    def test_load_spike_to_handoff_to_green(self, monkeypatch,
                                            event_log):
        monkeypatch.setenv("HVD_TPU_SLO_SPEC",
                           "jobA:step=0.02;jobB:step=10.0")
        helper = TestHandoffMovesRealState()
        store = helper._store()
        before = {t: helper._valid(st).copy()
                  for t, st in store.items()}
        r = Remediator(placement={"jobA": 1, "jobB": 3},
                       actuators=helper._actuators(store),
                       cooldown_s_=0.0, retry_attempts=1,
                       sleep=lambda s: None)
        c = slo.SLOController(
            slo.SLOWatchdog(slo.specs_from_env(), windows=2),
            remediator=r, check_interval_s_=0.0,
        )
        spike = {0: rank_snapshot(tenant_ms={"jobA": 60.0,
                                             "jobB": 1.0})}
        green = {0: rank_snapshot(tenant_ms={"jobA": 1.0,
                                             "jobB": 1.0})}
        # window 1: breaching but unconfirmed; 2..4: confirmed, the
        # ladder walks preempt -> degrade -> handoff.
        for i in range(4):
            c.maybe_tick(lambda: spike, now=float(i))
        rungs = [h["rung"] for h in r.history()]
        assert rungs == ["preempt", "degrade", "handoff"]
        assert all(h["outcome"] == "ok" for h in r.history())
        assert r.placement() == {"jobA": 2, "jobB": 2}
        # the spike resolved: both tenants green, recovery emitted
        status = c.maybe_tick(lambda: green, now=10.0)
        assert status["breaches"] == []
        assert _named(event_log, events.SLO_RECOVERED)
        # zero restarts: state moved bitwise inside this process
        for tenant in store:
            np.testing.assert_array_equal(
                helper._valid(store[tenant]), before[tenant]
            )

    def test_injected_handoff_fault_bitwise_rollback(self, monkeypatch,
                                                     event_log):
        monkeypatch.setenv("HVD_TPU_SLO_SPEC", "jobA:step=0.02")
        faults.set_plan("remediate.handoff:error:times=0")
        helper = TestHandoffMovesRealState()
        store = helper._store()
        before = {t: helper._valid(st).copy()
                  for t, st in store.items()}
        r = Remediator(placement={"jobA": 1, "jobB": 3},
                       actuators=helper._actuators(store),
                       cooldown_s_=0.0, retry_attempts=2,
                       retry_timeout_s=5.0, sleep=lambda s: None)
        rec = r.remediate(_breach("jobA"), "handoff")
        assert rec["outcome"] == "abort" and rec["stable"] is True
        assert r.placement() == {"jobA": 1, "jobB": 3}
        # training bitwise-continues on the pre-handoff placement
        for tenant in store:
            np.testing.assert_array_equal(
                helper._valid(store[tenant]), before[tenant]
            )


# ------------------------------------------- stall-abandon escalation

class TestStallAbandon:
    def _pending_sub(self, neg):
        from horovod_tpu import xir
        from horovod_tpu.runtime import WORLD_AXIS
        from horovod_tpu.svc.queue import (
            Submission,
            SvcFuture,
            TensorQueue,
        )

        q = TensorQueue()
        prog = xir.program("test", [
            xir.all_reduce(WORLD_AXIS, reduce="mean", bucket=0,
                           nbytes=16, dtype="float32"),
        ])
        sub = Submission(
            seq=q.next_seq(), producer="alive", program=prog,
            args=[], future=SvcFuture(),
            participants=("alive", "ghost"),
        )
        assert neg.post(sub) == []
        return sub

    def test_default_off_warns_forever(self, monkeypatch):
        from horovod_tpu.svc.negotiate import Negotiator

        monkeypatch.delenv("HVD_TPU_STALL_ABANDON", raising=False)
        neg = Negotiator()
        self._pending_sub(neg)
        for _ in range(5):
            reports = neg.check_stalls(timeout_s=0.0)
            assert reports and "abandoned" not in reports[0]
        assert neg.take_abandoned() == []
        assert neg.pending_count() == 1
        assert metrics.get_counter("svc.stall_abandoned") == 0

    def test_abandons_after_n_stalled_checks(self, monkeypatch,
                                             event_log):
        from horovod_tpu.svc.negotiate import Negotiator

        monkeypatch.setenv("HVD_TPU_STALL_ABANDON", "3")
        neg = Negotiator()
        sub = self._pending_sub(neg)
        assert "abandoned" not in neg.check_stalls(timeout_s=0.0)[0]
        assert "abandoned" not in neg.check_stalls(timeout_s=0.0)[0]
        report = neg.check_stalls(timeout_s=0.0)[0]
        assert report["abandoned"] is True
        assert report["checks"] == 3
        assert report["missing"] == ["ghost"]
        assert neg.pending_count() == 0
        assert neg.take_abandoned() == [sub]
        assert neg.take_abandoned() == []  # drained exactly once
        assert metrics.get_counter("svc.stall_abandoned") == 1
        assert metrics.get_gauge("svc.stalled_negotiations") == 0
        evs = _named(event_log, events.SVC_STALL_ABANDON)
        assert evs and evs[0]["missing"] == ["ghost"]

    def test_completion_resets_the_check_clock(self, monkeypatch):
        from horovod_tpu.svc.negotiate import Negotiator

        monkeypatch.setenv("HVD_TPU_STALL_ABANDON", "2")
        neg = Negotiator()
        sub = self._pending_sub(neg)
        neg.check_stalls(timeout_s=0.0)  # 1 stalled check
        # the ghost shows up after all: negotiation completes
        import dataclasses

        ghost = dataclasses.replace(
            sub, producer="ghost",
            future=type(sub.future)(),
        )
        assert len(neg.post(ghost)) == 2
        assert neg.take_abandoned() == []

    def test_service_resolves_abandoned_futures_inline(
            self, monkeypatch):
        from horovod_tpu.svc.negotiate import Negotiator

        monkeypatch.setenv("HVD_TPU_STALL_ABANDON", "1")
        neg = Negotiator()
        sub = self._pending_sub(neg)
        neg.check_stalls(timeout_s=0.0)
        # the abandon() drain path (service death before the loop's
        # take_abandoned ran) must still surface the orphans
        assert neg.abandon() == [sub]


# ---------------------------------------------- arbiter event entries

class TestArbiterAdmissionEvents:
    def test_admission_timeout_lands_in_event_log(self, event_log):
        from horovod_tpu.svc import arbiter as arbiter_mod

        arb = arbiter_mod.Arbiter()
        arbiter_mod.set_inflight_override(1)
        try:
            arb.admit("jobA")
            assert not arb.admit("jobA", timeout_s=0.2)
        finally:
            arbiter_mod.set_inflight_override(None)
        evs = _named(event_log, events.SVC_ADMIT_TIMEOUT)
        assert len(evs) == 1
        assert evs[0]["tenant"] == "jobA"
        assert evs[0]["waited_s"] >= 0.15
        assert evs[0]["cap"] == 1

    def test_preempt_expiry_lands_in_event_log(self, event_log):
        from horovod_tpu.svc import arbiter as arbiter_mod

        arb = arbiter_mod.Arbiter()
        arb.admit("hi")  # keep the high lane non-drained
        arb.request_preempt("hi", cycles=2)
        arb.on_cycle(1)  # inside the window: no event
        arb.on_cycle(5)  # past expiry
        evs = _named(event_log, events.SVC_PREEMPT_EXPIRED)
        assert len(evs) == 1
        assert evs[0]["tenant"] == "hi"
        assert evs[0]["reason"] == "expired"
        assert evs[0]["cycle"] == 5

    def test_preempt_drain_lands_in_event_log(self, event_log):
        from horovod_tpu.svc import arbiter as arbiter_mod

        arb = arbiter_mod.Arbiter()
        arb.request_preempt("hi", cycles=100)
        arb.on_cycle(1)  # hi's lane is empty: gate lifts as drained
        evs = _named(event_log, events.SVC_PREEMPT_EXPIRED)
        assert len(evs) == 1
        assert evs[0]["reason"] == "drained"
