"""Dtype sweep across the framework bindings (reference
``test/parallel/test_torch.py``/``test_tensorflow.py`` enumerate every
supported dtype per op; this sweeps the binding bridges — the eager
layer itself is swept in test_collective_matrix.py)."""

import numpy as np
import pytest

import horovod_tpu as hvd

torch = pytest.importorskip("torch")

import horovod_tpu.interop.torch as hvd_torch  # noqa: E402

N = 8

TORCH_DTYPES = [torch.float32, torch.float16, torch.bfloat16, torch.int32]


def _tol(dtype):
    if dtype in (torch.float16, torch.bfloat16):
        return dict(rtol=1e-2, atol=1e-2)
    return dict(rtol=1e-5, atol=1e-6)


class TestTorchDtypes:
    @pytest.fixture(autouse=True)
    def _seed(self):
        torch.manual_seed(0)

    @pytest.mark.parametrize("dtype", TORCH_DTYPES, ids=str)
    def test_allreduce_sum(self, hvd_module, dtype):
        if dtype.is_floating_point:
            t = torch.rand(N, 5).to(dtype)
        else:
            t = torch.randint(0, 7, (N, 5), dtype=dtype)
        out = hvd_torch.allreduce(t, op=hvd.Sum)
        assert out.dtype == dtype
        expect = t.to(torch.float64).sum(0)
        for r in range(N):
            np.testing.assert_allclose(
                out[r].to(torch.float64).numpy(), expect.numpy(),
                **_tol(dtype),
            )

    @pytest.mark.parametrize("dtype", TORCH_DTYPES, ids=str)
    def test_broadcast(self, hvd_module, dtype):
        if dtype.is_floating_point:
            t = torch.arange(N, dtype=torch.float32).reshape(N, 1).to(dtype)
        else:
            t = torch.arange(N, dtype=dtype).reshape(N, 1)
        out = hvd_torch.broadcast(t, root_rank=3)
        assert out.dtype == dtype
        np.testing.assert_allclose(out.to(torch.float64).numpy(), 3.0)

    @pytest.mark.parametrize("dtype",
                             [torch.float32, torch.bfloat16], ids=str)
    def test_allgather(self, hvd_module, dtype):
        t = torch.ones(N, 2, 3).to(dtype)
        out = hvd_torch.allgather(t)
        assert out.dtype == dtype
        assert out.shape == (N, N * 2, 3)

    def test_grouped_mixed_dtypes(self, hvd_module):
        ts = [torch.ones(N, 2), torch.ones(N, 3, dtype=torch.bfloat16)]
        outs = hvd_torch.grouped_allreduce(ts, op=hvd.Average)
        assert outs[0].dtype == torch.float32
        assert outs[1].dtype == torch.bfloat16
        np.testing.assert_allclose(outs[0].numpy(), 1.0)


class TestTFDtypes:
    @pytest.fixture(autouse=True)
    def _tf(self):
        self.tf = pytest.importorskip("tensorflow")
        import horovod_tpu.interop.tf as hvd_tf

        self.hvd_tf = hvd_tf

    @pytest.mark.parametrize("np_dtype",
                             [np.float32, np.float16, np.int32], ids=str)
    def test_allreduce_sum(self, hvd_module, np_dtype):
        tf = self.tf
        if np.issubdtype(np_dtype, np.floating):
            x = tf.constant(
                np.random.RandomState(0).rand(N, 4).astype(np_dtype)
            )
        else:
            x = tf.constant(
                np.random.RandomState(0).randint(0, 7, (N, 4)), np_dtype
            )
        y = self.hvd_tf.allreduce(x, op=hvd.Sum)
        assert y.dtype == x.dtype
        expect = np.asarray(x).astype(np.float64).sum(0)
        tol = 1e-2 if np_dtype == np.float16 else 1e-5
        for r in range(N):
            np.testing.assert_allclose(
                y.numpy()[r].astype(np.float64), expect, rtol=tol, atol=tol
            )


class TestMXNetDtypes:
    @pytest.mark.parametrize("np_dtype", [np.float32, np.int32], ids=str)
    def test_allreduce_sum(self, hvd_module, monkeypatch, np_dtype):
        from test_interop_mxnet import FakeNDArray, _install_fake_mxnet

        _install_fake_mxnet(monkeypatch)
        import horovod_tpu.interop.mxnet as hvd_mx

        if np.issubdtype(np_dtype, np.floating):
            rows = np.random.RandomState(0).rand(N, 3).astype(np_dtype)
        else:
            rows = np.random.RandomState(0).randint(0, 7, (N, 3)).astype(
                np_dtype
            )
        out = hvd_mx.allreduce(FakeNDArray(rows), average=False)
        assert out.dtype == np_dtype
        expect = rows.astype(np.float64).sum(0)
        for r in range(N):
            np.testing.assert_allclose(
                out.asnumpy()[r].astype(np.float64), expect, rtol=1e-5
            )
