"""Data loaders + elastic sampler (reference
``horovod/data/data_loader_base.py`` and torch ElasticSampler tests)."""

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.data import (
    ArrayDataLoader,
    AsyncArrayDataLoader,
    ElasticSampler,
)


def _arrays(n=64, d=4):
    rng = np.random.RandomState(0)
    return [rng.randn(n, d).astype(np.float32), rng.randint(0, 3, size=n)]


def test_array_loader_batches(hvd_module):
    x, y = _arrays()
    loader = ArrayDataLoader([x, y], batch_size=8, shuffle=False)
    batches = list(loader)
    assert len(batches) == len(loader)
    xb, yb = batches[0]
    assert xb.shape == (8, 4) and yb.shape == (8,)
    # full epoch covers the shard exactly once
    seen = np.concatenate([b[1] for b in batches])
    assert len(seen) == len(loader) * 8


def test_array_loader_epoch_shuffle(hvd_module):
    x, y = _arrays()
    loader = ArrayDataLoader([x, y], batch_size=8, shuffle=True, seed=3)
    loader.set_epoch(0)
    e0 = np.concatenate([b[1] for b in loader])
    loader.set_epoch(1)
    e1 = np.concatenate([b[1] for b in loader])
    assert not np.array_equal(e0, e1)
    loader.set_epoch(0)
    again = np.concatenate([b[1] for b in loader])
    np.testing.assert_array_equal(e0, again)


def test_async_loader_matches_sync(hvd_module):
    x, y = _arrays()
    sync = ArrayDataLoader([x, y], batch_size=8, shuffle=False)
    async_ = AsyncArrayDataLoader([x, y], batch_size=8, shuffle=False)
    sb = [b[1] for b in sync]
    ab = [b[1] for b in async_]
    assert len(sb) == len(ab)
    for s, a in zip(sb, ab):
        np.testing.assert_array_equal(s, a)
    async_.close_async_loader()


def test_async_loader_close_midway(hvd_module):
    x, y = _arrays(n=128)
    loader = AsyncArrayDataLoader([x, y], batch_size=4, queue_size=2)
    it = iter(loader)
    next(it)
    loader.close_async_loader()  # must not hang


def test_async_loader_propagates_errors(hvd_module):
    from horovod_tpu.data import AsyncDataLoaderMixin

    x, y = _arrays(n=8)

    class Bad(ArrayDataLoader):
        def _iterate(self):
            yield (x[:2], y[:2])
            raise RuntimeError("boom")

    class AsyncBad(AsyncDataLoaderMixin, Bad):
        pass

    loader = AsyncBad([x, y], batch_size=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(loader)


# ---- ElasticSampler ----------------------------------------------------

def test_elastic_sampler_full_coverage():
    s = ElasticSampler(dataset_size=20, shuffle=False, rank=0, num_replicas=2)
    s2 = ElasticSampler(dataset_size=20, shuffle=False, rank=1, num_replicas=2)
    assert sorted(list(s) + list(s2)) == list(range(20))
    assert len(s) == 10


def test_elastic_sampler_resume_skips_processed():
    s = ElasticSampler(dataset_size=16, shuffle=False, rank=0, num_replicas=2)
    first_two_batches = s.indices[:4]
    s.record_batch(0, 2)
    s.record_batch(1, 2)
    state = s.state_dict()

    s2 = ElasticSampler(dataset_size=16, shuffle=False, rank=0, num_replicas=2)
    s2.load_state_dict(state)
    remaining = set(s2) | set(
        ElasticSampler(dataset_size=16, shuffle=False, rank=1, num_replicas=2)
        .indices
    )
    for idx in first_two_batches:
        assert idx not in set(s2.indices)


def test_elastic_sampler_reshard_on_world_change():
    s = ElasticSampler(dataset_size=24, shuffle=True, seed=7, rank=0,
                       num_replicas=3)
    s.record_batch(0, 4)
    processed = set(s.processed_indices)
    # world shrinks 3 -> 2; remaining work redistributed
    s.reset(rank=0, num_replicas=2)
    other = ElasticSampler(dataset_size=24, shuffle=True, seed=7, rank=1,
                           num_replicas=2)
    other.load_state_dict({"epoch": 0,
                           "processed_indices": list(processed)})
    combined = set(s.indices) | set(other.indices)
    assert combined.isdisjoint(processed)
    # everything unprocessed is covered
    assert combined == set(range(24)) - processed


def test_elastic_sampler_pads_when_fewer_remaining_than_replicas():
    # 1 unprocessed index, 4 replicas: every rank must still get exactly
    # num_samples indices or collective step counts desynchronize.
    s0 = ElasticSampler(dataset_size=5, shuffle=False, rank=0, num_replicas=4)
    s0.load_state_dict({"epoch": 0, "processed_indices": [0, 1, 2, 3]})
    for r in range(4):
        s = ElasticSampler(dataset_size=5, shuffle=False, rank=r,
                           num_replicas=4)
        s.load_state_dict({"epoch": 0, "processed_indices": [0, 1, 2, 3]})
        assert list(s) == [4], (r, list(s))


def test_elastic_sampler_epoch_reset():
    s = ElasticSampler(dataset_size=10, shuffle=True, rank=0, num_replicas=1)
    s.record_batch(0, 5)
    assert len(s.processed_indices) == 5
    s.set_epoch(1)
    assert s.processed_indices == []
    assert len(s) == 10


class TestParquetStreamLoader:
    """Row-group streaming reader (petastorm data-loader analog,
    VERDICT r3 item 9): epochs stream bounded windows, never a shard."""

    @staticmethod
    def _write_parts(tmp_path, n_parts=3, rows=50, fmt="parquet"):
        from horovod_tpu.spark.store import write_shard

        rng = np.random.RandomState(0)
        paths, allx, ally = [], [], []
        for p in range(n_parts):
            x = rng.randn(rows, 4).astype(np.float32)
            y = rng.randn(rows).astype(np.float32)
            paths.append(write_shard(
                str(tmp_path / f"part-{p:05d}"),
                {"features": x, "label": y}, fmt=fmt,
            ))
            allx.append(x)
            ally.append(y)
        return paths, np.concatenate(allx), np.concatenate(ally)

    @pytest.mark.parametrize("fmt", ["parquet", "npz"])
    def test_streams_all_rows_exactly_once(self, tmp_path, fmt):
        from horovod_tpu.data import ParquetStreamLoader

        paths, X, Y = self._write_parts(tmp_path, fmt=fmt)
        loader = ParquetStreamLoader(
            paths, ["features", "label"], batch_size=16,
            shuffle=False, window_rows=16,  # window << shard
        )
        assert len(loader) == 150 // 16
        got_x, got_y = [], []
        for xb, yb in loader:
            assert xb.shape == (16, 4) and yb.shape == (16,)
            got_x.append(xb)
            got_y.append(yb)
        got_x = np.concatenate(got_x)
        # unshuffled stream preserves order; drop_last trims the tail
        np.testing.assert_allclose(got_x, X[: len(got_x)])
        np.testing.assert_allclose(np.concatenate(got_y), Y[: len(got_x)])

    def test_carry_across_windows_and_parts(self, tmp_path):
        """batch_size not dividing the window exercises the carry
        buffer across window AND part boundaries."""
        from horovod_tpu.data import ParquetStreamLoader

        paths, X, _ = self._write_parts(tmp_path, n_parts=2, rows=50)
        loader = ParquetStreamLoader(
            paths, ["features", "label"], batch_size=24,
            shuffle=False, window_rows=25,
        )
        batches = [xb for xb, _ in loader]
        assert len(batches) == len(loader) == 100 // 24
        np.testing.assert_allclose(np.concatenate(batches), X[:96])

    def test_shuffle_is_seeded_and_epoch_varying(self, tmp_path):
        from horovod_tpu.data import ParquetStreamLoader

        paths, X, _ = self._write_parts(tmp_path)

        def epoch_rows(epoch):
            # batch divides 150 exactly: no dropped tail, so each epoch
            # emits the same multiset and the permutation check holds
            loader = ParquetStreamLoader(
                paths, ["features", "label"], batch_size=15, seed=7,
                window_rows=32,
            )
            loader.set_epoch(epoch)
            return np.concatenate([xb for xb, _ in loader])

        a0, b0, a1 = epoch_rows(0), epoch_rows(0), epoch_rows(1)
        np.testing.assert_allclose(a0, b0)  # same epoch -> same stream
        assert not np.allclose(a0, a1)      # epochs reshuffle
        # windowed shuffle is still a permutation of the data it emits
        key = lambda m: sorted(map(tuple, np.round(m, 5)))
        assert key(a0) == key(a1)

    def test_async_wrapper_matches_sync(self, tmp_path):
        from horovod_tpu.data import (
            AsyncParquetStreamLoader,
            ParquetStreamLoader,
        )

        paths, _, _ = self._write_parts(tmp_path, n_parts=1)
        kw = dict(columns=["features", "label"], batch_size=10,
                  shuffle=False, window_rows=16)
        sync = ParquetStreamLoader(paths, **kw)
        asyn = AsyncParquetStreamLoader(paths, **kw)
        try:
            for (xs, _), (xa, _) in zip(sync, asyn):
                np.testing.assert_allclose(xs, xa)
        finally:
            asyn.close_async_loader()
