"""Bucketed overlap scheduler (sched/): plan determinism,
reverse-backward order, exchange-mode equivalence, bucketed ZeRO-1,
per-bucket compression, and registry-fed bucket-size tuning."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import metrics, sched
from horovod_tpu.ops import fusion
from horovod_tpu.sched import SchedConfig, build_schedule, hooks

pytestmark = pytest.mark.sched

F32 = 4  # bytes


def fresh(tree):
    return jax.tree.map(lambda a: jnp.array(a), tree)


@pytest.fixture(autouse=True)
def _clean_sched_state():
    hooks.reset()
    sched.set_config_override(None)
    yield
    hooks.reset()
    sched.set_config_override(None)


# ---------------------------------------------------------------- plan

def test_plan_deterministic():
    sizes = [100 * F32] * 6
    dtypes = ["float32"] * 6
    cfg = SchedConfig(bucket_bytes=200 * F32)
    a = build_schedule(sizes, dtypes, cfg)
    b = build_schedule(sizes, dtypes, cfg)
    assert a.signature() == b.signature()
    # config changes the plan identity
    c = build_schedule(sizes, dtypes, SchedConfig(bucket_bytes=300 * F32))
    assert a.signature() != c.signature()


def test_plan_reverse_backward_order():
    """Default order: last-registered leaves exchange first (their
    gradients finish the backward first)."""
    sizes = [100 * F32] * 6
    dtypes = ["float32"] * 6
    s = build_schedule(sizes, dtypes, SchedConfig(bucket_bytes=200 * F32))
    assert [b.indices for b in s.buckets] == [(4, 5), (2, 3), (0, 1)]
    assert s.total_bytes == 600 * F32


def test_plan_observed_order_overrides_reversed_default():
    sizes = [10 * F32] * 4
    dtypes = ["float32"] * 4
    s = build_schedule(
        sizes, dtypes, SchedConfig(bucket_bytes=20 * F32),
        order=[1, 0, 3, 2],
    )
    assert [b.indices for b in s.buckets] == [(0, 1), (2, 3)]


def test_plan_pinned_groups_fuse_atomically():
    sizes = [10 * F32] * 5
    dtypes = ["float32"] * 5
    s = build_schedule(
        sizes, dtypes, SchedConfig(bucket_bytes=10 * F32), pinned=[[0, 3]],
    )
    pinned = [b for b in s.buckets if b.pinned]
    assert len(pinned) == 1 and pinned[0].indices == (0, 3)
    # every leaf exchanged exactly once
    all_idx = sorted(i for b in s.buckets for i in b.indices)
    assert all_idx == [0, 1, 2, 3, 4]


def test_plan_incomplete_order_falls_back():
    sizes = [10 * F32] * 3
    dtypes = ["float32"] * 3
    s = build_schedule(
        sizes, dtypes, SchedConfig(bucket_bytes=10 * F32), order=[2, 2, 0],
    )
    assert [b.indices for b in s.buckets] == [(2,), (1,), (0,)]


# ------------------------------------------------- fusion look-ahead

def test_bucket_plan_look_ahead_closes_stale_bucket():
    """A same-dtype tensor arriving more than look_ahead positions after
    a different-dtype bucket opened must NOT rejoin the old bucket
    (it would break reverse-backward exchange ordering)."""
    sizes = [10, 10, 10, 10, 10, 10]
    dtypes = ["float32", "bfloat16", "bfloat16", "bfloat16", "bfloat16",
              "float32"]
    got = fusion.bucket_plan(sizes, dtypes, 1000, look_ahead=3)
    # bf16 bucket opened at position 1; f32 tensor 5 is 4 > 3 positions
    # past it -> the f32 bucket from position 0 is closed.
    assert got == [[0], [1, 2, 3, 4], [5]]
    # legacy unbounded look-ahead keeps the stale join
    legacy = fusion.bucket_plan(sizes, dtypes, 1000, look_ahead=-1)
    assert legacy == [[0, 5], [1, 2, 3, 4]]


def test_bucket_plan_look_ahead_allows_short_interleave():
    sizes = [10, 10, 10, 10]
    dtypes = ["float32", "bfloat16", "float32", "bfloat16"]
    got = fusion.bucket_plan(sizes, dtypes, 1000, look_ahead=3)
    assert got == [[0, 2], [1, 3]]


# ------------------------------------------------------------- hooks

def test_backward_order_capture():
    params = {"a": jnp.ones((4, 4)), "b": jnp.ones((4, 4)),
              "c": jnp.ones((4, 4))}

    def loss(p, x):
        return jnp.sum(x @ p["a"] @ p["b"] @ p["c"])

    jax.grad(hooks.capturing_loss(loss))(params, jnp.ones((2, 4)))
    order = hooks.consume_order(3)
    # c's cotangent materializes first (it is the last matmul applied)
    assert order == [2, 1, 0]


def test_consume_order_rejects_mismatched_leaf_count():
    params = {"a": jnp.ones(3)}
    jax.grad(hooks.capturing_loss(lambda p, x: jnp.sum(p["a"] * x)))(
        params, jnp.ones(3)
    )
    assert hooks.consume_order(7) is None
    assert hooks.consume_order(1) is None  # consumed above, cleared


# ------------------------------------------------- exchange equivalence

def _problem():
    X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    Y = (X @ np.full((4, 2), 0.7)).astype(np.float32)

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

    params = {
        "w1": jnp.full((4, 4), 0.2),
        "w2": jnp.full((4, 2), 0.5),
        "b": jnp.zeros((2,)),
    }
    return params, (jnp.asarray(X), jnp.asarray(Y)), loss_fn


def _run_steps(loss_fn, params, batch, cfg, n=3, **opt_kwargs):
    sched.set_config_override(cfg)
    try:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), **opt_kwargs)
        step = hvd.distributed_train_step(loss_fn, tx)
        st = step.init(params)
        p = fresh(params)
        losses = []
        for _ in range(n):
            p, st, loss = step(p, st, batch)
            losses.append(float(loss))
        return p, losses
    finally:
        sched.set_config_override(None)


def test_sched_on_off_losses_identical_f32(hvd_module):
    """The scheduler engine is numerics-identical (f32, rtol=0) to the
    legacy single-fused-exchange path."""
    params, batch, loss_fn = _problem()
    # tiny buckets: the three grads exchange as separate buckets
    on = SchedConfig(enabled=True, bucket_bytes=64)
    off = SchedConfig(enabled=False)
    p_on, l_on = _run_steps(loss_fn, params, batch, on)
    p_off, l_off = _run_steps(loss_fn, params, batch, off)
    assert l_on == l_off  # bitwise: same floats through repr round-trip
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(p_on[k]), np.asarray(p_off[k])
        )
    assert metrics.get_gauge("sched.buckets_per_step") >= 2


def test_sched_no_barriers_identical(hvd_module):
    params, batch, loss_fn = _problem()
    a = _run_steps(loss_fn, params, batch,
                   SchedConfig(bucket_bytes=64, barriers=False))
    b = _run_steps(loss_fn, params, batch, SchedConfig(enabled=False))
    assert a[1] == b[1]


def test_reduce_scatter_mode_matches_allreduce(hvd_module):
    params, batch, loss_fn = _problem()
    p_ar, l_ar = _run_steps(
        loss_fn, params, batch, SchedConfig(mode="allreduce"))
    p_rs, l_rs = _run_steps(
        loss_fn, params, batch, SchedConfig(mode="reduce_scatter"))
    np.testing.assert_allclose(l_ar, l_rs, rtol=1e-6)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p_ar[k]), np.asarray(p_rs[k]),
            rtol=1e-6, atol=1e-7,
        )


def test_sched_with_gradient_accumulation(hvd_module):
    """backward_passes_per_step defers the exchange to the boundary
    microbatch; the scheduler engine must keep the k-step union-batch
    equivalence."""
    params, batch, loss_fn = _problem()
    X, Y = batch
    cfg = SchedConfig(bucket_bytes=64)
    sched.set_config_override(cfg)
    try:
        tx2 = hvd.DistributedOptimizer(
            optax.sgd(0.1), backward_passes_per_step=2)
        s2 = hvd.distributed_train_step(loss_fn, tx2)
        st2 = s2.init(params)
        p2 = fresh(params)
        p2, st2, _ = s2(p2, st2, (X[:8], Y[:8]))
        p2, st2, _ = s2(p2, st2, (X[8:], Y[8:]))

        tx1 = hvd.DistributedOptimizer(optax.sgd(0.1))
        s1 = hvd.distributed_train_step(loss_fn, tx1)
        p1 = fresh(params)
        st1 = s1.init(p1)
        p1, st1, _ = s1(p1, st1, (X, Y))
    finally:
        sched.set_config_override(None)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p2[k]), np.asarray(p1[k]), rtol=1e-5
        )


def test_explicit_groups_ride_as_pinned_buckets(hvd_module):
    params, batch, loss_fn = _problem()
    a = _run_steps(loss_fn, params, batch, SchedConfig(bucket_bytes=64),
                   groups=[[0, 2]])
    b = _run_steps(loss_fn, params, batch, SchedConfig(enabled=False),
                   groups=[[0, 2]])
    assert a[1] == b[1]


# ------------------------------------------------ per-bucket compression

def test_compression_round_trip_per_bucket(hvd_module):
    """bf16 wire: the plan carries the bucket's wire dtype, the
    exchange casts per leaf, and the decompressed output restores f32
    — identical between scheduler and legacy engines."""
    params, batch, loss_fn = _problem()
    on = SchedConfig(bucket_bytes=64)
    p_on, l_on = _run_steps(loss_fn, params, batch, on,
                            compression=hvd.Compression.bf16)
    p_off, l_off = _run_steps(loss_fn, params, batch,
                              SchedConfig(enabled=False),
                              compression=hvd.Compression.bf16)
    assert l_on == l_off
    for k in params:
        assert p_on[k].dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(p_on[k]), np.asarray(p_off[k])
        )
    # and close to the uncompressed trajectory
    p_fp, _ = _run_steps(loss_fn, params, batch, on)
    np.testing.assert_allclose(
        np.asarray(p_on["w2"]), np.asarray(p_fp["w2"]),
        rtol=2e-2, atol=2e-2,
    )


def test_schedule_wire_dtype_recorded():
    s = build_schedule(
        [100, 100], ["bfloat16", "bfloat16"], SchedConfig()
    )
    assert s.buckets[0].wire_dtypes == ("bfloat16",)


# ------------------------------------------------------ bucketed ZeRO-1

def test_bucketed_zero_matches_unsharded_adam(hvd_module):
    params, batch, loss_fn = _problem()
    cfg = SchedConfig(bucket_bytes=32)  # forces several buckets
    step = sched.bucketed_zero_step(loss_fn, optax.adam(1e-2), cfg=cfg)
    st = step.init(params)
    assert len(step.schedule) >= 2
    p = fresh(params)
    for _ in range(5):
        p, st, loss = step(p, st, batch)

    ref_tx = optax.adam(1e-2)
    rp = fresh(params)
    rst = ref_tx.init(rp)
    for _ in range(5):
        g = jax.grad(loss_fn)(rp, batch)
        u, rst = ref_tx.update(g, rst, rp)
        rp = optax.apply_updates(rp, u)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p[k]), np.asarray(rp[k]), rtol=1e-5, atol=1e-5
        )


def test_bucketed_zero_state_shapes_reduced(hvd_module):
    """Optimizer state shrinks N-fold: the per-bucket adam moments sum
    to ~n_params total elements (each rank holds 1/N), not N copies."""
    params, batch, loss_fn = _problem()
    n_params = sum(x.size for x in jax.tree.leaves(params))
    world = hvd.size()
    step = sched.bucketed_zero_step(
        loss_fn, optax.adam(1e-2), cfg=SchedConfig(bucket_bytes=32))
    st = step.init(params)
    total_mu = sum(int(s[0].mu.size) for s in st)
    # padded per bucket: at most world-1 pad elements each
    assert n_params <= total_mu <= n_params + len(st) * world
    for s in st:
        mu = s[0].mu
        assert len(mu.sharding.device_set) == world
        assert {sh.data.shape for sh in mu.addressable_shards} == {
            (mu.shape[0] // world,)
        }


def test_bucketed_zero_with_global_norm_clip(hvd_module):
    from horovod_tpu.optim.zero import clip_by_global_norm

    params, (X, Y), loss_fn = _problem()
    batch = (X, Y * 100.0)  # big grads so the clip engages
    step = sched.bucketed_zero_step(
        loss_fn, optax.sgd(0.01), cfg=SchedConfig(bucket_bytes=32),
        pre_update=clip_by_global_norm(1.0),
    )
    st = step.init(params)
    p, st, loss = step(fresh(params), st, batch)

    ref_tx = optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(0.01))
    rp = fresh(params)
    rst = ref_tx.init(rp)
    g = jax.grad(loss_fn)(rp, batch)
    u, rst = ref_tx.update(g, rst, rp)
    rp = optax.apply_updates(rp, u)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p[k]), np.asarray(rp[k]), rtol=1e-5, atol=1e-6
        )


# -------------------------------------------------------------- tuning

def test_tuner_scores_windows_from_registry():
    metrics.reset_counters("train.")
    metrics.reset_counters("sched.")
    tuner = sched.ScheduleTuner(warmup_windows=2)
    tuner.begin_window()
    metrics.inc_counter("train.steps", 10)
    metrics.observe("train.step_seconds", 0.5)
    metrics.set_gauge("sched.bytes_per_step", 1000.0)
    score = tuner.end_window()
    # 10 steps / 0.5 s * 1000 bytes/step = 20 kB/s
    assert score == pytest.approx(20_000.0)
    assert metrics.get_counter("sched.tune_windows") == 1

    tuner.begin_window()
    metrics.inc_counter("train.steps", 10)
    metrics.observe("train.step_seconds", 1.0)
    tuner.end_window()
    assert tuner.converged
    assert tuner.bucket_bytes() >= 1


def test_tuner_idle_window_not_observed():
    metrics.reset_counters("train.")
    tuner = sched.ScheduleTuner(warmup_windows=2)
    tuner.begin_window()
    assert tuner.end_window() == 0.0  # no steps ran
    assert not tuner.converged


def test_window_score_falls_back_to_steps_per_sec():
    from horovod_tpu.sched.tune import window_score

    before = {"steps": 0, "step_seconds_sum": 0.0, "bytes_per_step": 0.0,
              "mono": 0.0}
    after = {"steps": 4, "step_seconds_sum": 2.0, "bytes_per_step": 0.0,
             "mono": 9.0}
    assert window_score(before, after) == pytest.approx(2.0)


# ------------------------------------------------------- observability

def test_exchange_metrics_and_gauges(hvd_module):
    metrics.reset_counters("sched.")
    params, batch, loss_fn = _problem()
    _run_steps(loss_fn, params, batch, SchedConfig(bucket_bytes=64), n=2)
    assert metrics.get_counter("sched.plans") >= 1
    assert metrics.get_gauge("sched.buckets_per_step") >= 2
    assert metrics.get_gauge("sched.bytes_per_step") > 0
    hist = metrics.get_histogram("sched.bytes_per_bucket")
    assert hist is not None and hist["count"] >= 2
    assert metrics.get_histogram("sched.exchange_seconds") is not None


def test_sched_config_from_env(monkeypatch):
    monkeypatch.setenv("HVD_TPU_SCHED", "off")
    monkeypatch.setenv("HVD_TPU_SCHED_MODE", "reduce_scatter")
    monkeypatch.setenv("HVD_TPU_SCHED_BUCKET_BYTES", "4096")
    monkeypatch.setenv("HVD_TPU_SCHED_LOOK_AHEAD", "7")
    cfg = SchedConfig.from_env()
    assert not cfg.enabled
    assert cfg.mode == "reduce_scatter"
    assert cfg.bucket_bytes == 4096
    assert cfg.look_ahead == 7
    monkeypatch.setenv("HVD_TPU_SCHED", "on")
    assert SchedConfig.from_env().enabled
