"""Multi-backend lowering-plane tests (backend/ registry + gpu topo).

The registry column of the matrix: family resolution and its env
aliases, the gpu NVLink/IB discovery feeding the shared Topology cost
model, rail relabeling through the payload surfaces, the gpu peak
table, the family-dependent quantized-wire default, and tune-DB
fingerprint keying by RESOLVED family (unset ≡ tpu shares pre-PR-20
entries; gpu keys apart).  The collective-parity half of the column
lives in tests/test_collective_matrix.py::TestBackendColumn;
tools/tier1_backend_smoke.sh drives the same marker end-to-end.
"""

import numpy as np
import pytest

from horovod_tpu import metrics, topo
from horovod_tpu.backend import gpu_topo, registry
from horovod_tpu.exceptions import HorovodTpuError

pytestmark = pytest.mark.backend


@pytest.fixture(autouse=True)
def _reset_backend(monkeypatch):
    """Every test starts and ends on the unforced (auto → tpu-on-CPU)
    family with fresh platform and topology caches."""
    monkeypatch.delenv("HVD_TPU_BACKEND", raising=False)
    monkeypatch.delenv("HOROVOD_BACKEND", raising=False)
    registry.reset()
    topo.reset()
    yield
    registry.reset()
    topo.reset()


def _force(monkeypatch, fam):
    monkeypatch.setenv("HVD_TPU_BACKEND", fam)
    registry.reset()
    topo.reset()


class TestFamilyResolution:
    def test_auto_on_cpu_resolves_tpu(self):
        assert registry.family() == "tpu"
        assert registry.get().name == "tpu"
        assert registry.kernel_module_name("quant_ring") == "pallas_quant"

    def test_env_override_gpu(self, monkeypatch):
        _force(monkeypatch, "gpu")
        assert registry.family() == "gpu"
        assert registry.get().name == "gpu"
        assert registry.kernel_module_name("quant_ring") == "mosaic_quant"

    @pytest.mark.parametrize("raw,fam", [
        ("tpu", "tpu"), ("axon", "tpu"), ("TPU", "tpu"),
        ("gpu", "gpu"), ("cuda", "gpu"), ("rocm", "gpu"),
        ("nvidia", "gpu"), (" Gpu ", "gpu"),
    ])
    def test_aliases(self, monkeypatch, raw, fam):
        _force(monkeypatch, raw)
        assert registry.family() == fam

    def test_legacy_horovod_spelling(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_BACKEND", "gpu")
        registry.reset()
        assert registry.family() == "gpu"

    def test_unknown_spelling_raises(self, monkeypatch):
        _force(monkeypatch, "trainium")
        with pytest.raises(HorovodTpuError):
            registry.family()

    def test_unknown_op_class_has_no_kernel(self):
        assert registry.kernel_module_name("no_such_op") is None


class TestRailNaming:
    def test_tpu_labels_are_identity(self):
        assert registry.rail_labels() == {"ici": "ici", "dcn": "dcn"}
        assert topo.rail_labels() == {"ici": "ici", "dcn": "dcn"}

    def test_gpu_labels(self, monkeypatch):
        _force(monkeypatch, "gpu")
        assert registry.rail_labels() == {"ici": "nvlink", "dcn": "ib"}
        assert topo.rail_label("ici") == "nvlink"
        assert topo.rail_label("dcn") == "ib"

    @pytest.mark.parametrize("tag,canon", [
        ("ici", "ici"), ("NVLink", "ici"), ("nvswitch", "ici"),
        ("dcn", "dcn"), ("IB", "dcn"), ("infiniband", "dcn"),
        ("roce", "dcn"),
    ])
    def test_canon_rail(self, tag, canon):
        assert topo.canon_rail(tag) == canon

    def test_unknown_rail_tag_never_raises(self):
        # pass-through lowercased, both in canon and in labeling
        assert topo.canon_rail("MysteryRail") == "mysteryrail"
        assert topo.rail_label("mysteryrail") == "mysteryrail"
        assert registry.get().rail_label("mysteryrail") == "mysteryrail"

    def test_tenants_payload_aliases(self, monkeypatch):
        from horovod_tpu.svc import arbiter

        _force(monkeypatch, "gpu")
        snap = {"gauges": [
            {"name": "svc.tenant.ici_bytes", "value": 100.0,
             "labels": {"tenant": "t0"}},
            {"name": "svc.tenant.rail_seconds", "value": 2.5,
             "labels": {"tenant": "t0", "rail": "ici"}},
            {"name": "svc.tenant.rail_seconds", "value": 0.5,
             "labels": {"tenant": "t0", "rail": "weird_rail"}},
        ]}
        payload = arbiter.tenants_payload({0: snap})
        assert payload["rail_labels"] == {"ici": "nvlink", "dcn": "ib"}
        t0 = payload["tenants"]["t0"]
        assert t0["ici_bytes"] == 100.0
        assert t0["nvlink_bytes"] == 100.0  # display alias mirrors
        rank0 = payload["ranks"]["0"]["t0"]
        assert rank0["rail_seconds_ici"] == 2.5
        assert rank0["rail_seconds_nvlink"] == 2.5
        # unknown rail tag lands under its own (lowercased) key
        assert rank0["rail_seconds_weird_rail"] == 0.5

    def test_prof_payload_rails(self, monkeypatch):
        import horovod_tpu.prof as prof

        _force(monkeypatch, "gpu")
        metrics.set_gauge("topo.rail_busy_frac", 0.25, {"rail": "ici"})
        try:
            view = prof._rails_view()
            assert view["labels"] == {"ici": "nvlink", "dcn": "ib"}
            assert view["busy_frac"]["ici"] == 0.25
            assert view["busy_frac"]["nvlink"] == 0.25
            assert "rails" in prof.prof_payload()
        finally:
            metrics.set_gauge("topo.rail_busy_frac", 0.0, {"rail": "ici"})


class TestGpuTopoDiscovery:
    class _Dev:
        def __init__(self, pid):
            self.process_index = pid

    def test_nvlink_domains_become_slices(self, monkeypatch):
        _force(monkeypatch, "gpu")
        devs = [self._Dev(p) for p in (0, 0, 0, 0, 1, 1, 1, 1)]
        t = gpu_topo.discover(devs)
        assert (t.num_slices, t.slice_size) == (2, 4)
        assert t.source == "gpu"
        # NVLink ≈ ICI is priced faster than IB ≈ DCN
        assert t.ici_gbps > t.dcn_gbps

    def test_ragged_domains_degenerate_flat(self, monkeypatch):
        _force(monkeypatch, "gpu")
        devs = [self._Dev(p) for p in (0, 0, 0, 1, 1)]
        t = gpu_topo.discover(devs)
        assert (t.num_slices, t.slice_size) == (1, 5)

    def test_family_routes_current(self, monkeypatch):
        _force(monkeypatch, "gpu")
        t = topo.current()
        assert t.source == "gpu"
        assert t.num_slices * t.slice_size == 8

    def test_spec_override_wins_over_family(self, monkeypatch):
        _force(monkeypatch, "gpu")
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        t = topo.current()
        assert t.source == "env"
        assert (t.num_slices, t.slice_size) == (2, 4)

    def test_cache_keyed_by_family(self, monkeypatch):
        t_tpu = topo.current()
        _force(monkeypatch, "gpu")
        t_gpu = topo.current()
        assert t_tpu.source != t_gpu.source  # no stale cross-family hit

    def test_link_param_env_overrides(self, monkeypatch):
        _force(monkeypatch, "gpu")
        monkeypatch.setenv("HVD_TPU_TOPO_ICI_GBPS", "123.0")
        monkeypatch.setenv("HVD_TPU_TOPO_DCN_GBPS", "7.0")
        topo.reset()
        t = gpu_topo.discover([self._Dev(0)] * 4)
        assert t.ici_gbps == 123.0
        assert t.dcn_gbps == 7.0

    def test_cost_model_prices_gpu_topology(self, monkeypatch):
        _force(monkeypatch, "gpu")
        devs = [self._Dev(p) for p in (0, 0, 0, 0, 1, 1, 1, 1)]
        t = gpu_topo.discover(devs)
        flat = t.estimate_cost("all_reduce", 1 << 20, lowering="flat")
        hier = t.estimate_cost("all_reduce", 1 << 20, lowering="hier")
        assert flat > 0 and hier > 0  # fitted-model consumers see real prices


class TestGpuPeakTable:
    class _Dev:
        def __init__(self, kind):
            self.device_kind = kind

    @pytest.mark.parametrize("kind,tflops", [
        ("NVIDIA H100 80GB HBM3", 989.0),
        ("NVIDIA A100-SXM4-40GB", 312.0),
        ("AMD Instinct MI300X", 1307.0),
    ])
    def test_gpu_kinds_resolve(self, monkeypatch, kind, tflops):
        from horovod_tpu.prof import peak

        _force(monkeypatch, "gpu")
        assert peak.chip_peak_tflops(self._Dev(kind)) == tflops

    def test_tpu_family_keeps_tpu_table(self, monkeypatch):
        from horovod_tpu.prof import peak

        assert peak.chip_peak_tflops(self._Dev("TPU v4")) == 275.0
        # a GPU kind under the tpu family is an unknown chip
        assert peak.chip_peak_tflops(self._Dev("NVIDIA H100")) is None


class TestQuantDefaultByFamily:
    def test_tpu_default_is_phase(self):
        from horovod_tpu.ops.quantized import quant_backend

        assert quant_backend() == "phase"

    def test_gpu_default_is_fused(self, monkeypatch):
        from horovod_tpu.ops import quantized

        _force(monkeypatch, "gpu")
        assert quantized.quant_backend() == "fused"
        assert quantized.fused_kernel_module().__name__.endswith(
            "mosaic_quant"
        )

    def test_explicit_knob_beats_family(self, monkeypatch):
        from horovod_tpu.ops.quantized import quant_backend

        _force(monkeypatch, "gpu")
        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "phase")
        assert quant_backend() == "phase"


class TestFingerprintKeying:
    def test_unset_equals_explicit_tpu(self, monkeypatch):
        from horovod_tpu.sched.store import knob_fingerprint

        unset = knob_fingerprint()
        _force(monkeypatch, "tpu")
        assert knob_fingerprint() == unset  # pre-PR-20 entries survive

    def test_gpu_keys_apart(self, monkeypatch):
        from horovod_tpu.sched.store import knob_fingerprint

        unset = knob_fingerprint()
        _force(monkeypatch, "gpu")
        assert knob_fingerprint() != unset

    def test_raw_env_spelling_never_leaks(self, monkeypatch):
        """Two spellings of the same family share one fold point —
        only the RESOLVED family is keyed, not the raw knob string."""
        from horovod_tpu.sched.store import knob_fingerprint

        _force(monkeypatch, "gpu")
        f_gpu = knob_fingerprint()
        _force(monkeypatch, "cuda")
        assert knob_fingerprint() == f_gpu

    def test_same_backend_warm_start(self, monkeypatch, tmp_path):
        """A winner recorded under the gpu fingerprint is found again
        by a fresh store under the same family, and invisible under
        tpu keys."""
        from horovod_tpu.sched.store import (
            ScheduleStore, knob_fingerprint, make_key,
        )

        sig = ("allreduce", ((0, 1), 4096))
        _force(monkeypatch, "gpu")
        key_gpu = make_key(sig, knobs=knob_fingerprint())
        db = str(tmp_path / "tune.json")
        ScheduleStore(db).record(
            key_gpu, bucket_bytes=1 << 20, wire="int8",
            lowering="flat", score=1.0,
        )
        warm = ScheduleStore(db).lookup(key_gpu)  # fresh process image
        assert warm is not None and warm["wire"] == "int8"
        _force(monkeypatch, "tpu")
        key_tpu = make_key(sig, knobs=knob_fingerprint())
        assert key_tpu != key_gpu
        assert ScheduleStore(db).lookup(key_tpu) is None


class TestDiagnostics:
    def test_bench_backend_record(self, monkeypatch):
        import bench

        _force(monkeypatch, "gpu")
        rec = bench._resolved_backend_record()
        assert rec["requested"] == "gpu"
        assert rec["family"] == "gpu"
        assert isinstance(rec["platform"], str) and rec["platform"]

    def test_bench_auto_follows_platform(self, monkeypatch):
        import bench

        rec = bench._resolved_backend_record()
        assert rec["requested"] == "auto"
        assert rec["family"] == "tpu"  # cpu host resolves tpu

    def test_probe_doctor_backend_record(self):
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "probe_doctor.py")
        spec = importlib.util.spec_from_file_location("_pd_t", path)
        pd = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pd)
        stages = [{"stage": "backend_init", "stdout": "cpu 8"}]
        rec = pd._backend_record({"HVD_TPU_BACKEND": "cuda"}, stages)
        assert rec == {"requested": "cuda", "platform": "cpu",
                       "family": "gpu"}
        rec = pd._backend_record({}, stages)
        assert rec["platform"] == "cpu" and rec["family"] == "tpu"
        # no stage output, no env: the record still resolves
        rec = pd._backend_record({"JAX_PLATFORMS": "gpu"}, [])
        assert rec["family"] == "gpu"
        rec = pd._backend_record({}, [])
        assert rec["family"] == "unknown"
        assert rec["platform"] == "uninitialized"
