"""Hot-path observability: transparent autotune windows driven by
TrainStep (reference ``parameter_manager.h:42-105``), timeline events
from the compiled step (``common/timeline.cc``), and the stall
watchdog over blocking waits (``stall_inspector.h:78``)."""

import json
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.utils.stall import PyStallInspector, StallWatchdog


@pytest.fixture(autouse=True)
def _fresh_runtime():
    # env-sensitive runtime construction: start each test uninitialized
    # (init() is idempotent, so a leftover runtime would mask the env).
    hvd.shutdown()
    yield
    hvd.shutdown()


def _tiny_step(hvd_mod, n_params: int = 4):
    params = {f"w{i}": jnp.ones((8, 8)) for i in range(n_params)}
    tx = hvd_mod.DistributedOptimizer(optax.sgd(0.01))

    def loss_fn(p, batch):
        acc = 0.0
        for k in sorted(p):
            acc = acc + jnp.sum((batch @ p[k]) ** 2)
        return acc

    step = hvd_mod.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    batch = jnp.ones((8, 8))
    return step, params, opt_state, batch


class TestAutotuneDriven:
    def test_threshold_changes_across_windows_and_freezes(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_AUTOTUNE", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_WINDOW", "2")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            assert step._autotune is not None
            seen = set()
            for _ in range(40):
                seen.add(step._autotune.threshold_bytes())
                params, opt_state, loss = step(params, opt_state, batch)
                if step._autotune.converged:
                    break
            assert step._autotune.converged, "driver never froze"
            # The tuner explored more than one candidate threshold.
            assert len(seen) > 1
            frozen = step._autotune.threshold_bytes()
            params, opt_state, loss = step(params, opt_state, batch)
            assert step._autotune.threshold_bytes() == frozen
            # Losing compiled variants are evicted after convergence.
            assert len(step._step_cache) == 1
            assert np.isfinite(float(loss))
        finally:
            hvd.shutdown()

    def test_autotune_skipped_for_explicit_threshold(self, monkeypatch):
        """An explicit fusion_threshold_bytes pins bucketing, so the
        driver must not burn recompiles exploring no-op candidates."""
        monkeypatch.setenv("HVD_TPU_AUTOTUNE", "1")
        hvd.init()
        try:
            params = {"w": jnp.ones((4, 4))}
            tx = hvd.DistributedOptimizer(
                optax.sgd(0.01), fusion_threshold_bytes=1 << 20
            )

            def loss_fn(p, batch):
                return jnp.sum((batch @ p["w"]) ** 2)

            step = hvd.distributed_train_step(loss_fn, tx)
            assert step._autotune is None
        finally:
            hvd.shutdown()

    def test_autotune_off_single_variant(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_AUTOTUNE", raising=False)
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            assert step._autotune is None
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state, batch)
            assert len(step._step_cache) == 1
        finally:
            hvd.shutdown()


class TestTrainStepTimeline:
    def test_timeline_records_step_events(self, monkeypatch, tmp_path):
        path = tmp_path / "timeline.json"
        monkeypatch.setenv("HVD_TPU_TIMELINE", str(path))
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
        finally:
            hvd.shutdown()  # closes + flushes the timeline
        events = json.loads(path.read_text())
        steps = [e for e in events if e.get("name") == "TrainStep"]
        begins = [e for e in steps if e.get("ph") == "B"]
        ends = [e for e in steps if e.get("ph") == "E"]
        assert len(begins) == 3 and len(ends) == 3

    def test_timeline_records_bucket_lanes(self, monkeypatch, tmp_path):
        """VERDICT r3 item 7 gate: the exchange plan emits one record
        per bucket (name carries index + tensor count, args the wire
        bytes) — SCHED_EXCHANGE lanes from the default overlap
        scheduler — and the compiled step's HLO carries the per-bucket
        named_scope so profiler traces attribute collectives to
        buckets."""
        path = tmp_path / "timeline.json"
        monkeypatch.setenv("HVD_TPU_TIMELINE", str(path))
        # tiny threshold -> multiple buckets for 4 params of 256 B each
        monkeypatch.setenv("HVD_TPU_FUSION_THRESHOLD", "600")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
        finally:
            hvd.shutdown()
        events = json.loads(path.read_text())
        plans = [e for e in events if e.get("cat") == "SCHED_EXCHANGE"]
        assert len(plans) >= 2, plans  # 4x256B at 600B -> 2 buckets
        assert all(e["args"]["bytes"] > 0 for e in plans)
        assert any(e["name"].startswith("bucket0") for e in plans)

    def test_timeline_records_bucket_lanes_legacy_engine(
        self, monkeypatch, tmp_path
    ):
        """HVD_TPU_SCHED=off keeps the legacy FUSION_PLAN lanes."""
        path = tmp_path / "timeline.json"
        monkeypatch.setenv("HVD_TPU_TIMELINE", str(path))
        monkeypatch.setenv("HVD_TPU_FUSION_THRESHOLD", "600")
        monkeypatch.setenv("HVD_TPU_SCHED", "off")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
        finally:
            hvd.shutdown()
        events = json.loads(path.read_text())
        plans = [e for e in events if e.get("cat") == "FUSION_PLAN"]
        assert len(plans) >= 2, plans
        assert all(e["args"]["bytes"] > 0 for e in plans)
        assert any(e["name"].startswith("bucket0") for e in plans)

    def test_compiled_step_hlo_names_buckets(self, monkeypatch):
        import jax

        monkeypatch.setenv("HVD_TPU_FUSION_THRESHOLD", "600")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            # compile once, then inspect the lowered program's metadata
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
            fn = next(iter(step._step_cache.values()))
            hlo = fn.lower(params, None, opt_state, batch).compile().as_text()
            assert "hvd_sched_bucket0" in hlo
            assert "hvd_sched_bucket1" in hlo
        finally:
            hvd.shutdown()

    def test_compiled_step_hlo_names_buckets_legacy_engine(
        self, monkeypatch
    ):
        import jax

        monkeypatch.setenv("HVD_TPU_FUSION_THRESHOLD", "600")
        monkeypatch.setenv("HVD_TPU_SCHED", "off")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
            fn = next(iter(step._step_cache.values()))
            hlo = fn.lower(params, None, opt_state, batch).compile().as_text()
            assert "hvd_bucket0" in hlo
            assert "hvd_bucket1" in hlo
        finally:
            hvd.shutdown()

    def test_measured_bucket_durations(self, monkeypatch, tmp_path):
        """VERDICT r5 item 7 gate: ``profile_bucket_step`` joins the
        ``hvd_bucket*`` named scopes against a real profiler trace and
        lands MEASURED per-bucket duration events (nonzero spans) in
        the chrome timeline's measured lane."""
        path = tmp_path / "timeline.json"
        monkeypatch.setenv("HVD_TPU_TIMELINE", str(path))
        monkeypatch.setenv("HVD_TPU_FUSION_THRESHOLD", "600")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
            fn = next(iter(step._step_cache.values()))
            totals, out = hvd.profile_bucket_step(
                fn, params, None, opt_state, batch
            )
            # donated inputs: the step output replaces them
            params, opt_state = out[0], out[-2]
            assert len(totals) >= 2, totals  # 4x256B at 600B -> 2 buckets
            assert all(v > 0 for v in totals.values()), totals
            assert all(k.startswith("bucket") for k in totals)
            # training continues from the profiled step's output
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
        finally:
            hvd.shutdown()
        events = json.loads(path.read_text())
        spans = [e for e in events if e.get("cat") == "BUCKET_EXEC"]
        assert len(spans) >= 2, spans
        assert all(e["dur"] > 0 for e in spans)
        assert all(e.get("tid") == 1 for e in spans)  # measured lane

    def test_autotune_writes_window_records(self, monkeypatch, tmp_path):
        path = tmp_path / "timeline.json"
        monkeypatch.setenv("HVD_TPU_TIMELINE", str(path))
        monkeypatch.setenv("HVD_TPU_AUTOTUNE", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_WINDOW", "2")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            for _ in range(5):  # at least two closed windows
                params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
        finally:
            hvd.shutdown()
        events = json.loads(path.read_text())
        windows = [e for e in events if e.get("cat") == "AUTOTUNE_WINDOW"]
        assert len(windows) >= 2, windows
        assert all("threshold=" in e["name"] and "score=" in e["name"]
                   for e in windows)

    def test_timeline_mark_cycles(self, monkeypatch, tmp_path):
        path = tmp_path / "timeline.json"
        monkeypatch.setenv("HVD_TPU_TIMELINE", str(path))
        monkeypatch.setenv("HVD_TPU_TIMELINE_MARK_CYCLES", "1")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
        finally:
            hvd.shutdown()
        events = json.loads(path.read_text())
        assert any(e.get("ph") == "i" for e in events)


class TestRuntimeTimelineSwitch:
    def test_start_stop_timeline(self, tmp_path):
        """Runtime activation without the env var (reference
        horovod_start_timeline, operations.cc:1011)."""
        from horovod_tpu.utils.timeline import start_timeline, stop_timeline

        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime

            assert get_runtime().timeline is None
            path = tmp_path / "runtime_timeline.json"
            start_timeline(str(path))
            assert get_runtime().timeline is not None
            hvd.allreduce(np.ones((8, 2), np.float32), name="switched.op")
            stop_timeline()
            assert get_runtime().timeline is None
            events = json.loads(path.read_text())
            assert any(e.get("name") == "switched.op" for e in events)
            # collectives after stop don't crash and don't record
            hvd.allreduce(np.ones((8, 2), np.float32))
        finally:
            hvd.shutdown()


class TestStallWatchdog:
    def test_py_inspector_report(self):
        ins = PyStallInspector(warn_seconds=0.05)
        ins.begin("allreduce.grad")
        time.sleep(0.1)
        stalled, shutdown = ins.report()
        assert stalled == ["allreduce.grad"]
        assert not shutdown
        ins.end("allreduce.grad")
        assert ins.report() == ([], False)
        ins.close()

    def test_watchdog_warns_on_stall(self):
        hits = []
        wd = StallWatchdog(
            warn_seconds=0.05, on_stall=hits.append, poll_seconds=0.02
        )
        try:
            wd.begin("allgather.emb")
            deadline = time.monotonic() + 2.0
            while not hits and time.monotonic() < deadline:
                time.sleep(0.02)
            assert hits and "allgather.emb" in hits[0]
            wd.end("allgather.emb")
        finally:
            wd.close()

    def test_watchdog_quiet_on_fast_ops(self):
        hits = []
        wd = StallWatchdog(
            warn_seconds=0.5, on_stall=hits.append, poll_seconds=0.02
        )
        try:
            out = wd.wait(jnp.ones(4) * 2, "allreduce.fast")
            assert float(out.sum()) == 8.0
            time.sleep(0.1)
            assert not hits
        finally:
            wd.close()

    def test_autotune_sync_is_watchdog_guarded(self, monkeypatch):
        """VERDICT r3 gate: the hot-path window fence (AutotuneDriver
        sync on the step output) must register with the stall inspector
        under the name TrainStep — a never-ready future has to trigger
        the warning, not hang invisibly in bare block_until_ready."""
        import jax as _jax

        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime
            from horovod_tpu.utils.autotune import AutotuneDriver

            rt = get_runtime()
            hits = []
            old_wd = rt.stall_watchdog
            wd = StallWatchdog(
                warn_seconds=0.05, on_stall=hits.append, poll_seconds=0.02
            )
            rt.stall_watchdog = wd
            # mock a never-ready future: the guarded wait blocks well
            # past the warn threshold
            monkeypatch.setattr(
                _jax, "block_until_ready", lambda v: time.sleep(0.5)
            )
            try:
                AutotuneDriver()._sync(object())
                assert hits and "TrainStep" in hits[0], hits
            finally:
                rt.stall_watchdog = old_wd
                wd.close()
        finally:
            hvd.shutdown()

    def test_runtime_owns_watchdog(self):
        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime

            assert get_runtime().stall_watchdog is not None
        finally:
            hvd.shutdown()

    def test_disable_env(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_STALL_CHECK_DISABLE", "1")
        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime

            assert get_runtime().stall_watchdog is None
        finally:
            hvd.shutdown()


class TestHierarchicalKnobExploration:
    """Second autotune knob (reference ParameterManager tunes several
    parameters jointly): after the threshold freezes, the hierarchical
    lowering is probed at the winner and kept only if faster."""

    def test_state_machine_keeps_winner(self):
        from horovod_tpu.utils.autotune import AutotuneDriver

        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime

            rt = get_runtime()
            old = rt.local_size, rt.cross_size
            rt.local_size, rt.cross_size = 2, 4  # multi-host overlay
            try:
                drv = AutotuneDriver(window_steps=2,
                                     warmup_windows=1)
                drv.tuner._frozen = 4096  # threshold already converged
                assert drv.hierarchical() is None
                drv._advance_hier(10.0)          # flat baseline windows
                drv._advance_hier(10.5)          # (same count as probe)
                assert drv.hierarchical() is True  # probing
                drv._advance_hier(12.0)
                drv._advance_hier(13.0)          # hier mean wins
                assert drv.converged
                assert drv.hierarchical() is True
            finally:
                rt.local_size, rt.cross_size = old
        finally:
            hvd.shutdown()

    def test_state_machine_rejects_loser_and_single_host_skips(self):
        from horovod_tpu.utils.autotune import AutotuneDriver

        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime

            rt = get_runtime()
            old = rt.local_size, rt.cross_size
            rt.local_size, rt.cross_size = 2, 4
            try:
                drv = AutotuneDriver(window_steps=2, warmup_windows=1)
                drv.tuner._frozen = 4096
                drv._advance_hier(10.0)
                drv._advance_hier(10.0)
                drv._advance_hier(8.0)
                drv._advance_hier(7.0)
                # rejected probe freezes to None so the flat baseline's
                # compiled variant (keyed on None) is reused, not
                # recompiled
                assert drv.converged and drv.hierarchical() is None
            finally:
                rt.local_size, rt.cross_size = old
            # single-host world: exploration skipped entirely
            drv2 = AutotuneDriver(window_steps=2, warmup_windows=1)
            drv2.tuner._frozen = 4096
            drv2._advance_hier(10.0)
            assert drv2.converged and drv2.hierarchical() is None
        finally:
            hvd.shutdown()

    def test_user_pinned_env_is_honored(self, monkeypatch):
        from horovod_tpu.utils.autotune import AutotuneDriver

        monkeypatch.setenv("HVD_TPU_HIERARCHICAL_ALLREDUCE", "1")
        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime

            rt = get_runtime()
            rt.local_size, rt.cross_size = 2, 4
            drv = AutotuneDriver(window_steps=2, warmup_windows=1)
            drv.tuner._frozen = 4096
            drv._advance_hier(10.0)
            # pinned: never probes, lowering comes from the env default
            assert drv.converged and drv.hierarchical() is None
        finally:
            hvd.shutdown()

    def test_trainstep_explores_quantized_variant(self, monkeypatch):
        """End to end: with the quantized opt-in, the schedule probes an
        int8-wire step variant after threshold+hier freeze, and the
        final cache holds exactly the winning (thr, hier, quant)
        entry."""
        monkeypatch.setenv("HVD_TPU_AUTOTUNE", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_WINDOW", "2")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_HIER_WINDOWS", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_EXPLORE_QUANTIZED", "1")
        hvd.init()
        try:
            step, params, opt_state, batch = _tiny_step(hvd)
            seen_quant = set()
            for _ in range(40):
                params, opt_state, loss = step(params, opt_state, batch)
                seen_quant.add(step._autotune.quantized())
                if step._autotune.converged:
                    break
            float(loss)
            assert step._autotune.converged
            assert True in seen_quant  # the int8 wire really probed
            params, opt_state, loss = step(params, opt_state, batch)
            assert len(step._step_cache) == 1
            (key,) = step._step_cache
            assert key[4] in (True, None)  # frozen quant decision
        finally:
            hvd.shutdown()

    def test_trainstep_explores_hier_variants(self, monkeypatch):
        """End to end: with autotune on and a multi-host overlay, the
        step cache gains a hierarchical variant during probing and the
        eviction keeps exactly the winning (threshold, hier) entry."""
        monkeypatch.setenv("HVD_TPU_AUTOTUNE", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_WINDOW", "2")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "2")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_HIER_WINDOWS", "2")
        hvd.init()
        try:
            from horovod_tpu.runtime import get_runtime

            rt = get_runtime()
            rt.local_size, rt.cross_size = 2, 4
            step, params, opt_state, batch = _tiny_step(hvd)
            seen_hier = set()
            for _ in range(40):
                params, opt_state, loss = step(params, opt_state, batch)
                seen_hier.add(step._autotune.hierarchical())
                if step._autotune.converged:
                    break
            float(loss)
            assert step._autotune.converged
            assert True in seen_hier  # the hier lowering really probed
            params, opt_state, loss = step(params, opt_state, batch)
            assert len(step._step_cache) == 1  # losers evicted
            (key,) = step._step_cache
            assert key[3] in (True, False, None)
        finally:
            hvd.shutdown()
