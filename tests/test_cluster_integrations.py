"""Ray/Spark integration logic (reference ``test/single/test_ray.py``
layout assertions + ``test/integration/test_spark.py`` store/estimator
pieces) — the pure-Python parts run without ray/pyspark installed."""

import os

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ray import (
    ColocatedStrategy,
    Coordinator,
    PackStrategy,
    RayExecutor,
    SpreadStrategy,
)
from horovod_tpu.spark import FilesystemStore, LocalStore, Store, TpuEstimator


# ---- Ray coordinator (reference ray/runner.py:41-126) ------------------

def test_coordinator_rank_layout():
    c = Coordinator()
    # two hosts, 2 + 1 slots, registration order defines host_rank
    c.register("hostA", 0)
    c.register("hostA", 1)
    c.register("hostB", 2)
    envs = c.finalize_registration()
    assert c.world_size == 3
    assert envs[0]["HVD_TPU_LOCAL_RANK"] == "0"
    assert envs[1]["HVD_TPU_LOCAL_RANK"] == "1"
    assert envs[2]["HVD_TPU_LOCAL_RANK"] == "0"
    # launcher contract: CROSS_RANK/SIZE = process id / process count
    # (what runtime._init_distributed feeds jax.distributed.initialize)
    assert [envs[r]["HVD_TPU_CROSS_RANK"] for r in range(3)] == ["0", "1", "2"]
    assert all(e["HVD_TPU_CROSS_SIZE"] == "3" for e in envs.values())
    # host-index semantics live in HOST_RANK/HOST_SIZE
    assert envs[0]["HVD_TPU_HOST_RANK"] == "0"
    assert envs[2]["HVD_TPU_HOST_RANK"] == "1"
    assert all(e["HVD_TPU_HOST_SIZE"] == "2" for e in envs.values())
    assert envs[0]["HVD_TPU_LOCAL_SIZE"] == "2"
    assert envs[2]["HVD_TPU_LOCAL_SIZE"] == "1"


def test_coordinator_slot_infos():
    c = Coordinator()
    c.register("h1", 0)
    c.register("h2", 1)
    slots = c.slot_infos()
    assert [s.rank for s in slots] == [0, 1]
    assert slots[0].cross_size == 2
    assert slots[0].size == 2


def test_coordinator_node_id_by_rank():
    c = Coordinator()
    c.register("h1", 0)
    c.register("h1", 1)
    assert c.node_id_by_rank == {0: "h1", 1: "h1"}


# ---- placement strategies (reference ray/strategy.py) ------------------

def test_pack_strategy_bundles():
    s = PackStrategy(num_workers=5, num_workers_per_host=2, cpus_per_worker=3)
    assert s.bundles() == [{"CPU": 6}, {"CPU": 6}, {"CPU": 3}]


def test_spread_strategy_bundles():
    s = SpreadStrategy(num_workers=3, cpus_per_worker=2)
    assert s.bundles() == [{"CPU": 2}] * 3


def test_colocated_strategy_divisibility():
    s = ColocatedStrategy(num_workers=4, num_workers_per_host=2)
    assert len(s.bundles()) == 2
    with pytest.raises(ValueError):
        ColocatedStrategy(num_workers=5, num_workers_per_host=2).bundles()


def test_ray_executor_requires_ray():
    ex = RayExecutor(num_workers=2)
    assert ex.placement_bundles() == [{"CPU": 1}, {"CPU": 1}]
    with pytest.raises(ImportError, match="ray"):
        ex.start()


# ---- Spark store (reference spark/common/store.py) ---------------------

def test_local_store_paths(tmp_path):
    store = LocalStore(str(tmp_path / "store"))
    assert store.get_checkpoint_path("run1").endswith("checkpoints/run1")
    assert store.get_logs_path("run1").endswith("logs/run1")
    assert store.get_train_data_path(3).endswith("intermediate_train_data.3")


def test_store_checkpoint_roundtrip(tmp_path):
    store = LocalStore(str(tmp_path / "store"))
    assert store.load_checkpoint("r") is None
    store.save_checkpoint("r", {"w": [1, 2, 3]})
    assert store.load_checkpoint("r") == {"w": [1, 2, 3]}


def test_store_create_dispatch(tmp_path):
    s = Store.create(str(tmp_path / "x"))
    assert isinstance(s, LocalStore)
    with pytest.raises(NotImplementedError):
        Store.create("hdfs://namenode/path")


# ---- Estimator (pure parts + array fit path) ---------------------------

def test_estimator_validates_params():
    with pytest.raises(ValueError, match="model"):
        TpuEstimator()


def test_estimator_fit_on_arrays(hvd_module, tmp_path):
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    def loss(pred, y):
        return optax.softmax_cross_entropy_with_integer_labels(pred, y).mean()

    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)

    est = TpuEstimator(
        model=Linear(), optimizer=optax.adam(1e-2), loss=loss,
        feature_cols=["features"], label_cols=["label"],
        batch_size=8, epochs=2, store=LocalStore(str(tmp_path / "store")),
        run_id="test_run",
    )
    model = est.fit_on_arrays(features=x, label=y)
    preds = model.predict(x[:4])
    assert preds.shape == (4, 2)
    # checkpoint persisted for resume
    assert est._has_checkpoint()


def test_estimator_multi_feature_columns(hvd_module, tmp_path):
    import flax.linen as nn
    import optax

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rng = np.random.RandomState(1)
    f1 = rng.randn(64, 3).astype(np.float32)
    f2 = rng.randn(64).astype(np.float32)  # 1-D column joins as width 1
    y = ((f1.sum(axis=1) + f2) > 0).astype(np.int32)

    est = TpuEstimator(
        model=Linear(), optimizer=optax.adam(1e-2),
        loss=lambda p, t: optax.softmax_cross_entropy_with_integer_labels(
            p, t).mean(),
        feature_cols=["f1", "f2"], label_cols=["label"],
        batch_size=16, epochs=2, store=LocalStore(str(tmp_path / "s")),
        run_id="mc",
    )
    model = est.fit_on_arrays(f1=f1, f2=f2, label=y)
    # trained on the 4-wide concatenation, not silently on f1 alone
    assert model.predict(np.concatenate(
        [f1[:4], f2[:4, None]], axis=-1)).shape == (4, 2)


def test_spark_run_requires_pyspark():
    from horovod_tpu import spark as hvd_spark

    with pytest.raises(ImportError, match="pyspark"):
        hvd_spark.run(lambda: None)
