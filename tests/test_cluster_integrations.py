"""Ray/Spark integration logic (reference ``test/single/test_ray.py``
layout assertions + ``test/integration/test_spark.py`` store/estimator
pieces) — the pure-Python parts run without ray/pyspark installed."""

import os

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.ray import (
    ColocatedStrategy,
    Coordinator,
    PackStrategy,
    RayExecutor,
    SpreadStrategy,
)
from horovod_tpu.spark import FilesystemStore, LocalStore, Store, TpuEstimator


# ---- Ray coordinator (reference ray/runner.py:41-126) ------------------

def test_coordinator_rank_layout():
    c = Coordinator()
    # two hosts, 2 + 1 slots, registration order defines host_rank
    c.register("hostA", 0)
    c.register("hostA", 1)
    c.register("hostB", 2)
    envs = c.finalize_registration()
    assert c.world_size == 3
    assert envs[0]["HVD_TPU_LOCAL_RANK"] == "0"
    assert envs[1]["HVD_TPU_LOCAL_RANK"] == "1"
    assert envs[2]["HVD_TPU_LOCAL_RANK"] == "0"
    # launcher contract: CROSS_RANK/SIZE = process id / process count
    # (what runtime._init_distributed feeds jax.distributed.initialize)
    assert [envs[r]["HVD_TPU_CROSS_RANK"] for r in range(3)] == ["0", "1", "2"]
    assert all(e["HVD_TPU_CROSS_SIZE"] == "3" for e in envs.values())
    # host-index semantics live in HOST_RANK/HOST_SIZE
    assert envs[0]["HVD_TPU_HOST_RANK"] == "0"
    assert envs[2]["HVD_TPU_HOST_RANK"] == "1"
    assert all(e["HVD_TPU_HOST_SIZE"] == "2" for e in envs.values())
    assert envs[0]["HVD_TPU_LOCAL_SIZE"] == "2"
    assert envs[2]["HVD_TPU_LOCAL_SIZE"] == "1"


def test_coordinator_slot_infos():
    c = Coordinator()
    c.register("h1", 0)
    c.register("h2", 1)
    slots = c.slot_infos()
    assert [s.rank for s in slots] == [0, 1]
    assert slots[0].cross_size == 2
    assert slots[0].size == 2


def test_coordinator_node_id_by_rank():
    c = Coordinator()
    c.register("h1", 0)
    c.register("h1", 1)
    assert c.node_id_by_rank == {0: "h1", 1: "h1"}


# ---- placement strategies (reference ray/strategy.py) ------------------

def test_pack_strategy_bundles():
    s = PackStrategy(num_workers=5, num_workers_per_host=2, cpus_per_worker=3)
    assert s.bundles() == [{"CPU": 6}, {"CPU": 6}, {"CPU": 3}]


def test_spread_strategy_bundles():
    s = SpreadStrategy(num_workers=3, cpus_per_worker=2)
    assert s.bundles() == [{"CPU": 2}] * 3


def test_colocated_strategy_divisibility():
    s = ColocatedStrategy(num_workers=4, num_workers_per_host=2)
    assert len(s.bundles()) == 2
    with pytest.raises(ValueError):
        ColocatedStrategy(num_workers=5, num_workers_per_host=2).bundles()


def test_ray_executor_requires_ray():
    ex = RayExecutor(num_workers=2)
    assert ex.placement_bundles() == [{"CPU": 1}, {"CPU": 1}]
    with pytest.raises(ImportError, match="ray"):
        ex.start()


# ---- Spark store (reference spark/common/store.py) ---------------------

def test_local_store_paths(tmp_path):
    store = LocalStore(str(tmp_path / "store"))
    assert store.get_checkpoint_path("run1").endswith("checkpoints/run1")
    assert store.get_logs_path("run1").endswith("logs/run1")
    assert store.get_train_data_path(3).endswith("intermediate_train_data.3")


def test_store_checkpoint_roundtrip(tmp_path):
    store = LocalStore(str(tmp_path / "store"))
    assert store.load_checkpoint("r") is None
    store.save_checkpoint("r", {"w": [1, 2, 3]})
    assert store.load_checkpoint("r") == {"w": [1, 2, 3]}


def test_store_create_dispatch(tmp_path):
    s = Store.create(str(tmp_path / "x"))
    assert isinstance(s, LocalStore)
    with pytest.raises(NotImplementedError):
        Store.create("hdfs://namenode/path")


# ---- Estimator (pure parts + array fit path) ---------------------------

def test_estimator_validates_params():
    with pytest.raises(ValueError, match="model"):
        TpuEstimator()


def test_estimator_fit_on_arrays(hvd_module, tmp_path):
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    def loss(pred, y):
        return optax.softmax_cross_entropy_with_integer_labels(pred, y).mean()

    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)

    est = TpuEstimator(
        model=Linear(), optimizer=optax.adam(1e-2), loss=loss,
        feature_cols=["features"], label_cols=["label"],
        batch_size=8, epochs=2, store=LocalStore(str(tmp_path / "store")),
        run_id="test_run",
    )
    model = est.fit_on_arrays(features=x, label=y)
    preds = model.predict(x[:4])
    assert preds.shape == (4, 2)
    # checkpoint persisted for resume
    assert est._has_checkpoint()


def test_estimator_multi_feature_columns(hvd_module, tmp_path):
    import flax.linen as nn
    import optax

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rng = np.random.RandomState(1)
    f1 = rng.randn(64, 3).astype(np.float32)
    f2 = rng.randn(64).astype(np.float32)  # 1-D column joins as width 1
    y = ((f1.sum(axis=1) + f2) > 0).astype(np.int32)

    est = TpuEstimator(
        model=Linear(), optimizer=optax.adam(1e-2),
        loss=lambda p, t: optax.softmax_cross_entropy_with_integer_labels(
            p, t).mean(),
        feature_cols=["f1", "f2"], label_cols=["label"],
        batch_size=16, epochs=2, store=LocalStore(str(tmp_path / "s")),
        run_id="mc",
    )
    model = est.fit_on_arrays(f1=f1, f2=f2, label=y)
    # trained on the 4-wide concatenation, not silently on f1 alone
    assert model.predict(np.concatenate(
        [f1[:4], f2[:4, None]], axis=-1)).shape == (4, 2)


def test_spark_run_requires_pyspark():
    from horovod_tpu import spark as hvd_spark

    with pytest.raises(ImportError, match="pyspark"):
        hvd_spark.run(lambda: None)


# ---- elastic on Spark (VERDICT r5 item 5) --------------------------------

def _elastic_fn(crash_round_rank=None):
    """Worker fn: real hvd init + allreduce across the round's world;
    optionally hard-crashes one rank in round 1 (worker loss)."""
    import os

    import numpy as np

    import horovod_tpu as hvd

    rnd = int(os.environ.get("HVD_TPU_ELASTIC_ROUND", "0"))
    rank = int(os.environ["HVD_TPU_CROSS_RANK"])
    if crash_round_rank is not None and rnd == 1 \
            and rank == crash_round_rank:
        os._exit(17)  # mid-epoch hard loss
    hvd.init()
    # process-local rows form (one CPU device per worker): row 0 = this
    # rank's tensor; the result comes back in the same local layout
    x = np.full((1, 2), float(rank + 1), np.float32)
    red = np.asarray(hvd.allreduce(x, op=hvd.Sum))
    hvd.shutdown()
    return {
        "round": rnd,
        "rank": rank,
        "world": int(os.environ["HVD_TPU_CROSS_SIZE"]),
        "sum0": float(red[0, 0]),
    }


@pytest.mark.integration
@pytest.mark.multiproc
def test_spark_elastic_clean_round():
    """run_elastic over the local agent backend (the Spark-task stand-in
    used when pyspark is absent): one clean round, per-rank results."""
    import sys

    import cloudpickle

    from horovod_tpu.spark.elastic import run_elastic

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = run_elastic(
        _elastic_fn, num_proc=2, min_np=2,
        extra_env={"HVD_TPU_FORCE_CPU": "1",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
        _backend="local",
    )
    assert len(results) == 2
    for r in results:
        assert r["world"] == 2
        assert r["sum0"] == 3.0  # ranks contribute 1+2


@pytest.mark.integration
@pytest.mark.multiproc
def test_spark_elastic_worker_loss_epoch():
    """Reference elastic_spark_common contract: a worker hard-dies
    mid-round; the driver blacklists its host, runs a fresh round on
    the surviving agents, and the job completes there."""
    import sys

    import cloudpickle

    from horovod_tpu.spark.elastic import run_elastic

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = run_elastic(
        _elastic_fn, kwargs={"crash_round_rank": 1},
        num_proc=3, min_np=2, max_np=3,
        extra_env={"HVD_TPU_FORCE_CPU": "1",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
        _backend="local",
    )
    # round 1 lost a worker -> round >= 2 succeeded with the remaining 2
    assert len(results) == 2
    for r in results:
        assert r["round"] >= 2
        assert r["world"] == 2
        assert r["sum0"] == 3.0


def test_spark_run_elastic_requires_pyspark():
    from horovod_tpu.spark import run_elastic

    with pytest.raises(ImportError, match="pyspark"):
        run_elastic(lambda: None, num_proc=1)
