"""Gradients of the raw bridge collectives.

Mirrors the reference's gradient tests
(``test/parallel/test_torch.py:558-1460`` test_horovod_*_grad,
``test/parallel/test_tensorflow.py`` equivalents) on the stacked
single-controller layout: an ``hvd.allreduce`` inside a loss graph must
backpropagate an allreduce of the gradient, allgather a sliced
set-average, broadcast a root-delivered set-average, alltoall the
reverse alltoall (``interop/_grads.py``).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu as hvd
from horovod_tpu.interop import torch as hvd_torch

N = 8


@pytest.fixture()
def dynamic_sets(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    yield


# ---- torch (reference torch/mpi_ops.py autograd.Function wrappers) ------

def test_torch_allreduce_grad_sum(hvd_module):
    x = torch.randn(N, 4, requires_grad=True)
    w = torch.randn(N, 4)
    y = hvd_torch.allreduce(x, op=hvd.Sum)
    y.backward(w)
    # grad = allreduce(dy, Sum): every row gets the row-sum of w
    want = np.tile(w.numpy().sum(axis=0), (N, 1))
    np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-5)


def test_torch_allreduce_grad_average(hvd_module):
    x = torch.randn(N, 3, requires_grad=True)
    y = hvd_torch.allreduce(x, op=hvd.Average)
    y.backward(torch.ones(N, 3))
    # grad = allreduce(ones, Average) = ones
    np.testing.assert_allclose(x.grad.numpy(), np.ones((N, 3)), rtol=1e-5)


def test_torch_allreduce_grad_scale_factors(hvd_module):
    x = torch.randn(N, 2, requires_grad=True)
    y = hvd_torch.allreduce(x, op=hvd.Sum, prescale_factor=2.0,
                            postscale_factor=0.5)
    y.backward(torch.ones(N, 2))
    # same factors on the way back: 2 * 0.5 * sum(ones) = N
    np.testing.assert_allclose(x.grad.numpy(), np.full((N, 2), float(N)),
                               rtol=1e-5)


def test_torch_allgather_grad(hvd_module):
    # reference test_horovod_allgather_grad: grad_ys block r = ones * r
    # (identical on every rank) -> grad on rank r = ones * r
    d = 2
    x = torch.ones(N, d, 3, requires_grad=True)
    blocks = np.concatenate(
        [np.full((d, 3), float(r), np.float32) for r in range(N)]
    )
    dy = torch.tensor(np.tile(blocks, (N, 1, 1)))
    y = hvd_torch.allgather(x)
    assert y.shape == (N, N * d, 3)
    y.backward(dy)
    want = np.stack(
        [np.full((d, 3), float(r), np.float32) for r in range(N)]
    )
    np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-5)


def test_torch_broadcast_grad(hvd_module):
    # reference test_horovod_broadcast_grad: root collects the
    # set-average, everyone else gets zero
    root = 2
    x = torch.randn(N, 5, requires_grad=True)
    dy = torch.randn(N, 5)
    y = hvd_torch.broadcast(x, root_rank=root)
    y.backward(dy)
    want = np.zeros((N, 5), np.float32)
    want[root] = dy.numpy().mean(axis=0)
    np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-5, atol=1e-6)


def test_torch_alltoall_grad_even(hvd_module):
    # even splits: the backward is the reverse alltoall (transpose of
    # the chunk grid)
    x = torch.randn(N, N, requires_grad=True)
    dy = torch.randn(N, N)
    y = hvd_torch.alltoall(x)
    y.backward(dy)
    np.testing.assert_allclose(x.grad.numpy(), dy.numpy().T, rtol=1e-5)


def test_torch_alltoall_grad_uneven(hvd_module):
    # uneven splits: gradient un-routes the padded placement exactly
    splits = np.ones((N, N), np.int32)
    splits[0, 1] += 1
    splits[0, 2] -= 1
    d0 = int(splits[0].sum())
    x = torch.randn(N, d0, requires_grad=True)
    out, recv = hvd_torch.alltoall(x, splits=splits)
    dy = torch.randn(*out.shape)
    out.backward(dy)
    # numpy reference routing
    max_chunk = int(splits.max())
    offs = np.concatenate(
        [np.zeros((N, 1), np.int64), np.cumsum(splits, axis=1)], axis=1
    )
    want = np.zeros((N, d0), np.float32)
    for m in range(N):
        for j in range(N):
            c = int(splits[m, j])
            want[m, offs[m, j]:offs[m, j] + c] = (
                dy.numpy()[j, m * max_chunk:m * max_chunk + c]
            )
    np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-5)


def test_torch_grouped_allreduce_grad(hvd_module):
    xs = [torch.randn(N, 3, requires_grad=True) for _ in range(3)]
    ys = hvd_torch.grouped_allreduce(xs, op=hvd.Sum)
    sum(y.sum() for y in ys).backward()
    for x in xs:
        np.testing.assert_allclose(
            x.grad.numpy(), np.full((N, 3), float(N)), rtol=1e-5
        )


def test_torch_process_set_allreduce_grad(hvd_module, dynamic_sets):
    members = [0, 2, 5]
    ps = hvd.add_process_set(members)
    try:
        x = torch.randn(N, 4, requires_grad=True)
        dy = torch.randn(N, 4)
        y = hvd_torch.allreduce(x, op=hvd.Average, process_set=ps)
        y.backward(dy)
        want = np.array(dy.numpy(), copy=True)
        want[members] = dy.numpy()[members].mean(axis=0)
        np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


def test_torch_process_set_allgather_grad_nonmember_zero(hvd_module,
                                                         dynamic_sets):
    members = [1, 4]
    ps = hvd.add_process_set(members)
    try:
        d = 2
        x = torch.ones(N, d, requires_grad=True)
        y = hvd_torch.allgather(x, process_set=ps)
        assert y.shape == (N, len(members) * d)
        y.backward(torch.ones_like(y))
        g = x.grad.numpy()
        for r in range(N):
            if r in members:
                np.testing.assert_allclose(g[r], np.ones(d), rtol=1e-5)
            else:
                np.testing.assert_allclose(g[r], np.zeros(d))
    finally:
        hvd.remove_process_set(ps)


def test_torch_no_grad_path_unchanged(hvd_module):
    # tensors without requires_grad skip the autograd wrapper entirely
    x = torch.arange(N * 2, dtype=torch.float32).reshape(N, 2)
    y = hvd_torch.allreduce(x, op=hvd.Sum)
    assert not y.requires_grad
    np.testing.assert_allclose(
        y.numpy(), np.tile(x.numpy().sum(axis=0), (N, 1)), rtol=1e-6
    )


# ---- TF (reference tensorflow/mpi_ops.py RegisterGradient) --------------

tf = pytest.importorskip("tensorflow")

from horovod_tpu.interop import tf as hvd_tf  # noqa: E402


def test_tf_allreduce_grad_sum(hvd_module):
    x = tf.constant(np.random.RandomState(0).randn(N, 4).astype(np.float32))
    w = np.random.RandomState(1).randn(N, 4).astype(np.float32)
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvd_tf.allreduce(x, op=hvd.Sum)
    g = tape.gradient(y, x, output_gradients=tf.constant(w))
    np.testing.assert_allclose(
        g.numpy(), np.tile(w.sum(axis=0), (N, 1)), rtol=1e-5
    )


def test_tf_allreduce_grad_average_through_loss(hvd_module):
    x = tf.constant(np.ones((N, 3), np.float32))
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvd_tf.allreduce(x, op=hvd.Average)
        loss = tf.reduce_sum(y)
    g = tape.gradient(loss, x)
    # d loss / dx = allreduce(ones, Average) = ones
    np.testing.assert_allclose(g.numpy(), np.ones((N, 3)), rtol=1e-5)


def test_tf_allgather_grad(hvd_module):
    d = 2
    x = tf.constant(np.ones((N, d), np.float32))
    blocks = np.concatenate(
        [np.full((d,), float(r), np.float32) for r in range(N)]
    )
    dy = tf.constant(np.tile(blocks, (N, 1)))
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvd_tf.allgather(x)
    g = tape.gradient(y, x, output_gradients=dy)
    want = np.stack([np.full((d,), float(r)) for r in range(N)])
    np.testing.assert_allclose(g.numpy(), want, rtol=1e-5)


def test_tf_broadcast_grad(hvd_module):
    root = 3
    dy = np.random.RandomState(2).randn(N, 4).astype(np.float32)
    x = tf.constant(np.ones((N, 4), np.float32))
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvd_tf.broadcast(x, root_rank=root)
    g = tape.gradient(y, x, output_gradients=tf.constant(dy))
    want = np.zeros((N, 4), np.float32)
    want[root] = dy.mean(axis=0)
    np.testing.assert_allclose(g.numpy(), want, rtol=1e-5, atol=1e-6)


def test_tf_alltoall_grad_even(hvd_module):
    x = tf.constant(np.random.RandomState(3).randn(N, N).astype(np.float32))
    dy = np.random.RandomState(4).randn(N, N).astype(np.float32)
    with tf.GradientTape() as tape:
        tape.watch(x)
        y = hvd_tf.alltoall(x)
    g = tape.gradient(y, x, output_gradients=tf.constant(dy))
    np.testing.assert_allclose(g.numpy(), dy.T, rtol=1e-5)


def test_tf_alltoall_grad_uneven(hvd_module):
    splits = np.ones((N, N), np.int32)
    splits[0, 1] += 1
    splits[0, 2] -= 1
    d0 = int(splits[0].sum())
    x = tf.constant(np.random.RandomState(5).randn(N, d0).astype(np.float32))
    with tf.GradientTape() as tape:
        tape.watch(x)
        out, recv = hvd_tf.alltoall(x, splits=splits)
    dy = np.random.RandomState(6).randn(*out.shape.as_list()).astype(
        np.float32
    )
    g = tape.gradient(out, x, output_gradients=tf.constant(dy))
    max_chunk = int(splits.max())
    offs = np.concatenate(
        [np.zeros((N, 1), np.int64), np.cumsum(splits, axis=1)], axis=1
    )
    want = np.zeros((N, d0), np.float32)
    for m in range(N):
        for j in range(N):
            c = int(splits[m, j])
            want[m, offs[m, j]:offs[m, j] + c] = (
                dy[j, m * max_chunk:m * max_chunk + c]
            )
    np.testing.assert_allclose(g.numpy(), want, rtol=1e-5)


def test_tf_allreduce_grad_inside_tf_function(hvd_module):
    """The in-graph py_function lowering carries the custom gradient."""
    @tf.function
    def f(x):
        with tf.GradientTape() as tape:
            tape.watch(x)
            y = hvd_tf.allreduce(x, op=hvd.Sum)
            loss = tf.reduce_sum(y)
        return tape.gradient(loss, x)

    g = f(tf.constant(np.ones((N, 2), np.float32)))
    np.testing.assert_allclose(g.numpy(), np.full((N, 2), float(N)),
                               rtol=1e-5)


def test_tf_indexed_slices_grad_flows(hvd_module):
    """The IndexedSlices reduce path composes differentiably through
    the allgather custom gradient."""
    values = tf.constant(np.ones((N, 2, 3), np.float32))
    indices = tf.constant(np.tile(np.arange(2), (N, 1)).astype(np.int32))
    with tf.GradientTape() as tape:
        tape.watch(values)
        s = tf.IndexedSlices(values=values, indices=indices,
                             dense_shape=tf.constant([4, 3]))
        red = hvd_tf.allreduce(s, op=hvd.Average)
        loss = tf.reduce_sum(red.values)
    g = tape.gradient(loss, values)
    assert g is not None
    assert g.shape == values.shape


def test_tf_indexed_slices_set_average_uses_set_size(hvd_module,
                                                     dynamic_sets):
    members = [0, 3, 6]
    ps = hvd.add_process_set(members)
    try:
        values = tf.constant(np.ones((N, 2, 3), np.float32))
        indices = tf.constant(np.tile(np.arange(2), (N, 1)).astype(np.int32))
        s = tf.IndexedSlices(values=values, indices=indices,
                             dense_shape=tf.constant([4, 3]))
        red = hvd_tf.allreduce(s, op=hvd.Average, process_set=ps)
        # member rows: gather of k members' ones, each scaled by 1/k
        # (NOT 1/world) so the scatter-add over the k duplicate indices
        # reconstructs exactly the member average (= ones)
        k = len(members)
        vals = red.values.numpy()
        for r in members:
            np.testing.assert_allclose(
                vals[r], np.full((k * 2, 3), 1.0 / k), rtol=1e-6
            )
            # dense reconstruction: accumulate duplicates
            dense = np.zeros((4, 3), np.float32)
            np.add.at(dense, red.indices.numpy()[r], vals[r])
            np.testing.assert_allclose(dense[:2], np.ones((2, 3)),
                                       rtol=1e-6)
    finally:
        hvd.remove_process_set(ps)


def test_tf_process_set_allreduce_grad(hvd_module, dynamic_sets):
    members = [0, 3, 6]
    ps = hvd.add_process_set(members)
    try:
        dy = np.random.RandomState(8).randn(N, 3).astype(np.float32)
        x = tf.constant(np.ones((N, 3), np.float32))
        with tf.GradientTape() as tape:
            tape.watch(x)
            y = hvd_tf.allreduce(x, op=hvd.Average, process_set=ps)
        g = tape.gradient(y, x, output_gradients=tf.constant(dy))
        want = np.array(dy, copy=True)
        want[members] = dy[members].mean(axis=0)
        np.testing.assert_allclose(g.numpy(), want, rtol=1e-5)
    finally:
        hvd.remove_process_set(ps)


@pytest.mark.integration
@pytest.mark.multiproc
def test_torch_grads_multiprocess_local_rows():
    """The gradient contracts hold in the multi-process LOCAL-ROWS
    layout too: each process passes its own rows and receives its own
    rows' gradients (reference per-rank semantics)."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import torch

        import horovod_tpu as hvd
        import horovod_tpu.interop.torch as hvd_torch

        hvd.init()
        r = hvd.process_rank()
        # local-rows allreduce grad: every rank's grad = sum of dys
        x = torch.full((1, 3), float(r + 1), requires_grad=True)
        y = hvd_torch.allreduce(x, op=hvd.Sum)
        y.backward(torch.full((1, 3), float(r + 1)))
        g_ar = x.grad.numpy().ravel().tolist()

        # local-rows allgather grad: rank r keeps its own slice of the
        # Average-allreduced dy
        x2 = torch.ones((1, 2), requires_grad=True)
        y2 = hvd_torch.allgather(x2)
        # dy identical on both ranks: block m = ones * (m+1)
        dy = torch.tensor(
            np.concatenate([np.full((1, 2), float(m + 1), np.float32)
                            for m in range(hvd.size())])
        ).reshape(y2.shape)
        y2.backward(dy)
        g_ag = x2.grad.numpy().ravel().tolist()
        return [g_ar, g_ag]

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    # allreduce grad = sum over ranks of dy = 1 + 2 = 3 on both ranks
    np.testing.assert_allclose(results[0][0], [3.0] * 3)
    np.testing.assert_allclose(results[1][0], [3.0] * 3)
    # allgather grad: rank r's slice of the averaged dy = ones * (r+1)
    np.testing.assert_allclose(results[0][1], [1.0, 1.0])
    np.testing.assert_allclose(results[1][1], [2.0, 2.0])
