"""End-to-end exchange tracing (trace/): spans, flight recorder,
straggler detection, merge tooling.

Contracts under test:

* **Propagation** — one TraceContext rides a submission end to end:
  the queue/negotiation/cache/dispatch spans the service emits all
  carry the submitting program's trace id, and the flight-recorder
  dump contains them.
* **Negotiation** — the negotiate span names the LAST-ARRIVING
  participant (who everyone waited on).
* **Cache** — a repeat signature's span set has a cache hit and NO
  "lower" span (the hit skips the whole lowering pass).
* **Nesting** — rail-phase spans (rs_ici / dcn / ag_ici) emitted while
  a hier step traces nest under that step's span tree, and the
  measured ``topo.rail_busy_frac{rail=}`` gauges come out nonzero.
* **Flight recorder** — the ring evicts FIFO at capacity; anomaly
  dumps fire on an injected slow step (z x rolling p50) and on a
  ``svc.loop`` fault, writing JSON to ``HVD_TPU_TRACE_DIR``.
* **Neutrality** — f32 dense losses are bitwise identical with
  tracing off / summary / full (host-side spans, no inserted ops).
* **Tools** — ``merge_timeline_files`` reports per-file parse status
  and merges trace exports + flight dumps; the straggler detector
  names the slow (rank, phase) and the /trace endpoint serves it.
"""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults, metrics, sched, svc, topo, trace, xir
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.topo import model as topo_model
from horovod_tpu.trace import straggler
from horovod_tpu.trace.recorder import FlightRecorder

pytestmark = pytest.mark.trace

N = 8
T24 = topo_model.Topology(num_slices=2, slice_size=4)


@pytest.fixture(autouse=True)
def _trace_isolation():
    trace.reset()
    metrics.reset_counters("trace.")
    metrics.reset_counters("svc.")
    metrics.reset_counters("faults.")
    metrics.clear_gauge("topo.rail_busy_frac")
    trace.set_level_override("summary")
    yield
    trace.set_level_override(None)
    trace.reset()
    svc.set_enabled_override(None)
    svc.reset_service()
    sched.set_config_override(None)
    topo.set_topology_override(None)
    faults.set_plan(None)
    metrics.reset_counters("faults.")
    os.environ.pop("HVD_TPU_TRACE_DIR", None)


def _ar_program(kind="tr", nbytes=32, bucket=0):
    return xir.program(kind, [
        xir.all_reduce(WORLD_AXIS, reduce="mean", bucket=bucket,
                       nbytes=nbytes, dtype="float32"),
    ])


def _walk(d):
    yield d
    for c in d.get("children", ()):
        yield from _walk(c)


def _all_spans():
    """Every span dict currently in the recorder (steps + background)."""
    rec = trace.get_recorder()
    out = []
    for r in rec.steps() + list(rec._background):
        out.extend(_walk(r["spans"]))
    return out


class TestLevels:
    def test_off_is_shared_noop(self):
        trace.set_level_override("off")
        assert trace.span("a", "b") is trace.tracer.NOOP
        assert trace.step() is trace.tracer.NOOP
        assert trace.record_complete("a", "b", 0.0) is None

    def test_level_spellings(self, monkeypatch):
        trace.set_level_override(None)
        for raw, want in (("off", "off"), ("0", "off"),
                          ("summary", "summary"), ("full", "full"),
                          ("on", "full"), ("1", "full"),
                          ("bogus", "summary")):
            monkeypatch.setenv("HVD_TPU_TRACE", raw)
            assert trace.level() == want, raw

    def test_context_ids_unique_and_child(self):
        a = trace.new_context("p")
        b = trace.new_context("p")
        assert a.trace_id != b.trace_id
        assert a.child("s9").span_id == "s9"
        assert a.child("s9").trace_id == a.trace_id


@pytest.mark.usefixtures("hvd_module")
class TestPropagation:
    def test_submission_spans_share_trace_id_and_reach_dump(self, tmp_path):
        os.environ["HVD_TPU_TRACE_DIR"] = str(tmp_path)
        ctx = trace.new_context("prop")
        prog = _ar_program(nbytes=64).with_trace(ctx)
        x = jnp.ones((N, 4), jnp.float32)
        s = svc.get_service()
        s.submit(prog, [x], producer="prop").result(timeout=60)
        s.drain(timeout_s=10)
        spans = _all_spans()
        tagged = {sp["name"]: sp for sp in spans
                  if sp.get("trace_id") == ctx.trace_id}
        # queue wait, dispatch, and the lowering underneath all carry
        # the submission's trace id
        assert any(sp["phase"] == "queue" for sp in tagged.values()), spans
        assert any(sp["phase"] == "dispatch" for sp in tagged.values())
        assert any(sp["phase"] == "lower" for sp in tagged.values())
        # ... and a dump carries them out to disk
        path = trace.get_recorder().dump("test")
        assert path is not None and os.path.exists(path)
        disk = json.load(open(path))
        disk_ids = {
            sp.get("trace_id")
            for rec in disk["steps"] + disk["background"]
            for sp in _walk(rec["spans"])
        }
        assert ctx.trace_id in disk_ids

    def test_program_with_trace_keeps_signature(self):
        prog = _ar_program()
        tagged = prog.with_trace(trace.new_context("x"))
        assert tagged.signature() == prog.signature()
        assert tagged == prog  # compare=False field

    def test_negotiation_records_last_arriver(self):
        s = svc.get_service()
        x = jnp.ones((N, 2), jnp.float32)
        parts = ("alpha", "beta")
        fa = s.submit(_ar_program(nbytes=16), [x], producer="alpha",
                      participants=parts)
        time.sleep(0.2)  # let alpha's post land first
        fb = s.submit(_ar_program(nbytes=16), [x], producer="beta",
                      participants=parts)
        fa.result(timeout=60)
        fb.result(timeout=60)
        neg = [sp for sp in _all_spans() if sp["phase"] == "negotiate"]
        assert neg, "no negotiation span recorded"
        assert neg[0]["attrs"]["last_arriver"] == "beta"
        assert "alpha" in neg[0]["attrs"]["participants"]

    def test_cache_hit_spans_skip_lowering(self):
        svc.set_enabled_override(True)
        s = svc.get_service()
        prog = _ar_program(nbytes=1 << 16)

        def spans_of(ctx):
            return [sp for sp in _all_spans()
                    if sp.get("trace_id") == ctx.trace_id]

        cold_ctx = trace.new_context("cold")
        s.submit_traced(prog.with_trace(cold_ctx), producer="cold")
        cold = {sp["phase"] for sp in spans_of(cold_ctx)}
        assert "lower" in cold, "cold path must lower"

        warm_ctx = trace.new_context("warm")
        s.submit_traced(prog.with_trace(warm_ctx), producer="warm")
        warm = spans_of(warm_ctx)
        warm_phases = {sp["phase"] for sp in warm}
        assert "lower" not in warm_phases, \
            f"cache hit re-lowered: {warm}"
        hits = [sp for sp in warm if sp["phase"] == "cache"]
        assert hits and hits[0]["attrs"]["hit"] == 1


@pytest.mark.usefixtures("hvd_module")
class TestStepNesting:
    def _hier_train(self, iters=3):
        topo.set_topology_override(T24)
        sched.set_config_override(sched.SchedConfig(
            enabled=True, bucket_bytes=2048, lowering="hier",
        ))
        rng = np.random.RandomState(0)
        X = rng.randn(16, 32).astype(np.float32)
        Y = (X @ rng.randn(32, 4).astype(np.float32)).astype(np.float32)

        def lf(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

        p = {
            "w": jnp.asarray(rng.randn(32, 4).astype(np.float32) * 0.1),
            "b": jnp.zeros((4,), jnp.float32),
        }
        tx = hvd.DistributedOptimizer(optax.sgd(0.05))
        step = hvd.distributed_train_step(lf, tx)
        st = step.init(p)
        batch = (jnp.asarray(X), jnp.asarray(Y))
        losses = []
        for _ in range(iters):
            p, st, loss = step(p, st, batch)
            losses.append(float(loss))
        return losses

    def test_rail_spans_nest_under_step_span(self):
        self._hier_train()
        rec = trace.get_recorder()
        steps = rec.steps()
        assert steps, "no step spans recorded"
        # the traced (first) step carries the exchange tree
        tree = steps[0]["spans"]
        assert tree["phase"] == "step"
        phases = [sp["phase"] for sp in _walk(tree)]
        for want in ("exchange", "rs_ici", "dcn", "ag_ici"):
            assert want in phases, f"{want} not nested under step: {phases}"
        # rails measured from those spans
        ici = metrics.get_gauge("topo.rail_busy_frac", {"rail": "ici"})
        dcn = metrics.get_gauge("topo.rail_busy_frac", {"rail": "dcn"})
        assert ici is not None and ici > 0
        assert dcn is not None and dcn > 0
        assert xir.pipeline.measured_rail_busy()["dcn"] == dcn

    def test_losses_bitwise_identical_across_levels(self):
        base = None
        for level in ("off", "summary", "full"):
            trace.reset()
            trace.set_level_override(level)
            losses = self._hier_train()
            if base is None:
                base = losses
            else:
                assert losses == base, \
                    f"tracing level {level} perturbed losses"


class TestFlightRecorder:
    def _mk_span(self, name="s", phase="step", dur=0.001, step=None):
        sp = trace.tracer.Span(name, phase, time.monotonic())
        sp.t1 = sp.t0 + dur
        if step is not None:
            sp.attrs = {"step": step}
        return sp

    def test_ring_evicts_fifo(self):
        rec = FlightRecorder(capacity=3)
        for i in range(5):
            rec.on_step(self._mk_span(step=i))
        kept = [r["step"] for r in rec.steps()]
        assert kept == [2, 3, 4], kept

    def test_anomaly_dump_fires_on_slow_step(self, tmp_path):
        os.environ["HVD_TPU_TRACE_DIR"] = str(tmp_path)
        rec = FlightRecorder(capacity=8)
        for i in range(6):
            rec.on_step(self._mk_span(dur=0.01, step=i))
        assert rec.dump_seq == 0
        rec.on_step(self._mk_span(dur=1.0, step=6))  # >> 3 x p50
        assert rec.dump_seq == 1
        path = rec.last_dump_path()
        assert path and os.path.exists(path)
        dump = json.load(open(path))
        assert dump["reason"] == "slow_step"
        assert dump["detail"]["step_seconds"] == pytest.approx(1.0, rel=0.1)
        assert len(dump["steps"]) >= 6

    def test_no_dump_without_history(self):
        rec = FlightRecorder(capacity=8)
        rec.on_step(self._mk_span(dur=5.0))  # first step: no baseline
        assert rec.dump_seq == 0

    @pytest.mark.usefixtures("hvd_module")
    def test_anomaly_dump_fires_on_svc_loop_fault(self, tmp_path):
        os.environ["HVD_TPU_TRACE_DIR"] = str(tmp_path)
        # seed the ring so the fault trigger has something to dump
        with trace.step():
            pass
        faults.set_plan("svc.loop:error:nth=1")
        s = svc.get_service()
        x = jnp.ones((N, 2), jnp.float32)
        s.submit(_ar_program(nbytes=8), [x], producer="t").result(
            timeout=60)
        assert s.dead
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_rank")]
        assert dumps, "no flight dump written on svc.loop fault"
        reasons = {json.load(open(tmp_path / f))["reason"] for f in dumps}
        assert {"fault:svc.loop", "svc_death"} & reasons, reasons
        assert metrics.get_counter("trace.anomaly_dumps") >= 1
        assert metrics.get_gauge("trace.last_anomaly_dump") >= 1

    def test_remesh_trigger_reason(self):
        with trace.step():
            pass
        trace.trigger_dump("remesh", np_old=8, np_new=4)
        dump = trace.get_recorder().last_dump()
        assert dump is not None and dump["reason"] == "remesh"
        # no HVD_TPU_TRACE_DIR: retained in memory, not on disk
        assert trace.get_recorder().last_dump_path() is None


class TestStraggler:
    def _snap(self, dcn_s, n=20):
        metrics.reset_counters("trace.")
        for _ in range(n):
            metrics.observe("trace.phase_seconds.dcn", dcn_s)
            metrics.observe("trace.phase_seconds.rs_ici", 0.001)
        metrics.inc_counter("trace.anomaly_dumps", 1)
        metrics.set_gauge("trace.last_anomaly_dump", 1)
        return metrics.snapshot()

    def test_detects_slow_rank_and_phase(self):
        per_rank = {0: self._snap(0.002), 1: self._snap(0.002),
                    2: self._snap(0.300), 3: self._snap(0.002)}
        found = straggler.detect(per_rank)
        assert found, "straggler not detected"
        assert found[0]["rank"] == 2
        assert found[0]["phase"] == "dcn"
        assert found[0]["ratio"] > 2.0

    def test_no_false_positive_on_uniform_ranks(self):
        per_rank = {r: self._snap(0.002) for r in range(4)}
        assert straggler.detect(per_rank) == []

    def test_publish_gauges_and_clear(self):
        found = straggler.detect(
            {0: self._snap(0.002), 1: self._snap(0.300)})
        straggler.publish(found)
        assert metrics.get_gauge(
            "trace.straggler", {"rank": "1", "phase": "dcn"}) is not None
        straggler.publish([])
        assert metrics.get_gauge(
            "trace.straggler", {"rank": "1", "phase": "dcn"}) is None
        assert metrics.get_gauge("trace.stragglers") == 0

    def test_trace_endpoint_names_straggler(self):
        import urllib.request

        from horovod_tpu.runner.telemetry_http import TelemetryServer

        snaps = [(0, self._snap(0.002)), (1, self._snap(0.300))]
        srv = TelemetryServer(port=0, workers_fn=lambda: list(snaps))
        try:
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/trace"))
        finally:
            srv.stop()
        assert body["stragglers"][0]["rank"] == 1
        assert body["stragglers"][0]["phase"] == "dcn"
        assert body["ranks"]["1"]["anomaly_dumps"] == 1
        assert body["ranks"]["1"]["phases"]["dcn"]["p50"] > \
            body["ranks"]["0"]["phases"]["dcn"]["p50"]

    def test_trace_endpoint_404_without_sources(self):
        import urllib.error, urllib.request

        from horovod_tpu.runner.telemetry_http import TelemetryServer

        srv = TelemetryServer(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/trace")
            assert ei.value.code == 404
        finally:
            srv.stop()


class TestExportAndMerge:
    def test_full_level_writes_mergeable_chrome_trace(self, tmp_path):
        os.environ["HVD_TPU_TRACE_DIR"] = str(tmp_path)
        trace.set_level_override("full")
        with trace.step():
            with trace.span("b0.dcn", "dcn", rail="dcn"):
                time.sleep(0.002)
        trace.reset()  # closes the writer -> valid JSON
        files = [f for f in os.listdir(tmp_path)
                 if f.startswith("trace_rank")]
        assert files
        events = json.load(open(tmp_path / files[0]))
        names = {e.get("name") for e in events}
        assert "HVD_PROC_META" in names and "b0.dcn" in names
        cats = {e.get("cat") for e in events if e.get("ph") == "X"}
        assert "TRACE_DCN" in cats and "TRACE_STEP" in cats

    def test_merge_report_flags_unparseable_file(self, tmp_path):
        from horovod_tpu.utils.timeline import merge_timeline_files

        good = tmp_path / "t.json"
        good.write_text(json.dumps([
            {"name": "HVD_PROC_META", "ph": "i", "ts": 0, "pid": 1,
             "args": {"rank": 1, "epoch_wall_us": 0.0}},
            {"name": "x", "cat": "SVC_EXCHANGE", "ph": "X", "ts": 1,
             "dur": 1, "pid": 1, "tid": 0},
        ]))
        bad = tmp_path / "bad.json"
        bad.write_text("{{{")
        report = []
        merged = merge_timeline_files([str(good), str(bad)],
                                      report=report)
        by_path = {r["path"]: r for r in report}
        assert by_path[str(good)]["status"] == "ok"
        assert by_path[str(bad)]["status"] == "error"
        # the SVC_EXCHANGE event landed on a named lane
        lanes = [e for e in merged["traceEvents"]
                 if e.get("name") == "thread_name"
                 and e["args"]["name"] == "SVC_EXCHANGE"]
        assert lanes, merged["traceEvents"]

    def test_merge_cli_exits_nonzero_on_unparseable(self, tmp_path):
        import subprocess
        import sys

        bad = tmp_path / "bad.json"
        bad.write_text("nope")
        out = tmp_path / "merged.json"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(repo, "tools", "merge_timeline.py"),
             str(bad), "-o", str(out)],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode != 0
        assert "error" in (proc.stderr + proc.stdout)

    def test_flight_dump_merges_with_anchor(self, tmp_path):
        from horovod_tpu.utils.timeline import merge_timeline_files

        with trace.step():
            with trace.span("d", "dcn", rail="dcn"):
                pass
        os.environ["HVD_TPU_TRACE_DIR"] = str(tmp_path)
        path = trace.get_recorder().dump("test")
        report = []
        merged = merge_timeline_files([path], report=report)
        assert report[0]["status"] == "ok"
        evs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert any(e.get("cat") == "TRACE_DCN" for e in evs)
