"""Basics API tests (reference analog: the rank/size assertions woven
through ``test/parallel/test_torch.py`` and ``test_tensorflow.py``)."""

import jax
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.exceptions import HorovodTpuError, NotInitializedError


def test_not_initialized_raises():
    if hvd.is_initialized():
        hvd.shutdown()
    with pytest.raises(NotInitializedError):
        hvd.size()


def test_init_topology(hvd_init):
    assert hvd.size() == 8
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.cross_size() == 1
    assert hvd.process_count() == 1
    assert hvd.is_homogeneous()
    assert hvd.xla_built()
    assert not hvd.mpi_enabled()


def test_init_idempotent(hvd_init):
    hvd.init()
    assert hvd.size() == 8


def test_mesh_shape(hvd_init):
    mesh = hvd.mesh()
    assert mesh.axis_names == (hvd.WORLD_AXIS,)
    assert mesh.devices.shape == (8,)


def test_process_set_registration(hvd_init):
    ps = hvd.ProcessSet([0, 1, 2, 3])
    with pytest.raises(HorovodTpuError):
        hvd.add_process_set(ps)  # dynamic not enabled


def test_process_set_dynamic(hvd_init, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set(hvd.ProcessSet([0, 1, 2, 3]))
    assert ps.process_set_id == 1
    assert ps.size() == 4
    assert ps.included(2)
    assert not ps.included(5)
    assert ps.rank() == 0  # global rank 0 is position 0
    # duplicate registration returns the same set
    ps2 = hvd.add_process_set(hvd.ProcessSet([3, 2, 1, 0]))
    assert ps2.process_set_id == 1
    hvd.remove_process_set(ps)
    assert hvd.get_process_set_ids() == [0]


def test_global_process_set(hvd_init):
    gps = hvd.global_process_set()
    assert gps.process_set_id == 0
    assert gps.size() == 8
    with pytest.raises(HorovodTpuError):
        hvd.remove_process_set(gps)


def test_init_with_process_sets():
    hvd.init(process_sets=[hvd.ProcessSet([0, 1]), hvd.ProcessSet([2, 3, 4])])
    try:
        assert hvd.get_process_set_ids() == [0, 1, 2]
    finally:
        hvd.shutdown()


def test_process_sets_from_env(monkeypatch):
    """HVD_TPU_PROCESS_SETS declares rank subsets at init (the env
    mirror of init(process_sets=...))."""
    hvd.shutdown()
    monkeypatch.setenv("HVD_TPU_PROCESS_SETS", "0,1;2,3,4")
    hvd.init()
    try:
        ids = hvd.get_process_set_ids()
        assert len(ids) == 3  # global + two declared
        x = np.ones((8, 2), np.float32)
        from horovod_tpu.process_sets import ProcessSet

        table = __import__("horovod_tpu").runtime.get_runtime().process_set_table
        declared = [table.get(i) for i in ids if i != 0]
        rank_sets = sorted(tuple(ps.ranks) for ps in declared)
        assert rank_sets == [(0, 1), (2, 3, 4)]
        y = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=declared[0]))
        assert np.isfinite(y).all()
    finally:
        hvd.shutdown()


# ---- init(comm=...) parity (reference basics.py:48) ---------------------


class _FakeGroup:
    def __init__(self, world_ranks):
        self._ranks = world_ranks

    def translate_ranks(self, comm_ranks):
        return [self._ranks[i] for i in comm_ranks]


class _FakeComm:
    """mpi4py-shaped communicator covering a subset of world ranks."""

    def __init__(self, world_ranks):
        self.group = _FakeGroup(world_ranks)
        self._n = len(world_ranks)

    def Get_size(self):
        return self._n

    def Get_rank(self):
        return 0


def test_init_comm_rank_list():
    hvd.init(comm=[0, 2, 5])
    try:
        assert hvd.size() == 3
        import jax

        world = jax.devices()
        from horovod_tpu.runtime import get_runtime

        assert get_runtime().devices == [world[0], world[2], world[5]]
    finally:
        hvd.shutdown()


def test_init_comm_mpi4py_like_object():
    """comm rank i maps onto the translated world rank (the reference's
    MPI group translation, duck-typed so no MPI install is needed)."""
    hvd.init(comm=_FakeComm([1, 3, 4, 6]))
    try:
        assert hvd.size() == 4
        import jax

        world = jax.devices()
        from horovod_tpu.runtime import get_runtime

        assert get_runtime().devices == [world[r] for r in (1, 3, 4, 6)]
    finally:
        hvd.shutdown()


def test_init_comm_validation():
    import pytest

    with pytest.raises(ValueError, match="out of range"):
        hvd.init(comm=[0, 99])
    with pytest.raises(ValueError, match="duplicates"):
        hvd.init(comm=[0, 0, 1])
    with pytest.raises(ValueError, match="not both"):
        import jax

        hvd.init(comm=[0, 1], devices=jax.devices()[:2])
    assert not hvd.is_initialized()


def test_init_process_sets_dynamic_string(monkeypatch):
    monkeypatch.delenv("HVD_TPU_DYNAMIC_PROCESS_SETS", raising=False)
    hvd.init(process_sets="dynamic")
    try:
        ps = hvd.add_process_set([0, 1])  # no env preset needed
        hvd.remove_process_set(ps)
    finally:
        hvd.shutdown()
        monkeypatch.delenv("HVD_TPU_DYNAMIC_PROCESS_SETS", raising=False)
