"""Device-time profiling plane (prof/): compiled-step introspection,
host-gap attribution, online MFU, perf-regression sentinel, /prof.

Contracts under test:

* **Introspection** — wrapping a jitted fn records XLA cost-analysis
  FLOPs/bytes, compile wall-clock, and call counts per program key,
  returns bitwise-identical results, recompiles once per argument
  signature, and degrades to the raw fn (one attempt, forever) when
  AOT lowering is impossible.
* **Host gap** — ``attribute()`` is pure math on a span tree: busy is
  the *union* of device-phase intervals (overlap never double counts),
  gap is wall minus busy, dispatches count exec/dispatch spans plus
  the service-loop counter delta, and tenant busy splits by the trace
  tenant slot.
* **MFU** — cost-analysis FLOPs over step wall-clock against a pinned
  peak gives the exact expected ratio (clamped to 1.0), per workload
  and per tenant; ``publish()`` is the bench-side entry point.
* **Sentinel** — the baseline store roundtrips through the
  ScheduleStore machinery (keep-best keeps the fastest run), an
  identical second run verdicts ``ok``, a slower run verdicts
  ``regression`` (gauge + counter), and the no-DB/no-data paths stay
  inert.
* **Endpoint** — ``GET /prof`` answers 200 with the full structure
  even on an empty plane; worker snapshots fold into a per-rank
  digest; ``GET /health`` carries the probe doctor's verdict without
  flipping health status.
* **Neutrality** — TrainStep losses are bitwise identical with
  profiling on vs off (AOT runs the same HLO the jit call would).
* **Retention** — flight-recorder dumps prune oldest-first to
  ``HVD_TPU_TRACE_DUMP_KEEP`` per rank; svc cache entries carry their
  accumulated compile bill and rank by it.
"""

import json
import os
import time
import urllib.request
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

import horovod_tpu as hvd
from horovod_tpu import metrics, prof, svc, trace, xir
from horovod_tpu.prof import baseline, capture, hostgap, introspect, mfu, peak
from horovod_tpu.runner import telemetry_http
from horovod_tpu.runner.telemetry_http import TelemetryServer
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.sched import store as store_mod
from horovod_tpu.trace.recorder import FlightRecorder
from horovod_tpu.trace.tracer import Span

pytestmark = pytest.mark.prof

N = 8


@pytest.fixture(autouse=True)
def _prof_isolation():
    prof.reset()
    metrics.reset_counters("prof.")
    metrics.reset_counters("svc.")
    metrics.reset_counters("trace.")
    for g in ("prof.mfu", "prof.flops", "prof.bytes_accessed",
              "prof.peak_hbm_bytes", "prof.host_gap_frac",
              "prof.dispatches_per_step", "prof.regression",
              "prof.flops_per_step", "prof.emitted_ops"):
        metrics.clear_gauge(g)
    trace.set_level_override("summary")
    yield
    prof.set_enabled_override(None)
    prof.reset()
    trace.set_level_override(None)
    trace.reset()
    svc.reset_service()
    for var in ("HVD_TPU_PROF_DB", "HVD_TPU_PROF_CHECK_EVERY",
                "HVD_TPU_TRACE_DIR", "HVD_TPU_TRACE_DUMP_KEEP"):
        os.environ.pop(var, None)


def _span(name, phase, t0, t1, tenant="", **attrs):
    s = Span(name, phase, t0, tenant=tenant, attrs=attrs or None)
    s.t1 = t1
    return s


def _step_span(wall, children=()):
    root = _span("step", "step", 0.0, wall)
    root.children.extend(children)
    return root


# ---------------------------------------------------------------- intro


class TestIntrospection:
    def test_wrap_records_cost_and_matches_raw(self):
        f = jax.jit(lambda x: jnp.tanh(x @ x).sum())
        ex = introspect.wrap(f, key="intro_a", kind="step", workload="wa")
        x = jnp.full((16, 16), 0.25, jnp.float32)
        out = ex(x)
        assert float(out) == float(f(x))  # AOT runs the jit's HLO
        rec = introspect.get("intro_a")
        assert rec is not None and rec["compiles"] == 1
        assert rec["flops"] is not None and rec["flops"] > 0
        assert rec["compile_seconds"] > 0
        assert metrics.get_counter("prof.compiles") == 1
        assert metrics.get_gauge(
            "prof.flops", {"key": "intro_a", "kind": "step"}) == rec["flops"]

    def test_compiles_once_per_signature(self):
        f = jax.jit(lambda x: x * 2.0)
        ex = introspect.wrap(f, key="intro_b", kind="step")
        ex(jnp.ones((4,), jnp.float32))
        ex(jnp.ones((4,), jnp.float32))
        assert introspect.get("intro_b")["compiles"] == 1
        assert introspect.get("intro_b")["calls"] == 2
        ex(jnp.ones((8,), jnp.float32))  # new shape -> one more compile
        assert introspect.get("intro_b")["compiles"] == 2

    def test_unlowerable_fn_falls_back_forever(self):
        calls = []

        def raw(x):
            calls.append(1)
            return x + 1

        ex = introspect.wrap(raw, key="intro_c", kind="step")
        assert ex(1) == 2 and ex(5) == 6  # results survive the fallback
        assert len(calls) == 2
        assert introspect.get("intro_c")["fallback"] is True
        assert metrics.get_counter("prof.fallbacks") >= 1
        assert metrics.get_counter("prof.compiles") == 0

    def test_calltime_failure_falls_back(self):
        # A Compiled whose lowering succeeded but whose *call* blows up
        # (the AOT-vs-jit gap: layout/sharding drift the signature key
        # cannot see) must demote to the raw fn, not raise.
        calls = []

        class Boom:
            def cost_analysis(self):
                return [{"flops": 1.0}]

            def memory_analysis(self):
                return None

            def __call__(self, *args):
                raise RuntimeError("layout mismatch")

        class FakeJit:
            def lower(self, *args):
                return self

            def compile(self):
                return Boom()

            def __call__(self, x):
                calls.append(x)
                return x + 1

        ex = introspect.wrap(FakeJit(), key="intro_e", kind="step")
        assert ex(1) == 2 and ex(2) == 3  # results survive the fallback
        assert calls == [1, 2]  # raw fn served both calls
        assert introspect.get("intro_e")["fallback"] is True
        assert metrics.get_counter("prof.fallbacks") >= 1

    def test_off_returns_fn_unwrapped(self):
        prof.set_enabled_override(False)
        f = jax.jit(lambda x: x)
        assert introspect.wrap(f, key="intro_d", kind="step") is f

    def test_ranked_orders_by_compile_cost(self):
        fa = jax.jit(lambda x: x + 1.0)
        fb = jax.jit(lambda x: jnp.tanh(x @ x))
        ea = introspect.wrap(fa, key="rank_a", kind="step")
        eb = introspect.wrap(fb, key="rank_b", kind="step")
        ea(jnp.ones((4,), jnp.float32))
        eb(jnp.ones((32, 32), jnp.float32))
        rows = introspect.ranked()
        assert [r["key"] for r in rows[:2]] == sorted(
            ("rank_a", "rank_b"),
            key=lambda k: introspect.get(k)["compile_seconds"],
            reverse=True,
        )


# -------------------------------------------------------------- hostgap


class TestHostGap:
    def test_attribute_union_gap_dispatch_tenant(self):
        root = _step_span(1.0, [
            _span("exec.a", "exec", 0.1, 0.4, tenant="ta"),
            # overlaps the exec span: union covers [0.1, 0.6], not 0.6s
            _span("disp", "dispatch", 0.3, 0.6),
            _span("rs", "rs_ici", 0.7, 0.8, tenant="tb"),
            # rail attribution without a rail phase name still counts
            _span("x", "custom", 0.85, 0.9, rail="ici"),
            # host-side phase: never device-busy
            _span("neg", "negotiate", 0.0, 1.0),
        ])
        stats = hostgap.attribute(root)
        assert stats["wall_s"] == pytest.approx(1.0)
        assert stats["busy_s"] == pytest.approx(0.5 + 0.1 + 0.05)
        assert stats["gap_s"] == pytest.approx(1.0 - 0.65)
        assert stats["dispatches"] == 2  # exec + dispatch, not rails
        assert stats["tenant_busy_s"] == {
            "ta": pytest.approx(0.3), "tb": pytest.approx(0.1)}

    def test_busy_capped_at_wall(self):
        root = _step_span(0.2, [_span("e", "exec", 0.0, 5.0)])
        stats = hostgap.attribute(root)
        assert stats["busy_s"] == pytest.approx(0.2)
        assert stats["gap_s"] == 0.0

    def test_on_step_adds_svc_dispatch_delta(self):
        first = hostgap.on_step(_step_span(0.1))
        assert first["dispatches"] == 0  # no counter history yet
        metrics.inc_counter("svc.dispatches", 3)
        second = hostgap.on_step(
            _step_span(0.1, [_span("e", "exec", 0.0, 0.05)]))
        assert second["dispatches"] == 1 + 3
        assert metrics.get_gauge("prof.dispatches_per_step") == 4.0
        summ = hostgap.summary()
        assert summ["steps"] == 2
        assert summ["step_p50_s"] == pytest.approx(0.1)

    def test_on_step_disabled_is_none(self):
        prof.set_enabled_override(False)
        assert hostgap.on_step(_step_span(0.1)) is None
        assert hostgap.summary()["steps"] == 0


# ------------------------------------------------------------------ mfu


class TestMFU:
    def _introspected(self, key):
        f = jax.jit(lambda x: jnp.tanh(x @ x).sum())
        ex = introspect.wrap(f, key=key, kind="step", workload=key)
        ex(jnp.full((32, 32), 0.5, jnp.float32))
        return introspect.get(key)["flops"]

    def test_mfu_exact_against_pinned_peak(self):
        flops = self._introspected("mfu_w")
        assert flops and flops > 0
        peak.set_peak_override(1.0)  # 1 TFLOP/s
        wall = 2.0
        root = _step_span(wall, [
            _span("exec.mfu_w", "exec", 0.0, 0.5, tenant="t0",
                  program="mfu_w"),
        ])
        mfu.on_step(root, hostgap.attribute(root))
        expect = min(flops / (wall * 1.0 * 1e12), 1.0)
        assert metrics.get_gauge("prof.mfu", {"workload": "mfu_w"}) == expect
        assert metrics.get_gauge("prof.mfu", {"tenant": "t0"}) == expect
        assert mfu.observed() == expect
        assert metrics.get_gauge("prof.flops_per_step") == flops

    def test_mfu_clamped_to_one(self):
        self._introspected("mfu_c")
        peak.set_peak_override(1e-12)  # absurdly slow "peak"
        root = _step_span(0.5, [
            _span("e", "exec", 0.0, 0.1, program="mfu_c")])
        mfu.on_step(root, hostgap.attribute(root))
        assert metrics.get_gauge("prof.mfu", {"workload": "mfu_c"}) == 1.0

    def test_peak_resolves_off_step_path(self, monkeypatch):
        # No cached peak yet: the step hook must skip MFU and kick the
        # (potentially benchmark-running) resolution onto a background
        # thread, then price normally once the denominator lands.
        monkeypatch.setattr(peak, "measured_peak_tflops", lambda: 1.0)
        monkeypatch.setattr(
            peak, "chip_peak_tflops", lambda device: None)
        peak.reset()
        flops = self._introspected("mfu_async")
        assert flops and flops > 0
        root = _step_span(2.0, [
            _span("e", "exec", 0.0, 0.5, program="mfu_async")])
        stats = hostgap.attribute(root)
        mfu.on_step(root, stats)  # peak unknown: skipped, kicked async
        assert metrics.get_gauge(
            "prof.mfu", {"workload": "mfu_async"}) is None
        thread = peak._measure_thread
        assert thread is not None
        thread.join(10)
        assert peak.cached_peak() == (1.0, "measured")
        mfu.on_step(root, stats)
        assert metrics.get_gauge("prof.mfu", {"workload": "mfu_async"}) \
            == pytest.approx(min(flops / (2.0 * 1e12), 1.0))

    def test_untraced_step_publishes_nothing(self):
        root = _step_span(0.5)  # no exec spans -> no FLOPs known
        mfu.on_step(root, hostgap.attribute(root))
        assert mfu.last() == {}
        assert mfu.observed() is None

    def test_publish_for_bench_records(self):
        assert mfu.publish("bench_w", 0.5, peak_tflops=2.0) == 0.25
        assert metrics.get_gauge(
            "prof.mfu", {"workload": "bench_w"}) == 0.25
        assert mfu.observed() == 0.25


# ------------------------------------------------------------- sentinel


class TestBaselineSentinel:
    SIG = ("wl",)

    def _key(self):
        return store_mod.make_key(self.SIG, kind="prof_baseline")

    def test_store_roundtrips_and_keeps_best(self, tmp_path):
        path = str(tmp_path / "prof_db.json")
        store = baseline.PerfBaselineStore(path)
        key = self._key()
        store.record_perf(key, step_p50_s=0.2, mfu_v=0.3)
        reopened = baseline.PerfBaselineStore(path)
        assert reopened.lookup(key)["step_p50_s"] == 0.2
        store.record_perf(key, step_p50_s=0.5)  # slower: keep-best wins
        assert store.lookup(key)["step_p50_s"] == 0.2
        store.record_perf(key, step_p50_s=0.1)  # faster: tightens
        assert store.lookup(key)["step_p50_s"] == 0.1

    def test_schedule_entries_rejected_by_shape(self, tmp_path):
        store = baseline.PerfBaselineStore(str(tmp_path / "db.json"))
        merged = store.merge({
            self._key(): {"bucket_bytes": 1, "wire": "f32",
                          "lowering": "flat", "score": 9.0},
        })
        assert merged == 0  # a schedule record is not a perf baseline

    def test_sentinel_verdict_ladder(self, tmp_path):
        store = baseline.PerfBaselineStore(str(tmp_path / "db.json"))
        sent = baseline.Sentinel(store)
        baseline.set_sentinel(sent)
        assert sent.check(self.SIG)["verdict"] == "no_data"
        hostgap.on_step(_step_span(0.2))
        assert sent.check(self.SIG)["verdict"] == "baseline_created"
        # identical run vs its own baseline: ok, gauge stays clear
        v = sent.check(self.SIG)
        assert v["verdict"] == "ok"
        assert metrics.get_gauge("prof.regression") == 0.0
        # pin a much faster baseline -> this run is a regression
        store.record_perf(self._key(), step_p50_s=0.01)
        v = sent.check(self.SIG)
        assert v["verdict"] == "regression" and v["slow"]
        assert metrics.get_gauge("prof.regression") == 1.0
        assert metrics.get_counter("prof.regressions") == 1
        assert v["baseline"]["step_p50_s"] == 0.01

    def test_mfu_drop_is_a_regression(self, tmp_path):
        store = baseline.PerfBaselineStore(str(tmp_path / "db.json"))
        sent = baseline.Sentinel(store)
        hostgap.on_step(_step_span(0.2))
        mfu.publish("wl", 0.1, peak_tflops=1.0)  # observed MFU 0.1
        store.record_perf(self._key(), step_p50_s=0.2, mfu_v=0.9)
        v = sent.check(self.SIG)
        assert v["verdict"] == "regression"
        assert v["mfu_drop"] and not v["slow"]

    def test_no_db_is_observe_only(self):
        sent = baseline.Sentinel(None)
        hostgap.on_step(_step_span(0.2))
        v = sent.check(self.SIG)
        assert v["verdict"] == "no_baseline"
        assert v["db"] is None
        assert sent.last()["verdict"] == "no_baseline"

    def test_auto_check_cadence(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HVD_TPU_PROF_CHECK_EVERY", "2")
        store = baseline.PerfBaselineStore(str(tmp_path / "db.json"))
        sent = baseline.Sentinel(store)
        baseline.set_sentinel(sent)
        hostgap.on_step(_step_span(0.1))
        baseline.drain_async()
        assert sent.last() is None  # step 1: below cadence
        hostgap.on_step(_step_span(0.1))
        baseline.drain_async()  # check runs off the step path
        assert sent.last() is not None  # step 2: sentinel ran
        assert sent.last()["verdict"] == "baseline_created"

    def test_capture_inert_without_dir(self):
        assert capture.maybe_capture("test") is False
        assert capture.stats()["active"] is False
        assert metrics.get_counter("prof.captures") == 0


# ------------------------------------------------------------- endpoint


class TestEndpoint:
    def _get(self, port, route):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=10) as resp:
            return resp.status, json.loads(resp.read())

    def test_prof_empty_plane_answers_200(self):
        srv = TelemetryServer(port=0, bind_host="127.0.0.1")
        try:
            code, data = self._get(srv.port, "/prof")
        finally:
            srv.stop()
        assert code == 200
        assert data["enabled"] is True
        assert data["programs"] == []
        assert data["host_gap"]["steps"] == 0
        assert data["baseline"] == {"db": None, "last": None}

    def test_prof_folds_worker_snapshots(self):
        mfu.publish("wl", 0.5, peak_tflops=1.0)
        hostgap.on_step(_step_span(0.1, [_span("e", "exec", 0.0, 0.05)]))
        snap = metrics.snapshot()
        srv = TelemetryServer(port=0, bind_host="127.0.0.1",
                              workers_fn=lambda: [(0, snap), (1, snap)])
        try:
            code, data = self._get(srv.port, "/prof")
        finally:
            srv.stop()
        assert code == 200
        assert set(data["ranks"]) == {"0", "1"}
        rank0 = data["ranks"]["0"]
        assert rank0["mfu"]["wl"] == 0.5
        assert rank0["dispatches_per_step"] == 1.0

    def test_health_carries_probe_verdict(self):
        srv = TelemetryServer(
            port=0, bind_host="127.0.0.1",
            health_fn=lambda: {"status": "ok", "round": 3},
            probe_fn=lambda: {"status": "sick",
                              "verdict": {"stage": "first_compute"}},
        )
        try:
            code, data = self._get(srv.port, "/health")
        finally:
            srv.stop()
        assert code == 200  # a sick probe never flips driver health
        assert data["round"] == 3
        assert data["probe"]["status"] == "sick"
        assert data["probe"]["verdict"]["stage"] == "first_compute"

    def test_probe_payload_pending_then_cached(self, monkeypatch):
        doctor = SimpleNamespace(diagnose=lambda: {
            "status": "ok", "verdict": None,
            "stages": [{"stage": "import", "status": "ok"}],
        })
        monkeypatch.setattr(
            telemetry_http, "_load_probe_doctor", lambda: doctor)
        telemetry_http.reset_probe_cache()
        try:
            first = telemetry_http.probe_payload()
            assert first["status"] in ("pending", "ok")
            deadline = time.monotonic() + 10
            while (telemetry_http.probe_payload()["status"] == "pending"
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            final = telemetry_http.probe_payload()
            assert final == {"status": "ok", "verdict": None,
                             "failing_stage": None, "stderr_tail": None}
        finally:
            telemetry_http.reset_probe_cache()


# ---------------------------------------------------------- retention


class TestDumpRetention:
    def _dump_n(self, rec, n):
        step = _span("step", "step", 0.0, 0.001)
        step.attrs = {"step": 1}
        for _ in range(n):
            rec.on_background(step)
            rec.dump("test")

    def test_prunes_oldest_beyond_keep(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HVD_TPU_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("HVD_TPU_TRACE_DUMP_KEEP", "3")
        rec = FlightRecorder(capacity=4)
        self._dump_n(rec, 6)
        files = sorted(f for f in os.listdir(tmp_path)
                       if f.endswith(".json"))
        assert len(files) == 3
        seqs = sorted(int(f.rsplit("_", 1)[1][:-5]) for f in files)
        assert seqs == [4, 5, 6]  # newest survive
        assert metrics.get_counter("trace.dumps_pruned") == 3

    def test_zero_keep_is_unbounded(self, monkeypatch, tmp_path):
        monkeypatch.setenv("HVD_TPU_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("HVD_TPU_TRACE_DUMP_KEEP", "0")
        rec = FlightRecorder(capacity=4)
        self._dump_n(rec, 5)
        assert len(os.listdir(tmp_path)) == 5
        assert metrics.get_counter("trace.dumps_pruned") == 0

    def test_default_keep(self):
        from horovod_tpu.trace import recorder
        assert recorder.dump_keep() == recorder.DEFAULT_DUMP_KEEP == 64


# -------------------------------------------------------- compile cost


class TestCompileCost:
    def test_cache_ranks_by_compile_bill(self):
        from horovod_tpu.svc.cache import CachedResponse, ResponseCache
        cache = ResponseCache(cap=8)
        cache.insert(("sig_cheap", 8), CachedResponse(
            program=SimpleNamespace(kind="tr"), compile_seconds=0.01))
        cache.insert(("sig_dear", 8), CachedResponse(
            program=SimpleNamespace(kind="hier"), compile_seconds=0.8))
        rows = cache.top_by_compile_cost()
        assert [r["kind"] for r in rows] == ["hier", "tr"]
        assert rows[0]["compile_seconds"] == 0.8
        assert rows[0]["axis_size"] == 8

    @pytest.mark.usefixtures("hvd_module")
    def test_service_accounts_lowering_cost(self):
        prog = xir.program("tr", [
            xir.all_reduce(WORLD_AXIS, reduce="mean", bucket=0,
                           nbytes=32, dtype="float32"),
        ])
        s = svc.get_service()
        s.submit(prog, [jnp.ones((N, 4), jnp.float32)],
                 producer="prof").result(timeout=60)
        s.drain(timeout_s=10)
        assert metrics.quantile("svc.compile_seconds", 0.5) is not None
        rows = s.cache.top_by_compile_cost()
        assert rows and rows[0]["compile_seconds"] > 0
        # the emission hook saw the dispatch too
        assert metrics.get_counter("prof.emissions") >= 1

    def test_note_emission_respects_off(self):
        prof.set_enabled_override(False)
        prof.note_emission("sched.tr", 4)
        assert metrics.get_counter("prof.emissions") == 0
        prof.set_enabled_override(True)
        prof.note_emission("sched.tr", 4)
        assert metrics.get_counter("prof.emissions") == 1
        assert metrics.get_gauge(
            "prof.emitted_ops", {"src": "sched.tr"}) == 4.0


# ------------------------------------------------------------ parity


@pytest.mark.usefixtures("hvd_module")
class TestBitwiseParity:
    def _losses(self):
        import optax
        from horovod_tpu.optim.distributed_optimizer import TrainStep

        def loss_fn(params, batch):
            x, y = batch
            return jnp.mean((x @ params["w"] - y) ** 2)

        step = TrainStep(loss_fn, optax.sgd(0.01), donate=False)
        params = {"w": jnp.ones((4, 2), jnp.float32)}
        state = step.init(params)
        x = jnp.arange(N * 4, dtype=jnp.float32).reshape(N, 4) / 32.0
        batch = (x, jnp.ones((N, 2), jnp.float32))
        losses = []
        for _ in range(3):
            params, state, loss = step(params, state, batch)
            losses.append(float(loss))
        return losses

    def test_prof_on_equals_off(self):
        prof.set_enabled_override(True)
        on = self._losses()
        prof.reset()
        prof.set_enabled_override(False)
        off = self._losses()
        assert on == off  # bitwise: profiling is host-side only

    def test_prof_on_populates_plane(self):
        prof.set_enabled_override(True)
        self._losses()
        assert metrics.get_counter("prof.compiles") >= 1
        payload = prof.prof_payload()
        assert payload["host_gap"]["steps"] >= 1
        assert payload["host_gap"]["dispatches_per_step"] >= 1
        assert any(r["workload"] == "train_step"
                   for r in payload["programs"])
