"""Framework-flavored elastic states (reference
``torch/elastic/state.py`` TorchState, ``tensorflow/elastic.py``
TensorFlowKerasState)."""

import numpy as np
import pytest

import horovod_tpu as hvd

torch = pytest.importorskip("torch")


def _torch_pair():
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    return model, opt


def _step(model, opt):
    x = torch.randn(8, 4)
    loss = model(x).pow(2).mean()
    opt.zero_grad()
    loss.backward()
    opt.step()


class TestTorchState:
    def test_commit_restore_roundtrip(self, hvd_module):
        from horovod_tpu.elastic import TorchState

        model, opt = _torch_pair()
        state = TorchState(model=model, optimizer=opt, epoch=3, batch=7)
        w0 = {k: v.clone() for k, v in model.state_dict().items()}
        state.commit()
        _step(model, opt)
        state.epoch = 9
        assert not torch.equal(model.weight, w0["weight"])
        state.restore()
        assert torch.equal(model.weight, w0["weight"])
        assert state.epoch == 3 and state.batch == 7

    def test_restore_rolls_back_optimizer_momentum(self, hvd_module):
        from horovod_tpu.elastic import TorchState

        model, opt = _torch_pair()
        _step(model, opt)  # populate momentum buffers
        state = TorchState(model=model, optimizer=opt)
        state.commit()
        mom0 = {
            k: v["momentum_buffer"].clone()
            for k, v in opt.state_dict()["state"].items()
        }
        _step(model, opt)
        state.restore()
        for k, buf in opt.state_dict()["state"].items():
            assert torch.equal(buf["momentum_buffer"], mom0[k])

    def test_sync_single_process(self, hvd_module):
        from horovod_tpu.elastic import TorchState

        model, opt = _torch_pair()
        state = TorchState(model=model, optimizer=opt, epoch=1)
        state.sync()  # no-op broadcastable path must not raise
        assert state.epoch == 1

    def test_serialize_roundtrip(self, hvd_module):
        from horovod_tpu.elastic import TorchState

        model, opt = _torch_pair()
        _step(model, opt)
        state = TorchState(model=model, optimizer=opt, epoch=5)
        blob = state._serialize()

        model2, opt2 = _torch_pair()
        state2 = TorchState(model=model2, optimizer=opt2, epoch=0)
        assert state2._deserialize(blob)
        assert state2.epoch == 5
        assert torch.equal(model2.weight, model.weight)


class TestTorchStateEdges:
    def test_bf16_model_serializes(self, hvd_module):
        from horovod_tpu.elastic import TorchState

        model = torch.nn.Linear(4, 2).to(torch.bfloat16)
        state = TorchState(model=model, epoch=1)
        blob = state._serialize()
        model2 = torch.nn.Linear(4, 2).to(torch.bfloat16)
        state2 = TorchState(model=model2, epoch=0)
        assert state2._deserialize(blob)
        assert model2.weight.dtype == torch.bfloat16
        assert torch.equal(model2.weight, model.weight)

    def test_deserialize_incompatible_model_rolls_back(self, hvd_module):
        from horovod_tpu.elastic import TorchState

        model = torch.nn.Linear(4, 2)
        state = TorchState(model=model, epoch=7)
        blob = state._serialize()
        other = torch.nn.Linear(8, 3)  # different shapes
        w0 = other.weight.clone()
        state2 = TorchState(model=other, epoch=0)
        assert not state2._deserialize(blob)
        assert state2.epoch == 0  # attrs untouched
        assert torch.equal(other.weight, w0)  # weights rolled back


class TestTensorFlowKerasState:
    def test_commit_restore_roundtrip(self, hvd_module):
        tf = pytest.importorskip("tensorflow")
        from horovod_tpu.elastic import TensorFlowKerasState

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(2, input_shape=(4,))]
        )
        opt = tf.keras.optimizers.SGD(0.1)
        state = TensorFlowKerasState(model=model, optimizer=opt, epoch=2)
        w0 = [w.copy() for w in model.get_weights()]
        state.commit()
        # perturb
        model.set_weights([w + 1.0 for w in model.get_weights()])
        state.epoch = 8
        state.restore()
        for a, b in zip(model.get_weights(), w0):
            np.testing.assert_allclose(a, b)
        assert state.epoch == 2

    def test_serialize_roundtrip(self, hvd_module):
        tf = pytest.importorskip("tensorflow")
        from horovod_tpu.elastic import TensorFlowKerasState

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(2, input_shape=(4,))]
        )
        state = TensorFlowKerasState(model=model, epoch=4)
        blob = state._serialize()

        model2 = tf.keras.Sequential(
            [tf.keras.layers.Dense(2, input_shape=(4,))]
        )
        state2 = TensorFlowKerasState(model=model2, epoch=0)
        assert state2._deserialize(blob)
        assert state2.epoch == 4
        for a, b in zip(model2.get_weights(), model.get_weights()):
            np.testing.assert_allclose(a, b)
