"""Eager collective tests — the analog of the collective × dtype × op
enumeration in reference ``test/parallel/test_torch.py`` (2448 LoC) and
``test_tensorflow.py``, against numpy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.exceptions import HorovodTpuError

N = 8
import ml_dtypes
DTYPES = [np.float32, np.float16, np.int32, ml_dtypes.bfloat16]


def stacked(shape=(4, 3), dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    if np.issubdtype(dtype, np.integer):
        return rng.randint(-10, 10, size=(N,) + shape).astype(dtype)
    return rng.randn(N, *shape).astype(dtype)


@pytest.mark.parametrize("dtype", DTYPES)
def test_allreduce_average(hvd_module, dtype):
    x = stacked(dtype=dtype)
    y = np.asarray(hvd.allreduce(x))
    expected = np.broadcast_to(x.mean(axis=0), x.shape)
    tol = 5e-2 if dtype in (np.float16, ml_dtypes.bfloat16) else 1e-5
    if np.issubdtype(dtype, np.integer):
        # average of ints stays int, truncated toward zero
        assert y.dtype == x.dtype
        np.testing.assert_array_equal(
            y,
            np.broadcast_to(
                np.trunc(x.sum(axis=0).astype(np.float32) / N).astype(dtype),
                x.shape,
            ),
        )
    else:
        np.testing.assert_allclose(y, expected.astype(dtype), rtol=tol, atol=tol)


@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_allreduce_sum(hvd_module, dtype):
    x = stacked(dtype=dtype)
    y = np.asarray(hvd.allreduce(x, op=hvd.Sum))
    expected = np.broadcast_to(x.sum(axis=0), x.shape)
    np.testing.assert_allclose(y, expected, rtol=1e-5, atol=1e-5)


def test_allreduce_min_max(hvd_module):
    x = stacked()
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Min)),
        np.broadcast_to(x.min(axis=0), x.shape),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Max)),
        np.broadcast_to(x.max(axis=0), x.shape),
        rtol=1e-6,
    )


def test_allreduce_product(hvd_module):
    x = stacked(shape=(2, 2))
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd.Product)),
        np.broadcast_to(np.prod(x, axis=0), x.shape),
        rtol=1e-4,
    )


def test_allreduce_prescale_postscale(hvd_module):
    x = stacked()
    y = np.asarray(
        hvd.allreduce(x, op=hvd.Sum, prescale_factor=0.5, postscale_factor=2.0)
    )
    expected = np.broadcast_to((x * 0.5).sum(axis=0) * 2.0, x.shape)
    np.testing.assert_allclose(y, expected, rtol=1e-5)


def test_allreduce_average_and_op_conflict(hvd_module):
    with pytest.raises(ValueError):
        hvd.allreduce(stacked(), average=True, op=hvd.Sum)


def test_allreduce_bad_shape(hvd_module):
    with pytest.raises(HorovodTpuError):
        hvd.allreduce(np.zeros((3, 2), np.float32))


def test_allreduce_process_set_partition(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2, 3])
    x = stacked()
    y = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps))
    expected = x.copy()
    expected[:4] = x[:4].sum(axis=0)
    np.testing.assert_allclose(y, expected, rtol=1e-5)
    hvd.remove_process_set(ps)


def test_allreduce_process_set_arbitrary(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([1, 4, 6])  # does not partition evenly
    x = stacked()
    y = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps))
    expected = x.copy()
    s = x[[1, 4, 6]].sum(axis=0)
    for r in (1, 4, 6):
        expected[r] = s
    np.testing.assert_allclose(y, expected, rtol=1e-5)
    hvd.remove_process_set(ps)


def test_allreduce_unregistered_process_set_rejected(hvd_module):
    ps = hvd.ProcessSet([0, 1])  # never registered
    with pytest.raises(HorovodTpuError, match="not registered"):
        hvd.allreduce(stacked(), process_set=ps)


def test_allreduce_removed_process_set_rejected(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1])
    hvd.remove_process_set(ps)
    with pytest.raises(HorovodTpuError, match="not registered"):
        hvd.allreduce(stacked(), process_set=ps)


def test_alltoall_splits_subset_shape_validated(hvd_module, monkeypatch):
    """Subset splits are supported now (member-indexed matrix); a
    world-shaped splits matrix for a 4-member set must be rejected."""
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2, 3])
    splits = np.full((N, N), 2)
    with pytest.raises(Exception, match="set_size"):
        hvd.alltoall(stacked(shape=(16, 2)), splits=splits, process_set=ps)
    hvd.remove_process_set(ps)


def test_grouped_allreduce(hvd_module):
    xs = [stacked(shape=(3,), seed=i) for i in range(4)]
    ys = hvd.grouped_allreduce(xs, op=hvd.Sum)
    for x, y in zip(xs, ys):
        np.testing.assert_allclose(
            np.asarray(y), np.broadcast_to(x.sum(axis=0), x.shape), rtol=1e-5
        )


def test_grouped_allreduce_mixed_dtypes(hvd_module):
    xs = [
        stacked(shape=(3,), dtype=np.float32, seed=1),
        stacked(shape=(5,), dtype=np.float16, seed=2),
        stacked(shape=(2, 2), dtype=np.float32, seed=3),
    ]
    ys = hvd.grouped_allreduce(xs, op=hvd.Sum)
    for x, y in zip(xs, ys):
        assert np.asarray(y).dtype == x.dtype
        np.testing.assert_allclose(
            np.asarray(y), np.broadcast_to(x.sum(axis=0), x.shape), rtol=1e-5
        )


def test_allgather(hvd_module):
    x = stacked(shape=(2, 3))
    y = np.asarray(hvd.allgather(x))
    # every output row is the concatenation over ranks
    assert y.shape == (N, N * 2, 3)
    expected = x.reshape(N * 2, 3)
    for r in range(N):
        np.testing.assert_allclose(y[r], expected, rtol=1e-6)


def test_allgather_process_set(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2, 3])
    x = stacked(shape=(2,))
    y = np.asarray(hvd.allgather(x, process_set=ps))
    assert y.shape == (N, 8)
    expected = x[:4].reshape(8)
    for r in range(4):
        np.testing.assert_allclose(y[r], expected, rtol=1e-6)
    hvd.remove_process_set(ps)


def test_broadcast(hvd_module):
    x = stacked()
    for root in (0, 3, 7):
        y = np.asarray(hvd.broadcast(x, root_rank=root))
        np.testing.assert_allclose(
            y, np.broadcast_to(x[root], x.shape), rtol=1e-6
        )


def test_broadcast_process_set(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([2, 5, 7])
    x = stacked()
    y = np.asarray(hvd.broadcast(x, root_rank=1, process_set=ps))  # root = rank 5
    expected = x.copy()
    for r in (2, 5, 7):
        expected[r] = x[5]
    np.testing.assert_allclose(y, expected, rtol=1e-6)
    hvd.remove_process_set(ps)


def test_reducescatter(hvd_module):
    x = stacked(shape=(16, 3))
    y = np.asarray(hvd.reducescatter(x, op=hvd.Sum))
    total = x.sum(axis=0)  # (16, 3)
    assert y.shape == (N, 2, 3)
    for r in range(N):
        np.testing.assert_allclose(y[r], total[r * 2 : (r + 1) * 2], rtol=1e-5)


def test_alltoall_equal(hvd_module):
    x = stacked(shape=(16, 2))
    y = np.asarray(hvd.alltoall(x))
    assert y.shape == x.shape
    # rank r chunk j -> rank j chunk r
    for r in range(N):
        for j in range(N):
            np.testing.assert_allclose(
                y[j, r * 2 : (r + 1) * 2], x[r, j * 2 : (j + 1) * 2], rtol=1e-6
            )


def test_alltoall_uneven(hvd_module):
    rng = np.random.RandomState(0)
    splits = rng.randint(0, 3, size=(N, N))
    d0 = int(splits.sum(axis=1).max())
    splits[:, 0] += d0 - splits.sum(axis=1)  # make rows equal length d0
    x = rng.randn(N, d0, 2).astype(np.float32)
    out, recv = hvd.alltoall(x, splits=splits)
    out, recv = np.asarray(out), np.asarray(recv)
    max_chunk = splits.max()
    for r in range(N):
        for j in range(N):
            c = splits[j, r]  # rank j sends c rows to rank r
            assert recv[r, j] == c
            sent = x[j, splits[j, :r].sum() : splits[j, :r].sum() + c]
            got = out[r, j * max_chunk : j * max_chunk + c]
            np.testing.assert_allclose(got, sent, rtol=1e-6)


def test_async_handles(hvd_module):
    x = stacked()
    h = hvd.allreduce_async(x, op=hvd.Sum, name="grad_0")
    y = hvd.synchronize(h)
    assert hvd.poll(h)
    np.testing.assert_allclose(
        np.asarray(y), np.broadcast_to(x.sum(axis=0), x.shape), rtol=1e-5
    )


def test_barrier_and_join(hvd_module):
    hvd.barrier()
    assert hvd.join() == N - 1


def test_traced_inside_shard_map(hvd_module):
    """Traced collectives compose inside a user shard_map (the hot path)."""
    from jax.sharding import PartitionSpec as P

    mesh = hvd.mesh()

    def step(x):
        g = hvd.traced.allreduce(x, op=hvd.Sum)
        return g

    f = jax.jit(
        jax.shard_map(step, mesh=mesh, in_specs=P(hvd.WORLD_AXIS), out_specs=P(hvd.WORLD_AXIS))
    )
    x = stacked(shape=(5,))
    y = np.asarray(f(x))
    np.testing.assert_allclose(y, np.broadcast_to(x.sum(0), x.shape), rtol=1e-5)


def test_compile_cache_reuse(hvd_module):
    """Second identical call must hit the compiled cache (ResponseCache
    analog)."""
    from horovod_tpu.ops import eager

    x = stacked()
    hvd.allreduce(x)
    before = eager._jitted_cache.cache_info().hits
    hvd.allreduce(x + 1)
    assert eager._jitted_cache.cache_info().hits > before


def test_hierarchical_allreduce_matches_flat(hvd_module):
    """reference NCCLHierarchicalAllreduce semantics: two-stage staging
    must produce the same sum as the flat psum (4 'local' x 2 'hosts')."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import runtime as rtm
    from horovod_tpu.ops import traced

    rt = rtm.get_runtime()
    old = rt.local_size, rt.cross_size
    rt.local_size, rt.cross_size = 4, 2
    try:
        x = np.arange(8 * 7, dtype=np.float32).reshape(8, 7)
        f = jax.jit(shard_map(
            lambda a: traced.allreduce(a, op=hvd.Sum, hierarchical=True),
            mesh=rt.mesh, in_specs=(P(hvd.WORLD_AXIS),),
            out_specs=P(hvd.WORLD_AXIS), check_vma=False,
        ))
        y = np.asarray(f(jnp.asarray(x)))
        np.testing.assert_allclose(y, np.tile(x.sum(axis=0), (8, 1)))
    finally:
        rt.local_size, rt.cross_size = old


def test_join_average_uneven_ranks(hvd_module):
    """SPMD Join semantics (reference JoinOp): ranks 5..7 are 'joined'
    (out of data); the average covers only the 5 active ranks."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops import traced

    rt_mesh = hvd.mesh()
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    active = np.asarray([1, 1, 1, 1, 1, 0, 0, 0], np.float32).reshape(8, 1)

    f = jax.jit(shard_map(
        lambda a, m: traced.join_average(a, m[0] > 0),
        mesh=rt_mesh, in_specs=(P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        out_specs=P(hvd.WORLD_AXIS), check_vma=False,
    ))
    y = np.asarray(f(jnp.asarray(x), jnp.asarray(active)))
    want = np.tile(x[:5].mean(axis=0), (8, 1))
    np.testing.assert_allclose(y, want, rtol=1e-6)


def test_join_average_none_active(hvd_module):
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops import traced

    x = np.ones((8, 2), np.float32)
    zero = np.zeros((8, 1), np.float32)
    f = jax.jit(shard_map(
        lambda a, m: traced.join_average(a, m[0] > 0),
        mesh=hvd.mesh(), in_specs=(P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        out_specs=P(hvd.WORLD_AXIS), check_vma=False,
    ))
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(x), jnp.asarray(zero))), 0.0)


def test_dispatch_cache_capacity_bounded(hvd_module, monkeypatch):
    """HVD_TPU_CACHE_CAPACITY bounds the compiled-dispatch LRU
    (reference HOROVOD_CACHE_CAPACITY, response_cache.h)."""
    from horovod_tpu.ops import eager

    eager.clear_cache()
    monkeypatch.setenv("HVD_TPU_CACHE_CAPACITY", "2")
    try:
        for d in (2, 3, 4, 5):  # four distinct signatures
            hvd.allreduce(np.ones((N, d), np.float32), op=hvd.Sum)
        info = eager._jitted_cache.cache_info()
        assert info.maxsize == 2
        assert info.currsize <= 2
    finally:
        eager.clear_cache()  # next dispatch re-reads the default env
