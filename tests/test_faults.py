"""Fault-injection registry + retry policy + hardened discovery
(``horovod_tpu/faults.py``, ``horovod_tpu/utils/retry.py``,
``horovod_tpu/elastic/discovery.py``).

Everything here is deterministic: plans are seeded, jitter comes from a
seeded RNG, cooldown clocks are injected.  The ``faults`` marker tags
the suite that guards the injection hooks against bit-rot (see
``tools/tier1_faultsmoke.sh``).
"""

import threading
import time

import pytest

from horovod_tpu import faults, metrics
from horovod_tpu.elastic.discovery import (
    FixedHosts,
    HostDiscoveryScript,
    HostManager,
)
from horovod_tpu.exceptions import FaultInjected, RetryTimeoutError
from horovod_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.set_plan(None)
    yield
    faults.set_plan(None)


# ---------------------------------------------------------------- parsing

class TestFaultPlanParsing:
    def test_parse_sites_kinds_args(self):
        plan = faults.FaultPlan.parse(
            "seed=7;discovery.script:error:nth=2;"
            "worker.step:crash:rank=1,round=2,code=9;"
            "checkpoint.write:corrupt:nth=1"
        )
        assert plan.seed == 7
        assert plan.sites() == [
            "checkpoint.write", "discovery.script", "worker.step",
        ]
        spec = plan._by_site["worker.step"][0]
        assert spec.kind == "crash"
        assert spec.code == 9
        assert spec.match == {"rank": 1, "round": 2}

    def test_flake_is_error_alias(self):
        plan = faults.FaultPlan.parse("a.b:flake")
        assert plan._by_site["a.b"][0].kind == "error"

    @pytest.mark.parametrize("bad", [
        "justasite", "a.b:nosuchkind", "a.b:error:oops",
    ])
    def test_malformed_entries_raise(self, bad):
        with pytest.raises(ValueError):
            faults.FaultPlan.parse(bad)

    def test_empty_plan_is_none(self):
        assert faults.set_plan("") is None
        assert faults.inject("anything") is False


# ----------------------------------------------------------- triggering

class TestDeterministicTriggering:
    def test_nth_fires_exactly_once(self):
        faults.set_plan("s:error:nth=2")
        assert faults.inject("s") is False
        with pytest.raises(FaultInjected):
            faults.inject("s")
        assert faults.inject("s") is False  # 3rd arrival: armed window past

    def test_nth_with_times_window(self):
        faults.set_plan("s:error:nth=2,times=2")
        assert faults.inject("s") is False
        for _ in range(2):
            with pytest.raises(FaultInjected):
                faults.inject("s")
        assert faults.inject("s") is False

    def test_times_without_nth_fires_first_n(self):
        faults.set_plan("s:error:times=2")
        for _ in range(2):
            with pytest.raises(FaultInjected):
                faults.inject("s")
        assert faults.inject("s") is False

    def test_context_selectors_gate_arrival_counting(self):
        faults.set_plan("s:error:rank=1,nth=1")
        # non-matching context neither fires nor consumes the arrival
        assert faults.inject("s", rank=0) is False
        assert faults.inject("s") is False  # missing key: no match
        with pytest.raises(FaultInjected):
            faults.inject("s", rank=1)

    def test_seeded_probability_is_reproducible(self):
        def pattern():
            plan = faults.FaultPlan.parse("seed=11;s:error:p=0.5,times=0")
            faults.set_plan(plan)
            fired = []
            for _ in range(32):
                try:
                    faults.inject("s")
                    fired.append(0)
                except FaultInjected:
                    fired.append(1)
            return fired

        a, b = pattern(), pattern()
        assert a == b
        assert 0 < sum(a) < 32  # actually probabilistic, not all-or-none

    def test_corrupt_returns_true(self):
        faults.set_plan("s:corrupt:nth=1")
        assert faults.inject("s") is True
        assert faults.inject("s") is False

    def test_slow_sleeps(self):
        faults.set_plan("s:slow:secs=0.05,times=1")
        t0 = time.perf_counter()
        assert faults.inject("s") is False
        assert time.perf_counter() - t0 >= 0.05

    def test_fired_counters_and_metrics(self):
        metrics.reset_counters("faults.")
        plan = faults.set_plan("s:error:times=2")
        for _ in range(2):
            with pytest.raises(FaultInjected):
                faults.inject("s")
        assert plan.counters() == {"s:error": 2}
        assert metrics.get_counter("faults.injected.s.error") == 2

    def test_env_plan_pickup_and_reset(self, monkeypatch):
        faults.reset()
        monkeypatch.setenv(faults.ENV_VAR, "s:corrupt:nth=1")
        assert faults.inject("s") is True
        faults.reset()
        monkeypatch.delenv(faults.ENV_VAR)
        assert faults.inject("s") is False


# -------------------------------------------------------------- retries

class TestRetryPolicy:
    def test_backoff_math_deterministic(self):
        pol = RetryPolicy(base_delay_s=0.1, multiplier=2.0,
                          max_delay_s=0.5, jitter=0.0)
        assert [pol.delay_s(k) for k in (1, 2, 3, 4, 5)] == \
            pytest.approx([0.1, 0.2, 0.4, 0.5, 0.5])

    def test_jitter_bounded_and_seeded(self):
        a = RetryPolicy(base_delay_s=1.0, jitter=0.25, seed=3)
        b = RetryPolicy(base_delay_s=1.0, jitter=0.25, seed=3)
        da = [a.delay_s(1) for _ in range(8)]
        db = [b.delay_s(1) for _ in range(8)]
        assert da == db
        assert all(0.75 <= d <= 1.25 for d in da)
        assert len(set(da)) > 1

    def test_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient")
            return "ok"

        slept = []
        pol = RetryPolicy(max_attempts=5, base_delay_s=0.01, seed=0,
                          sleep=slept.append, name="t_ok")
        assert pol.call(flaky) == "ok"
        assert len(calls) == 3
        assert len(slept) == 2

    def test_exhaustion_reraises_last_and_counts(self):
        metrics.reset_counters("retry.t_fail")

        def always():
            raise RuntimeError("perma")

        pol = RetryPolicy(max_attempts=3, base_delay_s=0.0,
                          sleep=lambda s: None, name="t_fail")
        with pytest.raises(RuntimeError, match="perma"):
            pol.call(always)
        got = metrics.get_counters("retry.t_fail")
        assert got == {
            "retry.t_fail.attempts": 3,
            "retry.t_fail.retries": 2,
            "retry.t_fail.exhausted": 1,
        }

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def typed():
            calls.append(1)
            raise KeyError("nope")

        pol = RetryPolicy(max_attempts=3, retry_on=(RuntimeError,),
                          sleep=lambda s: None)
        with pytest.raises(KeyError):
            pol.call(typed)
        assert len(calls) == 1

    def test_attempt_timeout_retries_hung_call(self):
        calls = []

        def hangs_once():
            calls.append(1)
            if len(calls) == 1:
                time.sleep(5.0)
            return "done"

        pol = RetryPolicy(max_attempts=2, attempt_timeout_s=0.2,
                          base_delay_s=0.0, sleep=lambda s: None)
        t0 = time.perf_counter()
        assert pol.call(hangs_once) == "done"
        assert time.perf_counter() - t0 < 2.0

    def test_attempt_timeout_exhausts_to_timeout_error(self):
        pol = RetryPolicy(max_attempts=2, attempt_timeout_s=0.05,
                          base_delay_s=0.0, sleep=lambda s: None)
        with pytest.raises(RetryTimeoutError):
            pol.call(time.sleep, 5.0)

    def test_on_retry_callback(self):
        seen = []

        def flaky():
            if not seen:
                raise RuntimeError("x")
            return 1

        pol = RetryPolicy(max_attempts=2, base_delay_s=0.0,
                          sleep=lambda s: None,
                          on_retry=lambda a, e, d: seen.append((a, str(e))))
        assert pol.call(flaky) == 1
        assert seen == [(1, "x")]


# ------------------------------------------ discovery retry + injection

class TestDiscoveryFaults:
    def test_discovery_flake_absorbed_by_retry(self):
        metrics.reset_counters("retry.discovery")
        faults.set_plan("discovery.script:flake:nth=1")
        disc = HostDiscoveryScript(
            "echo hostA:2; echo hostB", default_slots=3,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                              sleep=lambda s: None, name="discovery"),
        )
        assert disc.find_available_hosts_and_slots() == {
            "hostA": 2, "hostB": 3,
        }
        assert metrics.get_counter("retry.discovery.retries") == 1

    def test_discovery_persistent_failure_propagates(self):
        faults.set_plan("discovery.script:flake:times=0")
        disc = HostDiscoveryScript(
            "echo unused",
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                              sleep=lambda s: None, name="discovery"),
        )
        with pytest.raises(FaultInjected):
            disc.find_available_hosts_and_slots()

    def test_script_nonzero_exit_retried(self):
        # a script that fails on its first run and succeeds after: model
        # it with a state file toggled by the script itself
        import tempfile, os
        d = tempfile.mkdtemp()
        flag = os.path.join(d, "flag")
        script = (
            f"if [ -e {flag} ]; then echo host1; "
            f"else touch {flag}; exit 3; fi"
        )
        disc = HostDiscoveryScript(
            script,
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                              sleep=lambda s: None, name="discovery"),
        )
        assert disc.find_available_hosts_and_slots() == {"host1": 1}


# --------------------------------------------------- blacklist cooldown

class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


class TestBlacklistCooldown:
    def _manager(self, hosts, cooldown=10.0, cap=40.0):
        clock = FakeClock()
        mgr = HostManager(
            FixedHosts(hosts), cooldown_s=cooldown,
            cooldown_max_s=cap, clock=clock,
        )
        mgr.update_available_hosts()
        return mgr, clock

    def test_blacklist_and_cooldown_recovery(self):
        metrics.reset_counters("elastic.")
        mgr, clock = self._manager({"a": 1, "b": 1})
        mgr.blacklist("b")
        assert mgr.is_blacklisted("b")
        mgr.update_available_hosts()
        assert mgr.current_hosts == {"a": 1}
        clock.now += 10.1
        assert not mgr.is_blacklisted("b")
        assert mgr.update_available_hosts()  # change: b came back
        assert mgr.current_hosts == {"a": 1, "b": 1}
        assert metrics.get_counter("elastic.blacklist") == 1
        assert metrics.get_counter("elastic.unblacklist") == 1

    def test_repeat_failures_double_cooldown_capped(self):
        mgr, clock = self._manager({"a": 1}, cooldown=10.0, cap=25.0)
        for expect in (10.0, 20.0, 25.0, 25.0):  # doubled then capped
            mgr.blacklist("a")
            clock.now += expect - 0.1
            assert mgr.is_blacklisted("a"), expect
            clock.now += 0.2
            assert not mgr.is_blacklisted("a"), expect
            mgr.update_available_hosts()
        assert mgr.failure_count("a") == 4

    def test_zero_cooldown_is_permanent(self):
        mgr, clock = self._manager({"a": 1}, cooldown=0.0)
        mgr.blacklist("a")
        clock.now += 1e9
        assert mgr.is_blacklisted("a")
        mgr.update_available_hosts()
        assert mgr.current_hosts == {}


# ------------------------------------------- kill_at_step / resize_to

class TestKillAndResizeActions:
    """The remesh-test actions (docs/fault_tolerance.md): a crash
    pinned to one training-step boundary and a scripted world resize —
    both deterministic under a seeded plan."""

    def test_kill_at_step_is_crash_sugar_with_step_selector(self):
        plan = faults.FaultPlan.parse(
            "worker.commit:kill_at_step:step=5,code=9"
        )
        spec = plan._by_site["worker.commit"][0]
        assert spec.kind == "crash"
        assert spec.code == 9
        assert spec.match == {"step": 5}

    def test_kill_at_step_requires_step(self):
        with pytest.raises(ValueError, match="step=K"):
            faults.FaultPlan.parse("worker.commit:kill_at_step")

    def test_kill_at_step_fires_only_on_that_step(self):
        """Armed via set_plan; the crash is observed through the fired
        counter (we must not os._exit the test process, so we count
        arrivals against a selector that never matches this run)."""
        plan = faults.FaultPlan.parse(
            "worker.commit:kill_at_step:step=5,code=9"
        )
        spec = plan._by_site["worker.commit"][0]
        # simulate the commit counter: only step=5 matches
        import random

        rng = random.Random(0)
        fires = [
            spec.should_fire({"step": s}, rng) for s in range(1, 9)
        ]
        assert fires == [False] * 4 + [True] + [False] * 3

    def test_resize_to_requires_np(self):
        with pytest.raises(ValueError, match="np=N"):
            faults.FaultPlan.parse("discovery.resize:resize_to")

    def test_resize_to_returns_target(self):
        faults.set_plan("discovery.resize:resize_to:np=3,nth=2")
        assert faults.inject("discovery.resize") is False
        got = faults.inject("discovery.resize")
        assert got == {"np": 3}
        assert faults.inject("discovery.resize") is False

    def test_resize_to_reshapes_discovered_world(self):
        """HostManager consumes the action: the discovered slot total
        rescales to exactly np, deterministically."""
        mgr = HostManager(FixedHosts({"a": 2, "b": 2}), cooldown_s=30)
        mgr.update_available_hosts()
        assert mgr.available_slots() == 4
        # arm() short-circuits at the first firing spec, so the second
        # entry's arrival counter starts once the first has fired:
        # nth counts each spec's OWN matching arrivals.
        faults.set_plan(
            "discovery.resize:resize_to:np=3,nth=1;"
            "discovery.resize:resize_to:np=5,nth=1"
        )
        changed = mgr.update_available_hosts()
        assert changed
        assert mgr.available_slots() == 3
        assert mgr.current_hosts == {"a": 1, "b": 2}  # trimmed a first
        changed = mgr.update_available_hosts()
        assert changed
        assert mgr.available_slots() == 5

    def test_rescale_hosts_edge_cases(self):
        from horovod_tpu.elastic.discovery import _rescale_hosts

        assert _rescale_hosts({"a": 4}, 1) == {"a": 1}
        assert _rescale_hosts({"a": 1, "b": 1}, 4) == {"a": 3, "b": 1}
        assert _rescale_hosts({"a": 2, "b": 1}, 2) == {"b": 1, "a": 1}
        assert _rescale_hosts({}, 2) == {"localhost": 2}

    def test_commit_site_carries_step_counter(self):
        """State.commit is the kill_at_step anchor: its injection
        context advances with every commit."""
        from horovod_tpu.elastic.state import ObjectState
        from horovod_tpu.exceptions import FaultInjected

        faults.set_plan("worker.commit:error:step=2")
        state = ObjectState(epoch=0)
        state.commit()  # step=1: no match
        with pytest.raises(FaultInjected):
            state.commit()  # step=2: fires


# ----------------------------------------------------- thread soundness

def test_inject_is_thread_safe_under_contention():
    faults.set_plan("s:error:nth=50")
    fired = []

    def worker():
        for _ in range(25):
            try:
                faults.inject("s")
            except FaultInjected:
                fired.append(1)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(fired) == 1  # exactly one arrival was the 50th
