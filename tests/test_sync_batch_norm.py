"""SyncBatchNorm: cross-replica moments (reference
``torch/sync_batch_norm.py`` forward math, ``:120-160``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.runtime import WORLD_AXIS

N = 8
F = 4


@pytest.fixture(autouse=True)
def _init(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    hvd.init()
    yield
    hvd.shutdown()


def _mesh():
    from horovod_tpu.runtime import get_runtime

    return get_runtime().mesh


def _apply_sharded(bn, variables, x, in_set=True):
    def fwd(v, xb):
        out, updated = bn.apply(
            v, xb, use_running_average=False, mutable=["batch_stats"]
        )
        return out, updated["batch_stats"]

    f = jax.jit(
        shard_map(
            fwd, mesh=_mesh(), in_specs=(P(), P(WORLD_AXIS)),
            out_specs=(P(WORLD_AXIS), P()), check_vma=False,
        )
    )
    return f(variables, x)


def test_moments_match_global_batch():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, F) * 3 + 1, jnp.float32)
    bn = hvd.SyncBatchNorm()
    variables = bn.init(jax.random.PRNGKey(0), x[:2],
                        use_running_average=True)
    out, stats = _apply_sharded(bn, variables, x)
    # normalized output over the GLOBAL batch: zero mean, unit var
    np.testing.assert_allclose(np.asarray(out).mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out).std(axis=0), 1.0, atol=1e-3)
    # running stats moved toward the global batch moments
    gm = np.asarray(x).mean(axis=0)
    expect_mean = 0.99 * 0.0 + 0.01 * gm
    np.testing.assert_allclose(
        np.asarray(stats["mean"]), expect_mean, rtol=1e-4, atol=1e-6
    )


def test_grads_flow_through_collective():
    x = jnp.asarray(np.random.RandomState(1).randn(16, F), jnp.float32)
    bn = hvd.SyncBatchNorm()
    variables = bn.init(jax.random.PRNGKey(0), x[:2],
                        use_running_average=True)

    def loss(v, xb):
        def body(params, xs):
            out, _ = bn.apply(
                {"params": params, "batch_stats": v["batch_stats"]}, xs,
                use_running_average=False, mutable=["batch_stats"],
            )
            return jnp.sum(out ** 2), None

        f = shard_map(
            lambda p, xs: body(p, xs)[0], mesh=_mesh(),
            in_specs=(P(), P(WORLD_AXIS)), out_specs=P(),
            check_vma=False,
        )
        return f(v["params"], xb)

    g = jax.jit(jax.grad(loss))(dict(variables), x)
    assert float(jnp.abs(g["params"]["scale"]).sum()) > 0


def test_arbitrary_process_set_subset_moments():
    """A 3-of-8 set syncs only among members — impossible with XLA
    replica-group partitions, handled by the traced lowering."""
    members = [0, 2, 5]
    ps = hvd.add_process_set(members)
    rng = np.random.RandomState(2)
    # per-rank distinct data, 2 rows each
    x = jnp.asarray(rng.randn(16, F) * 2 + 3, jnp.float32)
    bn = hvd.SyncBatchNorm(process_set=ps)
    variables = bn.init(jax.random.PRNGKey(0), x[:2],
                        use_running_average=True)
    out, _ = _apply_sharded(bn, variables, x)
    out = np.asarray(out)
    xs = np.asarray(x).reshape(N, 2, F)
    member_rows = xs[members].reshape(-1, F)
    m = member_rows.mean(axis=0)
    v = member_rows.var(axis=0)
    expect = (xs[2] - m) / np.sqrt(v + 1e-5)
    np.testing.assert_allclose(
        out.reshape(N, 2, F)[2], expect, rtol=1e-3, atol=1e-4
    )
    # non-member normalizes with ITS OWN local moments (pass-through
    # allreduce returns the local sums)
    local = xs[3]
    expect_local = (local - local.mean(0)) / np.sqrt(local.var(0) + 1e-5)
    np.testing.assert_allclose(
        out.reshape(N, 2, F)[3], expect_local, rtol=1e-3, atol=1e-4
    )
    hvd.remove_process_set(ps)


def test_eval_mode_uses_running_stats():
    x = jnp.asarray(np.random.RandomState(3).randn(8, F), jnp.float32)
    bn = hvd.SyncBatchNorm(use_running_average=True)
    variables = bn.init(jax.random.PRNGKey(0), x)
    out = bn.apply(variables, x)  # outside shard_map: fine in eval
    # running stats are identity-init: output == scale*x + bias == x
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x) / np.sqrt(1 + 1e-5), rtol=1e-5
    )


def test_outside_shard_map_degrades_to_local():
    x = jnp.asarray(np.random.RandomState(4).randn(8, F), jnp.float32)
    bn = hvd.SyncBatchNorm()
    variables = bn.init(jax.random.PRNGKey(0), x, use_running_average=True)
    out, _ = bn.apply(variables, x, use_running_average=False,
                      mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(out).mean(0), 0.0, atol=1e-5)
