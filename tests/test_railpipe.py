"""XIR rail pipeliner unit tests (xir/pipeline.py + its hooks).

The execution-parity column lives in
tests/test_collective_matrix.py::TestPipelineColumn; this file covers
the pass itself: the knob, engagement rules, the max-of-rails pricing
and split-point search, the cross-workload merge rules, the plan-stage
hook, ZeRO-1 / grad-sync parity under the rail chains, tuner
exploration with tune-DB persistence, and the store fingerprint fold.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import metrics, sched, topo, xir
from horovod_tpu.exceptions import HorovodTpuError
from horovod_tpu.topo import model as topo_model
from horovod_tpu.xir import pipeline as railpipe

pytestmark = pytest.mark.railpipe


@pytest.fixture(autouse=True)
def _clean():
    yield
    railpipe.set_mode_override(None)
    sched.set_config_override(None)


@pytest.fixture()
def two_slice(monkeypatch):
    monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
    topo.reset()
    yield
    topo.reset()


def _bucket(nbytes, lowering="hier", wire="off", dtypes=("float32",)):
    from horovod_tpu.sched.plan import Bucket

    return Bucket(indices=(0,), nbytes=nbytes, wire_dtypes=tuple(dtypes),
                  wire=wire, lowering=lowering)


class _Sched:
    def __init__(self, buckets):
        self.buckets = tuple(buckets)


# ----------------------------------------------------------- the knob

class TestKnob:
    def test_default_is_auto(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_XIR_PIPELINE", raising=False)
        assert railpipe.mode() == "auto"

    @pytest.mark.parametrize("raw,want", [
        ("off", "off"), ("0", "off"), ("false", "off"),
        ("on", "on"), ("1", "on"), ("auto", "auto"), ("AUTO", "auto"),
    ])
    def test_spellings(self, monkeypatch, raw, want):
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", raw)
        assert railpipe.mode() == want

    def test_bad_spelling_raises(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", "sideways")
        with pytest.raises(HorovodTpuError, match="XIR_PIPELINE"):
            railpipe.mode()

    def test_override_wins_and_validates(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", "off")
        railpipe.set_mode_override("on")
        assert railpipe.mode() == "on"
        with pytest.raises(HorovodTpuError):
            railpipe.set_mode_override("diagonal")


# --------------------------------------------------------- engagement

class TestEngagement:
    def test_off_never_engages(self, two_slice):
        railpipe.set_mode_override("off")
        s = _Sched([_bucket(1 << 20), _bucket(1 << 20)])
        assert not railpipe.engaged(s, 8)

    def test_needs_two_decomposable_buckets(self, two_slice):
        railpipe.set_mode_override("on")
        assert not railpipe.engaged(_Sched([_bucket(1 << 20)]), 8)
        assert railpipe.engaged(
            _Sched([_bucket(1 << 20), _bucket(1 << 20)]), 8
        )

    def test_hier_adasum_and_flat_not_decomposable(self):
        assert railpipe.decomposable(_bucket(1, "hier"))
        assert not railpipe.decomposable(_bucket(1, "hier_adasum"))
        assert not railpipe.decomposable(_bucket(1, "flat"))
        assert not railpipe.decomposable(
            _bucket(1, "hier", dtypes=("float32", "bfloat16"))
        )

    def test_auto_engages_on_multi_slice(self, two_slice):
        railpipe.set_mode_override("auto")
        s = _Sched([_bucket(1 << 22), _bucket(1 << 22)])
        assert railpipe.engaged(s, 8)

    def test_single_slice_never_engages(self):
        # default topology of the 8-CPU world: one slice, so plans
        # resolve flat and nothing decomposes
        railpipe.set_mode_override("on")
        s = sched.build_schedule(
            [1 << 20] * 4, ["float32"] * 4,
            sched.SchedConfig(bucket_bytes=1 << 20),
        )
        assert not railpipe.engaged(s, 8)


# ------------------------------------------------------------ pricing

class TestPricing:
    def test_pipelined_bounds(self, two_slice):
        items = [("all_reduce", 1 << 22, "hier")] * 4
        serial = railpipe.estimate_schedule_cost(items, 8)
        pipe = railpipe.estimate_schedule_cost(items, 8, pipelined=True)
        splits = [railpipe.rail_times(*i, 8) for i in items]
        max_rail = max(sum(s[0] for s in splits),
                       sum(s[1] for s in splits))
        assert max_rail <= pipe < serial

    def test_rail_times_sum_to_estimate(self, two_slice):
        t = topo_model.current()
        for lowering in ("flat", "hier", "hier_adasum"):
            ici, dcn = t.rail_times("all_reduce", 1 << 20, lowering, 8)
            assert abs(
                (ici + dcn)
                - t.estimate_cost("all_reduce", 1 << 20, lowering, 8)
            ) < 1e-12

    def test_estimate_program_cost_hook(self, two_slice):
        prog = xir.program("dense_grad", [
            xir.all_reduce("hvd", lowering="hier", nbytes=1 << 22,
                           dtype="float32", bucket=i)
            for i in range(3)
        ])
        serial = xir.estimate_program_cost(prog, 8, pipelined=False)
        pipe = xir.estimate_program_cost(prog, 8, pipelined=True)
        assert 0 < pipe < serial

    def test_empty_schedule_costs_zero(self):
        assert railpipe.estimate_schedule_cost([], 8) == 0.0
        assert railpipe.estimate_schedule_cost(
            [], 8, pipelined=True
        ) == 0.0


# ------------------------------------------------------- split points

class TestSplitPoints:
    def test_suggests_only_under_on(self, two_slice):
        railpipe.set_mode_override("auto")
        assert railpipe.plan_bucket_bytes(1 << 24, 8) is None
        railpipe.set_mode_override("on")
        b = railpipe.plan_bucket_bytes(1 << 24, 8)
        assert b is not None and 65536 <= b <= (1 << 23)

    def test_single_slice_declines(self):
        railpipe.set_mode_override("on")
        topo.set_topology_override(
            topo_model.Topology(num_slices=1, slice_size=8)
        )
        try:
            assert railpipe.plan_bucket_bytes(1 << 24, 8) is None
        finally:
            topo.set_topology_override(None)

    def test_tiny_payload_declines(self, two_slice):
        railpipe.set_mode_override("on")
        assert railpipe.plan_bucket_bytes(1024, 8) is None

    def test_plan_stage_adopts_split(self, two_slice):
        """build_schedule with no pinned size splits under on-mode —
        and produces the identical (unsplit) plan under auto."""
        sizes = [1 << 22] * 8  # 32 MiB of gradients
        cfg = sched.SchedConfig(bucket_bytes=None, lowering="hier")
        railpipe.set_mode_override("auto")
        auto_plan = sched.build_schedule(sizes, ["float32"] * 8, cfg,
                                         axis_size=8)
        railpipe.set_mode_override("off")
        off_plan = sched.build_schedule(sizes, ["float32"] * 8, cfg,
                                        axis_size=8)
        assert auto_plan.signature() == off_plan.signature()
        railpipe.set_mode_override("on")
        on_plan = sched.build_schedule(sizes, ["float32"] * 8, cfg,
                                       axis_size=8)
        assert len(on_plan) >= 2  # a pipeline to run
        assert on_plan.total_bytes == off_plan.total_bytes


# -------------------------------------------------------------- merge

class TestMerge:
    def _dense(self, lowering="flat", axis="hvd"):
        return xir.program("dense_grad", [
            xir.all_reduce(axis, lowering=lowering, nbytes=1 << 22,
                           dtype="float32", bucket=i) for i in range(2)
        ])

    def _a2a_subgroup(self):
        # slice-local subgroups: ICI-only traffic
        groups = tuple(tuple(range(j * 4, (j + 1) * 4))
                       for j in range(2))
        return xir.program("moe", [xir.all_to_all(
            "hvd", split_axis=0, concat_axis=1, groups=groups,
            nbytes=1 << 18, dtype="float32",
        )])

    def test_rails_disjoint_dcn_vs_ici(self, two_slice):
        dense = xir.lower_program(self._dense("flat"), 8, store=False)
        a2a = xir.lower_program(self._a2a_subgroup(), 8, store=False)
        assert railpipe.program_rails(dense, 8) == frozenset({"dcn"})
        assert railpipe.program_rails(a2a, 8) == frozenset({"ici"})
        assert railpipe.rails_disjoint(dense, a2a, 8)

    def test_merge_declines_shared_rails(self, two_slice):
        railpipe.set_mode_override("on")
        hier = xir.lower_program(self._dense("hier"), 8, store=False)
        a2a = xir.lower_program(self._a2a_subgroup(), 8, store=False)
        assert railpipe.merge([hier, a2a], 8) is None  # hier = both rails
        assert railpipe.merge([hier], 8) is None  # one program

    def test_merge_declines_when_off(self, two_slice):
        railpipe.set_mode_override("off")
        dense = xir.lower_program(self._dense("flat"), 8, store=False)
        a2a = xir.lower_program(self._a2a_subgroup(), 8, store=False)
        assert railpipe.merge([dense, a2a], 8) is None

    def test_merge_interleaves_rails(self, two_slice):
        railpipe.set_mode_override("on")
        dense = xir.lower_program(self._dense("flat"), 8, store=False)
        a2a = xir.lower_program(self._a2a_subgroup(), 8, store=False)
        merged = railpipe.merge([dense, a2a], 8)
        assert merged is not None
        assert merged.kind == "dense_grad+moe"
        assert len(merged.ops) == 3
        rails = [railpipe.op_rail(op, 8) for op in merged.ops]
        # the ICI rider lands between the two DCN buckets
        assert rails[0] != rails[1]
        assert [op.bucket for op in merged.ops] == [0, 1, 2]
        # deterministic: same inputs, same order
        again = railpipe.merge([dense, a2a], 8)
        assert again.signature() == merged.signature()


# ------------------------------------------- zero1 / grad_sync parity

class TestRailParity:
    def _losses_zero1(self, mode, hvdm):
        import optax

        railpipe.set_mode_override(mode)
        cfg = sched.SchedConfig(enabled=True, bucket_bytes=16 * 1024,
                                lowering="hier")
        rng = np.random.RandomState(5)
        X = rng.randn(16, 32).astype(np.float32)
        Y = rng.randn(16, 4).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] - y) ** 2)

        p = {"w": jnp.asarray(
            np.random.RandomState(2).randn(32, 4).astype(np.float32)
        )}
        step = sched.bucketed_zero_step(loss_fn, optax_sgd(), cfg=cfg)
        st = step.init(p)
        batch = (jnp.asarray(X), jnp.asarray(Y))
        losses = []
        for _ in range(4):
            p, st, loss = step(p, st, batch)
            losses.append(float(loss))
        return losses

    def test_bucketed_zero_step_bitwise(self, hvd_module, two_slice):
        off = self._losses_zero1("off", hvd_module)
        on = self._losses_zero1("on", hvd_module)
        assert off == on

    def test_grad_sync_bucketed_bitwise(self, hvd_module, two_slice):
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.runtime import WORLD_AXIS, get_runtime
        from horovod_tpu.sched.execute import sync_gradients_bucketed

        g = {"a": np.random.RandomState(9).randn(8, 64)
             .astype(np.float32)}
        cfg = sched.SchedConfig(enabled=True, bucket_bytes=64,
                                lowering="hier")

        def f(grads):
            return sync_gradients_bucketed(grads, None, (WORLD_AXIS,),
                                           cfg)

        def run():
            return np.asarray(jax.jit(jax.shard_map(
                f, mesh=get_runtime().mesh,
                in_specs=({"a": P(WORLD_AXIS)},),
                out_specs={"a": P(WORLD_AXIS)}, check_vma=False,
            ))(g)["a"])

        railpipe.set_mode_override("off")
        off = run()
        railpipe.set_mode_override("on")
        on = run()
        np.testing.assert_array_equal(off, on)


def optax_sgd():
    import optax

    return optax.sgd(0.05)


# ----------------------------------------------------- tuner + store

class TestTunerPipelineKnob:
    SIG = ("railpipe-test-sig", 1)

    def _drive(self, tuner, favored="on", windows=16):
        for _ in range(windows):
            if tuner.converged:
                break
            tuner.begin_window()
            cand = tuner.pipeline()
            steps = 30 if cand == favored else 10
            metrics.inc_counter("train.steps", steps)
            metrics.observe("train.step_seconds", 0.5)
            metrics.set_gauge("sched.bytes_per_step", 1000.0)
            tuner.end_window()
        return tuner

    def test_explores_and_freezes_winner(self, two_slice, monkeypatch):
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", "auto")
        tuner = sched.ScheduleTuner(explore_pipeline=True,
                                    warmup_windows=2)
        assert not tuner.converged
        seen = set()
        for _ in range(3):
            tuner.begin_window()
            seen.add(tuner.pipeline())
            metrics.inc_counter(
                "train.steps", 30 if tuner.pipeline() == "on" else 10
            )
            metrics.observe("train.step_seconds", 0.5)
            metrics.set_gauge("sched.bytes_per_step", 1000.0)
            tuner.end_window()
        assert seen == {"off", "on", "auto"}  # every candidate ran
        assert tuner._pipeline_frozen == "on"
        # the winner is pinned into the env knob for the trace
        assert railpipe.mode() == "on"

    def test_single_slice_pins_off(self):
        topo.set_topology_override(
            topo_model.Topology(num_slices=1, slice_size=8)
        )
        try:
            tuner = sched.ScheduleTuner(explore_pipeline=True)
            assert tuner.pipeline() == "off"
        finally:
            topo.set_topology_override(None)

    def test_cold_db_converges_to_pipelined_and_warm_starts(
            self, two_slice, tmp_path, monkeypatch):
        """The acceptance loop: a cold DB explores, the pipelined
        candidate wins, the winner persists (meta.pipeline), and a
        second tuner warm-starts already pipelined at window 0."""
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", "auto")
        db = tmp_path / "tune.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        t1 = sched.ScheduleTuner(explore_pipeline=True,
                                 warmup_windows=2, store="env",
                                 store_key=self.SIG)
        self._drive(t1, favored="on")
        assert t1.converged
        assert t1.pipeline() == "on"
        entries = json.loads(db.read_text())["entries"]
        assert any(
            (e.get("meta") or {}).get("pipeline") == "on"
            for e in entries.values()
        )
        # warm start: converged at window 0, knob re-pinned
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", "auto")
        t2 = sched.ScheduleTuner(explore_pipeline=True, store="env",
                                 store_key=self.SIG)
        assert t2.converged
        assert t2.pipeline() == "on"
        assert railpipe.mode() == "on"

    def test_fingerprint_folds_resolved_mode(self, monkeypatch):
        from horovod_tpu.sched.store import knob_fingerprint

        monkeypatch.delenv("HVD_TPU_XIR_PIPELINE", raising=False)
        unset = knob_fingerprint()
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", "auto")
        assert knob_fingerprint() == unset  # unset ≡ explicit default
        monkeypatch.setenv("HVD_TPU_XIR_PIPELINE", "on")
        assert knob_fingerprint() != unset  # split points differ
