"""Collective x dtype x process-set sweep and error-case matrix.

Models the reference's exhaustive parallel test enumeration
(``test/parallel/test_torch.py`` — allreduce/allgather/broadcast across
every supported dtype, process-set variants, and typed error cases)."""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.exceptions import HorovodTpuError

N = 8

DTYPES = [
    np.float32, np.float64, np.float16, jnp.bfloat16,
    np.int32, np.int64, np.int8, np.uint8,
]


def _tol(dtype):
    if dtype in (np.float16, jnp.bfloat16):
        return dict(rtol=1e-2, atol=1e-2)
    # float64 silently downcasts to f32 under JAX's default x64-disabled
    # mode, so exact comparison is off the table for it too.
    return dict(rtol=1e-5, atol=1e-6)


def _is_float(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def _data(dtype, shape=(N, 5), seed=0):
    rng = np.random.RandomState(seed)
    if _is_float(dtype):
        return rng.uniform(-2, 2, shape).astype(dtype)
    return rng.randint(0, 7, shape).astype(dtype)


class TestDtypeSweep:
    @pytest.mark.parametrize("dtype", DTYPES, ids=str)
    def test_allreduce_sum(self, hvd_module, dtype):
        x = _data(dtype)
        y = np.asarray(hvd.allreduce(x, op=hvd.Sum)).astype(np.float64)
        expect = np.asarray(x).astype(np.float64).sum(axis=0)
        for r in range(N):
            np.testing.assert_allclose(y[r], expect, **_tol(dtype))

    @pytest.mark.parametrize("dtype", DTYPES, ids=str)
    def test_allreduce_average(self, hvd_module, dtype):
        x = _data(dtype, seed=1)
        y = np.asarray(hvd.allreduce(x, average=True)).astype(np.float64)
        expect = np.asarray(x).astype(np.float64).mean(axis=0)
        if not _is_float(dtype):
            # integer average truncates like the reference's int path
            expect = np.trunc(expect)
        for r in range(N):
            np.testing.assert_allclose(y[r], expect, **_tol(dtype))

    @pytest.mark.parametrize("dtype", DTYPES, ids=str)
    @pytest.mark.parametrize("opname", ["min", "max"])
    def test_allreduce_minmax(self, hvd_module, dtype, opname):
        x = _data(dtype, seed=2)
        op = hvd.Min if opname == "min" else hvd.Max
        y = np.asarray(hvd.allreduce(x, op=op)).astype(np.float64)
        red = np.min if opname == "min" else np.max
        expect = red(np.asarray(x).astype(np.float64), axis=0)
        for r in range(N):
            np.testing.assert_allclose(y[r], expect, **_tol(dtype))

    @pytest.mark.parametrize("dtype", DTYPES, ids=str)
    def test_allgather(self, hvd_module, dtype):
        x = _data(dtype, shape=(N, 2, 3), seed=3)
        y = np.asarray(hvd.allgather(x))
        expect = np.asarray(x).reshape(N * 2, 3).astype(np.float64)
        for r in range(N):
            np.testing.assert_allclose(
                y[r].astype(np.float64), expect, **_tol(dtype)
            )

    @pytest.mark.parametrize("dtype", DTYPES, ids=str)
    def test_broadcast(self, hvd_module, dtype):
        x = _data(dtype, seed=4)
        y = np.asarray(hvd.broadcast(x, root_rank=3))
        for r in range(N):
            np.testing.assert_allclose(
                y[r].astype(np.float64),
                np.asarray(x)[3].astype(np.float64), **_tol(dtype)
            )

    @pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16, np.int32],
                             ids=str)
    def test_alltoall(self, hvd_module, dtype):
        x = _data(dtype, shape=(N, N, 2), seed=5)
        y = np.asarray(hvd.alltoall(x))
        for r in range(N):
            for j in range(N):
                np.testing.assert_array_equal(y[r, j], np.asarray(x)[j, r])

    @pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int32],
                             ids=str)
    def test_reducescatter(self, hvd_module, dtype):
        x = _data(dtype, shape=(N, N, 3), seed=6)
        y = np.asarray(hvd.reducescatter(x, op=hvd.Sum)).astype(np.float64)
        full = np.asarray(x).astype(np.float64).sum(axis=0)
        for r in range(N):  # rank r's shard keeps the leading dim: (1, 3)
            np.testing.assert_allclose(y[r], full[r : r + 1], **_tol(dtype))


class TestAllgatherV:
    def test_ragged_first_dims(self, hvd_module):
        rng = np.random.RandomState(0)
        xs = [rng.randn(r + 1, 3).astype(np.float32) for r in range(N)]
        out = np.asarray(hvd.allgather_v(xs))
        expect = np.concatenate(xs, axis=0)
        assert out.shape == (N * (N + 1) // 2, 3)
        np.testing.assert_allclose(out, expect, rtol=1e-6)

    def test_ragged_with_empty_rank(self, hvd_module):
        xs = [np.ones((2, 2), np.float32) for _ in range(N)]
        xs[3] = np.zeros((0, 2), np.float32)  # a rank with no rows
        out = np.asarray(hvd.allgather_v(xs))
        assert out.shape == ((N - 1) * 2, 2)
        np.testing.assert_allclose(out, 1.0)

    def test_ragged_subset(self, hvd_module, monkeypatch):
        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        ps = hvd.add_process_set([0, 1, 2])
        xs = [np.full((r + 1, 2), float(r), np.float32) for r in range(N)]
        out = np.asarray(hvd.allgather_v(xs, process_set=ps))
        expect = np.concatenate([xs[0], xs[1], xs[2]], axis=0)
        np.testing.assert_allclose(out, expect)
        hvd.remove_process_set(ps)

    def test_trailing_mismatch_rejected(self, hvd_module):
        from horovod_tpu.exceptions import HorovodTpuError

        xs = [np.ones((2, 3))] * (N - 1) + [np.ones((2, 4))]
        with pytest.raises(HorovodTpuError, match="trailing"):
            hvd.allgather_v(xs)


class TestProcessSetSweep:
    @pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16, np.int32],
                             ids=str)
    @pytest.mark.parametrize("members", [[0, 1, 2, 3], [1, 5, 6]],
                             ids=["partition", "arbitrary"])
    def test_allreduce_sum_subset(self, hvd_module, monkeypatch, dtype,
                                  members):
        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        ps = hvd.add_process_set(members)
        x = _data(dtype, seed=7)
        y = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps)).astype(
            np.float64
        )
        expect = np.asarray(x[members]).astype(np.float64).sum(axis=0)
        for r in members:
            np.testing.assert_allclose(y[r], expect, **_tol(dtype))
        others = [r for r in range(N) if r not in members]
        np.testing.assert_array_equal(
            y[others], np.asarray(x)[others].astype(np.float64)
        )
        hvd.remove_process_set(ps)


class TestErrorMatrix:
    def test_average_and_op_mutually_exclusive(self, hvd_module):
        with pytest.raises(ValueError, match="either average or op"):
            hvd.allreduce(np.zeros((N, 2), np.float32), average=True,
                          op=hvd.Sum)

    def test_wrong_leading_dim_rejected(self, hvd_module):
        with pytest.raises(HorovodTpuError, match="leading"):
            hvd.allreduce(np.zeros((N + 1, 2), np.float32))

    def test_scalar_rejected(self, hvd_module):
        with pytest.raises(HorovodTpuError):
            hvd.allreduce(np.float32(1.0))

    def test_unregistered_process_set_rejected(self, hvd_module):
        from horovod_tpu.process_sets import ProcessSet

        ghost = ProcessSet([0, 1])
        with pytest.raises(HorovodTpuError, match="not registered"):
            hvd.allreduce(np.zeros((N, 2), np.float32), process_set=ghost)

    def test_alltoall_bad_splits_sum(self, hvd_module):
        splits = np.full((N, N), 1)
        splits[0, 0] = 2  # row sums no longer equal the row count
        with pytest.raises(HorovodTpuError, match="sum"):
            hvd.alltoall(np.zeros((N, N, 2), np.float32), splits=splits)

    def test_alltoall_bad_splits_shape(self, hvd_module):
        with pytest.raises(HorovodTpuError, match="shape"):
            hvd.alltoall(np.zeros((N, N, 2), np.float32),
                         splits=np.ones((2, 2), np.int32))

    def test_reducescatter_indivisible(self, hvd_module):
        with pytest.raises(Exception, match="divisible"):
            hvd.reducescatter(np.zeros((N, N + 1, 2), np.float32))

    def test_grouped_allreduce_empty(self, hvd_module):
        assert hvd.grouped_allreduce([]) == []

    def test_adasum_with_average_flag_conflict(self, hvd_module):
        with pytest.raises(ValueError, match="either average or op"):
            hvd.allreduce(np.zeros((N, 2), np.float32), average=True,
                          op=hvd.Adasum)


class TestNames:
    def test_duplicate_names_allowed_by_design(self, hvd_module):
        """The reference errors on a duplicate in-flight tensor name
        (its background queue keys submissions by name,
        ``operations.cc`` EnqueueTensorAllreduce duplicate check).
        Here there is no queue to collide in — XLA orders the program —
        so the same name may be reused freely, sync or async."""
        x = np.ones((N, 2), np.float32)
        a = hvd.allreduce_async(x, name="dup", op=hvd.Sum)
        b = hvd.allreduce_async(x, name="dup", op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(hvd.synchronize(a)),
                                   np.asarray(hvd.synchronize(b)))
        y1 = hvd.allreduce(x, name="dup", op=hvd.Sum)
        y2 = hvd.allreduce(x, name="dup", op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))

    def test_async_poll_and_wait(self, hvd_module):
        h = hvd.allreduce_async(np.ones((N, 3), np.float32), name="h1")
        assert hvd.poll(h) in (True, False)
        out = np.asarray(h.wait())
        np.testing.assert_allclose(out, 1.0)


class TestGroupedErrorCases:
    def test_grouped_mismatched_leading_dim(self, hvd_module):
        xs = [np.ones((N, 2), np.float32), np.ones((N + 1, 2), np.float32)]
        with pytest.raises(HorovodTpuError, match="leading"):
            hvd.grouped_allreduce(xs, op=hvd.Sum)

    def test_grouped_scalar_member_rejected(self, hvd_module):
        xs = [np.ones((N, 2), np.float32), np.float32(3.0)]
        with pytest.raises(HorovodTpuError):
            hvd.grouped_allreduce(xs, op=hvd.Sum)


class TestGroupedOps:
    @pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16], ids=str)
    def test_grouped_mixed_shapes(self, hvd_module, dtype):
        xs = [_data(dtype, shape=(N, s), seed=s) for s in (3, 7, 1)]
        ys = hvd.grouped_allreduce(xs, op=hvd.Sum)
        for x, y in zip(xs, ys):
            expect = np.asarray(x).astype(np.float64).sum(axis=0)
            for r in range(N):
                np.testing.assert_allclose(
                    np.asarray(y)[r].astype(np.float64), expect, **_tol(dtype)
                )

    def test_grouped_mixed_dtypes(self, hvd_module):
        xs = [
            _data(np.float32, shape=(N, 4), seed=10),
            _data(np.int32, shape=(N, 4), seed=11),
            _data(np.float32, shape=(N, 2), seed=12),
        ]
        ys = hvd.grouped_allreduce(xs, op=hvd.Sum)
        for x, y in zip(xs, ys):
            expect = np.asarray(x).astype(np.float64).sum(axis=0)
            for r in range(N):
                np.testing.assert_allclose(
                    np.asarray(y)[r].astype(np.float64), expect, rtol=1e-5
                )


class TestHierarchicalColumn:
    """Hierarchical (ICI/DCN two-level) lowering column of the matrix:
    flat vs hier equality across dtypes, process-set interplay, and a
    dp×tp hybrid mesh (topo/, forced 2-slice topology)."""

    @pytest.fixture(autouse=True)
    def _forced_two_slice(self, monkeypatch):
        from horovod_tpu import topo

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        yield
        topo.reset()

    def _run(self, fn, *args, n_out=2):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.runtime import WORLD_AXIS, get_runtime

        mesh = get_runtime().mesh
        spec = P(WORLD_AXIS)
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(spec,) * len(args),
            out_specs=(spec,) * n_out, check_vma=False,
        ))(*args)

    @pytest.mark.parametrize(
        "dtype", [np.float32, np.float16, jnp.bfloat16, np.int32], ids=str
    )
    def test_allreduce_flat_vs_hier(self, hvd_module, dtype):
        import jax

        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        x = _data(dtype, shape=(N, 37), seed=20)

        def f(a):
            return jax.lax.psum(a, WORLD_AXIS), \
                topo.hierarchical_all_reduce(a, WORLD_AXIS, op=Sum)

        flat, hier = self._run(f, x)
        if _is_float(dtype):
            np.testing.assert_allclose(
                np.asarray(flat, np.float64),
                np.asarray(hier, np.float64), **_tol(dtype)
            )
        else:
            # integer sums are exact: hier must be bitwise equal
            np.testing.assert_array_equal(
                np.asarray(flat), np.asarray(hier)
            )

    def test_allreduce_bitwise_f32_exact_sums(self, hvd_module):
        """f32 with integer values: all partial sums representable, so
        the two lowerings agree bit for bit."""
        import jax

        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        x = np.random.RandomState(21).randint(
            -16, 17, (N, 129)
        ).astype(np.float32)

        def f(a):
            return jax.lax.psum(a, WORLD_AXIS), \
                topo.hierarchical_all_reduce(a, WORLD_AXIS, op=Sum)

        flat, hier = self._run(f, x)
        np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))

    def test_rs_then_ag_matches_flat(self, hvd_module):
        import jax

        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        x = _data(np.float32, shape=(N, 53), seed=22)

        def f(a):
            sh = topo.hierarchical_reduce_scatter(a, WORLD_AXIS, op=Sum)
            out = topo.hierarchical_all_gather(sh, WORLD_AXIS)
            return jax.lax.psum(a, WORLD_AXIS), \
                out[:a.size].reshape(a.shape)

        flat, rt = self._run(f, x)
        np.testing.assert_allclose(np.asarray(flat), np.asarray(rt),
                                   rtol=1e-6, atol=1e-6)

    def test_process_set_restriction_stays_flat(self, hvd_module,
                                                monkeypatch):
        """A process-set-restricted optimizer exchange cannot carry the
        hier groups (they factor the whole axis): the plan downgrades
        to flat and values match the per-set allreduce exactly."""
        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        from horovod_tpu import sched

        ps = hvd.add_process_set([0, 1, 2, 3])
        sched.set_config_override(
            sched.SchedConfig(bucket_bytes=64, lowering="hier")
        )
        try:
            x = _data(np.float32, seed=23)
            y = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps))
            expect = np.asarray(x[:4]).sum(axis=0)
            for r in range(4):
                np.testing.assert_allclose(y[r], expect, rtol=1e-5)
        finally:
            sched.set_config_override(None)
            hvd.remove_process_set(ps)

    def test_non_tiling_set_raises_shared_error_type(self, hvd_module,
                                                     monkeypatch):
        from horovod_tpu.exceptions import ProcessSetTilingError
        from horovod_tpu.process_sets import tiling_groups

        with pytest.raises(ProcessSetTilingError, match="tile"):
            tiling_groups([0, 1, 2], N)

    @pytest.mark.parametrize("degrees", [(2, 2), (4, 2)],
                             ids=["dp2xtp2", "dp4xtp2"])
    def test_grad_sync_hier_column_on_dp_tp_mesh(self, hvd_module,
                                                 degrees):
        """dp×tp meshes: the hier lowering must agree with flat
        (dp2xtp2's dp axis cannot factor across 2 slices — clean
        degeneration; dp4xtp2's dp axis factors 2x2)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import sched
        from horovod_tpu.parallel import make_mesh, sync_gradients

        dp, tp = degrees
        devices = jax.devices()[: dp * tp]
        mesh = make_mesh(dp=dp, tp=tp, devices=devices)
        g = {"a": _data(np.float32, shape=(dp * tp, 5), seed=24),
             "b": _data(np.float32, shape=(dp * tp, 5), seed=25)}
        shard_axes = {"a": "", "b": "tp"}

        def f(grads):
            return sync_gradients(grads, shard_axes, axes=("dp", "tp"))

        outs = {}
        spec = {"a": P("dp"), "b": P("dp")}
        for lower in ("flat", "hier"):
            sched.set_config_override(sched.SchedConfig(
                bucket_bytes=64, lowering=lower))
            try:
                outs[lower] = jax.jit(jax.shard_map(
                    f, mesh=mesh, in_specs=(spec,), out_specs=spec,
                    check_vma=False,
                ))(g)
            finally:
                sched.set_config_override(None)
        for key in g:
            np.testing.assert_allclose(
                np.asarray(outs["flat"][key]),
                np.asarray(outs["hier"][key]), rtol=1e-6, atol=1e-6,
            )

    def test_cost_model_choice_never_exceeds_flat_dcn(self, hvd_module):
        """Property column: for random bucket sizes, the plan's chosen
        lowering never moves more DCN bytes than flat would."""
        from horovod_tpu import sched
        from horovod_tpu.topo import model as topo_model

        topo = topo_model.current()
        rng = np.random.RandomState(42)
        sizes = [int(rng.randint(64, 1 << 24)) for _ in range(40)]
        schedule = sched.build_schedule(
            sizes, ["float32"] * len(sizes),
            sched.SchedConfig(bucket_bytes=1 << 18, lowering="auto"),
        )
        for b in schedule.buckets:
            chosen = topo.lowering_bytes("all_reduce", b.nbytes,
                                         b.lowering)
            flat = topo.lowering_bytes("all_reduce", b.nbytes, "flat")
            assert chosen["dcn"] <= flat["dcn"], b


def _adasum_pair_np(a, b):
    a = a.astype(np.float64)
    b = b.astype(np.float64)
    dot, na, nb = (a * b).sum(), (a * a).sum(), (b * b).sum()
    ca = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
    cb = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
    return ca * a + cb * b


@pytest.mark.adasum
class TestHierAdasumColumn:
    """hier_adasum lowering column: plain sum over ICI, Adasum's
    adaptive combination across slices on the DCN hop (topo/, forced
    2-slice topology) — dtype sweep vs the NumPy reference, single-
    slice flat degeneration, process-set downgrade, quantized DCN hop,
    and the scheduler/ZeRO-1/tuner integration gauges."""

    @pytest.fixture(autouse=True)
    def _forced_two_slice(self, monkeypatch):
        from horovod_tpu import topo

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        yield
        topo.reset()

    def _run(self, fn, *args, n_out=1):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.runtime import WORLD_AXIS, get_runtime

        mesh = get_runtime().mesh
        spec = P(WORLD_AXIS)
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(spec,) * len(args),
            out_specs=(spec,) * n_out if n_out > 1 else spec,
            check_vma=False,
        ))(*args)

    def _sched_losses(self, lowering, steps=8, op=None, compression=None):
        import jax.numpy as jnp
        import optax

        from horovod_tpu import sched

        X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
        Y = (X @ np.full((4, 2), 0.7)).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

        params = {"w1": jnp.full((4, 4), 0.2),
                  "w2": jnp.full((4, 2), 0.5), "b": jnp.zeros((2,))}
        sched.set_config_override(sched.SchedConfig(
            enabled=True, bucket_bytes=64, lowering=lowering))
        try:
            kw = {}
            if op is not None:
                kw["op"] = op
            if compression is not None:
                kw["compression"] = compression
            tx = hvd.DistributedOptimizer(optax.sgd(0.1), **kw)
            step = hvd.distributed_train_step(loss_fn, tx)
            st = step.init(params)
            batch = (jnp.asarray(X), jnp.asarray(Y))
            out = []
            for _ in range(steps):
                params, st, loss = step(params, st, batch)
                out.append(float(loss))
            return out
        finally:
            sched.set_config_override(None)

    @pytest.mark.parametrize(
        "dtype", [np.float32, np.float16, jnp.bfloat16], ids=str
    )
    def test_allreduce_vs_numpy_reference(self, hvd_module, dtype):
        """op=Average: Adasum of per-slice mean gradients (the
        reference AdasumGpuAllreduceOp postscale semantics)."""
        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Average
        from horovod_tpu.runtime import WORLD_AXIS

        x = _data(dtype, shape=(N, 37), seed=30)

        def f(a):
            return topo.hierarchical_adasum_all_reduce(
                a, WORLD_AXIS, op=Average
            )

        out = np.asarray(self._run(f, x), np.float64)
        xs = np.asarray(x, np.float64)
        expect = _adasum_pair_np(xs[:4].mean(0), xs[4:].mean(0))
        for r in range(N):
            np.testing.assert_allclose(out[r], expect, **_tol(dtype))

    def test_allreduce_sum_semantics(self, hvd_module):
        """op=Sum: Adasum of per-slice sums."""
        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        x = _data(np.float32, shape=(N, 53), seed=31)

        def f(a):
            return topo.hierarchical_adasum_all_reduce(
                a, WORLD_AXIS, op=Sum
            )

        out = np.asarray(self._run(f, x), np.float64)
        xs = np.asarray(x, np.float64)
        expect = _adasum_pair_np(xs[:4].sum(0), xs[4:].sum(0))
        np.testing.assert_allclose(out[0], expect, rtol=1e-5, atol=1e-5)

    def test_non_float_rejected_and_bucket_resolves_flat(self,
                                                         hvd_module):
        from horovod_tpu import sched, topo
        from horovod_tpu.runtime import WORLD_AXIS

        x = _data(np.int32, shape=(N, 8), seed=32)
        with pytest.raises(HorovodTpuError, match="floating"):
            self._run(
                lambda a: topo.hierarchical_adasum_all_reduce(
                    a, WORLD_AXIS
                ),
                x,
            )
        # plan-level eligibility: integer buckets resolve flat
        s = sched.build_schedule(
            [4096], ["int32"],
            sched.SchedConfig(bucket_bytes=8192,
                              lowering="hier_adasum"),
        )
        assert s.buckets[0].lowering == "flat"

    def test_single_slice_resolves_flat_bitwise(self, hvd_module,
                                                monkeypatch):
        """Acceptance: on a forced single-slice topology a hier_adasum
        request resolves flat and f32 dense losses are bitwise
        identical to the flat run (and auto never selects it)."""
        from horovod_tpu import sched, topo

        monkeypatch.setenv("HVD_TPU_TOPO", "1x8")
        topo.reset()
        try:
            assert sched.resolve_lowering("hier_adasum", 1 << 20) == \
                "flat"
            flat = self._sched_losses("flat")
            ha = self._sched_losses("hier_adasum")
            auto = self._sched_losses("auto")
            assert flat == ha == auto
        finally:
            topo.reset()

    def test_auto_never_selects_hier_adasum(self, hvd_module):
        from horovod_tpu import sched

        rng = np.random.RandomState(7)
        sizes = [int(rng.randint(64, 1 << 24)) for _ in range(30)]
        schedule = sched.build_schedule(
            sizes, ["float32"] * len(sizes),
            sched.SchedConfig(bucket_bytes=1 << 18, lowering="auto"),
        )
        assert all(b.lowering in ("flat", "hier")
                   for b in schedule.buckets)

    def test_process_set_restriction_stays_flat(self, hvd_module,
                                                monkeypatch):
        """A process-set-restricted exchange cannot carry the slice
        groups: the plan downgrades to flat and values match the
        per-set allreduce exactly."""
        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        from horovod_tpu import sched

        ps = hvd.add_process_set([0, 1, 2, 3])
        sched.set_config_override(
            sched.SchedConfig(bucket_bytes=64, lowering="hier_adasum")
        )
        try:
            x = _data(np.float32, seed=33)
            y = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps))
            expect = np.asarray(x[:4]).sum(axis=0)
            for r in range(4):
                np.testing.assert_allclose(y[r], expect, rtol=1e-5)
        finally:
            sched.set_config_override(None)
            hvd.remove_process_set(ps)

    def test_two_slice_sched_gauges(self, hvd_module):
        """Acceptance: on the 2-slice sim mesh hier_adasum buckets
        publish nonzero dcn/ici gauges, the per-lowering bucket count,
        and DCN bytes <= hier's for the same schedule."""
        from horovod_tpu import metrics, sched

        self._sched_losses("hier")
        dcn_hier = metrics.get_gauge("topo.dcn_bytes")
        losses = self._sched_losses("hier_adasum")
        assert all(np.isfinite(losses))
        dcn = metrics.get_gauge("topo.dcn_bytes")
        ici = metrics.get_gauge("topo.ici_bytes")
        buckets = metrics.get_gauge(
            "topo.buckets", {"lowering": "hier_adasum"}
        )
        assert dcn and dcn > 0
        assert ici and ici > 0
        assert buckets and buckets >= 1
        assert dcn <= dcn_hier
        # byte-model property on random sizes too
        from horovod_tpu.topo import model as topo_model

        topo = topo_model.current()
        rng = np.random.RandomState(9)
        for _ in range(20):
            nb = int(rng.randint(64, 1 << 24))
            ha = topo.lowering_bytes("all_reduce", nb, "hier_adasum")
            hi = topo.lowering_bytes("all_reduce", nb, "hier")
            assert ha["dcn"] <= hi["dcn"], nb

    def test_op_adasum_routes_hierarchical(self, hvd_module):
        """DistributedOptimizer(op=Adasum) lowers its buckets
        hier_adasum on a cross-slice topology."""
        from horovod_tpu import metrics

        losses = self._sched_losses("auto", op=hvd.Adasum)
        assert all(np.isfinite(losses))
        assert metrics.get_gauge(
            "topo.buckets", {"lowering": "hier_adasum"}
        ) >= 1

    def test_quantized_dcn_hop(self, hvd_module):
        """Compression.int8 + op=Adasum rides the hier_adasum lowering
        (only the DCN gather quantizes) and stays close to the dense
        trajectory; a bf16/int8 wire on hier_adasum sum buckets too."""
        dense = self._sched_losses("hier_adasum")
        quant = self._sched_losses(
            "hier_adasum", compression=hvd.Compression.int8
        )
        assert abs(dense[-1] - quant[-1]) < 1e-2
        ad = self._sched_losses("auto", op=hvd.Adasum)
        adq = self._sched_losses(
            "auto", op=hvd.Adasum, compression=hvd.Compression.int8
        )
        assert abs(ad[-1] - adq[-1]) < 1e-2

    def test_quantized_flat_adasum_still_raises(self, hvd_module,
                                                monkeypatch):
        """The narrowed satellite contract: single-slice topologies
        (flat VHDD Adasum) still raise QuantizedWireError."""
        from horovod_tpu import topo
        from horovod_tpu.exceptions import QuantizedWireError

        monkeypatch.setenv("HVD_TPU_TOPO", "1x8")
        topo.reset()
        try:
            with pytest.raises(QuantizedWireError, match="Average"):
                self._sched_losses(
                    "auto", steps=1, op=hvd.Adasum,
                    compression=hvd.Compression.int8,
                )
        finally:
            topo.reset()

    def test_zero1_hier_adasum_buckets(self, hvd_module):
        """bucketed_zero_step: hier_adasum buckets shard k-fold over
        the ICI sub-axis and the Adasum combine happens on the 1/k DCN
        shard before the sharded update."""
        import jax.numpy as jnp
        import optax

        from horovod_tpu import sched
        from horovod_tpu.sched.zero1 import bucket_layouts, bucketed_zero_step

        X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
        Y = (X @ np.full((4, 2), 0.7)).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

        params = {"w1": jnp.full((4, 4), 0.2),
                  "w2": jnp.full((4, 2), 0.5), "b": jnp.zeros((2,))}
        cfg = sched.SchedConfig(
            enabled=True, bucket_bytes=64, mode="reduce_scatter",
            lowering="hier_adasum",
        )
        lays = bucket_layouts(params, 8, cfg)
        assert all(l.lowering == "hier_adasum" for l in lays)
        assert all(l.shards == 4 for l in lays)  # k = slice_size
        step = bucketed_zero_step(loss_fn, optax.adam(0.05), cfg=cfg)
        st = step.init(params)
        batch = (jnp.asarray(X), jnp.asarray(Y))
        loss = None
        for _ in range(5):
            params, st, loss = step(params, st, batch)
        assert np.isfinite(float(loss))

    def test_xir_eligibility_and_interp(self, hvd_module):
        """XIR column: eligible_lowering gates hier_adasum to float
        reduce ops; an all_reduce op carrying it interprets to the topo
        primitive (bitwise vs the direct call)."""
        import jax

        from horovod_tpu import xir
        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Average
        from horovod_tpu.runtime import WORLD_AXIS

        assert xir.eligible_lowering(
            "all_reduce", "hier_adasum", "float32") == "hier_adasum"
        assert xir.eligible_lowering(
            "all_reduce", "hier_adasum", "int32") == "flat"
        assert xir.eligible_lowering(
            "all_to_all", "hier_adasum", "float32") == "flat"
        assert xir.eligible_lowering(
            "all_gather", "hier_adasum", "float32") == "flat"
        assert xir.eligible_lowering("hier", "hier", None) == "hier"

        x = _data(np.float32, shape=(N, 21), seed=34)
        op = xir.all_reduce(
            WORLD_AXIS, reduce="mean", lowering="hier_adasum",
            nbytes=x[0].nbytes, dtype="float32",
        )

        def f(a):
            return xir.run_op(op, a)

        def g(a):
            return topo.hierarchical_adasum_all_reduce(
                a, WORLD_AXIS, op=Average
            )

        via_ir = np.asarray(self._run(f, x))
        direct = np.asarray(self._run(g, x))
        np.testing.assert_array_equal(via_ir, direct)

    def test_tuner_candidates_include_hier_adasum(self, hvd_module):
        from horovod_tpu.sched.tune import ScheduleTuner

        tuner = ScheduleTuner(explore_lowering=True)
        seen = set()
        # drain the exploration order without scoring
        for _ in range(4):
            lo = tuner.lowering()
            seen.add(lo)
            tuner._lowering_scores[lo] = 1.0
            if all(c in tuner._lowering_scores
                   for c in tuner._lowering_candidates):
                break
        assert {"flat", "hier", "hier_adasum"} <= seen | set(
            tuner._lowering_candidates
        )
        assert "hier_adasum" in tuner._lowering_candidates


class TestXirColumn:
    """Unified exchange IR column of the matrix: IR-routed MoE
    dispatch/combine and Ulysses flips against the direct ``lax`` path
    — bitwise on the f32 dense wire, 1e-6 on the bf16 wire (payloads
    chosen bf16-representable: a shuffle has no accumulation, so the
    cast round trip is exact) — on a 2x2 hybrid mesh, a simulated
    2-slice topology, and process-set subgroups."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        from horovod_tpu import xir

        yield
        xir.set_enabled_override(None)

    def _bf16_exact(self, shape, seed):
        # integer-valued f32: exactly representable in bf16, so the
        # bf16 wire's cast round trip changes nothing.
        return np.random.RandomState(seed).randint(
            -8, 9, shape
        ).astype(np.float32)

    def test_moe_dispatch_combine_2x2_mesh(self, hvd_module):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import xir
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.parallel.moe import (
            moe_alltoall_combine,
            moe_alltoall_dispatch,
        )

        mesh = make_mesh(dp=2, ep=2, devices=jax.devices()[:4])
        x = _data(np.float32, shape=(4, 4, 8), seed=30)  # per-dev [2,2,8]

        def roundtrip(a):
            buf = moe_alltoall_dispatch(a, "ep")
            return moe_alltoall_combine(buf, "ep")

        def direct(a):
            buf = jax.lax.all_to_all(a, "ep", split_axis=0,
                                     concat_axis=1, tiled=True)
            return jax.lax.all_to_all(buf, "ep", split_axis=1,
                                      concat_axis=0, tiled=True)

        def run(fn):
            return np.asarray(jax.jit(jax.shard_map(
                fn, mesh=mesh, in_specs=(P("dp", "ep"),),
                out_specs=P("dp", "ep"), check_vma=False,
            ))(x))

        xir.set_enabled_override(True)
        on = run(roundtrip)
        xir.set_enabled_override(False)
        off = run(roundtrip)
        want = run(direct)
        np.testing.assert_array_equal(on, want)
        np.testing.assert_array_equal(off, want)

    def test_moe_bf16_wire_2x2_mesh(self, hvd_module, monkeypatch):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import xir
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.parallel.moe import moe_alltoall_dispatch

        mesh = make_mesh(dp=2, ep=2, devices=jax.devices()[:4])
        x = self._bf16_exact((4, 4, 8), seed=31)  # per-dev [2,2,8]

        def run(fn):
            return np.asarray(jax.jit(jax.shard_map(
                fn, mesh=mesh, in_specs=(P("dp", "ep"),),
                out_specs=P("dp", "ep"), check_vma=False,
            ))(x))

        want = run(lambda a: jax.lax.all_to_all(
            a, "ep", split_axis=0, concat_axis=1, tiled=True))
        monkeypatch.setenv("HVD_TPU_XIR_WIRE", "bf16")
        xir.set_enabled_override(True)
        got = run(lambda a: moe_alltoall_dispatch(a, "ep"))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_moe_two_slice_world_with_byte_gauges(self, hvd_module,
                                                  monkeypatch):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import metrics, topo, xir
        from horovod_tpu.parallel.moe import moe_alltoall_dispatch
        from horovod_tpu.runtime import WORLD_AXIS

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        try:
            x = _data(np.float32, shape=(64, 3), seed=32)

            def run(fn):
                return np.asarray(jax.jit(jax.shard_map(
                    fn, mesh=hvd.mesh(), in_specs=(P(WORLD_AXIS),),
                    out_specs=P(WORLD_AXIS), check_vma=False,
                ))(x))

            want = run(lambda a: jax.lax.all_to_all(
                a, WORLD_AXIS, split_axis=0, concat_axis=1, tiled=True))
            xir.set_enabled_override(True)
            got = run(lambda a: moe_alltoall_dispatch(a, WORLD_AXIS))
            np.testing.assert_array_equal(got, want)
            # the previously-invisible a2a traffic, split by network
            assert metrics.get_gauge(
                "topo.dcn_bytes", {"kind": "moe"}
            ) > 0
            assert metrics.get_gauge(
                "topo.ici_bytes", {"kind": "moe"}
            ) > 0
        finally:
            topo.reset()

    @pytest.mark.parametrize("wire", ["off", "bf16"], ids=str)
    def test_ulysses_flips_2x2_mesh(self, hvd_module, monkeypatch, wire):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import xir
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.parallel.ulysses import ulysses_attention

        mesh = make_mesh(dp=2, sp=2, devices=jax.devices()[:4])
        # [dev-sharded B, T_loc, H, D]; integer-valued for the bf16 leg
        q = self._bf16_exact((4, 2, 4, 2), seed=33)
        passthrough = lambda qq, kk, vv, causal=False: qq

        def ul(a):
            return ulysses_attention(
                a, a, a, axis="sp", attn_fn=passthrough
            )

        def run(fn):
            return np.asarray(jax.jit(jax.shard_map(
                fn, mesh=mesh, in_specs=(P("dp", "sp"),),
                out_specs=P("dp", "sp"), check_vma=False,
            ))(q))

        def direct(a):
            h = jax.lax.all_to_all(a, "sp", split_axis=2, concat_axis=1,
                                   tiled=True)
            return jax.lax.all_to_all(h, "sp", split_axis=1,
                                      concat_axis=2, tiled=True)

        want = run(direct)
        monkeypatch.setenv("HVD_TPU_XIR_WIRE", wire)
        xir.set_enabled_override(True)
        on = run(ul)
        xir.set_enabled_override(False)
        off = run(ul)
        if wire == "off":
            np.testing.assert_array_equal(on, want)
        else:
            np.testing.assert_allclose(on, want, rtol=1e-6, atol=1e-6)
        np.testing.assert_array_equal(off, want)

    def test_ulysses_two_slice_full_attention(self, hvd_module,
                                              monkeypatch):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import topo, xir
        from horovod_tpu.parallel.ulysses import ulysses_attention
        from horovod_tpu.runtime import WORLD_AXIS

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        try:
            q = _data(np.float32, shape=(16, 4, 16, 2), seed=34)

            def ul(a):
                return ulysses_attention(a, a, a, axis=WORLD_AXIS)

            def run():
                return np.asarray(jax.jit(jax.shard_map(
                    ul, mesh=hvd.mesh(), in_specs=(P(WORLD_AXIS),),
                    out_specs=P(WORLD_AXIS), check_vma=False,
                ))(q))

            xir.set_enabled_override(True)
            on = run()
            xir.set_enabled_override(False)
            off = run()
            np.testing.assert_array_equal(on, off)
        finally:
            topo.reset()

    def test_alltoall_process_set_subgroups(self, hvd_module):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import xir
        from horovod_tpu.process_sets import tiling_groups
        from horovod_tpu.runtime import WORLD_AXIS

        groups = tiling_groups(range(4), N)  # [[0..3], [4..7]]
        x = _data(np.float32, shape=(32, 3), seed=35)

        def via_ir(a):
            op = xir.all_to_all(
                WORLD_AXIS, split_axis=0, concat_axis=1,
                groups=groups, nbytes=a.size * 4, dtype=a.dtype,
            )
            return xir.execute(
                xir.program("moe", [op]), [a], store=False
            )[0]

        def direct(a):
            return jax.lax.all_to_all(
                a, WORLD_AXIS, split_axis=0, concat_axis=1, tiled=True,
                axis_index_groups=[list(g) for g in groups],
            )

        def run(fn):
            return np.asarray(jax.jit(jax.shard_map(
                fn, mesh=hvd.mesh(), in_specs=(P(WORLD_AXIS),),
                out_specs=P(WORLD_AXIS), check_vma=False,
            ))(x))

        np.testing.assert_array_equal(run(via_ir), run(direct))

    def test_sparse_exchange_process_set(self, hvd_module, monkeypatch):
        """IR-routed sparse embedding exchange over a process-set
        subgroup: identical to the direct allgather-of-slices path."""
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import xir
        from horovod_tpu.ops.sparse import IndexedSlices, sparse_allreduce
        from horovod_tpu.runtime import WORLD_AXIS

        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        ps = hvd.add_process_set([0, 1, 2, 3])
        try:
            idx = np.tile(np.arange(4, dtype=np.int32), N)
            vals = _data(np.float32, shape=(N * 4, 3), seed=36)

            def sp(i, v):
                out = sparse_allreduce(
                    IndexedSlices(i, v, (16, 3)), axis=WORLD_AXIS,
                    process_set=ps,
                )
                return out.values

            def run():
                return np.asarray(jax.jit(jax.shard_map(
                    sp, mesh=hvd.mesh(),
                    in_specs=(P(WORLD_AXIS), P(WORLD_AXIS)),
                    out_specs=P(WORLD_AXIS), check_vma=False,
                ))(idx, vals))

            xir.set_enabled_override(True)
            on = run()
            xir.set_enabled_override(False)
            off = run()
            np.testing.assert_array_equal(on, off)
        finally:
            xir.set_enabled_override(None)
            hvd.remove_process_set(ps)


@pytest.mark.railpipe
class TestPipelineColumn:
    """XIR rail-pipeliner column of the matrix: the phase-interleaved
    emission (``HVD_TPU_XIR_PIPELINE``, xir/pipeline.py) against the
    serialized per-bucket chain — bitwise on the f32 dense wire, 1e-3
    on int8+EF — plus per-rail byte-gauge invariance, the merged
    a2a+dense program, and the max-of-rails cost properties."""

    @pytest.fixture(autouse=True)
    def _forced_two_slice(self, monkeypatch):
        from horovod_tpu import sched, topo
        from horovod_tpu.xir import pipeline as railpipe

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        yield
        railpipe.set_mode_override(None)
        sched.set_config_override(None)
        topo.reset()

    def _train(self, mode, wire="off", iters=5, lowering="hier"):
        import optax

        from horovod_tpu import metrics, sched
        from horovod_tpu.xir import pipeline as railpipe

        rng = np.random.RandomState(7)
        X = rng.randn(32, 64).astype(np.float32)
        Y = (X @ rng.randn(64, 8).astype(np.float32)).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            return jnp.mean((h @ p["w2"] - y) ** 2)

        r = np.random.RandomState(3)
        p = {
            "w1": jnp.asarray(r.randn(64, 256).astype(np.float32) * 0.05),
            "b1": jnp.zeros((256,)),
            "w2": jnp.asarray(r.randn(256, 8).astype(np.float32) * 0.05),
        }
        railpipe.set_mode_override(mode)
        sched.set_config_override(sched.SchedConfig(
            enabled=True, bucket_bytes=16 * 1024, lowering=lowering,
            wire=wire,
        ))
        overlap0 = metrics.get_counter("sched.pipeline.overlap_windows")
        try:
            tx = hvd.DistributedOptimizer(optax.sgd(0.05))
            step = hvd.distributed_train_step(loss_fn, tx)
            st = step.init(p)
            batch = (jnp.asarray(X), jnp.asarray(Y))
            losses = []
            for _ in range(iters):
                p, st, loss = step(p, st, batch)
                losses.append(float(loss))
            gauges = {
                "dcn": metrics.get_gauge("topo.dcn_bytes"),
                "ici": metrics.get_gauge("topo.ici_bytes"),
            }
            overlaps = metrics.get_counter(
                "sched.pipeline.overlap_windows"
            ) - overlap0
            return losses, gauges, overlaps
        finally:
            from horovod_tpu import sched as _s

            _s.set_config_override(None)
            railpipe.set_mode_override(None)

    def test_pipelined_vs_serialized_bitwise_f32(self, hvd_module):
        off, _, n_off = self._train("off")
        on, _, n_on = self._train("on")
        assert off == on  # bitwise: reordering never touches values
        assert n_off == 0
        assert n_on > 0  # the rail chains actually engaged

    def test_auto_mode_bitwise_and_engaged(self, hvd_module):
        off, _, _ = self._train("off")
        auto, _, n_auto = self._train("auto")
        assert off == auto
        assert n_auto > 0  # cost model prices pipelined cheaper here

    def test_int8_ef_within_tolerance(self, hvd_module):
        """Quantized buckets serialize inside the pipelined emission
        (they occupy both rails), so pipelined == serialized holds to
        the wire's own tolerance; both stay close to dense."""
        dense, _, _ = self._train("off")
        off, _, _ = self._train("off", wire="int8")
        on, _, _ = self._train("on", wire="int8")
        np.testing.assert_allclose(off, on, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(dense, on, rtol=1e-3, atol=1e-3)

    def test_rail_byte_gauges_identical(self, hvd_module):
        """Pipelining is ordering-only: the planned per-rail traffic —
        topo.dcn_bytes / topo.ici_bytes — is identical either way."""
        _, g_off, _ = self._train("off")
        _, g_on, _ = self._train("on")
        assert g_off == g_on
        assert g_on["dcn"] > 0 and g_on["ici"] > 0

    def test_merged_a2a_dense_program_parity(self, hvd_module):
        """Cross-workload merge on a 2x2 dp×ep mesh: a dense-grad
        all_reduce program over dp merged with a MoE all_to_all over
        ep — executed as one rail-interleaved emission — is bitwise
        identical to executing the programs separately."""
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import xir
        from horovod_tpu.parallel import make_mesh
        from horovod_tpu.xir import pipeline as railpipe

        mesh = make_mesh(dp=2, ep=2, devices=jax.devices()[:4])
        g = _data(np.float32, shape=(4, 8), seed=40)
        a = _data(np.float32, shape=(4, 4, 8), seed=41)

        def progs():
            dense = xir.program("dense_grad", [xir.all_reduce(
                "dp", lowering="flat", nbytes=g.size * 4,
                dtype="float32",
            )])
            moe = xir.program("moe", [xir.all_to_all(
                "ep", split_axis=0, concat_axis=1,
                nbytes=a.size * 4, dtype="float32",
            )])
            return dense, moe

        def merged(gg, aa):
            dense, moe = progs()
            outs = xir.execute_merged(
                [dense, moe], [[gg], [aa]], store=False
            )
            return outs[0][0], outs[1][0]

        def separate(gg, aa):
            dense, moe = progs()
            o1 = xir.execute(dense, [gg], store=False)[0]
            o2 = xir.execute(moe, [aa], store=False)[0]
            return o1, o2

        def run(fn):
            return jax.jit(jax.shard_map(
                fn, mesh=mesh,
                in_specs=(P("dp"), P("dp", "ep")),
                out_specs=(P("dp"), P("dp", "ep")),
                check_vma=False,
            ))(g, a)

        railpipe.set_mode_override("on")
        m1, m2 = run(merged)
        railpipe.set_mode_override("off")
        s1, s2 = run(separate)
        np.testing.assert_array_equal(np.asarray(m1), np.asarray(s1))
        np.testing.assert_array_equal(np.asarray(m2), np.asarray(s2))

    def test_cost_model_properties(self, hvd_module):
        """max(rail sums) ≤ pipelined ≤ serialized for random
        schedules, and the rail coefficient rows partition the
        serialized row exactly."""
        from horovod_tpu.topo import model as topo_model
        from horovod_tpu.xir import pipeline as railpipe

        topo = topo_model.current()
        rng = np.random.RandomState(11)
        for _ in range(20):
            items = [
                ("all_reduce", int(rng.randint(1 << 10, 1 << 24)),
                 rng.choice(["hier", "flat"]))
                for _ in range(int(rng.randint(2, 8)))
            ]
            serial = railpipe.estimate_schedule_cost(items, 8)
            pipe = railpipe.estimate_schedule_cost(
                items, 8, pipelined=True
            )
            splits = [railpipe.rail_times(c, b, lo, 8)
                      for c, b, lo in items]
            max_rail = max(sum(s[0] for s in splits),
                           sum(s[1] for s in splits))
            assert max_rail <= pipe <= serial, (items, max_rail, pipe,
                                                serial)
        for lowering in ("flat", "hier", "hier_adasum"):
            for coll in ("all_reduce", "reduce_scatter", "all_gather"):
                full = topo_model.cost_coefficients(
                    coll, 1 << 20, lowering, 8, topo
                )
                ici, dcn = topo_model.rail_cost_coefficients(
                    coll, 1 << 20, lowering, 8, topo
                )
                for f, i, d in zip(full, ici, dcn):
                    assert abs(f - (i + d)) < 1e-9
        # the single-op pipelined estimate is the max of its rails
        t = topo.estimate_cost("all_reduce", 1 << 20, "hier", 8,
                               pipelined=True)
        assert abs(
            t - max(topo.rail_times("all_reduce", 1 << 20, "hier", 8))
        ) < 1e-12


@pytest.mark.onestep
class TestOnestepColumn:
    """Whole-step emission column of the matrix (``HVD_TPU_ONESTEP``,
    xir/interp.py): the single-dispatch fold — exchange schedule plus
    optimizer update traced into one jitted program — against the
    per-bucket dispatch chain.  Bitwise on the f32 dense wire in every
    mode (the fold is function composition at trace time: same ops in
    the same order, the barrier is value-identity), 1e-3 on int8+EF,
    composed with the hier lowering and the rail-pipelined ordering,
    plus donation parity for both step classes with ``donate=False``
    as the numerics hook."""

    @pytest.fixture(autouse=True)
    def _forced_two_slice(self, monkeypatch):
        from horovod_tpu import sched, topo
        from horovod_tpu.xir import interp as xinterp
        from horovod_tpu.xir import pipeline as railpipe

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        yield
        xinterp.set_onestep_override(None)
        railpipe.set_mode_override(None)
        sched.set_config_override(None)
        topo.reset()

    def _train(self, mode, wire="off", pipeline="off", iters=5,
               lowering="hier", donate=True):
        import optax

        from horovod_tpu import metrics, sched
        from horovod_tpu.xir import interp as xinterp
        from horovod_tpu.xir import pipeline as railpipe

        rng = np.random.RandomState(7)
        X = rng.randn(32, 64).astype(np.float32)
        Y = (X @ rng.randn(64, 8).astype(np.float32)).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            return jnp.mean((h @ p["w2"] - y) ** 2)

        r = np.random.RandomState(3)
        p = {
            "w1": jnp.asarray(r.randn(64, 256).astype(np.float32) * 0.05),
            "b1": jnp.zeros((256,)),
            "w2": jnp.asarray(r.randn(256, 8).astype(np.float32) * 0.05),
        }
        xinterp.set_onestep_override(mode)
        railpipe.set_mode_override(pipeline)
        sched.set_config_override(sched.SchedConfig(
            enabled=True, bucket_bytes=16 * 1024, lowering=lowering,
            wire=wire,
        ))
        folds0 = metrics.get_counter("xir.onestep.steps")
        try:
            tx = hvd.DistributedOptimizer(optax.sgd(0.05))
            step = hvd.distributed_train_step(loss_fn, tx, donate=donate)
            st = step.init(p)
            batch = (jnp.asarray(X), jnp.asarray(Y))
            losses = []
            for _ in range(iters):
                p, st, loss = step(p, st, batch)
                losses.append(float(loss))
            folds = metrics.get_counter("xir.onestep.steps") - folds0
            return losses, folds
        finally:
            from horovod_tpu import sched as _s

            _s.set_config_override(None)
            railpipe.set_mode_override(None)
            xinterp.set_onestep_override(None)

    def test_onestep_vs_off_bitwise_f32(self, hvd_module):
        off, n_off = self._train("off")
        on, n_on = self._train("on")
        assert off == on  # bitwise: the fold is trace-time composition
        assert n_off == 0
        assert n_on > 0  # the whole-step emission actually engaged

    def test_auto_mode_bitwise_and_engaged(self, hvd_module):
        off, _ = self._train("off")
        auto, n_auto = self._train("auto")
        assert off == auto
        assert n_auto > 0  # multi-unit schedule: auto folds

    def test_int8_ef_within_tolerance(self, hvd_module):
        """The quantize/dequantize phases fold along with everything
        else, so onestep == off holds to the wire's own tolerance and
        both stay close to dense."""
        dense, _ = self._train("off")
        off, _ = self._train("off", wire="int8")
        on, n_on = self._train("on", wire="int8")
        assert n_on > 0
        np.testing.assert_allclose(off, on, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(dense, on, rtol=1e-3, atol=1e-3)

    def test_composes_with_pipelined_ordering(self, hvd_module):
        """The fold stitches the update onto whatever ordering the
        rail pipeliner emitted: onestep+pipelined == both off,
        bitwise (ordering and stitching are both value-identity)."""
        base, _ = self._train("off", pipeline="off")
        both, n_both = self._train("on", pipeline="on")
        assert base == both
        assert n_both > 0

    def test_train_step_donation_parity_under_onestep(self, hvd_module):
        """Donated whole-step program == undonated, bitwise —
        ``donate=False`` is the numerics hook when in-place buffer
        reuse is suspected."""
        donated, _ = self._train("on", donate=True)
        undonated, _ = self._train("on", donate=False)
        assert donated == undonated

    def test_stale_step_donation_parity_under_onestep(self, hvd_module):
        import optax

        from horovod_tpu import svc
        from horovod_tpu.svc.stale import StaleTrainStep
        from horovod_tpu.xir import interp as xinterp

        svc.set_enabled_override(True)
        svc.set_staleness_override(1)
        xinterp.set_onestep_override("on")

        def lf(p, b):
            return jnp.sum((p["w"] - 3.0) ** 2) + 0.0 * jnp.sum(b)

        def run(donate):
            step = StaleTrainStep(lf, optax.sgd(0.2), k=1,
                                  donate=donate)
            sp, st = step.init({"w": jnp.zeros((4,), jnp.float32)})
            batch = jnp.zeros((N, 1), jnp.float32)
            losses = []
            for _ in range(8):
                sp, st, loss = step(sp, st, batch)
                losses.append(float(loss))
            step.drain()
            return losses

        try:
            donated = run(True)
            svc.reset_service()
            undonated = run(False)
            assert donated == undonated, \
                "stale onestep donation changed numerics"
        finally:
            svc.set_enabled_override(None)
            svc.set_staleness_override(None)
            svc.reset_service()


@pytest.mark.pallas
@pytest.mark.quant
class TestFusedQuantColumn:
    """Fused quantized-wire backend column of the matrix
    (``HVD_TPU_QUANT_BACKEND=fused`` → ops/pallas_quant.py ring
    kernels, interpret mode + ppermute transport on the CPU mesh):
    fused vs phase across dtypes, exact-payload bitwise agreement,
    process-set subgroups, the hierarchical lowering with the fused
    backend on its quantized hop, and EF residual equivalence."""

    def _run(self, fn, *args, n_out=1):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.runtime import WORLD_AXIS, get_runtime

        mesh = get_runtime().mesh
        spec = P(WORLD_AXIS)
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(spec,) * len(args),
            out_specs=(spec,) * n_out if n_out > 1 else spec,
            check_vma=False,
        ))(*args)

    @pytest.mark.parametrize(
        "dtype", [np.float32, np.float16, jnp.bfloat16], ids=str
    )
    @pytest.mark.parametrize("wire", ["int8", "fp8"])
    def test_allreduce_fused_vs_phase(self, hvd_module, dtype, wire):
        from horovod_tpu.ops.quantized import quantized_allreduce
        from horovod_tpu.ops.traced import Sum

        x = _data(dtype, shape=(N, 777), seed=40)

        def f(backend):
            return self._run(
                lambda a, _b=backend: quantized_allreduce(
                    a[0], op=Sum, wire=wire, backend=_b
                ).astype(jnp.float32)[None], x,
            )

        # same grid, same fp32 accumulation — only summation order
        # differs between the ring and the all_to_all wire, so f32
        # agrees at 1e-6 and the half dtypes at their own rounding
        # (the phase primitive casts its fp32 result back to dtype)
        tol = dict(rtol=1e-6, atol=1e-6) if dtype == np.float32 \
            else _tol(dtype)
        np.testing.assert_allclose(
            np.asarray(f("phase"), np.float64),
            np.asarray(f("fused"), np.float64), **tol,
        )

    def test_bitwise_when_every_block_quantizes_exactly(self,
                                                        hvd_module):
        """Payload crafted so every quantization block has amax 127 and
        integer values: both backends' grids are exact, partial sums
        are exactly representable, so summation order cannot matter —
        fused must equal phase bit for bit."""
        from horovod_tpu.ops.quantized import quant_block, quantized_allreduce
        from horovod_tpu.ops.traced import Sum

        block = quant_block()
        rng = np.random.RandomState(41)
        x = rng.randint(-16, 17, (N, 2 * block)).astype(np.float32)
        x[:, ::block] = 127.0  # pin every block's amax -> scale == 1

        def f(backend):
            return np.asarray(self._run(
                lambda a, _b=backend: quantized_allreduce(
                    a[0], op=Sum, wire="int8", backend=_b
                )[None], x,
            ))

        np.testing.assert_array_equal(f("phase"), f("fused"))

    def test_process_set_subgroups(self, hvd_module, monkeypatch):
        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        from horovod_tpu.ops.quantized import quantized_allreduce
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        ps = hvd.add_process_set([0, 1, 2, 3])
        try:
            x = _data(np.float32, shape=(N, 1030), seed=42)

            def f(backend):
                return np.asarray(self._run(
                    lambda a, _b=backend: quantized_allreduce(
                        a[0], WORLD_AXIS, op=Sum, process_set=ps,
                        backend=_b,
                    )[None], x,
                ))

            ph, fu = f("phase"), f("fused")
            np.testing.assert_allclose(ph, fu, rtol=1e-6, atol=1e-6)
            # and the grouped reduction actually stayed within the set
            expect = np.asarray(x[:4], np.float64).sum(axis=0)
            np.testing.assert_allclose(
                np.asarray(fu[0], np.float64), expect,
                rtol=1e-2, atol=1e-1,
            )
        finally:
            hvd.remove_process_set(ps)

    def test_hier_lowering_fused_quantized_hop(self, hvd_module,
                                               monkeypatch):
        """Hierarchical lowering on a forced 2-slice topology with a
        quantized wire: the quantized hop dispatches through the
        backend knob — fused must agree with phase (on hardware the
        cross-slice DCN hop falls back to phase and only ICI-resident
        rings go fused; the CPU mesh exercises the fused kernels on
        the same groups)."""
        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        try:
            x = _data(np.float32, shape=(N, 1100), seed=43)

            def f(backend):
                monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", backend)
                return np.asarray(self._run(
                    lambda a: topo.hierarchical_all_reduce(
                        a, WORLD_AXIS, op=Sum, wire="int8"
                    ), x,
                ))

            np.testing.assert_allclose(
                f("phase"), f("fused"), rtol=1e-6, atol=1e-6
            )
        finally:
            topo.reset()

    def test_ef_residual_equivalence(self, hvd_module):
        """End-to-end EF: quantize(g + r) on the wire under both
        backends — reduced values agree to summation order and the new
        residual (one local quantization) is bitwise identical."""
        from horovod_tpu.ops.quantized import quantized_allreduce_ef
        from horovod_tpu.ops.traced import Sum

        x = _data(np.float32, shape=(N, 1536), seed=44)
        r = _data(np.float32, shape=(N, 1536), seed=45) * 0.01

        def f(backend):
            def body(a, b):
                out, rn = quantized_allreduce_ef(
                    a, b, op=Sum, backend=backend
                )
                return out, rn

            o, rn = self._run(body, x, r, n_out=2)
            return np.asarray(o), np.asarray(rn)

        o_p, r_p = f("phase")
        o_f, r_f = f("fused")
        np.testing.assert_allclose(o_p, o_f, rtol=1e-6, atol=1e-6)
        np.testing.assert_array_equal(r_p, r_f)


class TestGroupFusionKnob:
    def test_disable_group_fusion_matches_fused(self, hvd_module,
                                                monkeypatch):
        """HOROVOD_DISABLE_GROUP_FUSION: same numerics, unfused lowering
        (reference knob of the same name)."""
        xs = [_data(np.float32, shape=(N, s), seed=s) for s in (3, 5)]
        fused = [np.asarray(y) for y in hvd.grouped_allreduce(xs, op=hvd.Sum)]
        monkeypatch.setenv("HVD_TPU_DISABLE_GROUP_FUSION", "1")
        unfused = [np.asarray(y)
                   for y in hvd.grouped_allreduce(xs, op=hvd.Sum)]
        for a, b in zip(fused, unfused):
            np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_disable_group_fusion_traced(self, hvd_module, monkeypatch):
        import jax

        from horovod_tpu.ops import traced

        xs = [np.ones((4, 3), np.float32), np.ones((4, 2), np.float32)]

        def run():
            def f(*ts):
                return tuple(
                    traced.grouped_allreduce(list(ts), op=traced.Sum)
                )

            from jax.sharding import PartitionSpec as P

            from horovod_tpu.runtime import WORLD_AXIS, get_runtime
            mesh = get_runtime().mesh
            spec = P(WORLD_AXIS)
            return [
                np.asarray(y) for y in jax.jit(jax.shard_map(
                    f, mesh=mesh, in_specs=(spec, spec),
                    out_specs=(spec, spec), check_vma=False,
                ))(*[np.tile(x, (2, 1)) for x in xs])
            ]

        fused = run()
        monkeypatch.setenv("HVD_TPU_DISABLE_GROUP_FUSION", "1")
        unfused = run()
        for a, b in zip(fused, unfused):
            np.testing.assert_allclose(a, b, rtol=1e-6)


@pytest.mark.backend
class TestBackendColumn:
    """The gpu backend-family column of the matrix
    (``HVD_TPU_BACKEND=gpu`` → backend/registry.py routes quantized
    reduce ops through ops/mosaic_quant.py, interpret mode on the CPU
    mesh): gpu-interpret vs phase vs dense parity, gpu-vs-tpu family
    bitwise identity (the two families share the kernel math), the
    forced 2-slice hierarchical lowering, process-set subgroups, the
    hardware-ineligibility fallback, and the acceptance counters
    (nonzero ``backend.gpu.*``, zero silent fallbacks)."""

    @pytest.fixture(autouse=True)
    def _fresh_backend(self, monkeypatch):
        from horovod_tpu import topo
        from horovod_tpu.backend import registry

        monkeypatch.delenv("HVD_TPU_BACKEND", raising=False)
        monkeypatch.delenv("HVD_TPU_QUANT_BACKEND", raising=False)
        registry.reset()
        topo.reset()
        yield
        registry.reset()
        topo.reset()

    def _force(self, monkeypatch, fam):
        from horovod_tpu import topo
        from horovod_tpu.backend import registry

        monkeypatch.setenv("HVD_TPU_BACKEND", fam)
        registry.reset()
        topo.reset()

    def _run(self, fn, *args, n_out=1):
        import jax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.runtime import WORLD_AXIS, get_runtime

        mesh = get_runtime().mesh
        spec = P(WORLD_AXIS)
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(spec,) * len(args),
            out_specs=(spec,) * n_out if n_out > 1 else spec,
            check_vma=False,
        ))(*args)

    @pytest.mark.parametrize("wire", ["int8", "fp8"])
    def test_gpu_family_vs_phase_vs_dense(self, hvd_module, monkeypatch,
                                          wire):
        """Under the gpu family the UNSET quant knob routes through the
        mosaic ring (family default ``fused``); it must agree with an
        explicit phase backend at summation-order tolerance and with
        the dense sum at quantization tolerance."""
        from jax import lax

        from horovod_tpu.ops.quantized import quantized_allreduce
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        x = _data(np.float32, shape=(N, 999), seed=50)
        self._force(monkeypatch, "gpu")
        gpu = np.asarray(self._run(
            lambda a: quantized_allreduce(a[0], op=Sum, wire=wire)[None],
            x,
        ))
        phase = np.asarray(self._run(
            lambda a: quantized_allreduce(
                a[0], op=Sum, wire=wire, backend="phase"
            )[None], x,
        ))
        dense = np.asarray(self._run(
            lambda a: lax.psum(a[0], WORLD_AXIS)[None], x,
        ))
        np.testing.assert_allclose(gpu, phase, rtol=1e-6, atol=1e-6)
        # dense tolerance is the wire's quantization error summed over
        # N contributions (fp8 e4m3 carries ~6% per-element error)
        dense_tol = dict(rtol=1e-2, atol=1e-1) if wire == "int8" \
            else dict(rtol=1e-1, atol=1.0)
        np.testing.assert_allclose(gpu, dense, **dense_tol)

    def test_bitwise_exact_grid_gpu_phase_dense(self, hvd_module,
                                                monkeypatch):
        """Payload crafted so BOTH quantization grids are exact: the
        contribution hop sees amax 127 (scale 1) over integer values,
        and the gathered-sum hop sees amax 1016 = 8 x 127 (scale 8)
        over multiple-of-8 sums — so gpu == phase == dense bit for
        bit."""
        from jax import lax

        from horovod_tpu.ops.quantized import quant_block, quantized_allreduce
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        block = quant_block()
        rng = np.random.RandomState(51)
        x = (8 * rng.randint(-15, 16, (N, 2 * block))).astype(np.float32)
        # Pin the amax on every 8-aligned run — the ring path re-chunks
        # rows before blocking, and whatever block the quantizer lands
        # on must contain a 127 (and the reduced tensor a 1016).
        x[:, ::8] = 127.0
        self._force(monkeypatch, "gpu")
        gpu = np.asarray(self._run(
            lambda a: quantized_allreduce(a[0], op=Sum, wire="int8")[None],
            x,
        ))
        phase = np.asarray(self._run(
            lambda a: quantized_allreduce(
                a[0], op=Sum, wire="int8", backend="phase"
            )[None], x,
        ))
        dense = np.asarray(self._run(
            lambda a: lax.psum(a[0], WORLD_AXIS)[None], x,
        ))
        np.testing.assert_array_equal(gpu, phase)
        np.testing.assert_array_equal(gpu, dense)

    def test_gpu_family_bitwise_equals_tpu_family(self, hvd_module,
                                                  monkeypatch):
        """mosaic_quant imports pallas_quant's kernels rather than
        copying them, so the two families' fused interpret paths are
        the same program — bitwise, for arbitrary payloads."""
        from horovod_tpu.ops.quantized import quantized_allreduce
        from horovod_tpu.ops.traced import Sum

        x = _data(np.float32, shape=(N, 1234), seed=52)
        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "fused")

        def f():
            return np.asarray(self._run(
                lambda a: quantized_allreduce(
                    a[0], op=Sum, wire="int8"
                )[None], x,
            ))

        self._force(monkeypatch, "gpu")
        out_gpu = f()
        self._force(monkeypatch, "tpu")
        out_tpu = f()
        np.testing.assert_array_equal(out_gpu, out_tpu)

    def test_forced_two_slice_hier_gpu_family(self, hvd_module,
                                              monkeypatch):
        """Forced 2-slice topology + gpu family: the hierarchical
        lowering's quantized hop dispatches through the mosaic module
        on the same tiling groups the tpu family uses — identical hop
        math, bitwise-equal result."""
        from horovod_tpu import topo
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "fused")
        x = _data(np.float32, shape=(N, 1100), seed=53)

        def f():
            return np.asarray(self._run(
                lambda a: topo.hierarchical_all_reduce(
                    a, WORLD_AXIS, op=Sum, wire="int8"
                ), x,
            ))

        self._force(monkeypatch, "gpu")
        assert topo.current().num_slices == 2  # spec wins over family
        out_gpu = f()
        self._force(monkeypatch, "tpu")
        out_tpu = f()
        np.testing.assert_array_equal(out_gpu, out_tpu)

    def test_process_set_subgroups_gpu_family(self, hvd_module,
                                              monkeypatch):
        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        from horovod_tpu.ops.quantized import quantized_allreduce
        from horovod_tpu.ops.traced import Sum
        from horovod_tpu.runtime import WORLD_AXIS

        self._force(monkeypatch, "gpu")
        ps = hvd.add_process_set([0, 1, 2, 3])
        try:
            x = _data(np.float32, shape=(N, 1030), seed=54)
            out = np.asarray(self._run(
                lambda a: quantized_allreduce(
                    a[0], WORLD_AXIS, op=Sum, process_set=ps
                )[None], x,
            ))
            expect = np.asarray(x[:4], np.float64).sum(axis=0)
            np.testing.assert_allclose(
                np.asarray(out[0], np.float64), expect,
                rtol=1e-2, atol=1e-1,
            )
        finally:
            hvd.remove_process_set(ps)

    def test_hardware_ineligibility_falls_back_to_phase(
        self, hvd_module, monkeypatch
    ):
        """A 'real GPU' whose jax build lacks the Triton lowering:
        dispatch_mode returns None, the collective falls back to the
        phase backend with the ``quant.fused_fallback`` counter — and
        the answer is still right."""
        from horovod_tpu import metrics
        from horovod_tpu.ops import mosaic_quant
        from horovod_tpu.ops.quantized import quantized_allreduce
        from horovod_tpu.ops.traced import Sum

        self._force(monkeypatch, "gpu")
        monkeypatch.setattr(mosaic_quant, "_on_gpu", lambda: True)
        monkeypatch.setattr(mosaic_quant, "_HAS_PLGPU", False)
        assert mosaic_quant.dispatch_mode(None, N) is None
        metrics.reset_counters("quant.")
        metrics.reset_counters("backend.")
        x = _data(np.float32, shape=(N, 512), seed=55)
        out = np.asarray(self._run(
            lambda a: quantized_allreduce(a[0], op=Sum)[None], x,
        ))
        assert metrics.get_counter("quant.fused_fallback") > 0
        assert metrics.get_counter("backend.gpu.quant_collectives") == 0
        expect = np.asarray(x, np.float64).sum(axis=0)
        np.testing.assert_allclose(
            np.asarray(out[0], np.float64), expect,
            rtol=1e-2, atol=1e-1,
        )

    def test_acceptance_counters_nonzero_no_silent_fallback(
        self, hvd_module, monkeypatch
    ):
        """The PR's acceptance gauge: under ``HVD_TPU_BACKEND=gpu`` a
        quantized reduce op routes through the mosaic lowering —
        nonzero ``backend.gpu.*`` counters, zero fallbacks."""
        from horovod_tpu import metrics
        from horovod_tpu.ops.quantized import quantized_allreduce
        from horovod_tpu.ops.traced import Sum

        self._force(monkeypatch, "gpu")
        metrics.reset_counters("quant.")
        metrics.reset_counters("backend.")
        x = _data(np.float32, shape=(N, 768), seed=56)
        self._run(
            lambda a: quantized_allreduce(a[0], op=Sum)[None], x,
        )
        assert metrics.get_counter("backend.gpu.quant_collectives") > 0
        assert metrics.get_counter("backend.gpu.quant_bytes") > 0
        assert metrics.get_counter("quant.fused_collectives") > 0
        assert metrics.get_counter("quant.fused_fallback") == 0
