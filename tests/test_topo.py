"""Topology-aware hierarchical collectives (topo/): discovery + env
override, cost-model lowering choice, phase-primitive equality vs the
flat path, mesh-axis factoring, scheduler/ZeRO-1 integration, and the
topo.* observability surface."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import metrics, sched, topo
from horovod_tpu.exceptions import HorovodTpuError, ProcessSetTilingError
from horovod_tpu.ops.traced import Average, Sum
from horovod_tpu.runtime import WORLD_AXIS, get_runtime
from horovod_tpu.topo.model import Topology

pytestmark = pytest.mark.topo

N = 8
T24 = Topology(num_slices=2, slice_size=4)


@pytest.fixture(autouse=True)
def _clean_topo_state():
    topo.reset()
    sched.set_config_override(None)
    yield
    topo.reset()
    sched.set_config_override(None)


# ------------------------------------------------------------- model

class TestTopologyModel:
    def test_env_spec_sxk(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        t = topo.discover([None] * 8)
        assert (t.num_slices, t.slice_size) == (2, 4)
        assert t.source == "env"

    def test_env_spec_ici_mesh(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x2x2")
        t = topo.discover([None] * 8)
        assert (t.num_slices, t.slice_size) == (2, 4)
        assert t.ici_shape == (2, 2)

    def test_env_spec_json(self, monkeypatch):
        monkeypatch.setenv(
            "HVD_TPU_TOPO",
            '{"slices": 4, "ici_shape": [2], "dcn_gbps": 5.0,'
            ' "phase_overhead_us": 50}',
        )
        t = topo.discover([None] * 8)
        assert (t.num_slices, t.slice_size) == (4, 2)
        assert t.dcn_gbps == 5.0
        assert t.phase_overhead_s == pytest.approx(50e-6)

    def test_env_spec_device_count_mismatch_rejected(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x3")
        with pytest.raises(HorovodTpuError, match="devices"):
            topo.discover([None] * 8)

    def test_env_spec_garbage_rejected(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "banana")
        with pytest.raises(HorovodTpuError):
            topo.discover([None] * 8)

    def test_cpu_discovery_is_single_slice(self):
        t = topo.discover()
        assert t.num_slices == 1 and not t.multi_slice

    def test_discovery_reads_slice_index(self):
        class Dev:
            def __init__(self, s):
                self.slice_index = s

        devs = [Dev(0)] * 4 + [Dev(1)] * 4
        t = topo.discover(devs)
        assert (t.num_slices, t.slice_size) == (2, 4)
        assert t.source == "devices"

    def test_ragged_slices_collapse_to_flat(self):
        class Dev:
            def __init__(self, s):
                self.slice_index = s

        t = topo.discover([Dev(0)] * 5 + [Dev(1)] * 3)
        assert t.num_slices == 1

    def test_factor_axis(self):
        assert T24.factor_axis(8) == (2, 4)
        assert T24.factor_axis(4) == (2, 2)
        assert T24.factor_axis(2) == (1, 2)  # <= num_slices: degenerate
        assert T24.factor_axis(7) == (1, 7)  # indivisible
        single = Topology(num_slices=1, slice_size=8)
        assert single.factor_axis(8) == (1, 8)

    def test_axis_groups(self):
        intra, cross = T24.axis_groups(8)
        assert intra == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert cross == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_axis_groups_raise_shared_tiling_error(self):
        single = Topology(num_slices=1, slice_size=8)
        with pytest.raises(ProcessSetTilingError):
            single.axis_groups(8)

    def test_override_wins(self):
        topo.set_topology_override(T24)
        assert topo.current() is T24


class TestCostModel:
    def test_hier_for_large_flat_for_small(self):
        assert T24.choose_lowering("all_reduce", 1 << 10) == "flat"
        assert T24.choose_lowering("all_reduce", 16 << 20) == "hier"

    def test_single_slice_always_flat(self):
        t = Topology(num_slices=1, slice_size=8)
        assert t.choose_lowering("all_reduce", 1 << 30) == "flat"

    def test_lower_mode_forces(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO_LOWER", "hier")
        assert T24.choose_lowering("all_reduce", 1) == "hier"
        monkeypatch.setenv("HVD_TPU_TOPO_LOWER", "off")
        assert T24.choose_lowering("all_reduce", 1 << 30) == "flat"

    def test_hier_dcn_bytes_are_flat_over_slice_size(self):
        for nbytes in (1 << 10, 1 << 20, 1 << 26):
            flat = T24.lowering_bytes("all_reduce", nbytes, "flat")
            hier = T24.lowering_bytes("all_reduce", nbytes, "hier")
            assert hier["dcn"] == pytest.approx(
                flat["dcn"] / T24.slice_size, abs=1
            )

    def test_cost_model_crossover_is_monotone(self):
        """One crossover: once hier wins it keeps winning as payload
        grows (the decision is a threshold, like the fusion knob)."""
        prev = "flat"
        for exp in range(6, 28):
            cur = T24.choose_lowering("all_reduce", 1 << exp)
            if prev == "hier":
                assert cur == "hier", f"regressed to flat at 2^{exp}"
            prev = cur
        assert prev == "hier"

    def test_chosen_lowering_never_exceeds_flat_dcn_bytes(self):
        """Property: across random topologies and payloads, the cost
        model's choice never moves more DCN bytes than flat."""
        rng = np.random.RandomState(0)
        for _ in range(200):
            s = int(rng.choice([1, 2, 3, 4, 8]))
            k = int(rng.choice([1, 2, 4, 8, 16]))
            t = Topology(
                num_slices=s, slice_size=k,
                ici_gbps=float(rng.uniform(50, 400)),
                dcn_gbps=float(rng.uniform(1, 50)),
                phase_overhead_s=float(rng.uniform(10e-6, 500e-6)),
            )
            nbytes = int(rng.randint(1, 1 << 28))
            chosen = t.choose_lowering("all_reduce", nbytes)
            got = t.lowering_bytes("all_reduce", nbytes, chosen)
            flat = t.lowering_bytes("all_reduce", nbytes, "flat")
            assert got["dcn"] <= flat["dcn"], (s, k, nbytes, chosen)


# ------------------------------------------------- fitted cost model

def _record_ring_observations(topo_truth, axis_size=8, reps=8,
                              noise=0.0, seed=0,
                              sizes=(1 << 12, 1 << 16, 1 << 20, 1 << 24),
                              lowerings=("flat", "hier")):
    """Feed fit cells with latencies generated from a ground-truth
    parameter set through the SAME coefficient row the fitter uses."""
    from horovod_tpu.topo import fit
    from horovod_tpu.topo.model import cost_coefficients

    rng = np.random.RandomState(seed)
    for lo in lowerings:
        for nb in sizes:
            c = cost_coefficients("all_reduce", nb, lo, axis_size,
                                  topo_truth)
            base = (
                c[0] * topo_truth.phase_overhead_s
                + c[1] * topo_truth.ici_latency_s
                + c[2] * topo_truth.dcn_latency_s
                + c[3] / (topo_truth.ici_gbps * 1e9)
                + c[4] / (topo_truth.dcn_gbps * 1e9)
            )
            for _ in range(reps):
                jitter = 1.0 + noise * float(rng.uniform(-1, 1))
                fit.record_observation("all_reduce", lo, nb, axis_size,
                                       base * jitter)


@pytest.mark.tune
class TestFittedCostModel:
    def test_predictions_within_2x_of_measured_p50(self):
        """Acceptance property: on the simulated 2x4 mesh the fitted
        model's per-bucket predictions land within 2x of the measured
        histogram p50 for BOTH lowerings, across cells and noise."""
        from horovod_tpu.topo import fit

        truth = Topology(
            num_slices=2, slice_size=4, ici_gbps=80.0, dcn_gbps=8.0,
            ici_latency_s=2e-6, dcn_latency_s=30e-6,
            phase_overhead_s=150e-6,
        )
        topo.set_topology_override(T24)  # fit anchors to current()
        _record_ring_observations(truth, noise=0.10)
        fp = fit.refresh(force=True)
        assert fp is not None and fp.topo_key == (2, 4)
        cells = fit.observed_cells()
        assert len(cells) == 8  # 2 lowerings x 4 size bins
        for c in cells:
            pred = T24.estimate_cost(
                "all_reduce", int(c.mean_nbytes), c.lowering,
                c.axis_size,
            )
            assert 0.5 <= pred / c.p50_s <= 2.0, (c, pred)

    def test_choose_lowering_tracks_fitted_parameters(self, monkeypatch):
        """A pod whose measured phase overhead dwarfs its wire time
        must flip big buckets back to flat — even though the static
        env model prices them hier."""
        from horovod_tpu.topo import fit

        topo.set_topology_override(T24)
        assert T24.choose_lowering("all_reduce", 16 << 20) == "hier"
        # ground truth: launches cost 5 ms, links are fast -> the
        # hier three-phase staging can never win
        truth = Topology(
            num_slices=2, slice_size=4, ici_gbps=100.0, dcn_gbps=50.0,
            ici_latency_s=1e-6, dcn_latency_s=2e-6,
            phase_overhead_s=5e-3,
        )
        _record_ring_observations(truth, noise=0.05)
        assert fit.refresh(force=True) is not None
        assert T24.choose_lowering("all_reduce", 16 << 20) == "flat"
        # the kill switch restores static pricing (and the decision)
        monkeypatch.setenv("HVD_TPU_TOPO_FIT", "off")
        assert T24.choose_lowering("all_reduce", 16 << 20) == "hier"

    def test_fitted_gauges_and_counters_exported(self):
        from horovod_tpu.topo import fit

        topo.set_topology_override(T24)
        _record_ring_observations(T24)
        assert fit.refresh(force=True) is not None
        assert metrics.get_gauge("topo.fitted_ici_gbps") > 0
        assert metrics.get_gauge("topo.fitted_dcn_gbps") > 0
        assert metrics.get_gauge("topo.fitted_phase_overhead_us") >= 0
        assert metrics.get_gauge("topo.fit.cells") == 8
        assert metrics.get_counter("topo.fit.updates") >= 1

    def test_underdetermined_observations_keep_static_pricing(self):
        from horovod_tpu.topo import fit

        topo.set_topology_override(T24)
        # one cell < MIN_CELL_OBS samples: no fit, static stands
        fit.record_observation("all_reduce", "flat", 1 << 20, 8, 1e-3)
        assert fit.refresh(force=True) is None
        assert fit.fitted_params(T24) is None
        static = T24.estimate_cost("all_reduce", 1 << 20, "flat")
        assert static == pytest.approx(
            T24.phase_overhead_s + 2 * 7 * T24.dcn_latency_s
            + 2 * (1 << 20) * 7 / 8 / (T24.dcn_gbps * 1e9)
        )

    def test_fit_never_leaks_onto_other_shapes(self):
        from horovod_tpu.topo import fit

        topo.set_topology_override(T24)
        _record_ring_observations(T24)
        assert fit.refresh(force=True) is not None
        other = Topology(num_slices=4, slice_size=2)
        assert fit.fitted_params(other) is None
        assert fit.fitted_params(T24) is not None

    def test_record_observation_drops_degenerate_inputs(self):
        from horovod_tpu.topo import fit

        fit.record_observation("all_reduce", "flat", 1 << 20, 1, 1e-3)
        fit.record_observation("all_reduce", "flat", 0, 8, 1e-3)
        fit.record_observation("all_reduce", "weird", 1 << 20, 8, 1e-3)
        fit.record_observation("broadcast", "flat", 1 << 20, 8, 1e-3)
        fit.record_observation("all_reduce", "flat", 1 << 20, 8, -1.0)
        assert fit.observed_cells() == []

    def test_eager_allreduce_feeds_tagged_cells(self, hvd_module):
        """The PR 2 dispatch histograms now carry (lowering, size,
        axis) tags: one eager allreduce lands in a topo.obs cell."""
        from horovod_tpu.topo import fit

        metrics.reset_counters(fit.OBS_PREFIX)
        x = jnp.ones((N, 16), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(x, average=True)), np.ones((N, 16))
        )
        snap = metrics.snapshot()["histograms"]
        names = [k for k in snap if k.startswith("topo.obs.all_reduce.")]
        assert names, f"no tagged cells in {sorted(snap)[:10]}"
        name = names[0]
        assert f".n{N}." in name and ".flat." in name
        assert metrics.get_counter(name + ".bytes") == x.nbytes

    def test_nonphysical_fit_rejected(self):
        """Latencies that DECREASE with payload cannot satisfy the ring
        model with positive bandwidth: the fit must reject itself and
        leave static pricing in place."""
        from horovod_tpu.topo import fit

        topo.set_topology_override(T24)
        for i, nb in enumerate((1 << 12, 1 << 16, 1 << 20, 1 << 24)):
            for _ in range(6):
                fit.record_observation(
                    "all_reduce", "flat", nb, 8, 1e-2 / (10.0 ** i)
                )
        fit.refresh(force=True)
        fp = fit.fitted_params(T24)
        if fp is not None:  # a fit may survive via clamps...
            assert fp.dcn_gbps > 0  # ...but never go non-physical


# ----------------------------------------------- hierarchical primitives

def _shard_run(fn, *args, mesh=None, n_out=1):
    mesh = mesh or get_runtime().mesh
    specs = (P(WORLD_AXIS),) * len(args)
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=specs,
        out_specs=(P(WORLD_AXIS),) * n_out if n_out > 1 else P(WORLD_AXIS),
        check_vma=False,
    ))(*args)


class TestHierarchicalPrimitives:
    @pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16, np.int32],
                             ids=str)
    def test_all_reduce_matches_flat(self, hvd_module, dtype):
        rng = np.random.RandomState(0)
        if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            x = rng.uniform(-2, 2, (N, 33)).astype(dtype)
            tol = 1e-2 if dtype == jnp.bfloat16 else 1e-6
        else:
            x = rng.randint(0, 7, (N, 33)).astype(dtype)
            tol = 0

        def f(a):
            flat = jax.lax.psum(a, WORLD_AXIS)
            hier = topo.hierarchical_all_reduce(
                a, WORLD_AXIS, op=Sum, topo=T24
            )
            return flat, hier

        flat, hier = _shard_run(f, x, n_out=2)
        np.testing.assert_allclose(
            np.asarray(flat, np.float64), np.asarray(hier, np.float64),
            rtol=tol, atol=tol,
        )

    def test_all_reduce_bitwise_on_exact_sums(self, hvd_module):
        """Integer-valued f32: every partial sum is exactly
        representable, so flat and hier agree bit for bit regardless of
        summation order."""
        x = np.random.RandomState(1).randint(-8, 9, (N, 130)).astype(
            np.float32
        )

        def f(a):
            return jax.lax.psum(a, WORLD_AXIS), \
                topo.hierarchical_all_reduce(a, WORLD_AXIS, op=Sum,
                                             topo=T24)

        flat, hier = _shard_run(f, x, n_out=2)
        np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))

    def test_average_matches_pmean(self, hvd_module):
        x = np.random.RandomState(2).randn(N, 17).astype(np.float32)

        def f(a):
            return jax.lax.pmean(a, WORLD_AXIS), \
                topo.hierarchical_all_reduce(a, WORLD_AXIS, op=Average,
                                             topo=T24)

        flat, hier = _shard_run(f, x, n_out=2)
        np.testing.assert_allclose(np.asarray(flat), np.asarray(hier),
                                   rtol=1e-6, atol=1e-6)

    def test_rs_ag_roundtrip_matches_flat(self, hvd_module):
        x = np.random.RandomState(3).randn(N, 41).astype(np.float32)

        def f(a):
            shard = topo.hierarchical_reduce_scatter(
                a, WORLD_AXIS, op=Sum, topo=T24
            )
            out = topo.hierarchical_all_gather(shard, WORLD_AXIS, topo=T24)
            return jax.lax.psum(a, WORLD_AXIS), \
                out[:a.size].reshape(a.shape)

        flat, rt = _shard_run(f, x, n_out=2)
        np.testing.assert_allclose(np.asarray(flat), np.asarray(rt),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("wire", ["bf16", "int8", "fp8"])
    def test_wire_compresses_only_dcn_hop(self, hvd_module, wire):
        """A compressed wire on the hier path still lands within the
        DCN hop's quantization error of the flat sum — the ICI phases
        are exact."""
        x = np.random.RandomState(4).randn(N, 700).astype(np.float32)

        def f(a):
            return jax.lax.psum(a, WORLD_AXIS), \
                topo.hierarchical_all_reduce(a, WORLD_AXIS, op=Sum,
                                             topo=T24, wire=wire)

        flat, hier = _shard_run(f, x, n_out=2)
        # fp8 e4m3 keeps only 3 mantissa bits: coarser grid than int8's
        tol = dict(rtol=0.12, atol=0.6) if wire == "fp8" else \
            dict(rtol=0.05, atol=0.08)
        np.testing.assert_allclose(
            np.asarray(flat), np.asarray(hier), **tol
        )

    def test_single_slice_degenerates_to_flat_psum(self, hvd_module):
        x = np.random.RandomState(5).randn(N, 9).astype(np.float32)
        single = Topology(num_slices=1, slice_size=8)

        def f(a):
            return jax.lax.psum(a, WORLD_AXIS), \
                topo.hierarchical_all_reduce(a, WORLD_AXIS, op=Sum,
                                             topo=single)

        flat, hier = _shard_run(f, x, n_out=2)
        # identical lowering -> bitwise, not just close
        np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))

    def test_factored_sub_axes_mode(self, hvd_module):
        """split_axis machinery: a mesh factored into (hvd_dcn,
        hvd_ici) sub-axes runs the hierarchy over the named axes with
        no groups."""
        from horovod_tpu.parallel import split_axis, sub_axis_names

        mesh = split_axis(get_runtime().mesh, WORLD_AXIS, 4)
        names = sub_axis_names(WORLD_AXIS)
        x = np.random.RandomState(6).randn(N, 21).astype(np.float32)

        def f(a):
            return jax.lax.psum(a, names), \
                topo.hierarchical_all_reduce(a, names, op=Sum)

        spec = P(names)
        flat, hier = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(spec,), out_specs=(spec, spec),
            check_vma=False,
        ))(x)
        np.testing.assert_allclose(np.asarray(flat), np.asarray(hier),
                                   rtol=1e-6, atol=1e-6)

    def test_split_axis_validates(self, hvd_module):
        from horovod_tpu.parallel import split_axis

        mesh = get_runtime().mesh
        with pytest.raises(ValueError, match="factor"):
            split_axis(mesh, WORLD_AXIS, 3)
        with pytest.raises(ValueError, match="no axis"):
            split_axis(mesh, "nope", 2)


# ------------------------------------------------- scheduler integration

def _losses(cfg, steps=12):
    X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    Y = (X @ np.full((4, 2), 0.7)).astype(np.float32)

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

    params = {"w1": jnp.full((4, 4), 0.2), "w2": jnp.full((4, 2), 0.5),
              "b": jnp.zeros((2,))}
    sched.set_config_override(cfg)
    try:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        step = hvd.distributed_train_step(loss_fn, tx)
        st = step.init(params)
        batch = (jnp.asarray(X), jnp.asarray(Y))
        out = []
        for _ in range(steps):
            params, st, loss = step(params, st, batch)
            out.append(float(loss))
        return out
    finally:
        sched.set_config_override(None)


class TestSchedulerLowering:
    def test_plan_stamps_cost_model_choice(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        cfg = sched.SchedConfig(bucket_bytes=1 << 20, lowering="auto")
        small = sched.build_schedule([256] * 4, ["float32"] * 4, cfg)
        big = sched.build_schedule(
            [8 << 20] * 4, ["float32"] * 4, cfg
        )
        assert all(b.lowering == "flat" for b in small.buckets)
        assert all(b.lowering == "hier" for b in big.buckets)
        # lowering is part of the plan identity
        assert small.signature() != dataclasses.replace(
            small,
            buckets=tuple(dataclasses.replace(b, lowering="hier")
                          for b in small.buckets),
        ).signature()

    def test_single_slice_plan_is_flat_and_unchanged(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "1x8")
        cfg_auto = sched.SchedConfig(bucket_bytes=1024, lowering="auto")
        cfg_off = sched.SchedConfig(bucket_bytes=1024, lowering="off")
        a = sched.build_schedule([4096] * 3, ["float32"] * 3, cfg_auto)
        b = sched.build_schedule([4096] * 3, ["float32"] * 3, cfg_off)
        assert a.signature() == b.signature()
        assert all(bk.lowering == "flat" for bk in a.buckets)

    @pytest.mark.parametrize("mode", ["allreduce", "reduce_scatter"])
    def test_hier_losses_match_flat(self, hvd_module, monkeypatch, mode):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        flat = _losses(sched.SchedConfig(
            bucket_bytes=64, mode=mode, lowering="flat"))
        hier = _losses(sched.SchedConfig(
            bucket_bytes=64, mode=mode, lowering="hier"))
        np.testing.assert_allclose(flat, hier, rtol=1e-6, atol=1e-6)

    def test_single_slice_auto_bitwise_identical_to_off(
        self, hvd_module, monkeypatch
    ):
        monkeypatch.setenv("HVD_TPU_TOPO", "1x8")
        auto = _losses(sched.SchedConfig(bucket_bytes=64, lowering="auto"))
        off = _losses(sched.SchedConfig(bucket_bytes=64, lowering="off"))
        assert auto == off  # bitwise: identical floats, not just close

    def test_topo_metrics_flow(self, hvd_module, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        _losses(sched.SchedConfig(bucket_bytes=64, lowering="flat"))
        dcn_flat = metrics.get_gauge("topo.dcn_bytes")
        _losses(sched.SchedConfig(bucket_bytes=64, lowering="hier"))
        dcn_hier = metrics.get_gauge("topo.dcn_bytes")
        ici_hier = metrics.get_gauge("topo.ici_bytes")
        assert dcn_hier and dcn_hier > 0
        assert ici_hier and ici_hier > 0
        assert dcn_flat / dcn_hier == pytest.approx(4.0)
        assert metrics.get_gauge(
            "topo.buckets", {"lowering": "hier"}
        ) >= 1
        assert metrics.get_counter("topo.dcn_bytes_total") > 0

    def test_hier_with_quantized_wire(self, hvd_module, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        flat = _losses(sched.SchedConfig(bucket_bytes=64, lowering="flat"))
        hq = _losses(sched.SchedConfig(
            bucket_bytes=64, lowering="hier", wire="int8"))
        # only the DCN hop quantizes: close, not identical
        assert abs(flat[-1] - hq[-1]) < 1e-2

    def test_grad_sync_hier_on_hybrid_mesh(self, hvd_module, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        from horovod_tpu.parallel import make_mesh, sync_gradients

        mesh = make_mesh(dp=4, tp=2)
        g = {"a": np.random.RandomState(0).randn(8, 6).astype(np.float32),
             "b": np.random.RandomState(1).randn(8, 6).astype(np.float32)}
        shard_axes = {"a": "", "b": "tp"}

        def f(grads):
            return sync_gradients(grads, shard_axes, axes=("dp", "tp"))

        outs = {}
        for lower in ("flat", "hier"):
            sched.set_config_override(sched.SchedConfig(
                bucket_bytes=64, lowering=lower))
            spec = {"a": P("dp"), "b": P("dp")}
            outs[lower] = jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=(spec,), out_specs=spec,
                check_vma=False,
            ))(g)
            sched.set_config_override(None)
        for key in g:
            np.testing.assert_allclose(
                np.asarray(outs["flat"][key]),
                np.asarray(outs["hier"][key]), rtol=1e-6, atol=1e-6,
            )


class TestZero1Hier:
    def _run(self, cfg):
        X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
        Y = (X @ np.full((4, 2), 0.7)).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

        params = {"w1": jnp.full((4, 4), 0.2),
                  "w2": jnp.full((4, 2), 0.5), "b": jnp.zeros((2,))}
        step = sched.bucketed_zero_step(loss_fn, optax.adam(0.05), cfg=cfg)
        st = step.init(params)
        batch = (jnp.asarray(X), jnp.asarray(Y))
        out = []
        for _ in range(10):
            params, st, loss = step(params, st, batch)
            out.append(float(loss))
        return out, step.schedule

    def test_hier_matches_flat(self, hvd_module, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        flat, _ = self._run(sched.SchedConfig(
            bucket_bytes=64, lowering="flat"))
        hier, sh = self._run(sched.SchedConfig(
            bucket_bytes=64, lowering="hier"))
        assert any(b.lowering == "hier" for b in sh.buckets)
        np.testing.assert_allclose(flat, hier, rtol=1e-6, atol=1e-6)

    def test_hier_shards_on_ici_subaxis(self, hvd_module, monkeypatch):
        """ZeRO state under hier shards k-fold (slice_size), not
        N-fold — the update never crosses DCN."""
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
        Y = (X @ np.full((4, 2), 0.7)).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] - y) ** 2)

        params = {"w": jnp.full((4, 2), 0.5)}
        step = sched.bucketed_zero_step(
            loss_fn, optax.sgd(0.1),
            cfg=sched.SchedConfig(bucket_bytes=1 << 20, lowering="hier"),
        )
        step.init(params)
        from horovod_tpu.sched.zero1 import _layouts

        layouts, _ = _layouts(
            params, 8,
            sched.SchedConfig(bucket_bytes=1 << 20, lowering="hier"),
        )
        assert layouts[0].lowering == "hier"
        assert layouts[0].shards == 4  # slice_size, not world=8


class TestTunerLowering:
    def test_explores_then_freezes(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        tuner = sched.ScheduleTuner(explore_lowering=True)
        seen = []
        for score in (5.0, 3.0, 2.0):  # flat wins
            lo = tuner.lowering()
            seen.append(lo)
            tuner.begin_window()
            metrics.inc_counter("train.steps")
            metrics.observe("train.step_seconds", 1.0 / score)
            metrics.set_gauge("sched.bytes_per_step", 1000)
            tuner.end_window()
        assert seen == ["flat", "hier", "hier_adasum"]
        assert tuner.lowering() == "flat"

    def test_single_slice_skips_exploration(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "1x8")
        tuner = sched.ScheduleTuner(explore_lowering=True)
        assert tuner.lowering() == "flat"

    def test_default_defers_to_cost_model(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        tuner = sched.ScheduleTuner()
        assert tuner.lowering() == "auto"
        cfg = sched.SchedConfig(bucket_bytes=1 << 20)
        schedule = sched.build_schedule(
            [8 << 20] * 2, ["float32"] * 2, cfg, lowering="flat"
        )
        stamped = tuner.apply(schedule)
        assert all(b.lowering == "hier" for b in stamped.buckets)


# --------------------------------------------------- shared tiling error

class TestSharedTilingError:
    def test_process_set_quantized_and_hier_raise_same_type(
        self, hvd_module, monkeypatch
    ):
        """Satellite contract: the non-tiling check lives in one place
        and every consumer raises the same structured error."""
        from horovod_tpu.process_sets import tiling_groups

        with pytest.raises(ProcessSetTilingError) as e1:
            tiling_groups([0, 1, 2], 8)
        assert e1.value.world_size == 8 and e1.value.ranks == (0, 1, 2)

        single = Topology(num_slices=1, slice_size=8)
        with pytest.raises(ProcessSetTilingError):
            single.axis_groups(8)

        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        ps = hvd.add_process_set([0, 1, 2])
        try:
            from horovod_tpu.ops.quantized import quantized_allreduce

            def f(a):
                return quantized_allreduce(
                    a, WORLD_AXIS, op=Sum, process_set=ps
                )

            with pytest.raises(ProcessSetTilingError, match="tile"):
                _shard_run(
                    f, np.ones((N, 512), np.float32)
                )
        finally:
            hvd.remove_process_set(ps)

    def test_partition_groups_still_returns_none(self, hvd_module):
        """Back-compat: the table API keeps its Optional contract."""
        from horovod_tpu.process_sets import ProcessSet

        table = get_runtime().process_set_table
        assert table.partition_groups(table.global_set) is None
        ps = ProcessSet([0, 1, 2])
        ps.process_set_id = 99  # detached; only tiling logic matters
        assert table.partition_groups(ps) is None  # 5 % 3 != 0
