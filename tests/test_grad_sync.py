"""sync_gradients: the per-parameter pmean/psum rule for hybrid
parallelism, validated by multi-step training equivalence — a dp×tp
sharded model trained with sync_gradients must track single-device
training on the same global weights step for step."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import gpt_tiny
from horovod_tpu.models.transformer import param_shard_axes
from horovod_tpu.parallel import make_mesh, sync_gradients


def test_param_shard_axes_classification():
    model = gpt_tiny(moe_every=2, num_experts_local=2)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    axes = param_shard_axes(params, model.cfg)

    assert axes["block_0"]["attn"]["qkv"]["Dense_0"]["kernel"] == "tp"
    assert axes["block_0"]["attn"]["qkv"]["Dense_0"]["bias"] == "tp"
    assert axes["block_0"]["attn"]["proj"]["Dense_0"]["kernel"] == "tp"
    assert axes["block_0"]["attn"]["proj"]["bias"] == ""
    assert axes["block_0"]["mlp"]["wi"]["Dense_0"]["kernel"] == "tp"
    assert axes["block_0"]["mlp"]["wo"]["Dense_0"]["kernel"] == "tp"
    assert axes["block_0"]["mlp"]["wo"]["bias"] == ""
    # block_1 is the MoE block (moe_every=2)
    assert axes["block_1"]["moe"]["wi"] == "ep"
    assert axes["block_1"]["moe"]["wo"] == "ep"
    assert axes["block_1"]["moe"]["router"]["kernel"] == ""
    assert axes["wte"]["embedding"] == ""
    assert axes["wpe"] == ""
    assert axes["ln_f"]["scale"] == ""


def test_hybrid_dp_tp_training_matches_single_device():
    """3 SGD steps on a dp=2 × tp=4 mesh == 3 steps on one device.

    The model mixes a replicated input projection (grad must be
    psum'd over tp, pmean'd over dp) with a column/row TP MLP (grad
    local over tp, pmean'd over dp)."""
    d, hidden, n_tp, n_dp = 8, 16, 4, 2
    hloc = hidden // n_tp
    key = jax.random.PRNGKey(7)
    k0, k1, k2, kx, kt = jax.random.split(key, 5)
    w_rep = jax.random.normal(k0, (d, d)) * 0.3
    wi = jax.random.normal(k1, (d, hidden)) * 0.3
    wo = jax.random.normal(k2, (hidden, d)) * 0.3
    bo = jnp.zeros((d,))
    x = jax.random.normal(kx, (8, d))
    tgt = jax.random.normal(kt, (8, d))
    lr = 0.1

    def forward(w_rep, wi, wo, bo, x):
        h = nn.gelu(x @ w_rep)
        return nn.gelu(h @ wi) @ wo + bo

    # ---- single-device reference: 3 SGD steps on global weights ----
    ref = {"w_rep": w_rep, "wi": wi, "wo": wo, "bo": bo}

    def ref_loss(p):
        y = forward(p["w_rep"], p["wi"], p["wo"], p["bo"], x)
        return jnp.mean((y - tgt) ** 2)

    for _ in range(3):
        g = jax.grad(ref_loss)(ref)
        ref = jax.tree.map(lambda p, g: p - lr * g, ref, g)

    # ---- sharded: stacked tp shards, batch sharded over dp ----
    params = {
        "w_rep": w_rep,
        "wi": wi.reshape(d, n_tp, hloc).transpose(1, 0, 2),   # [tp, d, hloc]
        "wo": wo.reshape(n_tp, hloc, d),                      # [tp, hloc, d]
        "bo": bo,
    }
    shard_axes = {"w_rep": "", "wi": "tp", "wo": "tp", "bo": ""}
    specs = {"w_rep": P(), "wi": P("tp"), "wo": P("tp"), "bo": P()}
    mesh = make_mesh(dp=n_dp, tp=n_tp)

    def step(p, x, tgt):
        def loss_fn(p):
            y = nn.gelu(x @ p["w_rep"])
            y = nn.gelu(y @ p["wi"][0]) @ p["wo"][0]
            y = jax.lax.psum(y, "tp") + p["bo"]
            return jnp.mean((y - tgt) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(p)
        g = sync_gradients(g, shard_axes, axes=("dp", "tp"))
        return jax.tree.map(lambda p, g: p - lr * g, p, g), loss

    f = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(specs, P("dp"), P("dp")),
        out_specs=(specs, P()),
        check_vma=False,
    ))
    for _ in range(3):
        params, loss = f(params, x, tgt)

    np.testing.assert_allclose(
        np.asarray(params["w_rep"]), np.asarray(ref["w_rep"]), atol=1e-5
    )
    got_wi = np.asarray(params["wi"]).transpose(1, 0, 2).reshape(d, hidden)
    np.testing.assert_allclose(got_wi, np.asarray(ref["wi"]), atol=1e-5)
    got_wo = np.asarray(params["wo"]).reshape(hidden, d)
    np.testing.assert_allclose(got_wo, np.asarray(ref["wo"]), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(params["bo"]), np.asarray(ref["bo"]), atol=1e-5
    )


class TestRule2x2Mesh:
    """pmean-vs-divide rule pinned on a 2×2 dp×tp mesh: replicated,
    tp-sharded, and mixed ``param_shard_axes`` pytrees — and the
    scheduler-mode exchange must match the reference per-leaf path
    bit-for-bit in f32."""

    def _mesh(self):
        return make_mesh(dp=2, tp=2, devices=jax.devices()[:4])

    def _run(self, scheduled):
        mesh = self._mesh()
        # distinct per-device blocks: x is sharded over (dp, tp)
        x = jnp.arange(16.0, dtype=jnp.float32).reshape(4, 4)
        axes_tree = {"rep": "", "tp": "tp", "mix": ""}

        def fn(x):
            g = {"rep": x, "tp": x * 2.0, "mix": x + 1.0}
            return sync_gradients(
                g, axes_tree, axes=("dp", "tp"), scheduled=scheduled
            )

        spec = {"rep": P("dp", "tp"), "tp": P("dp", "tp"),
                "mix": P("dp", "tp")}
        f = jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(P("dp", "tp"),), out_specs=spec,
            check_vma=False,
        ))
        return jax.tree.map(np.asarray, f(x))

    @staticmethod
    def _blocks(arr):
        """(dp, tp) -> 2x2 block of the 4x4 array."""
        return {
            (d, t): arr[2 * d:2 * d + 2, 2 * t:2 * t + 2]
            for d in range(2) for t in range(2)
        }

    def _expected(self):
        x = np.arange(16.0, dtype=np.float32).reshape(4, 4)
        xb = self._blocks(x)
        out = {"rep": np.zeros_like(x), "tp": np.zeros_like(x),
               "mix": np.zeros_like(x)}
        for d in range(2):
            for t in range(2):
                # replicated: pmean over dp AND tp
                out["rep"][2 * d:2 * d + 2, 2 * t:2 * t + 2] = np.mean(
                    [xb[(dd, tt)] for dd in range(2) for tt in range(2)],
                    axis=0,
                )
                # tp-sharded: pmean over dp only, then divide by |tp|
                out["tp"][2 * d:2 * d + 2, 2 * t:2 * t + 2] = (
                    (xb[(0, t)] * 2 + xb[(1, t)] * 2) / 2 / 2
                )
                # replicated again, shifted input
                out["mix"][2 * d:2 * d + 2, 2 * t:2 * t + 2] = np.mean(
                    [xb[(dd, tt)] + 1 for dd in range(2)
                     for tt in range(2)], axis=0,
                )
        return out

    def test_rule_replicated_tp_sharded_mixed(self):
        got = self._run(scheduled=False)
        want = self._expected()
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-6)

    def test_scheduler_mode_bit_for_bit(self):
        """Scheduler-mode exchange == reference per-leaf path, exact
        f32 equality (pmean is elementwise; bucketing moves no value)."""
        ref = self._run(scheduled=False)
        got = self._run(scheduled=True)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k])

    def test_scheduler_mode_matches_rule(self):
        got = self._run(scheduled=True)
        want = self._expected()
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-6)


def test_sync_gradients_default_replicated():
    """With no shard-axes tree every grad is pmean'd over the data axes
    (pure-DP semantics, matching DistributedOptimizer)."""
    mesh = make_mesh(dp=8)
    g = jnp.arange(8.0)

    def fn(g):
        out = sync_gradients({"w": g}, axes=("dp",))
        return out["w"]

    f = jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
        check_vma=False,
    ))
    out = np.asarray(f(g))
    np.testing.assert_allclose(out, np.full(8, np.mean(np.arange(8.0))))
