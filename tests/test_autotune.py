"""FusionAutotuner tests (reference analog: parameter_manager logic)."""

import numpy as np
import pytest

from horovod_tpu.utils.autotune import FusionAutotuner


def _synthetic_score(threshold_bytes: float) -> float:
    # bell curve peaking at 4MB
    x = np.log2(threshold_bytes)
    return float(np.exp(-0.5 * ((x - 22.0) / 2.0) ** 2))


def test_autotuner_converges_near_peak():
    tuner = FusionAutotuner(low_bytes=1 << 16, high_bytes=1 << 28,
                            warmup_windows=12)
    while not tuner.converged:
        thr = tuner.threshold_bytes()
        tuner.observe(_synthetic_score(thr))
    best = tuner.threshold_bytes()
    assert tuner.converged
    # frozen threshold stable
    assert tuner.threshold_bytes() == best
    assert abs(np.log2(best) - 22.0) < 3.0


def test_autotuner_log(tmp_path):
    log = tmp_path / "autotune.csv"
    tuner = FusionAutotuner(warmup_windows=3, log_path=str(log))
    while not tuner.converged:
        tuner.observe(_synthetic_score(tuner.threshold_bytes()))
    lines = log.read_text().strip().splitlines()
    assert len(lines) == 3


def test_reference_autotune_subknobs(monkeypatch):
    """Reference parameter_manager tunables map onto ours:
    BAYES_OPT_MAX_SAMPLES = explore budget, WARMUP_SAMPLES = leading
    samples discarded before scoring, STEPS_PER_SAMPLE = window
    length."""
    from horovod_tpu.utils.autotune import AutotuneDriver, FusionAutotuner

    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "3")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "2")
    t = FusionAutotuner()
    assert t.warmup_windows == 3
    for _ in range(2):  # discarded warmup samples: no convergence credit
        t.threshold_bytes()
        t.observe(1.0)
    assert not t.converged
    for _ in range(3):
        t.threshold_bytes()
        t.observe(1.0)
    assert t.converged

    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "5")
    d = AutotuneDriver()
    assert d.window_steps == 5


def test_autotune_nonpositive_warmup_clamped(monkeypatch):
    from horovod_tpu.utils.autotune import FusionAutotuner

    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "0")
    t = FusionAutotuner()
    assert t.warmup_windows == 1
    assert t.threshold_bytes() > 0  # no IndexError on the grid path
