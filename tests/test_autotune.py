"""FusionAutotuner tests (reference analog: parameter_manager logic)."""

import numpy as np
import pytest

from horovod_tpu.utils.autotune import FusionAutotuner


def _synthetic_score(threshold_bytes: float) -> float:
    # bell curve peaking at 4MB
    x = np.log2(threshold_bytes)
    return float(np.exp(-0.5 * ((x - 22.0) / 2.0) ** 2))


def test_autotuner_converges_near_peak():
    tuner = FusionAutotuner(low_bytes=1 << 16, high_bytes=1 << 28,
                            warmup_windows=12)
    while not tuner.converged:
        thr = tuner.threshold_bytes()
        tuner.observe(_synthetic_score(thr))
    best = tuner.threshold_bytes()
    assert tuner.converged
    # frozen threshold stable
    assert tuner.threshold_bytes() == best
    assert abs(np.log2(best) - 22.0) < 3.0


def test_autotuner_log(tmp_path):
    log = tmp_path / "autotune.csv"
    tuner = FusionAutotuner(warmup_windows=3, log_path=str(log))
    while not tuner.converged:
        tuner.observe(_synthetic_score(tuner.threshold_bytes()))
    lines = log.read_text().strip().splitlines()
    assert len(lines) == 3


def test_reference_autotune_subknobs(monkeypatch):
    """Reference parameter_manager tunables map onto ours:
    BAYES_OPT_MAX_SAMPLES = explore budget, WARMUP_SAMPLES = leading
    samples discarded before scoring, STEPS_PER_SAMPLE = window
    length."""
    from horovod_tpu.utils.autotune import AutotuneDriver, FusionAutotuner

    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "3")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "2")
    t = FusionAutotuner()
    assert t.warmup_windows == 3
    for _ in range(2):  # discarded warmup samples: no convergence credit
        t.threshold_bytes()
        t.observe(1.0)
    assert not t.converged
    for _ in range(3):
        t.threshold_bytes()
        t.observe(1.0)
    assert t.converged

    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "5")
    d = AutotuneDriver()
    assert d.window_steps == 5


def test_autotune_nonpositive_warmup_clamped(monkeypatch):
    from horovod_tpu.utils.autotune import FusionAutotuner

    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "0")
    t = FusionAutotuner()
    assert t.warmup_windows == 1
    assert t.threshold_bytes() > 0  # no IndexError on the grid path


class TestJointKnobSchedule:
    """Third knob (quantized wire) + joint refinement (VERDICT r5
    item 8): the schedule threshold -> hier -> quant -> refine must find
    interaction effects pure sequential freezing misses."""

    @staticmethod
    def _driver(monkeypatch, surface, quant_eligible=True):
        from horovod_tpu.utils.autotune import AutotuneDriver

        monkeypatch.setenv("HVD_TPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_HIER_WINDOWS", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_EXPLORE_QUANTIZED", "1")
        d = AutotuneDriver(window_steps=2, quant_eligible=quant_eligible)
        monkeypatch.setattr(d, "_hier_explorable", lambda: True)

        def run():
            for _ in range(50):
                if d.converged:
                    break
                cfg = (bool(d.hierarchical()), bool(d.quantized()))
                d._observe_window(surface[cfg])
            assert d.converged
            return (bool(d.hierarchical()), bool(d.quantized()))

        return d, run

    def test_joint_refinement_beats_sequential_freeze(self, monkeypatch):
        # Interaction surface: hier HURTS alone, quant helps alone, and
        # hier+quant together is the true optimum.  Sequential freezing
        # (round-4 behavior: hier probed at quant=off, then frozen
        # forever) lands on (flat, int8) = 1.2; the refinement
        # round-trip re-probes hier at the quantized winner and finds
        # 1.5 — better than the threshold-only (1.0) and
        # sequential-freeze (1.2) schedules.
        surface = {
            (False, False): 1.0,
            (True, False): 0.9,
            (False, True): 1.2,
            (True, True): 1.5,
        }
        d, run = self._driver(monkeypatch, surface)
        final = run()
        assert final == (True, True), final
        assert surface[final] > 1.2  # sequential-freeze endpoint
        assert d.quantized() is True
        assert d.hierarchical() is True

    def test_refinement_keeps_hier_when_flip_loses(self, monkeypatch):
        # No interaction: quant helps, hier always hurts -> the refine
        # probe flips hier, sees a worse score, and keeps it off.
        surface = {
            (False, False): 1.0,
            (True, False): 0.8,
            (False, True): 1.3,
            (True, True): 1.1,
        }
        d, run = self._driver(monkeypatch, surface)
        final = run()
        assert final == (False, True), final

    def test_quant_rejected_when_slower(self, monkeypatch):
        surface = {
            (False, False): 1.0,
            (True, False): 0.8,
            (False, True): 0.7,
            (True, True): 0.6,
        }
        d, run = self._driver(monkeypatch, surface)
        final = run()
        assert final == (False, False), final
        # frozen-off freezes to None (keeps the baseline variant)
        assert d.quantized() is None

    def test_quant_skipped_without_opt_in(self, monkeypatch):
        from horovod_tpu.utils.autotune import AutotuneDriver

        monkeypatch.setenv("HVD_TPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "1")
        monkeypatch.setenv("HVD_TPU_AUTOTUNE_HIER_WINDOWS", "1")
        monkeypatch.delenv("HVD_TPU_AUTOTUNE_EXPLORE_QUANTIZED",
                           raising=False)
        d = AutotuneDriver(window_steps=2, quant_eligible=True)
        monkeypatch.setattr(d, "_hier_explorable", lambda: False)
        for _ in range(10):
            if d.converged:
                break
            d._observe_window(1.0)
        assert d.converged
        assert d.quantized() is None  # never probed

    def test_reject_quantized_freezes_off(self, monkeypatch):
        surface = {
            (False, False): 1.0,
            (True, False): 0.8,
            (False, True): 2.0,
            (True, True): 2.0,
        }
        d, run = self._driver(monkeypatch, surface)
        # simulate the step builder refusing the probe variant
        for _ in range(50):
            if d.converged:
                break
            if d.quantized() is True:
                d.reject_quantized()
                continue
            cfg = (bool(d.hierarchical()), bool(d.quantized()))
            d._observe_window(surface[cfg])
        assert d.converged
        assert d.quantized() is None
