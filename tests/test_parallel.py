"""parallel/ package: ring attention, Ulysses, TP, PP, MoE vs
single-device reference math on the 8-device CPU mesh (the TPU analog
of the reference's test/parallel tier, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import (
    ColumnParallelDense,
    ParallelConfig,
    RowParallelDense,
    make_mesh,
    moe_alltoall_dispatch,  # noqa: F401  (public API smoke)
    pipeline_apply,
    ring_attention,
    ulysses_attention,
)
from horovod_tpu.parallel.moe import MoELayer
from horovod_tpu.parallel.ring_attention import full_attention
from horovod_tpu.parallel.tensor import TensorParallelMLP


# ---------------------------------------------------------------- mesh

class TestMakeMesh:
    def test_degrees(self):
        mesh = make_mesh(dp=2, tp=4)
        assert mesh.shape == {"dp": 2, "tp": 4}

    def test_infer(self):
        mesh = make_mesh(dp=-1, tp=2)
        assert mesh.shape == {"dp": 4, "tp": 2}

    def test_axis_order_outer_to_inner(self):
        mesh = make_mesh(dp=2, sp=2, tp=2)
        assert tuple(mesh.axis_names) == ("dp", "sp", "tp")

    def test_bad_product(self):
        with pytest.raises(ValueError):
            make_mesh(dp=3, tp=2)

    def test_config_total(self):
        assert ParallelConfig(dp=2, tp=4).total == 8


# ------------------------------------------------------ ring attention

def _qkv(b=2, t=32, h=4, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    q, k, v = _qkv()
    mesh = make_mesh(sp=8)
    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(f)(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_grads_flow():
    q, k, v = _qkv(t=16)
    mesh = make_mesh(sp=8)

    def loss(q, k, v):
        f = shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis="sp", causal=True),
            mesh=mesh,
            in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"),
        )
        return jnp.sum(f(q, k, v) ** 2)

    g = jax.jit(jax.grad(loss))(q, k, v)
    assert np.isfinite(np.asarray(g)).all()

    def ref_loss(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g_ref = jax.jit(jax.grad(ref_loss))(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)


# ------------------------------------------------------------- ulysses

@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    q, k, v = _qkv(h=8)
    mesh = make_mesh(sp=8)
    f = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"),
    )
    out = jax.jit(f)(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(h=4)  # 4 heads on an 8-way axis
    mesh = make_mesh(sp=8)
    f = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis="sp"),
        mesh=mesh,
        in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"),
    )
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(f)(q, k, v)


# ------------------------------------------------------ tensor parallel

def test_tp_mlp_matches_dense():
    d, hidden, b = 16, 32, 4
    key = jax.random.PRNGKey(1)
    k1, k2, k3, kx = jax.random.split(key, 4)
    wi = jax.random.normal(k1, (d, hidden)) * 0.1
    bi = jax.random.normal(k2, (hidden,)) * 0.1
    wo = jax.random.normal(k3, (hidden, d)) * 0.1
    bo = jnp.zeros((d,))
    x = jax.random.normal(kx, (b, d))

    import flax.linen as nn

    ref = jnp.asarray(nn.gelu(x @ wi + bi) @ wo + bo)

    mesh = make_mesh(tp=8)
    mlp = TensorParallelMLP(hidden=hidden, features=d)
    # Shards by hand: column shards of wi/bi, row shards of wo; the
    # row-parallel output bias stays replicated (added after the psum).
    params = {
        "wi_k": wi.reshape(d, 8, hidden // 8).transpose(1, 0, 2),
        "wi_b": bi.reshape(8, hidden // 8),
        "wo_k": wo.reshape(8, hidden // 8, d),
        "wo_b": bo,
    }

    def fn(params, x):
        local = {
            "wi": {"Dense_0": {"kernel": params["wi_k"][0],
                               "bias": params["wi_b"][0]}},
            "wo": {"Dense_0": {"kernel": params["wo_k"][0]},
                   "bias": params["wo_b"]},
        }
        return mlp.apply({"params": local}, x)

    f = shard_map(
        fn, mesh=mesh,
        in_specs=(
            {"wi_k": P("tp"), "wi_b": P("tp"), "wo_k": P("tp"),
             "wo_b": P()},
            P(),
        ),
        out_specs=P(),
    )
    out = jax.jit(f)(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_column_row_single_device_path():
    # Outside shard_map the layers behave as plain dense layers.
    x = jnp.ones((2, 8))
    col = ColumnParallelDense(4)
    p = col.init(jax.random.PRNGKey(0), x)
    assert col.apply(p, x).shape == (2, 4)
    row = RowParallelDense(6)
    p = row.init(jax.random.PRNGKey(0), x)
    assert row.apply(p, x).shape == (2, 6)


# ---------------------------------------------------------- pipeline

def test_pipeline_matches_sequential():
    n, m, b, f = 8, 4, 2, 16
    keys = jax.random.split(jax.random.PRNGKey(2), 3)
    w = jax.random.normal(keys[0], (n, f, f)) * 0.3
    bias = jax.random.normal(keys[1], (n, f)) * 0.1
    x = jax.random.normal(keys[2], (m, b, f))

    def stage(params, h):
        wk, bk = params
        return jnp.tanh(h @ wk + bk)

    ref = x
    for i in range(n):
        ref = jnp.tanh(ref @ w[i] + bias[i])

    mesh = make_mesh(pp=8)

    def fn(w, bias, x):
        return pipeline_apply(stage, (w[0], bias[0]), x, axis="pp")

    f_sharded = shard_map(
        fn, mesh=mesh,
        in_specs=(P("pp"), P("pp"), P()),
        out_specs=P(),
    )
    out = jax.jit(f_sharded)(w, bias, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_differentiable():
    n, m, b, f = 8, 2, 1, 8
    w = jax.random.normal(jax.random.PRNGKey(3), (n, f, f)) * 0.3
    x = jnp.ones((m, b, f))
    mesh = make_mesh(pp=8)

    def loss(w):
        def stage(wk, h):
            return jnp.tanh(h @ wk)

        f_sharded = shard_map(
            lambda w, x: pipeline_apply(stage, w[0], x, axis="pp"),
            mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        )
        return jnp.sum(f_sharded(w, x) ** 2)

    g = jax.jit(jax.grad(loss))(w)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0  # every stage got gradient
    # Reference gradient from the sequential computation.
    def ref_loss(w):
        h = x
        for i in range(n):
            h = jnp.tanh(h @ w[i])
        return jnp.sum(h ** 2)

    g_ref = jax.jit(jax.grad(ref_loss))(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5)


def test_pipeline_remat_stage_same_grads():
    """remat_stage trades recompute for memory only — identical grads."""
    n, m, b, f = 8, 2, 1, 8
    w = jax.random.normal(jax.random.PRNGKey(5), (n, f, f)) * 0.3
    x = jnp.ones((m, b, f))
    mesh = make_mesh(pp=8)

    def make_loss(remat):
        def loss(w):
            def stage(wk, h):
                return jnp.tanh(h @ wk)

            f_sharded = shard_map(
                lambda w, x: pipeline_apply(
                    stage, w[0], x, axis="pp", remat_stage=remat
                ),
                mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            )
            return jnp.sum(f_sharded(w, x) ** 2)

        return loss

    g_plain = jax.jit(jax.grad(make_loss(False)))(w)
    g_remat = jax.jit(jax.grad(make_loss(True)))(w)
    np.testing.assert_allclose(
        np.asarray(g_remat), np.asarray(g_plain), atol=1e-6
    )


# --------------------------------------------------------------- moe

def test_moe_expert_parallel_matches_reference():
    # 8 devices × 1 expert each, k=1, ample capacity: every token goes
    # to its argmax expert, so the layer must equal per-token expert MLP
    # selection computed densely.
    n, b, t, d, hidden = 8, 1, 16, 8, 16
    e = n
    keys = jax.random.split(jax.random.PRNGKey(4), 5)
    rk = jax.random.normal(keys[0], (d, e)) * 0.5
    rb = jnp.zeros((e,))
    wi = jax.random.normal(keys[1], (e, d, hidden)) * 0.2
    wo = jax.random.normal(keys[2], (e, hidden, d)) * 0.2
    x = jax.random.normal(keys[3], (n * b, t, d))

    import flax.linen as nn

    # Dense reference: compute every expert on every token, select.
    xf = x.reshape(-1, d)
    gates = jax.nn.softmax(xf @ rk + rb, axis=-1)
    choice = jnp.argmax(gates, axis=-1)
    per_expert = jnp.einsum(
        "sd,edh->esh", xf, wi
    )
    per_expert = jnp.einsum("esh,ehd->esd", nn.gelu(per_expert), wo)
    sel = per_expert[choice, jnp.arange(xf.shape[0])]
    ref = (gates[jnp.arange(xf.shape[0]), choice][:, None] * sel).reshape(
        x.shape
    )

    mesh = make_mesh(ep=8)
    layer = MoELayer(num_experts_local=1, hidden=hidden, k=1,
                     capacity_factor=float(e))

    def fn(params, x):
        local = jax.tree.map(lambda a: a[0], params)  # drop stacked dim
        out, aux = layer.apply({"params": local}, x)
        return out, jax.lax.pmean(aux, "ep")

    params = {
        "router": {"kernel": jnp.tile(rk[None], (n, 1, 1)),
                   "bias": jnp.tile(rb[None], (n, 1))},
        "wi": wi[:, None],   # [E, 1, d, h] → local [1, d, h]
        "wo": wo[:, None],
    }
    f = shard_map(
        fn, mesh=mesh,
        in_specs=(
            {"router": {"kernel": P("ep"), "bias": P("ep")},
             "wi": P("ep"), "wo": P("ep")},
            P("ep"),
        ),
        out_specs=(P("ep"), P()),
    )
    out, aux = jax.jit(f)(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)
    assert np.isfinite(np.asarray(aux)).all()


def test_moe_single_device_path():
    layer = MoELayer(num_experts_local=4, hidden=16, k=2)
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 8, 8))
    params = layer.init(jax.random.PRNGKey(6), x)
    out, aux = layer.apply(params, x)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) > 0
