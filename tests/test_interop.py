"""Torch interop binding (reference ``horovod/torch`` surface tests in
``test/parallel/test_torch.py``, scaled to the DLPack adapter)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu as hvd
from horovod_tpu.interop import torch as hvd_torch

N = 8


def test_torch_allreduce_average(hvd_module):
    t = torch.arange(N * 4, dtype=torch.float32).reshape(N, 4)
    out = hvd_torch.allreduce(t, op=hvd.Average)
    assert torch.is_tensor(out) and out.dtype == torch.float32
    want = np.tile(np.asarray(t).mean(axis=0), (N, 1))
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-6)


def test_torch_broadcast(hvd_module):
    t = torch.arange(N * 3, dtype=torch.float32).reshape(N, 3)
    out = hvd_torch.broadcast(t, root_rank=2)
    want = np.tile(np.asarray(t)[2], (N, 1))
    np.testing.assert_allclose(out.numpy(), want)


def test_torch_allgather_and_alltoall(hvd_module):
    t = torch.ones((N, 2))
    g = hvd_torch.allgather(t)
    assert g.shape[0] == N  # stacked convention: concat of rank rows
    a = hvd_torch.alltoall(torch.arange(N * N, dtype=torch.float32
                                        ).reshape(N, N))
    assert a.shape == (N, N)


def test_torch_broadcast_parameters_state_dict(hvd_module):
    model = torch.nn.Linear(4, 2)
    sd = model.state_dict()
    before = {k: v.clone() for k, v in sd.items()}
    hvd_torch.broadcast_parameters(sd, root_rank=0)
    for k in sd:
        np.testing.assert_allclose(
            sd[k].detach().numpy(), before[k].detach().numpy()
        )


def test_torch_broadcast_optimizer_state(hvd_module):
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    loss = model(torch.randn(4, 3)).sum()
    loss.backward()
    opt.step()
    hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
    # momentum buffers survive the round trip
    state = opt.state_dict()["state"]
    assert any("momentum_buffer" in s for s in state.values())


def test_torch_rejects_non_tensor(hvd_module):
    with pytest.raises(TypeError):
        hvd_torch.allreduce(np.ones((N, 2)))


def test_torch_bf16_allreduce_exact_wire_dtype(hvd_module):
    t = torch.arange(N * 2, dtype=torch.float32).reshape(N, 2).bfloat16()
    out = hvd_torch.allreduce(t, op=hvd.Sum)
    assert out.dtype == torch.bfloat16
    want = np.asarray(t.float()).sum(axis=0)
    np.testing.assert_allclose(
        out.float().numpy(), np.tile(want, (N, 1)), rtol=2e-2
    )


def test_torch_int64_rejected(hvd_module):
    with pytest.raises(TypeError, match="truncated"):
        hvd_torch.allreduce(torch.ones((N, 2), dtype=torch.int64))


class TestInplaceAndAsync:
    """In-place (`*_`) and async (`*_async`) variants (reference
    ``torch/mpi_ops.py:114-887``)."""

    def test_allreduce_inplace(self, hvd_module):
        t = torch.ones(N, 2)
        out = hvd_torch.allreduce_(t, op=hvd.Sum)
        assert out is t
        np.testing.assert_allclose(t.numpy(), float(N))

    def test_broadcast_inplace(self, hvd_module):
        t = torch.arange(N, dtype=torch.float32).reshape(N, 1)
        hvd_torch.broadcast_(t, root_rank=3)
        np.testing.assert_allclose(t.numpy(), 3.0)

    def test_grouped_allreduce_and_inplace(self, hvd_module):
        ts = [torch.ones(N, 2), 2 * torch.ones(N, 3)]
        outs = hvd_torch.grouped_allreduce(ts, op=hvd.Average)
        np.testing.assert_allclose(outs[0].numpy(), 1.0)
        np.testing.assert_allclose(outs[1].numpy(), 2.0)
        hvd_torch.grouped_allreduce_(ts, op=hvd.Sum)
        np.testing.assert_allclose(ts[0].numpy(), float(N))

    def test_allreduce_async_handle(self, hvd_module):
        t = torch.ones(N, 2)
        h = hvd_torch.allreduce_async(t, op=hvd.Sum, name="a")
        assert hvd_torch.poll(h) in (True, False)
        out = hvd_torch.synchronize(h)
        assert torch.is_tensor(out)
        np.testing.assert_allclose(out.numpy(), float(N))
        # original untouched by the non-inplace async variant
        np.testing.assert_allclose(t.numpy(), 1.0)

    def test_allreduce_async_inplace(self, hvd_module):
        t = torch.ones(N, 2)
        h = hvd_torch.allreduce_async_(t, op=hvd.Sum)
        out = hvd_torch.synchronize(h)
        assert out is t
        np.testing.assert_allclose(t.numpy(), float(N))

    def test_broadcast_async_inplace(self, hvd_module):
        t = torch.arange(N, dtype=torch.float32).reshape(N, 1)
        hvd_torch.synchronize(hvd_torch.broadcast_async_(t, root_rank=1))
        np.testing.assert_allclose(t.numpy(), 1.0)

    def test_grouped_allreduce_async(self, hvd_module):
        ts = [torch.ones(N, 2), torch.full((N, 1), 3.0)]
        h = hvd_torch.grouped_allreduce_async_(ts, op=hvd.Average)
        outs = hvd_torch.synchronize(h)
        assert outs[0] is ts[0]
        np.testing.assert_allclose(ts[1].numpy(), 3.0)

    def test_allgather_and_broadcast_async(self, hvd_module):
        t = torch.ones(N, 1, 2)
        out = hvd_torch.synchronize(hvd_torch.allgather_async(t))
        assert out.shape == (N, N, 2)
        out2 = hvd_torch.synchronize(
            hvd_torch.broadcast_async(t, root_rank=0)
        )
        np.testing.assert_allclose(out2.numpy(), 1.0)


class TestSparseAllreduce:
    def test_sparse_allreduce_single_process(self, hvd_module):
        """Single process: the gather set is itself; averaging returns
        the same (coalesced) tensor."""
        i = torch.tensor([[0, 2, 2], [1, 0, 0]])
        v = torch.tensor([1.0, 2.0, 3.0])
        sp = torch.sparse_coo_tensor(i, v, (4, 3))
        h = hvd_torch.sparse_allreduce_async(sp, name="emb")
        out = hvd_torch.synchronize(h)
        assert out.is_sparse
        dense = out.to_dense().numpy()
        want = np.zeros((4, 3), np.float32)
        want[0, 1] = 1.0
        want[2, 0] = 5.0  # duplicate coordinate summed
        np.testing.assert_allclose(dense, want)

    def test_sparse_rejects_dense(self, hvd_module):
        with pytest.raises(ValueError, match="sparse"):
            hvd_torch.sparse_allreduce_async(torch.ones(3, 3))


def test_torch_alltoall_uneven_splits_returns_received(hvd_module):
    """Uneven splits return (output, received_splits) like the
    reference alltoall (torch/mpi_ops.py:361)."""
    # genuinely uneven entries (0/1/2 rows per destination) with equal
    # row totals (the stacked layout's constraint): each rank sends an
    # extra row to its right neighbor and none to the one after
    splits = np.full((N, N), 1)
    for r in range(N):
        splits[r, (r + 1) % N] += 1
        splits[r, (r + 2) % N] -= 1
    t = torch.arange(N * N * 2, dtype=torch.float32).reshape(N, N, 2)
    out, received = hvd_torch.alltoall(t, splits=splits)
    assert torch.is_tensor(out) and torch.is_tensor(received)
    np.testing.assert_array_equal(received.numpy(), splits.T)
    # route check: the first row rank 1 receives is rank 0's first row
    # (rank 0's block for rank 1 starts after its splits[0,0]=1 rows
    # for rank 0... destination 1 offset = splits[0,0])
    full = t.numpy()
    np.testing.assert_allclose(
        out.numpy()[1][0], full[0][int(splits[0, 0])]
    )


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_sparse_allreduce_array_wire():
    """torch sparse COO allreduce rides the padded array wire (int64
    coordinates narrow losslessly); the pickle path is patched out."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import torch

        import horovod_tpu as hvd
        import horovod_tpu.interop.torch as hvd_torch

        hvd.init()

        def no_pickle(*a, **k):
            raise AssertionError("COO payload must not pickle")

        hvd_torch._functions.allgather_object = no_pickle
        r = hvd.process_rank()
        t = torch.sparse_coo_tensor(
            torch.tensor([[0, r + 1]]),          # rank-specific coords
            torch.tensor([1.0, float(r + 1)]),
            size=(4,),
        )
        h = hvd_torch.sparse_allreduce_async(t, op=hvd.Average)
        out = hvd_torch.synchronize(h).to_dense()
        return out.numpy().tolist()

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    # coords 0: (1+1)/2 = 1; coord 1: 1/2; coord 2: 2/2
    for r in results:
        np.testing.assert_allclose(r, [1.0, 0.5, 1.0, 0.0])
