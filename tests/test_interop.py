"""Torch interop binding (reference ``horovod/torch`` surface tests in
``test/parallel/test_torch.py``, scaled to the DLPack adapter)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu as hvd
from horovod_tpu.interop import torch as hvd_torch

N = 8


def test_torch_allreduce_average(hvd_module):
    t = torch.arange(N * 4, dtype=torch.float32).reshape(N, 4)
    out = hvd_torch.allreduce(t, op=hvd.Average)
    assert torch.is_tensor(out) and out.dtype == torch.float32
    want = np.tile(np.asarray(t).mean(axis=0), (N, 1))
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-6)


def test_torch_broadcast(hvd_module):
    t = torch.arange(N * 3, dtype=torch.float32).reshape(N, 3)
    out = hvd_torch.broadcast(t, root_rank=2)
    want = np.tile(np.asarray(t)[2], (N, 1))
    np.testing.assert_allclose(out.numpy(), want)


def test_torch_allgather_and_alltoall(hvd_module):
    t = torch.ones((N, 2))
    g = hvd_torch.allgather(t)
    assert g.shape[0] == N  # stacked convention: concat of rank rows
    a = hvd_torch.alltoall(torch.arange(N * N, dtype=torch.float32
                                        ).reshape(N, N))
    assert a.shape == (N, N)


def test_torch_broadcast_parameters_state_dict(hvd_module):
    model = torch.nn.Linear(4, 2)
    sd = model.state_dict()
    before = {k: v.clone() for k, v in sd.items()}
    hvd_torch.broadcast_parameters(sd, root_rank=0)
    for k in sd:
        np.testing.assert_allclose(
            sd[k].detach().numpy(), before[k].detach().numpy()
        )


def test_torch_broadcast_optimizer_state(hvd_module):
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    loss = model(torch.randn(4, 3)).sum()
    loss.backward()
    opt.step()
    hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
    # momentum buffers survive the round trip
    state = opt.state_dict()["state"]
    assert any("momentum_buffer" in s for s in state.values())


def test_torch_rejects_non_tensor(hvd_module):
    with pytest.raises(TypeError):
        hvd_torch.allreduce(np.ones((N, 2)))


def test_torch_bf16_allreduce_exact_wire_dtype(hvd_module):
    t = torch.arange(N * 2, dtype=torch.float32).reshape(N, 2).bfloat16()
    out = hvd_torch.allreduce(t, op=hvd.Sum)
    assert out.dtype == torch.bfloat16
    want = np.asarray(t.float()).sum(axis=0)
    np.testing.assert_allclose(
        out.float().numpy(), np.tile(want, (N, 1)), rtol=2e-2
    )


def test_torch_int64_rejected(hvd_module):
    with pytest.raises(TypeError, match="truncated"):
        hvd_torch.allreduce(torch.ones((N, 2), dtype=torch.int64))
