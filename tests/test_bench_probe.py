"""Regression tests for the bench.py device probe (the dead-probe
satellite): a hung probe subprocess must yield the structured skip
record — non-empty reason, captured stderr, bounded per-attempt
deadline inside the alarm window — AND the device-free sim records
must still run (the BENCH_r03..r05 failure mode was the probe racing
the SIGALRM into the outer raw-error path, which skipped them all)."""

import os
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402

from horovod_tpu.utils.retry import RetryPolicy  # noqa: E402


def _no_sleep_retry():
    return RetryPolicy(
        max_attempts=2, base_delay_s=0.0, jitter=0.0,
        name="bench.probe.test",
        retry_on=(RuntimeError, subprocess.TimeoutExpired),
    )


@pytest.fixture(autouse=True)
def _no_cache(monkeypatch, tmp_path):
    # never read or write the real probe-cache sidecar
    monkeypatch.setenv(
        "HVD_BENCH_PROBE_CACHE", str(tmp_path / "probe_cache.json")
    )


class TestHungProbe:
    def test_timeout_yields_skip_with_stderr(self, monkeypatch):
        calls = {"n": 0, "timeouts": []}

        def hung_run(cmd, timeout=None, **kw):
            calls["n"] += 1
            calls["timeouts"].append(timeout)
            raise subprocess.TimeoutExpired(
                cmd, timeout, stderr=b"tpu tunnel wedged: boom"
            )

        monkeypatch.setattr(bench.subprocess, "run", hung_run)
        skip = bench.run_device_probe(
            480, time.monotonic(), retry=_no_sleep_retry()
        )
        assert skip is not None
        assert skip["status"] == "skipped"
        assert skip["reason"]  # non-empty, always
        assert "TimeoutExpired" in skip["reason"]
        assert "boom" in skip["probe_stderr"]
        # both probe attempts ran, then the doctor's FIRST stage (its
        # subprocess hits the same mock, times out, and the ladder
        # stops at the first failing stage)
        assert calls["n"] == 3
        diagnosis = skip["probe_diagnosis"]
        assert diagnosis["status"] == "sick"
        assert diagnosis["verdict"]["stage"] == "import_jax"
        # per-attempt probe deadline bounded INSIDE the alarm window:
        # never more than half the remaining budget minus the records
        # reserve (the trailing doctor-stage timeout has its own rule)
        for t in calls["timeouts"][:2]:
            assert t <= 480 / 2 - 45 + 1

    def test_attempt_budget_shrinks_with_alarm(self, monkeypatch):
        seen = []

        def hung_run(cmd, timeout=None, **kw):
            seen.append(timeout)
            raise subprocess.TimeoutExpired(cmd, timeout, stderr=None)

        monkeypatch.setattr(bench.subprocess, "run", hung_run)
        # alarm armed 400 s ago of a 480 s window: 80 s remain, so each
        # attempt gets the 20 s floor, never 150 s
        bench.run_device_probe(
            480, time.monotonic() - 400, retry=_no_sleep_retry()
        )
        assert seen and all(t == 20 for t in seen)

    def test_failed_probe_captures_rc_and_stderr(self, monkeypatch):
        def failing_run(cmd, **kw):
            return subprocess.CompletedProcess(
                cmd, returncode=3, stdout="",
                stderr="ImportError: libtpu not found",
            )

        monkeypatch.setattr(bench.subprocess, "run", failing_run)
        skip = bench.run_device_probe(
            480, time.monotonic(), retry=_no_sleep_retry()
        )
        assert skip is not None
        assert "rc=3" in skip["reason"]
        assert "libtpu" in skip["probe_stderr"]

    def test_live_probe_returns_none_and_caches(self, monkeypatch):
        def ok_run(cmd, **kw):
            return subprocess.CompletedProcess(
                cmd, returncode=0, stdout="8.0\n", stderr=""
            )

        monkeypatch.setattr(bench.subprocess, "run", ok_run)
        assert bench.run_device_probe(
            480, time.monotonic(), retry=_no_sleep_retry()
        ) is None
        assert bench._probe_cached_ok()  # second call skips subprocess

        def exploding_run(cmd, **kw):  # pragma: no cover - must not run
            raise AssertionError("probe re-ran despite fresh cache")

        monkeypatch.setattr(bench.subprocess, "run", exploding_run)
        assert bench.run_device_probe(480, time.monotonic()) is None


class TestSkipPathStillRecords:
    def test_device_free_records_run_on_skip(self, monkeypatch):
        """The skip result flows through the SAME record list as a
        healthy cpu-only run: a hung probe still yields real sim
        records plus the non-empty reason."""
        ran = []

        def fake_record(name):
            def record(result, deadline_s, t_start):
                ran.append(name)
                result[name] = {"metric": name, "value": 1.0}
            return record

        monkeypatch.setattr(
            bench, "_cpu_resnet_fallback", fake_record("cpu_fallback")
        )
        for rec in ("_maybe_scaling", "_maybe_topo",
                    "_maybe_quant_backend", "_maybe_adasum",
                    "_maybe_railpipe", "_maybe_svc_fusion",
                    "_maybe_tenant", "_maybe_serve"):
            monkeypatch.setattr(bench, rec, fake_record(rec))

        result = {
            "metric": "resnet50_synthetic_train_throughput",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "status": "skipped",
            "reason": "device probe exhausted retries: TimeoutExpired",
            "probe_stderr": "boom",
        }
        bench._device_free_records(result, 480, time.monotonic())
        assert ran == ["cpu_fallback", "_maybe_scaling", "_maybe_topo",
                       "_maybe_quant_backend", "_maybe_adasum",
                       "_maybe_railpipe", "_maybe_svc_fusion",
                       "_maybe_tenant", "_maybe_serve"]
        assert result["reason"]
        assert result["cpu_fallback"]["value"] == 1.0

    def test_fallback_skipped_when_primary_measured(self, monkeypatch):
        """A healthy TPU run (nonzero primary value) never pays the
        CPU-sim resnet fallback subprocess."""
        ran = []

        def fake(result, deadline_s, t_start):
            ran.append("cpu_fallback")

        def noop(result, deadline_s, t_start):
            pass

        monkeypatch.setattr(bench, "_cpu_resnet_fallback", fake)
        for rec in ("_maybe_scaling", "_maybe_topo",
                    "_maybe_quant_backend", "_maybe_adasum",
                    "_maybe_railpipe", "_maybe_svc_fusion",
                    "_maybe_tenant", "_maybe_serve"):
            monkeypatch.setattr(bench, rec, noop)
        bench._device_free_records(
            {"value": 123.0}, 480, time.monotonic()
        )
        assert ran == []


class TestStructuredAbort:
    def test_outer_escape_emits_structured_skip(self, monkeypatch,
                                                capsys):
        """Satellite regression (BENCH_r05): an exception that escapes
        main() — e.g. a TimeoutExpired racing past the probe — must
        produce the structured-skip primary record (status/reason, no
        raw "error" blob) AND still run the device-free records so the
        CPU-sim resnet fallback can fill the primary metric."""
        ran = []

        def fake_records(result, deadline_s, t_start):
            ran.append(True)
            result["value"] = 42.0  # the cpu_sim fallback's job

        monkeypatch.setattr(bench, "_device_free_records", fake_records)
        err = subprocess.TimeoutExpired(["python"], 150)
        record = bench.emit_structured_abort(err, grace_s=60)
        out = capsys.readouterr().out.strip().splitlines()[-1]
        import json

        emitted = json.loads(out)
        assert emitted == record
        assert record["status"] == "skipped"
        assert "TimeoutExpired" in record["reason"]
        assert "error" not in record
        assert ran == [True]
        assert record["value"] == 42.0

    def test_records_failure_stays_structured(self, monkeypatch,
                                              capsys):
        """Even when the device-free pass itself dies, the emitted line
        keeps the structured shape (records_error, never "error")."""

        def exploding(result, deadline_s, t_start):
            raise RuntimeError("records pass died")

        monkeypatch.setattr(bench, "_device_free_records", exploding)
        record = bench.emit_structured_abort(
            RuntimeError("boom"), grace_s=30
        )
        assert record["status"] == "skipped"
        assert "records pass died" in record["records_error"]
        assert "error" not in record
        assert capsys.readouterr().out.strip()
