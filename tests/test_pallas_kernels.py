"""Pallas kernel parity tests (interpret mode on the CPU mesh).

Mirrors the reference's CUDA-kernel coverage: scale/cast parity with
the plain XLA path (``test_torch.py`` prescale/postscale cases) and
flash attention vs the exact ``full_attention`` reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.pallas_kernels import flash_attention, scale_buffer
from horovod_tpu.parallel.ring_attention import full_attention


@pytest.mark.parametrize(
    "shape,dtype,out_dtype",
    [
        ((17,), jnp.float32, None),
        ((10, 100), jnp.float32, jnp.bfloat16),
        ((3, 5, 7), jnp.bfloat16, jnp.float32),
        ((65536,), jnp.float32, None),
    ],
)
def test_scale_buffer(shape, dtype, out_dtype):
    x = jnp.arange(int(np.prod(shape)), dtype=dtype).reshape(shape) / 100
    got = scale_buffer(x, 0.25, out_dtype)
    want = (x.astype(jnp.float32) * 0.25).astype(out_dtype or dtype)
    assert got.shape == x.shape and got.dtype == want.dtype
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32)
    )


def test_scale_buffer_jit_and_grad():
    x = jnp.ones((256,), jnp.float32)
    y = jax.jit(lambda a: scale_buffer(a, 2.0))(x)
    np.testing.assert_allclose(np.asarray(y), 2.0 * np.ones(256))
    # custom VJP: d/dx (x*2).sum() == 2, d/dscale == Σx
    dx = jax.grad(lambda a: scale_buffer(a, 2.0).sum())(x)
    np.testing.assert_allclose(np.asarray(dx), 2.0 * np.ones(256))
    dscale = jax.grad(lambda s: scale_buffer(x, s).sum())(jnp.float32(2.0))
    np.testing.assert_allclose(float(dscale), 256.0)


def test_flash_attention_rejects_unequal_seq_lens():
    from horovod_tpu.ops.pallas_kernels import flash_attention

    q = jnp.zeros((1, 64, 2, 32))
    kv = jnp.zeros((1, 128, 2, 32))
    with pytest.raises(ValueError, match="equal q/k/v sequence lengths"):
        flash_attention(q, kv, kv)


@pytest.mark.parametrize(
    "b,t,h,d,causal",
    [
        (2, 128, 4, 64, False),
        (2, 128, 4, 64, True),
        (1, 100, 2, 32, True),   # ragged T → padding path
        (1, 257, 3, 64, False),  # ragged, multiple blocks
    ],
)
def test_flash_attention_forward(b, t, h, d, causal):
    rng = jax.random.PRNGKey(0)
    q, k, v = jax.random.normal(rng, (3, b, t, h, d), jnp.float32)
    ref = full_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal, None, 64, 64)
    assert out.shape == ref.shape and out.dtype == ref.dtype
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    rng = jax.random.PRNGKey(1)
    b, t, h, d = 1, 96, 2, 32
    q, k, v = jax.random.normal(rng, (3, b, t, h, d), jnp.float32)

    def loss_ref(q, k, v):
        return (full_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, None, 32, 32, 32) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for want, got in zip(g_ref, g_fl):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
        )


def test_flash_attention_bf16():
    rng = jax.random.PRNGKey(2)
    q, k, v = jax.random.normal(rng, (3, 2, 64, 2, 32), jnp.bfloat16)
    ref = full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, None, 32, 32)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2,
    )


def test_flash_packed_multiblock_matches_full():
    """Packed segment masking across MULTIPLE k/q blocks (block=16,
    T=70 not a block multiple): exercises the cross-block online-softmax
    correction under segment masks and the -1 segment padding."""
    from horovod_tpu.ops.pallas_kernels import flash_attention
    from horovod_tpu.parallel.ring_attention import full_attention

    rng = np.random.RandomState(0)
    b, t, h, d = 2, 70, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    seg = np.zeros((b, t), np.int32)
    # segments straddle the 16-wide block boundaries
    seg[:, :23] = 1
    seg[:, 23:41] = 2
    seg[:, 41:] = 3
    seg = jnp.asarray(seg)

    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal, block_q=16,
                              block_k=16, bwd_chunk=16, segment_ids=seg)
        ref = full_attention(q, k, v, causal=causal, segment_ids=seg)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    # gradient parity at the same block geometry
    def f_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16, bwd_chunk=16,
            segment_ids=seg,
        ) ** 2)

    def f_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True,
                                      segment_ids=seg) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_full, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4
        )
