"""Adasum math tests against a NumPy reference implementation
(the analog of reference ``test/parallel/test_adasum_pytorch.py``, which
checks the C++ Adasum against a NumPy recursion)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd

N = 8


def adasum_pair_np(a, b):
    """Reference math, adasum.h:397-409."""
    dot = float(np.dot(a.ravel(), b.ravel()))
    na = float(np.dot(a.ravel(), a.ravel()))
    nb = float(np.dot(b.ravel(), b.ravel()))
    ca = 1.0 - dot / (2 * na) if na > 0 else 1.0
    cb = 1.0 - dot / (2 * nb) if nb > 0 else 1.0
    return ca * a + cb * b


def adasum_np(tensors):
    """Recursive-doubling reference over a power-of-two list."""
    n = len(tensors)
    vals = [t.astype(np.float64) for t in tensors]
    level = 1
    while level < n:
        new = list(vals)
        for r in range(n):
            partner = r ^ level
            new[r] = adasum_pair_np(vals[r], vals[partner])
        vals = new
        level <<= 1
    return vals


def test_adasum_matches_numpy_reference(hvd_module):
    x = np.random.RandomState(0).randn(N, 16).astype(np.float32)
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    expected = adasum_np([x[r] for r in range(N)])
    for r in range(N):
        np.testing.assert_allclose(y[r], expected[r], rtol=1e-4, atol=1e-5)


def test_adasum_orthogonal_adds(hvd_module):
    """Orthogonal gradients must add (scale-invariance property)."""
    x = np.zeros((N, N), np.float32)
    for r in range(N):
        x[r, r] = 3.0  # mutually orthogonal
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    np.testing.assert_allclose(y[0], np.full(N, 3.0) * np.eye(N).sum(0), rtol=1e-5)


def test_adasum_parallel_averages(hvd_module):
    """Identical gradients must average (parallel case)."""
    v = np.random.RandomState(1).randn(12).astype(np.float32)
    x = np.tile(v, (N, 1))
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    np.testing.assert_allclose(y[0], v, rtol=1e-4)


def test_adasum_process_set(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2, 3])
    x = np.random.RandomState(2).randn(N, 8).astype(np.float32)
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
    expected = adasum_np([x[r] for r in range(4)])
    for r in range(4):
        np.testing.assert_allclose(y[r], expected[r], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(y[4:], x[4:], rtol=1e-6)  # non-members
    hvd.remove_process_set(ps)


def adasum_np_any(tensors):
    """Straggler-fold model for non-power-of-two sets (reference
    adasum_mpi.cc communicator construction): extras pair-combine into
    the first cores, then the power-of-two tree runs."""
    k = len(tensors)
    p = 1 << (k.bit_length() - 1)
    vals = [t.astype(np.float64) for t in tensors]
    core = list(vals[:p])
    for i in range(k - p):
        core[i] = adasum_pair_np(core[i], vals[p + i])
    return adasum_np(core)[0]  # pair formula is symmetric: all equal


def test_adasum_non_power_of_two_folds(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2])
    x = np.random.RandomState(3).randn(N, 8).astype(np.float32)
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
    expected = adasum_np_any([x[0], x[1], x[2]])
    for r in range(3):
        np.testing.assert_allclose(y[r], expected, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(y[3:], x[3:], rtol=1e-6)  # non-members
    hvd.remove_process_set(ps)


def test_adasum_odd_world_sizes(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    for k in (3, 5, 6, 7):
        ps = hvd.add_process_set(list(range(k)))
        x = np.random.RandomState(k).randn(N, 5).astype(np.float32)
        y = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
        expected = adasum_np_any([x[r] for r in range(k)])
        for r in range(k):
            np.testing.assert_allclose(y[r], expected, rtol=1e-4, atol=1e-5)
        hvd.remove_process_set(ps)


def test_adasum_vhdd_traffic_is_linear(hvd_module):
    """VHDD wire check (reference adasum.h:380-439): each ppermute moves
    half the previous level's payload — per-rank permute traffic sums to
    ~V, not the O(V log n) of full-vector recursive doubling."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.adasum import adasum_allreduce
    from horovod_tpu.runtime import WORLD_AXIS, get_runtime

    V = 1 << 12  # fp32 elements, divisible by 8

    def body(x):
        return adasum_allreduce(x[0])[None]

    hlo = jax.jit(
        shard_map(
            body, mesh=get_runtime().mesh, in_specs=(P(WORLD_AXIS),),
            out_specs=P(WORLD_AXIS), check_vma=False,
        )
    ).lower(jnp.zeros((N, V), jnp.float32)).compile().as_text()

    import re

    moved = 0
    for line in hlo.splitlines():
        if "collective-permute(" in line:
            m = re.search(r"f32\[(\d+)\]", line)
            if m:
                moved += int(m.group(1))
    assert moved > 0
    # halving schedule: V/2 + V/4 + V/8 = 7V/8 < V; full-vector
    # recursive doubling would be 3V.
    assert moved <= V, f"per-rank permute traffic {moved} elems > V={V}"


def test_delta_adasum_optimizer(hvd_module):
    """DistributedAdasumOptimizer applies inner update locally then
    adasums deltas; with identical data everywhere it must equal the
    plain local update (parallel deltas average to themselves)."""
    X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    Y = (X @ np.full((4, 1), 0.7)).astype(np.float32)
    # replicate the same batch on every rank so deltas are identical
    Xr = np.tile(X[:2], (N, 1))
    Yr = np.tile(Y[:2], (N, 1))

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w"] - y) ** 2)

    params = {"w": jnp.full((4, 1), 0.3)}
    tx = hvd.DistributedAdasumOptimizer(optax.sgd(0.1))
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    p, _, _ = step(
        jax.tree.map(jnp.array, params), st, (jnp.asarray(Xr), jnp.asarray(Yr))
    )
    g = jax.grad(loss_fn)(params, (jnp.asarray(X[:2]), jnp.asarray(Y[:2])))
    ref = params["w"] - 0.1 * g["w"]
    np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(ref), rtol=1e-4)


# ---- hierarchical Adasum (AdasumGpuAllreduceOp analog) -----------------


def _run_adasum(x, hierarchical):
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.adasum import adasum_allreduce
    from horovod_tpu.runtime import WORLD_AXIS, get_runtime

    def body(v):
        return adasum_allreduce(v[0], hierarchical=hierarchical)[None]

    f = jax.jit(shard_map(
        body, mesh=get_runtime().mesh, in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    ))
    return f, np.asarray(f(jnp.asarray(x)))


def _host_grid(L, H):
    """Overlay a logical L-chips-per-host grid on the test world."""
    from horovod_tpu.runtime import get_runtime

    rt = get_runtime()
    old = rt.local_size, rt.cross_size
    rt.local_size, rt.cross_size = L, H
    return rt, old


def test_hierarchical_adasum_matches_flat_on_replicated_hosts(hvd_module):
    """With each host's L ranks holding identical gradients, the
    intra-host-sum/cross-host-Adasum schedule must agree with the flat
    VHDD tree (parallel local gradients average; divide-by-L restores
    host-average scale, reference operations.cc:1404-1410)."""
    L, H = 2, 4
    rt, old = _host_grid(L, H)
    try:
        rng = np.random.RandomState(7)
        hosts = rng.randn(H, 33).astype(np.float32)
        x = np.repeat(hosts, L, axis=0)  # contiguous blocks per host
        _, y_h = _run_adasum(x, hierarchical=True)
        _, y_f = _run_adasum(x, hierarchical=False)
        np.testing.assert_allclose(y_h, y_f, rtol=1e-4, atol=1e-5)
    finally:
        rt.local_size, rt.cross_size = old


def test_hierarchical_adasum_semantics_direct(hvd_module):
    """Independent check against NumPy: result == Adasum over per-host
    average gradients (arbitrary per-rank data this time)."""
    L, H = 4, 2
    rt, old = _host_grid(L, H)
    try:
        rng = np.random.RandomState(8)
        x = rng.randn(N, 24).astype(np.float32)
        _, y = _run_adasum(x, hierarchical=True)
        host_avg = [x[h * L:(h + 1) * L].mean(axis=0) for h in range(H)]
        expected = adasum_np(host_avg)
        for r in range(N):
            np.testing.assert_allclose(y[r], expected[r // L],
                                       rtol=1e-4, atol=1e-5)
    finally:
        rt.local_size, rt.cross_size = old


def test_hierarchical_adasum_cross_payload_is_v_over_l(hvd_module):
    """VERDICT r3 item 3 gate: every cross-host hop carries shards of
    the intra-host reduce-scatter — collective-permute traffic must be
    < V/L elements total (vs 7V/8 for the flat tree)."""
    import re

    L, H = 2, 4
    V = 1 << 12
    rt, old = _host_grid(L, H)
    try:
        x = np.zeros((N, V), np.float32)
        f, _ = _run_adasum(x, hierarchical=True)
        hlo = f.lower(jnp.zeros((N, V), jnp.float32)).compile().as_text()
        moved = 0
        for line in hlo.splitlines():
            if "collective-permute(" in line:
                m = re.search(r"f32\[(\d+)\]", line)
                if m:
                    moved += int(m.group(1))
        assert moved > 0
        # shard is V/L; VHDD over H hosts moves (V/L)(1 - 1/p) < V/L
        assert moved < V // L, (
            f"cross-host permute traffic {moved} elems >= V/L={V // L}"
        )
        # and the intra-host stages must be grouped scatter/gather ops
        assert "reduce-scatter" in hlo or "all-reduce" in hlo
        assert "all-gather" in hlo
    finally:
        rt.local_size, rt.cross_size = old


def test_hierarchical_adasum_falls_back_on_ragged_grid(hvd_module):
    """A world that is not a homogeneous L x H grid must silently use
    the flat VHDD tree (always correct)."""
    L, H = 3, 2  # 3*2 != 8 -> ragged
    rt, old = _host_grid(L, H)
    try:
        x = np.random.RandomState(9).randn(N, 16).astype(np.float32)
        _, y_h = _run_adasum(x, hierarchical=True)
        _, y_f = _run_adasum(x, hierarchical=False)
        np.testing.assert_allclose(y_h, y_f, rtol=1e-6)
    finally:
        rt.local_size, rt.cross_size = old


def test_hierarchical_adasum_env_knob(hvd_module, monkeypatch):
    """HVD_TPU_HIERARCHICAL_ALLREDUCE=1 routes hvd.allreduce(op=Adasum)
    through the hierarchical schedule."""
    monkeypatch.setenv("HVD_TPU_HIERARCHICAL_ALLREDUCE", "1")
    L, H = 2, 4
    rt, old = _host_grid(L, H)
    try:
        rng = np.random.RandomState(10)
        hosts = rng.randn(H, 10).astype(np.float32)
        x = np.repeat(hosts, L, axis=0)
        y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
        expected = adasum_np(list(hosts))
        for r in range(N):
            np.testing.assert_allclose(y[r], expected[r // L],
                                       rtol=1e-4, atol=1e-5)
    finally:
        rt.local_size, rt.cross_size = old


# ---- hierarchical Adasum as a lowering (PR 10, docs/adasum.md) ---------


@pytest.mark.adasum
def test_topo_slice_grid_serves_eager_hierarchical(hvd_module,
                                                   monkeypatch):
    """A forced cross-slice topology (no multi-host grid) now serves
    the hierarchical Adasum schedule: intra-slice sum, cross-slice
    VHDD on the rails, /slice_size postscale."""
    from horovod_tpu import topo

    monkeypatch.setenv("HVD_TPU_HIERARCHICAL_ALLREDUCE", "1")
    monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
    topo.reset()
    try:
        rng = np.random.RandomState(11)
        x = rng.randn(N, 33).astype(np.float32)
        y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
        expected = adasum_np([x[:4].mean(0), x[4:].mean(0)])
        for r in range(N):
            np.testing.assert_allclose(y[r], expected[r // 4],
                                       rtol=1e-4, atol=1e-5)
    finally:
        topo.reset()


@pytest.mark.adasum
def test_large_batch_stability_property(hvd_module, monkeypatch):
    """Quadratic-bowl convergence property (the Adasum paper's
    large-batch claim, arXiv:2006.02924): at 4x the batch the learning
    rate was tuned for, summed gradients step past the stability
    boundary (8*lr*curvature > 2) and diverge, while the hier_adasum
    lowering — sum inside the slice, adaptive combination of the
    near-parallel slice aggregates across DCN — stays in the stable
    region (4*lr*curvature < 2) and reaches the loss target with NO LR
    retuning.  Adasum stability >= plain sum, measured, not assumed."""
    from horovod_tpu import sched, topo

    monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
    topo.reset()
    try:
        d = 4
        curv = np.asarray([1.0, 0.5, 0.25, 0.125], np.float32)
        wstar = np.asarray([2.0, -1.0, 0.5, 1.5], np.float32)
        lr = 1.5 / (4.0 * float(curv.max()))
        batch = (
            jnp.asarray(np.tile(curv, (N, 1))),
            jnp.asarray(np.tile(wstar, (N, 1))),
        )

        def loss_fn(p, b):
            h, ws = b
            return 0.5 * jnp.mean(
                jnp.sum(h * (p["w"] - ws) ** 2, axis=-1)
            )

        def run(lowering, steps=40):
            params = {"w": jnp.zeros((d,))}
            sched.set_config_override(sched.SchedConfig(
                enabled=True, bucket_bytes=4096, lowering=lowering))
            try:
                tx = hvd.DistributedOptimizer(optax.sgd(lr), op=hvd.Sum)
                step = hvd.distributed_train_step(loss_fn, tx)
                st = step.init(params)
                out = []
                for _ in range(steps):
                    params, st, loss = step(params, st, batch)
                    out.append(float(loss))
                    if not np.isfinite(out[-1]) or out[-1] > 1e9:
                        break
                return out
            finally:
                sched.set_config_override(None)

        flat = run("flat")
        adasum = run("hier_adasum")
        target = 1e-3
        assert adasum[-1] < target, f"adasum did not converge: {adasum}"
        assert not np.isfinite(flat[-1]) or flat[-1] > adasum[-1], \
            f"plain sum unexpectedly stable: {flat[-1]}"
        # monotone stability: the adasum trajectory never blows up
        assert all(np.isfinite(v) for v in adasum)
    finally:
        topo.reset()
