"""Adasum math tests against a NumPy reference implementation
(the analog of reference ``test/parallel/test_adasum_pytorch.py``, which
checks the C++ Adasum against a NumPy recursion)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd

N = 8


def adasum_pair_np(a, b):
    """Reference math, adasum.h:397-409."""
    dot = float(np.dot(a.ravel(), b.ravel()))
    na = float(np.dot(a.ravel(), a.ravel()))
    nb = float(np.dot(b.ravel(), b.ravel()))
    ca = 1.0 - dot / (2 * na) if na > 0 else 1.0
    cb = 1.0 - dot / (2 * nb) if nb > 0 else 1.0
    return ca * a + cb * b


def adasum_np(tensors):
    """Recursive-doubling reference over a power-of-two list."""
    n = len(tensors)
    vals = [t.astype(np.float64) for t in tensors]
    level = 1
    while level < n:
        new = list(vals)
        for r in range(n):
            partner = r ^ level
            new[r] = adasum_pair_np(vals[r], vals[partner])
        vals = new
        level <<= 1
    return vals


def test_adasum_matches_numpy_reference(hvd_module):
    x = np.random.RandomState(0).randn(N, 16).astype(np.float32)
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    expected = adasum_np([x[r] for r in range(N)])
    for r in range(N):
        np.testing.assert_allclose(y[r], expected[r], rtol=1e-4, atol=1e-5)


def test_adasum_orthogonal_adds(hvd_module):
    """Orthogonal gradients must add (scale-invariance property)."""
    x = np.zeros((N, N), np.float32)
    for r in range(N):
        x[r, r] = 3.0  # mutually orthogonal
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    np.testing.assert_allclose(y[0], np.full(N, 3.0) * np.eye(N).sum(0), rtol=1e-5)


def test_adasum_parallel_averages(hvd_module):
    """Identical gradients must average (parallel case)."""
    v = np.random.RandomState(1).randn(12).astype(np.float32)
    x = np.tile(v, (N, 1))
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
    np.testing.assert_allclose(y[0], v, rtol=1e-4)


def test_adasum_process_set(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2, 3])
    x = np.random.RandomState(2).randn(N, 8).astype(np.float32)
    y = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
    expected = adasum_np([x[r] for r in range(4)])
    for r in range(4):
        np.testing.assert_allclose(y[r], expected[r], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(y[4:], x[4:], rtol=1e-6)  # non-members
    hvd.remove_process_set(ps)


def test_adasum_non_power_of_two_rejected(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2])
    with pytest.raises(Exception, match="power-of-two"):
        hvd.allreduce(np.zeros((N, 4), np.float32), op=hvd.Adasum, process_set=ps)
    hvd.remove_process_set(ps)


def test_delta_adasum_optimizer(hvd_module):
    """DistributedAdasumOptimizer applies inner update locally then
    adasums deltas; with identical data everywhere it must equal the
    plain local update (parallel deltas average to themselves)."""
    X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    Y = (X @ np.full((4, 1), 0.7)).astype(np.float32)
    # replicate the same batch on every rank so deltas are identical
    Xr = np.tile(X[:2], (N, 1))
    Yr = np.tile(Y[:2], (N, 1))

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w"] - y) ** 2)

    params = {"w": jnp.full((4, 1), 0.3)}
    tx = hvd.DistributedAdasumOptimizer(optax.sgd(0.1))
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    p, _, _ = step(
        jax.tree.map(jnp.array, params), st, (jnp.asarray(Xr), jnp.asarray(Yr))
    )
    g = jax.grad(loss_fn)(params, (jnp.asarray(X[:2]), jnp.asarray(Y[:2])))
    ref = params["w"] - 0.1 * g["w"]
    np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(ref), rtol=1e-4)
