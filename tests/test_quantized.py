"""Quantized collective engine (EQuARX-style two-phase scheme,
arXiv:2506.17615 via PAPERS.md): the composed allreduce, the v2 phase
primitives (quantized_reduce_scatter / quantized_all_gather), fp8 wire,
error feedback, and the documented error bound as a property test."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.quantized import (
    quantized_all_gather,
    quantized_allreduce,
    quantized_allreduce_ef,
    quantized_reduce_scatter,
)
from horovod_tpu.ops import traced
from horovod_tpu.runtime import WORLD_AXIS

pytestmark = pytest.mark.quant

N = 8


def _mesh():
    from horovod_tpu.runtime import get_runtime

    return get_runtime().mesh


def _run(x, **kw):
    def body(v):
        return quantized_allreduce(v[0], **kw)[None]

    f = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    ))
    return np.asarray(f(jnp.asarray(x)))


def test_exact_for_quantization_friendly_values(hvd_module):
    # integers within +-127 quantize exactly (scale = amax/127 divides
    # them when amax == 127)
    rng = np.random.RandomState(0)
    x = rng.randint(-127, 128, (N, 1024)).astype(np.float32)
    x[:, 0] = 127.0  # pin amax so scale == 1 exactly
    y = _run(x, op=traced.Sum)
    expect = x.sum(axis=0)
    # phase-2 scale is sum's amax/127; sums are integers <= 127*N so
    # they re-quantize with bounded error
    err = np.abs(y[0] - expect)
    assert err.max() <= np.abs(expect).max() / 127.0 + 1e-4


def test_relative_error_bounded(hvd_module):
    rng = np.random.RandomState(1)
    x = rng.randn(N, 4096).astype(np.float32)
    y = _run(x, op=traced.Average)
    expect = x.mean(axis=0)
    # two quantizations: |err| <= 0.5*amax_in/127 + 0.5*amax_sum/(127*N)
    bound = (
        0.5 * np.abs(x).max(axis=1).max() / 127.0
        + 0.5 * np.abs(x.sum(0)).max() / 127.0
    ) / N * 2.0 + 1e-5
    assert np.abs(y[0] - expect).max() <= bound


def test_wire_is_int8(hvd_module):
    """The collectives must carry s8 operands, not f32."""
    V = 4096

    def body(v):
        return quantized_allreduce(v[0], op=traced.Sum)[None]

    hlo = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    )).lower(jnp.zeros((N, V), jnp.float32)).compile().as_text()
    colls = [
        l for l in hlo.splitlines()
        if re.search(r"= \S+ (all-to-all|all-gather)\(", l)
    ]
    assert colls
    # the payload-sized collectives are int8; fp32 appears only in the
    # tiny scale exchanges
    for line in colls:
        if str(V) in line or str(V // N) in line:
            assert "s8[" in line, line


def test_block_scales_preserve_small_magnitude_regions(hvd_module):
    """A huge-magnitude region must not flush a small-magnitude region
    to zero — the reason for blockwise scales (EQuARX block design)."""
    from horovod_tpu.ops.quantized import BLOCK

    x = np.zeros((N, 4 * BLOCK), np.float32)
    x[:, :BLOCK] = 1e3          # "layer A" block
    x[:, BLOCK:] = 1e-4         # "layer B" blocks
    y = _run(x, op=traced.Average)
    # small region survives with small relative error
    np.testing.assert_allclose(y[0][BLOCK:], 1e-4, rtol=2e-2)
    np.testing.assert_allclose(y[0][:BLOCK], 1e3, rtol=2e-2)


def test_nonfinite_propagates(hvd_module):
    """inf/nan gradients must surface, not silently zero (the cast
    compressors preserve non-finites; overflow-skip logic depends on
    seeing them)."""
    x = np.ones((N, 2048), np.float32)
    x[3, 7] = np.inf
    y = _run(x, op=traced.Sum)
    assert not np.isfinite(y[0]).all()
    x2 = np.ones((N, 2048), np.float32)
    x2[1, 0] = np.nan
    y2 = _run(x2, op=traced.Sum)
    assert np.isnan(y2[0]).any()


def test_int8_rejects_sparse_leaves(hvd_module):
    from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
    from horovod_tpu.ops.sparse import IndexedSlices
    from horovod_tpu.ops.traced import Average

    s = IndexedSlices(jnp.zeros((2,), jnp.int32), jnp.zeros((2, 4)),
                      (16, 4))
    with pytest.raises(ValueError, match="IndexedSlices"):
        _reduce_gradients(
            {"emb": s}, axis=WORLD_AXIS, op=Average,
            compression=hvd.Compression.int8, prescale_factor=1.0,
            postscale_factor=1.0, process_set=None,
            fusion_threshold_bytes=None,
        )


def test_zero_input_safe(hvd_module):
    x = np.zeros((N, 128), np.float32)
    y = _run(x, op=traced.Sum)
    np.testing.assert_array_equal(y, 0.0)


@pytest.mark.parametrize("wire", ["int8", "fp8"])
def test_zero_block_roundtrip_is_exact_zero_property(wire):
    """Property: quantize→dequantize of an all-zero block is EXACTLY
    zero for both wire formats, across block sizes and in any mixed
    payload position — the _block_scale guard clamps the divisor away
    from zero once, centrally, so no call site can reintroduce a 0/0.
    The scale itself stays finite (a NaN scale is reserved for
    non-finite payloads, where propagation is the contract)."""
    from horovod_tpu.ops.quantized import (
        _block_scale,
        _dequantize_blocks,
        _quantize_blocks,
    )

    rng = np.random.RandomState(9)
    for block in (64, 128, 512):
        for rows in (1, 3):
            x = rng.randn(rows, 4 * block).astype(np.float32)
            # zero out a different block per row, plus one fully-zero row
            for r in range(rows):
                x[r, r * block:(r + 1) * block] = 0.0
            x[-1, :] = 0.0
            q, s = _quantize_blocks(jnp.asarray(x), wire, block)
            out = np.asarray(_dequantize_blocks(q, s, block))
            assert np.isfinite(np.asarray(s)).all()
            np.testing.assert_array_equal(out[x == 0.0], 0.0)
    # the guard itself: zero amax -> unit divisor, finite unit scale
    scale, safe = _block_scale(jnp.zeros((4,), jnp.float32), 127.0)
    np.testing.assert_array_equal(np.asarray(safe), 1.0)
    np.testing.assert_array_equal(np.asarray(scale), 1.0)


def test_rejects_nontiling_subsets_and_bad_ops(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    # [0, 1, 2] cannot tile 8 ranks into equal replica groups (5 % 3)
    ps = hvd.add_process_set([0, 1, 2])
    with pytest.raises(Exception, match="tile"):
        _run(np.ones((N, 8), np.float32), process_set=ps)
    hvd.remove_process_set(ps)
    with pytest.raises(ValueError, match="Sum/Average"):
        _run(np.ones((N, 8), np.float32), op=traced.Max)


def test_tiling_subset_reduces_within_groups(hvd_module, monkeypatch):
    """v2 serves process sets that tile the axis: each replica group
    reduces among itself (the grouped-collective fast-path semantics of
    traced.allreduce)."""
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1, 2, 3])
    try:
        x = np.zeros((N, 1024), np.float32)
        for r in range(N):
            x[r, :] = float(r + 1)
        y = _run(x, op=traced.Sum, process_set=ps)
        # group [0..3] sums 1+2+3+4 = 10, group [4..7] sums 5+6+7+8 = 26
        np.testing.assert_allclose(y[0], 10.0, rtol=2e-2)
        np.testing.assert_allclose(y[4], 26.0, rtol=2e-2)
    finally:
        hvd.remove_process_set(ps)


def test_optimizer_int8_compression_trains(hvd_module):
    rng = np.random.RandomState(2)
    W = rng.randn(16, 1).astype(np.float32)
    X = rng.randn(64 * N, 16).astype(np.float32)
    Y = X @ W
    params = {"w": jnp.zeros((16, 1))}
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.05), compression=hvd.Compression.int8
    )

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    losses = []
    for _ in range(60):
        params, opt_state, loss = step(
            params, opt_state, (jnp.asarray(X), jnp.asarray(Y))
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_optimizer_int8_rejects_nontiling_subsets(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
    from horovod_tpu.ops.traced import Average

    ps = hvd.add_process_set([0, 1, 2])  # 5 % 3 != 0: no equal tiling
    with pytest.raises(ValueError, match="tile"):
        _reduce_gradients(
            {"w": jnp.ones((4,))}, axis=WORLD_AXIS, op=Average,
            compression=hvd.Compression.int8, prescale_factor=1.0,
            postscale_factor=1.0, process_set=ps,
            fusion_threshold_bytes=None,
        )
    hvd.remove_process_set(ps)


# ------------------------------------------------- v2 phase primitives

def _run_rs(x, **kw):
    def body(v):
        return quantized_reduce_scatter(v[0], **kw)[None]

    f = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    ))
    return np.asarray(f(jnp.asarray(x)))


def test_reduce_scatter_shard_is_exact_block_sum(hvd_module):
    """Phase 1 accumulates dequantized contributions in fp32: with
    quantization-exact inputs rank j's shard equals the exact sum of
    chunk j."""
    rng = np.random.RandomState(3)
    V = 8 * 1024
    x = rng.randint(-127, 128, (N, V)).astype(np.float32)
    x[:, ::512] = 127.0  # pin every block's amax so scale == 1 exactly
    shards = _run_rs(x, op=traced.Sum)
    expect = x.sum(axis=0).reshape(N, V // N)
    np.testing.assert_allclose(shards, expect, atol=1e-4)


def test_all_gather_roundtrips_shards(hvd_module):
    """Phase 2: each rank re-quantizes its shard; the gathered result
    reconstructs every shard within one quantization error."""
    rng = np.random.RandomState(4)
    c = 1024  # block-aligned shard
    shards = rng.randn(N, c).astype(np.float32)

    def body(v):
        return quantized_all_gather(v[0])[None]

    f = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    ))
    full = np.asarray(f(jnp.asarray(shards)))[0].reshape(N, c)
    bound = np.abs(shards).max(axis=1, keepdims=True) / 127.0 * 0.5 + 1e-6
    assert (np.abs(full - shards) <= bound).all()


def test_all_gather_rejects_unaligned_shards(hvd_module):
    def body(v):
        return quantized_all_gather(v[0, :100])[None]

    with pytest.raises(ValueError, match="multiple"):
        jax.jit(shard_map(
            body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
            out_specs=P(WORLD_AXIS), check_vma=False,
        ))(jnp.ones((N, 512)))


def test_phases_compose_to_allreduce(hvd_module):
    """RS then AG equals the composed quantized_allreduce bit-for-bit
    (the v2 decomposition is the same program)."""
    rng = np.random.RandomState(5)
    x = rng.randn(N, 4096).astype(np.float32)

    def composed(v):
        return quantized_allreduce(v[0], op=traced.Sum)[None]

    def phased(v):
        V = v[0].size
        shard = quantized_reduce_scatter(v[0], op=traced.Sum)
        return quantized_all_gather(shard)[:V].reshape(v[0].shape)[None]

    outs = []
    for body in (composed, phased):
        f = jax.jit(shard_map(
            body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
            out_specs=P(WORLD_AXIS), check_vma=False,
        ))
        outs.append(np.asarray(f(jnp.asarray(x))))
    np.testing.assert_array_equal(outs[0], outs[1])


@pytest.mark.parametrize("wire", ["int8", "fp8"])
def test_wire_formats_bounded_error(hvd_module, wire):
    rng = np.random.RandomState(6)
    x = rng.randn(N, 4096).astype(np.float32)
    y = _run(x, op=traced.Average, wire=wire)
    expect = x.mean(axis=0)
    qmax = 127.0 if wire == "int8" else 448.0
    # fp8's grid is non-uniform; rel step <= 1/16 around each binade,
    # but the amax/qmax scale bound still holds elementwise.
    bound = (
        np.abs(x).max() / qmax + np.abs(x.sum(0)).max() / qmax
    ) / N + np.abs(x).max() / 8.0 / N  # fp8 mantissa slack
    assert np.abs(y[0] - expect).max() <= bound


def test_fp8_wire_carries_f8_operands(hvd_module):
    V = 4096

    def body(v):
        return quantized_allreduce(v[0], op=traced.Sum, wire="fp8")[None]

    hlo = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    )).lower(jnp.zeros((N, V), jnp.float32)).compile().as_text()
    colls = [
        l for l in hlo.splitlines()
        if re.search(r"= \S+ (all-to-all|all-gather)\(", l)
    ]
    assert colls
    for line in colls:
        if str(V) in line or str(V // N) in line:
            # the CPU backend legalizes f8 collectives to f16; either
            # way the payload must be sub-f32 width on the wire
            assert "f8e4m3" in line or "f16[" in line, line
            assert "f32[" not in line.split(" metadata=")[0], line


def test_quant_block_env_knob(hvd_module, monkeypatch):
    from horovod_tpu.ops.quantized import quant_block

    monkeypatch.setenv("HVD_TPU_QUANT_BLOCK", "128")
    assert quant_block() == 128
    # still trains / reduces with the smaller block
    x = np.random.RandomState(7).randn(N, 1024).astype(np.float32)
    y = _run(x, op=traced.Average)
    np.testing.assert_allclose(y[0], x.mean(0), atol=0.1)
    monkeypatch.delenv("HVD_TPU_QUANT_BLOCK")
    assert quant_block() == 512


# -------------------------------------------- documented error bound

def _np_quantize(rows, block, qmax=127.0):
    """Numpy mirror of ops.quantized._quantize_blocks (int8)."""
    r, c = rows.shape
    b = rows.reshape(r, c // block, block).astype(np.float32)
    amax = np.abs(b).max(axis=-1)
    safe = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
    q = np.clip(np.round(b / safe[..., None]), -qmax, qmax)
    return q.astype(np.float32), safe


def test_error_bound_property(hvd_module):
    """Property test of the documented per-element bound: two
    round-to-nearest quantizations contribute at most half a step each,
    |err| <= 0.5*(amax_in/127) + 0.5*(amax_sum/127) with blockwise
    amaxes (phase 1 sums one half-step per contribution)."""
    from horovod_tpu.ops.quantized import quant_block

    block = quant_block()
    for seed in range(4):
        rng = np.random.RandomState(100 + seed)
        V = N * block * rng.randint(1, 4)
        scale = 10.0 ** rng.uniform(-3, 3)
        x = (rng.randn(N, V) * scale).astype(np.float32)
        y = _run(x, op=traced.Sum)[0]
        exact = x.sum(axis=0)

        c = V // N
        # per-rank phase-1 scales: rank r's chunk j, blockwise
        bound = np.zeros((V,), np.float64)
        mine = np.zeros((N, c), np.float64)  # reduced chunk per owner
        for r in range(N):
            chunks = x[r].reshape(N, c)
            q, s = _np_quantize(chunks, block)
            deq = (
                q.reshape(N, c // block, block) * s[..., None]
            ).reshape(N, c)
            mine += deq
            # half a quantization step per contribution, per element
            bound += 0.5 * np.repeat(s, block, axis=1).reshape(-1)
        # phase-2 scales from the actually-reduced chunks
        q2, s2 = _np_quantize(mine.astype(np.float32), block)
        bound += 0.5 * np.repeat(s2, block, axis=1).reshape(-1)

        err = np.abs(y.astype(np.float64) - exact)
        assert (err <= bound * (1 + 1e-5) + 1e-7).all(), (
            seed, float(err.max()), float(bound.min()),
        )


# ------------------------------------------------------ error feedback

def test_ef_residual_captures_quantization_error(hvd_module):
    """r_new == e - dequant(quantize(e)) elementwise, and adding the
    residual back next round re-injects the lost mass."""
    rng = np.random.RandomState(11)
    x = rng.randn(N, 2048).astype(np.float32)
    r0 = np.zeros_like(x)

    def body(v, r):
        out, r_new = quantized_allreduce_ef(v[0], r[0], op=traced.Sum)
        return out[None], r_new[None]

    f = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS), P(WORLD_AXIS)),
        out_specs=(P(WORLD_AXIS), P(WORLD_AXIS)), check_vma=False,
    ))
    out, r_new = f(jnp.asarray(x), jnp.asarray(r0))
    out, r_new = np.asarray(out), np.asarray(r_new)
    # residual is bounded by one quantization step of the payload
    step = np.abs(x).max() / 127.0
    assert np.abs(r_new).max() <= step * 0.5 * (1 + 1e-5) + 1e-7
    assert np.abs(r_new).max() > 0  # random payloads do quantize lossily
    # feeding residual back compensates: mean over many rounds converges
    # (checked end-to-end in test_quant_wire.py's EF convergence test)


def test_ef_int8_matches_fp32_wire_on_quadratic_bowl(hvd_module):
    """Satellite: EF convergence — a quadratic bowl reaches the same
    loss (atol 1e-3) with int8+EF as with the fp32 wire in the same
    number of steps on the multi-device CPU mesh."""
    from horovod_tpu import sched

    rng = np.random.RandomState(12)
    W = rng.randn(16, 2).astype(np.float32)
    X = rng.randn(8 * N, 16).astype(np.float32)
    Y = X @ W
    params = {"w": jnp.zeros((16, 2))}

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    def run(cfg):
        sched.set_config_override(cfg)
        try:
            tx = hvd.DistributedOptimizer(optax.sgd(0.05))
            step = hvd.distributed_train_step(loss_fn, tx)
            p = {"w": jnp.zeros((16, 2))}
            st = step.init(p)
            losses = []
            for _ in range(40):
                p, st, loss = step(p, st, (jnp.asarray(X), jnp.asarray(Y)))
                losses.append(float(loss))
            return losses
        finally:
            sched.set_config_override(None)

    dense = run(sched.SchedConfig(bucket_bytes=64))
    ef = run(sched.SchedConfig(bucket_bytes=64, wire="int8", wire_ef=True))
    assert ef[-1] == pytest.approx(dense[-1], abs=1e-3), (
        dense[-1], ef[-1],
    )
