"""Int8 quantized allreduce (EQuARX-style two-phase scheme,
arXiv:2506.17615 via PAPERS.md)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.quantized import quantized_allreduce
from horovod_tpu.ops import traced
from horovod_tpu.runtime import WORLD_AXIS

N = 8


def _mesh():
    from horovod_tpu.runtime import get_runtime

    return get_runtime().mesh


def _run(x, **kw):
    def body(v):
        return quantized_allreduce(v[0], **kw)[None]

    f = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    ))
    return np.asarray(f(jnp.asarray(x)))


def test_exact_for_quantization_friendly_values(hvd_module):
    # integers within +-127 quantize exactly (scale = amax/127 divides
    # them when amax == 127)
    rng = np.random.RandomState(0)
    x = rng.randint(-127, 128, (N, 1024)).astype(np.float32)
    x[:, 0] = 127.0  # pin amax so scale == 1 exactly
    y = _run(x, op=traced.Sum)
    expect = x.sum(axis=0)
    # phase-2 scale is sum's amax/127; sums are integers <= 127*N so
    # they re-quantize with bounded error
    err = np.abs(y[0] - expect)
    assert err.max() <= np.abs(expect).max() / 127.0 + 1e-4


def test_relative_error_bounded(hvd_module):
    rng = np.random.RandomState(1)
    x = rng.randn(N, 4096).astype(np.float32)
    y = _run(x, op=traced.Average)
    expect = x.mean(axis=0)
    # two quantizations: |err| <= 0.5*amax_in/127 + 0.5*amax_sum/(127*N)
    bound = (
        0.5 * np.abs(x).max(axis=1).max() / 127.0
        + 0.5 * np.abs(x.sum(0)).max() / 127.0
    ) / N * 2.0 + 1e-5
    assert np.abs(y[0] - expect).max() <= bound


def test_wire_is_int8(hvd_module):
    """The collectives must carry s8 operands, not f32."""
    V = 4096

    def body(v):
        return quantized_allreduce(v[0], op=traced.Sum)[None]

    hlo = jax.jit(shard_map(
        body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
        out_specs=P(WORLD_AXIS), check_vma=False,
    )).lower(jnp.zeros((N, V), jnp.float32)).compile().as_text()
    colls = [
        l for l in hlo.splitlines()
        if re.search(r"= \S+ (all-to-all|all-gather)\(", l)
    ]
    assert colls
    # the payload-sized collectives are int8; fp32 appears only in the
    # tiny scale exchanges
    for line in colls:
        if str(V) in line or str(V // N) in line:
            assert "s8[" in line, line


def test_block_scales_preserve_small_magnitude_regions(hvd_module):
    """A huge-magnitude region must not flush a small-magnitude region
    to zero — the reason for blockwise scales (EQuARX block design)."""
    from horovod_tpu.ops.quantized import BLOCK

    x = np.zeros((N, 4 * BLOCK), np.float32)
    x[:, :BLOCK] = 1e3          # "layer A" block
    x[:, BLOCK:] = 1e-4         # "layer B" blocks
    y = _run(x, op=traced.Average)
    # small region survives with small relative error
    np.testing.assert_allclose(y[0][BLOCK:], 1e-4, rtol=2e-2)
    np.testing.assert_allclose(y[0][:BLOCK], 1e3, rtol=2e-2)


def test_nonfinite_propagates(hvd_module):
    """inf/nan gradients must surface, not silently zero (the cast
    compressors preserve non-finites; overflow-skip logic depends on
    seeing them)."""
    x = np.ones((N, 2048), np.float32)
    x[3, 7] = np.inf
    y = _run(x, op=traced.Sum)
    assert not np.isfinite(y[0]).all()
    x2 = np.ones((N, 2048), np.float32)
    x2[1, 0] = np.nan
    y2 = _run(x2, op=traced.Sum)
    assert np.isnan(y2[0]).any()


def test_int8_rejects_sparse_leaves(hvd_module):
    from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
    from horovod_tpu.ops.sparse import IndexedSlices
    from horovod_tpu.ops.traced import Average

    s = IndexedSlices(jnp.zeros((2,), jnp.int32), jnp.zeros((2, 4)),
                      (16, 4))
    with pytest.raises(ValueError, match="IndexedSlices"):
        _reduce_gradients(
            {"emb": s}, axis=WORLD_AXIS, op=Average,
            compression=hvd.Compression.int8, prescale_factor=1.0,
            postscale_factor=1.0, process_set=None,
            fusion_threshold_bytes=None,
        )


def test_zero_input_safe(hvd_module):
    x = np.zeros((N, 128), np.float32)
    y = _run(x, op=traced.Sum)
    np.testing.assert_array_equal(y, 0.0)


def test_rejects_subsets_and_bad_ops(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    ps = hvd.add_process_set([0, 1])
    with pytest.raises(Exception, match="global"):
        _run(np.ones((N, 8), np.float32), process_set=ps)
    hvd.remove_process_set(ps)
    with pytest.raises(ValueError, match="Sum/Average"):
        _run(np.ones((N, 8), np.float32), op=traced.Max)


def test_optimizer_int8_compression_trains(hvd_module):
    rng = np.random.RandomState(2)
    W = rng.randn(16, 1).astype(np.float32)
    X = rng.randn(64 * N, 16).astype(np.float32)
    Y = X @ W
    params = {"w": jnp.zeros((16, 1))}
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.05), compression=hvd.Compression.int8
    )

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    losses = []
    for _ in range(60):
        params, opt_state, loss = step(
            params, opt_state, (jnp.asarray(X), jnp.asarray(Y))
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_optimizer_int8_rejects_subsets(hvd_module, monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
    from horovod_tpu.ops.traced import Average

    ps = hvd.add_process_set([0, 1])
    with pytest.raises(ValueError, match="global"):
        _reduce_gradients(
            {"w": jnp.ones((4,))}, axis=WORLD_AXIS, op=Average,
            compression=hvd.Compression.int8, prescale_factor=1.0,
            postscale_factor=1.0, process_set=ps,
            fusion_threshold_bytes=None,
        )
    hvd.remove_process_set(ps)
