"""Lightning-protocol estimator (reference
``spark/lightning/estimator.py:619`` + ``spark/lightning/remote.py``).

No pytorch_lightning dependency: plain ``torch.nn.Module``s that define
``training_step``/``configure_optimizers`` (the lightning protocol, as
real LightningModules do) are the fixtures.
"""

import numpy as np
import pytest
import torch

from horovod_tpu.spark import LightningEstimator, LocalStore


def _regression_data(n=256, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    y = (X @ w).squeeze(-1) + 0.01 * rng.randn(n).astype(np.float32)
    return X, y


class LitRegressor(torch.nn.Module):
    """Lightning-protocol module without lightning."""

    def __init__(self, d=4, lr=0.05):
        super().__init__()
        self.net = torch.nn.Linear(d, 1)
        self.lr = lr
        # a buffer so the count survives the worker's state_dict
        # roundtrip (the worker trains a pickled copy of the module)
        self.register_buffer("epoch_end_calls",
                             torch.zeros((), dtype=torch.int64))

    def forward(self, x):
        return self.net(x)

    def training_step(self, batch, batch_idx):
        x, y = batch
        pred = self(x).squeeze(-1)
        return torch.nn.functional.mse_loss(pred, y.float())

    def validation_step(self, batch, batch_idx):
        x, y = batch
        pred = self(x).squeeze(-1)
        return {"val_loss": torch.nn.functional.mse_loss(pred, y.float())}

    def configure_optimizers(self):
        return torch.optim.Adam(self.parameters(), lr=self.lr)

    def on_train_epoch_end(self):
        self.epoch_end_calls += 1


class LitWithScheduler(LitRegressor):
    def configure_optimizers(self):
        opt = torch.optim.SGD(self.parameters(), lr=0.1, momentum=0.9)
        sch = torch.optim.lr_scheduler.StepLR(opt, step_size=1, gamma=0.5)
        return [opt], [sch]


class TestLightningEstimator:
    def test_fit_predict_history(self, hvd_module, tmp_path):
        X, y = _regression_data()
        est = LightningEstimator(
            model=LitRegressor(), batch_size=32, epochs=5,
            validation=0.25,
            store=LocalStore(str(tmp_path / "lstore")), run_id="lit_run",
        )
        model = est.fit_on_arrays(features=X, label=y)
        pred = model.predict(X)
        mse = float(np.mean((pred.squeeze(-1) - y) ** 2))
        assert mse < float(np.var(y)) * 0.5, mse
        # keras-shaped history with train + val series, one point/epoch
        assert len(model.history["loss"]) == 5
        assert len(model.history["val_loss"]) == 5
        assert model.history["loss"][-1] < model.history["loss"][0]
        # protocol hooks ran (in the worker; buffer rode the state back)
        assert int(est.model.epoch_end_calls) == 5
        assert est._has_checkpoint()

    def test_checkpoint_resume(self, hvd_module, tmp_path):
        X, y = _regression_data()
        store = LocalStore(str(tmp_path / "rstore"))
        est1 = LightningEstimator(
            model=LitRegressor(), batch_size=32, epochs=2, store=store,
            run_id="resume_run",
        )
        m1 = est1.fit_on_arrays(features=X, label=y)
        w_after_2 = {k: v.copy() for k, v in
                     {k: v.detach().numpy()
                      for k, v in m1.model.state_dict().items()}.items()}
        # A fresh estimator with more epochs resumes from epoch 2: the
        # history only contains the NEW epochs (reference
        # _has_checkpoint resume).
        est2 = LightningEstimator(
            model=LitRegressor(), batch_size=32, epochs=4, store=store,
            run_id="resume_run",
        )
        m2 = est2.fit_on_arrays(features=X, label=y)
        assert len(m2.history["loss"]) == 2
        # and training continued (weights moved beyond the checkpoint)
        moved = any(
            not np.allclose(w_after_2[k], v.detach().numpy())
            for k, v in m2.model.state_dict().items()
        )
        assert moved

    def test_scheduler_steps(self, hvd_module, tmp_path):
        X, y = _regression_data()
        est = LightningEstimator(
            model=LitWithScheduler(), batch_size=64, epochs=3,
            store=LocalStore(str(tmp_path / "sstore")), run_id="sch_run",
        )
        est.fit_on_arrays(features=X, label=y)
        # StepLR gamma=0.5 stepped once per epoch: 0.1 -> 0.0125
        lr = est.model.configure_optimizers()[0][0].param_groups[0]["lr"]
        assert lr == pytest.approx(0.1)  # fresh optimizer unaffected

    def test_optimizer_and_scheduler_state_resumed(self, hvd_module,
                                                   tmp_path):
        """Resume restores Adam moments and scheduler counters — the
        checkpoint's sched state must show the TOTAL epochs stepped,
        not a restart from zero."""
        X, y = _regression_data()
        store = LocalStore(str(tmp_path / "ostore"))
        est1 = LightningEstimator(
            model=LitWithScheduler(), batch_size=64, epochs=2,
            store=store, run_id="opt_run",
        )
        est1.fit_on_arrays(features=X, label=y)
        ck = store.load_checkpoint("opt_run")
        assert ck["sched"][0]["last_epoch"] == 2
        est2 = LightningEstimator(
            model=LitWithScheduler(), batch_size=64, epochs=4,
            store=store, run_id="opt_run",
        )
        est2.fit_on_arrays(features=X, label=y)
        ck = store.load_checkpoint("opt_run")
        # 2 resumed + 2 new epochs; a restart-from-zero would read 2
        assert ck["sched"][0]["last_epoch"] == 4
        assert ck["opt"]["state"], "optimizer state not checkpointed"

    def test_two_optimizer_tuple_uses_first(self, hvd_module, tmp_path):
        """A bare 2-tuple of optimizers is multiple optimizers (not
        (optimizers, schedulers)); the first drives training and the
        second must NOT be stepped as a scheduler."""
        class TwoOpt(LitRegressor):
            def configure_optimizers(self):
                return (torch.optim.Adam(self.parameters(), lr=0.05),
                        torch.optim.SGD(self.parameters(), lr=0.0))

        X, y = _regression_data(n=64)
        est = LightningEstimator(
            model=TwoOpt(), batch_size=32, epochs=3,
            store=LocalStore(str(tmp_path / "twostore")), run_id="two_run",
        )
        model = est.fit_on_arrays(features=X, label=y)
        assert model.history["loss"][-1] < model.history["loss"][0]

    def test_protocol_enforced(self):
        with pytest.raises(TypeError, match="lightning protocol"):
            LightningEstimator(model=torch.nn.Linear(4, 1))

    def test_validation_fraction_validated(self):
        with pytest.raises(ValueError, match="fraction"):
            LightningEstimator(model=LitRegressor(), validation=1.5)

    def test_dict_configure_optimizers(self, hvd_module, tmp_path):
        class DictOpt(LitRegressor):
            def configure_optimizers(self):
                opt = torch.optim.Adam(self.parameters(), lr=0.05)
                sch = torch.optim.lr_scheduler.StepLR(opt, 1, gamma=0.9)
                return {"optimizer": opt,
                        "lr_scheduler": {"scheduler": sch}}

        X, y = _regression_data(n=64)
        est = LightningEstimator(
            model=DictOpt(), batch_size=32, epochs=2,
            store=LocalStore(str(tmp_path / "dostore")), run_id="do_run",
        )
        model = est.fit_on_arrays(features=X, label=y)
        assert len(model.history["loss"]) == 2

    def test_dict_training_step_loss(self, hvd_module, tmp_path):
        class DictLit(LitRegressor):
            def training_step(self, batch, batch_idx):
                return {"loss": super().training_step(batch, batch_idx)}

        X, y = _regression_data(n=64)
        est = LightningEstimator(
            model=DictLit(), batch_size=32, epochs=2,
            store=LocalStore(str(tmp_path / "dstore")), run_id="dict_run",
        )
        model = est.fit_on_arrays(features=X, label=y)
        assert len(model.history["loss"]) == 2
