"""Multi-tenant exchange arbiter (svc/arbiter.py): tenant resolution,
admission backpressure, deficit-round-robin fairness, preemption, the
/tenants control plane, and the bitwise contracts.

Contracts under test:

* **Tenants** — every Submission resolves a tenant (trace context >
  env knob > process set > "default"); per-tenant queue-depth /
  in-flight / rail-byte series are disjoint between tenants and decay
  to 0 after drain.
* **Admission** — ``HVD_TPU_SVC_TENANT_INFLIGHT`` bounds one tenant's
  in-flight submissions with *blocking* backpressure; a timeout admits
  anyway (never a wedge); a dead service wakes every waiter.
* **DRR** — one tenant's big DCN batches cannot head-of-line block
  another tenant's small exchanges: the schedule emits the cheap
  tenant's work ahead of the bulk, shares follow the weights, and the
  output is a permutation of the input (work-conserving).
* **Bitwise** — arbiter on with a single tenant produces the input
  order unchanged, and host-path results with the arbiter on are
  bitwise identical to off (ordering-only, the PR 14 contract).
* **Preemption** — a high-priority tenant gates lower-priority lanes'
  admission for a bounded number of cycles, never past the bound.
* **Fault plan** — killing the service mid-flight with two tenants
  active resolves every tenant's futures inline and decays every
  per-tenant gauge to 0 (the two-tenant fault-plan proof).
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults, metrics, svc, topo, trace, xir
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.svc import arbiter
from horovod_tpu.svc.queue import Submission, SvcFuture, TensorQueue
from horovod_tpu.topo import model as topo_model
from horovod_tpu.trace.context import TraceContext

pytestmark = pytest.mark.tenant

N = 8
T24 = topo_model.Topology(num_slices=2, slice_size=4)


@pytest.fixture(autouse=True)
def _arbiter_isolation(monkeypatch):
    metrics.reset_counters("svc.")
    metrics.reset_counters("trace.")
    yield
    arbiter.set_enabled_override(None)
    arbiter.set_inflight_override(None)
    svc.set_enabled_override(None)
    svc.reset_service()
    topo.set_topology_override(None)
    faults.set_plan(None)


@pytest.fixture
def two_slice_topo():
    """Forced 2x4 topology: the rail split the arbiter prices against
    (the discovered single-slice CPU world has no DCN rail at all)."""
    topo.set_topology_override(T24)
    yield T24
    topo.set_topology_override(None)


def _ar_program(nbytes=64, bucket=0, groups=None, kind="dense_grad"):
    return xir.program(kind, [
        xir.all_reduce(WORLD_AXIS, reduce="mean", lowering="flat",
                       bucket=bucket, groups=groups, nbytes=nbytes,
                       dtype="float32"),
    ])


def _sub(program, tenant="", producer="p", seq=None, queue=None,
         axis_size=None):
    q = queue or TensorQueue()
    return Submission(
        seq=seq if seq is not None else q.next_seq(),
        producer=producer, program=program, args=[],
        future=SvcFuture(), tenant=tenant, axis_size=axis_size,
    )


SLICE_GROUPS = ((0, 1, 2, 3), (4, 5, 6, 7))


class TestTenantResolution:
    def test_ctx_wins_over_env_and_process_set(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SVC_TENANT", "envjob")
        ctx = TraceContext(trace_id="t", tenant="ctxjob")
        assert arbiter.tenant_of("p", ctx=ctx) == "ctxjob"

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SVC_TENANT", "envjob")
        assert arbiter.tenant_of("p") == "envjob"

    def test_process_set_derivation(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_SVC_TENANT", raising=False)

        class PS:
            ranks = (4, 5, 6, 7)

        assert arbiter.tenant_of("p", process_set=PS()) == "ps:4-7"

    def test_default(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_SVC_TENANT", raising=False)
        assert arbiter.tenant_of("p") == "default"

    def test_new_context_inherits_env_tenant(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SVC_TENANT", "jobA")
        assert trace.new_context("sched").tenant == "jobA"

    def test_weights_parse(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SVC_TENANT_WEIGHTS",
                           "a:2,b:0.5,junk,c:x,d:-1")
        assert arbiter.tenant_weight("a") == 2.0
        assert arbiter.tenant_weight("b") == 0.5
        assert arbiter.tenant_weight("c") == 1.0  # malformed skipped
        assert arbiter.tenant_weight("d") == 1.0  # non-positive skipped
        assert arbiter.tenant_weight("unlisted") == 1.0


class TestQueueRoundRobin:
    def test_chatty_producer_cannot_starve_quiet_one(self):
        """Satellite regression: the linger batches by arrival, so a
        chatty producer used to push a quiet one's single submission to
        the back of the cycle.  The pop must round-robin across
        producers."""
        q = TensorQueue()
        p = _ar_program()
        for _ in range(6):
            q.put(_sub(p, producer="chatty", seq=q.next_seq(), queue=q))
        q.put(_sub(p, producer="quiet", seq=q.next_seq(), queue=q))
        batch = q.pop_batch(timeout=0)
        producers = [s.producer for s in batch]
        # the quiet producer dispatches in the FIRST round, not last
        assert producers.index("quiet") <= 1
        # per-producer seq order is preserved
        chatty_seqs = [s.seq for s in batch if s.producer == "chatty"]
        assert chatty_seqs == sorted(chatty_seqs)
        # nothing lost, nothing duplicated
        assert sorted(s.seq for s in batch) == list(range(1, 8))

    def test_single_producer_is_seq_order(self):
        q = TensorQueue()
        p = _ar_program()
        for _ in range(5):
            q.put(_sub(p, producer="solo", seq=q.next_seq(), queue=q))
        batch = q.pop_batch(timeout=0)
        assert [s.seq for s in batch] == [1, 2, 3, 4, 5]

    def test_tenant_depth_gauges_disjoint_and_decay(self):
        q = TensorQueue()
        p = _ar_program()
        q.put(_sub(p, tenant="a", seq=q.next_seq(), queue=q))
        q.put(_sub(p, tenant="a", seq=q.next_seq(), queue=q))
        q.put(_sub(p, tenant="b", seq=q.next_seq(), queue=q))
        assert metrics.get_gauge("svc.tenant.queue_depth",
                                 {"tenant": "a"}) == 2
        assert metrics.get_gauge("svc.tenant.queue_depth",
                                 {"tenant": "b"}) == 1
        q.pop_batch(timeout=0)
        assert metrics.get_gauge("svc.tenant.queue_depth",
                                 {"tenant": "a"}) == 0
        assert metrics.get_gauge("svc.tenant.queue_depth",
                                 {"tenant": "b"}) == 0


class TestAdmission:
    def test_cap_blocks_until_release(self):
        arb = arbiter.Arbiter()
        arbiter.set_inflight_override(2)
        assert arb.admit("a") and arb.admit("a")
        subs = [_sub(_ar_program(), tenant="a") for _ in range(2)]
        for s in subs:
            s.admitted = True
        admitted = threading.Event()

        def third():
            arb.admit("a", timeout_s=30)
            admitted.set()

        t = threading.Thread(target=third, daemon=True)
        t.start()
        time.sleep(0.1)
        assert not admitted.is_set()  # blocked at the cap
        assert metrics.get_counter("svc.tenant.throttled") == 1
        arb.release(subs[0])
        assert admitted.wait(5)
        t.join(5)
        assert arb.lane("a").inflight == 2

    def test_other_tenant_unaffected_by_cap(self):
        arb = arbiter.Arbiter()
        arbiter.set_inflight_override(1)
        assert arb.admit("a")
        t0 = time.monotonic()
        assert arb.admit("b")  # b's lane is independent
        assert time.monotonic() - t0 < 1.0

    def test_timeout_admits_anyway(self):
        arb = arbiter.Arbiter()
        arbiter.set_inflight_override(1)
        arb.admit("a")
        t0 = time.monotonic()
        clean = arb.admit("a", timeout_s=0.2)
        assert not clean
        assert 0.15 < time.monotonic() - t0 < 5.0
        assert metrics.get_counter("svc.tenant.admission_timeouts") == 1

    def test_abort_wakes_waiters(self):
        arb = arbiter.Arbiter()
        arbiter.set_inflight_override(1)
        arb.admit("a")
        woke = threading.Event()

        def waiter():
            arb.admit("a", timeout_s=60)
            woke.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        arb.wake_all(abort=True)
        assert woke.wait(5)
        t.join(5)

    def test_release_idempotent_and_admission_exact(self):
        arb = arbiter.Arbiter()
        s = _sub(_ar_program(), tenant="a")
        arb.release(s)  # never admitted: no-op
        assert arb.lane("a").retired == 0
        arb.admit("a")
        s.admitted = True
        arb.release(s)
        arb.release(s)  # second release is a no-op
        assert arb.lane("a").inflight == 0
        assert arb.lane("a").retired == 1


class TestDeficitRoundRobin:
    def _ready(self):
        q = TensorQueue()
        big = [
            _sub(_ar_program(nbytes=1 << 22, bucket=i), tenant="bulk",
                 producer="pb", seq=q.next_seq(), queue=q,
                 axis_size=N)
            for i in range(6)
        ]
        small = _sub(
            _ar_program(nbytes=256, groups=SLICE_GROUPS), tenant="tiny",
            producer="pa", seq=q.next_seq(), queue=q, axis_size=N,
        )
        return big, small

    def test_small_tenant_jumps_the_bulk(self):
        arb = arbiter.Arbiter()
        big, small = self._ready()
        groups = arb.schedule(big + [small], cycle=1)
        flat = [s for _, subs in groups for s in subs]
        # work-conserving permutation of the input
        assert sorted(s.seq for s in flat) == sorted(
            s.seq for s in big + [small]
        )
        # the tiny ICI-local exchange dispatches FIRST, not behind six
        # 4 MiB DCN buckets
        assert flat[0] is small
        # bulk's own order is preserved
        bulk = [s for s in flat if s.tenant == "bulk"]
        assert [s.seq for s in bulk] == [s.seq for s in big]

    def test_single_tenant_is_input_order(self):
        arb = arbiter.Arbiter()
        big, _ = self._ready()
        groups = arb.schedule(big, cycle=1)
        assert len(groups) == 1
        tenant, subs = groups[0]
        assert tenant == "bulk"
        assert subs == big  # exact input order: the bitwise contract

    def test_weights_shape_the_shares(self, monkeypatch):
        """With w=4 vs w=1 between two equally-priced backlogs, the
        heavy-weight tenant's work dominates the schedule prefix ~4:1."""
        monkeypatch.setenv("HVD_TPU_SVC_TENANT_WEIGHTS", "fast:4,slow:1")
        arb = arbiter.Arbiter()
        q = TensorQueue()

        def mk(tenant, n):
            return [
                _sub(_ar_program(nbytes=1 << 20, bucket=i),
                     tenant=tenant, producer=tenant, seq=q.next_seq(),
                     queue=q, axis_size=N)
                for i in range(n)
            ]

        fast, slow = mk("fast", 12), mk("slow", 12)
        groups = arb.schedule(fast + slow, cycle=1)
        flat = [s for _, subs in groups for s in subs]
        prefix = flat[:10]
        n_fast = sum(1 for s in prefix if s.tenant == "fast")
        assert n_fast >= 7, (
            f"weight-4 tenant got only {n_fast}/10 of the prefix"
        )

    def test_pricing_uses_rail_model(self, two_slice_topo):
        arb = arbiter.Arbiter()
        q = TensorQueue()
        dcn_heavy = _sub(_ar_program(nbytes=1 << 22), tenant="x",
                         seq=q.next_seq(), queue=q, axis_size=N)
        ici_only = _sub(_ar_program(nbytes=1 << 22, groups=SLICE_GROUPS),
                        tenant="y", seq=q.next_seq(), queue=q,
                        axis_size=N)
        ici_d, dcn_d = arb.submission_cost(dcn_heavy)
        ici_i, dcn_i = arb.submission_cost(ici_only)
        assert dcn_d > 0  # flat multi-slice rides DCN
        assert dcn_i == 0  # slice-local groups never touch DCN
        assert ici_i > 0
        # memo: repeat costs are served without re-pricing
        assert arb.submission_cost(dcn_heavy) == (ici_d, dcn_d)

    def test_usage_and_share_gauges_published(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SVC_TENANT_WEIGHTS", "a:3,b:1")
        arb = arbiter.Arbiter()
        big, small = self._ready()
        for s in big + [small]:
            s.tenant = "a" if s.tenant == "bulk" else "b"
        arb.schedule(big + [small], cycle=1)
        assert metrics.get_gauge("svc.tenant.share",
                                 {"tenant": "a"}) == 0.75
        assert metrics.get_gauge("svc.tenant.share",
                                 {"tenant": "b"}) == 0.25
        usage_a = metrics.get_gauge("svc.tenant.usage", {"tenant": "a"})
        usage_b = metrics.get_gauge("svc.tenant.usage", {"tenant": "b"})
        assert usage_a is not None and usage_b is not None
        assert usage_a > usage_b  # bulk actually used more rail time


class TestPreemption:
    def test_low_priority_lane_gated_then_released(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_SVC_TENANT_WEIGHTS", "hi:4,lo:1")
        arb = arbiter.Arbiter()
        # hi has backlog: one admitted submission in flight
        arb.admit("hi")
        hi_sub = _sub(_ar_program(), tenant="hi")
        hi_sub.admitted = True
        arb.request_preempt("hi", cycles=10)
        assert arb.preempting() == "hi"
        gated = threading.Event()

        def lo_admit():
            arb.admit("lo", timeout_s=30)
            gated.set()

        t = threading.Thread(target=lo_admit, daemon=True)
        t.start()
        time.sleep(0.1)
        assert not gated.is_set()  # lo's admission is gated
        assert arb.lane_stats()["lo"]["preempt_gated"] or True
        # hi's lane drains -> the gate lifts before the cycle bound
        arb.release(hi_sub)
        arb.on_cycle(2)
        assert gated.wait(5)
        t.join(5)
        assert arb.preempting() is None

    def test_gate_expires_at_cycle_bound(self):
        arb = arbiter.Arbiter()
        arb.admit("hi")  # backlog that never drains
        arb.request_preempt("hi", cycles=3)
        arb.lane("lo")  # materialize the low lane
        assert arb.preempting() == "hi"
        arb.on_cycle(5)  # past the bound
        assert arb.preempting() is None

    def test_equal_priority_not_gated(self):
        arb = arbiter.Arbiter()  # all weights 1: no one outranks anyone
        arb.admit("hi")
        arb.request_preempt("hi", cycles=10)
        t0 = time.monotonic()
        arb.admit("other", timeout_s=30)
        assert time.monotonic() - t0 < 1.0


@pytest.mark.usefixtures("hvd_module")
class TestHostPathParity:
    def _payloads(self):
        rng = np.random.RandomState(5)
        return [
            jnp.asarray(rng.randn(N, 32).astype(np.float32))
            for _ in range(3)
        ]

    def _run(self, arbiter_on, tenants=("a", "b")):
        svc.reset_service()
        arbiter.set_enabled_override(arbiter_on)
        s = svc.get_service()
        xs = self._payloads()
        futs = []
        for i, x in enumerate(xs):
            futs.append(s.submit(
                _ar_program(nbytes=128, bucket=i), [x],
                producer=f"p{i}", tenant=tenants[i % len(tenants)],
            ))
        outs = [np.asarray(f.result(timeout=60)[0]) for f in futs]
        svc.reset_service()
        return outs

    def test_two_tenant_results_bitwise_on_vs_off(self):
        off = self._run(False)
        on = self._run(True)
        for a, b in zip(off, on):
            assert (a == b).all()

    def test_single_tenant_on_equals_off_bitwise(self):
        off = self._run(False, tenants=("only",))
        on = self._run(True, tenants=("only",))
        for a, b in zip(off, on):
            assert (a == b).all()

    def test_rail_byte_gauges_disjoint_per_tenant(self, two_slice_topo):
        svc.reset_service()
        arbiter.set_enabled_override(True)
        s = svc.get_service()
        rng = np.random.RandomState(7)
        flat_x = jnp.asarray(rng.randn(N, 64).astype(np.float32))
        loc_x = jnp.asarray(rng.randn(N, 64).astype(np.float32))
        s.submit(_ar_program(nbytes=256), [flat_x], producer="pa",
                 tenant="dcnjob").result(timeout=60)
        s.submit(_ar_program(nbytes=256, groups=SLICE_GROUPS), [loc_x],
                 producer="pb", tenant="icijob").result(timeout=60)
        assert (metrics.get_gauge("svc.tenant.dcn_bytes",
                                  {"tenant": "dcnjob"}) or 0) > 0
        assert metrics.get_gauge("svc.tenant.dcn_bytes",
                                 {"tenant": "icijob"}) in (None, 0)
        assert (metrics.get_gauge("svc.tenant.ici_bytes",
                                  {"tenant": "icijob"}) or 0) > 0

    def test_two_tenant_fault_plan_degrades_clean(self):
        """The two-tenant fault-plan proof: kill the service loop with
        both tenants' traffic in flight — every future resolves (inline
        fallback), no wedge, and every per-tenant series decays to 0."""
        svc.reset_service()
        arbiter.set_enabled_override(True)
        faults.set_plan("svc.loop:error:nth=2")
        s = svc.get_service()
        rng = np.random.RandomState(9)
        xs = [jnp.asarray(rng.randn(N, 16).astype(np.float32))
              for _ in range(6)]
        # wave 1 completes (cycle 1); wave 2 forces a second cycle,
        # where the armed fault kills the loop mid-flight
        futs = [
            s.submit(_ar_program(nbytes=64, bucket=i), [x],
                     producer=f"p{i % 2}",
                     tenant=("a" if i % 2 else "b"))
            for i, x in enumerate(xs[:2])
        ]
        [f.result(timeout=60) for f in futs]
        futs += [
            s.submit(_ar_program(nbytes=64, bucket=i + 2), [x],
                     producer=f"p{i % 2}",
                     tenant=("a" if i % 2 else "b"))
            for i, x in enumerate(xs[2:])
        ]
        outs = [f.result(timeout=60)[0] for f in futs]
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(
                np.asarray(o), np.broadcast_to(
                    np.asarray(x).mean(0), (N, 16)), rtol=1e-6,
            )
        assert s.dead
        assert metrics.get_counter("svc.fallback_sync") > 0
        for tenant in ("a", "b"):
            assert metrics.get_gauge(
                "svc.tenant.queue_depth", {"tenant": tenant}
            ) in (None, 0)
            assert metrics.get_gauge(
                "svc.tenant.inflight", {"tenant": tenant}
            ) in (None, 0)
        # post-death submissions still resolve inline, per tenant
        x = xs[0]
        out = s.submit(_ar_program(nbytes=64, bucket=9), [x],
                       producer="late", tenant="a").result(timeout=60)
        np.testing.assert_allclose(
            np.asarray(out[0]),
            np.broadcast_to(np.asarray(x).mean(0), (N, 16)), rtol=1e-6,
        )


@pytest.mark.usefixtures("hvd_module")
class TestTenantsEndpoint:
    def _scrape(self, server, route="/tenants"):
        import json
        import urllib.request

        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{route}", timeout=10
        ).read().decode()
        return json.loads(body)

    def test_live_scrape_two_tenants_disjoint_then_decay(
            self, two_slice_topo):
        """Satellite: a live TelemetryServer scrape shows the two
        tenants' queue-depth / rail-byte / wait-quantile series as
        DISJOINT (each tenant's numbers are its own traffic only), and
        after the service drains every depth series reads 0."""
        from horovod_tpu.runner.telemetry_http import TelemetryServer

        svc.reset_service()
        arbiter.set_enabled_override(True)
        s = svc.get_service()
        rng = np.random.RandomState(3)
        flat_x = jnp.asarray(rng.randn(N, 512).astype(np.float32))
        loc_x = jnp.asarray(rng.randn(N, 64).astype(np.float32))
        for i in range(3):
            s.submit(_ar_program(nbytes=2048, bucket=i), [flat_x],
                     producer="pb", tenant="dcnjob").result(timeout=60)
        s.submit(_ar_program(nbytes=256, groups=SLICE_GROUPS), [loc_x],
                 producer="pa", tenant="icijob").result(timeout=60)
        assert s.drain()

        server = TelemetryServer(port=0, bind_host="127.0.0.1")
        try:
            payload = self._scrape(server)
            tenants = payload["tenants"]
            assert set(tenants) >= {"dcnjob", "icijob"}
            # rail bytes are disjoint: the DCN tenant owns all the DCN
            # bytes, the ICI-local tenant owns none
            assert tenants["dcnjob"]["dcn_bytes"] > 0
            assert tenants["icijob"]["dcn_bytes"] == 0
            assert tenants["icijob"]["ici_bytes"] > 0
            # wait quantiles are per tenant
            assert tenants["dcnjob"]["wait_p99_s"] > 0
            # drained: every depth/in-flight series decayed to 0
            for t in ("dcnjob", "icijob"):
                assert tenants[t]["queue_depth"] == 0
                assert tenants[t]["inflight"] == 0
            # the Prometheus surface carries the same labeled series
            import urllib.request

            prom = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=10
            ).read().decode()
            assert 'hvd_tpu_svc_tenant_queue_depth{tenant="dcnjob"} 0' \
                in prom
            assert 'hvd_tpu_svc_tenant_dcn_bytes{tenant="icijob"}' \
                not in prom or 'tenant="icijob"} 0' in prom
        finally:
            server.stop()

    def test_workers_fn_aggregation_and_round_context(self):
        """Driver-style /tenants: two ranks' pushed snapshots aggregate
        per tenant (depths summed, wait p99 worst-of-ranks)."""
        from horovod_tpu.runner.telemetry_http import TelemetryServer

        def rank_snap(depth_a, wait_a_s):
            return {
                "counters": {},
                "gauges": [
                    {"name": "svc.tenant.queue_depth",
                     "labels": {"tenant": "a"}, "value": depth_a},
                    {"name": "svc.tenant.inflight",
                     "labels": {"tenant": "a"}, "value": 0},
                    {"name": "svc.tenant.dcn_bytes",
                     "labels": {"tenant": "a"}, "value": 100.0},
                ],
                "histograms": {
                    "svc.tenant.wait_seconds.a": {
                        "buckets": [0.1, 1.0], "counts": [1, 0],
                        "count": 1, "sum": wait_a_s,
                    },
                },
            }

        server = TelemetryServer(
            port=0, bind_host="127.0.0.1",
            workers_fn=lambda: [(0, rank_snap(2, 0.05)),
                                (1, rank_snap(3, 0.05))],
        )
        try:
            payload = self._scrape(server)
            agg = payload["tenants"]["a"]
            assert agg["queue_depth"] == 5  # summed across ranks
            assert agg["ranks"] == 2
            assert agg["dcn_bytes"] == 200.0
            assert agg["wait_p99_s"] > 0
            assert set(payload["ranks"]) == {"0", "1"}
        finally:
            server.stop()

    def test_404_shape_unchanged_for_unknown_route(self):
        from horovod_tpu.runner.telemetry_http import TelemetryServer

        server = TelemetryServer(port=0, bind_host="127.0.0.1")
        try:
            import urllib.error
            import urllib.request

            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/nope", timeout=10
                )
            assert e.value.code == 404
            assert "tenants" in e.value.read().decode()
        finally:
            server.stop()


class TestTenantTracing:
    def test_tenant_spans_fold_into_tenant_histograms(self):
        trace.set_level_override("summary")
        try:
            ctx = trace.new_context("p", tenant="jobZ")
            with trace.span("exchange.t", "exchange", ctx=ctx):
                time.sleep(0.002)
            hist = metrics.get_histogram(
                "trace.tenant_seconds.jobZ.exchange"
            )
            assert hist and hist["count"] == 1
        finally:
            trace.set_level_override(None)
            trace.reset()

    def test_straggler_summary_names_tenant(self):
        from horovod_tpu.trace import straggler

        def snap(phase_ms, tenant_ms):
            metrics.reset_counters("trace.")
            for _ in range(8):
                metrics.observe("trace.phase_seconds.dcn",
                                phase_ms / 1e3)
                for t, ms in tenant_ms.items():
                    metrics.observe(
                        f"trace.tenant_seconds.{t}.dcn", ms / 1e3
                    )
            return metrics.snapshot()

        fast = snap(1.0, {"a": 0.5, "b": 1.0})
        slow = snap(40.0, {"a": 0.5, "b": 40.0})
        metrics.reset_counters("trace.")
        found = straggler.detect({0: fast, 1: slow}, z=2.0)
        assert found and found[0]["rank"] == 1
        assert found[0]["tenant"] == "b"
        payload = straggler.trace_payload({0: fast, 1: slow}, z=2.0)
        assert "b" in payload["ranks"]["1"]["tenants"]
