"""Framework-binding breadth: the reference binds TF/torch/MXNet/Keras
(SURVEY.md §2.3); our surface is pytree-native, so any JAX framework
plugs in unchanged.  These tests pin that claim for dm-haiku and
HuggingFace transformers-flax (both common in TPU shops), alongside the
flax models used everywhere else and the torch adapter in
test_interop.py."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd


def test_haiku_model_trains(hvd_module):
    haiku = pytest.importorskip("haiku")

    def net_fn(x):
        return haiku.nets.MLP([16, 4])(x)

    net = haiku.without_apply_rng(haiku.transform(net_fn))
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    y = (np.abs(x.sum(axis=1)) * 10).astype(np.int32) % 4

    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(p, batch):
        xb, yb = batch
        return optax.softmax_cross_entropy_with_integer_labels(
            net.apply(p, xb), yb
        ).mean()

    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    losses = []
    for _ in range(10):
        params, st, loss = step(params, st, (jnp.asarray(x), jnp.asarray(y)))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_transformers_flax_gpt2_trains(hvd_module):
    transformers = pytest.importorskip("transformers")
    from transformers import FlaxGPT2LMHeadModel, GPT2Config

    config = GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2,
    )
    model = FlaxGPT2LMHeadModel(config, seed=0)  # random init, no download
    params = hvd.broadcast_parameters(model.params, root_rank=0)

    rng = np.random.RandomState(0)
    toks = rng.randint(0, 128, (8, 16)).astype(np.int32)

    def loss_fn(p, batch):
        input_ids = batch[0]
        logits = model(input_ids=input_ids, params=p).logits
        onehot = jax.nn.one_hot(input_ids[:, 1:], 128)
        return -jnp.mean(
            jnp.sum(jax.nn.log_softmax(logits[:, :-1]) * onehot, -1)
        )

    tx = hvd.DistributedOptimizer(optax.adamw(5e-3))
    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    losses = []
    for _ in range(8):
        params, st, loss = step(params, st, (jnp.asarray(toks),))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
