"""Keras/Torch estimator parity and the torch DistributedOptimizer.

Reference anchors: ``spark/keras/estimator.py:581``,
``spark/torch/estimator.py:506``, ``torch/optimizer.py:506``,
``spark/common/estimator.py:91`` (_has_checkpoint resume)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.spark import KerasEstimator, LocalStore, TorchEstimator


def _linear_flax():
    import flax.linen as nn

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)

    return Linear()


def _regression_data(n=256, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    y = (X @ w).squeeze(-1) + 0.01 * rng.randn(n).astype(np.float32)
    return X, y


class TestKerasEstimator:
    def _make(self, tmp_path, epochs=3, **kw):
        import optax

        def mse(pred, y):
            return jnp.mean((pred.squeeze(-1) - y) ** 2)

        def mae(pred, y):
            return jnp.mean(jnp.abs(pred.squeeze(-1) - y))

        return KerasEstimator(
            model=_linear_flax(), optimizer=optax.adam(0.05), loss=mse,
            metrics={"mae": mae}, validation=0.25, batch_size=32,
            epochs=epochs, store=LocalStore(str(tmp_path / "store")),
            run_id="keras_run", **kw,
        )

    def test_fit_history_and_metrics(self, hvd_module, tmp_path):
        X, y = _regression_data()
        est = self._make(tmp_path)
        model = est.fit_on_arrays(features=X, label=y)
        h = model.history
        assert set(h) == {"loss", "val_loss", "val_mae"}
        assert len(h["loss"]) == 3
        assert h["loss"][-1] < h["loss"][0]
        pred = model.predict(X[:8])
        assert pred.shape == (8, 1)

    def test_callbacks_invoked(self, hvd_module, tmp_path):
        """Callbacks ship to the worker by value (the reference also
        runs user callbacks remotely), so observe them via the fs."""
        X, y = _regression_data()
        log = tmp_path / "cb.log"

        class Recorder:
            def __init__(self, path):
                self.path = path

            def on_epoch_begin(self, epoch, logs):
                with open(self.path, "a") as fh:
                    fh.write(f"begin {epoch}\n")

            def on_epoch_end(self, epoch, logs):
                with open(self.path, "a") as fh:
                    fh.write(f"end {epoch} {','.join(sorted(logs))}\n")

        est = self._make(tmp_path, epochs=2, callbacks=[Recorder(str(log))])
        est.fit_on_arrays(features=X, label=y)
        lines = log.read_text().splitlines()
        assert "begin 0" in lines and "begin 1" in lines
        ends = [l for l in lines if l.startswith("end")]
        assert len(ends) == 2 and "val_loss" in ends[0]

    def test_checkpoint_resume(self, hvd_module, tmp_path):
        """_has_checkpoint semantics (estimator.py:91): a second fit
        resumes from the stored epoch instead of restarting."""
        X, y = _regression_data()
        est = self._make(tmp_path, epochs=2)
        assert not est._has_checkpoint()
        est.fit_on_arrays(features=X, label=y)
        assert est._has_checkpoint()
        ckpt = est.store.load_checkpoint("keras_run")
        assert ckpt["epoch"] == 1
        assert "opt_state" in ckpt  # optimizer moments survive resume
        # Resume: epochs=4 now -> only epochs 2,3 actually train.
        est2 = self._make(tmp_path, epochs=4)
        est2.run_id = "keras_run"
        model = est2.fit_on_arrays(features=X, label=y)
        assert len(model.history["loss"]) == 2  # epochs 2 and 3 only
        assert est2.store.load_checkpoint("keras_run")["epoch"] == 3

    def test_validation_fraction_validated(self, tmp_path):
        import optax

        with pytest.raises(ValueError, match="fraction"):
            KerasEstimator(
                model=_linear_flax(), optimizer=optax.adam(0.05),
                loss=lambda p, y: jnp.mean(p), validation=1.5,
                store=LocalStore(str(tmp_path / "s")),
            )


class TestTorchEstimator:
    def test_fit_and_predict(self, hvd_module, tmp_path):
        import torch

        X, y = _regression_data()
        est = TorchEstimator(
            model=torch.nn.Sequential(torch.nn.Linear(4, 1)),
            optimizer=lambda params: torch.optim.Adam(params, lr=0.05),
            loss=lambda pred, t: torch.nn.functional.mse_loss(
                pred.squeeze(-1), t.float()
            ),
            batch_size=32, epochs=5,
            store=LocalStore(str(tmp_path / "tstore")), run_id="torch_run",
        )
        model = est.fit_on_arrays(features=X, label=y)
        pred = model.predict(X)
        mse = float(np.mean((pred.squeeze(-1) - y) ** 2))
        assert mse < float(np.var(y)) * 0.5, mse
        assert est._has_checkpoint()


class TestTorchDistributedOptimizer:
    def test_single_process_step_applies(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        lin = torch.nn.Linear(3, 1)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(lin.parameters(), lr=0.1)
        )
        x = torch.randn(8, 3)
        before = lin.weight.detach().clone()
        loss = lin(x).pow(2).mean()
        loss.backward()
        opt.step()
        opt.zero_grad()
        assert not torch.allclose(before, lin.weight)
        # passthrough surface
        assert opt.param_groups[0]["lr"] == 0.1
        assert "state" in opt.state_dict()

    def test_backward_passes_per_step_accumulates(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        lin = torch.nn.Linear(2, 1, bias=False)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(lin.parameters(), lr=1.0),
            backward_passes_per_step=2,
        )
        x = torch.ones(1, 2)
        before = lin.weight.detach().clone()
        lin(x).sum().backward()
        opt.step()  # accumulation call: must not apply
        assert torch.allclose(before, lin.weight)
        lin(x).sum().backward()  # grads accumulate (no zero_grad between)
        opt.step()  # boundary: averaged accumulated grad applied
        assert not torch.allclose(before, lin.weight)
        opt.zero_grad()
        # average_aggregated_gradients: applied grad = (g1+g2)/2 = g
        expect = before - 1.0 * torch.ones_like(before) * x[0, 0]
        assert torch.allclose(lin.weight, expect, atol=1e-6)

    def test_is_a_torch_optimizer(self, hvd_module):
        """Reference parity (torch/optimizer.py:718 dynamic subclass):
        LR schedulers isinstance-check the optimizer."""
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        lin = torch.nn.Linear(2, 1)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(lin.parameters(), lr=0.1)
        )
        assert isinstance(opt, torch.optim.Optimizer)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1, gamma=0.5)
        lin(torch.ones(1, 2)).sum().backward()
        opt.step()
        sched.step()
        assert opt.param_groups[0]["lr"] == pytest.approx(0.05)

    def test_load_state_dict_reaches_wrapped_optimizer(self, hvd_module):
        """Inherited torch mutators must delegate to the wrapped
        optimizer — a rebinding load_state_dict would silently train
        from reset moments after checkpoint resume."""
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        def make():
            lin = torch.nn.Linear(2, 1)
            return lin, hvd_torch.DistributedOptimizer(
                torch.optim.Adam(lin.parameters(), lr=0.1)
            )

        lin1, opt1 = make()
        for _ in range(3):
            lin1(torch.ones(1, 2)).sum().backward()
            opt1.step()
            opt1.zero_grad()
        saved = opt1.state_dict()

        lin2, opt2 = make()
        opt2.load_state_dict(saved)
        # the WRAPPED optimizer (what step() applies) carries the state
        inner_state = opt2._opt.state_dict()["state"]
        assert inner_state and any(
            int(s.get("step", 0)) == 3 for s in inner_state.values()
        )
        # and LR updates via param_groups still reach the wrapped opt
        opt2.param_groups[0]["lr"] = 0.5
        assert opt2._opt.param_groups[0]["lr"] == 0.5

    def test_explicit_synchronize_not_doubled(self, hvd_module):
        """synchronize() then step() must reduce exactly once
        (reference _synchronized/skip_synchronize contract)."""
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        lin = torch.nn.Linear(2, 1, bias=False)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(lin.parameters(), lr=1.0)
        )
        lin(torch.ones(1, 2)).sum().backward()
        opt.synchronize()
        g_after_sync = lin.weight.grad.detach().clone()
        before = lin.weight.detach().clone()
        with opt.skip_synchronize():
            opt.step()
        # applied update used exactly the synchronized grad, unscaled
        assert torch.allclose(lin.weight, before - g_after_sync)

    def test_predivide_requires_average(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        lin = torch.nn.Linear(2, 1)
        with pytest.raises(ValueError, match="Average"):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(lin.parameters(), lr=0.1),
                op=hvd.Sum, gradient_predivide_factor=2.0,
            )


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_torch_optimizer_averages():
    """Two processes with different grads must converge to the mean
    (the reference's allreduce-in-step contract)."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import torch

        import horovod_tpu as hvd
        import horovod_tpu.interop.torch as hvd_torch

        hvd.init()
        lin = torch.nn.Linear(1, 1, bias=False)
        with torch.no_grad():
            lin.weight.fill_(0.0)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(lin.parameters(), lr=1.0)
        )
        # rank r's gradient of (w * g_r) wrt w is g_r: 2 on rank 0, 4 on 1
        g = 2.0 * (hvd.process_rank() + 1)
        (lin(torch.ones(1, 1)) * g).sum().backward()
        opt.step()
        return float(lin.weight.detach()[0, 0])

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    # averaged grad = (2+4)/2 = 3 -> w = -3 on both ranks
    np.testing.assert_allclose(results, [-3.0, -3.0], rtol=1e-6)


class TestParquetStore:
    pytest.importorskip("pyarrow")

    def test_shard_roundtrip_ndarrays(self, tmp_path):
        """Parquet shards round-trip N-d columns exactly (the
        petastorm-parity format)."""
        from horovod_tpu.spark.store import read_shard, write_shard

        rng = np.random.RandomState(0)
        arrays = {
            "features": rng.rand(10, 4, 3).astype(np.float32),
            "label": rng.randint(0, 5, 10).astype(np.int64),
            "weight": rng.rand(10).astype(np.float32),
        }
        path = write_shard(str(tmp_path / "part-0"), arrays, "parquet")
        assert path.endswith(".parquet")
        back = read_shard(path)
        for k, v in arrays.items():
            np.testing.assert_array_equal(back[k], v)

    def test_readable_by_plain_pyarrow(self, tmp_path):
        """The files are REAL parquet — any parquet reader opens them."""
        import pyarrow.parquet as pq

        from horovod_tpu.spark.store import write_shard

        path = write_shard(
            str(tmp_path / "part-0"),
            {"label": np.arange(6, dtype=np.int32)}, "parquet",
        )
        table = pq.read_table(path)
        assert table.num_rows == 6

    def test_keras_estimator_parquet_format(self, hvd_module, tmp_path):
        import optax

        from horovod_tpu.spark import KerasEstimator, LocalStore

        X, y = _regression_data()
        est = KerasEstimator(
            model=_linear_flax(), optimizer=optax.adam(0.05),
            loss=lambda p, t: jnp.mean((p.squeeze(-1) - t) ** 2),
            batch_size=32, epochs=2,
            store=LocalStore(str(tmp_path / "pqstore")),
            run_id="pq_run", store_format="parquet",
        )
        model = est.fit_on_arrays(features=X, label=y)
        assert model.history["loss"][-1] < model.history["loss"][0]
        import glob

        assert glob.glob(str(tmp_path / "pqstore" / "*" / "part-0.parquet"))

    def test_bad_format_rejected(self, tmp_path):
        import optax

        from horovod_tpu.spark import LocalStore, TpuEstimator

        with pytest.raises(ValueError, match="npz.*parquet|parquet.*npz"):
            TpuEstimator(
                model=_linear_flax(), optimizer=optax.adam(0.05),
                loss=lambda p, t: jnp.mean(p),
                store=LocalStore(str(tmp_path / "s")), store_format="csv",
            )


class TestStreamingEstimatorReads:
    """VERDICT r3 item 9 gate: estimator epochs stream row-group
    windows (shard >> window) with fit results as good as the
    in-memory loader's."""

    def _fit(self, tmp_path, run_id, monkeypatch, streaming: bool):
        import optax

        from horovod_tpu.spark import LocalStore, TpuEstimator

        monkeypatch.setenv("HVD_TPU_STREAMING_READS",
                           "1" if streaming else "0")
        # 512-row shard vs a 64-row window: 8 windows per epoch
        monkeypatch.setenv("HVD_TPU_STREAM_WINDOW_ROWS", "64")
        rng = np.random.RandomState(3)
        X = rng.randn(512, 4).astype(np.float32)
        w = rng.randn(4, 1).astype(np.float32)
        y = (X @ w).squeeze(-1)
        import flax.linen as nn

        class Linear(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(1)(x)

        est = TpuEstimator(
            model=Linear(), optimizer=optax.adam(0.05),
            loss=lambda p, t: jnp.mean((p.squeeze(-1) - t) ** 2),
            batch_size=32, epochs=6, store_format="parquet",
            store=LocalStore(str(tmp_path / f"store_{run_id}")),
            run_id=run_id,
        )
        model = est.fit_on_arrays(features=X, label=y)
        pred = model.predict(X)
        return float(np.mean((pred.squeeze(-1) - y) ** 2)), float(np.var(y))

    def test_streaming_fit_matches_in_memory_quality(self, hvd_module,
                                                     tmp_path, monkeypatch):
        mse_stream, var = self._fit(tmp_path, "stream", monkeypatch, True)
        mse_mem, _ = self._fit(tmp_path, "mem", monkeypatch, False)
        assert mse_stream < var * 0.05, (mse_stream, var)
        # same convergence band as the materializing loader
        assert mse_stream < max(mse_mem * 3.0, var * 0.05)

    def test_streaming_loader_selected(self, hvd_module, tmp_path,
                                       monkeypatch):
        """The parquet path must actually pick the streaming loader."""
        from horovod_tpu.data import ParquetStreamLoader
        from horovod_tpu.spark.estimator import (
            _FeatureComposingLoader,
            _make_loader,
        )
        from horovod_tpu.spark.store import write_shard

        monkeypatch.setenv("HVD_TPU_STREAMING_READS", "1")
        rng = np.random.RandomState(0)
        write_shard(str(tmp_path / "part-00000"),
                    {"features": rng.randn(64, 4).astype(np.float32),
                     "label": rng.randn(64).astype(np.float32)},
                    fmt="parquet")
        loader, did_partition = _make_loader(
            str(tmp_path), ["features"], ["label"], batch_size=16
        )
        assert isinstance(loader, _FeatureComposingLoader)
        assert isinstance(loader._base, ParquetStreamLoader)
        assert not did_partition
        xb, yb = next(iter(loader))
        assert xb.shape == (16, 4) and yb.shape == (16,)


class TestTorchSyncBatchNorm:
    def test_single_process_matches_plain_bn(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        torch.manual_seed(0)
        x = torch.randn(8, 4, requires_grad=True)
        x2 = x.detach().clone().requires_grad_(True)
        sync = hvd_torch.SyncBatchNorm(4)
        plain = torch.nn.BatchNorm1d(4)
        plain.load_state_dict(sync.state_dict())
        y1 = sync(x)
        y2 = plain(x2)
        np.testing.assert_allclose(
            y1.detach().numpy(), y2.detach().numpy(), rtol=1e-5, atol=1e-6
        )
        y1.sum().backward()
        y2.sum().backward()
        np.testing.assert_allclose(
            x.grad.numpy(), x2.grad.numpy(), rtol=1e-4, atol=1e-6
        )
        np.testing.assert_allclose(
            sync.weight.grad.numpy(), plain.weight.grad.numpy(),
            rtol=1e-4, atol=1e-6,
        )
        np.testing.assert_allclose(
            sync.running_mean.numpy(), plain.running_mean.numpy(),
            rtol=1e-5, atol=1e-6,
        )

    def test_eval_mode_uses_running_stats(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        bn = hvd_torch.SyncBatchNorm(3)
        bn(torch.randn(16, 3))  # one training pass to move stats
        bn.eval()
        x = torch.randn(4, 3)
        y = bn(x)
        expect = (x - bn.running_mean) / torch.sqrt(
            bn.running_var + bn.eps
        ) * bn.weight + bn.bias
        np.testing.assert_allclose(
            y.detach().numpy(), expect.detach().numpy(),
            rtol=1e-5, atol=1e-6,
        )


class TestTorchCompression:
    def test_fp16_roundtrip(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        t = torch.tensor([1.5, -2.25, 3.0])
        wire, ctx = hvd_torch.Compression.fp16.compress(t)
        assert wire.dtype == torch.float16
        back = hvd_torch.Compression.fp16.decompress(wire, ctx)
        assert back.dtype == torch.float32
        np.testing.assert_allclose(back.numpy(), t.numpy())
        i = torch.tensor([1, 2])
        wire, ctx = hvd_torch.Compression.fp16.compress(i)
        assert wire.dtype == torch.int64 and ctx is None

    def test_optimizer_accepts_compression(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        m = torch.nn.Linear(4, 1)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(m.parameters(), lr=0.1),
            compression=hvd_torch.Compression.fp16,
        )
        loss = m(torch.ones(2, 4)).sum()
        loss.backward()
        opt.step()  # single process: reduction short-circuits


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_torch_sync_bn_global_moments():
    """Two processes, disjoint batches: torch SyncBatchNorm must
    normalize with GLOBAL moments and produce the global-batch dx
    (reference torch/sync_batch_norm.py semantics)."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import torch

        import horovod_tpu as hvd
        import horovod_tpu.interop.torch as hvd_torch

        hvd.init()
        r = hvd.process_rank()
        # global batch: rank0 rows = 0, rank1 rows = 10
        x = torch.full((4, 2), float(r * 10), requires_grad=True)
        bn = hvd_torch.SyncBatchNorm(2, momentum=1.0)
        y = bn(x)
        # weighted loss makes dx nontrivial and rank-dependent
        (y * (r + 1.0)).sum().backward()
        return {
            "y0": float(y.detach()[0, 0]),
            "rm": float(bn.running_mean[0]),
            "rv": float(bn.running_var[0]),
            "gx": x.grad.numpy().tolist(),
        }

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    # global mean 5, biased var 25 -> rank0 normalizes to -1, rank1 +1
    np.testing.assert_allclose(results[0]["y0"], -1.0, rtol=1e-4)
    np.testing.assert_allclose(results[1]["y0"], 1.0, rtol=1e-4)
    for r in results:
        np.testing.assert_allclose(r["rm"], 5.0, rtol=1e-4)
        np.testing.assert_allclose(r["rv"], 25.0 * 8 / 7, rtol=1e-4)

    # reference: single-process BN over the concatenated batch with the
    # same weighted loss; dx must match each rank's half
    import torch

    xa = torch.full((4, 2), 0.0)
    xb = torch.full((4, 2), 10.0)
    x_all = torch.cat([xa, xb]).requires_grad_(True)
    bn_ref = torch.nn.BatchNorm1d(2, momentum=1.0)
    y_ref = bn_ref(x_all)
    w = torch.cat([torch.full((4, 2), 1.0), torch.full((4, 2), 2.0)])
    (y_ref * w).sum().backward()
    np.testing.assert_allclose(
        results[0]["gx"], x_all.grad[:4].numpy(), rtol=1e-3, atol=1e-5
    )
    np.testing.assert_allclose(
        results[1]["gx"], x_all.grad[4:].numpy(), rtol=1e-3, atol=1e-5
    )


class TestTorchSyncBatchNormEdgeCases:
    def test_picklable_via_torch_save(self, hvd_module, tmp_path):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        m = torch.nn.Sequential(
            torch.nn.Linear(4, 4), hvd_torch.SyncBatchNorm(4)
        )
        p = tmp_path / "model.pt"
        torch.save(m, p)
        m2 = torch.load(p, weights_only=False)
        x = torch.randn(8, 4)
        m.eval(), m2.eval()
        np.testing.assert_allclose(
            m(x).detach().numpy(), m2(x).detach().numpy(), rtol=1e-6
        )

    def test_fp16_input_stats_do_not_overflow(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        bn = hvd_torch.SyncBatchNorm(2)
        # values whose sum-of-squares overflows fp16 (max 65504)
        x = torch.full((4096, 2), 10.0, dtype=torch.float16)
        y = bn(x)
        assert torch.isfinite(y.float()).all()
        assert torch.isfinite(bn.running_var).all()

    def test_num_batches_tracked_and_momentum_none(self, hvd_module):
        import torch

        import horovod_tpu.interop.torch as hvd_torch

        sync = hvd_torch.SyncBatchNorm(3, momentum=None)  # cumulative
        plain = torch.nn.BatchNorm1d(3, momentum=None)
        plain.load_state_dict(sync.state_dict())
        for seed in range(3):
            torch.manual_seed(seed)
            x = torch.randn(16, 3)
            sync(x), plain(x)
        assert int(sync.num_batches_tracked) == 3
        np.testing.assert_allclose(
            sync.running_mean.numpy(), plain.running_mean.numpy(),
            rtol=1e-5, atol=1e-6,
        )
