"""Fused quantized collectives (ops/pallas_quant.py): kernel-level
checks in Pallas interpret mode, backend dispatch, and fused-vs-phase
parity of the primitives on the 8-device CPU mesh.

The end-to-end fused column (dtype sweep, process-set subgroups, hier
lowering, EF equivalence) lives in tests/test_collective_matrix.py;
this file pins the kernel math itself — the shared quantization grid,
odd shapes, the block-size sweep — and the dispatch/knob surface.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.exceptions import QuantizedWireError
from horovod_tpu.ops import traced
from horovod_tpu.ops.quantized import (
    _block_scale,
    _dequantize_blocks,
    _quantize_blocks,
    quant_backend,
    quantized_all_gather,
    quantized_allreduce,
    quantized_reduce_scatter,
)
from horovod_tpu.runtime import WORLD_AXIS, get_runtime

pytestmark = [pytest.mark.pallas, pytest.mark.quant]

N = 8


def _mesh():
    return get_runtime().mesh


def _run(fn, *args, n_out=1):
    spec = P(WORLD_AXIS)
    out_specs = (spec,) * n_out if n_out > 1 else spec
    f = jax.jit(shard_map(
        fn, mesh=_mesh(), in_specs=(spec,) * len(args),
        out_specs=out_specs, check_vma=False,
    ))
    return f(*[jnp.asarray(a) for a in args])


# ------------------------------------------------------- kernel math


class TestHopKernel:
    """The interpret-mode hop kernel must reproduce the phase
    backend's quantization grid bit for bit (shared _block_scale)."""

    @pytest.mark.parametrize("wire", ["int8", "fp8"])
    @pytest.mark.parametrize("block", [64, 128, 512])
    def test_quant_math_matches_phase_grid(self, wire, block):
        from horovod_tpu.ops.pallas_quant import _quant_math

        rng = np.random.RandomState(0)
        c = 4 * block
        x = rng.randn(c).astype(np.float32) * 3.0
        # both sides under jit: XLA rewrites the /qmax into a
        # reciprocal multiply, so an eager reference would differ in
        # the last bit — the contract is jitted-grid == jitted-grid
        q_ref, s_ref = jax.jit(
            lambda v: _quantize_blocks(v[None], wire, block)
        )(jnp.asarray(x))
        q, s, deq = jax.jit(
            lambda v: _quant_math(v.reshape(c // block, block), wire)
        )(jnp.asarray(x))
        np.testing.assert_array_equal(
            np.asarray(q).reshape(-1), np.asarray(q_ref).reshape(-1)
        )
        np.testing.assert_array_equal(
            np.asarray(s).reshape(-1), np.asarray(s_ref).reshape(-1)
        )
        want_deq = _dequantize_blocks(q_ref, s_ref, block)
        np.testing.assert_array_equal(
            np.asarray(deq).reshape(-1), np.asarray(want_deq).reshape(-1)
        )

    @pytest.mark.parametrize("wire", ["int8", "fp8"])
    def test_zero_block_dequantizes_to_exact_zero(self, wire):
        """The _block_scale guard: an all-zero block must quantize→
        dequantize to exactly zero (divisor clamped to 1.0, never
        0/0)."""
        from horovod_tpu.ops.pallas_quant import _quant_math

        z = jnp.zeros((2, 128), jnp.float32)
        q, s, deq = jax.jit(lambda v: _quant_math(v, wire))(z)
        assert np.asarray(deq).max() == 0.0
        assert np.all(np.isfinite(np.asarray(s)))
        # phase backend agrees through the same guard
        qp, sp = _quantize_blocks(z.reshape(1, 256), wire, 128)
        np.testing.assert_array_equal(
            np.asarray(_dequantize_blocks(qp, sp, 128)),
            np.zeros((1, 256), np.float32),
        )

    def test_nonfinite_block_propagates_nan_scale(self):
        from horovod_tpu.ops.pallas_quant import _quant_math

        x = jnp.full((1, 128), jnp.inf, jnp.float32)
        _, s, deq = jax.jit(lambda v: _quant_math(v, "int8"))(x)
        assert np.isnan(np.asarray(s)).all()
        assert np.isnan(np.asarray(deq)).all()

    def test_block_scale_guard_values(self):
        scale, safe = _block_scale(jnp.asarray([0.0, 127.0, jnp.nan]),
                                   127.0)
        np.testing.assert_array_equal(np.asarray(safe)[:2], [1.0, 1.0])
        assert np.asarray(safe)[2] == 1.0
        assert np.isnan(np.asarray(scale)[2])
        assert np.asarray(scale)[0] == 1.0  # zero block: clamped once


# -------------------------------------------------- fused primitives


class TestFusedPrimitives:
    @pytest.mark.parametrize("wire", ["int8", "fp8"])
    def test_reduce_scatter_matches_phase_1e6(self, hvd_module, wire):
        rng = np.random.RandomState(1)
        x = rng.randn(N, 3000).astype(np.float32)

        def rs(backend):
            return np.asarray(_run(
                lambda v, _b=backend: quantized_reduce_scatter(
                    v[0], op=traced.Sum, wire=wire, backend=_b
                )[None], x,
            ))

        np.testing.assert_allclose(rs("phase"), rs("fused"),
                                   rtol=1e-6, atol=1e-6)

    def test_all_gather_bitwise_matches_phase(self, hvd_module):
        """No accumulation in the gather: fused == phase bit for bit
        for every input."""
        rng = np.random.RandomState(2)
        shard = rng.randn(N, 1024).astype(np.float32) * 10.0

        def ag(backend):
            return np.asarray(_run(
                lambda v, _b=backend: quantized_all_gather(
                    v[0], wire="int8", backend=_b
                )[None], shard,
            ))

        np.testing.assert_array_equal(ag("phase"), ag("fused"))

    def test_ef_residual_bitwise_matches_phase(self, hvd_module):
        """One quantization per contribution on both backends: the EF
        residual is computed from the same local grid and must be
        bitwise identical."""
        rng = np.random.RandomState(3)
        x = rng.randn(N, 2048).astype(np.float32)

        def rs_ef(backend):
            def body(v):
                m, r = quantized_reduce_scatter(
                    v[0], op=traced.Sum, ef=True, backend=backend
                )
                return m[None], r[None]

            return [np.asarray(o) for o in _run(body, x, n_out=2)]

        m_p, r_p = rs_ef("phase")
        m_f, r_f = rs_ef("fused")
        np.testing.assert_array_equal(r_p, r_f)
        np.testing.assert_allclose(m_p, m_f, rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("v", [65, 513, 4097])
    def test_odd_shapes_pad_like_phase(self, hvd_module, v):
        """Lengths that don't divide n*block: the fused chunk layout is
        the phase one (block-aligned pad), so results line up slot for
        slot."""
        rng = np.random.RandomState(4)
        x = rng.randn(N, v).astype(np.float32)
        ph = np.asarray(_run(
            lambda t: quantized_allreduce(
                t[0], op=traced.Average, wire="int8"
            )[None], x,
        ))
        fu = np.asarray(_run(
            lambda t: quantized_allreduce(
                t[0], op=traced.Average, wire="int8", backend="fused"
            )[None], x,
        ))
        np.testing.assert_allclose(ph, fu, rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("block", [64, 256])
    def test_block_size_sweep(self, hvd_module, block):
        rng = np.random.RandomState(5)
        x = rng.randn(N, 8 * block).astype(np.float32)
        ph = np.asarray(_run(
            lambda t: quantized_allreduce(
                t[0], op=traced.Sum, wire="int8", block=block
            )[None], x,
        ))
        fu = np.asarray(_run(
            lambda t: quantized_allreduce(
                t[0], op=traced.Sum, wire="int8", block=block,
                backend="fused",
            )[None], x,
        ))
        np.testing.assert_allclose(ph, fu, rtol=1e-6, atol=1e-6)

    def test_fused_counters_tick(self, hvd_module):
        from horovod_tpu import metrics

        before = metrics.get_counter("quant.fused_collectives")
        rng = np.random.RandomState(6)
        x = rng.randn(N, 600).astype(np.float32)
        _run(lambda t: quantized_allreduce(
            t[0], op=traced.Sum, backend="fused"
        )[None], x)
        assert metrics.get_counter("quant.fused_collectives") > before
        assert metrics.get_counter("quant.fused_bytes") > 0


# ------------------------------------------------- dispatch and knobs


class TestBackendDispatch:
    def test_knob_default_is_phase(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_QUANT_BACKEND", raising=False)
        assert quant_backend() == "phase"

    def test_knob_spellings(self, monkeypatch):
        for raw, want in [("fused", "fused"), ("PALLAS", "fused"),
                          ("ring", "fused"), ("phase", "phase"),
                          ("off", "phase"), ("xla", "phase")]:
            monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", raw)
            assert quant_backend() == want, raw

    def test_unknown_backend_rejected(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "warp")
        with pytest.raises(QuantizedWireError, match="QUANT_BACKEND"):
            quant_backend()

    def test_dispatch_interp_off_tpu(self):
        from horovod_tpu.ops.pallas_quant import dispatch_mode

        # the CPU mesh serves any axis/groups combination in interpret
        # mode — including the hierarchical DCN hop's cross-slice groups
        assert dispatch_mode(None, N) == "interp"
        assert dispatch_mode(((0, 1, 2, 3), (4, 5, 6, 7)), 4) == "interp"
        assert dispatch_mode(None, 1) is None  # degenerate ring

    def test_env_knob_reaches_primitives(self, hvd_module, monkeypatch):
        from horovod_tpu import metrics

        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "fused")
        before = metrics.get_counter("quant.fused_collectives")
        rng = np.random.RandomState(7)
        x = rng.randn(N, 700).astype(np.float32)
        _run(lambda t: quantized_allreduce(
            t[0], op=traced.Sum
        )[None], x)
        assert metrics.get_counter("quant.fused_collectives") > before

    def test_backend_in_store_fingerprint(self, monkeypatch):
        """fused vs phase winners must never collide in the tune DB —
        and 'unset' must equal an explicit 'phase'."""
        from horovod_tpu.sched.store import knob_fingerprint

        monkeypatch.delenv("HVD_TPU_QUANT_BACKEND", raising=False)
        unset = knob_fingerprint()
        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "phase")
        assert knob_fingerprint() == unset
        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "fused")
        assert knob_fingerprint() != unset

    def test_bucketed_zero1_composes_with_fused(self, hvd_module,
                                                monkeypatch):
        """ZeRO-1 composes unchanged: the per-bucket quantized RS and
        the post-update quantized AG dispatch through the backend knob
        — fused reaches the phase trajectory within the wire's own
        noise and the state structure (incl. EF residuals) is
        identical."""
        import optax

        from horovod_tpu import sched

        rng = np.random.RandomState(8)
        X = rng.randn(16, 6).astype(np.float32)
        Y = (X @ np.full((6, 2), 0.5)).astype(np.float32)
        params = {"w": jnp.full((6, 2), 0.3), "b": jnp.zeros((2,))}

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

        def run(backend):
            monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", backend)
            step = sched.bucketed_zero_step(
                loss_fn, optax.sgd(0.05),
                cfg=sched.SchedConfig(bucket_bytes=32, wire="int8"),
            )
            st = step.init(params)
            p = jax.tree.map(jnp.array, params)
            losses = []
            for _ in range(10):
                p, st, loss = step(p, st, (jnp.asarray(X),
                                           jnp.asarray(Y)))
                losses.append(float(loss))
            return losses, st

        ph, st_p = run("phase")
        fu, st_f = run("fused")
        np.testing.assert_allclose(ph, fu, rtol=1e-4, atol=1e-5)
        assert jax.tree.structure(st_p) == jax.tree.structure(st_f)

    def test_tuner_explores_and_freezes_backend(self, monkeypatch):
        """ScheduleTuner(explore_backend=True): one window per
        candidate, best score freezes and pins the env knob — the
        fused backend is a tuner-selectable dimension."""
        import horovod_tpu.sched.tune as tune_mod
        from horovod_tpu import metrics
        from horovod_tpu.sched.tune import ScheduleTuner

        # setenv (not delenv) so monkeypatch restores the pre-test
        # state even though the tuner itself mutates the knob
        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "phase")
        scores = {"phase": 50.0, "fused": 80.0}
        t = ScheduleTuner(explore_backend=True, store=None)
        seen = []
        for _ in range(2):
            b = t.backend()
            seen.append(b)
            monkeypatch.setattr(
                tune_mod, "window_score",
                lambda *_a, _b=b: scores[_b],
            )
            t.begin_window()
            assert os.environ["HVD_TPU_QUANT_BACKEND"] == b
            t.end_window()
        assert sorted(seen) == ["fused", "phase"]
        assert t.backend() == "fused"  # higher window score wins
        assert os.environ["HVD_TPU_QUANT_BACKEND"] == "fused"
        assert metrics.get_gauge(
            "sched.tune_backend_frozen", {"backend": "fused"}
        ) == 1.0

    def test_tuner_default_defers_backend_to_env(self, monkeypatch):
        from horovod_tpu.sched.tune import ScheduleTuner

        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "fused")
        t = ScheduleTuner(store=None)
        assert t.backend() == "fused"
        monkeypatch.delenv("HVD_TPU_QUANT_BACKEND")
        assert t.backend() == "phase"

    def test_store_roundtrips_backend(self, monkeypatch, tmp_path):
        """A converged fused winner warm-starts a later tuner with the
        backend pinned (and the knob fingerprint keys fused entries
        apart from phase ones)."""
        from horovod_tpu.sched.store import ScheduleStore, make_key
        from horovod_tpu.sched.tune import ScheduleTuner

        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "phase")
        store = ScheduleStore(str(tmp_path / "db.json"))
        key = make_key(("sig",))
        store.record(key, bucket_bytes=1 << 20, wire="int8",
                     lowering="flat", score=9.0,
                     meta={"backend": "fused"})
        t = ScheduleTuner(explore_backend=True, store=store,
                          store_key=key)
        assert t.backend() == "fused"
        assert os.environ.get("HVD_TPU_QUANT_BACKEND") == "fused"
        assert t.converged  # warm start: zero exploration windows

    def test_xir_lowering_gates_backend_per_op_class(self, monkeypatch):
        from horovod_tpu import xir

        monkeypatch.setenv("HVD_TPU_QUANT_BACKEND", "fused")
        red = xir.reduce_scatter(
            WORLD_AXIS, wire="int8", nbytes=4096, dtype="float32"
        )
        assert xir.lower.resolve_backend(
            red.replace(lowering="flat")
        ) == "fused"
        # shuffle ops never quantize, and even a hypothetical quantized
        # one pins the phase pipeline — there is no ring to fuse
        a2a = xir.ExchangeOp("all_to_all", WORLD_AXIS, wire="int8",
                             lowering="flat")
        assert xir.lower.resolve_backend(a2a) == "phase"
        dense = red.replace(wire="off")
        assert xir.lower.resolve_backend(dense) is None
        lowered = xir.lower_program(
            xir.program("dense_grad", [red]), axis_size=N, store=False
        )
        assert lowered.ops[0].attr("qbackend") == "fused"
