"""Launcher unit tests (reference analog: ``test/single/test_run.py`` —
host parsing, assignment math, CLI parsing with mocked exec)."""

import os
import textwrap

import pytest

from horovod_tpu.runner import hosts as hosts_mod
from horovod_tpu.runner import launch as launch_mod


def test_parse_hosts():
    hs = hosts_mod.parse_hosts("a:4,b,c:2")
    assert [(h.hostname, h.slots) for h in hs] == [("a", 4), ("b", 1), ("c", 2)]


def test_parse_host_files(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text(textwrap.dedent("""\
        # comment
        node1 slots=4
        node2 slots=2
        node3
    """))
    hs = hosts_mod.parse_host_files(str(f))
    assert [(h.hostname, h.slots) for h in hs] == [
        ("node1", 4), ("node2", 2), ("node3", 1)
    ]


def test_get_host_assignments():
    hs = hosts_mod.parse_hosts("a:2,b:2")
    slots = hosts_mod.get_host_assignments(hs, 4)
    assert [(s.hostname, s.rank, s.local_rank, s.cross_rank) for s in slots] == [
        ("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)
    ]
    assert all(s.size == 4 and s.cross_size == 2 for s in slots)
    assert slots[0].local_size == 2


def test_get_host_assignments_partial_last_host():
    hs = hosts_mod.parse_hosts("a:2,b:4")
    slots = hosts_mod.get_host_assignments(hs, 3)
    assert len(slots) == 3
    assert slots[2].hostname == "b" and slots[2].local_size == 1


def test_get_host_assignments_insufficient():
    with pytest.raises(ValueError, match="only 2 slot"):
        hosts_mod.get_host_assignments(hosts_mod.parse_hosts("a:2"), 4)


def test_parse_args_basic():
    args = launch_mod.parse_args(["-np", "4", "python", "train.py", "--lr", "1"])
    assert args.np == 4
    assert args.command == ["python", "train.py", "--lr", "1"]


def test_parse_args_knobs_to_env():
    args = launch_mod.parse_args([
        "-np", "2", "--fusion-threshold-mb", "32", "--timeline-filename",
        "/tmp/tl.json", "--autotune", "--log-level", "debug", "python", "x.py",
    ])
    env = launch_mod.env_from_args(args)
    assert env["HVD_TPU_FUSION_THRESHOLD"] == str(32 << 20)
    assert env["HVD_TPU_TIMELINE"] == "/tmp/tl.json"
    assert env["HVD_TPU_AUTOTUNE"] == "1"
    assert env["HVD_TPU_LOG_LEVEL"] == "debug"


def test_parse_args_requires_np_and_command():
    with pytest.raises(SystemExit):
        launch_mod.parse_args(["python", "x.py"])
    with pytest.raises(SystemExit):
        launch_mod.parse_args(["-np", "2"])


def test_py_controller_roundtrip():
    from horovod_tpu.runner import controller_py as cp

    srv = cp.PyControllerServer(secret="s3cret", world=2)
    try:
        c1 = cp.PyControllerClient("127.0.0.1", srv.port, "s3cret", 0)
        c2 = cp.PyControllerClient("127.0.0.1", srv.port, "s3cret", 1)
        c1.put("sc", "k", b"\x00binary\xff")
        assert c2.get("sc", "k", timeout_ms=1000) == b"\x00binary\xff"
        assert c2.get("sc", "nope", timeout_ms=50) is None
        import threading

        ok = [False, False]
        ts = [
            threading.Thread(
                target=lambda i=i, c=c: ok.__setitem__(
                    i, c.barrier("b0", 2, timeout_ms=3000)
                ),
            )
            for i, c in enumerate((c1, c2))
        ]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert all(ok)
        # auth failure
        evil = cp.PyControllerClient("127.0.0.1", srv.port, "wrong", 2)
        with pytest.raises(OSError):
            evil.put("sc", "k2", b"x")
        evil.close()
        c1.close()
        c2.close()
    finally:
        srv.stop()


def test_native_python_controller_interop():
    """The Python client must speak the native server's protocol and
    vice versa (same wire format + HMAC)."""
    from horovod_tpu import native
    from horovod_tpu.runner import controller_py as cp

    if not native.available():
        pytest.skip("native core not built")
    # native server <- python client
    srv = native.ControllerServer(secret="tok", world=1)
    try:
        pyc = cp.PyControllerClient("127.0.0.1", srv.port, "tok", 0)
        pyc.put("s", "k", b"value1")
        assert pyc.get("s", "k", timeout_ms=1000) == b"value1"
        pyc.close()
    finally:
        srv.stop()
    # python server <- native client
    pysrv = cp.PyControllerServer(secret="tok2", world=1)
    try:
        nc = native.ControllerClient("127.0.0.1", pysrv.port, "tok2", 0)
        nc.put("s", "k", b"value2")
        assert nc.get("s", "k", timeout_ms=1000) == b"value2"
        assert nc.barrier("bb", 1, timeout_ms=1000)
        nc.close()
    finally:
        pysrv.stop()


# ---- config file + check-build (reference launch.py:110, config_parser) ----

def test_config_parser_simple_yaml(tmp_path):
    from horovod_tpu.runner.config_parser import parse_config_file

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "params:\n"
        "  fusion_threshold_mb: 128   # comment\n"
        "timeline:\n"
        "  filename: /tmp/tl.json\n"
        "autotune:\n"
        "  enabled: true\n"
        "elastic:\n"
        "  min_np: 2\n"
    )
    parsed = parse_config_file(str(cfg))
    assert parsed["params"]["fusion_threshold_mb"] == 128
    assert parsed["timeline"]["filename"] == "/tmp/tl.json"
    assert parsed["autotune"]["enabled"] is True
    assert parsed["elastic"]["min_np"] == 2


def test_config_parser_json(tmp_path):
    from horovod_tpu.runner.config_parser import parse_config_file

    cfg = tmp_path / "cfg.json"
    cfg.write_text('{"params": {"fusion_threshold_mb": 64}}')
    assert parse_config_file(str(cfg))["params"]["fusion_threshold_mb"] == 64


def test_config_file_feeds_args_cli_wins(tmp_path):
    from horovod_tpu.runner.launch import env_from_args, parse_args

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "params:\n  fusion_threshold_mb: 128\nlogging:\n  level: debug\n"
    )
    args = parse_args([
        "-np", "2", "--config-file", str(cfg),
        "--fusion-threshold-mb", "32",  # CLI beats config
        "python", "train.py",
    ])
    env = env_from_args(args)
    assert env["HVD_TPU_FUSION_THRESHOLD"] == str(32 << 20)
    assert env["HVD_TPU_LOG_LEVEL"] == "debug"


def test_check_build_reports(capsys):
    from horovod_tpu.runner.launch import check_build

    check_build()
    out = capsys.readouterr().out
    assert "Available Frameworks" in out
    assert "[X] JAX" in out
    assert "native core" in out
    assert "Adasum" in out


def test_config_parser_hash_in_value(tmp_path):
    from horovod_tpu.runner.config_parser import parse_config_file

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "timeline:\n"
        "  filename: /data/run#3/tl.json\n"
        "params:\n"
        "  fusion_threshold_mb: 16  # trailing comment\n"
    )
    parsed = parse_config_file(str(cfg))
    assert parsed["timeline"]["filename"] == "/data/run#3/tl.json"
    assert parsed["params"]["fusion_threshold_mb"] == 16


def test_config_parser_apostrophe_in_value(tmp_path):
    from horovod_tpu.runner.config_parser import parse_config_file

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "timeline:\n"
        "  filename: user's tl.json  # note\n"
        "  quoted: '#literal'\n"
    )
    parsed = parse_config_file(str(cfg))
    assert parsed["timeline"]["filename"] == "user's tl.json"
    assert parsed["timeline"]["quoted"] == "#literal"


def test_elastic_driver_defaults_compilation_cache(monkeypatch, tmp_path):
    """_with_compilation_cache: job-scoped default, explicit dir wins,
    driver-env dir is copied for remote workers, opt-out respected."""
    from horovod_tpu.runner.elastic_driver import _with_compilation_cache

    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.delenv("HVD_TPU_NO_COMPILATION_CACHE", raising=False)

    env, created = _with_compilation_cache({})
    assert created is not None and "hvd_tpu_xla_cache_" in created
    assert env["JAX_COMPILATION_CACHE_DIR"] == created
    import shutil

    shutil.rmtree(created, ignore_errors=True)

    # explicit user dir wins, nothing created
    env, created = _with_compilation_cache(
        {"JAX_COMPILATION_CACHE_DIR": "/x"}
    )
    assert created is None and env["JAX_COMPILATION_CACHE_DIR"] == "/x"

    # driver-env dir is COPIED into the worker env (remote ssh workers
    # never inherit the driver environment), not merely skipped
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/driver/cache")
    env, created = _with_compilation_cache({})
    assert created is None
    assert env["JAX_COMPILATION_CACHE_DIR"] == "/driver/cache"
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")

    # opt-out respected
    monkeypatch.setenv("HVD_TPU_NO_COMPILATION_CACHE", "1")
    env, created = _with_compilation_cache({})
    assert created is None and "JAX_COMPILATION_CACHE_DIR" not in env


def test_elastic_timeout_env_knob(monkeypatch):
    """HVD_TPU_ELASTIC_TIMEOUT / HOROVOD_ELASTIC_TIMEOUT set the
    wait_for_available_slots deadline (reference ELASTIC_TIMEOUT_SECS)."""
    from horovod_tpu.runner.elastic_driver import ElasticDriver

    class NoSlots:
        def available_slots(self):
            return 0

        current_hosts = {}

    drv = ElasticDriver.__new__(ElasticDriver)
    drv.host_manager = NoSlots()
    import threading

    drv._shutdown = threading.Event()
    monkeypatch.setenv("HVD_TPU_ELASTIC_TIMEOUT", "0")
    import time

    t0 = time.monotonic()
    assert not drv.wait_for_available_slots(2)
    assert time.monotonic() - t0 < 2.0  # returned at the env deadline

    # fractional timeouts parse (get_float, not get_int)
    monkeypatch.setenv("HVD_TPU_ELASTIC_TIMEOUT", "0.5")
    t0 = time.monotonic()
    assert not drv.wait_for_available_slots(2)
    assert time.monotonic() - t0 < 3.0

    # zero timeout still succeeds when capacity is already there
    class HasSlots:
        def available_slots(self):
            return 4

        current_hosts = {}

    drv.host_manager = HasSlots()
    monkeypatch.setenv("HVD_TPU_ELASTIC_TIMEOUT", "0")
    assert drv.wait_for_available_slots(2)


class TestNicProbe:
    """Mutual-interface probe (reference driver_service _run_probe /
    task_service.py:383 recast, VERDICT r3 missing-7)."""

    def test_all_local_is_loopback(self):
        from horovod_tpu.runner import exec_utils

        assert exec_utils.probe_routable_addr(["localhost"]) == "127.0.0.1"

    def test_picks_mutually_reachable_candidate(self, monkeypatch):
        from horovod_tpu.runner import exec_utils

        monkeypatch.setattr(
            exec_utils, "_local_candidate_addrs",
            lambda remotes: ["10.0.0.5", "192.168.1.5"],
        )
        # hostA can only route the 192 interface; hostB routes both
        results = {"hostA": {"192.168.1.5"},
                   "hostB": {"10.0.0.5", "192.168.1.5"}}
        addr = exec_utils.probe_routable_addr(
            ["hostA", "hostB"], _dial=lambda h: results[h]
        )
        assert addr == "192.168.1.5"

    def test_falls_back_with_warning_when_no_common(self, monkeypatch):
        from horovod_tpu.runner import exec_utils
        from horovod_tpu.utils.logging import get_logger

        monkeypatch.setattr(
            exec_utils, "_local_candidate_addrs",
            lambda remotes: ["10.0.0.5"],
        )
        warned = []
        monkeypatch.setattr(
            get_logger(), "warning",
            lambda msg, *a, **k: warned.append(msg % a if a else msg),
        )
        heuristic = exec_utils.routable_addr(["hostA"])
        addr = exec_utils.probe_routable_addr(
            ["hostA"], _dial=lambda h: set()
        )
        assert addr == heuristic
        assert any("NIC probe" in m for m in warned), warned

    def test_echo_listener_end_to_end(self, monkeypatch):
        """A dialer that REALLY dials the probe's listener from this
        machine: the token echo handshake must validate the address."""
        import re
        import socket as _socket

        from horovod_tpu.runner import exec_utils

        monkeypatch.setattr(
            exec_utils, "_local_candidate_addrs",
            lambda remotes: ["127.0.0.1"],  # dial loopback for the test
        )
        seen = {}

        def real_dial(host):
            # grab the port/token from the enclosing probe via its
            # listener: emulate the remote script faithfully
            srv_port = seen["port"]
            token = seen["token"]
            ok = set()
            try:
                s = _socket.create_connection(("127.0.0.1", srv_port),
                                              timeout=3)
                s.sendall(token.encode() + b"\n")
                if s.recv(64).strip() == token.encode():
                    ok.add("127.0.0.1")
                s.close()
            except OSError:
                pass
            return ok

        orig_ssh_dial = exec_utils._ssh_dial

        # intercept the internals to learn port+token, then delegate to
        # the real local dial
        real_probe = exec_utils.probe_routable_addr

        def spy_dial_factory(h, addrs, port, token, *a):
            seen["port"] = port
            seen["token"] = token
            return real_dial(h)

        monkeypatch.setattr(exec_utils, "_ssh_dial", spy_dial_factory)
        addr = real_probe(["some-remote-host"])
        assert addr == "127.0.0.1"

    def test_disable_knob(self, monkeypatch):
        from horovod_tpu.runner import exec_utils

        monkeypatch.setenv("HVD_TPU_NIC_PROBE", "0")
        called = []
        monkeypatch.setattr(
            exec_utils, "_local_candidate_addrs",
            lambda remotes: called.append(1) or [],
        )
        exec_utils.probe_routable_addr(["hostX"])
        assert not called  # probe skipped entirely
