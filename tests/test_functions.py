"""broadcast/allgather helpers, SyncBatchNorm, metric averaging, elastic
state (reference analogs: torch/functions tests in test_torch.py,
sync batch norm tests, test_torch_elastic.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.elastic import ArrayState, ObjectState


def test_broadcast_parameters_single_process(hvd_module):
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    out = hvd.broadcast_parameters(params, root_rank=0)
    assert out is params  # single controller: identity


def test_broadcast_object_and_allgather_object(hvd_module):
    obj = {"epoch": 3, "name": "abc"}
    assert hvd.broadcast_object(obj) == obj
    assert hvd.allgather_object(obj) == [obj]


def test_metric_average_single_process(hvd_module):
    assert hvd.metric_average(0.5) == 0.5


def test_sync_batch_norm_module(hvd_module):
    """SyncBatchNorm inside the distributed step: moments averaged over
    the world axis -> identical to BN over the global batch."""
    import flax.linen as nn
    from jax.sharding import PartitionSpec as P

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(4)(x)
            x = hvd.SyncBatchNorm(use_running_average=not train)(x)
            return x

    model = Net()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 4), jnp.float32)
    # init in eval mode: the moments collective needs the mesh axis,
    # which only exists inside shard_map
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=False)
    params, stats = variables["params"], variables["batch_stats"]

    mesh = hvd.mesh()

    def fwd(p, s, xb):
        out, updated = model.apply(
            {"params": p, "batch_stats": s}, xb, train=True,
            mutable=["batch_stats"],
        )
        return out, updated["batch_stats"]

    f = jax.jit(
        jax.shard_map(
            fwd, mesh=mesh,
            in_specs=(P(), P(), P(hvd.WORLD_AXIS)),
            out_specs=(P(hvd.WORLD_AXIS), P()),
            check_vma=False,
        )
    )
    out_sharded, stats_sharded = f(params, stats, x)

    # single-device reference: identical net with a plain (unsynced)
    # BatchNorm over the full global batch — same leaf names
    # (scale/bias, mean/var), module key renamed across the trees
    class NetRef(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(4)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return x

    def renamed(tree):
        return {
            ("BatchNorm_0" if k == "SyncBatchNorm_0" else k): v
            for k, v in tree.items()
        }

    out_ref, updated_ref = NetRef().apply(
        {"params": renamed(params), "batch_stats": renamed(stats)}, x,
        train=True, mutable=["batch_stats"],
    )
    np.testing.assert_allclose(
        np.asarray(out_sharded), np.asarray(out_ref), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(stats_sharded["SyncBatchNorm_0"]["mean"]),
        np.asarray(updated_ref["batch_stats"]["BatchNorm_0"]["mean"]),
        rtol=1e-4, atol=1e-6,
    )


def test_object_state_commit_restore(hvd_module):
    state = ObjectState(epoch=0, batch=0)
    state.epoch = 5
    state.commit()
    state.epoch = 9
    state.restore()
    assert state.epoch == 5


def test_array_state_save_restore(hvd_module):
    params = {"w": jnp.ones((2, 2))}
    state = ArrayState(params=params, epoch=1)
    state.params = jax.tree.map(lambda a: a * 3, state.params)
    state.commit()
    state.params = jax.tree.map(lambda a: a * 7, state.params)
    state.restore()
    np.testing.assert_allclose(np.asarray(state.params["w"]), 3.0)
    assert state.epoch == 1


def test_elastic_run_retry_loop(hvd_module):
    """HorovodInternalError restores committed state and retries
    (reference elastic.py:151 run_fn)."""
    from horovod_tpu.elastic.run import run_fn

    calls = {"n": 0}
    state = ObjectState(step=0)

    def train(st):
        calls["n"] += 1
        if calls["n"] == 1:
            st.step = 99  # uncommitted progress, lost on failure
            raise hvd.HorovodInternalError("simulated peer failure")
        return st.step

    resets = {"n": 0}
    wrapped = run_fn(train, lambda: resets.__setitem__("n", resets["n"] + 1))
    result = wrapped(state)
    assert result == 0  # restored to committed value
    assert calls["n"] == 2 and resets["n"] == 1


def test_elastic_hosts_updated_continues(hvd_module):
    from horovod_tpu.elastic.run import run_fn

    calls = {"n": 0}
    state = ObjectState(step=0)

    def train(st):
        calls["n"] += 1
        if calls["n"] == 1:
            st.step = 42  # live progress survives a host update
            raise hvd.HostsUpdatedInterrupt()
        return st.step

    wrapped = run_fn(train, lambda: None)
    assert wrapped(state) == 42
    assert calls["n"] == 2


def test_broadcast_optimizer_state_and_variables_aliases(hvd_module):
    """broadcast_variables / broadcast_optimizer_state mirror the
    reference surfaces (tensorflow/functions.py:276,
    torch/functions.py:118) over optax pytrees."""
    import optax

    params = {"w": jnp.ones((4, 2))}
    tx = optax.adam(1e-3)
    state = tx.init(params)
    # single-controller broadcast: result equals input, full structure
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    assert jax.tree.structure(out) == jax.tree.structure(state)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    v = hvd.broadcast_variables({"w": jnp.full((3,), 7.0)}, root_rank=0)
    np.testing.assert_allclose(np.asarray(v["w"]), 7.0)


class TestChunkedBroadcast:
    """Size-boundary contract (VERDICT r3 item 6): large payloads ride
    chunked flat-buffer device broadcasts, small ones the single-call
    path; array data never pickles on the large path."""

    @staticmethod
    def _spy(monkeypatch):
        from jax.experimental import multihost_utils

        calls = []

        def fake_bcast(x, is_source):
            calls.append(x)
            return x

        monkeypatch.setattr(
            multihost_utils, "broadcast_one_to_all", fake_bcast
        )
        return calls

    def test_small_tree_single_call(self, hvd_module, monkeypatch):
        from horovod_tpu import functions
        from horovod_tpu.runtime import get_runtime

        calls = self._spy(monkeypatch)
        monkeypatch.setattr(get_runtime(), "process_count", 2)
        params = {"w": np.ones((4, 4), np.float32)}
        out = functions.broadcast_parameters(params, root_rank=0)
        # plan header + whole tree in one call
        assert len(calls) == 2
        np.testing.assert_allclose(out["w"], params["w"])

    def test_large_tree_chunks_and_never_pickles(self, hvd_module,
                                                 monkeypatch):
        from horovod_tpu import functions
        from horovod_tpu.runtime import get_runtime

        calls = self._spy(monkeypatch)
        monkeypatch.setattr(get_runtime(), "process_count", 2)
        monkeypatch.setenv("HVD_TPU_BCAST_PICKLE_THRESHOLD", "1024")
        monkeypatch.setenv("HVD_TPU_BCAST_CHUNK_BYTES", "65536")

        def no_pickle(*a, **k):
            raise AssertionError("array payload must not pickle")

        monkeypatch.setattr(functions.pickle, "dumps", no_pickle)
        params = {
            "w": np.arange(40_000, dtype=np.float32).reshape(200, 200),
            "b": np.ones((7,), np.int32),
        }
        out = functions.broadcast_parameters(params, root_rank=0)
        # plan header + 160_000 B f32 at 65536 B chunks -> 3, + 1 i32 chunk
        assert len(calls) == 5, [np.asarray(c).nbytes for c in calls]
        assert all(np.asarray(c).ndim == 1 for c in calls)
        np.testing.assert_allclose(out["w"], params["w"])
        np.testing.assert_allclose(out["b"], params["b"])

    def test_wide_dtypes_stay_bit_exact_via_pickle(self, hvd_module,
                                                   monkeypatch):
        """64-bit leaves must NOT ride the device path (x64-disabled JAX
        would truncate them in flight); they pickle bit-exactly."""
        from horovod_tpu import functions
        from horovod_tpu.runtime import get_runtime

        calls = self._spy(monkeypatch)
        monkeypatch.setattr(get_runtime(), "process_count", 2)
        monkeypatch.setenv("HVD_TPU_BCAST_PICKLE_THRESHOLD", "1024")
        big = np.array([2**40 + 3, -(2**35)], np.int64)
        params = {
            "w": np.arange(64_000, dtype=np.float32),
            "wide": big,
            "dbl": np.array([1.0 + 2**-40], np.float64),
        }
        out = functions.broadcast_parameters(params, root_rank=0)
        assert out["wide"].dtype == np.int64
        np.testing.assert_array_equal(out["wide"], big)
        assert out["dbl"].dtype == np.float64
        assert out["dbl"][0] == params["dbl"][0]  # bit-exact
        np.testing.assert_allclose(out["w"], params["w"])
        # wide leaves went via pickled broadcast_object (u8 buffers),
        # never as raw 64-bit device arrays
        for c in calls:
            leaves = np.asarray(c) if not isinstance(c, dict) else None
            if leaves is not None and leaves.dtype.itemsize > 4:
                # the only allowed 8-byte items are tiny int64 metadata
                # headers (plan negotiation / broadcast_object length),
                # never array payload
                assert leaves.dtype == np.int64 and leaves.size <= 3, (
                    leaves.dtype, leaves.shape,
                )

    def test_large_object_buffer_chunks(self, hvd_module, monkeypatch):
        from horovod_tpu import functions
        from horovod_tpu.runtime import get_runtime

        calls = self._spy(monkeypatch)
        monkeypatch.setattr(get_runtime(), "process_count", 2)
        monkeypatch.setenv("HVD_TPU_BCAST_PICKLE_THRESHOLD", "1024")
        monkeypatch.setenv("HVD_TPU_BCAST_CHUNK_BYTES", "65536")
        blob = {"x": b"q" * 200_000}
        out = functions.broadcast_object(blob, root_rank=0)
        assert out == blob
        # 1 length call + ceil(~200k/65536)=4 buffer chunks
        assert len(calls) == 5, [np.asarray(c).size for c in calls]


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_chunked_broadcast_parameters():
    """Two real processes: a large (above-threshold) pytree must reach
    rank 1 bit-correct through the chunked device path, 64-bit leaves
    through the pickle path."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import functions

        hvd.init()
        rng = np.random.RandomState(0)  # same seed: root value known
        big = rng.randn(300_000).astype(np.float32)   # 1.2 MB > 1 MB
        wide = np.array([2**40 + 7, -(2**33)], np.int64)
        if hvd.process_rank() == 0:
            params = {"big": big, "wide": wide}
        else:
            params = {"big": np.zeros_like(big),
                      "wide": np.zeros_like(wide)}
        out = functions.broadcast_parameters(params, root_rank=0)
        ok_big = bool(np.allclose(np.asarray(out["big"]), big))
        ok_wide = bool((np.asarray(out["wide"]) == wide).all())
        return [ok_big, ok_wide]

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    assert results == [[True, True], [True, True]], results
