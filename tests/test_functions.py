"""broadcast/allgather helpers, SyncBatchNorm, metric averaging, elastic
state (reference analogs: torch/functions tests in test_torch.py,
sync batch norm tests, test_torch_elastic.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.elastic import ArrayState, ObjectState


def test_broadcast_parameters_single_process(hvd_module):
    params = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    out = hvd.broadcast_parameters(params, root_rank=0)
    assert out is params  # single controller: identity


def test_broadcast_object_and_allgather_object(hvd_module):
    obj = {"epoch": 3, "name": "abc"}
    assert hvd.broadcast_object(obj) == obj
    assert hvd.allgather_object(obj) == [obj]


def test_metric_average_single_process(hvd_module):
    assert hvd.metric_average(0.5) == 0.5


def test_sync_batch_norm_module(hvd_module):
    """SyncBatchNorm inside the distributed step: moments averaged over
    the world axis -> identical to BN over the global batch."""
    import flax.linen as nn
    from jax.sharding import PartitionSpec as P

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(4)(x)
            x = hvd.SyncBatchNorm(use_running_average=not train)(x)
            return x

    model = Net()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 4), jnp.float32)
    # init in eval mode: the moments collective needs the mesh axis,
    # which only exists inside shard_map
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=False)
    params, stats = variables["params"], variables["batch_stats"]

    mesh = hvd.mesh()

    def fwd(p, s, xb):
        out, updated = model.apply(
            {"params": p, "batch_stats": s}, xb, train=True,
            mutable=["batch_stats"],
        )
        return out, updated["batch_stats"]

    f = jax.jit(
        jax.shard_map(
            fwd, mesh=mesh,
            in_specs=(P(), P(), P(hvd.WORLD_AXIS)),
            out_specs=(P(hvd.WORLD_AXIS), P()),
            check_vma=False,
        )
    )
    out_sharded, stats_sharded = f(params, stats, x)

    # single-device reference: identical net with a plain (unsynced)
    # BatchNorm over the full global batch — same leaf names
    # (scale/bias, mean/var), module key renamed across the trees
    class NetRef(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(4)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return x

    def renamed(tree):
        return {
            ("BatchNorm_0" if k == "SyncBatchNorm_0" else k): v
            for k, v in tree.items()
        }

    out_ref, updated_ref = NetRef().apply(
        {"params": renamed(params), "batch_stats": renamed(stats)}, x,
        train=True, mutable=["batch_stats"],
    )
    np.testing.assert_allclose(
        np.asarray(out_sharded), np.asarray(out_ref), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(stats_sharded["SyncBatchNorm_0"]["mean"]),
        np.asarray(updated_ref["batch_stats"]["BatchNorm_0"]["mean"]),
        rtol=1e-4, atol=1e-6,
    )


def test_object_state_commit_restore(hvd_module):
    state = ObjectState(epoch=0, batch=0)
    state.epoch = 5
    state.commit()
    state.epoch = 9
    state.restore()
    assert state.epoch == 5


def test_array_state_save_restore(hvd_module):
    params = {"w": jnp.ones((2, 2))}
    state = ArrayState(params=params, epoch=1)
    state.params = jax.tree.map(lambda a: a * 3, state.params)
    state.commit()
    state.params = jax.tree.map(lambda a: a * 7, state.params)
    state.restore()
    np.testing.assert_allclose(np.asarray(state.params["w"]), 3.0)
    assert state.epoch == 1


def test_elastic_run_retry_loop(hvd_module):
    """HorovodInternalError restores committed state and retries
    (reference elastic.py:151 run_fn)."""
    from horovod_tpu.elastic.run import run_fn

    calls = {"n": 0}
    state = ObjectState(step=0)

    def train(st):
        calls["n"] += 1
        if calls["n"] == 1:
            st.step = 99  # uncommitted progress, lost on failure
            raise hvd.HorovodInternalError("simulated peer failure")
        return st.step

    resets = {"n": 0}
    wrapped = run_fn(train, lambda: resets.__setitem__("n", resets["n"] + 1))
    result = wrapped(state)
    assert result == 0  # restored to committed value
    assert calls["n"] == 2 and resets["n"] == 1


def test_elastic_hosts_updated_continues(hvd_module):
    from horovod_tpu.elastic.run import run_fn

    calls = {"n": 0}
    state = ObjectState(step=0)

    def train(st):
        calls["n"] += 1
        if calls["n"] == 1:
            st.step = 42  # live progress survives a host update
            raise hvd.HostsUpdatedInterrupt()
        return st.step

    wrapped = run_fn(train, lambda: None)
    assert wrapped(state) == 42
    assert calls["n"] == 2


def test_broadcast_optimizer_state_and_variables_aliases(hvd_module):
    """broadcast_variables / broadcast_optimizer_state mirror the
    reference surfaces (tensorflow/functions.py:276,
    torch/functions.py:118) over optax pytrees."""
    import optax

    params = {"w": jnp.ones((4, 2))}
    tx = optax.adam(1e-3)
    state = tx.init(params)
    # single-controller broadcast: result equals input, full structure
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    assert jax.tree.structure(out) == jax.tree.structure(state)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    v = hvd.broadcast_variables({"w": jnp.full((3,), 7.0)}, root_rank=0)
    np.testing.assert_allclose(np.asarray(v["w"]), 7.0)
