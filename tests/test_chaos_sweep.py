"""Chaos sweep: every registered fault site fires once through REAL
code, and nothing wedges.

The fault registry (``horovod_tpu/faults.py``) documents its sites in
a docstring table; each PR that adds a site also adds the code path
that honors it — but nothing before this sweep guaranteed the whole
table stays *live*.  This module pins that: the site list is parsed
from the table itself (plus the ``remesh.<phase>`` expansion), every
site has a scenario that arms a plan and drives the real code path to
it, and a site without a scenario FAILS the coverage test — a new
fault site cannot land without its chaos scenario.

Each scenario asserts the three sweep invariants:

* the armed fault actually fired (``faults.injected.<site>.<kind>``);
* the run completed — degraded, aborted cleanly, or retried through,
  but never wedged (every scenario returns within its own timeout);
* the degradation surface fired (fallback/retry/abort counters or the
  exception the abort contract names).

The multi-process version of the same sweep — a two-tenant 4-process
train loop under a fault plan — is ``tools/tier1_slo_smoke.sh``; this
in-process half runs in the default tier so the registry cannot rot
between smoke runs.
"""

import re
import sys
import time

import numpy as np
import pytest

import horovod_tpu.faults as faults_mod
from horovod_tpu import faults, metrics
from horovod_tpu.exceptions import FaultInjected
from horovod_tpu.utils.retry import RetryPolicy

pytestmark = [pytest.mark.slo, pytest.mark.faults]


@pytest.fixture(autouse=True)
def _sweep_isolation():
    faults.set_plan(None)
    metrics.reset_counters("faults.")
    metrics.reset_counters("svc.")
    metrics.reset_counters("slo.")
    yield
    faults.set_plan(None)


def registered_sites():
    """Ground truth: the docstring table rows (every site is dotted),
    with ``remesh.<phase>`` expanded to the real phase list."""
    from horovod_tpu.elastic import remesh

    rows = re.findall(r"^``([a-z_]+\.[a-z_.<>]+)``",
                      faults_mod.__doc__, re.M)
    sites = set()
    for site in rows:
        if site.startswith("faults."):
            continue  # the counter-name row, not a site
        if site == "remesh.<phase>":
            sites.update(f"remesh.{p}" for p in remesh.PHASES)
        else:
            sites.add(site)
    return sorted(sites)


def _fired(site, kind):
    n = metrics.get_counter(f"faults.injected.{site}.{kind}")
    assert n >= 1, f"armed fault at {site} never fired ({kind})"


# ------------------------------------------------------- scenarios

def _noop_sleep(_s):
    return None


def scenario_discovery_script(tmp_path):
    faults.set_plan("discovery.script:error:nth=1")
    from horovod_tpu.elastic.discovery import HostDiscoveryScript

    disc = HostDiscoveryScript(
        "echo hostA:2",
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                          sleep=_noop_sleep, name="discovery"),
    )
    assert disc.find_available_hosts_and_slots() == {"hostA": 2}
    _fired("discovery.script", "error")
    assert metrics.get_counter("retry.discovery.retries") >= 1


def scenario_discovery_resize(tmp_path):
    faults.set_plan("discovery.resize:resize_to:np=3,nth=1")
    from horovod_tpu.elastic.discovery import FixedHosts, HostManager

    mgr = HostManager(FixedHosts({"a": 2, "b": 2}))
    mgr.update_available_hosts()
    assert mgr.available_slots() == 3
    _fired("discovery.resize", "resize_to")


def scenario_driver_spawn(tmp_path):
    # One real (degenerate) round: the first spawn attempt faults, the
    # spawn RetryPolicy absorbs it, the worker runs and exits 0.
    faults.set_plan("driver.spawn:error:nth=1")
    from horovod_tpu.elastic.discovery import FixedHosts, HostManager
    from horovod_tpu.runner.elastic_driver import ElasticDriver

    driver = ElasticDriver(
        HostManager(FixedHosts({"localhost": 1})), min_np=1,
        cooldown_s=0.05,
        spawn_retry=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                                sleep=_noop_sleep,
                                name="elastic.spawn"),
    )
    driver.start_discovery()
    try:
        rc = driver.run_rounds([sys.executable, "-c", "pass"])
    finally:
        driver.stop()
    assert rc == 0
    _fired("driver.spawn", "error")
    assert metrics.get_counter("retry.elastic.spawn.retries") >= 1


def _worker_manager(monkeypatch, plan):
    from horovod_tpu.runner import controller_py as cp
    from horovod_tpu.runner.elastic_worker import (
        WorkerNotificationManager,
    )

    srv = cp.PyControllerServer(secret="s3cret", world=1)
    monkeypatch.setenv("HVD_TPU_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HVD_TPU_RENDEZVOUS_PORT", str(srv.port))
    monkeypatch.setenv("HVD_TPU_SECRET", "s3cret")
    faults.set_plan(plan)
    return srv, WorkerNotificationManager()


def scenario_worker_connect(tmp_path, monkeypatch):
    srv, mgr = _worker_manager(
        monkeypatch, "worker.connect:error:nth=1"
    )
    try:
        mgr.init()  # first dial faults, the connect retry absorbs it
        assert mgr._client is not None
    finally:
        mgr.close()
        srv.stop()
    _fired("worker.connect", "error")
    assert metrics.get_counter("retry.worker.connect.retries") >= 1


def scenario_worker_heartbeat(tmp_path, monkeypatch):
    # A slow fault inside the heartbeat tick: the beat delays but the
    # thread survives and keeps beating (the straggler stand-in).
    srv, mgr = _worker_manager(
        monkeypatch, "worker.heartbeat:slow:secs=0.01"
    )
    try:
        mgr.init()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if metrics.get_counter(
                    "faults.injected.worker.heartbeat.slow"):
                break
            time.sleep(0.05)
    finally:
        mgr.close()
        srv.stop()
    _fired("worker.heartbeat", "slow")


def scenario_worker_commit(tmp_path):
    faults.set_plan("worker.commit:error:nth=1")
    from horovod_tpu.elastic.state import ObjectState

    state = ObjectState(epoch=0)
    with pytest.raises(FaultInjected):
        state.commit()
    state.commit()  # the run continues past the injected boundary
    _fired("worker.commit", "error")


def scenario_checkpoint_write(tmp_path):
    import horovod_tpu as hvd

    path = str(tmp_path / "ckpt")
    hvd.save_checkpoint(path, {"epoch": 1}, step=1, use_orbax=False)
    faults.set_plan("checkpoint.write:corrupt:nth=1")
    hvd.save_checkpoint(path, {"epoch": 2}, step=2, use_orbax=False)
    faults.set_plan(None)
    # degraded, not wedged: restore falls back to the last good step
    assert hvd.latest_good_step(path) == 1
    state, step = hvd.restore_or_init(path, {"epoch": 0})
    assert (state["epoch"], step) == (1, 1)
    _fired("checkpoint.write", "corrupt")
    assert metrics.get_counter("checkpoint.corrupt_detected") >= 1


def _scenario_remesh_phase(phase):
    def run(tmp_path):
        faults.set_plan(f"remesh.{phase}:error:nth=1")
        from horovod_tpu.elastic import remesh

        # the abort contract: a faulted phase raises out of the
        # instrumented block (the driver catches and falls back to the
        # respawn path) — and the next pass through is clean
        with pytest.raises(FaultInjected):
            with remesh.remesh_phase(phase, remesh_id="chaos"):
                pass
        with remesh.remesh_phase(phase, remesh_id="chaos"):
            pass
        _fired(f"remesh.{phase}", "error")
        assert metrics.get_counter(f"remesh.phase.{phase}") >= 1
    return run


def _svc_submit_one():
    import jax.numpy as jnp

    from horovod_tpu import svc, xir
    from horovod_tpu.runtime import WORLD_AXIS

    prog = xir.program("test", [
        xir.all_reduce(WORLD_AXIS, reduce="mean", bucket=0, nbytes=32,
                       dtype="float32"),
    ])
    s = svc.get_service()
    x = jnp.ones((8, 1), jnp.float32)
    out = s.submit(prog, [x], producer="chaos").result(timeout=60)[0]
    np.testing.assert_allclose(np.asarray(out), 1.0)
    return s


def scenario_svc_submit(tmp_path, hvd_module):
    faults.set_plan("svc.submit:error:nth=1")
    s = _svc_submit_one()
    assert s.dead
    _fired("svc.submit", "error")
    assert metrics.get_counter("svc.fallback_sync") >= 1


def scenario_svc_admit(tmp_path, hvd_module):
    faults.set_plan("svc.admit:error:nth=1")
    s = _svc_submit_one()
    assert s.dead
    _fired("svc.admit", "error")
    assert metrics.get_counter("svc.fallback_sync") >= 1


def scenario_svc_loop(tmp_path, hvd_module):
    faults.set_plan("svc.loop:error:nth=1")
    s = _svc_submit_one()
    assert s.dead
    _fired("svc.loop", "error")


def scenario_svc_drain(tmp_path, hvd_module):
    faults.set_plan("svc.drain:error:nth=1")
    from horovod_tpu import svc

    s = svc.get_service()
    assert s.drain(timeout_s=5) is False
    assert s.dead
    faults.set_plan(None)
    s2 = _svc_submit_one()  # post-death submissions resolve inline
    assert s2.dead
    _fired("svc.drain", "error")


def scenario_topo_dcn_phase(tmp_path, hvd_module):
    import jax.numpy as jnp

    from horovod_tpu.topo import hierarchical

    faults.set_plan("topo.dcn_phase:slow:secs=0.01")
    with hierarchical._dcn_trace("rs_dcn", jnp.ones(8), "dense"):
        pass
    _fired("topo.dcn_phase", "slow")


def _remediator(store=None):
    from horovod_tpu.elastic.remediate import Remediator

    calls = store if store is not None else []
    return Remediator(
        placement={"jobA": 1, "jobB": 3},
        actuators={
            "handoff": lambda o, n, b: calls.append("handoff"),
            "rollback": lambda o, n, b: calls.append("rollback"),
        },
        cooldown_s_=0.0, retry_attempts=2, retry_timeout_s=5.0,
        sleep=_noop_sleep,
    )


def scenario_remediate_plan(tmp_path):
    faults.set_plan("remediate.plan:error:nth=1")
    r = _remediator()
    rec = r.remediate({"tenant": "jobA", "kind": "step"}, "handoff")
    assert rec["outcome"] == "abort" and rec["stable"] is True
    assert r.placement() == {"jobA": 1, "jobB": 3}  # nothing changed
    _fired("remediate.plan", "error")
    assert metrics.get_counter("slo.remediation_abort") == 1


def scenario_remediate_handoff(tmp_path):
    faults.set_plan("remediate.handoff:error:times=0")
    calls = []
    r = _remediator(calls)
    rec = r.remediate({"tenant": "jobA", "kind": "step"}, "handoff")
    assert rec["outcome"] == "abort" and rec["stable"] is True
    assert r.placement() == {"jobA": 1, "jobB": 3}  # rolled back
    assert "rollback" in calls
    _fired("remediate.handoff", "error")
    assert metrics.get_counter("slo.rollbacks") == 1


def scenario_remediate_rollback(tmp_path):
    faults.set_plan(
        "remediate.handoff:error:times=0;"
        "remediate.rollback:error:times=0"
    )
    r = _remediator()
    rec = r.remediate({"tenant": "jobA", "kind": "step"}, "handoff")
    assert rec["outcome"] == "abort" and rec["stable"] is False
    _fired("remediate.rollback", "error")
    assert metrics.get_counter("slo.remediation_unstable") == 1


SCENARIOS = {
    "discovery.script": scenario_discovery_script,
    "discovery.resize": scenario_discovery_resize,
    "driver.spawn": scenario_driver_spawn,
    "worker.connect": scenario_worker_connect,
    "worker.heartbeat": scenario_worker_heartbeat,
    "worker.commit": scenario_worker_commit,
    "checkpoint.write": scenario_checkpoint_write,
    "svc.submit": scenario_svc_submit,
    "svc.admit": scenario_svc_admit,
    "svc.drain": scenario_svc_drain,
    "svc.loop": scenario_svc_loop,
    "topo.dcn_phase": scenario_topo_dcn_phase,
    "remediate.plan": scenario_remediate_plan,
    "remediate.handoff": scenario_remediate_handoff,
    "remediate.rollback": scenario_remediate_rollback,
}
SCENARIOS.update({
    f"remesh.{p}": _scenario_remesh_phase(p)
    for p in ("pause", "snapshot", "publish", "barrier", "reinit",
              "fetch", "rebuild")
})


def test_every_registered_site_has_a_scenario():
    """A fault site without a chaos scenario cannot land: the docstring
    table and this sweep move together."""
    assert set(SCENARIOS) == set(registered_sites())


@pytest.mark.parametrize("site", sorted(SCENARIOS))
def test_site_fires_and_nothing_wedges(site, tmp_path, monkeypatch,
                                       request):
    scenario = SCENARIOS[site]
    kwargs = {}
    code = scenario.__code__
    if "monkeypatch" in code.co_varnames[:code.co_argcount]:
        kwargs["monkeypatch"] = monkeypatch
    if "hvd_module" in code.co_varnames[:code.co_argcount]:
        kwargs["hvd_module"] = request.getfixturevalue("hvd_module")
        from horovod_tpu import svc

        svc.reset_service()
        request.addfinalizer(svc.reset_service)
    scenario(tmp_path, **kwargs)
