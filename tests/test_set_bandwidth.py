"""Arbitrary process-set bandwidth paths: member-only rings/trees
instead of masked whole-world collectives (reference behavior anchor:
per-set communicators touch only members, process_set.h:26-80)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import traced
from horovod_tpu.runtime import WORLD_AXIS

N = 8


@pytest.fixture(autouse=True)
def _init(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    monkeypatch.setenv("HVD_TPU_SET_RING_THRESHOLD", "0")  # force rings
    hvd.init()
    yield
    hvd.shutdown()


def _mesh():
    from horovod_tpu.runtime import get_runtime

    return get_runtime().mesh


def _collective_lines(hlo):
    return [
        l for l in hlo.splitlines()
        if re.search(r"= \S+ (all-reduce|all-gather|all-to-all)\(", l)
    ]


class TestRingAllreduce:
    @pytest.mark.parametrize("members", [[0, 1, 2], [1, 3, 4, 6, 7], [2, 5]])
    def test_matches_masked_sum(self, members):
        ps = hvd.add_process_set(members)
        x = np.random.RandomState(len(members)).randn(N, 4096).astype(
            np.float32
        )
        y = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps))
        expect = x[members].sum(axis=0)
        for r in members:
            np.testing.assert_allclose(y[r], expect, rtol=1e-4, atol=1e-5)
        others = [r for r in range(N) if r not in members]
        np.testing.assert_allclose(y[others], x[others])
        hvd.remove_process_set(ps)

    def test_no_world_allreduce_in_hlo(self):
        """VERDICT item 6 gate: a 3-of-8 set's allreduce must not lower
        to a whole-world psum over the payload."""
        ps = hvd.add_process_set([0, 1, 2])
        V = 4096

        def body(x):
            return traced.allreduce(x[0], op=traced.Sum, process_set=ps)[None]

        hlo = jax.jit(
            shard_map(body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
                      out_specs=P(WORLD_AXIS), check_vma=False)
        ).lower(jnp.zeros((N, V), jnp.float32)).compile().as_text()
        for line in _collective_lines(hlo):
            assert str(V) not in line, f"payload-sized world collective: {line}"
        assert "collective-permute" in hlo
        hvd.remove_process_set(ps)


class TestTreeBroadcast:
    @pytest.mark.parametrize("members,root", [([0, 2, 4, 6, 7], 3),
                                              ([1, 5, 6], 0)])
    def test_matches_reference(self, members, root):
        ps = hvd.add_process_set(members)
        x = np.random.RandomState(0).randn(N, 4096).astype(np.float32)
        y = np.asarray(hvd.broadcast(x, root_rank=root, process_set=ps))
        expect = x[members[root]]
        for r in members:
            np.testing.assert_allclose(y[r], expect)
        others = [r for r in range(N) if r not in members]
        np.testing.assert_allclose(y[others], x[others])
        hvd.remove_process_set(ps)


class TestRingAllgather:
    def test_matches_concat(self):
        members = [0, 3, 5]
        ps = hvd.add_process_set(members)
        x = np.random.RandomState(1).randn(N, 2, 2048).astype(np.float32)
        y = np.asarray(hvd.allgather(x, process_set=ps))
        expect = np.concatenate([x[r] for r in members], axis=0)
        for r in members:
            np.testing.assert_allclose(y[r], expect)
        # documented contract: non-members receive zeros
        others = [r for r in range(N) if r not in members]
        np.testing.assert_array_equal(y[others], 0.0)
        hvd.remove_process_set(ps)


class TestSubsetAlltoall:
    def test_equal_split_arbitrary_set(self):
        members = [0, 2, 7]
        ps = hvd.add_process_set(members)
        k = len(members)
        x = np.random.RandomState(2).randn(N, k, 512).astype(np.float32)
        y = np.asarray(hvd.alltoall(x, process_set=ps))
        # member at position p's output row j = member j's chunk p
        for p, r in enumerate(members):
            for j, rj in enumerate(members):
                np.testing.assert_allclose(y[r, j], x[rj, p])
        hvd.remove_process_set(ps)

    def test_uneven_splits_subset(self):
        members = [1, 4, 6]
        ps = hvd.add_process_set(members)
        k = len(members)
        splits = np.array([[1, 2, 1], [2, 1, 1], [0, 3, 1]])
        d0 = 4
        x = np.random.RandomState(3).randn(N, d0, 8).astype(np.float32)
        out, recv = hvd.alltoall(x, splits=splits, process_set=ps)
        out, recv = np.asarray(out), np.asarray(recv)
        max_chunk = int(splits.max())
        offs = np.concatenate(
            [np.zeros((k, 1), np.int64), np.cumsum(splits, axis=1)], axis=1
        )
        for p, r in enumerate(members):
            np.testing.assert_array_equal(recv[r], splits.T[p])
            for j, rj in enumerate(members):
                c = int(splits[j, p])  # member j sends c rows to member p
                # output row-block j holds member j's chunk for p
                got = out[r, j * max_chunk : j * max_chunk + c]
                want = x[rj, offs[j, p] : offs[j, p] + c]
                np.testing.assert_allclose(got, want)
        # non-member recv counts are zero
        others = [r for r in range(N) if r not in members]
        assert (recv[others] == 0).all()
        hvd.remove_process_set(ps)


class TestOverlappingSets:
    def test_two_overlapping_sets_allreduce(self):
        """Sets sharing rank 2 both reduce correctly (the masked and
        ring lowerings are per-set pure functions, so overlap is
        naturally supported — the reference needs disjoint
        communicators per set but allows overlapping membership)."""
        ps_a = hvd.add_process_set([0, 1, 2])
        ps_b = hvd.add_process_set([2, 3, 4, 5])
        x = np.random.RandomState(0).randn(N, 2048).astype(np.float32)
        ya = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps_a))
        yb = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps_b))
        np.testing.assert_allclose(
            ya[2], x[[0, 1, 2]].sum(0), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            yb[2], x[[2, 3, 4, 5]].sum(0), rtol=1e-4, atol=1e-5
        )
        # rank 6 is in neither set: passthrough both times
        np.testing.assert_allclose(ya[6], x[6])
        np.testing.assert_allclose(yb[6], x[6])
        hvd.remove_process_set(ps_a)
        hvd.remove_process_set(ps_b)
