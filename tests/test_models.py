"""Model zoo smoke tests (tiny shapes; CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.models import MnistCNN, MnistMLP, ResNet


def test_mnist_cnn_shapes(hvd_module):
    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 28, 28, 1)))
    out = model.apply(params, jnp.zeros((4, 28, 28, 1)))
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32


def test_mnist_end_to_end_loss_decreases(hvd_module):
    model = MnistMLP(hidden=32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    params = hvd.broadcast_parameters(params)
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))

    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    step = hvd.distributed_train_step(loss_fn, tx)
    st = step.init(params)
    rng = np.random.RandomState(0)
    X = rng.rand(256, 28, 28, 1).astype(np.float32)
    Y = (X.mean(axis=(1, 2, 3)) * 1000).astype(np.int32) % 10
    losses = []
    for i in range(20):
        idx = rng.choice(256, 64)
        params, st, loss = step(params, st, (jnp.asarray(X[idx]), jnp.asarray(Y[idx])))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_tiny_resnet_stateful_training(hvd_module):
    """A 2-stage mini ResNet with BatchNorm trains through the stateful
    step and batch_stats update."""
    model = ResNet(stage_sizes=[1, 1], num_classes=4, num_filters=8,
                   dtype=jnp.float32)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True
    )
    params, stats = variables["params"], variables["batch_stats"]
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))

    def loss_fn(p, s, batch):
        x, y = batch
        logits, updated = model.apply(
            {"params": p, "batch_stats": s}, x, train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        return loss, updated["batch_stats"]

    step = hvd.distributed_train_step(loss_fn, tx, stateful=True)
    st = step.init(params)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(16, 32, 32, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 16), jnp.int32)
    stats0 = jax.tree.map(lambda a: np.asarray(a).copy(), stats)
    params, stats, st, loss = step(params, stats, st, (x, y))
    assert np.isfinite(float(loss))
    # batch_stats actually updated
    changed = jax.tree.map(
        lambda a, b: not np.allclose(np.asarray(a), b), stats, stats0
    )
    assert any(jax.tree.leaves(changed))


@pytest.mark.slow
def test_vgg16_forward_and_param_count(hvd_module):
    from horovod_tpu.models import VGG16

    model = VGG16(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    params = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(params, x, train=False)
    assert logits.shape == (2, 10)
    n_conv_stages = len({k for k in params["params"] if k.startswith("conv")})
    assert n_conv_stages == 13  # VGG-16 = 13 convs + 3 FC


@pytest.mark.slow
def test_inception_v3_forward(hvd_module):
    from horovod_tpu.models import InceptionV3

    model = InceptionV3(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((1, 96, 96, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 10)
    assert "batch_stats" in variables


def test_resnet_sync_bn_matches_global_batch_norm(hvd_module):
    """sync_bn=True: BN moments are the GLOBAL batch's (cross-replica
    sync), so the sharded forward equals the unsharded forward."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import ResNet

    model = ResNet(stage_sizes=[1], num_classes=4, num_filters=8,
                   dtype=jnp.float32, sync_bn=True)
    x = jnp.asarray(
        np.random.RandomState(0).rand(16, 8, 8, 3), jnp.float32
    )
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)

    def fwd(v, xb):
        out, _ = model.apply(v, xb, train=True, mutable=["batch_stats"])
        return out

    sharded = jax.jit(shard_map(
        fwd, mesh=hvd.mesh(), in_specs=(P(), P(hvd.WORLD_AXIS)),
        out_specs=P(hvd.WORLD_AXIS), check_vma=False,
    ))(variables, x)
    # single-device reference: same model over the whole batch — the
    # local moments ARE the global moments there
    dense = fwd(variables, x)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(dense), rtol=2e-3, atol=2e-3
    )


class TestSpaceToDepthStem:
    """The MLPerf-TPU stem fold: conv7x7/2(pad 3) == s2d(2) + conv4x4/1
    with the zero-extended, block-folded kernel (models/resnet.py)."""

    def test_exact_equivalence_to_conv7(self, hvd_module):
        import jax
        from flax import linen as nn

        from horovod_tpu.models.resnet import space_to_depth

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
        w7 = jnp.asarray(rng.randn(7, 7, 3, 16) * 0.1, jnp.float32)

        ref = jax.lax.conv_general_dilated(
            x, w7, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

        # fold: zero-extend 7->8, then K4[kh,kw, ph*2C+pw*C+c, f]
        #     = W8[2kh+ph, 2kw+pw, c, f]
        w8 = jnp.pad(w7, ((0, 1), (0, 1), (0, 0), (0, 0)))
        c = 3
        w4 = np.zeros((4, 4, 4 * c, 16), np.float32)
        for kh in range(4):
            for kw in range(4):
                for ph in range(2):
                    for pw in range(2):
                        w4[kh, kw, (ph * 2 + pw) * c:(ph * 2 + pw + 1) * c] = \
                            w8[2 * kh + ph, 2 * kw + pw]
        xp = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
        xs = space_to_depth(xp, 2)
        out = jax.lax.conv_general_dilated(
            xs, jnp.asarray(w4), window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        assert out.shape == ref.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_resnet_s2d_stem_trains(self, hvd_module):
        import jax
        import optax

        from horovod_tpu.models import ResNet

        model = ResNet(stage_sizes=[1, 1], num_classes=4, num_filters=8,
                       dtype=jnp.float32, stem="space_to_depth")
        x = jnp.asarray(np.random.RandomState(0).rand(8, 32, 32, 3),
                        jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        logits, _ = model.apply(variables, x, train=True,
                                mutable=["batch_stats"])
        assert logits.shape == (8, 4)
        # same spatial pipeline as the conv7 stem
        conv7 = ResNet(stage_sizes=[1, 1], num_classes=4, num_filters=8,
                       dtype=jnp.float32)
        v7 = conv7.init(jax.random.PRNGKey(0), x, train=True)
        l7, _ = conv7.apply(v7, x, train=True, mutable=["batch_stats"])
        assert l7.shape == logits.shape
