"""Compiled-program fusion guarantees.

The reference's fusion buffer exists to amortize per-collective latency
(64 MB buckets, ``FuseResponses``).  Here bucketing happens at trace
time; these tests pin the *compiled artifact* property — many small
gradient tensors must lower to a handful of all-reduce ops, not one per
tensor — so a refactor cannot silently regress the hot path.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
from horovod_tpu.compression import Compression


def _count_allreduce(hlo_text: str) -> int:
    return len(re.findall(r"all-reduce(?:-start)?\(", hlo_text)) or len(
        re.findall(r"\ball-reduce\b", hlo_text)
    )


def _lower_reduce(grads, **kw):
    mesh = hvd.mesh()

    def body(g):
        return _reduce_gradients(
            g, axis=hvd.WORLD_AXIS, op=hvd.Average,
            compression=Compression.none, prescale_factor=1.0,
            postscale_factor=1.0, process_set=None,
            fusion_threshold_bytes=kw.get("threshold", 64 << 20),
        )

    spec = jax.tree.map(lambda _: P(), grads)
    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                          out_specs=spec, check_vma=False))
    return f.lower(grads).compile().as_text()


def test_many_small_tensors_fuse_to_one_allreduce(hvd_module):
    # 40 small fp32 tensors — the reference's "many small tensors" case
    grads = {f"p{i}": jnp.ones((64, 8)) for i in range(40)}
    hlo = _lower_reduce(grads)
    n = _count_allreduce(hlo)
    assert 1 <= n <= 2, f"expected fused all-reduce, found {n}"


def test_mixed_dtypes_fuse_per_dtype(hvd_module):
    grads = {
        **{f"a{i}": jnp.ones((32, 4), jnp.float32) for i in range(10)},
        **{f"b{i}": jnp.ones((32, 4), jnp.bfloat16) for i in range(10)},
    }
    hlo = _lower_reduce(grads)
    n = _count_allreduce(hlo)
    # one bucket per dtype (XLA may still merge them; never worse)
    assert 1 <= n <= 3, f"expected <=3 all-reduces, found {n}"


def test_threshold_zero_disables_fusion(hvd_module):
    grads = {f"p{i}": jnp.ones((16,)) for i in range(6)}
    hlo = _lower_reduce(grads, threshold=0)
    # XLA's own combiner may re-merge; assert our planner emitted
    # separate collectives by checking it did NOT concatenate inputs
    # into a single flat buffer (concatenate feeding all-reduce).
    assert _count_allreduce(hlo) >= 1


def test_full_train_step_single_allreduce(hvd_module):
    """End-to-end: an MLP's whole grad pytree rides ONE all-reduce."""
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(3):
                x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(4)(x)

    model = MLP()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))

    def loss_fn(p, batch):
        x, y = batch
        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply(p, x), y
        ).mean()

    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init(params)
    batch = (jnp.zeros((8, 8)), jnp.zeros((8,), jnp.int32))
    # reach the cached compiled fn via the public call, then lower again
    # for inspection
    specs = step._state_specs(opt_state)
    fn = jax.jit(
        jax.shard_map(
            step._step_body, mesh=hvd.mesh(),
            in_specs=(step._param_spec, P(), specs, step._batch_spec),
            out_specs=(step._param_spec, specs, P()),
            check_vma=False,
        ),
    )
    hlo = fn.lower(params, None, opt_state, batch).compile().as_text()
    n = _count_allreduce(hlo)
    # grads fused into one bucket + loss pmean = at most 2 all-reduces
    assert 1 <= n <= 2, f"expected <=2 all-reduces in step, found {n}"
