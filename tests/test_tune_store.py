"""Persistent schedule autotuning: the ScheduleStore (key derivation,
keep-best merge, corruption tolerance, stale invalidation), the
ScheduleTuner warm-start path (converged at window 0 on a hit, zero
exploration windows, write-back on a miss), the /schedules fleet
endpoint, the driver/worker KV seeding hooks, and the bench probe-cache
knob fingerprint."""

import json
import urllib.request

import pytest

from horovod_tpu import metrics, sched
from horovod_tpu.sched.store import (
    ScheduleStore,
    knob_fingerprint,
    make_key,
)

pytestmark = [pytest.mark.tune, pytest.mark.sched]

SIG = ("allreduce", (((0, 1), 4096, ("float32",), False, "off", "flat"),))


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    metrics.reset_counters("sched.tune")
    metrics.reset_counters("train.")
    monkeypatch.delenv("HVD_TPU_TUNE_DB", raising=False)
    yield
    metrics.reset_counters("sched.tune")
    metrics.reset_counters("train.")


def _drive_to_convergence(tuner, windows=8):
    """Feed synthetic registry windows until the tuner converges."""
    for _ in range(windows):
        if tuner.converged:
            break
        tuner.begin_window()
        metrics.inc_counter("train.steps", 10)
        metrics.observe("train.step_seconds", 0.5)
        metrics.set_gauge("sched.bytes_per_step", 1000.0)
        tuner.end_window()
    return tuner


# ------------------------------------------------------------- store

class TestScheduleStore:
    def test_record_lookup_roundtrip(self, tmp_path):
        db = tmp_path / "tune.json"
        store = ScheduleStore(str(db))
        key = make_key(SIG)
        store.record(key, bucket_bytes=1 << 20, wire="int8",
                     lowering="flat", score=7.0)
        # a fresh store instance reads the persisted entry
        entry = ScheduleStore(str(db)).lookup(key)
        assert entry["bucket_bytes"] == 1 << 20
        assert entry["wire"] == "int8"
        assert entry["lowering"] == "flat"
        assert entry["score"] == 7.0
        # on-disk schema carries version + provenance
        data = json.loads(db.read_text())
        assert data["version"] == 1
        assert data["entries"][key]["jax"]

    def test_key_covers_all_identity_components(self, monkeypatch):
        base = make_key(SIG)
        assert make_key(SIG) == base  # deterministic
        assert make_key(("other",)) != base
        assert make_key(SIG, topo_spec="2x4(4)") != base
        assert make_key(SIG, jaxver="9.9.9") != base
        monkeypatch.setenv("HVD_TPU_SCHED_WIRE", "fp8")
        assert make_key(SIG) != base  # knob fingerprint changed

    def test_knob_fingerprint_tracks_sched_wire_topo_quant(
        self, monkeypatch
    ):
        base = knob_fingerprint()
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        f1 = knob_fingerprint()
        assert f1 != base
        monkeypatch.setenv("HOROVOD_QUANT_BLOCK", "128")
        assert knob_fingerprint() != f1
        # unrelated env never moves the fingerprint
        monkeypatch.setenv("HVD_TPU_ELASTIC", "1")
        monkeypatch.setenv("SOME_RANDOM_VAR", "x")
        assert knob_fingerprint() == knob_fingerprint()
        monkeypatch.delenv("HOROVOD_QUANT_BLOCK")
        assert knob_fingerprint() == f1

    def test_merge_keeps_best_score(self, tmp_path):
        store = ScheduleStore(str(tmp_path / "db.json"))
        key = "k" * 64
        store.record(key, bucket_bytes=100, wire="off", lowering="flat",
                     score=5.0)
        n = store.merge({key: {"bucket_bytes": 200, "wire": "bf16",
                               "lowering": "flat", "score": 9.0}})
        assert n == 1
        assert store.lookup(key)["bucket_bytes"] == 200
        # a worse entry never clobbers the stored winner
        n = store.merge({key: {"bucket_bytes": 300, "wire": "off",
                               "lowering": "flat", "score": 1.0}})
        assert n == 0
        assert store.lookup(key)["bucket_bytes"] == 200

    def test_merge_rejects_malformed_entries(self, tmp_path):
        store = ScheduleStore(str(tmp_path / "db.json"))
        assert store.merge({"k": {"score": 1.0}}) == 0  # missing fields
        assert store.merge("not a dict") == 0
        assert store.entries() == {}

    def test_corrupted_db_ignored_with_one_warning(self, tmp_path):
        from horovod_tpu.sched import store as store_mod

        db = tmp_path / "garbage.json"
        db.write_text("{definitely not json")
        s1 = ScheduleStore(str(db))
        s2 = ScheduleStore(str(db))
        assert s1.entries() == {} and s2.entries() == {}
        # log-once: the path registers in the warned set exactly once
        # (the horovod_tpu logger does not propagate, so the guard set
        # is the observable), while every load attempt still counts
        assert str(db) in store_mod._warned_paths
        assert metrics.get_counter("sched.tune.db_corrupt") >= 2
        # and a later record() rewrites the file cleanly
        s1.record("a" * 64, bucket_bytes=1, wire="off", lowering="flat",
                  score=1.0)
        assert json.loads(db.read_text())["version"] == 1

    def test_wrong_shape_json_ignored(self, tmp_path):
        db = tmp_path / "shape.json"
        db.write_text(json.dumps({"entries": [1, 2, 3]}))
        assert ScheduleStore(str(db)).entries() == {}
        db.write_text(json.dumps(
            {"entries": {"k": {"bucket_bytes": 1, "wire": "off",
                               "lowering": "flat"},
                         "bad": "not-an-object"}}
        ))
        assert list(ScheduleStore(str(db)).entries()) == ["k"]

    def test_stale_entry_invalidated_by_cost_model(self, tmp_path):
        from horovod_tpu import topo
        from horovod_tpu.topo.model import Topology

        topo.reset()
        topo.set_topology_override(Topology(num_slices=2, slice_size=4))
        try:
            store = ScheduleStore(str(tmp_path / "db.json"),
                                  stale_factor=4.0)
            key = "s" * 64
            store.record(key, bucket_bytes=1 << 20, wire="off",
                         lowering="hier", score=3.0)
            assert store.lookup(key) is not None
            # fake a recorded price 100x off today's model
            entry = store.entries()[key]
            entry["pred_cost_s"] = entry["pred_cost_s"] * 100.0
            store.merge({key: dict(entry, score=entry["score"] + 1)})
            assert store.lookup(key) is None
            assert metrics.get_counter("sched.tune.db_stale") == 1
        finally:
            topo.reset()

    def test_in_memory_store_without_path(self):
        store = ScheduleStore(None)
        store.record("m" * 64, bucket_bytes=7, wire="off",
                     lowering="flat", score=1.0)
        assert store.lookup("m" * 64)["bucket_bytes"] == 7


# ------------------------------------------------------ tuner warm start

class TestTunerWarmStart:
    def test_cold_then_warm(self, tmp_path, monkeypatch):
        db = tmp_path / "tune.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        # run 1 (cold): explores, converges, writes back
        t1 = sched.ScheduleTuner(warmup_windows=2, store="env",
                                 store_key=SIG)
        assert not t1.converged
        assert metrics.get_counter("sched.tune.db_miss") == 1
        _drive_to_convergence(t1)
        assert t1.converged
        assert metrics.get_counter("sched.tune.db_store") == 1
        assert db.exists()

        # run 2 (warm): converged at window 0, zero exploration windows
        metrics.reset_counters("sched.tune")
        t2 = sched.ScheduleTuner(warmup_windows=2, store="env",
                                 store_key=SIG)
        assert t2.converged  # window 0
        assert metrics.get_counter("sched.tune.db_hit") == 1
        assert t2.tuner._windows == 0  # no exploration ever ran
        assert t2.bucket_bytes() == t1.bucket_bytes()
        assert t2.wire() == t1.wire()
        assert t2.lowering() == t1.lowering()
        # warm windows score but never re-write the DB
        _drive_to_convergence(t2, windows=1)
        assert metrics.get_counter("sched.tune.db_store") == 0

    def test_warm_start_applies_stored_schedule(self, tmp_path,
                                                monkeypatch):
        db = tmp_path / "tune.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        store = ScheduleStore(str(db))
        key = make_key(SIG)
        store.record(key, bucket_bytes=512, wire="off", lowering="flat",
                     score=42.0)
        tuner = sched.ScheduleTuner(explore_wire=True, store="env",
                                    store_key=SIG)
        assert tuner.converged
        schedule = sched.build_schedule([256, 256, 512],
                                        ["float32"] * 3)
        stamped = tuner.apply(schedule)
        assert all(b.wire == "off" for b in stamped.buckets)
        assert all(b.lowering == "flat" for b in stamped.buckets)

    def test_corrupted_db_never_crashes_tuner(self, tmp_path,
                                              monkeypatch):
        db = tmp_path / "tune.json"
        db.write_text("\x00\x01 garbage \xff")
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        tuner = sched.ScheduleTuner(warmup_windows=2, store="env",
                                    store_key=SIG)
        assert not tuner.converged  # treated as a miss
        _drive_to_convergence(tuner)
        assert tuner.converged
        # convergence rewrote the DB into a valid file
        assert json.loads(db.read_text())["version"] == 1

    def test_no_db_env_means_no_store(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_TUNE_DB", raising=False)
        tuner = sched.ScheduleTuner(warmup_windows=2, store="env",
                                    store_key=SIG)
        assert tuner._store is None
        _drive_to_convergence(tuner)
        assert tuner.converged
        assert metrics.get_counter("sched.tune.db_store") == 0
        assert metrics.get_counter("sched.tune.db_hit") == 0
        assert metrics.get_counter("sched.tune.db_miss") == 0

    def test_unknown_stored_values_degrade_safely(self, tmp_path):
        store = ScheduleStore(str(tmp_path / "db.json"))
        key = make_key(SIG)
        store.record(key, bucket_bytes=4096, wire="exotic-wire",
                     lowering="exotic-lowering", score=1.0)
        tuner = sched.ScheduleTuner(store=store, store_key=SIG)
        assert tuner.converged
        assert tuner.wire() == "off"
        assert tuner.lowering() == "auto"


# -------------------------------------------------- /schedules endpoint

class TestSchedulesEndpoint:
    def _server(self, store):
        from horovod_tpu.runner.telemetry_http import TelemetryServer

        return TelemetryServer(port=0, bind_host="127.0.0.1",
                               schedule_store=store)

    def test_get_and_post(self, tmp_path):
        store = ScheduleStore(str(tmp_path / "db.json"))
        key = "a" * 64
        store.record(key, bucket_bytes=1 << 18, wire="bf16",
                     lowering="flat", score=3.0)
        srv = self._server(store)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            got = json.load(urllib.request.urlopen(f"{base}/schedules"))
            assert got["entries"][key]["wire"] == "bf16"
            got = json.load(urllib.request.urlopen(
                f"{base}/schedules?key={key}"
            ))
            assert list(got["entries"]) == [key]
            got = json.load(urllib.request.urlopen(
                f"{base}/schedules?key={'f' * 64}"
            ))
            assert got["entries"] == {}
            # POST merges keep-best
            body = json.dumps({"entries": {
                "b" * 64: {"bucket_bytes": 64, "wire": "off",
                           "lowering": "flat", "score": 1.0},
            }}).encode()
            req = urllib.request.Request(
                f"{base}/schedules", data=body, method="POST"
            )
            assert json.load(urllib.request.urlopen(req))["merged"] == 1
            assert "b" * 64 in store.entries()
        finally:
            srv.stop()

    def test_no_store_404s(self):
        srv = self._server(None)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/schedules"
                )
            assert exc.value.code == 404
        finally:
            srv.stop()

    def test_bad_post_is_400_and_survives(self, tmp_path):
        store = ScheduleStore(str(tmp_path / "db.json"))
        srv = self._server(store)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            req = urllib.request.Request(
                f"{base}/schedules", data=b"not json", method="POST"
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req)
            assert exc.value.code == 400
            # server is still alive
            assert json.load(urllib.request.urlopen(
                f"{base}/schedules"
            )) == {"entries": {}}
        finally:
            srv.stop()


# ------------------------------------------------ KV seeding plumbing

class _FakeControl:
    """Dict-backed stand-in for the rendezvous KV client."""

    def __init__(self):
        self.kv = {}

    def put(self, scope, key, blob):
        self.kv[(scope, key)] = blob

    def get(self, scope, key, timeout_ms=0):
        return self.kv.get((scope, key))


class TestKVSeeding:
    def test_driver_publish_and_collect(self, tmp_path, monkeypatch):
        from horovod_tpu.elastic.discovery import FixedHosts, HostManager
        from horovod_tpu.runner.elastic_driver import ElasticDriver
        from horovod_tpu.runner.hosts import SlotInfo

        monkeypatch.setenv("HVD_TPU_TUNE_DB",
                           str(tmp_path / "driver.json"))
        driver = ElasticDriver(
            HostManager(FixedHosts({"localhost": 1})), min_np=1
        )
        driver.schedule_store().record(
            "d" * 64, bucket_bytes=1 << 16, wire="off", lowering="flat",
            score=2.0,
        )
        control = _FakeControl()
        driver._publish_schedules(control)
        published = json.loads(control.kv[("__schedules__", "db")])
        assert "d" * 64 in published["entries"]

        # a worker push at round end folds into the driver store
        driver._last_assignments = [
            SlotInfo(hostname="localhost", rank=0, local_rank=0,
                     cross_rank=0, local_size=1, cross_size=1, size=1)
        ]
        control.put("__schedules__", "rank_0", json.dumps({"entries": {
            "w" * 64: {"bucket_bytes": 1 << 22, "wire": "int8",
                       "lowering": "flat", "score": 9.0},
        }}).encode())
        driver._collect_schedules(control)
        assert "w" * 64 in driver.schedule_store().entries()
        assert metrics.get_counter("sched.tune.db_collected") == 1

    def test_worker_fetch_seeds_local_db(self, tmp_path, monkeypatch):
        from horovod_tpu.runner.elastic_worker import (
            WorkerNotificationManager,
        )

        local = tmp_path / "worker.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(local))
        mgr = WorkerNotificationManager()
        mgr._client = _FakeControl()
        mgr._client.put("__schedules__", "db", json.dumps({"entries": {
            "f" * 64: {"bucket_bytes": 1 << 20, "wire": "bf16",
                       "lowering": "flat", "score": 4.0},
        }}).encode())
        mgr._fetch_schedules()
        assert metrics.get_counter("sched.tune.kv_seeded") == 1
        assert "f" * 64 in ScheduleStore(str(local)).entries()
        # ...and the heartbeat-side push mirrors a local change back
        mgr._push_schedules(mgr._client)
        pushed = json.loads(mgr._client.kv[("__schedules__", "rank_0")])
        assert "f" * 64 in pushed["entries"]

    def test_worker_fetch_without_db_is_noop(self, monkeypatch):
        from horovod_tpu.runner.elastic_worker import (
            WorkerNotificationManager,
        )

        monkeypatch.delenv("HVD_TPU_TUNE_DB", raising=False)
        mgr = WorkerNotificationManager()
        mgr._client = _FakeControl()
        mgr._fetch_schedules()  # must not raise
        mgr._push_schedules(mgr._client)
        assert mgr._client.kv == {}


# ----------------------------------------------- bench probe cache key

class TestBenchProbeCacheKey:
    def test_knob_fingerprint_in_key(self, monkeypatch):
        import bench

        base = bench._probe_cache_key()
        monkeypatch.setenv("HVD_TPU_SCHED_WIRE", "int8")
        k1 = bench._probe_cache_key()
        assert k1 != base
        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        k2 = bench._probe_cache_key()
        assert k2 != k1
        monkeypatch.setenv("HOROVOD_WIRE_X", "1")
        assert bench._probe_cache_key() != k2
        # unrelated env does not churn the cache
        monkeypatch.setenv("HVD_BENCH_SWEEP", "0")
        assert bench._probe_cache_key() == bench._probe_cache_key()

    def test_cache_roundtrip(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setenv("HVD_BENCH_PROBE_CACHE",
                           str(tmp_path / "probe.json"))
        assert not bench._probe_cached_ok()
        bench._probe_cache_store()
        assert bench._probe_cached_ok()
        # a knob change invalidates the cached probe
        monkeypatch.setenv("HVD_TPU_SCHED_MODE", "reduce_scatter")
        assert not bench._probe_cached_ok()
