"""Unified exchange IR (xir/): plan→lower→execute pipeline tests.

The parity contract under test: an IR-routed exchange on the dense
wire emits the identical collective its direct-``lax`` predecessor
did, so ``HVD_TPU_XIR`` on/off is bitwise-invisible — for the dense
DP scheduler (PR 7 equivalence), MoE dispatch/combine, Ulysses flips,
the sparse embedding exchange, pipeline ppermute, and FSDP RS+AG.
Plus: lowering-pass resolution against the topology cost model, wire
eligibility gating per op class, byte accounting by network class,
workload-kind keying in the persistent store, and the observability
surface (kind-labeled gauges, XIR counters, timeline lanes).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import metrics, sched, xir
from horovod_tpu.exceptions import HorovodTpuError
from horovod_tpu.runtime import WORLD_AXIS

pytestmark = pytest.mark.xir

N = 8


@pytest.fixture(autouse=True)
def _clean_overrides():
    yield
    xir.set_enabled_override(None)
    sched.set_config_override(None)


def _shard_run(fn, *args, mesh=None, n_out=1):
    mesh = mesh or hvd.mesh()
    spec = P(WORLD_AXIS)
    out_specs = spec if n_out == 1 else (spec,) * n_out
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(spec,) * len(args),
        out_specs=out_specs, check_vma=False,
    ))(*args)


class TestIrConstruction:
    def test_op_set_and_validation(self):
        with pytest.raises(HorovodTpuError, match="unknown exchange op"):
            xir.ExchangeOp("broadcast", "hvd")
        with pytest.raises(HorovodTpuError, match="unknown wire"):
            xir.ExchangeOp("all_reduce", "hvd", wire="fp4")
        with pytest.raises(HorovodTpuError, match="unknown lowering"):
            xir.ExchangeOp("all_reduce", "hvd", lowering="ring")

    def test_signature_deterministic_and_kind_sensitive(self):
        def build(kind):
            return xir.program(kind, [
                xir.all_to_all("ep", split_axis=0, concat_axis=1,
                               nbytes=1024, dtype="float32"),
            ])

        assert build("moe").signature() == build("moe").signature()
        assert build("moe").signature() != build("ulysses").signature()

    def test_attrs_hashable_and_accessible(self):
        op = xir.permute("pp", [(0, 1), (1, 0)], nbytes=64,
                         dtype="float32")
        assert op.attr("perm") == ((0, 1), (1, 0))
        hash(op.signature())  # must not raise

    def test_from_schedule_one_op_per_bucket(self):
        schedule = sched.build_schedule(
            [256, 256, 256], ["float32"] * 3,
            sched.SchedConfig(bucket_bytes=256),
        )
        prog = xir.from_schedule(schedule, kind="dense_grad")
        assert len(prog) == len(schedule)
        assert prog.kind == "dense_grad"
        for op, b in zip(prog.ops, schedule.buckets):
            assert op.op == "all_reduce"
            assert op.wire == b.wire
            assert op.lowering == b.lowering
            assert op.attr("nbytes") == b.nbytes
        rs = sched.build_schedule(
            [256], ["float32"],
            sched.SchedConfig(bucket_bytes=256, mode="reduce_scatter"),
        )
        rs_prog = xir.from_schedule(rs)
        assert rs_prog.ops[0].op == "reduce_scatter"
        assert rs_prog.ops[0].attr("paired_all_gather") is True


class TestEligibility:
    def test_reduce_ops_keep_quantized_wire(self):
        for op in xir.REDUCE_OPS:
            assert xir.eligible_wire(op, "int8", "float32") == "int8"
            assert xir.eligible_wire(op, "fp8", "float32") == "fp8"

    def test_shuffle_ops_cap_at_bf16(self):
        for op in ("all_to_all", "permute", "gather_dense_from_sparse"):
            assert xir.eligible_wire(op, "int8", "float32") == "off"
            assert xir.eligible_wire(op, "fp8", "float32") == "off"
            assert xir.eligible_wire(op, "bf16", "float32") == "bf16"

    def test_non_floating_always_dense(self):
        assert xir.eligible_wire("all_to_all", "bf16", "int32") == "off"
        assert xir.eligible_wire("all_reduce", "int8", "int32") == "off"

    def test_bf16_payload_needs_no_cast(self):
        assert xir.eligible_wire("all_to_all", "bf16", "bfloat16") == "off"


class TestLowering:
    def test_single_slice_resolves_flat(self, hvd_module):
        prog = xir.program("dense_grad", [
            xir.all_reduce(WORLD_AXIS, nbytes=1 << 24, dtype="float32"),
        ])
        lowered = xir.lower_program(prog, store=False)
        assert lowered.ops[0].lowering == "flat"
        assert lowered.lowered

    def test_two_slice_large_bucket_goes_hier(self, hvd_module,
                                              monkeypatch):
        from horovod_tpu import topo

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        try:
            prog = xir.program("dense_grad", [
                xir.all_reduce(WORLD_AXIS, nbytes=1 << 26,
                               dtype="float32"),
                xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=1,
                               nbytes=1 << 26, dtype="float32"),
            ])
            lowered = xir.lower_program(prog, axis_size=8, store=False)
            assert lowered.ops[0].lowering == "hier"
            # shuffle ops never stage hierarchically
            assert lowered.ops[1].lowering == "flat"
        finally:
            topo.reset()

    def test_explicit_groups_stay_flat(self, hvd_module, monkeypatch):
        from horovod_tpu import topo

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        try:
            prog = xir.program("dense_grad", [
                xir.all_reduce(WORLD_AXIS, nbytes=1 << 26,
                               dtype="float32",
                               groups=[[0, 1, 2, 3], [4, 5, 6, 7]]),
            ])
            lowered = xir.lower_program(prog, axis_size=8, store=False)
            assert lowered.ops[0].lowering == "flat"
        finally:
            topo.reset()


class TestByteAccounting:
    def test_alltoall_split_single_slice(self, hvd_module):
        op = xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=1,
                            nbytes=8000, dtype="float32")
        by = xir.op_network_bytes(op, axis_size=8)
        # single slice: everything is ICI, (n-1)/n of the buffer moves
        assert by["dcn"] == 0
        assert by["ici"] == int(8000 * 7 / 8)

    def test_alltoall_split_two_slice(self, hvd_module, monkeypatch):
        from horovod_tpu import topo

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        try:
            op = xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=1,
                                nbytes=8000, dtype="float32")
            by = xir.op_network_bytes(op, axis_size=8)
            # k-1=3 same-slice peers, n-k=4 cross-slice peers
            assert by["ici"] == int(8000 * 3 / 8)
            assert by["dcn"] == int(8000 * 4 / 8)
        finally:
            topo.reset()

    def test_permute_dcn_share_from_perm(self, hvd_module, monkeypatch):
        from horovod_tpu import topo

        monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
        topo.reset()
        try:
            ring = [(j, (j + 1) % 8) for j in range(8)]
            op = xir.permute(WORLD_AXIS, ring, nbytes=8000,
                             dtype="float32")
            by = xir.op_network_bytes(op, axis_size=8)
            # exactly 2 of the 8 hops cross the slice boundary
            assert by["dcn"] == int(8000 * 2 / 8)
            assert by["ici"] == 8000 - by["dcn"]
        finally:
            topo.reset()

    def test_bf16_wire_halves_payload(self, hvd_module):
        dense = xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=1,
                               nbytes=8000, dtype="float32")
        bf16 = dense.replace(wire="bf16")
        assert xir.op_wire_nbytes(bf16) == xir.op_wire_nbytes(dense) // 2


class TestStoreKeying:
    def test_kind_discriminates_keys(self):
        sig = ("payload", (1, 2, 3))
        k_dense = sched.make_key(sig, kind="dense_grad")
        k_moe = sched.make_key(sig, kind="moe")
        assert k_dense != k_moe
        assert k_dense == sched.make_key(sig)  # default kind is dense

    def test_program_seeded_into_db(self, hvd_module, tmp_path,
                                    monkeypatch):
        db = tmp_path / "tune.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        xir.lower.reset()
        metrics.reset_counters("xir.db")
        prog = xir.program("moe", [
            xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=1,
                           nbytes=4096, dtype="float32"),
        ])
        lowered = xir.lower_program(prog)
        assert metrics.get_counter("xir.db_seeded") == 1
        data = json.loads(db.read_text())
        (entry,) = data["entries"].values()
        assert entry["meta"]["kind"] == "moe"
        assert entry["bucket_bytes"] == 4096
        # second lowering of the same program: memoized, no extra write
        xir.lower_program(prog)
        assert metrics.get_counter("xir.db_seeded") == 1
        # a fresh process (reset memo) hits the stored entry
        xir.lower.reset()
        xir.lower_program(lowered)
        assert metrics.get_counter("xir.db_hit") == 1

    def test_stored_wire_adopted_when_eligible(self, hvd_module,
                                               tmp_path, monkeypatch):
        from horovod_tpu.sched.store import ScheduleStore

        db = tmp_path / "tune.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        xir.lower.reset()
        prog = xir.program("moe", [
            xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=1,
                           nbytes=4096, dtype="float32"),
        ])
        key = xir.tuner_key(xir.lower_program(prog, store=False))
        ScheduleStore(str(db)).record(
            key, bucket_bytes=4096, wire="int8", lowering="hier",
            score=9.0,
        )
        lowered = xir.lower_program(prog)
        # int8 is ineligible for a shuffle op -> off; hier -> flat
        assert lowered.ops[0].wire == "off"
        assert lowered.ops[0].lowering == "flat"


class TestDenseGradParity:
    """The tentpole acceptance: f32 dense DP programs through the IR
    are bitwise-identical to the PR 7 direct path."""

    def _losses(self, xir_on):
        import optax

        xir.set_enabled_override(xir_on)
        X = np.random.RandomState(1).randn(16, 4).astype(np.float32)
        Y = (X @ np.full((4, 2), 0.7)).astype(np.float32)

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w1"] @ p["w2"] + p["b"] - y) ** 2)

        params = {"w1": jnp.full((4, 4), 0.2),
                  "w2": jnp.full((4, 2), 0.5), "b": jnp.zeros((2,))}
        sched.set_config_override(
            sched.SchedConfig(enabled=True, bucket_bytes=64)
        )
        try:
            tx = hvd.DistributedOptimizer(optax.sgd(0.1))
            step = hvd.distributed_train_step(loss_fn, tx)
            st = step.init(params)
            batch = (jnp.asarray(X), jnp.asarray(Y))
            out = []
            for _ in range(8):
                params, st, loss = step(params, st, batch)
                out.append(float(loss))
            return out
        finally:
            sched.set_config_override(None)
            xir.set_enabled_override(None)

    def test_f32_dense_losses_bitwise(self, hvd_module):
        assert self._losses(True) == self._losses(False)

    def test_dense_program_counted(self, hvd_module):
        metrics.reset_counters("xir.programs")
        self._losses(True)
        assert metrics.get_counter("xir.programs.dense_grad") > 0


class TestWorkloadParity:
    def test_pipeline_permute_bitwise(self, hvd_module):
        from horovod_tpu.parallel.pipeline import pipeline_apply

        mb = np.random.RandomState(3).randn(4, 2, 6).astype(np.float32)
        w = np.random.RandomState(4).randn(8, 6, 6).astype(
            np.float32) * 0.1

        def pp(wstack, m):
            return pipeline_apply(
                lambda p, a: jnp.tanh(a @ p), wstack[0], m,
                axis=WORLD_AXIS,
            )

        def run():
            return np.asarray(jax.jit(jax.shard_map(
                pp, mesh=hvd.mesh(), in_specs=(P(WORLD_AXIS), P()),
                out_specs=P(), check_vma=False,
            ))(w, mb))

        xir.set_enabled_override(True)
        on = run()
        xir.set_enabled_override(False)
        off = run()
        np.testing.assert_array_equal(on, off)

    def test_fsdp_step_bitwise(self, hvd_module):
        import optax

        from horovod_tpu.optim.zero import fsdp_train_step

        X = np.random.RandomState(5).randn(8, 4).astype(np.float32)
        params = {"w": jnp.asarray(
            np.random.RandomState(6).randn(4, 2).astype(np.float32))}

        def loss_fn(p, b):
            return jnp.mean((b @ p["w"]) ** 2)

        losses = {}
        for flag in (True, False):
            xir.set_enabled_override(flag)
            step = fsdp_train_step(loss_fn, optax.sgd(0.1))
            ps, st = step.init(params)
            ls = []
            for _ in range(3):
                ps, st, loss = step(ps, st, jnp.asarray(X))
                ls.append(float(loss))
            losses[flag] = ls
        assert losses[True] == losses[False]

    def test_sparse_exchange_bitwise_and_observable(self, hvd_module):
        from horovod_tpu.ops.sparse import IndexedSlices, sparse_allreduce

        idx = np.tile(np.arange(4, dtype=np.int32), N)
        vals = np.random.RandomState(2).randn(N * 4, 3).astype(np.float32)

        def sp(i, v):
            out = sparse_allreduce(
                IndexedSlices(i, v, (16, 3)), axis=WORLD_AXIS
            )
            return out.values

        def run():
            return np.asarray(jax.jit(jax.shard_map(
                sp, mesh=hvd.mesh(),
                in_specs=(P(WORLD_AXIS), P(WORLD_AXIS)),
                out_specs=P(WORLD_AXIS), check_vma=False,
            ))(idx, vals))

        metrics.reset_counters("xir.programs.sparse_embed")
        xir.set_enabled_override(True)
        on = run()
        xir.set_enabled_override(False)
        off = run()
        np.testing.assert_array_equal(on, off)
        assert metrics.get_counter("xir.programs.sparse_embed") == 1
        assert metrics.get_gauge(
            "sched.wire_bytes", {"wire": "off", "kind": "sparse_embed"}
        ) > 0


class TestInterpReduceOps:
    def test_all_reduce_matches_psum(self, hvd_module):
        x = np.random.RandomState(7).randn(N, 5).astype(np.float32)

        def f(a):
            op = xir.all_reduce(WORLD_AXIS, nbytes=a.size * 4,
                                dtype="float32", lowering="flat")
            return xir.run_op(op, a), jax.lax.psum(a, WORLD_AXIS)

        got, want = _shard_run(f, x, n_out=2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_rs_ag_roundtrip(self, hvd_module):
        x = np.random.RandomState(8).randn(N, 16).astype(np.float32)

        def f(a):
            flat = a.reshape(-1)
            rs = xir.reduce_scatter(WORLD_AXIS, lowering="flat")
            ag = xir.all_gather(WORLD_AXIS, lowering="flat")
            shard = xir.run_op(rs, flat)
            out = xir.run_op(ag, shard)
            return out.reshape(a.shape), jax.lax.psum(a, WORLD_AXIS)

        got, want = _shard_run(f, x, n_out=2)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
        )

    def test_reduce_scatter_indivisible_raises(self, hvd_module):
        x = np.random.RandomState(9).randn(N, 9).astype(np.float32)

        def f(a):
            rs = xir.reduce_scatter(WORLD_AXIS, lowering="flat")
            return xir.run_op(rs, a.reshape(-1))

        with pytest.raises(Exception, match="divide"):
            _shard_run(f, x)

    def test_execute_arity_mismatch(self, hvd_module):
        prog = xir.program("moe", [
            xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=1),
        ])
        with pytest.raises(HorovodTpuError, match="payloads"):
            xir.execute(prog, [1, 2], store=False)


class TestEnableKnob:
    def test_env_default_on(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_XIR", raising=False)
        assert xir.enabled()
        monkeypatch.setenv("HVD_TPU_XIR", "off")
        assert not xir.enabled()

    def test_override_wins(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_XIR", "off")
        xir.set_enabled_override(True)
        assert xir.enabled()

    def test_wire_request_default_off_and_validated(self, monkeypatch):
        monkeypatch.delenv("HVD_TPU_XIR_WIRE", raising=False)
        monkeypatch.setenv("HVD_TPU_SCHED_WIRE", "int8")
        # deliberately NOT inherited from the gradient wire knob
        assert xir.wire_request() == "off"
        monkeypatch.setenv("HVD_TPU_XIR_WIRE", "e4m3")
        assert xir.wire_request() == "fp8"
        monkeypatch.setenv("HVD_TPU_XIR_WIRE", "fp4")
        with pytest.raises(HorovodTpuError, match="XIR_WIRE"):
            xir.wire_request()
