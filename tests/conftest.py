"""Test fixtures: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): parallel-tier
tests exercise real collectives — here on 8 XLA host devices
(``--xla_force_host_platform_device_count=8``), the CPU stand-in for a
TPU slice.
"""

import os

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 "
    + os.environ.get("XLA_FLAGS", "")
)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

import horovod_tpu as hvd


@pytest.fixture(scope="session", autouse=True)
def _devices():
    assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"
    yield


@pytest.fixture()
def hvd_init():
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture(scope="module")
def hvd_module():
    hvd.init()
    yield hvd
    hvd.shutdown()
