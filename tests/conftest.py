"""Test fixtures: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): parallel-tier
tests exercise real collectives — here on 8 XLA host devices
(``--xla_force_host_platform_device_count=8``), the CPU stand-in for a
TPU slice.
"""

import os

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 "
    + os.environ.get("XLA_FLAGS", "")
)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

import horovod_tpu as hvd

# ---------------------------------------------------------------------
# multiproc triage: tests marked @pytest.mark.multiproc need a CPU
# backend that can run cross-process computations (real worker
# processes rendezvousing through jax.distributed).  Some jax builds
# reject that outright ("Multiprocess computations aren't implemented
# on the CPU backend") — an environment limitation, not a regression —
# so those tests SKIP with the probe's reason instead of failing,
# keeping tier-1 output legible: skips = environment can't run this,
# failures = something actually broke.

_MULTIPROC_PROBE: list = []  # memoized [reason-or-None]

_PROBE_SRC = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=sys.argv[1], num_processes=2,
    process_id=int(sys.argv[2]), initialization_timeout=60,
)
import numpy as np
from jax.experimental import multihost_utils
out = multihost_utils.process_allgather(np.int32(1))
assert int(np.asarray(out).sum()) == 2
"""


def _multiproc_unavailable_reason():
    """Probe once per session: spawn two 1-device CPU workers and run
    one cross-process allgather.  Returns None when the distributed CPU
    backend works, else a one-line reason for the skip."""
    if _MULTIPROC_PROBE:
        return _MULTIPROC_PROBE[0]
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_"))
    }
    reason = None
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _PROBE_SRC, addr, str(i)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                out = (out or "") + "\n[probe timed out]"
            outs.append((p.returncode, out or ""))
        if any(rc != 0 for rc, _ in outs):
            lines = [
                ln.strip() for _, out in outs
                for ln in out.splitlines()
                if "Error" in ln or "error" in ln or "timed out" in ln
            ]
            reason = (lines[-1] if lines else "probe worker failed")[:200]
    except OSError as e:
        reason = f"could not spawn probe workers: {e}"
    _MULTIPROC_PROBE.append(reason)
    return reason


def pytest_collection_modifyitems(config, items):
    if not any(item.get_closest_marker("multiproc") for item in items):
        return
    reason = _multiproc_unavailable_reason()
    if reason is None:
        return
    skip = pytest.mark.skip(
        reason=f"distributed CPU backend unavailable: {reason}"
    )
    for item in items:
        if item.get_closest_marker("multiproc"):
            item.add_marker(skip)


@pytest.fixture(scope="session", autouse=True)
def _devices():
    assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"
    yield


@pytest.fixture()
def hvd_init():
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture(scope="module")
def hvd_module():
    hvd.init()
    yield hvd
    hvd.shutdown()
