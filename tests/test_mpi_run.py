"""MPI launch path (reference ``horovod/runner/mpi_run.py`` +
``test/single/test_run.py`` mpirun command construction tests)."""

import os
import stat
import subprocess
import sys

import pytest

from horovod_tpu.runner import mpi_run as mr
from horovod_tpu.runner.mpi_worker import resolve_mpi_env


class TestCommandConstruction:
    def test_basic_shape(self):
        cmd = mr.get_mpi_command(
            4, "host1:2,host2:2", ["python", "train.py"],
            {"HVD_TPU_SECRET": "s", "PYTHONPATH": "/x", "HOME": "/root"},
        )
        assert cmd[0] == "mpirun"
        assert "--allow-run-as-root" in cmd
        i = cmd.index("-np")
        assert cmd[i + 1] == "4"
        i = cmd.index("-H")
        assert cmd[i + 1] == "host1:2,host2:2"
        # framework env forwarded; unrelated env not
        xs = [cmd[j + 1] for j, a in enumerate(cmd) if a == "-x"]
        assert "HVD_TPU_SECRET" in xs and "PYTHONPATH" in xs
        assert "HOME" not in xs
        # worker shim wraps the user command
        j = cmd.index("-m")
        assert cmd[j + 1] == "horovod_tpu.runner.mpi_worker"
        assert cmd[-2:] == ["python", "train.py"]

    def test_extra_mpi_args(self):
        cmd = mr.get_mpi_command(
            2, None, ["echo"], {}, mpi_args=["--map-by", "socket"]
        )
        k = cmd.index("--map-by")
        assert cmd[k + 1] == "socket"
        assert "-H" not in cmd

    def test_unavailable_raises(self, monkeypatch):
        monkeypatch.setenv("PATH", "/nonexistent")
        assert not mr.is_mpi_available()
        with pytest.raises(RuntimeError, match="mpirun not found"):
            mr.mpi_run(2, None, ["echo"])


class TestWorkerShim:
    def test_resolve_openmpi_env(self):
        env = {
            "OMPI_COMM_WORLD_RANK": "3",
            "OMPI_COMM_WORLD_SIZE": "8",
            "OMPI_COMM_WORLD_LOCAL_RANK": "1",
            "OMPI_COMM_WORLD_LOCAL_SIZE": "4",
        }
        out = resolve_mpi_env(env)
        assert out == {
            "HVD_TPU_CROSS_RANK": "3",
            "HVD_TPU_CROSS_SIZE": "8",
            "HVD_TPU_LOCAL_RANK": "1",
            "HVD_TPU_LOCAL_SIZE": "4",
        }

    def test_resolve_slurm_env(self):
        out = resolve_mpi_env({"SLURM_PROCID": "5", "SLURM_NTASKS": "16"})
        assert out["HVD_TPU_CROSS_RANK"] == "5"
        assert out["HVD_TPU_CROSS_SIZE"] == "16"

    def test_resolve_slurm_tasks_per_node_runlength(self):
        out = resolve_mpi_env({
            "SLURM_PROCID": "0", "SLURM_NTASKS": "6",
            "SLURM_LOCALID": "1", "SLURM_TASKS_PER_NODE": "2(x3)",
        })
        assert out["HVD_TPU_LOCAL_SIZE"] == "2"  # integer, not "2(x3)"
        out2 = resolve_mpi_env({"SLURM_TASKS_PER_NODE": "4,2"})
        assert out2["HVD_TPU_LOCAL_SIZE"] == "4"

    def test_resolve_empty(self):
        assert resolve_mpi_env({}) == {}

    def test_shim_execs_command(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.mpi_worker",
             sys.executable, "-c",
             "import os; print(os.environ.get('HVD_TPU_CROSS_RANK'))"],
            env={**os.environ, "OMPI_COMM_WORLD_RANK": "2",
                 "PYTHONPATH": os.path.dirname(os.path.dirname(
                     os.path.abspath(__file__)))},
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.strip() == "2"


def test_mpi_run_end_to_end_with_fake_mpirun(tmp_path, monkeypatch):
    """Full mpi_run flow against a fake mpirun that spawns np local
    shim processes with OMPI env — the reference tests fake the mpirun
    binary the same way."""
    fake = tmp_path / "mpirun"
    fake.write_text(
        """#!/usr/bin/env python3
import os, subprocess, sys
args = sys.argv[1:]
np_ = 1
cmd = []
i = 0
while i < len(args):
    if args[i] == "-np":
        np_ = int(args[i + 1]); i += 2
    elif args[i] in ("-H", "-x", "--map-by"):
        i += 2
    elif args[i].startswith("--"):
        i += 1
    else:
        cmd = args[i:]; break
procs = []
for r in range(np_):
    env = dict(os.environ)
    env["OMPI_COMM_WORLD_RANK"] = str(r)
    env["OMPI_COMM_WORLD_SIZE"] = str(np_)
    procs.append(subprocess.Popen(cmd, env=env))
sys.exit(max(p.wait() for p in procs))
"""
    )
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    assert mr.is_mpi_available()

    out_file = tmp_path / "out"
    rc = mr.mpi_run(
        2, None,
        [sys.executable, "-c",
         "import os; open(os.environ['OUT'], 'a').write("
         "os.environ['HVD_TPU_CROSS_RANK'] + ':' + "
         "os.environ['HVD_TPU_CROSS_SIZE'] + ':' + "
         "('y' if os.environ.get('HVD_TPU_SECRET') else 'n') + '\\n')"],
        extra_env={"OUT": str(out_file)},
    )
    assert rc == 0
    lines = sorted(out_file.read_text().splitlines())
    assert lines == ["0:2:y", "1:2:y"]
