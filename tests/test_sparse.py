"""Sparse (IndexedSlices) gradient collectives.

Reference parity targets: ``tensorflow/__init__.py:95-162`` (allgather-
of-slices allreduce), ``torch/optimizer.py`` ``sparse_as_dense`` knob.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import traced
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.ops.sparse import (
    IndexedSlices,
    dense_grad_to_indexed_slices,
    densify,
    sparse_allreduce,
    sparse_allreduce_eager,
)

VOCAB, DIM, NNZ = 64, 8, 4


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield
    hvd.shutdown()


def _mesh():
    from horovod_tpu.runtime import get_runtime

    return get_runtime().mesh


def test_dense_grad_to_indexed_slices_dedup():
    dense = jnp.zeros((VOCAB, DIM)).at[3].set(2.0).at[7].set(1.0)
    ids = jnp.array([3, 3, 7, 3])  # duplicates must not double-count
    s = dense_grad_to_indexed_slices(dense, ids, nnz=NNZ)
    assert s.indices.shape == (NNZ,)
    np.testing.assert_allclose(np.asarray(densify(s)), np.asarray(dense))


def test_capacity_overflow_poisons_not_drops():
    """More distinct ids than nnz can't be represented statically; the
    failure must be loud (NaN), never a silent row drop."""
    dense = jnp.ones((VOCAB, DIM))
    ids = jnp.arange(6)  # 6 distinct ids
    s = dense_grad_to_indexed_slices(dense, ids, nnz=4)
    assert bool(jnp.isnan(s.values).any())
    # exactly-fitting capacity stays clean
    s_ok = dense_grad_to_indexed_slices(dense, ids, nnz=6)
    assert not bool(jnp.isnan(s_ok.values).any())


def test_densify_duplicate_indices_sum():
    s = IndexedSlices(
        jnp.array([2, 2, 5, 0]),
        jnp.ones((4, DIM)),
        (VOCAB, DIM),
    )
    d = np.asarray(densify(s))
    assert d[2, 0] == 2.0 and d[5, 0] == 1.0 and d[0, 0] == 1.0


def test_traced_sparse_allreduce_matches_dense():
    n = 8
    rng = np.random.RandomState(0)
    # Each rank touches a few rows; build per-rank dense grads too.
    ids = rng.randint(0, VOCAB, (n, NNZ)).astype(np.int32)
    vals = rng.rand(n, NNZ, DIM).astype(np.float32)
    dense = np.zeros((n, VOCAB, DIM), np.float32)
    for r in range(n):
        for k in range(NNZ):
            dense[r, ids[r, k]] += vals[r, k]
    expect = dense.sum(axis=0) / n  # Average

    def body(ids_r, vals_r):
        s = IndexedSlices(ids_r[0], vals_r[0], (VOCAB, DIM))
        out = sparse_allreduce(s, op=traced.Average)
        return densify(out)[None]

    f = jax.jit(
        shard_map(
            body, mesh=_mesh(), in_specs=(P(WORLD_AXIS), P(WORLD_AXIS)),
            out_specs=P(WORLD_AXIS), check_vma=False,
        )
    )
    got = np.asarray(f(jnp.asarray(ids), jnp.asarray(vals)))
    for r in range(n):
        np.testing.assert_allclose(got[r], expect, rtol=1e-5)


def test_eager_sparse_allreduce():
    n = hvd.size()
    ids = jnp.tile(jnp.arange(NNZ, dtype=jnp.int32)[None], (n, 1))
    vals = jnp.ones((n, NNZ, DIM))
    s = IndexedSlices(ids, vals, (VOCAB, DIM))
    out = sparse_allreduce_eager(s, average=True)
    assert out.indices.shape == (n, n * NNZ)
    np.testing.assert_allclose(np.asarray(out.values), 1.0 / n)
    d = densify(IndexedSlices(out.indices[0], out.values[0], (VOCAB, DIM)))
    np.testing.assert_allclose(np.asarray(d)[:NNZ], 1.0)


class TestOptimizerIntegration:
    def _embedding_loss(self, sparse: bool):
        """Embedding + dense head; sparse=True converts the embedding
        grad to IndexedSlices inside the loss gradient pytree."""

        def loss_fn(params, batch):
            table, w = params["emb"], params["w"]
            ids, y = batch
            h = table[ids].mean(axis=1) @ w
            return jnp.mean((h.squeeze(-1) - y) ** 2)

        def grads_fn(params, batch):
            loss, g = jax.value_and_grad(loss_fn)(params, batch)
            if sparse:
                g = dict(g)
                g["emb"] = dense_grad_to_indexed_slices(
                    g["emb"], batch[0], nnz=8
                )
            return loss, g

        return loss_fn, grads_fn

    @pytest.mark.parametrize("sparse_as_dense", [False, True])
    def test_sparse_grads_match_dense_path(self, sparse_as_dense):
        n = hvd.size()
        rng = np.random.RandomState(1)
        params = {
            "emb": jnp.asarray(rng.rand(VOCAB, DIM), jnp.float32),
            "w": jnp.asarray(rng.rand(DIM, 1), jnp.float32),
        }
        ids = jnp.asarray(rng.randint(0, VOCAB, (n, 4)), jnp.int32)
        y = jnp.asarray(rng.rand(n), jnp.float32)

        _, grads_fn = self._embedding_loss(sparse=True)
        _, dense_grads_fn = self._embedding_loss(sparse=False)

        tx_sparse = hvd.DistributedOptimizer(
            optax.sgd(0.1), sparse_as_dense=sparse_as_dense
        )
        tx_dense = hvd.DistributedOptimizer(optax.sgd(0.1))

        def run(gfn, tx):
            def body(params, ids_r, y_r):
                loss, g = gfn(params, (ids_r, y_r))
                updates, _ = tx.update(g, tx.init(params), params)
                return updates

            f = jax.jit(
                shard_map(
                    body, mesh=_mesh(),
                    in_specs=(P(), P(WORLD_AXIS), P(WORLD_AXIS)),
                    out_specs=P(), check_vma=False,
                )
            )
            return f(params, ids, y)

        upd_sparse = run(grads_fn, tx_sparse)
        upd_dense = run(dense_grads_fn, tx_dense)
        np.testing.assert_allclose(
            np.asarray(upd_sparse["emb"]), np.asarray(upd_dense["emb"]),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(upd_sparse["w"]), np.asarray(upd_dense["w"]), rtol=1e-5
        )

    def test_sparse_path_moves_fewer_bytes(self):
        """The wire win: sparse reduce lowers to all-gathers of the
        (nnz, dim) slab; the dense path all-reduces the whole
        (VOCAB, DIM) table (reference rationale for IndexedSlices
        handling, tensorflow/__init__.py:95)."""
        big_vocab = 4096
        params_shape = (big_vocab, DIM)
        nnz = 8

        def sparse_body(idx, vals):
            s = IndexedSlices(idx[0], vals[0], params_shape)
            out = sparse_allreduce(s, op=traced.Sum)
            return densify(out)[None]

        def dense_body(g):
            return traced.allreduce(g[0], op=traced.Sum)[None]

        n = 8
        idx = jnp.zeros((n, nnz), jnp.int32)
        vals = jnp.zeros((n, nnz, DIM), jnp.float32)
        g = jnp.zeros((n,) + params_shape, jnp.float32)

        sparse_hlo = jax.jit(
            shard_map(sparse_body, mesh=_mesh(),
                      in_specs=(P(WORLD_AXIS), P(WORLD_AXIS)),
                      out_specs=P(WORLD_AXIS), check_vma=False)
        ).lower(idx, vals).compile().as_text()
        dense_hlo = jax.jit(
            shard_map(dense_body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
                      out_specs=P(WORLD_AXIS), check_vma=False)
        ).lower(g).compile().as_text()

        def collective_lines(hlo):
            # Match the collective ops themselves; tuple/copy lines that
            # merely reference a collective's result would drag every
            # co-tupled operand shape into the assertion (older jax HLO
            # emits while-loop carries as one wide tuple line).
            return [
                l for l in hlo.splitlines()
                if re.search(r"= \S+ (all-reduce|all-gather)\(", l)
            ]

        # Dense path: a collective carries the full vocab-sized table.
        assert any(str(big_vocab) in l for l in collective_lines(dense_hlo))
        # Sparse path: no collective touches a vocab-sized operand.
        sparse_colls = collective_lines(sparse_hlo)
        assert sparse_colls, "sparse path must still communicate"
        for line in sparse_colls:
            assert str(big_vocab) not in line, line


def test_sparse_rejects_adasum_and_sparse_groups():
    from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
    from horovod_tpu.compression import Compression
    from horovod_tpu.ops.traced import Adasum, Average

    s = IndexedSlices(jnp.zeros((2,), jnp.int32), jnp.zeros((2, DIM)),
                      (VOCAB, DIM))
    grads = {"emb": s, "w": jnp.zeros((DIM,))}
    common = dict(
        axis=WORLD_AXIS, compression=Compression.none,
        prescale_factor=1.0, postscale_factor=1.0, process_set=None,
        fusion_threshold_bytes=None,
    )
    with pytest.raises(ValueError, match="Average or Sum"):
        _reduce_gradients(grads, op=Adasum, **common)
    with pytest.raises(ValueError, match="fusion groups"):
        _reduce_gradients(grads, op=Average, groups=[[0, 1]], **common)


def test_sparse_prescale_matches_dense():
    """prescale/postscale must hit sparse leaves like dense ones."""
    n = hvd.size()
    rng = np.random.RandomState(7)
    dense_g = jnp.asarray(rng.rand(VOCAB, DIM), jnp.float32)
    ids = jnp.arange(NNZ, dtype=jnp.int32)

    def run(sparse):
        tx = hvd.DistributedOptimizer(
            optax.sgd(1.0), op=hvd.Sum, prescale_factor=0.5,
            postscale_factor=2.0,
        )

        def body(g):
            if sparse:
                g = {"emb": dense_grad_to_indexed_slices(g["emb"], ids, NNZ)}
            updates, _ = tx.update(g, tx.init({"emb": jnp.zeros((VOCAB, DIM))}))
            return updates

        f = jax.jit(
            shard_map(body, mesh=_mesh(), in_specs=(P(),), out_specs=P(),
                      check_vma=False)
        )
        # make the grad zero outside the touched rows so sparse == dense
        g = jnp.zeros((VOCAB, DIM)).at[:NNZ].set(dense_g[:NNZ])
        return np.asarray(f({"emb": g})["emb"])

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_sparse_process_set_nonmember_passthrough(monkeypatch):
    """Non-members must apply their own local gradient, mirroring the
    dense path's mask pass-through (traced.py allreduce)."""
    monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
    from horovod_tpu.optim.distributed_optimizer import _reduce_gradients
    from horovod_tpu.compression import Compression
    from horovod_tpu.ops.traced import Average

    ps = hvd.add_process_set([0, 1])
    n = hvd.size()

    def body(rank_vals):
        s = IndexedSlices(
            jnp.arange(NNZ, dtype=jnp.int32), rank_vals[0], (VOCAB, DIM)
        )
        out = _reduce_gradients(
            {"emb": s}, axis=WORLD_AXIS, op=Average,
            compression=Compression.none, prescale_factor=1.0,
            postscale_factor=1.0, process_set=ps,
            fusion_threshold_bytes=None,
        )
        return out["emb"][None]

    vals = jnp.asarray(
        np.arange(n, dtype=np.float32)[:, None, None]
        * np.ones((n, NNZ, DIM), np.float32)
    ) + 1.0
    f = jax.jit(
        shard_map(body, mesh=_mesh(), in_specs=(P(WORLD_AXIS),),
                  out_specs=P(WORLD_AXIS), check_vma=False)
    )
    out = np.asarray(f(vals))
    # Members 0,1 get the set average (1+2)/2 = 1.5 on touched rows.
    np.testing.assert_allclose(out[0][:NNZ], 1.5)
    np.testing.assert_allclose(out[1][:NNZ], 1.5)
    # Non-member rank 5 keeps its own local gradient (value 6).
    np.testing.assert_allclose(out[5][:NNZ], 6.0)
    hvd.remove_process_set(ps)


def test_backward_passes_per_step_densifies(monkeypatch):
    """Sparse leaves accumulate into the dense local-aggregation buffer."""
    n = hvd.size()
    tx = hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=2)
    params = {"emb": jnp.ones((VOCAB, DIM))}

    def body(params, ids_r):
        g = {
            "emb": dense_grad_to_indexed_slices(
                jnp.ones((VOCAB, DIM)), ids_r, nnz=4
            )
        }
        st = tx.init(params)
        updates, st = tx.update(g, st, params)
        updates2, st = tx.update(g, st, params)
        return updates, updates2

    ids = jnp.tile(jnp.arange(4, dtype=jnp.int32)[None], (n, 1))
    f = jax.jit(
        shard_map(
            body, mesh=_mesh(), in_specs=(P(), P(WORLD_AXIS)),
            out_specs=P(), check_vma=False,
        )
    )
    u1, u2 = f(params, ids)
    # First call: no step (zero updates); second call: the real update.
    assert float(jnp.abs(u1["emb"]).sum()) == 0.0
    assert float(jnp.abs(u2["emb"]).sum()) > 0.0
