"""ZeRO-1 sharded optimizer: exact parity with the unsharded update and
N-fold optimizer-state memory reduction."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.optim.zero import zero_train_step

N = 8


def _problem(seed=0, d_in=5, d_out=3, n=32):
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rng.randn(d_in, d_out), jnp.float32),
        "b": jnp.zeros((d_out,), jnp.float32),
    }
    x = rng.randn(n, d_in).astype(np.float32)
    y = rng.randn(n, d_out).astype(np.float32)

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, (jnp.asarray(x), jnp.asarray(y)), loss_fn


@pytest.mark.parametrize("make_tx", [
    lambda: optax.sgd(0.1, momentum=0.9),
    lambda: optax.adam(1e-2),
], ids=["sgd_momentum", "adam"])
def test_zero_matches_unsharded(hvd_module, make_tx):
    params, batch, loss_fn = _problem()

    step = zero_train_step(loss_fn, make_tx())
    st = step.init(params)
    p = jax.tree.map(jnp.array, params)
    for _ in range(5):
        p, st, loss = step(p, st, batch)

    # single-device reference on the same (global) batch
    ref_tx = make_tx()
    rp = jax.tree.map(jnp.array, params)
    rst = ref_tx.init(rp)
    for _ in range(5):
        g = jax.grad(loss_fn)(rp, batch)
        u, rst = ref_tx.update(g, rst, rp)
        rp = optax.apply_updates(rp, u)

    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(p[k]), np.asarray(rp[k]), rtol=1e-5, atol=1e-5
        )


def test_zero_state_is_sharded(hvd_module):
    params, batch, loss_fn = _problem(d_in=16, d_out=16)
    step = zero_train_step(loss_fn, optax.adam(1e-3))
    st = step.init(params)
    # each adam moment leaf is a global array of padded_n elements,
    # sharded across the 8 devices — not replicated N copies
    n_params = 16 * 16 + 16
    mu = st[0].mu
    assert mu.shape[0] >= n_params and mu.shape[0] % N == 0
    shardings = mu.sharding.device_set
    assert len(shardings) == N
    # per-device slice is 1/N of the padded vector
    shard_shapes = {s.data.shape for s in mu.addressable_shards}
    assert shard_shapes == {(mu.shape[0] // N,)}


def test_zero_training_converges(hvd_module):
    params, batch, loss_fn = _problem(n=64)
    step = zero_train_step(loss_fn, optax.adam(5e-2))
    st = step.init(params)
    p = jax.tree.map(jnp.array, params)
    losses = []
    for _ in range(30):
        p, st, loss = step(p, st, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


class TestFSDP:
    def test_fsdp_matches_unsharded_sgd(self, hvd_module):
        from horovod_tpu.optim.zero import fsdp_train_step

        params, (x, y), loss_fn = _problem()
        step = fsdp_train_step(loss_fn, optax.sgd(0.1))
        pshards, opt_state = step.init(params)
        # reference: plain replicated training on the same global batch
        ref_tx = optax.sgd(0.1)
        ref_state = ref_tx.init(params)
        ref_params = params
        for _ in range(5):
            pshards, opt_state, loss = step(pshards, opt_state, (x, y))
            g = jax.grad(loss_fn)(ref_params, (x, y))
            updates, ref_state = ref_tx.update(g, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, updates)
        gathered = step.gather(pshards)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(gathered[k]), np.asarray(ref_params[k]),
                rtol=1e-4, atol=1e-5,
            )
        assert float(loss) >= 0

    def test_fsdp_adam_state_and_params_sharded(self, hvd_module):
        from jax.flatten_util import ravel_pytree

        from horovod_tpu.optim.zero import fsdp_train_step

        params, (x, y), loss_fn = _problem(d_in=8, d_out=4)
        flat, _ = ravel_pytree(params)
        n = flat.shape[0]
        shard_len = -(-n // N)
        step = fsdp_train_step(loss_fn, optax.adam(1e-2))
        pshards, opt_state = step.init(params)
        # persistent storage is 1/N per chip: global stacked arrays have
        # leading dim N with shard_len elements each
        assert pshards.shape == (N * shard_len,)
        m = opt_state[0].mu  # adam first moment
        assert m.shape == (N * shard_len,)
        pshards, opt_state, loss = step(pshards, opt_state, (x, y))
        assert np.isfinite(float(loss))

    def test_fsdp_training_converges(self, hvd_module):
        from horovod_tpu.optim.zero import fsdp_train_step

        params, (x, y), loss_fn = _problem(n=64)
        step = fsdp_train_step(loss_fn, optax.adam(5e-2))
        pshards, opt_state = step.init(params)
        first = None
        for i in range(40):
            pshards, opt_state, loss = step(pshards, opt_state, (x, y))
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.5, (first, float(loss))

    def test_fsdp_restore_without_full_params(self, hvd_module):
        """Checkpoint-restore path: layout from jax.eval_shape structure,
        shards fed directly — no full params ever materialized."""
        from horovod_tpu.optim.zero import fsdp_train_step

        params, (x, y), loss_fn = _problem()
        step1 = fsdp_train_step(loss_fn, optax.sgd(0.1))
        pshards, opt_state = step1.init(params)
        pshards, opt_state, _ = step1(pshards, opt_state, (x, y))
        trained = step1.gather(pshards)

        shapes = jax.eval_shape(lambda: params)
        step2 = fsdp_train_step(loss_fn, optax.sgd(0.1),
                                example_params=shapes)
        restored = step2.gather(pshards)  # no init() call needed
        for k in params:
            np.testing.assert_allclose(
                np.asarray(restored[k]), np.asarray(trained[k]), rtol=1e-6
            )
        pshards, opt_state, loss = step2(pshards, opt_state, (x, y))
        assert np.isfinite(float(loss))

    def test_fsdp_layout_required_error(self, hvd_module):
        from horovod_tpu.optim.zero import fsdp_train_step

        params, (x, y), loss_fn = _problem()
        step = fsdp_train_step(loss_fn, optax.sgd(0.1))
        with pytest.raises(RuntimeError, match="example_params"):
            step.gather(jnp.zeros((8,)))

    def test_fsdp_bf16_wire_compression(self, hvd_module):
        import horovod_tpu as hvd
        from horovod_tpu.optim.zero import fsdp_train_step

        params, (x, y), loss_fn = _problem()
        step = fsdp_train_step(loss_fn, optax.sgd(0.1),
                               compression=hvd.Compression.bf16)
        pshards, opt_state = step.init(params)
        ref = fsdp_train_step(loss_fn, optax.sgd(0.1))
        rs, ro = ref.init(params)
        for _ in range(3):
            pshards, opt_state, loss = step(pshards, opt_state, (x, y))
            rs, ro, rloss = ref(rs, ro, (x, y))
        # bf16 wire: close to the uncompressed trajectory
        np.testing.assert_allclose(
            np.asarray(step.gather(pshards)["w"]),
            np.asarray(ref.gather(rs)["w"]), rtol=2e-2, atol=2e-3,
        )
        assert np.isfinite(float(loss))
