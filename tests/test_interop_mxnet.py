"""MXNet binding over the eager collective layer (reference
``horovod/mxnet/__init__.py`` + ``mxnet/mpi_ops.py``,
``test/parallel/test_mxnet1.py`` semantics).

mxnet is not installable in this environment, so a minimal stub module
standing in for ``mxnet`` (NDArray with asnumpy/setitem, ``nd.array``,
``gluon.Trainer``) is injected into ``sys.modules`` — the binding only
touches that surface, by design.
"""

import sys
import types

import numpy as np
import pytest

import horovod_tpu as hvd


class FakeNDArray:
    """ndarray wrapper with the NDArray surface the binding touches."""

    def __init__(self, arr, ctx="cpu(0)"):
        self._arr = np.asarray(arr)
        self.context = ctx

    def asnumpy(self):
        return self._arr.copy()

    def __setitem__(self, key, value):
        if isinstance(value, FakeNDArray):
            value = value._arr
        self._arr[key] = value

    @property
    def shape(self):
        return self._arr.shape

    @property
    def dtype(self):
        return self._arr.dtype


class FakeParam:
    def __init__(self, arr, grad, grad_req="write"):
        self._data = FakeNDArray(arr)
        self._grad = FakeNDArray(grad)
        self.grad_req = grad_req

    def data(self):
        return self._data

    def set_data(self, v):
        self._data = v if isinstance(v, FakeNDArray) else FakeNDArray(
            np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
        )

    def list_grad(self):
        return [self._grad]


def _install_fake_mxnet(monkeypatch):
    mx = types.ModuleType("mxnet")

    nd = types.ModuleType("mxnet.nd")

    def nd_array(arr, dtype=None, ctx=None):
        a = np.asarray(arr, dtype=dtype)
        return FakeNDArray(a, ctx=ctx or "cpu(0)")

    nd.array = nd_array
    mx.nd = nd

    gluon = types.ModuleType("mxnet.gluon")

    class Trainer:
        def __init__(self, params, optimizer, optimizer_params=None,
                     kvstore=None):
            self._params = list(params)
            self.optimizer = optimizer
            self.optimizer_params = optimizer_params
            self.kvstore = kvstore

        def step(self, batch_size):
            self._allreduce_grads()

        def _allreduce_grads(self):
            pass

    gluon.Trainer = Trainer
    mx.gluon = gluon

    monkeypatch.setitem(sys.modules, "mxnet", mx)
    monkeypatch.setitem(sys.modules, "mxnet.nd", nd)
    monkeypatch.setitem(sys.modules, "mxnet.gluon", gluon)
    return mx


@pytest.fixture()
def hvd_mx(hvd_module, monkeypatch):
    _install_fake_mxnet(monkeypatch)
    import horovod_tpu.interop.mxnet as hvd_mx

    return hvd_mx


SIZE = 8


class TestCollectives:
    def test_allreduce_average(self, hvd_mx):
        rows = np.arange(SIZE * 3, dtype=np.float32).reshape(SIZE, 3)
        out = hvd_mx.allreduce(FakeNDArray(rows))
        assert isinstance(out, FakeNDArray)
        np.testing.assert_allclose(
            out.asnumpy(), np.tile(rows.mean(0), (SIZE, 1)), rtol=1e-6
        )

    def test_allreduce_sum_inplace(self, hvd_mx):
        rows = np.ones((SIZE, 2), np.float32)
        t = FakeNDArray(rows)
        out = hvd_mx.allreduce_(t, average=False)
        assert out is t
        np.testing.assert_allclose(t.asnumpy(), np.full((SIZE, 2), SIZE))

    def test_grouped_allreduce(self, hvd_mx):
        a = np.ones((SIZE, 2), np.float32)
        b = 2 * np.ones((SIZE, 3), np.float32)
        outs = hvd_mx.grouped_allreduce(
            [FakeNDArray(a), FakeNDArray(b)], average=True
        )
        np.testing.assert_allclose(outs[0].asnumpy(), a)
        np.testing.assert_allclose(outs[1].asnumpy(), b)

    def test_broadcast(self, hvd_mx):
        rows = np.arange(SIZE, dtype=np.float32)[:, None] * np.ones((1, 2))
        out = hvd_mx.broadcast(FakeNDArray(rows.astype(np.float32)), 3)
        np.testing.assert_allclose(
            out.asnumpy(), np.full((SIZE, 2), 3.0)
        )

    def test_broadcast_inplace(self, hvd_mx):
        rows = np.arange(SIZE, dtype=np.float32)[:, None]
        t = FakeNDArray(rows.copy())
        hvd_mx.broadcast_(t, 0)
        np.testing.assert_allclose(t.asnumpy(), np.zeros((SIZE, 1)))

    def test_allgather(self, hvd_mx):
        rows = np.arange(SIZE, dtype=np.float32)[:, None, None]
        out = hvd_mx.allgather(FakeNDArray(np.tile(rows, (1, 2, 3))))
        # every rank sees all rows concatenated
        assert out.asnumpy().shape == (SIZE, SIZE * 2, 3)

    def test_alltoall(self, hvd_mx):
        rows = np.arange(SIZE * SIZE, dtype=np.float32).reshape(SIZE, SIZE)
        out = hvd_mx.alltoall(FakeNDArray(rows))
        np.testing.assert_allclose(out.asnumpy(), rows.T)


class TestBroadcastParameters:
    def test_dict_of_ndarrays(self, hvd_mx):
        params = {
            "w": FakeNDArray(
                np.arange(SIZE, dtype=np.float32)[:, None] + np.zeros((1, 2))
            ),
        }
        hvd_mx.broadcast_parameters(params, root_rank=2)
        np.testing.assert_allclose(
            params["w"].asnumpy(), np.full((SIZE, 2), 2.0)
        )

    def test_gluon_params(self, hvd_mx):
        p = FakeParam(
            np.arange(SIZE, dtype=np.float32)[:, None],
            np.zeros((SIZE, 1), np.float32),
        )
        hvd_mx.broadcast_parameters({"p": p}, root_rank=1)
        np.testing.assert_allclose(p.data().asnumpy(), np.ones((SIZE, 1)))


class FakeOptimizer:
    def __init__(self):
        self.updates = []
        self.lr = 0.1

    def update(self, index, weight, grad, state):
        self.updates.append((index, grad.asnumpy().copy()))

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return None

    def set_learning_rate(self, lr):
        self.lr = lr


class TestDistributedOptimizer:
    def test_update_averages_then_delegates(self, hvd_mx):
        inner = FakeOptimizer()
        opt = hvd_mx.DistributedOptimizer(inner)
        rows = np.arange(SIZE, dtype=np.float32)[:, None] * np.ones((1, 2))
        grad = FakeNDArray(rows.astype(np.float32))
        w = FakeNDArray(np.zeros((SIZE, 2), np.float32))
        opt.update(0, w, grad, None)
        assert len(inner.updates) == 1
        # grad rows replaced by the cross-rank average
        mean = rows.mean(0)
        np.testing.assert_allclose(
            inner.updates[0][1], np.tile(mean, (SIZE, 1)), rtol=1e-6
        )

    def test_delegation(self, hvd_mx):
        inner = FakeOptimizer()
        opt = hvd_mx.DistributedOptimizer(inner)
        opt.set_learning_rate(0.5)
        assert inner.lr == 0.5
        assert opt.lr == 0.5  # __getattr__ passthrough


class TestDistributedTrainer:
    def test_allreduce_grads_averages(self, hvd_mx):
        g_rows = np.arange(SIZE, dtype=np.float32)[:, None]
        p = FakeParam(np.zeros((SIZE, 1), np.float32), g_rows.copy())
        trainer = hvd_mx.DistributedTrainer([p], FakeOptimizer())
        trainer._allreduce_grads()
        np.testing.assert_allclose(
            p.list_grad()[0].asnumpy(),
            np.full((SIZE, 1), g_rows.mean()), rtol=1e-6,
        )

    def test_null_grad_req_skipped(self, hvd_mx):
        g = np.arange(SIZE, dtype=np.float32)[:, None]
        p = FakeParam(np.zeros((SIZE, 1), np.float32), g.copy(),
                      grad_req="null")
        trainer = hvd_mx.DistributedTrainer([p], FakeOptimizer())
        trainer._allreduce_grads()
        np.testing.assert_allclose(p.list_grad()[0].asnumpy(), g)

    def test_unwraps_distributed_optimizer(self, hvd_mx):
        inner = FakeOptimizer()
        trainer = hvd_mx.DistributedTrainer(
            [], hvd_mx.DistributedOptimizer(inner)
        )
        assert trainer.optimizer is inner


def test_import_without_mxnet_is_clean():
    """The module imports fine without mxnet; only NDArray use raises."""
    import horovod_tpu.interop.mxnet as m

    with pytest.raises((ImportError, TypeError)):
        m.allreduce(np.ones(3))  # not an NDArray -> TypeError before mx


def test_alltoall_uneven_splits(hvd_mx):
    splits = np.full((SIZE, SIZE), 1)
    for r in range(SIZE):
        splits[r, (r + 1) % SIZE] += 1
        splits[r, (r + 2) % SIZE] -= 1
    rows = np.arange(SIZE * SIZE * 2, dtype=np.float32).reshape(
        SIZE, SIZE, 2
    )
    out, received = hvd_mx.alltoall(FakeNDArray(rows), splits=splits)
    assert isinstance(out, FakeNDArray) and isinstance(received, FakeNDArray)
    np.testing.assert_array_equal(received.asnumpy(), splits.T)
    # routing: rank 1's first received row is rank 0's row at offset
    # splits[0,0] (rank 0's block addressed to rank 1)
    np.testing.assert_allclose(
        out.asnumpy()[1][0], rows[0][int(splits[0, 0])]
    )
