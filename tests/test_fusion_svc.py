"""Service-side fusion buffers (svc/fuse.py + svc/params.py).

Contracts under test:

* **Packer units** — fusion-class keys admit only provably
  value-preserving coalescing (all_reduce, no EF, never hier_adasum);
  pack/unpack round-trips with block-aligned offsets; plan_cycle packs
  in deterministic (producer, seq) order, splits at the threshold, and
  passes oversize programs through.
* **Service dispatch** — a cycle's submissions coalesce into one wire
  dispatch per class: fused == unfused **bitwise** at f32 dense (and
  within 1e-3 on the int8 wire, where aligned offsets make the blocks
  identical); mixed dense + MoE a2a + sparse submissions fuse only
  within class; ``svc.fusion.buffers_out`` < ``programs_in``; padding
  is metered and bounded; threshold=0 restores the PR 13 behavior
  exactly (zero fusion counters, bitwise-identical results).
* **Concat merged mode** — ``xir.interp.execute_merged`` concatenates
  same-class ops of rail-sharing programs into one collective, bitwise
  equal to sequential execution, priced through
  ``lower.estimate_program_cost``.
* **Grouped eager path** — ``grouped_allreduce`` routes through the
  same packer: one fused buffer per dtype, bitwise equal to the
  per-tensor path.
* **Donation** — TrainStep and StaleTrainStep donate params/opt-state;
  ``donate=False`` produces bitwise-identical losses (the parity
  guard).
* **Params tuner** — the (cycle_time, fusion_threshold) window loop
  converges, pins the env knobs, persists to the tune DB, and
  warm-starts with zero exploration windows; its store key survives
  its own winner being pinned.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults, metrics, sched, svc, topo, xir
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.svc import fuse, params as svc_params
from horovod_tpu.svc.queue import Submission, SvcFuture, TensorQueue
from horovod_tpu.topo import model as topo_model

pytestmark = [pytest.mark.svc, pytest.mark.fusion]

N = 8
T24 = topo_model.Topology(num_slices=2, slice_size=4)


@pytest.fixture(autouse=True)
def _fusion_isolation(monkeypatch):
    metrics.reset_counters("svc.")
    metrics.reset_counters("xir.fusion")
    for knob in ("HVD_TPU_SVC_CYCLE_TIME", "HVD_TPU_SVC_FUSION_THRESHOLD",
                 "HVD_TPU_SVC_TUNE", "HVD_TPU_TUNE_DB"):
        monkeypatch.delenv(knob, raising=False)
    yield
    svc.set_enabled_override(None)
    svc.set_staleness_override(None)
    svc.set_threshold_override(None)
    svc.reset_service()
    sched.set_config_override(None)
    topo.set_topology_override(None)
    faults.set_plan(None)
    xir.lower.reset()


def _ar_op(nbytes=64, wire="off", lowering="flat", reduce="mean",
           dtype="float32", bucket=0, ef=False):
    return xir.ExchangeOp(
        "all_reduce", WORLD_AXIS, wire=wire, lowering=lowering,
        bucket=bucket, ef=ef,
        attrs=(("dtype", dtype), ("nbytes", nbytes), ("reduce", reduce)),
    )


def _ar_program(nbytes=64, reduce="mean", wire="off", lowering="flat",
                kind="dense_grad", n_ops=1):
    return xir.program(kind, [
        _ar_op(nbytes=nbytes, wire=wire, lowering=lowering,
               reduce=reduce, bucket=i)
        for i in range(n_ops)
    ])


def _sub(program, args, producer="p", seq=1, participants=()):
    return Submission(
        seq=seq, producer=producer, program=program, args=list(args),
        future=SvcFuture(), participants=tuple(participants),
    )


class TestClassKey:
    def test_dense_all_reduce_classifies(self):
        key = fuse.class_key(_ar_op())
        assert key is not None
        assert key == fuse.class_key(_ar_op(nbytes=4096, bucket=3))

    def test_wire_lowering_dtype_split_classes(self):
        base = fuse.class_key(_ar_op())
        assert fuse.class_key(_ar_op(wire="int8")) != base
        assert fuse.class_key(_ar_op(lowering="hier")) != base
        assert fuse.class_key(_ar_op(dtype="bfloat16")) != base
        assert fuse.class_key(_ar_op(reduce="sum")) != base

    def test_unfusable_ops_return_none(self):
        a2a = xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=0,
                             nbytes=64, dtype="float32")
        assert fuse.class_key(a2a) is None
        assert fuse.class_key(_ar_op(ef=True)) is None
        assert fuse.class_key(_ar_op(lowering="hier_adasum")) is None
        assert fuse.class_key(_ar_op(lowering="auto")) is None
        assert fuse.class_key(_ar_op(), process_set=object()) is None

    def test_mixed_program_does_not_classify(self):
        mixed = xir.program("dense_grad", [
            _ar_op(dtype="float32"), _ar_op(dtype="bfloat16", bucket=1),
        ])
        assert fuse.classify_program(mixed) is None
        uniform = _ar_program(n_ops=3)
        assert fuse.classify_program(uniform) is not None


class TestPackGroup:
    def test_roundtrip_with_aligned_offsets(self):
        rng = np.random.RandomState(0)
        xs = [jnp.asarray(rng.randn(*s).astype(np.float32))
              for s in [(5,), (3, 7), (1,), (2, 2, 2)]]
        buf, layout = fuse.pack_group(xs, align=128)
        for off, _n, _shape in layout:
            assert off % 128 == 0
        outs = fuse.unpack_group(buf, layout)
        for x, o in zip(xs, outs):
            assert (np.asarray(x) == np.asarray(o)).all()

    def test_group_layout_padding_accounting(self):
        layout, elems, payload, padding = fuse.group_layout(
            [(5,), (130,)], align=128, itemsize=4
        )
        assert elems == 128 + 256
        assert payload == (5 + 130) * 4
        assert padding == elems * 4 - payload
        assert [e[0] for e in layout] == [0, 128]

    def test_quant_wire_aligns_to_quant_block(self):
        from horovod_tpu.ops.quantized import quant_block

        assert fuse.align_elems("int8", "float32") == quant_block()
        assert fuse.align_elems("off", "float32") == 512 // 4


class TestPlanCycle:
    def _resolved(self, sizes, producer="p", start_seq=1, threshold=None):
        subs = []
        for i, per_rank in enumerate(sizes):
            x = jnp.zeros((N, per_rank // 4), jnp.float32)
            prog = _ar_program(nbytes=per_rank)
            subs.append((_sub(prog, [x], producer=producer,
                              seq=start_seq + i), prog))
        return subs

    def test_oversize_passes_through(self):
        resolved = self._resolved([1 << 20])
        buffers, passthrough = fuse.plan_cycle(resolved, threshold=4096)
        assert buffers == [] and len(passthrough) == 1
        assert metrics.get_counter("svc.fusion.oversize") == 1

    def test_threshold_splits_buffers(self):
        resolved = self._resolved([2048] * 4)
        buffers, passthrough = fuse.plan_cycle(resolved, threshold=4096)
        assert passthrough == []
        assert len(buffers) == 2
        assert all(len(b.members) == 2 for b in buffers)
        assert all(
            b.payload_bytes + b.padding_bytes <= 4096 for b in buffers
        )

    def test_pack_order_invariant_under_arrival_permutation(self):
        """The fused layout is a pure function of WHAT was released,
        never of the thread interleaving that released it."""
        def plan(order):
            subs = []
            for seq, producer in enumerate(order, start=1):
                x = jnp.zeros((N, 16), jnp.float32)
                prog = _ar_program(nbytes=64)
                subs.append((_sub(prog, [x], producer=producer,
                                  seq=seq), prog))
            buffers, _ = fuse.plan_cycle(subs, threshold=1 << 20)
            assert len(buffers) == 1
            return [m.sub.producer for m in buffers[0].members]

        orders = list(itertools.permutations(("a", "b", "c")))
        layouts = [plan(o) for o in orders]
        assert all(lo == ["a", "b", "c"] for lo in layouts), layouts


@pytest.mark.usefixtures("hvd_module")
class TestServiceFusion:
    def _submit_many(self, s, count=6, nbytes_rows=16, wire="off",
                     reduce="mean"):
        rng = np.random.RandomState(3)
        xs = [
            jnp.asarray(rng.randn(N, nbytes_rows).astype(np.float32))
            for _ in range(count)
        ]
        futs = [
            s.submit(
                _ar_program(nbytes=nbytes_rows * 4, wire=wire,
                            reduce=reduce),
                [x], producer=f"p{i % 2}",
            )
            for i, x in enumerate(xs)
        ]
        outs = [np.asarray(f.result(timeout=60)[0]) for f in futs]
        return xs, outs

    def test_fused_bitwise_equals_unfused_f32(self):
        svc.set_threshold_override(64 << 20)
        s = svc.get_service()
        xs, fused = self._submit_many(s)
        assert metrics.get_counter("svc.fusion.programs_in") >= 6
        assert metrics.get_counter("svc.fusion.buffers_out") < \
            metrics.get_counter("svc.fusion.programs_in")
        assert metrics.get_counter("svc.fusion.fallback") == 0
        svc.reset_service()
        svc.set_threshold_override(0)
        s2 = svc.get_service()
        _, serial = self._submit_many(s2)
        for a, b in zip(fused, serial):
            assert (a == b).all(), "fused diverged from unfused at f32"

    def test_fused_int8_wire_close_to_unfused(self):
        # 512-element rows = one quant block per member: the aligned
        # offsets make fused blocks identical to unfused ones.
        svc.set_threshold_override(64 << 20)
        s = svc.get_service()
        xs, fused = self._submit_many(s, count=4, nbytes_rows=512,
                                      wire="int8", reduce="sum")
        svc.reset_service()
        svc.set_threshold_override(0)
        s2 = svc.get_service()
        _, serial = self._submit_many(s2, count=4, nbytes_rows=512,
                                      wire="int8", reduce="sum")
        for a, b in zip(fused, serial):
            np.testing.assert_allclose(a, b, atol=1e-3)

    def test_mixed_workloads_fuse_only_within_class(self):
        svc.set_threshold_override(64 << 20)
        s = svc.get_service()
        rng = np.random.RandomState(5)
        dense = [
            jnp.asarray(rng.randn(N, 16).astype(np.float32))
            for _ in range(3)
        ]
        shuf = jnp.asarray(rng.randn(N, N).astype(np.float32))
        idx = jnp.tile(jnp.arange(4, dtype=jnp.int32)[None], (N, 1))
        vals = jnp.asarray(rng.randn(N, 4, 2).astype(np.float32))
        futs = [
            s.submit(_ar_program(nbytes=64), [x], producer="dense")
            for x in dense
        ]
        a2a = s.submit(
            xir.program("moe", [
                xir.all_to_all(WORLD_AXIS, split_axis=0, concat_axis=0,
                               nbytes=int(shuf.nbytes), dtype="float32"),
            ]), [shuf], producer="moe",
        )
        sparse = s.submit(
            xir.program("sparse_embed", [
                xir.gather_dense_from_sparse(
                    WORLD_AXIS, nbytes=int(vals.nbytes),
                    dtype="float32",
                ),
            ]), [(idx, vals)], producer="sparse",
        )
        for f, x in zip(futs, dense):
            np.testing.assert_allclose(
                np.asarray(f.result(timeout=60)[0]),
                np.broadcast_to(np.asarray(x).mean(0), (N, 16)),
                rtol=1e-6,
            )
        out = np.asarray(a2a.result(timeout=60)[0])
        np.testing.assert_array_equal(out, np.asarray(shuf).T)
        gi, gv = sparse.result(timeout=60)[0]
        assert np.asarray(gi).shape == (N, N * 4)
        # only the dense class fused: members counted for dense only
        assert metrics.get_counter("svc.fusion.members") == 3
        assert metrics.get_counter("svc.fusion.buffers_out") < \
            metrics.get_counter("svc.fusion.programs_in")

    def test_padding_accounted_and_bounded(self):
        svc.set_threshold_override(1 << 20)
        s = svc.get_service()
        self._submit_many(s, count=4, nbytes_rows=5)  # ragged: pads
        padding = metrics.get_counter("svc.fusion.padding_bytes")
        buffers = metrics.get_counter("svc.fusion.buffers_out")
        assert padding > 0
        assert padding <= buffers * (1 << 20), \
            "per-buffer padding exceeded the threshold"

    def test_threshold_zero_restores_prefusion_behavior(self):
        svc.set_threshold_override(0)
        s = svc.get_service()
        xs, outs = self._submit_many(s)
        for x, o in zip(xs, outs):
            np.testing.assert_allclose(
                o, np.broadcast_to(np.asarray(x).mean(0), (N, 16)),
                rtol=1e-6,
            )
        for counter in ("svc.fusion.programs_in",
                        "svc.fusion.buffers_out",
                        "svc.fusion.members",
                        "svc.fusion.padding_bytes",
                        "svc.fusion.fallback"):
            assert metrics.get_counter(counter) == 0, counter

    def test_oversize_program_passes_through_service(self):
        svc.set_threshold_override(4096)
        s = svc.get_service()
        x = jnp.ones((N, 4096), jnp.float32)  # 16 KiB per rank
        out = s.submit(
            _ar_program(nbytes=4096 * 4), [x], producer="big",
        ).result(timeout=60)[0]
        np.testing.assert_allclose(np.asarray(out), 1.0)
        assert metrics.get_counter("svc.fusion.oversize") >= 1
        assert metrics.get_counter("svc.fusion.members") == 0

    def test_negotiated_release_fuses_across_producers(self):
        svc.set_threshold_override(64 << 20)
        s = svc.get_service()
        x = jnp.ones((N, 8), jnp.float32)
        prog = _ar_program(nbytes=32, reduce="sum")
        fa = s.submit(prog, [x], producer="a", participants=("a", "b"))
        fb = s.submit(prog, [x * 2], producer="b",
                      participants=("a", "b"))
        np.testing.assert_allclose(
            np.asarray(fa.result(timeout=60)[0]), N * 1.0
        )
        np.testing.assert_allclose(
            np.asarray(fb.result(timeout=60)[0]), N * 2.0
        )
        assert metrics.get_counter("svc.fusion.members") == 2
        assert metrics.get_counter("svc.fusion.buffers_out") == 1


@pytest.mark.usefixtures("hvd_module")
class TestConcatMergedMode:
    def test_concat_bitwise_equals_sequential(self):
        from horovod_tpu.xir import interp
        from tests.test_xir import _shard_run

        rng = np.random.RandomState(0)
        a = jnp.asarray(rng.randn(N, 32).astype(np.float32))
        b = jnp.asarray(rng.randn(N, 24).astype(np.float32))
        p1 = _ar_program(nbytes=128, kind="dense_grad")
        p2 = _ar_program(nbytes=96, kind="fsdp")

        def merged(va, vb):
            outs = interp.execute_merged(
                [p1, p2], [[va], [vb]], store=False
            )
            return outs[0][0], outs[1][0]

        def sequential(va, vb):
            return (
                interp.execute(p1, [va], store=False)[0],
                interp.execute(p2, [vb], store=False)[0],
            )

        ma, mb = _shard_run(merged, a, b, n_out=2)
        sa, sb = _shard_run(sequential, a, b, n_out=2)
        assert (np.asarray(ma) == np.asarray(sa)).all()
        assert (np.asarray(mb) == np.asarray(sb)).all()
        assert metrics.get_counter("xir.fusion.buffers") >= 1
        assert metrics.get_counter("xir.fusion.members") >= 2

    def test_threshold_zero_disables_concat_mode(self):
        from horovod_tpu.xir import pipeline

        p1 = _ar_program(nbytes=128)
        p2 = _ar_program(nbytes=96)
        svc.set_threshold_override(0)
        assert pipeline.merge_concat([p1, p2]) is None
        svc.set_threshold_override(1 << 20)
        units = pipeline.merge_concat([p1, p2])
        assert units is not None
        fused = [u for u in units if u[0] == "fused"]
        assert fused and len(fused[0][1]) == 2

    def test_concat_prices_through_program_cost(self):
        p1 = _ar_program(nbytes=4096)
        p2 = _ar_program(nbytes=4096)
        gain = fuse.estimate_concat_gain([p1, p2])
        assert gain["fused_s"] <= gain["serial_s"]
        assert gain["gain_s"] >= 0

    def test_fused_dispatch_cost_property(self):
        topo.set_topology_override(T24)
        serial, fused = topo_model.current().fused_dispatch_cost(
            "all_reduce", [4096] * 16, "flat", N
        )
        assert fused < serial  # 16 dispatch overheads amortize to one


@pytest.mark.usefixtures("hvd_module")
class TestGroupedEagerPath:
    def test_grouped_fused_bitwise_equals_per_tensor(self, monkeypatch):
        from horovod_tpu.ops import eager

        rng = np.random.RandomState(11)
        xs = [
            jnp.asarray(rng.randn(N, 5).astype(np.float32)),
            jnp.asarray(rng.randn(N, 129).astype(np.float32)),
            jnp.asarray((rng.randn(N, 3) * 9).astype(np.int32)),
        ]
        fused = eager.grouped_allreduce(xs, op=eager.Sum)
        assert metrics.get_counter("svc.fusion.grouped_buffers") >= 2
        monkeypatch.setenv("HVD_TPU_DISABLE_GROUP_FUSION", "1")
        serial = eager.grouped_allreduce(xs, op=eager.Sum)
        for f, s in zip(fused, serial):
            assert (np.asarray(f) == np.asarray(s)).all(), \
                "grouped fused wire diverged from per-tensor dispatch"

    def test_grouped_shapes_and_dtypes_roundtrip(self):
        from horovod_tpu.ops import eager

        xs = [jnp.ones((N, 2, 3), jnp.float32),
              jnp.ones((N, 4), jnp.bfloat16)]
        outs = eager.grouped_allreduce(xs, op=eager.Sum)
        assert outs[0].shape == (N, 2, 3) and outs[0].dtype == jnp.float32
        assert outs[1].shape == (N, 4) and outs[1].dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(outs[0]), float(N))


@pytest.mark.usefixtures("hvd_module")
class TestDonation:
    def _losses(self, donate, iters=6):
        rng = np.random.RandomState(0)
        X = rng.randn(16, 32).astype(np.float32)
        Y = (X @ rng.randn(32, 4).astype(np.float32)).astype(np.float32)

        def lf(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] - y) ** 2)

        p = {"w": jnp.asarray(rng.randn(32, 4).astype(np.float32) * 0.1)}
        tx = hvd.DistributedOptimizer(optax.sgd(0.05))
        step = hvd.distributed_train_step(lf, tx, donate=donate)
        st = step.init(p)
        batch = (jnp.asarray(X), jnp.asarray(Y))
        losses = []
        for _ in range(iters):
            p, st, loss = step(p, st, batch)
            losses.append(float(loss))
        return losses

    def test_train_step_donation_numerics_parity(self):
        assert self._losses(True) == self._losses(False)

    def _stale_losses(self, donate, iters=10):
        from horovod_tpu.svc.stale import StaleTrainStep

        def lf(p, b):
            return jnp.sum((p["w"] - 3.0) ** 2) + 0.0 * jnp.sum(b)

        step = StaleTrainStep(lf, optax.sgd(0.2), k=1, donate=donate)
        sp, st = step.init({"w": jnp.zeros((4,), jnp.float32)})
        batch = jnp.zeros((N, 1), jnp.float32)
        losses = []
        for _ in range(iters):
            sp, st, loss = step(sp, st, batch)
            losses.append(float(loss))
        step.drain()
        return losses

    def test_stale_step_donation_numerics_parity(self):
        topo.set_topology_override(T24)
        svc.set_enabled_override(True)
        svc.set_staleness_override(1)
        donated = self._stale_losses(True)
        svc.reset_service()
        undonated = self._stale_losses(False)
        assert donated == undonated, \
            f"stale donation changed numerics: {donated} vs {undonated}"


class TestServiceParams:
    def test_cycle_time_env_and_legacy_fallback(self, monkeypatch):
        assert svc_params.cycle_time_ms() == 1.0
        monkeypatch.setenv("HOROVOD_CYCLE_TIME", "7.5")
        assert svc_params.cycle_time_ms() == 7.5
        monkeypatch.setenv("HVD_TPU_SVC_CYCLE_TIME", "2.5")
        assert svc_params.cycle_time_ms() == 2.5
        monkeypatch.setenv("HVD_TPU_SVC_CYCLE_TIME", "0")
        assert svc_params.cycle_time_ms() == 0.0

    def _drive(self, mgr, cycles=40):
        t = 0.0
        for _ in range(cycles):
            metrics.inc_counter("svc.submits", 10)
            mgr.on_cycle(now=t)
            t += 1.0
            if mgr.converged:
                break
        return mgr

    def test_window_loop_converges_and_pins_env(self, monkeypatch):
        import os

        mgr = svc_params.ServiceParameterManager(
            tune=True, cycle_candidates_ms=(0.0, 2.0), window_s=0.0,
            warmup_windows=2, store=None,
        )
        assert not mgr.converged
        self._drive(mgr)
        assert mgr.converged
        assert mgr._cycle_frozen in (0.0, 2.0)
        assert "HVD_TPU_SVC_CYCLE_TIME" in os.environ
        assert "HVD_TPU_SVC_FUSION_THRESHOLD" in os.environ
        assert metrics.get_counter("svc.tune.windows") >= 4
        for knob in ("HVD_TPU_SVC_CYCLE_TIME",
                     "HVD_TPU_SVC_FUSION_THRESHOLD"):
            monkeypatch.delenv(knob, raising=False)

    def test_store_roundtrip_and_warm_start(self, tmp_path, monkeypatch):
        from horovod_tpu.sched.store import ScheduleStore

        db = tmp_path / "tune.json"
        store = ScheduleStore(str(db))
        mgr = svc_params.ServiceParameterManager(
            tune=True, cycle_candidates_ms=(0.0, 2.0), window_s=0.0,
            warmup_windows=2, store=store,
        )
        self._drive(mgr)
        assert mgr.converged
        assert metrics.get_counter("svc.tune.db_store") == 1
        entry = store.lookup(mgr.store_key())
        assert entry is not None
        assert entry["meta"]["cycle_time_ms"] == mgr._cycle_frozen
        # A second job warm-starts frozen at window 0.
        metrics.reset_counters("svc.tune")
        warm = svc_params.ServiceParameterManager(
            tune=True, cycle_candidates_ms=(0.0, 2.0), window_s=0.0,
            warmup_windows=2, store=ScheduleStore(str(db)),
        )
        assert warm.converged
        assert metrics.get_counter("svc.tune.db_hit") == 1
        assert metrics.get_counter("svc.tune.windows") == 0
        assert warm.tuner.threshold_bytes() == mgr.tuner.threshold_bytes()
        for knob in ("HVD_TPU_SVC_CYCLE_TIME",
                     "HVD_TPU_SVC_FUSION_THRESHOLD"):
            monkeypatch.delenv(knob, raising=False)

    def test_store_key_survives_pinned_winner(self, monkeypatch):
        from horovod_tpu.sched.store import knob_fingerprint

        mgr = svc_params.ServiceParameterManager(tune=False)
        before = mgr.store_key()
        fp_before = knob_fingerprint()
        monkeypatch.setenv("HVD_TPU_SVC_FUSION_THRESHOLD", "123456")
        monkeypatch.setenv("HVD_TPU_SVC_CYCLE_TIME", "9.0")
        # The full fingerprint sees the pinned pair (schedules tuned
        # under different coalescing regimes key distinctly)...
        assert knob_fingerprint() != fp_before
        # ...but the params entry's own key deliberately does not.
        assert mgr.store_key() == before

    def test_disabled_manager_is_static(self):
        mgr = svc_params.ServiceParameterManager(tune=False)
        assert mgr.converged
        before = metrics.get_counter("svc.tune.windows")
        mgr.on_cycle()
        assert metrics.get_counter("svc.tune.windows") == before
