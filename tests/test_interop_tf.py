"""TensorFlow binding tests (reference ``test/parallel/test_tensorflow.py``
scope, scaled to the single-controller stacked convention)."""

import numpy as np
import pytest

import horovod_tpu as hvd

tf = pytest.importorskip("tensorflow")

import horovod_tpu.interop.tf as hvd_tf  # noqa: E402

N = 8


class TestCollectives:
    def test_allreduce_average(self, hvd_module):
        x = tf.constant(np.arange(N * 4, dtype=np.float32).reshape(N, 4))
        y = hvd_tf.allreduce(x)
        expect = np.asarray(x).mean(axis=0)
        for r in range(N):
            np.testing.assert_allclose(y.numpy()[r], expect, rtol=1e-6)

    def test_allreduce_sum_op(self, hvd_module):
        x = tf.ones((N, 3))
        y = hvd_tf.allreduce(x, op=hvd.Sum)
        np.testing.assert_allclose(y.numpy(), float(N))

    def test_allgather(self, hvd_module):
        x = tf.constant(np.random.RandomState(0).randn(N, 2, 3), tf.float32)
        y = hvd_tf.allgather(x)
        expect = np.asarray(x).reshape(N * 2, 3)
        np.testing.assert_allclose(y.numpy()[0], expect, rtol=1e-6)

    def test_broadcast(self, hvd_module):
        x = tf.constant(np.random.RandomState(1).randn(N, 5), tf.float32)
        y = hvd_tf.broadcast(x, root_rank=2)
        for r in range(N):
            np.testing.assert_allclose(y.numpy()[r], x.numpy()[2])

    def test_indexed_slices_allreduce(self, hvd_module):
        slices = tf.IndexedSlices(
            values=tf.ones((N, 2, 4)),
            indices=tf.constant(np.tile([1, 3], (N, 1)), tf.int32),
            dense_shape=tf.constant([8, 4]),
        )
        out = hvd_tf.allreduce(slices)
        assert isinstance(out, tf.IndexedSlices)
        # gathered slices: N ranks x 2 rows each, averaged values
        assert out.values.shape[1] == N * 2
        np.testing.assert_allclose(out.values.numpy(), 1.0 / N)

    def test_broadcast_variables_single_process_noop(self, hvd_module):
        v = tf.Variable([1.0, 2.0])
        hvd_tf.broadcast_variables([v], root_rank=0)
        np.testing.assert_allclose(v.numpy(), [1.0, 2.0])


class TestGradientTape:
    def test_tape_reduces_dense(self, hvd_module):
        w = tf.Variable([[1.0], [2.0]])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.matmul(tf.ones((3, 2)), w))
        dtape = hvd_tf.DistributedGradientTape(tape)
        (g,) = dtape.gradient(loss, [w])
        # single process: reduction is identity
        np.testing.assert_allclose(g.numpy(), [[3.0], [3.0]])

    def test_tape_sparse_as_dense(self, hvd_module):
        emb = tf.Variable(tf.ones((10, 4)))
        with tf.GradientTape() as tape:
            rows = tf.gather(emb, [1, 3])
            loss = tf.reduce_sum(rows)
        dtape = hvd_tf.DistributedGradientTape(tape, sparse_as_dense=True)
        (g,) = dtape.gradient(loss, [emb])
        assert not isinstance(g, tf.IndexedSlices)
        assert g.shape == (10, 4)

    def test_tape_passthrough_attrs(self, hvd_module):
        with tf.GradientTape(persistent=True) as tape:
            pass
        dtape = hvd_tf.DistributedGradientTape(tape)
        assert dtape.watch.__func__ is tape.watch.__func__
        assert dtape.watch.__self__ is tape


class TestDistributedOptimizer:
    def test_apply_gradients_trains(self, hvd_module):
        w = tf.Variable([[0.0], [0.0]])
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.5)
        )
        X = tf.constant([[1.0, 0.0], [0.0, 1.0]])
        for _ in range(20):
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean(
                    (tf.matmul(X, w) - tf.constant([[1.0], [2.0]])) ** 2
                )
            grads = tape.gradient(loss, [w])
            opt.apply_gradients(zip(grads, [w]))
        np.testing.assert_allclose(
            w.numpy(), [[1.0], [2.0]], atol=0.05
        )


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_tape_averages():
    """Two processes, different grads: DistributedGradientTape must hand
    both the mean (reference DistributedGradientTape contract)."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu as hvd
        import horovod_tpu.interop.tf as hvd_tf

        hvd.init()
        scale = float(hvd.process_rank() + 1)  # grads: 1x vs 2x
        w = tf.Variable([[1.0], [1.0]])
        with tf.GradientTape() as tape:
            loss = scale * tf.reduce_sum(tf.matmul(tf.ones((1, 2)), w))
        dtape = hvd_tf.DistributedGradientTape(tape)
        (g,) = dtape.gradient(loss, [w])
        return g.numpy().reshape(-1).tolist()

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    # mean of grad 1 and grad 2 = 1.5 on both processes
    np.testing.assert_allclose(results, [[1.5, 1.5], [1.5, 1.5]])


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_tape_process_set_subset():
    """Two processes, a set containing only rank 0: process 0 reduces
    over itself, process 1 keeps local grads (masked pass-through)."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import tensorflow as tf

        import horovod_tpu as hvd
        import horovod_tpu.interop.tf as hvd_tf

        hvd.init()
        ps = hvd.add_process_set([0])
        scale = float(hvd.process_rank() + 1)  # grads: 1x vs 2x
        w = tf.Variable([[1.0], [1.0]])
        with tf.GradientTape() as tape:
            loss = scale * tf.reduce_sum(tf.matmul(tf.ones((1, 2)), w))
        dtape = hvd_tf.DistributedGradientTape(tape, process_set=ps)
        (g,) = dtape.gradient(loss, [w])
        return g.numpy().reshape(-1).tolist()

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(
        worker, np=2, use_cpu_devices=True,
        extra_env={"HVD_TPU_DYNAMIC_PROCESS_SETS": "1"},
    )
    np.testing.assert_allclose(results[0], [1.0, 1.0])  # member: own mean
    np.testing.assert_allclose(results[1], [2.0, 2.0])  # non-member: local


@pytest.mark.slow
def test_keras_model_end_to_end(hvd_module):
    """Full reference-style TF training recipe: broadcast_variables +
    DistributedGradientTape + DistributedOptimizer on a keras Model."""
    rng = np.random.RandomState(0)
    X = tf.constant(rng.randn(128, 4).astype(np.float32))
    w_true = rng.randn(4, 1).astype(np.float32)
    Y = tf.constant(X.numpy() @ w_true)

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(8, activation="tanh"),
        tf.keras.layers.Dense(1),
    ])
    model.build((None, 4))
    opt = hvd_tf.DistributedOptimizer(
        tf.keras.optimizers.Adam(learning_rate=0.05)
    )
    hvd_tf.broadcast_variables(model.trainable_variables, root_rank=0)

    first = None
    for _ in range(60):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean((model(X, training=True) - Y) ** 2)
        dtape = hvd_tf.DistributedGradientTape(tape)
        grads = dtape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.3, (first, float(loss))


class TestScalarOps:
    """Reference scalar query kernels (``mpi_ops.cc:883-935``)."""

    def test_topology_ops(self, hvd_module):
        import horovod_tpu.interop.tf as hvd_tf

        assert int(hvd_tf.size_op()) == hvd.size()
        assert int(hvd_tf.rank_op()) == hvd.rank()
        assert int(hvd_tf.local_size_op()) == hvd.local_size()
        assert int(hvd_tf.local_rank_op()) == hvd.local_rank()
        assert int(hvd_tf.process_set_included_op(0)) == 1

    def test_size_op_for_subset(self, hvd_module, monkeypatch):
        import horovod_tpu.interop.tf as hvd_tf

        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        ps = hvd.add_process_set([0, 1, 2])
        assert int(hvd_tf.size_op(ps.process_set_id)) == 3
        included = int(hvd_tf.process_set_included_op(ps.process_set_id))
        assert included == (1 if hvd.rank() in (0, 1, 2) else 0)
        hvd.remove_process_set(ps)

    def test_broadcast_object_fn(self, hvd_module):
        import horovod_tpu.interop.tf as hvd_tf

        fn = hvd_tf.broadcast_object_fn(root_rank=0)
        assert fn({"a": 1}) == {"a": 1}


class TestLoadModel:
    """hvd.load_model parity (reference keras/__init__.py:167)."""

    def test_save_load_rewraps_optimizer(self, hvd_module, tmp_path):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(2, input_shape=(4,))]
        )
        opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
        assert getattr(opt, "_hvd_wrapped", False)
        # serializes under the base name, not the wrapper's
        assert type(opt).__name__ == "SGD"
        model.compile(optimizer=opt, loss="mse")
        model.fit(np.zeros((8, 4), np.float32),
                  np.zeros((8, 2), np.float32), epochs=1, verbose=0)
        path = str(tmp_path / "m.keras")
        model.save(path)

        loaded = hvd_tf.load_model(path)
        assert getattr(loaded.optimizer, "_hvd_wrapped", False)
        # still usable for training after the re-wrap
        loaded.fit(np.zeros((8, 4), np.float32),
                   np.zeros((8, 2), np.float32), epochs=1, verbose=0)

    def test_plain_keras_can_load_the_file(self, hvd_module, tmp_path):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(1, input_shape=(3,))]
        )
        model.compile(
            optimizer=hvd_tf.DistributedOptimizer(
                tf.keras.optimizers.Adam(1e-3)
            ),
            loss="mse",
        )
        path = str(tmp_path / "plain.keras")
        model.save(path)
        # no horovod involvement: the file must load with stock keras
        loaded = tf.keras.models.load_model(path)
        assert loaded.optimizer is not None
        assert not getattr(loaded.optimizer, "_hvd_wrapped", False)

    def test_double_wrap_is_idempotent(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
        assert hvd_tf.DistributedOptimizer(opt) is opt

    def test_rewrap_with_different_settings_raises(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        opt = hvd_tf.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
        with pytest.raises(ValueError, match="different settings"):
            hvd_tf.DistributedOptimizer(opt, sparse_as_dense=True)

    def test_process_set_single_process_passthrough(self, hvd_module,
                                                    monkeypatch):
        """Single process: subset reduction degenerates to identity."""
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        monkeypatch.setenv("HVD_TPU_DYNAMIC_PROCESS_SETS", "1")
        ps = hvd.add_process_set([0, 1])
        w = tf.Variable([[1.0], [2.0]])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.matmul(tf.ones((1, 2)), w))
        dtape = hvd_tf.DistributedGradientTape(tape, process_set=ps)
        (g,) = dtape.gradient(loss, [w])
        np.testing.assert_allclose(g.numpy(), [[1.0], [1.0]])
        hvd.remove_process_set(ps)


class TestGradientAggregation:
    """LocalGradientAggregationHelper semantics (reference
    ``gradient_aggregation_eager.py:1-155`` + the aggregation checks of
    ``test/parallel/test_tensorflow2_keras.py``)."""

    def test_optimizer_applies_every_kth_step(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        k = 3
        w = tf.Variable([1.0, 2.0])
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(1.0), backward_passes_per_step=k
        )
        g = tf.constant([0.5, 0.5])
        before = w.numpy().copy()
        for i in range(k - 1):
            opt.apply_gradients([(g, w)])
            np.testing.assert_allclose(
                w.numpy(), before, err_msg=f"step {i} must not apply"
            )
        opt.apply_gradients([(g, w)])  # k-th: aggregate (k*g) applies
        np.testing.assert_allclose(w.numpy(), before - 1.0 * k * 0.5)

    def test_average_aggregated_gradients_divides_by_k(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        k = 4
        w = tf.Variable([2.0])
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(1.0), backward_passes_per_step=k,
            average_aggregated_gradients=True,
        )
        g = tf.constant([1.0])
        for _ in range(k):
            opt.apply_gradients([(g, w)])
        # aggregate k*g averaged back by /k -> one unit step
        np.testing.assert_allclose(w.numpy(), [1.0])

    def test_iterations_advance_on_skipped_steps(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), backward_passes_per_step=2
        )
        w = tf.Variable([1.0])
        g = tf.constant([1.0])
        opt.apply_gradients([(g, w)])  # skipped step
        assert int(opt.iterations.numpy()) == 1

    def test_tape_yields_none_until_boundary(self, hvd_module):
        """Non-boundary tape calls hand back None gradients (applying
        the running aggregate every step would double-count
        microbatches); the boundary call returns the reduced k-step
        aggregate."""
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        w = tf.Variable([3.0])

        def grads_once(d):
            with tf.GradientTape() as tape:
                loss = tf.reduce_sum(w * w)
            if d is None:
                d = hvd_tf.DistributedGradientTape(
                    tape, backward_passes_per_step=2
                )
            d._tape = tape
            return d, d.gradient(loss, [w])[0]

        d, g1 = grads_once(None)
        assert g1 is None  # aggregation-only pass
        d, g2 = grads_once(d)
        # boundary: aggregate 2*grad reduced (single process: identity)
        np.testing.assert_allclose(g2.numpy(), [12.0])

    def test_indexed_slices_rejected_when_aggregating(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), backward_passes_per_step=2
        )
        w = tf.Variable([[1.0], [2.0]])
        sl = tf.IndexedSlices(values=tf.constant([[1.0]]),
                              indices=tf.constant([0]),
                              dense_shape=tf.constant([2, 1]))
        with pytest.raises(ValueError, match="IndexedSlices"):
            opt.apply_gradients([(sl, w)])

    def test_compiled_keras_fit_aggregates(self, hvd_module):
        """model.fit traces apply_gradients into a tf.function — the
        aggregation helper must run graph-side (tf.Variable buffers +
        tf.cond, reference gradient_aggregation_eager.py:126-155), not
        crash converting symbolic tensors to numpy."""
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        model = tf.keras.Sequential([tf.keras.layers.Dense(1)])
        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.05), backward_passes_per_step=2,
            average_aggregated_gradients=True,
        )
        model.compile(optimizer=opt, loss="mse")  # traced by default
        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype(np.float32)
        Y = X @ rng.randn(4, 1).astype(np.float32)
        h = model.fit(X, Y, batch_size=8, epochs=6, verbose=0)
        losses = h.history["loss"]
        assert losses[-1] < losses[0] * 0.5, losses

    def test_rewrap_checks_aggregation_settings(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1), backward_passes_per_step=2
        )
        with pytest.raises(ValueError, match="different settings"):
            hvd_tf.DistributedOptimizer(opt, backward_passes_per_step=3)


class TestBroadcastCallback:
    def test_fit_with_broadcast_callback(self, hvd_module):
        """The callback must plug into keras fit and fire exactly once
        (single process: the broadcast itself is the documented no-op)."""
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        model = tf.keras.Sequential(
            [tf.keras.layers.Dense(2, input_shape=(3,))]
        )
        model.compile(optimizer=tf.keras.optimizers.SGD(0.01), loss="mse")
        cb = hvd_tf.BroadcastGlobalVariablesCallback(root_rank=0)
        x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
        y = np.zeros((8, 2), np.float32)
        model.fit(x, y, batch_size=4, epochs=1, verbose=0, callbacks=[cb])
        assert cb.broadcast_done
        assert isinstance(cb, tf.keras.callbacks.Callback)


class TestTFCompression:
    def test_fp16_roundtrip(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        t = tf.constant([1.5, -2.25, 3.0])
        wire, ctx = hvd_tf.Compression.fp16.compress(t)
        assert wire.dtype == tf.float16
        back = hvd_tf.Compression.fp16.decompress(wire, ctx)
        assert back.dtype == tf.float32
        np.testing.assert_allclose(back.numpy(), t.numpy())
        # ints pass through untouched
        i = tf.constant([1, 2])
        wire, ctx = hvd_tf.Compression.fp16.compress(i)
        assert wire.dtype == tf.int32 and ctx is None

    def test_wire_is_fp16_in_reduction(self, hvd_module, monkeypatch):
        """With Compression.fp16 the gather payload must be half
        precision (the reference FP16Compressor wire contract)."""
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf
        from horovod_tpu.runtime import get_runtime

        seen = []

        def spy_reduce(arr, average, member_procs=None):
            seen.append(arr.dtype)
            return arr  # identity: shapes preserved

        monkeypatch.setattr(hvd_tf, "_process_reduce", spy_reduce)
        monkeypatch.setattr(get_runtime(), "process_count", 2)
        g = tf.constant(np.random.RandomState(0).randn(64).astype(np.float32))
        out = hvd_tf._reduce_grads(
            tf, [g], average=True, compression=hvd_tf.Compression.fp16
        )
        assert seen == [np.dtype(np.float16)]
        assert out[0].dtype == tf.float32  # decompressed for the user

    def test_optimizer_accepts_compression(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        opt = hvd_tf.DistributedOptimizer(
            tf.keras.optimizers.SGD(0.1),
            compression=hvd_tf.Compression.fp16,
        )
        w = tf.Variable([1.0])
        opt.apply_gradients([(tf.constant([0.5]), w)])
        np.testing.assert_allclose(w.numpy(), [0.95])


class TestTFSyncBatchNorm:
    def test_single_process_matches_plain_bn(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        rng = np.random.RandomState(0)
        x = rng.randn(16, 4).astype(np.float32)
        sync = hvd_tf.SyncBatchNormalization()
        plain = tf.keras.layers.BatchNormalization()
        y_s = sync(tf.constant(x), training=True)
        y_p = plain(tf.constant(x), training=True)
        np.testing.assert_allclose(y_s.numpy(), y_p.numpy(), rtol=1e-5)
        assert isinstance(sync, tf.keras.layers.BatchNormalization)

    def test_fit_with_sync_bn(self, hvd_module):
        import tensorflow as tf

        import horovod_tpu.interop.tf as hvd_tf

        model = tf.keras.Sequential([
            tf.keras.layers.Dense(8),
            hvd_tf.SyncBatchNormalization(),
            tf.keras.layers.Dense(1),
        ])
        model.compile(optimizer="sgd", loss="mse")
        rng = np.random.RandomState(1)
        X = rng.randn(32, 4).astype(np.float32)
        Y = rng.randn(32, 1).astype(np.float32)
        model.fit(X, Y, batch_size=8, epochs=1, verbose=0)


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_sync_bn_averages_stats():
    """Two processes with different data: SyncBatchNormalization must
    normalize with the GLOBAL batch moments (reference
    tensorflow/sync_batch_norm.py:65 semantics), so both processes map
    identical inputs to identical outputs."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu as hvd
        import horovod_tpu.interop.tf as hvd_tf

        hvd.init()
        r = hvd.process_rank()
        # disjoint per-process batches with different means
        x = np.full((4, 2), float(r * 10), np.float32)
        bn = hvd_tf.SyncBatchNormalization(momentum=0.0, epsilon=1e-5)
        y = bn(tf.constant(x), training=True)
        # global batch = rows of 0 and 10 -> mean 5, var 25
        return [float(y.numpy()[0, 0]), float(bn.moving_mean.numpy()[0]),
                float(bn.moving_variance.numpy()[0])]

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    # rank0 input 0 -> (0-5)/sqrt(25) = -1; rank1 input 10 -> +1
    np.testing.assert_allclose(results[0][0], -1.0, rtol=1e-3)
    np.testing.assert_allclose(results[1][0], 1.0, rtol=1e-3)
    for r in results:  # moving stats hold the synced moments
        np.testing.assert_allclose(r[1], 5.0, rtol=1e-4)
        np.testing.assert_allclose(r[2], 25.0, rtol=1e-3)


class TestInGraphCollectives:
    """Collectives inside tf.function graphs (the reference registers
    AsyncOpKernels for exactly this, ``tensorflow/mpi_ops.cc:409-880``;
    here a py_function re-enters the eager bridge at execution time)."""

    def test_allreduce_in_tf_function(self, hvd_module):
        x = tf.constant(np.arange(N * 4, dtype=np.float32).reshape(N, 4))

        @tf.function
        def fn(t):
            return hvd_tf.allreduce(t, op=hvd.Sum) * 2.0

        y = fn(x)
        np.testing.assert_allclose(
            y.numpy()[0], np.asarray(x).sum(axis=0) * 2.0, rtol=1e-6
        )
        # static shape preserved for downstream graph ops
        assert fn.get_concrete_function(x).output_shapes.as_list() == [N, 4]

    def test_broadcast_and_allgather_in_tf_function(self, hvd_module):
        x = tf.constant(np.random.RandomState(0).randn(N, 3), tf.float32)

        @tf.function
        def fn(t):
            b = hvd_tf.broadcast(t, root_rank=2)
            g = hvd_tf.allgather(t)
            return b, g

        b, g = fn(x)
        for r in range(N):
            np.testing.assert_allclose(b.numpy()[r], x.numpy()[2])
        # stacked convention: every rank holds the (N*3,) concatenation
        assert g.numpy().shape == (N, N * 3)

    def test_alltoall_in_tf_function(self, hvd_module):
        x = tf.constant(np.random.RandomState(1).randn(N, N), tf.float32)

        @tf.function
        def fn(t):
            return hvd_tf.alltoall(t)

        y = fn(x)
        assert y.numpy().shape == (N, N)

        @tf.function
        def bad(t):
            return hvd_tf.alltoall(t, splits=np.ones((N, N), np.int32))

        with pytest.raises(Exception, match="splits inside tf.function"):
            bad(x)

    def test_scalar_query_ops_in_graph(self, hvd_module):
        @tf.function
        def fn():
            return hvd_tf.size_op() + hvd_tf.rank_op()

        assert int(fn().numpy()) == N + 0


def test_in_graph_int_average_preserves_dtype(hvd_module):
    """The eager lowering is dtype-preserving (int Average truncates,
    reference semantics) — the in-graph path must declare the same Tout
    and agree numerically with the eager call."""
    x = tf.constant(np.arange(N * 2, dtype=np.int32).reshape(N, 2))

    @tf.function
    def fn(t):
        return hvd_tf.allreduce(t)  # default Average

    y = fn(x)
    eager = hvd_tf.allreduce(x)
    assert y.dtype == eager.dtype == tf.int32
    np.testing.assert_array_equal(y.numpy(), eager.numpy())


def test_in_graph_allgather_keeps_static_rank(hvd_module):
    """Downstream rank-sensitive graph ops must still build: only the
    gathered dim may be dynamic (review regression)."""
    x = tf.constant(np.random.RandomState(0).randn(N, 2, 3), tf.float32)

    @tf.function
    def fn(t):
        g = hvd_tf.allgather(t)
        return tf.linalg.matmul(g, tf.ones((3, 1)))  # needs known rank

    y = fn(x)
    assert y.numpy().shape == (N, N * 2, 1)
    cf = fn.get_concrete_function(x)
    assert cf.output_shapes.rank == 3


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_in_graph_allreduce():
    """Collectives inside tf.function across two REAL processes: the
    py_function lowering must re-enter the eager bridge and average
    across ranks at graph-execution time."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu as hvd
        import horovod_tpu.interop.tf as hvd_tf

        hvd.init()
        scale = float(hvd.process_rank() + 1)

        @tf.function
        def fn(t):
            return hvd_tf.allreduce(t, op=hvd.Average) + 1.0

        x = tf.constant(np.full((1, 4), scale, np.float32))
        return fn(x).numpy().reshape(-1).tolist()

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    # mean(1, 2) + 1 = 2.5 on both processes
    np.testing.assert_allclose(results, [[2.5] * 4, [2.5] * 4])


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_subset_rides_member_mesh_no_gather():
    """VERDICT r5 item 6: subset bridge reductions must ride the
    member-only submesh — the O(P·V) gather fallback and any pickled
    transport are forbidden on this path."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import tensorflow as tf

        import horovod_tpu as hvd
        import horovod_tpu.interop._common as common
        import horovod_tpu.interop.tf as hvd_tf

        hvd.init()

        def no_gather(*a, **k):
            raise AssertionError("subset reduction must not gather rows")

        common._gather_reduce = no_gather
        ps = hvd.add_process_set([0])
        scale = float(hvd.process_rank() + 1)
        w = tf.Variable([[1.0], [1.0]])
        with tf.GradientTape() as tape:
            loss = scale * tf.reduce_sum(tf.matmul(tf.ones((1, 2)), w))
        dtape = hvd_tf.DistributedGradientTape(tape, process_set=ps)
        (g,) = dtape.gradient(loss, [w])
        return g.numpy().reshape(-1).tolist()

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(
        worker, np=2, use_cpu_devices=True,
        extra_env={"HVD_TPU_DYNAMIC_PROCESS_SETS": "1"},
    )
    np.testing.assert_allclose(results[0], [1.0, 1.0])  # member: own mean
    np.testing.assert_allclose(results[1], [2.0, 2.0])  # non-member: local


@pytest.mark.integration
@pytest.mark.multiproc
def test_multiprocess_indexed_slices_array_wire():
    """IndexedSlices gradients ride padded array allgathers, never
    pickle: the pickled-object path is patched to raise."""
    import sys

    import cloudpickle

    import horovod_tpu.runner as runner

    def worker():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu as hvd
        import horovod_tpu.interop.tf as hvd_tf

        hvd.init()

        def no_pickle(*a, **k):
            raise AssertionError(
                "IndexedSlices payload must not ride allgather_object"
            )

        hvd_tf._functions.allgather_object = no_pickle
        r = hvd.process_rank()
        # ragged per-process slices: rank0 sends 1 row, rank1 sends 2
        g = tf.IndexedSlices(
            values=tf.constant(
                np.full((r + 1, 3), float(r + 1), np.float32)
            ),
            indices=tf.constant(np.arange(r + 1), tf.int64),
            dense_shape=tf.constant([4, 3], tf.int64),
        )
        (out,) = hvd_tf._reduce_grads(tf, [g], average=True)
        return [
            np.asarray(out.indices).tolist(),
            np.asarray(out.values).reshape(-1).tolist(),
        ]

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    results = runner.run(worker, np=2, use_cpu_devices=True)
    for idx, vals in results:
        # concat of rank0's [0] and rank1's [0, 1]; averaged by 2
        assert idx == [0, 0, 1]
        np.testing.assert_allclose(
            np.asarray(vals).reshape(3, 3),
            np.asarray([[0.5] * 3, [1.0] * 3, [1.0] * 3]),
        )
