"""Transformer model family: single-device semantics, and equality of
the sp (ring/Ulysses) and tp sharded paths against the unsharded model
with identical weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.models import Transformer, TransformerConfig, gpt_tiny
from horovod_tpu.models.transformer import Attention
from horovod_tpu.parallel import make_mesh


def _tokens(b=2, t=32, vocab=256, seed=0):
    return jax.random.randint(jax.random.PRNGKey(seed), (b, t), 0, vocab)


def test_forward_single_device():
    model = gpt_tiny()
    toks = _tokens(t=16)
    params = model.init(jax.random.PRNGKey(1), toks)
    logits, aux = model.apply(params, toks)
    assert logits.shape == (2, 16, 256)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) == 0.0


def test_grads_flow():
    model = gpt_tiny()
    toks = _tokens(t=16)
    params = model.init(jax.random.PRNGKey(1), toks)

    def loss(p):
        logits, aux = model.apply(p, toks)
        onehot = jax.nn.one_hot(toks, 256)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1)) + aux

    g = jax.jit(jax.grad(loss))(params)
    leaves = jax.tree.leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    assert any(float(jnp.abs(l).sum()) > 0 for l in leaves)


@pytest.mark.parametrize("impl,heads,head_dim", [
    ("ring", 4, 16),
    ("ulysses", 8, 8),
])
def test_sequence_parallel_matches_single_device(impl, heads, head_dim):
    """sp-sharded transformer (ring / Ulysses) == unsharded transformer
    with the same weights: sequence parallelism is numerically
    transparent."""
    toks = _tokens(b=2, t=32)
    # attn_impl="full": the reference must be *exact* attention, not the
    # flash kernel, so shared flash numerics can't cancel out.
    ref_model = gpt_tiny(num_heads=heads, head_dim=head_dim, attn_impl="full")
    params = ref_model.init(jax.random.PRNGKey(2), toks)
    ref_logits, _ = jax.jit(ref_model.apply)(params, toks)

    sp_model = gpt_tiny(num_heads=heads, head_dim=head_dim, attn_impl=impl)
    mesh = make_mesh(sp=8)
    f = shard_map(
        lambda p, tk: sp_model.apply(p, tk)[0],
        mesh=mesh,
        in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"),
        check_vma=False,  # pallas_call has no replication rule pre-0.5
    )
    logits = jax.jit(f)(params, toks)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=2e-4
    )


def test_tp_attention_matches_single_device():
    """tp-sharded attention == unsharded attention when the local QKV /
    proj kernels are the per-head shards of the global kernels."""
    d, heads, head_dim, b, t = 32, 8, 8, 2, 16
    cfg = TransformerConfig(
        vocab_size=64, num_layers=1, model_dim=d, num_heads=heads,
        head_dim=head_dim, ff_dim=64, max_len=t, dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.PRNGKey(3), (b, t, d))
    attn = Attention(cfg)
    params = attn.init(jax.random.PRNGKey(4), x)["params"]
    ref = jax.jit(lambda p, x: attn.apply({"params": p}, x))(params, x)

    n = 8
    qkv_k = params["qkv"]["Dense_0"]["kernel"].reshape(d, 3, heads, head_dim)
    qkv_b = params["qkv"]["Dense_0"]["bias"].reshape(3, heads, head_dim)
    proj_k = params["proj"]["Dense_0"]["kernel"].reshape(heads, head_dim, d)
    flat = {
        # per-device leading dim: head h of q/k/v goes to device h
        "qkv_k": qkv_k.transpose(2, 0, 1, 3).reshape(n, d, 3 * head_dim),
        "qkv_b": qkv_b.transpose(1, 0, 2).reshape(n, 3 * head_dim),
        "proj_k": proj_k,
        "proj_b": params["proj"]["bias"],
    }

    mesh = make_mesh(tp=8)

    def fn(flat, x):
        local = {
            "qkv": {"Dense_0": {"kernel": flat["qkv_k"][0],
                                "bias": flat["qkv_b"][0]}},
            "proj": {"Dense_0": {"kernel": flat["proj_k"][0]},
                     "bias": flat["proj_b"]},
        }
        return attn.apply({"params": local}, x)

    f = shard_map(
        fn, mesh=mesh,
        in_specs=(
            {"qkv_k": P("tp"), "qkv_b": P("tp"), "proj_k": P("tp"),
             "proj_b": P()},
            P(),
        ),
        out_specs=P(),
        check_vma=False,  # pallas_call has no replication rule pre-0.5
    )
    out = jax.jit(f)(flat, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_transformer_forward():
    model = gpt_tiny(moe_every=1, num_experts_local=4)
    toks = _tokens(t=16)
    params = model.init(jax.random.PRNGKey(5), toks)
    logits, aux = model.apply(params, toks)
    assert logits.shape == (2, 16, 256)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0.0


def test_tp_transformer_runs_sharded():
    toks = _tokens(b=2, t=16)
    model = gpt_tiny(num_heads=8, head_dim=8)
    mesh = make_mesh(tp=8)

    def init_and_apply(toks):
        params = model.init(jax.random.PRNGKey(6), toks)
        logits, _ = model.apply(params, toks)
        return logits

    f = shard_map(
        init_and_apply, mesh=mesh, in_specs=(P(),), out_specs=P(),
        check_vma=False,  # rng-based init is replicated but uninferable
    )
    logits = jax.jit(f)(toks)
    assert logits.shape == (2, 16, 256)
    assert np.isfinite(np.asarray(logits)).all()


def test_shard_local_attention_on_sp_mesh_raises():
    """flash/full on a sequence-sharded mesh must refuse (they would
    silently drop cross-shard attention)."""
    toks = _tokens(b=2, t=32)
    model = gpt_tiny(attn_impl="flash")
    params = model.init(jax.random.PRNGKey(0), toks)
    mesh = make_mesh(sp=8)
    f = shard_map(
        lambda p, tk: model.apply(p, tk)[0],
        mesh=mesh, in_specs=(P(), P(None, "sp")), out_specs=P(None, "sp"),
    )
    with pytest.raises(ValueError, match="shard-local"):
        jax.jit(f)(params, toks)


@pytest.mark.slow
def test_remat_matches_no_remat():
    """cfg.remat must change memory behavior only — identical logits
    and gradients (jax.checkpoint semantics)."""
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 16)))
    plain = gpt_tiny()
    remat = gpt_tiny(remat=True)
    params = plain.init(jax.random.PRNGKey(0), toks)

    def loss(model, p):
        logits, aux = model.apply(p, toks)
        return jnp.mean(logits ** 2) + aux

    l1, g1 = jax.value_and_grad(lambda p: loss(plain, p))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss(remat, p))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_token_cross_entropy_matches_one_hot_form():
    """Gather-form LM loss == one-hot log-softmax form (value + grad)
    without materializing a (B, T, vocab) temporary."""
    import jax

    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(2, 7, 131), jnp.float32)
    tgt = jnp.asarray(rng.randint(0, 131, (2, 7)), jnp.int32)

    from horovod_tpu.models.transformer import token_cross_entropy

    onehot = jax.nn.one_hot(tgt, 131)

    def ref_loss(l):
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(l) * onehot, -1))

    np.testing.assert_allclose(
        float(token_cross_entropy(logits, tgt)), float(ref_loss(logits)),
        rtol=1e-6,
    )
    g_ref = jax.grad(ref_loss)(logits)
    g_new = jax.grad(lambda l: token_cross_entropy(l, tgt))(logits)
    np.testing.assert_allclose(
        np.asarray(g_new), np.asarray(g_ref), rtol=1e-5, atol=1e-7
    )
    # bf16 logits: loss still accumulates in fp32
    lb = logits.astype(jnp.bfloat16)
    assert token_cross_entropy(lb, tgt).dtype == jnp.float32


# ---- sequence packing (VERDICT r4 item 3) -------------------------------

class TestSequencePacking:
    """Packed rows must compute exactly what the same documents would
    compute unpacked: per-document logits equal, loss equal."""

    @staticmethod
    def _docs_and_packed(seq_len=32, impl="full"):
        from horovod_tpu.data.packing import pack_documents

        rng = np.random.RandomState(0)
        docs = [
            rng.randint(1, 256, n).astype(np.int32) for n in (12, 9, 7, 20)
        ]
        toks, segs = pack_documents(docs, seq_len)
        model = gpt_tiny(attn_impl=impl, max_len=seq_len)
        params = model.init(
            jax.random.PRNGKey(1), jnp.asarray(toks), jnp.asarray(segs)
        )
        return docs, toks, segs, model, params

    @pytest.mark.parametrize("impl", ["full", "flash"])
    def test_packed_logits_match_unpacked_per_document(self, impl):
        docs, toks, segs, model, params = self._docs_and_packed(impl=impl)
        packed_logits, _ = model.apply(
            params, jnp.asarray(toks), jnp.asarray(segs)
        )
        packed_logits = np.asarray(packed_logits)
        for d in docs:
            # locate this doc's span in the packed rows
            found = False
            for r in range(toks.shape[0]):
                for s in range(1, segs[r].max() + 1):
                    idx = np.where(segs[r] == s)[0]
                    if len(idx) == len(d) and (toks[r, idx] == d).all():
                        solo, _ = model.apply(params, jnp.asarray(d)[None])
                        np.testing.assert_allclose(
                            packed_logits[r, idx], np.asarray(solo)[0],
                            rtol=2e-4, atol=2e-4,
                        )
                        found = True
                        break
                if found:
                    break
            assert found, f"doc of len {len(d)} not located in packed rows"

    def test_packed_loss_matches_unpacked_mean(self):
        from horovod_tpu.models.transformer import (
            packed_token_cross_entropy,
            token_cross_entropy,
        )

        docs, toks, segs, model, params = self._docs_and_packed()
        logits, _ = model.apply(params, jnp.asarray(toks), jnp.asarray(segs))
        packed_loss = float(packed_token_cross_entropy(
            logits, jnp.asarray(toks), jnp.asarray(segs)
        ))
        # unpacked: token-weighted mean of per-document next-token CE
        tot, cnt = 0.0, 0
        for d in docs:
            solo, _ = model.apply(params, jnp.asarray(d)[None])
            per_tok = float(token_cross_entropy(
                solo[:, :-1], jnp.asarray(d)[None, 1:]
            ))
            tot += per_tok * (len(d) - 1)
            cnt += len(d) - 1
        np.testing.assert_allclose(packed_loss, tot / cnt, rtol=1e-4)

    def test_packed_grads_flow(self):
        from horovod_tpu.models.transformer import packed_token_cross_entropy

        _, toks, segs, model, params = self._docs_and_packed(impl="flash")

        def loss_fn(p):
            logits, _ = model.apply(p, jnp.asarray(toks), jnp.asarray(segs))
            return packed_token_cross_entropy(
                logits, jnp.asarray(toks), jnp.asarray(segs)
            )

        grads = jax.grad(loss_fn)(params)
        total = sum(
            float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads)
        )
        assert np.isfinite(total) and total > 0

    def test_packing_utility_first_fit(self):
        from horovod_tpu.data.packing import (
            pack_documents,
            packing_efficiency,
        )

        docs = [np.arange(1, n + 1, dtype=np.int32) for n in (30, 20, 10, 2)]
        toks, segs = pack_documents(docs, 32)
        # first-fit decreasing: [30, 2] and [20, 10] -> exactly 2 rows
        assert toks.shape == (2, 32)
        assert packing_efficiency(segs) > 0.9
        # every document fully present exactly once
        flat = []
        for r in range(toks.shape[0]):
            for s in range(1, segs[r].max() + 1):
                idx = np.where(segs[r] == s)[0]
                flat.append(tuple(toks[r, idx]))
        assert sorted(len(f) for f in flat) == [2, 10, 20, 30]

    def test_long_document_splits_into_chunks(self):
        from horovod_tpu.data.packing import pack_documents

        toks, segs = pack_documents(
            [np.arange(1, 71, dtype=np.int32)], 32
        )
        got = np.concatenate(
            [toks[r][segs[r] > 0] for r in range(toks.shape[0])]
        )
        assert sorted(got.tolist()) == list(range(1, 71))

    def test_packed_rejects_sequence_parallel(self):
        from horovod_tpu.parallel import make_mesh

        model = gpt_tiny(attn_impl="ring")
        mesh = make_mesh(sp=8)
        toks = jnp.zeros((1, 32), jnp.int32)
        segs = jnp.ones((1, 32), jnp.int32)

        def run(t, s):
            return model.init(jax.random.PRNGKey(0), t, s)

        with pytest.raises(ValueError, match="pack"):
            jax.jit(shard_map(
                run, mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp")),
                out_specs=P(), check_vma=False,
            ))(toks, segs)

    def test_pack_batches_streaming(self):
        from horovod_tpu.data.packing import pack_batches

        rng = np.random.RandomState(1)
        docs = [
            rng.randint(1, 99, rng.randint(5, 30)).astype(np.int32)
            for _ in range(120)
        ]
        batches = list(pack_batches(iter(docs), seq_len=32, batch_size=4))
        assert len(batches) >= 5
        seen = []
        for toks, segs in batches:
            assert toks.shape == (4, 32) and segs.shape == (4, 32)
            for r in range(4):
                for s in range(1, int(segs[r].max()) + 1):
                    idx = np.where(segs[r] == s)[0]
                    if len(idx):
                        seen.append(tuple(toks[r, idx]))
        # every emitted span is one of the source docs (or a chunk of
        # one), and most of the stream was emitted
        doc_set = {tuple(d) for d in docs}
        assert sum(s in doc_set for s in seen) >= len(seen) * 0.9
        assert len(seen) >= 100

    def test_pack_batches_remainder_padding(self):
        from horovod_tpu.data.packing import pack_batches

        docs = [np.arange(1, 11, dtype=np.int32) for _ in range(3)]
        out = list(pack_batches(iter(docs), seq_len=16, batch_size=4,
                                drop_remainder=False))
        assert len(out) == 1
        toks, segs = out[0]
        assert toks.shape == (4, 16)
        # padded rows carry segment 0 everywhere
        assert (segs[(segs > 0).any(axis=1) == False] == 0).all()  # noqa: E712
