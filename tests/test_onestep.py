"""Whole-step single-dispatch emission (``HVD_TPU_ONESTEP``).

The train-step parity column lives in
tests/test_collective_matrix.py::TestOnestepColumn; this file covers
the machinery (ROADMAP item 4's "fold the whole exchange schedule into
one XLA program"): the knob and engagement rules, ``emit_step``'s
value-identity stitch, the host-gap profiler's single-dispatch step
shape (``prof.dispatches_per_step`` reads exactly 1, never 0 or 2),
the service-side whole-cycle fold (bitwise parity with per-unit
dispatch, exactly one ``svc.dispatches`` increment per cycle, fallback
on a broken fold), the whole-cycle ResponseCache key, tuner
exploration with tune-DB persistence, and the store fingerprint fold.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import metrics, sched, svc, topo, xir
from horovod_tpu.exceptions import HorovodTpuError
from horovod_tpu.prof import hostgap
from horovod_tpu.runtime import WORLD_AXIS
from horovod_tpu.svc.cache import CycleProgram, ResponseCache
from horovod_tpu.topo import model as topo_model
from horovod_tpu.trace.tracer import Span
from horovod_tpu.xir import interp as xinterp

pytestmark = pytest.mark.onestep

N = 8
T24 = topo_model.Topology(num_slices=2, slice_size=4)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for knob in ("HVD_TPU_ONESTEP", "HOROVOD_ONESTEP"):
        monkeypatch.delenv(knob, raising=False)
    yield
    xinterp.set_onestep_override(None)
    sched.set_config_override(None)
    svc.set_enabled_override(None)
    svc.set_threshold_override(None)
    svc.reset_service()
    topo.set_topology_override(None)


# ----------------------------------------------------------- the knob

class TestKnob:
    def test_default_is_auto(self):
        assert xinterp.onestep_mode() == "auto"

    @pytest.mark.parametrize("raw,want", [
        ("off", "off"), ("0", "off"), ("false", "off"),
        ("on", "on"), ("1", "on"), ("true", "on"),
        ("auto", "auto"), ("AUTO", "auto"),
    ])
    def test_spellings(self, monkeypatch, raw, want):
        monkeypatch.setenv("HVD_TPU_ONESTEP", raw)
        assert xinterp.onestep_mode() == want

    def test_bad_spelling_raises(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_ONESTEP", "sideways")
        with pytest.raises(HorovodTpuError, match="ONESTEP"):
            xinterp.onestep_mode()

    def test_override_wins_and_validates(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_ONESTEP", "off")
        xinterp.set_onestep_override("on")
        assert xinterp.onestep_mode() == "on"
        with pytest.raises(HorovodTpuError):
            xinterp.set_onestep_override("diagonal")

    def test_engagement_rules(self):
        xinterp.set_onestep_override("off")
        assert not xinterp.onestep_engaged(100)
        xinterp.set_onestep_override("on")
        assert xinterp.onestep_engaged(1)
        # auto folds only when there is more than one dispatch unit to
        # save: a single-unit cycle already pays one round-trip.
        xinterp.set_onestep_override("auto")
        assert not xinterp.onestep_engaged(1)
        assert xinterp.onestep_engaged(2)


# ---------------------------------------------------------- emit_step

class TestEmitStep:
    def test_stitch_is_value_identity(self, hvd_init):
        """The barrier tie is ordering-only: a jitted body routed
        through ``emit_step`` is bitwise identical to the plain
        composition."""
        x = jnp.arange(16, dtype=jnp.float32)

        def update(leaves):
            return leaves[0] * 2.0 + 1.0

        plain = jax.jit(lambda t: update([t * 3.0]))(x)
        folded = jax.jit(
            lambda t: xinterp.emit_step([t * 3.0], update)
        )(x)
        np.testing.assert_array_equal(np.asarray(plain),
                                      np.asarray(folded))

    def test_counts_once_per_trace(self, hvd_init):
        before = metrics.get_counter("xir.onestep.steps")
        f = jax.jit(
            lambda t: xinterp.emit_step([t], lambda ls: ls[0] + 1.0)
        )
        for _ in range(4):  # traced once, executed four times
            f(jnp.ones((4,)))
        assert metrics.get_counter("xir.onestep.steps") == before + 1

    def test_passes_non_array_leaves_through(self, hvd_init):
        out = xinterp.emit_step(
            [jnp.ones((2,)), "not-an-array"],
            lambda ls: (ls[0] + 1.0, ls[1]),
        )
        np.testing.assert_array_equal(np.asarray(out[0]), 2.0)
        assert out[1] == "not-an-array"


# ------------------------------------------- host-gap dispatch gauge

def _span(name, phase, t0, t1, **attrs):
    s = Span(name, phase, t0, attrs=attrs or None)
    s.t1 = t1
    return s


def _step(wall, children=(), **attrs):
    root = _span("step", "step", 0.0, wall, **attrs)
    root.children.extend(children)
    return root


class TestDispatchGauge:
    def test_unmarked_tree_counts_every_call_span(self):
        root = _step(1.0, [
            _span("e1", "exec", 0.0, 0.2),
            _span("d", "dispatch", 0.2, 0.4),
            _span("x", "exchange", 0.4, 0.6),  # emission, not a call
        ])
        assert hostgap.attribute(root)["dispatches"] == 2

    def test_marked_root_is_exactly_one_dispatch(self):
        """The single-dispatch step shape: however many exec/dispatch
        spans nest under a marked root, the step IS one round-trip —
        the gauge must read 1, not 0 and not the epilogue-inflated
        count."""
        root = _step(1.0, [
            _span("e1", "exec", 0.0, 0.5),
            _span("upd", "exchange", 0.5, 0.6, onestep=1),
        ], onestep=1)
        assert hostgap.attribute(root)["dispatches"] == 1

    def test_marked_root_without_exec_children_still_counts_one(self):
        # the executor wrapper losing its exec span must not read as 0
        assert hostgap.attribute(_step(1.0, onestep=1))["dispatches"] \
            == 1

    def test_marked_exec_subtree_collapses_to_one(self):
        root = _step(1.0, [
            _span("folded", "exec", 0.0, 0.5, onestep=1),
            _span("other", "exec", 0.5, 0.7),
        ])
        folded = root.children[0]
        folded.children.append(_span("inner", "dispatch", 0.1, 0.2))
        assert hostgap.attribute(root)["dispatches"] == 2

    def test_marked_emission_span_does_not_count(self):
        """``exchange.{kind}`` / ``onestep.update`` spans carry the
        onestep attr for the trace UI but are emission, not
        round-trips: they neither short-circuit nor count."""
        root = _step(1.0, [
            _span("x", "exchange", 0.0, 0.5, onestep=1),
        ])
        root.children[0].children.extend([
            _span("e1", "exec", 0.0, 0.2),
            _span("e2", "exec", 0.2, 0.4),
        ])
        assert hostgap.attribute(root)["dispatches"] == 2

    def test_unmarked_zero_mode_attr_keeps_flat_count(self):
        # trace.step(onestep=0) under mode off/auto must not trigger
        # the short-circuit: 0 is falsy.
        root = _step(1.0, [
            _span("e1", "exec", 0.0, 0.2),
            _span("e2", "exec", 0.2, 0.4),
        ], onestep=0)
        assert hostgap.attribute(root)["dispatches"] == 2


# ------------------------------------------- service whole-cycle fold

def _ar_program(nbytes=64, reduce="mean", kind="dense_grad"):
    return xir.program(kind, [xir.ExchangeOp(
        "all_reduce", WORLD_AXIS, wire="off", lowering="flat",
        bucket=0,
        attrs=(("dtype", "float32"), ("nbytes", nbytes),
               ("reduce", reduce)),
    )])


@pytest.mark.svc
@pytest.mark.usefixtures("hvd_module")
class TestServiceCycleFold:
    def _submit_mixed(self, s, count=6):
        """Mixed fusion classes (mean + sum) so one cycle holds
        MULTIPLE dispatch units even under a high fusion threshold —
        the shape the fold exists for."""
        rng = np.random.RandomState(3)
        xs = [jnp.asarray(rng.randn(N, 16).astype(np.float32))
              for _ in range(count)]
        futs = [
            s.submit(
                _ar_program(64, reduce="mean" if i % 2 else "sum"),
                [x], producer=f"p{i % 2}",
            )
            for i, x in enumerate(xs)
        ]
        return [np.asarray(f.result(timeout=60)[0]) for f in futs]

    def test_fold_bitwise_equals_per_unit_and_single_dispatch(self):
        svc.set_threshold_override(64 << 20)
        xinterp.set_onestep_override("on")
        d0 = metrics.get_counter("svc.dispatches")
        c0 = metrics.get_counter("svc.onestep.cycles")
        fb0 = metrics.get_counter("svc.onestep.fallback")
        folded = self._submit_mixed(svc.get_service())
        cycles = metrics.get_counter("svc.onestep.cycles") - c0
        dispatches = metrics.get_counter("svc.dispatches") - d0
        assert cycles >= 1
        # ONE dispatch per cycle, however many units the cycle held
        assert dispatches == cycles
        assert metrics.get_counter("svc.onestep.fallback") == fb0
        svc.reset_service()
        xinterp.set_onestep_override("off")
        serial = self._submit_mixed(svc.get_service())
        for a, b in zip(folded, serial):
            assert (a == b).all(), "fold diverged from per-unit"

    def test_auto_engages_on_multi_unit_cycles(self):
        svc.set_threshold_override(64 << 20)
        xinterp.set_onestep_override("auto")
        c0 = metrics.get_counter("svc.onestep.cycles")
        self._submit_mixed(svc.get_service())
        assert metrics.get_counter("svc.onestep.cycles") > c0

    def test_broken_fold_falls_back_to_per_unit(self, monkeypatch):
        """The fold is a performance lever, never a new way to wedge a
        producer: a failing whole-cycle build must leave every future
        resolved through the per-unit paths."""
        svc.set_threshold_override(64 << 20)
        xinterp.set_onestep_override("on")
        s = svc.get_service()
        monkeypatch.setattr(
            type(s), "_build_onestep_executor",
            lambda self, units: (_ for _ in ()).throw(
                RuntimeError("injected fold failure")
            ),
        )
        fb0 = metrics.get_counter("svc.onestep.fallback")
        outs = self._submit_mixed(s)
        assert metrics.get_counter("svc.onestep.fallback") > fb0
        svc.reset_service()
        xinterp.set_onestep_override("off")
        serial = self._submit_mixed(svc.get_service())
        for a, b in zip(outs, serial):
            assert (a == b).all(), "fallback diverged from per-unit"

    def test_repeat_cycle_hits_whole_cycle_cache(self):
        svc.set_threshold_override(64 << 20)
        xinterp.set_onestep_override("on")
        s = svc.get_service()
        self._submit_mixed(s)
        hits0 = metrics.get_counter("svc.cache_hit")
        self._submit_mixed(s)
        assert metrics.get_counter("svc.cache_hit") > hits0


class TestCycleCacheKey:
    def test_key_shape_and_order_sensitivity(self):
        a = _ar_program(64, reduce="mean")
        b = _ar_program(64, reduce="sum")
        k_ab = ResponseCache.cycle_key([(a, 8), (b, 8)])
        k_ba = ResponseCache.cycle_key([(b, 8), (a, 8)])
        assert k_ab[0] == "onestep_cycle"
        assert k_ab == ResponseCache.cycle_key([(a, 8), (b, 8)])
        # the scatter is positional: cycle order is part of the key
        assert k_ab != k_ba
        assert ResponseCache.cycle_key([(a, 8)]) != \
            ResponseCache.cycle_key([(a, 4)])

    def test_cycle_program_signature_surface(self):
        key = ResponseCache.cycle_key([(_ar_program(64), 8)])
        prog = CycleProgram(member_keys=key[1])
        assert prog.kind == "onestep"
        assert prog.signature()[0] == "onestep"
        assert prog.lowered and prog.ops == ()


# ------------------------------------------------- tuner + store key

@pytest.fixture()
def two_slice(monkeypatch):
    monkeypatch.setenv("HVD_TPU_TOPO", "2x4")
    topo.reset()
    yield
    topo.reset()


class TestTunerOnestepKnob:
    SIG = ("onestep-test-sig", 1)

    def _drive(self, tuner, favored="on", windows=16):
        for _ in range(windows):
            if tuner.converged:
                break
            tuner.begin_window()
            cand = tuner.onestep()
            steps = 30 if cand == favored else 10
            metrics.inc_counter("train.steps", steps)
            metrics.observe("train.step_seconds", 0.5)
            metrics.set_gauge("sched.bytes_per_step", 1000.0)
            tuner.end_window()
        return tuner

    def test_explores_and_freezes_winner(self, two_slice, monkeypatch):
        monkeypatch.setenv("HVD_TPU_ONESTEP", "auto")
        tuner = sched.ScheduleTuner(explore_onestep=True,
                                    warmup_windows=2)
        assert not tuner.converged
        seen = set()
        for _ in range(3):
            tuner.begin_window()
            seen.add(tuner.onestep())
            metrics.inc_counter(
                "train.steps", 30 if tuner.onestep() == "on" else 10
            )
            metrics.observe("train.step_seconds", 0.5)
            metrics.set_gauge("sched.bytes_per_step", 1000.0)
            tuner.end_window()
        assert seen == {"off", "on", "auto"}  # every candidate ran
        assert tuner._onestep_frozen == "on"
        # the winner is pinned into the env knob
        assert xinterp.onestep_mode() == "on"

    def test_not_explored_reads_env(self, monkeypatch):
        monkeypatch.setenv("HVD_TPU_ONESTEP", "off")
        tuner = sched.ScheduleTuner()
        assert tuner.onestep() == "off"

    def test_cold_db_converges_and_warm_starts(self, two_slice,
                                               tmp_path, monkeypatch):
        monkeypatch.setenv("HVD_TPU_ONESTEP", "auto")
        db = tmp_path / "tune.json"
        monkeypatch.setenv("HVD_TPU_TUNE_DB", str(db))
        t1 = sched.ScheduleTuner(explore_onestep=True,
                                 warmup_windows=2, store="env",
                                 store_key=self.SIG)
        self._drive(t1, favored="on")
        assert t1.converged
        assert t1.onestep() == "on"
        entries = json.loads(db.read_text())["entries"]
        assert any(
            (e.get("meta") or {}).get("onestep") == "on"
            for e in entries.values()
        )
        # warm start: converged at window 0, knob re-pinned
        monkeypatch.setenv("HVD_TPU_ONESTEP", "auto")
        t2 = sched.ScheduleTuner(explore_onestep=True, store="env",
                                 store_key=self.SIG)
        assert t2.converged
        assert t2.onestep() == "on"
        assert xinterp.onestep_mode() == "on"

    def test_fingerprint_folds_resolved_mode(self, monkeypatch):
        from horovod_tpu.sched.store import knob_fingerprint

        unset = knob_fingerprint()
        monkeypatch.setenv("HVD_TPU_ONESTEP", "auto")
        assert knob_fingerprint() == unset  # unset ≡ explicit default
        monkeypatch.setenv("HVD_TPU_ONESTEP", "on")
        assert knob_fingerprint() != unset  # fold points differ
