"""LSF detection + jsrun launch path (reference
``horovod/runner/util/lsf.py`` + ``horovod/runner/js_run.py``,
``test/single/test_run.py`` jsrun command/rankfile tests)."""

import os
import stat
import subprocess
import sys

import pytest

from horovod_tpu.runner import hosts as hosts_mod
from horovod_tpu.runner import lsf


class TestDetection:
    def test_using_lsf(self):
        assert lsf.using_lsf({"LSB_JOBID": "123"})
        assert not lsf.using_lsf({})

    def test_hosts_from_djob_hostfile(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("node1\nnode1\nnode1\nnode2\nnode2\nnode2\n")
        env = {"LSB_JOBID": "1", "LSB_DJOB_HOSTFILE": str(hf)}
        assert lsf.get_allocated_hosts(env) == {"node1": 3, "node2": 3}
        assert lsf.get_compute_hosts(env) == ["node1", "node2"]
        assert lsf.get_num_cores(env) == 3

    def test_hosts_from_mcpu(self):
        env = {"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "batch1 4 batch2 4"}
        assert lsf.get_allocated_hosts(env) == {"batch1": 4, "batch2": 4}

    def test_hosts_from_lsb_hosts(self):
        env = {"LSB_JOBID": "1", "LSB_HOSTS": "a a b"}
        assert lsf.get_allocated_hosts(env) == {"a": 2, "b": 1}

    def test_malformed_mcpu_raises(self):
        with pytest.raises(ValueError):
            lsf._hosts_from_mcpu("host1 4 host2")

    def test_no_allocation_info_raises(self):
        with pytest.raises(RuntimeError):
            lsf.get_allocated_hosts({"LSB_JOBID": "1"})

    def test_host_list_one_worker_per_host(self):
        env = {"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "n1 40 n2 40"}
        hl = lsf.lsf_host_list(env)
        assert hl == [hosts_mod.HostInfo("n1", 1), hosts_mod.HostInfo("n2", 1)]

    def test_host_list_grows_slots_for_large_np(self):
        """Explicit -np beyond the host count spreads slots instead of
        making get_host_assignments raise."""
        env = {"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "localhost 16"}
        hl = lsf.lsf_host_list(env, np_=4)
        assert hl == [hosts_mod.HostInfo("localhost", 4)]

    def test_launch_host_excluded_by_signature(self):
        """Summit-style batch node (1 slot, first) is dropped from the
        compute list; HVD_TPU_LSF_INCLUDE_LAUNCH_HOST keeps it."""
        env = {"LSB_JOBID": "1",
               "LSB_MCPU_HOSTS": "batch1 1 cn1 40 cn2 40"}
        assert lsf.get_compute_hosts(env) == ["cn1", "cn2"]
        env["HVD_TPU_LSF_INCLUDE_LAUNCH_HOST"] = "1"
        assert lsf.get_compute_hosts(env) == ["batch1", "cn1", "cn2"]

    def test_single_host_never_excluded(self):
        env = {"LSB_JOBID": "1", "LSB_MCPU_HOSTS": "onlyhost 1"}
        assert lsf.get_compute_hosts(env) == ["onlyhost"]


class TestRankfile:
    def test_rankfile_contents(self, tmp_path):
        path = str(tmp_path / "rf.erf")
        out = lsf.generate_jsrun_rankfile(
            4, {"n1": 2, "n2": 2}, cores_per_proc=10, path=path
        )
        assert out == path
        text = open(path).read()
        assert "overlapping_rs: allow" in text
        assert "cpu_index_using: logical" in text
        # 4 ranks, cores split 10 apiece, restarting per host
        assert "rank: 0: { hostname: n1; cpu: {0-9} }" in text
        assert "rank: 1: { hostname: n1; cpu: {10-19} }" in text
        assert "rank: 2: { hostname: n2; cpu: {0-9} }" in text
        assert "rank: 3: { hostname: n2; cpu: {10-19} }" in text

    def test_rankfile_truncates_to_np(self, tmp_path):
        path = str(tmp_path / "rf.erf")
        lsf.generate_jsrun_rankfile(1, {"n1": 2, "n2": 2}, 4, path=path)
        text = open(path).read()
        assert "rank: 0" in text and "rank: 1" not in text
        assert "n2" not in text

    def test_rankfile_heterogeneous_cores(self, tmp_path):
        """Per-host core budgets: a 1-core batch host next to 40-core
        compute hosts must not clamp (or overflow) the others."""
        path = str(tmp_path / "rf.erf")
        lsf.generate_jsrun_rankfile(
            3, {"batch1": 1, "cn1": 1, "cn2": 1},
            {"batch1": 1, "cn1": 40, "cn2": 40}, path=path,
        )
        text = open(path).read()
        assert "rank: 0: { hostname: batch1; cpu: {0-0} }" in text
        assert "rank: 1: { hostname: cn1; cpu: {0-39} }" in text
        assert "rank: 2: { hostname: cn2; cpu: {0-39} }" in text

    def test_rankfile_insufficient_slots_raises(self, tmp_path):
        with pytest.raises(ValueError):
            lsf.generate_jsrun_rankfile(
                8, {"n1": 2}, 4, path=str(tmp_path / "rf.erf")
            )


class TestSpread:
    def test_one_per_host(self):
        assert lsf.spread_workers(2, ["a", "b"]) == {"a": 1, "b": 1}

    def test_balanced_overflow(self):
        assert lsf.spread_workers(5, ["a", "b"]) == {"a": 3, "b": 2}

    def test_fewer_workers_than_hosts(self):
        assert lsf.spread_workers(1, ["a", "b", "c"]) == {"a": 1}


class TestJsrunCommand:
    def test_command_shape(self):
        cmd = lsf.get_jsrun_command(
            4, ["python", "train.py"], rankfile="/tmp/rf.erf",
        )
        assert cmd[0] == "jsrun"
        i = cmd.index("--erf_input")
        assert cmd[i + 1] == "/tmp/rf.erf"
        j = cmd.index("-m")
        assert cmd[j + 1] == "horovod_tpu.runner.mpi_worker"
        assert cmd[-2:] == ["python", "train.py"]

    def test_command_without_rankfile(self):
        cmd = lsf.get_jsrun_command(8, ["echo"])
        i = cmd.index("--nrs")
        assert cmd[i + 1] == "8"
        assert "--tasks_per_rs" in cmd

    def test_output_file_and_extra_args(self):
        cmd = lsf.get_jsrun_command(
            2, ["echo"], output_filename="/tmp/out.log",
            extra_args=["--smpiargs", "none"],
        )
        assert "--stdio_stdout" in cmd and "--stdio_stderr" in cmd
        assert "--smpiargs" in cmd

    def test_js_run_requires_jsrun(self, monkeypatch):
        monkeypatch.setenv("LSB_JOBID", "1")
        monkeypatch.setattr(lsf.shutil, "which", lambda _: None)
        with pytest.raises(RuntimeError, match="jsrun not found"):
            lsf.js_run(2, ["echo"])

    def test_js_run_rejects_oversubscription(self, monkeypatch):
        monkeypatch.setattr(lsf.shutil, "which", lambda _: "/usr/bin/jsrun")
        monkeypatch.setenv("LSB_JOBID", "1")
        monkeypatch.setenv("LSB_MCPU_HOSTS", "a 4 b 4")
        with pytest.raises(ValueError, match="oversubscribed"):
            lsf.js_run(16, ["echo"])

    def test_js_run_rejects_foreign_hosts(self, monkeypatch):
        monkeypatch.setattr(lsf.shutil, "which", lambda _: "/usr/bin/jsrun")
        monkeypatch.setenv("LSB_JOBID", "1")
        monkeypatch.setenv("LSB_MCPU_HOSTS", "a 4 b 4")
        with pytest.raises(ValueError, match="not part of the LSF"):
            lsf.js_run(2, ["echo"], hosts={"zz": 2})

    def test_js_run_hosts_normalized_to_placement(self, monkeypatch,
                                                  tmp_path):
        """-H slot counts beyond np must not trip the capacity check:
        only PLACED workers count (np=2 fits a 4-core host even when
        -H requests 32 slots)."""
        marker = tmp_path / "ran"
        fake = tmp_path / "jsrun"
        fake.write_text(f"#!/bin/bash\ntouch {marker}\n")
        fake.chmod(0o755)
        monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
        monkeypatch.setenv("LSB_JOBID", "1")
        monkeypatch.setenv("LSB_MCPU_HOSTS", "localhost 4")
        rc = lsf.js_run(2, ["echo"], hosts={"localhost": 32})
        assert rc == 0 and marker.exists()

    def test_js_run_outside_lsf_friendly_error(self, monkeypatch):
        monkeypatch.delenv("LSB_JOBID", raising=False)
        with pytest.raises(RuntimeError, match="requires an LSF job"):
            lsf.js_run(2, ["echo"])

    def test_conflicting_launchers_rejected(self):
        from horovod_tpu.runner import launch

        with pytest.raises(SystemExit):
            launch.parse_args(["--use-mpi", "--use-jsrun", "-np", "2",
                               "--", "echo"])
        with pytest.raises(SystemExit):
            launch.parse_args(["--use-jsrun", "--min-np", "2", "--", "echo"])
        with pytest.raises(SystemExit):
            launch.parse_args(["--use-jsrun", "-np", "2", "--max-np", "4",
                               "--", "echo"])


@pytest.mark.integration
def test_js_run_end_to_end_with_fake_jsrun(tmp_path, monkeypatch):
    """A fake ``jsrun`` on PATH execs the worker shim locally once per
    requested rank with PMIX env, proving the full launch path: env
    contract export, rankfile, shim translation, rc propagation."""
    marker = tmp_path / "out"
    fake = tmp_path / "jsrun"
    fake.write_text(
        "#!/bin/bash\n"
        # find the '-m' python invocation at the tail of our argv
        "while [[ $1 != *python* && $# -gt 0 ]]; do shift; done\n"
        f"PMIX_RANK=0 OMPI_COMM_WORLD_SIZE=2 \"$@\" >> {marker} 2>&1\n"
        f"PMIX_RANK=1 OMPI_COMM_WORLD_SIZE=2 \"$@\" >> {marker} 2>&1\n"
    )
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    monkeypatch.setenv("LSB_JOBID", "77")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "localhost 4")
    rc = lsf.js_run(
        2,
        [sys.executable, "-c",
         "import os; print('rank', os.environ['HVD_TPU_CROSS_RANK'], "
         "'size', os.environ['HVD_TPU_CROSS_SIZE'])"],
    )
    assert rc == 0
    text = marker.read_text()
    assert "rank 0 size 2" in text
    assert "rank 1 size 2" in text


def test_launcher_infers_hosts_under_lsf(monkeypatch):
    """``hvdrun`` with no -H inside an LSF allocation uses the job's
    hosts and infers np (reference launch.py LSFUtils integration)."""
    from horovod_tpu.runner import launch

    monkeypatch.setenv("LSB_JOBID", "5")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "localhost 2")
    captured = {}

    def fake_static(np_, host_list, command, **kw):
        captured.update(np=np_, hosts=host_list, command=command)
        return 0

    monkeypatch.setattr(launch, "launch_static", fake_static)
    rc = launch.run_commandline(["--", "echo", "hi"])
    assert rc == 0
    assert captured["np"] == 1
    assert captured["hosts"] == [hosts_mod.HostInfo("localhost", 1)]
    assert captured["command"] == ["echo", "hi"]


def test_launcher_rejects_explicit_hosts_without_np_under_lsf(monkeypatch):
    """-H with no -np must not silently take np from the allocation."""
    from horovod_tpu.runner import launch

    monkeypatch.setenv("LSB_JOBID", "5")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "n1 2 n2 2")
    rc = launch.run_commandline(["-H", "a:4,b:4", "--", "echo", "hi"])
    assert rc == 2


def test_use_mpi_under_lsf_gets_allocation_hosts(monkeypatch):
    """--use-mpi inside LSF forwards the allocation's hosts to mpirun
    instead of packing workers onto the launch host."""
    from horovod_tpu.runner import launch

    monkeypatch.setenv("LSB_JOBID", "5")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "n1 40 n2 40")
    captured = {}

    def fake_mpi_run(np_, hosts, command, **kw):
        captured.update(np=np_, hosts=hosts, command=command)
        return 0

    import horovod_tpu.runner.mpi_run as mpi_run_mod

    monkeypatch.setattr(mpi_run_mod, "mpi_run", fake_mpi_run)
    rc = launch.run_commandline(["--use-mpi", "--", "echo", "hi"])
    assert rc == 0
    assert captured["np"] == 2
    assert captured["hosts"] == "n1:1,n2:1"
