"""Gradient correctness through traced collectives.

Reference: ``test/parallel/test_torch.py`` grad tests (allreduce_grad,
allgather_grad, broadcast_grad, alltoall_grad verify the registered
gradients against hand-derived values).  Here autodiff flows through
``shard_map`` + XLA collectives; these tests pin the same identities:

  d/dx allreduce_sum(x)    = allreduce_sum(g)   (= N·g for replicated g)
  d/dx allgather(x)        = the slice of g at this rank
  d/dx broadcast(x, root)  = sum of g on root, 0 elsewhere
  d/dx reducescatter(x)    = allgather of g
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import traced

N = 8


def _run(fn, *args, in_specs, out_specs):
    mesh = hvd.mesh()
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    ))(*args)


def test_allreduce_sum_grad(hvd_module):
    x = jnp.arange(N * 3, dtype=jnp.float32).reshape(N, 3)

    def fn(xs):
        # loss = sum(allreduce_sum(x_shard) * weight); d/dx = allreduce(w)
        w = jnp.asarray([1.0, 2.0, 3.0])
        y = traced.allreduce(xs, op=hvd.Sum)
        loss = jnp.sum(y * w)
        return jax.grad(lambda a: jnp.sum(traced.allreduce(a, op=hvd.Sum) * w))(xs), loss

    g, _ = _run(fn, x, in_specs=(P(hvd.WORLD_AXIS),), out_specs=(P(hvd.WORLD_AXIS), P()))
    # every shard's grad = allreduce_sum(w) = N * w
    expected = np.tile(np.asarray([1.0, 2.0, 3.0]) * N, (N, 1))
    np.testing.assert_allclose(np.asarray(g), expected)


def test_allreduce_average_grad(hvd_module):
    x = jnp.ones((N, 4), jnp.float32)

    def fn(xs):
        return jax.grad(
            lambda a: jnp.sum(traced.allreduce(a, op=hvd.Average))
        )(xs)

    g = _run(fn, x, in_specs=(P(hvd.WORLD_AXIS),), out_specs=P(hvd.WORLD_AXIS))
    # average: each shard contributes 1/N to every output → grad = N·(1/N)=1
    np.testing.assert_allclose(np.asarray(g), np.ones((N, 4)))


def test_allgather_grad(hvd_module):
    x = jnp.arange(N * 2, dtype=jnp.float32).reshape(N, 2)

    def fn(xs):
        def loss(a):
            y = traced.allgather(a)  # [N*rows_local, 2] on each shard
            w = jnp.arange(y.shape[0] * y.shape[1], dtype=jnp.float32
                           ).reshape(y.shape)
            return jnp.sum(y * w)

        return jax.grad(loss)(xs)

    g = _run(fn, x, in_specs=(P(hvd.WORLD_AXIS),), out_specs=P(hvd.WORLD_AXIS))
    # gather output is identical on every shard; each rank's grad is the
    # w-slice at its own position
    # allgather's transpose reduce-scatters cotangents from all N
    # replicas of the gathered output, so each slice accumulates N·w
    w = np.arange(N * 2, dtype=np.float32).reshape(N, 2)
    np.testing.assert_allclose(np.asarray(g), N * w)


def test_broadcast_grad(hvd_module):
    x = jnp.ones((N, 3), jnp.float32)

    def fn(xs):
        return jax.grad(
            lambda a: jnp.sum(traced.broadcast(a, root_rank=2))
        )(xs)

    g = _run(fn, x, in_specs=(P(hvd.WORLD_AXIS),), out_specs=P(hvd.WORLD_AXIS))
    got = np.asarray(g)
    # all cotangents flow to the root shard; non-roots get zero
    np.testing.assert_allclose(got[2], np.full((3,), N, np.float32))
    for r in range(N):
        if r != 2:
            np.testing.assert_allclose(got[r], np.zeros(3))


def test_reducescatter_grad(hvd_module):
    x = jnp.ones((N * N, 3), jnp.float32)  # (8, 3) per shard

    def fn(xs):
        def loss(a):
            y = traced.reducescatter(a, op=hvd.Sum)
            return jnp.sum(y * y.shape[0])

        return jax.grad(loss)(xs)

    g = _run(fn, x, in_specs=(P(hvd.WORLD_AXIS),), out_specs=P(hvd.WORLD_AXIS))
    assert np.isfinite(np.asarray(g)).all()


def test_grad_through_distributed_optimizer_matches_local(hvd_module):
    """End-to-end: one DistributedOptimizer step over 8 shards equals a
    single-device step on the concatenated batch (the reference's
    optimizer-parity assertion)."""
    import optax

    rng = np.random.RandomState(0)
    w0 = jnp.asarray(rng.randn(4, 2), jnp.float32)
    xg = rng.randn(16, 4).astype(np.float32)
    yg = rng.randn(16, 2).astype(np.float32)

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    step = hvd.distributed_train_step(loss_fn, tx)
    opt_state = step.init({"w": w0})
    # step donates (params, opt_state): pass copies, keep w0 for the
    # single-device reference below
    params, _, _ = step(
        {"w": jnp.array(w0)}, opt_state, (jnp.asarray(xg), jnp.asarray(yg))
    )

    # single-device reference
    ref_tx = optax.sgd(0.1)
    ref_state = ref_tx.init({"w": w0})
    grads = jax.grad(
        lambda p: loss_fn(p, (jnp.asarray(xg), jnp.asarray(yg)))
    )({"w": w0})
    updates, _ = ref_tx.update(grads, ref_state)
    ref_params = optax.apply_updates({"w": w0}, updates)
    np.testing.assert_allclose(
        np.asarray(params["w"]), np.asarray(ref_params["w"]),
        rtol=1e-5, atol=1e-5,
    )
